"""Golden-metrics regression vs the reference's recorded run (SURVEY.md §4/§6).

The reference repo ships no tests, but it ships exact recorded results: the
metrics CSVs pin accuracy/precision/recall/F1 to full float precision and
the confusion-matrix PNGs pin exact error counts for the 2025-08-05 run
(client 1 test set n=4515: aggregated FP=0 / FN=3). Those two records are
mutually consistent only for one confusion matrix — reconstructing it and
pushing it through this framework's metric pipeline must reproduce the
reference's CSV numbers exactly. This pins our metric definitions (sklearn
``average='binary'`` semantics, percent-scaled accuracy, reference
client1.py:134-143) to the reference's observed behavior.
"""

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.ops.metrics import (
    BinaryCounts,
    finalize_metrics,
)

# client1_aggregated_metrics.csv:2 (full precision, quoted in SURVEY.md §6
# and tests/test_reporting.py):
GOLDEN_AGG = {
    "Accuracy": 99.93355481727574,
    "Precision": 1.0,
    "Recall": 0.9988399071925754,
    "F1-Score": 0.9994196170177677,
}
N_TEST = 4515  # client 1 test split size (confusion-matrix PNG)
FP, FN = 0, 3  # aggregated-model error counts (confusion-matrix PNG)


def _reference_confusion():
    """Solve for the only (TP, TN) consistent with the recorded metrics:
    accuracy fixes total errors (= FP + FN ✓) and recall fixes the positive
    count: FN / (1 - recall) = TP + FN."""
    positives = round(FN / (1.0 - GOLDEN_AGG["Recall"]))
    tp = positives - FN
    tn = N_TEST - positives - FP
    return tp, tn


def test_reconstruction_is_self_consistent():
    tp, tn = _reference_confusion()
    assert tp + tn + FP + FN == N_TEST
    # 2586 DDoS rows in client 1's test split — the recorded recall demands it.
    assert tp + FN == 2586


def test_finalize_metrics_reproduces_reference_csv():
    tp, tn = _reference_confusion()
    z = np.float32(0.0)
    counts = BinaryCounts(
        loss_sum=z,
        n_batches=np.float32(1.0),
        n_examples=np.float32(N_TEST),
        correct=np.float32(tp + tn),
        tp=np.float32(tp),
        fp=np.float32(FP),
        fn=np.float32(FN),
        tn=np.float32(tn),
    )
    m = finalize_metrics(counts)
    for key, want in GOLDEN_AGG.items():
        # Accuracy/precision/recall reproduce to full float64 precision.
        # The recorded F1's final digits (…70177677 vs our …69471851, a
        # 7e-11 gap) are not reproducible from these counts by any standard
        # float64 F1 formula (2PR/(P+R), 2TP/(2TP+FP+FN), fbeta form all
        # agree with ours) — an artifact of the reference's toolchain, so
        # F1 is pinned at 1e-9 instead.
        tol = 1e-9 if key == "F1-Score" else 1e-12
        assert m[key] == pytest.approx(want, abs=tol), key
    np.testing.assert_array_equal(
        m["confusion_matrix"], np.array([[tn, FP], [FN, tp]])
    )


def test_local_error_counts_reproduce_recorded_accuracy():
    """Local model record: FP=41 / FN=0 (confusion PNG) through the metric
    pipeline must yield the recorded 99.09% accuracy with perfect recall
    (client1_local_metrics.csv). The positive count (2586) is fixed by the
    aggregated-model reconstruction above — same test split."""
    tp = 2586  # all positives found (FN=0)
    tn = N_TEST - tp - 41
    m = finalize_metrics(
        BinaryCounts(
            loss_sum=np.float32(0.0),
            n_batches=np.float32(1.0),
            n_examples=np.float32(N_TEST),
            correct=np.float32(tp + tn),
            tp=np.float32(tp),
            fp=np.float32(41),
            fn=np.float32(0),
            tn=np.float32(tn),
        )
    )
    assert m["Accuracy"] == pytest.approx(99.09, abs=0.005)
    assert m["Recall"] == 1.0
    assert m["Precision"] == pytest.approx(tp / (tp + 41), abs=1e-12)
