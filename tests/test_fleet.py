"""Fleet-scale rounds (ISSUE 7): streamed reply fan-out + the
hierarchical fold tree (comm/relay.py) for 64-256-client cohorts.

Contracts pinned here:

* Streamed replies are BYTE-IDENTICAL in value to dense replies —
  mixed fleets (advertising and old-peer clients) in one round receive
  the same aggregate, crc-equal to the barrier ``aggregate_flat``.
* The depth-2 fold tree's root aggregate is crc-bit-exact against
  :func:`aggregate_tree` — the pinned order (ascending client id within
  a subtree, fixed subtree order at the root) replayed flat from the
  captured uploads — and every individual fold in the tree is bit-exact
  against ``aggregate_flat`` over its own inputs.
* A LIVE 64-client loopback round at tree depth 2 completes under the
  bounded handler pool and keeps both contracts.
* Fold order is deterministic at scale: shuffled arrival orders through
  StreamAgg (flat and depth-2) produce ONE crc.
"""

import socket
import threading
import time

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
    FederatedClient,
    RelayAggregator,
    StreamAgg,
    WireError,
    aggregate_flat,
    aggregate_tree,
    wire,
)

pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnraisableExceptionWarning"
)


def _leaves(rng, n=4, shape=(32, 9), scale=1.0):
    """Flat separator-free keys: exchange() returns these unchanged."""
    return {
        f"w{i:02d}": rng.normal(size=shape).astype(np.float32) * scale
        for i in range(n)
    }


def _run_clients(clients, uploads, n_samples=None, results=None, errors=None):
    """Drive one exchange per client on its own thread; collect replies."""
    results = {} if results is None else results
    errors = [] if errors is None else errors

    def go(cid):
        try:
            kw = {}
            if n_samples is not None:
                kw["n_samples"] = n_samples[cid]
            results[cid] = clients[cid].exchange(uploads[cid], **kw)
        except Exception as e:  # noqa: BLE001 - surfaced via the list
            errors.append((cid, e))

    threads = [
        threading.Thread(target=go, args=(cid,), daemon=True)
        for cid in clients
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    return results, errors


# ------------------------------------------------------ streamed replies
def test_streamed_reply_mixed_fleet_bit_exact(rng):
    """One round, one advertising client + one old-peer (dense) client:
    both receive the SAME aggregate, crc-equal to the barrier mean, and
    exactly one reply went out as a chunk stream."""
    models = [_leaves(rng) for _ in range(2)]
    with AggregationServer(
        port=0, num_clients=2, timeout=30, stream_chunk_bytes=1 << 10
    ) as server:
        clients = {
            0: FederatedClient(
                "127.0.0.1", server.port, client_id=0, timeout=30
            ),
            # stream=False = the pre-PR peer: no reply advert, no
            # streamed upload — the dense wire shape end to end.
            1: FederatedClient(
                "127.0.0.1", server.port, client_id=1, timeout=30,
                stream=False,
            ),
        }
        agg_thread_out = {}
        t = threading.Thread(
            target=lambda: agg_thread_out.setdefault(
                "agg", server.serve_round()
            ),
            daemon=True,
        )
        t.start()
        results, errors = _run_clients(clients, models)
        t.join(timeout=60)
        assert not errors, errors
        want = aggregate_flat(models)
        for cid in (0, 1):
            got = results[cid]
            assert wire.flat_crc32(got) == wire.flat_crc32(want)
            for k in want:
                np.testing.assert_array_equal(got[k], want[k])
        # Exactly the advertising client's reply streamed; the old peer's
        # dense upload counted as a fallback while streaming was on.
        assert server.stream_totals["stream_replies"] == 1
        assert server.stream_totals["stream_fallbacks"] >= 1


def test_streamed_reply_auth_round(rng):
    """HMAC round: the reply's header/chunk/trailer tags ride the
    REPLY-direction domains and the aggregate still decodes bit-exact."""
    key = b"fleet-secret"
    models = [_leaves(rng) for _ in range(2)]
    with AggregationServer(
        port=0, num_clients=2, timeout=30, auth_key=key,
        stream_chunk_bytes=1 << 10,
    ) as server:
        clients = {
            cid: FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=30,
                auth_key=key,
            )
            for cid in range(2)
        }
        t = threading.Thread(target=server.serve_round, daemon=True)
        t.start()
        results, errors = _run_clients(clients, models)
        t.join(timeout=60)
        assert not errors, errors
        want = aggregate_flat(models)
        for cid in range(2):
            assert wire.flat_crc32(results[cid]) == wire.flat_crc32(want)
        assert server.stream_totals["stream_replies"] == 2


def test_reply_direction_domains_reject_reflection():
    """An upload-domain chunk tag never verifies under the reply-domain
    check (and vice versa) — the reflection hole disjoint domains close."""
    key, nonce = b"secret", b"\x07" * 16
    up = wire.encode_stream_chunk(0, b"data", auth_key=key, nonce=nonce)
    with pytest.raises(WireError, match="HMAC"):
        wire.decode_stream_chunk(
            up, expect_seq=0, auth_key=key, nonce=nonce, direction="down"
        )
    down = wire.encode_stream_chunk(
        0, b"data", auth_key=key, nonce=nonce, direction="down"
    )
    with pytest.raises(WireError, match="HMAC"):
        wire.decode_stream_chunk(
            down, expect_seq=0, auth_key=key, nonce=nonce
        )
    hdr = wire.encode_stream_header(
        [], chunk_bytes=64, payload_nbytes=0, auth_key=key
    )
    with pytest.raises(WireError, match="HMAC"):
        wire.decode_stream_header(hdr, auth_key=key, direction="down")
    end = wire.encode_stream_end(3, auth_key=key, nonce=nonce)
    with pytest.raises(WireError, match="HMAC"):
        wire.decode_stream_end(
            end, expect_chunks=3, auth_key=key, nonce=nonce,
            direction="down",
        )


def test_reply_leaf_sink_sees_every_leaf(rng):
    """The streamed-reply sink runs per leaf as bytes land; its returned
    objects ARE the aggregate the caller receives (the mesh tier returns
    device-placed leaves here)."""

    class Tagged:
        def __init__(self, arr):
            self.arr = arr

    models = [_leaves(rng) for _ in range(2)]
    seen: list[str] = []
    with AggregationServer(
        port=0, num_clients=2, timeout=30, stream_chunk_bytes=1 << 10
    ) as server:
        clients = {
            cid: FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=30
            )
            for cid in range(2)
        }

        def sink(key, arr):
            seen.append(key)
            return Tagged(arr)

        clients[0].reply_leaf_sink = sink
        t = threading.Thread(target=server.serve_round, daemon=True)
        t.start()
        results, errors = _run_clients(clients, models)
        t.join(timeout=60)
        assert not errors, errors
        want = aggregate_flat(models)
        assert sorted(seen) == sorted(want)
        for k in want:
            assert isinstance(results[0][k], Tagged)
            np.testing.assert_array_equal(results[0][k].arr, want[k])
            np.testing.assert_array_equal(results[1][k], want[k])


def test_mesh_trainer_sink_places_on_device(rng):
    """MeshTrainer.reply_leaf_sink returns a replicated device leaf with
    unchanged bytes — placement only, no arithmetic."""
    import jax

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.mesh import (
        make_host_mesh,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.client_mesh import (
        MeshTrainer,
    )

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    trainer = MeshTrainer(
        ModelConfig.tiny(), TrainConfig(), mesh=make_host_mesh(2)
    )
    arr = rng.normal(size=(8, 4)).astype(np.float32)
    placed = trainer.reply_leaf_sink("w", arr)
    assert isinstance(placed, jax.Array)
    assert placed.sharding == trainer.replicated
    np.testing.assert_array_equal(np.asarray(placed), arr)


# -------------------------------------------------- hierarchical fold tree
def _run_tree(rng, n_clients, n_relays, *, n_samples=None, leaf_shape=(16, 5),
              trace_dir=None, rounds=1, chunk=1 << 10):
    """Stand up root + relays + clients on loopback, run ``rounds``
    rounds, return (models, results, groups, root_aggs)."""
    per = n_clients // n_relays
    groups = [list(range(r * per, (r + 1) * per)) for r in range(n_relays)]
    models = [_leaves(rng, n=3, shape=leaf_shape) for _ in range(n_clients)]
    tracer = None
    if trace_dir is not None:
        from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
            Tracer,
        )

        tracer = Tracer(f"{trace_dir}/relay.jsonl", proc="relay-0")
    root_aggs: list[dict] = []
    with AggregationServer(
        port=0, num_clients=n_relays, weighted=True, timeout=60,
        stream_chunk_bytes=chunk,
    ) as root:
        relays = [
            RelayAggregator(
                "127.0.0.1", 0,
                parent_host="127.0.0.1", parent_port=root.port,
                relay_id=r, num_clients=per, timeout=60,
                stream_chunk_bytes=chunk,
                tracer=tracer if r == 0 else None,
            )
            for r in range(n_relays)
        ]
        try:
            def root_loop():
                for _ in range(rounds):
                    root_aggs.append(root.serve_round())

            rt = threading.Thread(target=root_loop, daemon=True)
            rt.start()
            relay_threads = [
                threading.Thread(
                    target=rel.serve, args=(rounds,), daemon=True
                )
                for rel in relays
            ]
            for t in relay_threads:
                t.start()
            clients = {
                cid: FederatedClient(
                    "127.0.0.1",
                    relays[cid // per].port,
                    client_id=cid,
                    timeout=60,
                )
                for cid in range(n_clients)
            }
            all_results: dict[int, dict] = {}
            errors: list = []
            for _ in range(rounds):
                results, errs = _run_clients(
                    clients, models, n_samples=n_samples
                )
                errors.extend(errs)
                all_results = results
            rt.join(timeout=90)
            for t in relay_threads:
                t.join(timeout=30)
            assert not errors, errors
            peak = max(
                rel.server.stream_totals["peak_agg_bytes"] for rel in relays
            )
            return models, all_results, groups, root_aggs, peak
        finally:
            for rel in relays:
                rel.close()


def test_relay_depth2_bit_exact_vs_tree_replay(rng, tmp_path):
    """Live depth-2 round (2 relays x 2 clients): every client receives
    the root aggregate, crc-bit-exact vs aggregate_tree's pinned replay;
    each subtree fold and the root fold are each bit-exact vs
    aggregate_flat over their own inputs; the flat all-N mean agrees to
    reduction-order ulps. The relay-forward span lands on the obs
    timeline vocabulary."""
    n_samples = {0: 5, 1: 1, 2: 3, 3: 2}
    models, results, groups, root_aggs, _peak = _run_tree(
        rng, 4, 2, n_samples=n_samples, trace_dir=str(tmp_path)
    )
    weights = [float(n_samples[i]) for i in range(4)]
    want = aggregate_tree(models, weights, groups)
    assert len(root_aggs) == 1 and root_aggs[0] is not None
    assert wire.flat_crc32(root_aggs[0]) == wire.flat_crc32(want)
    for cid in range(4):
        assert wire.flat_crc32(results[cid]) == wire.flat_crc32(want)
    # Each tree fold individually == the barrier mean over its inputs.
    partial0 = aggregate_flat([models[0], models[1]], weights[:2])
    partial1 = aggregate_flat([models[2], models[3]], weights[2:])
    root_ref = aggregate_flat(
        [partial0, partial1], [sum(weights[:2]), sum(weights[2:])]
    )
    assert wire.flat_crc32(root_ref) == wire.flat_crc32(want)
    # The flat all-N mean differs by fp32 reduction-order ulps at most.
    flat_ref = aggregate_flat(models, weights)
    for k in want:
        np.testing.assert_allclose(
            want[k], flat_ref[k], rtol=1e-5, atol=1e-6
        )
    # relay-forward span: the tree tier's line on the obs timeline.
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.timeline import (
        load_spans,
    )

    spans = load_spans(trace_dir=str(tmp_path))
    fwd = [s for s in spans if s["span"] == "relay-forward"]
    assert fwd and fwd[0]["subtree_clients"] == 2
    assert fwd[0]["parent_round"] is not None


def test_relay_sparse_delta_base_tracks_root(rng):
    """A topk client behind a relay: round 2's sparse delta validates
    against the ROOT aggregate the client adopted (the relay's _last_agg
    is the forwarded result, not the subtree partial)."""
    n_clients, n_relays, rounds = 4, 2, 2
    per = n_clients // n_relays
    models = [_leaves(rng, n=3, shape=(16, 5)) for _ in range(n_clients)]
    with AggregationServer(
        port=0, num_clients=n_relays, weighted=True, timeout=60,
        stream_chunk_bytes=1 << 10,
    ) as root:
        relays = [
            RelayAggregator(
                "127.0.0.1", 0, parent_host="127.0.0.1",
                parent_port=root.port, relay_id=r, num_clients=per,
                timeout=60, stream_chunk_bytes=1 << 10,
            )
            for r in range(n_relays)
        ]
        try:
            rt = threading.Thread(
                target=lambda: [root.serve_round() for _ in range(rounds)],
                daemon=True,
            )
            rt.start()
            for rel in relays:
                threading.Thread(
                    target=rel.serve, args=(rounds,), daemon=True
                ).start()
            clients = {
                cid: FederatedClient(
                    "127.0.0.1", relays[cid // per].port, client_id=cid,
                    timeout=60,
                    compression="topk:0.5" if cid == 0 else "none",
                )
                for cid in range(n_clients)
            }
            last = {}
            for _ in range(rounds):
                uploads = {
                    cid: {
                        k: v + np.float32(0.01)
                        for k, v in (last.get(cid) or models[cid]).items()
                    }
                    for cid in clients
                }
                last, errors = _run_clients(clients, uploads)
                assert not errors, errors
            # Round 2 went sparse against the adopted ROOT base — the
            # client only adopts a base whose crc matches the relay's
            # agg_crc stamp, so reaching here proves base agreement.
            assert clients[0]._base is not None
            rt.join(timeout=60)
        finally:
            for rel in relays:
                rel.close()


def test_fleet_64_clients_depth2_live(rng):
    """The acceptance-shaped round: 64 live loopback clients, 8 relays
    of 8, one root — completes under the bounded handler pool with the
    root aggregate crc-bit-exact vs the pinned tree replay."""
    models, results, groups, root_aggs, peak = _run_tree(
        rng, 64, 8, leaf_shape=(64,), chunk=256
    )
    want = aggregate_tree(models, None, groups)
    assert wire.flat_crc32(root_aggs[0]) == wire.flat_crc32(want)
    crcs = {wire.flat_crc32(results[cid]) for cid in range(64)}
    assert crcs == {wire.flat_crc32(want)}
    assert peak > 0


# -------------------------------------------- fold-order determinism @ 64
def test_fold_order_determinism_64_contributors(rng):
    """Property test: 64 seeded contributors folded through StreamAgg in
    shuffled arrival orders — flat and depth-2 — always produce ONE crc
    (the pinned ascending-id / fixed-subtree-order arithmetic is arrival-
    order invariant)."""
    n, n_groups = 64, 8
    keys = tuple(sorted(f"k{i}" for i in range(3)))
    models = [
        {k: rng.normal(size=(8, 3)).astype(np.float32) for k in keys}
        for _ in range(n)
    ]
    weights = [float(w) for w in rng.integers(1, 9, size=n)]
    groups = [
        list(range(g * (n // n_groups), (g + 1) * (n // n_groups)))
        for g in range(n_groups)
    ]

    def flat_crc(order):
        st = StreamAgg()
        for cid in order:
            st.register(cid, keys=keys, n_samples=weights[cid])
        st.freeze(list(range(n)), weights)
        for cid in order:
            st.add_dense(cid, models[cid])
        return wire.flat_crc32(st.finalize(list(range(n)), weights))

    def tree_crc(order):
        partials, masses = [], []
        for g in groups:
            st = StreamAgg()
            ws = [weights[i] for i in g]
            for cid in [c for c in order if c in g]:
                st.register(cid, keys=keys, n_samples=weights[cid])
            st.freeze(list(g), ws)
            for cid in [c for c in order if c in g]:
                st.add_dense(cid, models[cid])
            partials.append(st.finalize(list(g), ws))
            masses.append(sum(ws))
        root = StreamAgg()
        for r in range(n_groups):
            root.register(r, keys=keys, n_samples=masses[r])
        root.freeze(list(range(n_groups)), masses)
        for r in range(n_groups):
            root.add_dense(r, partials[r])
        return wire.flat_crc32(
            root.finalize(list(range(n_groups)), masses)
        )

    orders = [list(range(n))]
    for _ in range(3):
        o = list(range(n))
        rng.shuffle(o)
        orders.append(o)
    flat_crcs = {flat_crc(o) for o in orders}
    assert flat_crcs == {wire.flat_crc32(aggregate_flat(models, weights))}
    tree_crcs = {tree_crc(o) for o in orders}
    assert tree_crcs == {
        wire.flat_crc32(aggregate_tree(models, weights, groups))
    }


def test_aggregate_tree_validates_groups(rng):
    with pytest.raises(ValueError, match="non-empty"):
        aggregate_tree([_leaves(rng)], None, [])
    with pytest.raises(ValueError, match="non-empty"):
        aggregate_tree([_leaves(rng)], None, [[0], []])
    with pytest.raises(ValueError, match="non-empty"):
        # Nested subtrees validate at every depth.
        aggregate_tree([_leaves(rng)], None, [[[0], []]])


def test_aggregate_tree_nested_depth3_replay(rng):
    """The nested-groups replay (a relay whose parent is another relay):
    a depth-3 tree folds bottom-up, each fold the exact weighted
    ``aggregate_flat`` over its children, and the depth-2 call shape is
    byte-for-byte what it always was."""
    n = 8
    models = [_leaves(rng, n=3, shape=(8, 3)) for _ in range(n)]
    weights = [float(w) for w in rng.integers(1, 9, size=n)]
    tree = [[[0, 1], [2, 3]], [[4, 5], [6, 7]]]
    got = aggregate_tree(models, weights, tree)
    # Manual bottom-up replay with aggregate_flat.
    lows, lmass = [], []
    for g in ([0, 1], [2, 3], [4, 5], [6, 7]):
        ws = [weights[i] for i in g]
        lows.append(aggregate_flat([models[i] for i in g], ws))
        lmass.append(sum(ws))
    mids = [
        aggregate_flat(lows[:2], lmass[:2]),
        aggregate_flat(lows[2:], lmass[2:]),
    ]
    want = aggregate_flat(
        mids, [lmass[0] + lmass[1], lmass[2] + lmass[3]]
    )
    assert wire.flat_crc32(got) == wire.flat_crc32(want)
    # Depth-2 shape: the classic groups call is arithmetic-identical to
    # composing the same groups as one-level subtrees.
    groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
    d2 = aggregate_tree(models, weights, groups)
    p0 = aggregate_flat([models[i] for i in groups[0]],
                        [weights[i] for i in groups[0]])
    p1 = aggregate_flat([models[i] for i in groups[1]],
                        [weights[i] for i in groups[1]])
    d2_want = aggregate_flat(
        [p0, p1],
        [sum(weights[i] for i in groups[0]),
         sum(weights[i] for i in groups[1])],
    )
    assert wire.flat_crc32(d2) == wire.flat_crc32(d2_want)
    # Bare int leaves may sit next to subtrees at any level.
    mixed = aggregate_tree(models, weights, [0, [1, 2], 3])
    inner = aggregate_flat([models[1], models[2]], weights[1:3])
    mixed_want = aggregate_flat(
        [models[0], inner, models[3]],
        [weights[0], weights[1] + weights[2], weights[3]],
    )
    assert wire.flat_crc32(mixed) == wire.flat_crc32(mixed_want)


@pytest.mark.slow
def test_relay_depth3_live_bit_exact_vs_nested_replay(rng):
    """A LIVE 3-level loopback round — 8 clients under 4 leaf relays
    under 2 mid relays under one weighted root (a relay's parent IS
    another relay; the wire composes) — crc-pinned bit-exact against the
    depth-3 ``aggregate_tree`` replay, with every client receiving the
    root aggregate."""
    n_clients, n_leaf, n_mid = 8, 4, 2
    n_samples = {i: int(w) for i, w in enumerate(
        rng.integers(1, 9, size=n_clients)
    )}
    models = [_leaves(rng, n=3, shape=(16, 3)) for _ in range(n_clients)]
    chunk = 1 << 10
    results: dict[int, dict] = {}
    root_aggs: list[dict] = []
    with AggregationServer(
        port=0, num_clients=n_mid, weighted=True, timeout=60,
        stream_chunk_bytes=chunk,
    ) as root:
        mids = [
            RelayAggregator(
                "127.0.0.1", 0, parent_host="127.0.0.1",
                parent_port=root.port, relay_id=m, num_clients=2,
                timeout=60, stream_chunk_bytes=chunk,
            )
            for m in range(n_mid)
        ]
        leafs = [
            RelayAggregator(
                "127.0.0.1", 0, parent_host="127.0.0.1",
                parent_port=mids[r // 2].port, relay_id=r % 2,
                num_clients=2, timeout=60, stream_chunk_bytes=chunk,
            )
            for r in range(n_leaf)
        ]
        try:
            rt = threading.Thread(
                target=lambda: root_aggs.append(root.serve_round()),
                daemon=True,
            )
            rt.start()
            for rel in mids + leafs:
                threading.Thread(
                    target=rel.serve, args=(1,), daemon=True
                ).start()
            clients = {
                cid: FederatedClient(
                    "127.0.0.1", leafs[cid // 2].port, client_id=cid,
                    timeout=60,
                )
                for cid in range(n_clients)
            }
            results, errors = _run_clients(
                clients, models, n_samples=n_samples
            )
            rt.join(timeout=90)
            assert not errors, errors
        finally:
            for rel in mids + leafs:
                rel.close()
    weights = [float(n_samples[i]) for i in range(n_clients)]
    tree = [[[0, 1], [2, 3]], [[4, 5], [6, 7]]]
    want = aggregate_tree(models, weights, tree)
    assert len(root_aggs) == 1 and root_aggs[0] is not None
    assert wire.flat_crc32(root_aggs[0]) == wire.flat_crc32(want)
    for cid in range(n_clients):
        assert wire.flat_crc32(results[cid]) == wire.flat_crc32(want)
    # Sanity: the depth-3 replay differs from flat all-N by reduction-
    # order ulps only.
    flat_ref = aggregate_flat(models, weights)
    for k in want:
        np.testing.assert_allclose(want[k], flat_ref[k], rtol=1e-5,
                                   atol=1e-6)


# ---------------------------------------------------- server fleet plumbing
def test_bounded_pool_and_backlog_sizing():
    with AggregationServer(port=0, num_clients=256, timeout=5) as server:
        # Bounded handler pool: 2x the fleet + slack, never unbounded.
        assert server._pool._max_workers == 2 * 256 + 8
    with AggregationServer(port=0, num_clients=2, timeout=5) as server:
        assert server._pool._max_workers == 12


def test_reply_via_refused_under_dp_and_secure():
    with AggregationServer(
        port=0, num_clients=2, timeout=1, dp_clip=1.0
    ) as server:
        server.reply_via = lambda agg, info: agg
        with pytest.raises(ValueError, match="reply_via"):
            server.serve_round(deadline=0.2)
    with AggregationServer(
        port=0, num_clients=2, timeout=1, secure_agg=True
    ) as server:
        server.reply_via = lambda agg, info: agg
        with pytest.raises(ValueError, match="reply_via"):
            server.serve_round(deadline=0.2)


def test_dense_fallback_reason_logged_once(rng):
    """A client that cannot stream logs its one-line reason exactly once
    per reason (old peers would otherwise say it every round)."""
    models = [_leaves(rng) for _ in range(1)]
    with AggregationServer(
        port=0, num_clients=1, timeout=30, stream_chunk_bytes=1 << 10
    ) as server:
        fc = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=30, stream=False
        )
        for _ in range(2):
            t = threading.Thread(target=server.serve_round, daemon=True)
            t.start()
            fc.exchange(models[0])
            t.join(timeout=30)
        assert fc._fallback_logged == {"--no-stream-upload"}
        assert server.stream_totals["stream_fallbacks"] == 2
        assert server.stream_totals["stream_replies"] == 0


def test_relay_cli_parser_wiring():
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        build_parser,
    )

    args = build_parser().parse_args(
        [
            "relay", "--port", "0", "--parent-port", "12345",
            "--relay-id", "3", "--num-clients", "8", "--rounds", "2",
            "--stream-chunk-mb", "1",
        ]
    )
    assert args.relay_id == 3 and args.num_clients == 8
    assert args.fn.__name__ == "cmd_relay"
    assert args.stream_upload is True


@pytest.mark.slow
def test_fleet_128_clients_depth2_live(rng):
    """Scale margin beyond the acceptance floor: 128 clients, 16 relays."""
    models, results, groups, root_aggs, _peak = _run_tree(
        rng, 128, 16, leaf_shape=(32,), chunk=128
    )
    want = aggregate_tree(models, None, groups)
    assert wire.flat_crc32(root_aggs[0]) == wire.flat_crc32(want)
    assert {wire.flat_crc32(results[c]) for c in range(128)} == {
        wire.flat_crc32(want)
    }


# ------------------------------------------- survivable fold trees (PR 14)
def _wait_registered(server, ids, timeout):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.faults.deadrelay import (
        wait_registered,
    )

    return wait_registered(server, ids, timeout=timeout)


def _dead_port() -> int:
    """A loopback port with nothing listening (bind, read, release)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_rehome_tree(rng, *, kill, trace_dir=None, root_deadline=6.0):
    """Depth-2 tree where subtree 1 dies and its clients re-home to
    relay 0. ``kill="dial"``: the victims' primary is a dead port (their
    dial budget exhausts). ``kill="mid"``: relay 1 is alive (expecting a
    phantom third client, so its round stays open), and is torn down
    AFTER the victims' uploads landed — they observe a mid-exchange
    death. Returns (models, results, clients, root_state, timings)."""
    n = 4
    models = [_leaves(rng, n=3, shape=(16, 5)) for _ in range(n)]
    n_samples = {c: c + 1 for c in range(n)}
    results: dict[int, dict] = {}
    errors: list = []
    root_aggs: list = []
    timings: dict[str, float] = {}
    tracer = None
    if trace_dir is not None:
        from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
            Tracer,
        )

        tracer = Tracer(f"{trace_dir}/root.jsonl", proc="root")
    with AggregationServer(
        port=0, num_clients=2, min_clients=1, weighted=True, timeout=30,
        stream_chunk_bytes=1 << 10, tracer=tracer,
    ) as root:
        relay0 = RelayAggregator(
            "127.0.0.1", 0, parent_host="127.0.0.1",
            parent_port=root.port, relay_id=0, num_clients=2,
            timeout=30, stream_chunk_bytes=1 << 10,
        )
        relay1 = (
            RelayAggregator(
                "127.0.0.1", 0, parent_host="127.0.0.1",
                parent_port=root.port, relay_id=1, num_clients=3,
                timeout=30, stream_chunk_bytes=1 << 10,
            )
            if kill == "mid"
            else None
        )
        try:
            rt = threading.Thread(
                target=lambda: root_aggs.append(
                    root.serve_round(deadline=root_deadline)
                ),
                daemon=True,
            )
            rt.start()
            threading.Thread(target=relay0.serve, args=(1,), daemon=True).start()
            if relay1 is not None:
                threading.Thread(
                    target=relay1.serve, args=(1,), daemon=True
                ).start()
            victim_port = relay1.port if relay1 is not None else _dead_port()
            clients = {}
            for cid in (0, 1):
                clients[cid] = FederatedClient(
                    "127.0.0.1", relay0.port, client_id=cid, timeout=20
                )
            for cid in (2, 3):
                clients[cid] = FederatedClient(
                    "127.0.0.1", victim_port, client_id=cid, timeout=20,
                    fallback_parents=[("127.0.0.1", relay0.port)],
                    rehome_dial_budget=1.2,
                )

            def go(cid):
                try:
                    results[cid] = clients[cid].exchange(
                        models[cid], n_samples=n_samples[cid],
                        max_retries=3,
                    )
                except Exception as e:  # noqa: BLE001
                    errors.append((cid, e))

            vt = [
                threading.Thread(target=go, args=(c,), daemon=True)
                for c in (2, 3)
            ]
            for t in vt:
                t.start()
            if relay1 is not None:
                # Wait until both victim uploads REGISTERED at relay 1
                # (they then block on its reply), and kill it — the
                # victims see a mid-exchange death, promptly.
                _wait_registered(relay1.server, {2, 3}, 10)
                timings["killed_at"] = time.monotonic()
                relay1.close()
            # Adoption gate: hold relay 0's own clients until the
            # re-homed uploads registered there, keeping its round open
            # through the adoption window.
            _wait_registered(relay0.server, {2, 3}, 15)
            timings["adopted_at"] = time.monotonic()
            st = [
                threading.Thread(target=go, args=(c,), daemon=True)
                for c in (0, 1)
            ]
            for t in st:
                t.start()
            for t in vt + st:
                t.join(timeout=40)
            rt.join(timeout=20)
            assert not errors, errors
        finally:
            relay0.close()
            if relay1 is not None:
                relay1.close()
        root_state = {
            "agg": root_aggs[0] if root_aggs else None,
            "assignment": root.last_assignment,
            "tree_totals": dict(root.tree_totals),
        }
    want = aggregate_tree(
        models,
        [float(n_samples[c]) for c in range(n)],
        root_state["assignment"]["groups"],
    )
    return models, results, clients, root_state, want, timings


@pytest.mark.slow
def test_rehome_on_dial_exhausted_converges_in_round(rng, tmp_path):
    """The victims' primary never answers: their seeded dial budget
    exhausts, they re-home to the sibling relay, and the degraded root
    round completes over the surviving subtree — crc-bit-exact vs
    aggregate_tree over the ROOT's recorded actual assignment."""
    models, results, clients, root_state, want, _ = _run_rehome_tree(
        rng, kill="dial", trace_dir=str(tmp_path)
    )
    assert root_state["agg"] is not None
    # The recorded assignment: one surviving subtree that folded
    # everyone, own + adopted, in ascending client id.
    assert root_state["assignment"]["groups"] == [[0, 1, 2, 3]]
    assert wire.flat_crc32(root_state["agg"]) == wire.flat_crc32(want)
    for cid in range(4):
        assert wire.flat_crc32(results[cid]) == wire.flat_crc32(want)
    for cid in (2, 3):
        assert clients[cid].rehomes == {"dial-exhausted": 1}
    for cid in (0, 1):
        assert clients[cid].rehomes == {}
    # Root-side degradation accounting: one whole subtree dropped.
    assert root_state["tree_totals"]["subtree_failures"] == 1
    assert root_state["tree_totals"]["degraded_rounds"] == 1
    assert root_state["tree_totals"]["stragglers_shed"] == 0
    # The missing-subtree event is stamped on the root's agg span.
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.timeline import (
        load_spans,
    )

    aggs = [
        s for s in load_spans(trace_dir=str(tmp_path)) if s["span"] == "agg"
    ]
    assert aggs and aggs[-1]["missing_subtrees"] == 1
    assert aggs[-1]["assignment"] == [[0, 1, 2, 3]]
    # Adoption happened at the relay tier, not the root — the root's
    # span carries no adopted list.
    assert aggs[-1].get("adopted") in (None, [])


def test_rehome_on_mid_exchange_death_converges_in_round(rng):
    """Relay 1 dies AFTER the victims' uploads landed (they are blocked
    on its reply): close() sheds them promptly — explicit failures, not
    socket timeouts — they re-home as mid-exchange, re-upload dense, and
    the round converges bit-exactly."""
    models, results, clients, root_state, want, timings = _run_rehome_tree(
        rng, kill="mid"
    )
    assert root_state["agg"] is not None
    assert wire.flat_crc32(root_state["agg"]) == wire.flat_crc32(want)
    for cid in range(4):
        assert wire.flat_crc32(results[cid]) == wire.flat_crc32(want)
    for cid in (2, 3):
        assert clients[cid].rehomes == {"mid-exchange": 1}
    # Prompt shedding (the PR 6 prompt-close discipline applied to
    # subtree teardown): the window from the kill to both re-homed
    # uploads being ADOPTED at the sibling must be seconds, not a
    # socket-timeout (20 s here, 300 s default).
    assert timings["adopted_at"] - timings["killed_at"] < 5.0
    assert root_state["tree_totals"]["subtree_failures"] == 1


def test_rehome_duplicate_after_fold_refused_on_adoptive_parent(rng):
    """A re-homed client whose streamed upload already FOLDED at the
    adoptive parent retries (dense, still marked): the duplicate is
    refused, the folded original stands, and the retry connection still
    receives the round's reply — the supersede semantics, re-homed
    flavor."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        framing,
    )

    own = _leaves(rng, n=2, shape=(8, 3))
    adopted_upload = _leaves(rng, n=2, shape=(8, 3))
    poison = {k: v + np.float32(99.0) for k, v in adopted_upload.items()}
    with AggregationServer(
        port=0, num_clients=1, timeout=15, stream_chunk_bytes=1 << 10
    ) as server:
        agg_out: list = []
        t = threading.Thread(
            target=lambda: agg_out.append(server.serve_round(deadline=10)),
            daemon=True,
        )
        t.start()
        # Adopted client 5: streamed upload, header + every leaf chunk,
        # but NO trailer — the round must hold for it (adopted uploads
        # gate completion) while its leaves are all present and can fold.
        flat5 = wire.flatten_params(adopted_upload)
        tensors, payload_nbytes = wire.plan_stream(flat5, "none")
        s5 = socket.create_connection(("127.0.0.1", server.port), timeout=10)
        header = wire.encode_stream_header(
            tensors,
            meta={
                "client_id": 5,
                "n_samples": 7,
                wire.REHOME_META_KEY: 1,
            },
            chunk_bytes=1 << 10,
            payload_nbytes=payload_nbytes,
            direction="up",
        )
        framing.send_frame(s5, header)
        payload = b"".join(
            wire.encode_stream_leaf(flat5[t_["key"]], t_["enc"])
            for t_ in tensors
        )
        seq = 0
        for off in range(0, len(payload), 1 << 10):
            framing.send_frame(
                s5,
                wire.encode_stream_chunk(
                    seq, payload[off : off + (1 << 10)], direction="up"
                ),
                await_ack=False,
            )
            seq += 1
        # Own client 0 uploads dense: the fold set freezes over
        # {0, adopted 5} and — all leaves present — folds both.
        fc0 = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=15
        )
        r0 = {}
        t0 = threading.Thread(
            target=lambda: r0.update(fc0.exchange(own, n_samples=3)),
            daemon=True,
        )
        t0.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            rnd = server._cur_rnd
            if rnd is not None and rnd.stream is not None and (
                rnd.stream.fold_ids is not None
                and len(rnd.stream._folded) == len(tensors)
            ):
                break
            time.sleep(0.02)
        # The re-homed retry: DENSE, marked, different bytes (poison) —
        # must be refused in favor of the folded original.
        dup = wire.encode(
            poison,
            meta={"client_id": 5, "n_samples": 7, wire.REHOME_META_KEY: 1},
        )
        s5b = socket.create_connection(("127.0.0.1", server.port), timeout=10)
        framing.send_frame(s5b, dup)
        reply = framing.recv_frame(s5b)
        got, meta = wire.decode(reply)
        t0.join(timeout=15)
        t.join(timeout=15)
        s5.close()
        s5b.close()
        want = aggregate_flat(
            [wire.flatten_params(own), flat5], None
        )
        assert wire.flat_crc32(agg_out[0]) == wire.flat_crc32(want)
        assert wire.flat_crc32(wire.flatten_params(got)) == wire.flat_crc32(
            want
        )
        assert wire.flat_crc32(wire.flatten_params(r0)) == wire.flat_crc32(
            want
        )


def test_fold_order_determinism_rehomed_assignment(rng):
    """The shuffled-arrival fold-order property extended to a re-homed
    assignment: group 3's contributors adopted by groups 1 and 5 — every
    arrival order through StreamAgg produces ONE crc, equal to
    aggregate_tree over the ACTUAL (post-re-home) groups."""
    n = 64
    keys = tuple(sorted(f"k{i}" for i in range(3)))
    modelz = [
        {k: rng.normal(size=(8, 3)).astype(np.float32) for k in keys}
        for _ in range(n)
    ]
    weights = [float(w) for w in rng.integers(1, 9, size=n)]
    base = [list(range(g * 8, (g + 1) * 8)) for g in range(8)]
    dead = base[3]
    # The actual assignment after re-homing: dead subtree's clients
    # split across two adoptive subtrees; ascending id inside each.
    groups = [
        sorted(base[1] + dead[:4]),
        *[sorted(g) for g in (base[0], base[2])],
        sorted(base[5] + dead[4:]),
        *[sorted(g) for g in (base[4], base[6], base[7])],
    ]
    groups = sorted(groups)  # fixed subtree order at the root

    def tree_crc(order):
        partials, masses = [], []
        for g in groups:
            st = StreamAgg()
            ws = [weights[i] for i in g]
            for cid in [c for c in order if c in g]:
                st.register(cid, keys=keys, n_samples=weights[cid])
            st.freeze(list(g), ws)
            for cid in [c for c in order if c in g]:
                st.add_dense(cid, modelz[cid])
            partials.append(st.finalize(list(g), ws))
            masses.append(sum(ws))
        root = StreamAgg()
        for r in range(len(groups)):
            root.register(r, keys=keys, n_samples=masses[r])
        root.freeze(list(range(len(groups))), masses)
        for r in range(len(groups)):
            root.add_dense(r, partials[r])
        return wire.flat_crc32(
            root.finalize(list(range(len(groups))), masses)
        )

    orders = [list(range(n))]
    for _ in range(3):
        o = list(range(n))
        rng.shuffle(o)
        orders.append(o)
    crcs = {tree_crc(o) for o in orders}
    assert crcs == {
        wire.flat_crc32(aggregate_tree(modelz, weights, groups))
    }


def test_subtree_deadline_sheds_locally_while_root_stays_green(rng):
    """A relay with a tight subtree deadline and a quorum sheds its
    missing straggler LOCALLY (stragglers_shed, not a failed round) and
    still forwards in time — the root round completes green, within the
    root deadline, not degraded."""
    model0 = _leaves(rng, n=3, shape=(8, 3))
    root_aggs: list = []
    with AggregationServer(
        port=0, num_clients=1, weighted=True, timeout=30,
        stream_chunk_bytes=1 << 10,
    ) as root:
        relay = RelayAggregator(
            "127.0.0.1", 0, parent_host="127.0.0.1",
            parent_port=root.port, relay_id=0, num_clients=2,
            min_clients=1, timeout=8.0, subtree_deadline_factor=0.25,
        )
        try:
            rt = threading.Thread(
                target=lambda: root_aggs.append(
                    root.serve_round(deadline=15.0)
                ),
                daemon=True,
            )
            rt.start()
            t0 = time.monotonic()
            threading.Thread(target=relay.serve, args=(1,), daemon=True).start()
            fc = FederatedClient(
                "127.0.0.1", relay.port, client_id=0, timeout=20
            )
            got = fc.exchange(model0, n_samples=5)
            relay_wall = time.monotonic() - t0
        finally:
            relay.close()
        # Shed at ~factor * timeout = 2 s, well under the root's 15 s.
        assert relay_wall < 8.0
        assert relay.server.tree_totals["stragglers_shed"] == 1
        assert relay.server.tree_totals["subtree_failures"] == 0
        # The root saw its one expected subtree: green, not degraded.
        assert root.tree_totals["degraded_rounds"] == 0
        want = aggregate_tree([model0], [5.0], [[0]])
        assert wire.flat_crc32(root_aggs[0]) == wire.flat_crc32(want)
        assert wire.flat_crc32(got) == wire.flat_crc32(want)
        assert root.last_assignment["groups"] == [[0]]


def test_relay_close_aborts_parent_exchange_promptly(rng):
    """close() mid-round: the parent-facing exchange (blocked in its
    dial backoff against a dead root) aborts NOW, and the pending child
    upload is shed as an explicit failure — neither waits out a socket
    timeout (the PR 6 prompt-close discipline applied to teardown)."""
    relay = RelayAggregator(
        "127.0.0.1", 0, parent_host="127.0.0.1",
        parent_port=_dead_port(), relay_id=0, num_clients=1,
        timeout=120.0,
    )
    serve_done = threading.Event()

    def serve():
        relay.serve(rounds=1)
        serve_done.set()

    threading.Thread(target=serve, daemon=True).start()
    fc = FederatedClient("127.0.0.1", relay.port, client_id=0, timeout=120)
    err: list = []

    def child():
        try:
            fc.exchange(_leaves(rng, n=2, shape=(4, 2)), max_retries=1)
        except (ConnectionError, OSError, WireError) as e:
            err.append(e)

    ct = threading.Thread(target=child, daemon=True)
    ct.start()
    # Let the child upload land and the relay's forward start dialing
    # the dead root.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        rnd = relay.server._cur_rnd
        if rnd is not None and 0 in rnd.models:
            break
        time.sleep(0.02)
    time.sleep(0.5)
    t0 = time.monotonic()
    relay.close()
    ct.join(timeout=10)
    assert err and time.monotonic() - t0 < 8.0, (
        "child upload not shed promptly on relay close()"
    )
    assert serve_done.wait(timeout=10.0), (
        "relay serve loop still blocked after close() "
        "(parent dial not aborted)"
    )


def test_root_refuses_overlapping_subtree_claims(rng):
    """Two uploads whose subtree contributor records claim the same
    client id (a re-homed upload double-counted by a surviving old
    parent): the round fails loudly — no renormalization can repair
    that mean."""
    models = [_leaves(rng, n=2, shape=(4, 2)) for _ in range(2)]
    err: list = []
    with AggregationServer(
        port=0, num_clients=2, weighted=True, timeout=15
    ) as server:
        def serve():
            try:
                server.serve_round(deadline=8)
            except RuntimeError as e:
                err.append(e)

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        clients = {
            cid: FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=10
            )
            for cid in range(2)
        }
        metas = {
            0: {wire.SUBTREE_IDS_META_KEY: [10, 11]},
            1: {wire.SUBTREE_IDS_META_KEY: [11, 12]},
        }
        results, cerrs = {}, []

        def go(cid):
            try:
                results[cid] = clients[cid].exchange(
                    models[cid], meta=metas[cid], max_retries=1
                )
            except Exception as e:  # noqa: BLE001
                cerrs.append((cid, e))

        threads = [
            threading.Thread(target=go, args=(c,), daemon=True)
            for c in range(2)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=20)
        t.join(timeout=15)
    assert err and "double-counted" in str(err[0])
    assert len(cerrs) == 2  # both clients failed fast, round retried


def test_rehome_config_and_parser_wiring():
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        build_parser,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        FedConfig,
    )

    # client --parent repeatable + --rehome-dial-budget.
    args = build_parser().parse_args(
        [
            "client", "--client-id", "2",
            "--parent", "10.0.0.1:12346", "--parent", "10.0.0.2:12346",
            "--rehome-dial-budget", "3.5",
        ]
    )
    assert args.parent == ["10.0.0.1:12346", "10.0.0.2:12346"]
    assert args.rehome_dial_budget == 3.5
    # relay --subtree-deadline-factor + --flight-dir parity.
    args = build_parser().parse_args(
        [
            "relay", "--relay-id", "1", "--subtree-deadline-factor",
            "0.3", "--flight-dir", "/tmp/fl",
        ]
    )
    assert args.subtree_deadline_factor == 0.3
    assert args.flight_dir == "/tmp/fl"
    # Validation: the factor must be strictly inside (0, 1) everywhere.
    with pytest.raises(ValueError, match="subtree_deadline_factor"):
        FedConfig(subtree_deadline_factor=1.0)
    with pytest.raises(ValueError, match="subtree_deadline_factor"):
        RelayAggregator(
            "127.0.0.1", 0, parent_host="127.0.0.1", parent_port=1,
            relay_id=0, num_clients=1, subtree_deadline_factor=1.5,
        )
    # Re-homing refuses the single-aggregator modes.
    with pytest.raises(ValueError, match="fallback_parents"):
        FederatedClient(
            "127.0.0.1", 1, client_id=0, secure_agg=True, num_clients=2,
            fallback_parents=[("127.0.0.1", 2)],
        )
    with pytest.raises(ValueError, match="rehome_dial_budget"):
        FederatedClient(
            "127.0.0.1", 1, client_id=0, rehome_dial_budget=0.0,
        )


def test_rehome_counters_on_default_registry():
    """fedtpu_client_rehomes_total is a labeled counter family on the
    default registry (registered ONLY from comm/client.py —
    obs-metric-once), shared by every client in the process, and
    incremented on each re-home by reason."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
        metrics as obs_metrics,
    )

    m = obs_metrics.default_registry()
    c_dial = m.counter(
        "fedtpu_client_rehomes_total", labels={"reason": "dial-exhausted"}
    )
    c_mid = m.counter(
        "fedtpu_client_rehomes_total", labels={"reason": "mid-exchange"}
    )
    before = (c_dial.value, c_mid.value)
    fc = FederatedClient(
        "127.0.0.1", _dead_port(), client_id=9,
        fallback_parents=[("127.0.0.1", _dead_port())],
    )
    assert fc._rehome("dial-exhausted")
    assert not fc._rehome("mid-exchange")  # list exhausted
    assert c_dial.value == before[0] + 1
    assert c_mid.value == before[1]
    assert fc.rehomes == {"dial-exhausted": 1}
    snap = m.snapshot()["families"]["fedtpu_client_rehomes_total"]
    assert snap["type"] == "counter"
    assert {s["labels"]["reason"] for s in snap["samples"]} >= {
        "dial-exhausted",
        "mid-exchange",
    }


@pytest.mark.slow
def test_fleet_128_clients_two_relays_killed(rng):
    """Scale margin for the failover plane: 128 clients / 16 relays,
    TWO relays killed mid-round (their clients re-home to two surviving
    siblings); the degraded root round completes crc-bit-exact vs the
    recorded actual assignment."""
    n_clients, n_relays, per = 128, 16, 8
    models = [_leaves(rng, n=3, shape=(16,)) for _ in range(n_clients)]
    victim_relays = {3, 11}
    adoptive = {3: 0, 11: 8}  # victim relay -> fallback relay index
    results: dict[int, dict] = {}
    errors: list = []
    root_aggs: list = []
    with AggregationServer(
        port=0, num_clients=n_relays, min_clients=1, weighted=True,
        timeout=60, stream_chunk_bytes=1 << 10,
    ) as root:
        relays = [
            RelayAggregator(
                "127.0.0.1", 0, parent_host="127.0.0.1",
                parent_port=root.port, relay_id=r, num_clients=per,
                timeout=60, stream_chunk_bytes=1 << 10,
            )
            for r in range(n_relays)
        ]
        try:
            rt = threading.Thread(
                target=lambda: root_aggs.append(
                    root.serve_round(deadline=20.0)
                ),
                daemon=True,
            )
            rt.start()
            for r, rel in enumerate(relays):
                if r not in victim_relays:
                    threading.Thread(
                        target=rel.serve, args=(1,), daemon=True
                    ).start()
                else:
                    rel.close()  # dead from the start: dial-exhausted
            clients = {}
            for cid in range(n_clients):
                r = cid // per
                if r in victim_relays:
                    clients[cid] = FederatedClient(
                        "127.0.0.1", relays[r].port, client_id=cid,
                        timeout=40,
                        fallback_parents=[
                            ("127.0.0.1", relays[adoptive[r]].port)
                        ],
                        rehome_dial_budget=1.5,
                    )
                else:
                    clients[cid] = FederatedClient(
                        "127.0.0.1", relays[r].port, client_id=cid,
                        timeout=40,
                    )

            def go(cid):
                try:
                    results[cid] = clients[cid].exchange(
                        models[cid], max_retries=3
                    )
                except Exception as e:  # noqa: BLE001
                    errors.append((cid, e))

            victim_ids = [
                c for c in range(n_clients) if c // per in victim_relays
            ]
            vt = [
                threading.Thread(target=go, args=(c,), daemon=True)
                for c in victim_ids
            ]
            for t in vt:
                t.start()
            # Hold the adoptive relays' own clients until every victim
            # re-homed and registered.
            for ar, want_ids in (
                (0, {c for c in victim_ids if c // per == 3}),
                (8, {c for c in victim_ids if c // per == 11}),
            ):
                _wait_registered(relays[ar].server, want_ids, 30)
            st = [
                threading.Thread(target=go, args=(c,), daemon=True)
                for c in range(n_clients)
                if c // per not in victim_relays
            ]
            for t in st:
                t.start()
            for t in vt + st:
                t.join(timeout=90)
            rt.join(timeout=60)
            assert not errors, errors[:3]
        finally:
            for rel in relays:
                rel.close()
        assert root_aggs and root_aggs[0] is not None
        assert root.tree_totals["subtree_failures"] == 2
        want = aggregate_tree(
            models, None, root.last_assignment["groups"]
        )
        assert wire.flat_crc32(root_aggs[0]) == wire.flat_crc32(want)
        crcs = {wire.flat_crc32(results[c]) for c in results}
        assert crcs == {wire.flat_crc32(want)}
