"""Fleet-scale rounds (ISSUE 7): streamed reply fan-out + the
hierarchical fold tree (comm/relay.py) for 64-256-client cohorts.

Contracts pinned here:

* Streamed replies are BYTE-IDENTICAL in value to dense replies —
  mixed fleets (advertising and old-peer clients) in one round receive
  the same aggregate, crc-equal to the barrier ``aggregate_flat``.
* The depth-2 fold tree's root aggregate is crc-bit-exact against
  :func:`aggregate_tree` — the pinned order (ascending client id within
  a subtree, fixed subtree order at the root) replayed flat from the
  captured uploads — and every individual fold in the tree is bit-exact
  against ``aggregate_flat`` over its own inputs.
* A LIVE 64-client loopback round at tree depth 2 completes under the
  bounded handler pool and keeps both contracts.
* Fold order is deterministic at scale: shuffled arrival orders through
  StreamAgg (flat and depth-2) produce ONE crc.
"""

import threading

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
    FederatedClient,
    RelayAggregator,
    StreamAgg,
    WireError,
    aggregate_flat,
    aggregate_tree,
    wire,
)

pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnraisableExceptionWarning"
)


def _leaves(rng, n=4, shape=(32, 9), scale=1.0):
    """Flat separator-free keys: exchange() returns these unchanged."""
    return {
        f"w{i:02d}": rng.normal(size=shape).astype(np.float32) * scale
        for i in range(n)
    }


def _run_clients(clients, uploads, n_samples=None, results=None, errors=None):
    """Drive one exchange per client on its own thread; collect replies."""
    results = {} if results is None else results
    errors = [] if errors is None else errors

    def go(cid):
        try:
            kw = {}
            if n_samples is not None:
                kw["n_samples"] = n_samples[cid]
            results[cid] = clients[cid].exchange(uploads[cid], **kw)
        except Exception as e:  # noqa: BLE001 - surfaced via the list
            errors.append((cid, e))

    threads = [
        threading.Thread(target=go, args=(cid,), daemon=True)
        for cid in clients
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    return results, errors


# ------------------------------------------------------ streamed replies
def test_streamed_reply_mixed_fleet_bit_exact(rng):
    """One round, one advertising client + one old-peer (dense) client:
    both receive the SAME aggregate, crc-equal to the barrier mean, and
    exactly one reply went out as a chunk stream."""
    models = [_leaves(rng) for _ in range(2)]
    with AggregationServer(
        port=0, num_clients=2, timeout=30, stream_chunk_bytes=1 << 10
    ) as server:
        clients = {
            0: FederatedClient(
                "127.0.0.1", server.port, client_id=0, timeout=30
            ),
            # stream=False = the pre-PR peer: no reply advert, no
            # streamed upload — the dense wire shape end to end.
            1: FederatedClient(
                "127.0.0.1", server.port, client_id=1, timeout=30,
                stream=False,
            ),
        }
        agg_thread_out = {}
        t = threading.Thread(
            target=lambda: agg_thread_out.setdefault(
                "agg", server.serve_round()
            ),
            daemon=True,
        )
        t.start()
        results, errors = _run_clients(clients, models)
        t.join(timeout=60)
        assert not errors, errors
        want = aggregate_flat(models)
        for cid in (0, 1):
            got = results[cid]
            assert wire.flat_crc32(got) == wire.flat_crc32(want)
            for k in want:
                np.testing.assert_array_equal(got[k], want[k])
        # Exactly the advertising client's reply streamed; the old peer's
        # dense upload counted as a fallback while streaming was on.
        assert server.stream_totals["stream_replies"] == 1
        assert server.stream_totals["stream_fallbacks"] >= 1


def test_streamed_reply_auth_round(rng):
    """HMAC round: the reply's header/chunk/trailer tags ride the
    REPLY-direction domains and the aggregate still decodes bit-exact."""
    key = b"fleet-secret"
    models = [_leaves(rng) for _ in range(2)]
    with AggregationServer(
        port=0, num_clients=2, timeout=30, auth_key=key,
        stream_chunk_bytes=1 << 10,
    ) as server:
        clients = {
            cid: FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=30,
                auth_key=key,
            )
            for cid in range(2)
        }
        t = threading.Thread(target=server.serve_round, daemon=True)
        t.start()
        results, errors = _run_clients(clients, models)
        t.join(timeout=60)
        assert not errors, errors
        want = aggregate_flat(models)
        for cid in range(2):
            assert wire.flat_crc32(results[cid]) == wire.flat_crc32(want)
        assert server.stream_totals["stream_replies"] == 2


def test_reply_direction_domains_reject_reflection():
    """An upload-domain chunk tag never verifies under the reply-domain
    check (and vice versa) — the reflection hole disjoint domains close."""
    key, nonce = b"secret", b"\x07" * 16
    up = wire.encode_stream_chunk(0, b"data", auth_key=key, nonce=nonce)
    with pytest.raises(WireError, match="HMAC"):
        wire.decode_stream_chunk(
            up, expect_seq=0, auth_key=key, nonce=nonce, direction="down"
        )
    down = wire.encode_stream_chunk(
        0, b"data", auth_key=key, nonce=nonce, direction="down"
    )
    with pytest.raises(WireError, match="HMAC"):
        wire.decode_stream_chunk(
            down, expect_seq=0, auth_key=key, nonce=nonce
        )
    hdr = wire.encode_stream_header(
        [], chunk_bytes=64, payload_nbytes=0, auth_key=key
    )
    with pytest.raises(WireError, match="HMAC"):
        wire.decode_stream_header(hdr, auth_key=key, direction="down")
    end = wire.encode_stream_end(3, auth_key=key, nonce=nonce)
    with pytest.raises(WireError, match="HMAC"):
        wire.decode_stream_end(
            end, expect_chunks=3, auth_key=key, nonce=nonce,
            direction="down",
        )


def test_reply_leaf_sink_sees_every_leaf(rng):
    """The streamed-reply sink runs per leaf as bytes land; its returned
    objects ARE the aggregate the caller receives (the mesh tier returns
    device-placed leaves here)."""

    class Tagged:
        def __init__(self, arr):
            self.arr = arr

    models = [_leaves(rng) for _ in range(2)]
    seen: list[str] = []
    with AggregationServer(
        port=0, num_clients=2, timeout=30, stream_chunk_bytes=1 << 10
    ) as server:
        clients = {
            cid: FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=30
            )
            for cid in range(2)
        }

        def sink(key, arr):
            seen.append(key)
            return Tagged(arr)

        clients[0].reply_leaf_sink = sink
        t = threading.Thread(target=server.serve_round, daemon=True)
        t.start()
        results, errors = _run_clients(clients, models)
        t.join(timeout=60)
        assert not errors, errors
        want = aggregate_flat(models)
        assert sorted(seen) == sorted(want)
        for k in want:
            assert isinstance(results[0][k], Tagged)
            np.testing.assert_array_equal(results[0][k].arr, want[k])
            np.testing.assert_array_equal(results[1][k], want[k])


def test_mesh_trainer_sink_places_on_device(rng):
    """MeshTrainer.reply_leaf_sink returns a replicated device leaf with
    unchanged bytes — placement only, no arithmetic."""
    import jax

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.mesh import (
        make_host_mesh,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.client_mesh import (
        MeshTrainer,
    )

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    trainer = MeshTrainer(
        ModelConfig.tiny(), TrainConfig(), mesh=make_host_mesh(2)
    )
    arr = rng.normal(size=(8, 4)).astype(np.float32)
    placed = trainer.reply_leaf_sink("w", arr)
    assert isinstance(placed, jax.Array)
    assert placed.sharding == trainer.replicated
    np.testing.assert_array_equal(np.asarray(placed), arr)


# -------------------------------------------------- hierarchical fold tree
def _run_tree(rng, n_clients, n_relays, *, n_samples=None, leaf_shape=(16, 5),
              trace_dir=None, rounds=1, chunk=1 << 10):
    """Stand up root + relays + clients on loopback, run ``rounds``
    rounds, return (models, results, groups, root_aggs)."""
    per = n_clients // n_relays
    groups = [list(range(r * per, (r + 1) * per)) for r in range(n_relays)]
    models = [_leaves(rng, n=3, shape=leaf_shape) for _ in range(n_clients)]
    tracer = None
    if trace_dir is not None:
        from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
            Tracer,
        )

        tracer = Tracer(f"{trace_dir}/relay.jsonl", proc="relay-0")
    root_aggs: list[dict] = []
    with AggregationServer(
        port=0, num_clients=n_relays, weighted=True, timeout=60,
        stream_chunk_bytes=chunk,
    ) as root:
        relays = [
            RelayAggregator(
                "127.0.0.1", 0,
                parent_host="127.0.0.1", parent_port=root.port,
                relay_id=r, num_clients=per, timeout=60,
                stream_chunk_bytes=chunk,
                tracer=tracer if r == 0 else None,
            )
            for r in range(n_relays)
        ]
        try:
            def root_loop():
                for _ in range(rounds):
                    root_aggs.append(root.serve_round())

            rt = threading.Thread(target=root_loop, daemon=True)
            rt.start()
            relay_threads = [
                threading.Thread(
                    target=rel.serve, args=(rounds,), daemon=True
                )
                for rel in relays
            ]
            for t in relay_threads:
                t.start()
            clients = {
                cid: FederatedClient(
                    "127.0.0.1",
                    relays[cid // per].port,
                    client_id=cid,
                    timeout=60,
                )
                for cid in range(n_clients)
            }
            all_results: dict[int, dict] = {}
            errors: list = []
            for _ in range(rounds):
                results, errs = _run_clients(
                    clients, models, n_samples=n_samples
                )
                errors.extend(errs)
                all_results = results
            rt.join(timeout=90)
            for t in relay_threads:
                t.join(timeout=30)
            assert not errors, errors
            peak = max(
                rel.server.stream_totals["peak_agg_bytes"] for rel in relays
            )
            return models, all_results, groups, root_aggs, peak
        finally:
            for rel in relays:
                rel.close()


def test_relay_depth2_bit_exact_vs_tree_replay(rng, tmp_path):
    """Live depth-2 round (2 relays x 2 clients): every client receives
    the root aggregate, crc-bit-exact vs aggregate_tree's pinned replay;
    each subtree fold and the root fold are each bit-exact vs
    aggregate_flat over their own inputs; the flat all-N mean agrees to
    reduction-order ulps. The relay-forward span lands on the obs
    timeline vocabulary."""
    n_samples = {0: 5, 1: 1, 2: 3, 3: 2}
    models, results, groups, root_aggs, _peak = _run_tree(
        rng, 4, 2, n_samples=n_samples, trace_dir=str(tmp_path)
    )
    weights = [float(n_samples[i]) for i in range(4)]
    want = aggregate_tree(models, weights, groups)
    assert len(root_aggs) == 1 and root_aggs[0] is not None
    assert wire.flat_crc32(root_aggs[0]) == wire.flat_crc32(want)
    for cid in range(4):
        assert wire.flat_crc32(results[cid]) == wire.flat_crc32(want)
    # Each tree fold individually == the barrier mean over its inputs.
    partial0 = aggregate_flat([models[0], models[1]], weights[:2])
    partial1 = aggregate_flat([models[2], models[3]], weights[2:])
    root_ref = aggregate_flat(
        [partial0, partial1], [sum(weights[:2]), sum(weights[2:])]
    )
    assert wire.flat_crc32(root_ref) == wire.flat_crc32(want)
    # The flat all-N mean differs by fp32 reduction-order ulps at most.
    flat_ref = aggregate_flat(models, weights)
    for k in want:
        np.testing.assert_allclose(
            want[k], flat_ref[k], rtol=1e-5, atol=1e-6
        )
    # relay-forward span: the tree tier's line on the obs timeline.
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.timeline import (
        load_spans,
    )

    spans = load_spans(trace_dir=str(tmp_path))
    fwd = [s for s in spans if s["span"] == "relay-forward"]
    assert fwd and fwd[0]["subtree_clients"] == 2
    assert fwd[0]["parent_round"] is not None


def test_relay_sparse_delta_base_tracks_root(rng):
    """A topk client behind a relay: round 2's sparse delta validates
    against the ROOT aggregate the client adopted (the relay's _last_agg
    is the forwarded result, not the subtree partial)."""
    n_clients, n_relays, rounds = 4, 2, 2
    per = n_clients // n_relays
    models = [_leaves(rng, n=3, shape=(16, 5)) for _ in range(n_clients)]
    with AggregationServer(
        port=0, num_clients=n_relays, weighted=True, timeout=60,
        stream_chunk_bytes=1 << 10,
    ) as root:
        relays = [
            RelayAggregator(
                "127.0.0.1", 0, parent_host="127.0.0.1",
                parent_port=root.port, relay_id=r, num_clients=per,
                timeout=60, stream_chunk_bytes=1 << 10,
            )
            for r in range(n_relays)
        ]
        try:
            rt = threading.Thread(
                target=lambda: [root.serve_round() for _ in range(rounds)],
                daemon=True,
            )
            rt.start()
            for rel in relays:
                threading.Thread(
                    target=rel.serve, args=(rounds,), daemon=True
                ).start()
            clients = {
                cid: FederatedClient(
                    "127.0.0.1", relays[cid // per].port, client_id=cid,
                    timeout=60,
                    compression="topk:0.5" if cid == 0 else "none",
                )
                for cid in range(n_clients)
            }
            last = {}
            for _ in range(rounds):
                uploads = {
                    cid: {
                        k: v + np.float32(0.01)
                        for k, v in (last.get(cid) or models[cid]).items()
                    }
                    for cid in clients
                }
                last, errors = _run_clients(clients, uploads)
                assert not errors, errors
            # Round 2 went sparse against the adopted ROOT base — the
            # client only adopts a base whose crc matches the relay's
            # agg_crc stamp, so reaching here proves base agreement.
            assert clients[0]._base is not None
            rt.join(timeout=60)
        finally:
            for rel in relays:
                rel.close()


def test_fleet_64_clients_depth2_live(rng):
    """The acceptance-shaped round: 64 live loopback clients, 8 relays
    of 8, one root — completes under the bounded handler pool with the
    root aggregate crc-bit-exact vs the pinned tree replay."""
    models, results, groups, root_aggs, peak = _run_tree(
        rng, 64, 8, leaf_shape=(64,), chunk=256
    )
    want = aggregate_tree(models, None, groups)
    assert wire.flat_crc32(root_aggs[0]) == wire.flat_crc32(want)
    crcs = {wire.flat_crc32(results[cid]) for cid in range(64)}
    assert crcs == {wire.flat_crc32(want)}
    assert peak > 0


# -------------------------------------------- fold-order determinism @ 64
def test_fold_order_determinism_64_contributors(rng):
    """Property test: 64 seeded contributors folded through StreamAgg in
    shuffled arrival orders — flat and depth-2 — always produce ONE crc
    (the pinned ascending-id / fixed-subtree-order arithmetic is arrival-
    order invariant)."""
    n, n_groups = 64, 8
    keys = tuple(sorted(f"k{i}" for i in range(3)))
    models = [
        {k: rng.normal(size=(8, 3)).astype(np.float32) for k in keys}
        for _ in range(n)
    ]
    weights = [float(w) for w in rng.integers(1, 9, size=n)]
    groups = [
        list(range(g * (n // n_groups), (g + 1) * (n // n_groups)))
        for g in range(n_groups)
    ]

    def flat_crc(order):
        st = StreamAgg()
        for cid in order:
            st.register(cid, keys=keys, n_samples=weights[cid])
        st.freeze(list(range(n)), weights)
        for cid in order:
            st.add_dense(cid, models[cid])
        return wire.flat_crc32(st.finalize(list(range(n)), weights))

    def tree_crc(order):
        partials, masses = [], []
        for g in groups:
            st = StreamAgg()
            ws = [weights[i] for i in g]
            for cid in [c for c in order if c in g]:
                st.register(cid, keys=keys, n_samples=weights[cid])
            st.freeze(list(g), ws)
            for cid in [c for c in order if c in g]:
                st.add_dense(cid, models[cid])
            partials.append(st.finalize(list(g), ws))
            masses.append(sum(ws))
        root = StreamAgg()
        for r in range(n_groups):
            root.register(r, keys=keys, n_samples=masses[r])
        root.freeze(list(range(n_groups)), masses)
        for r in range(n_groups):
            root.add_dense(r, partials[r])
        return wire.flat_crc32(
            root.finalize(list(range(n_groups)), masses)
        )

    orders = [list(range(n))]
    for _ in range(3):
        o = list(range(n))
        rng.shuffle(o)
        orders.append(o)
    flat_crcs = {flat_crc(o) for o in orders}
    assert flat_crcs == {wire.flat_crc32(aggregate_flat(models, weights))}
    tree_crcs = {tree_crc(o) for o in orders}
    assert tree_crcs == {
        wire.flat_crc32(aggregate_tree(models, weights, groups))
    }


def test_aggregate_tree_validates_groups(rng):
    with pytest.raises(ValueError, match="non-empty"):
        aggregate_tree([_leaves(rng)], None, [])
    with pytest.raises(ValueError, match="non-empty"):
        aggregate_tree([_leaves(rng)], None, [[0], []])
    with pytest.raises(ValueError, match="non-empty"):
        # Nested subtrees validate at every depth.
        aggregate_tree([_leaves(rng)], None, [[[0], []]])


def test_aggregate_tree_nested_depth3_replay(rng):
    """The nested-groups replay (a relay whose parent is another relay):
    a depth-3 tree folds bottom-up, each fold the exact weighted
    ``aggregate_flat`` over its children, and the depth-2 call shape is
    byte-for-byte what it always was."""
    n = 8
    models = [_leaves(rng, n=3, shape=(8, 3)) for _ in range(n)]
    weights = [float(w) for w in rng.integers(1, 9, size=n)]
    tree = [[[0, 1], [2, 3]], [[4, 5], [6, 7]]]
    got = aggregate_tree(models, weights, tree)
    # Manual bottom-up replay with aggregate_flat.
    lows, lmass = [], []
    for g in ([0, 1], [2, 3], [4, 5], [6, 7]):
        ws = [weights[i] for i in g]
        lows.append(aggregate_flat([models[i] for i in g], ws))
        lmass.append(sum(ws))
    mids = [
        aggregate_flat(lows[:2], lmass[:2]),
        aggregate_flat(lows[2:], lmass[2:]),
    ]
    want = aggregate_flat(
        mids, [lmass[0] + lmass[1], lmass[2] + lmass[3]]
    )
    assert wire.flat_crc32(got) == wire.flat_crc32(want)
    # Depth-2 shape: the classic groups call is arithmetic-identical to
    # composing the same groups as one-level subtrees.
    groups = [[0, 1, 2, 3], [4, 5, 6, 7]]
    d2 = aggregate_tree(models, weights, groups)
    p0 = aggregate_flat([models[i] for i in groups[0]],
                        [weights[i] for i in groups[0]])
    p1 = aggregate_flat([models[i] for i in groups[1]],
                        [weights[i] for i in groups[1]])
    d2_want = aggregate_flat(
        [p0, p1],
        [sum(weights[i] for i in groups[0]),
         sum(weights[i] for i in groups[1])],
    )
    assert wire.flat_crc32(d2) == wire.flat_crc32(d2_want)
    # Bare int leaves may sit next to subtrees at any level.
    mixed = aggregate_tree(models, weights, [0, [1, 2], 3])
    inner = aggregate_flat([models[1], models[2]], weights[1:3])
    mixed_want = aggregate_flat(
        [models[0], inner, models[3]],
        [weights[0], weights[1] + weights[2], weights[3]],
    )
    assert wire.flat_crc32(mixed) == wire.flat_crc32(mixed_want)


@pytest.mark.slow
def test_relay_depth3_live_bit_exact_vs_nested_replay(rng):
    """A LIVE 3-level loopback round — 8 clients under 4 leaf relays
    under 2 mid relays under one weighted root (a relay's parent IS
    another relay; the wire composes) — crc-pinned bit-exact against the
    depth-3 ``aggregate_tree`` replay, with every client receiving the
    root aggregate."""
    n_clients, n_leaf, n_mid = 8, 4, 2
    n_samples = {i: int(w) for i, w in enumerate(
        rng.integers(1, 9, size=n_clients)
    )}
    models = [_leaves(rng, n=3, shape=(16, 3)) for _ in range(n_clients)]
    chunk = 1 << 10
    results: dict[int, dict] = {}
    root_aggs: list[dict] = []
    with AggregationServer(
        port=0, num_clients=n_mid, weighted=True, timeout=60,
        stream_chunk_bytes=chunk,
    ) as root:
        mids = [
            RelayAggregator(
                "127.0.0.1", 0, parent_host="127.0.0.1",
                parent_port=root.port, relay_id=m, num_clients=2,
                timeout=60, stream_chunk_bytes=chunk,
            )
            for m in range(n_mid)
        ]
        leafs = [
            RelayAggregator(
                "127.0.0.1", 0, parent_host="127.0.0.1",
                parent_port=mids[r // 2].port, relay_id=r % 2,
                num_clients=2, timeout=60, stream_chunk_bytes=chunk,
            )
            for r in range(n_leaf)
        ]
        try:
            rt = threading.Thread(
                target=lambda: root_aggs.append(root.serve_round()),
                daemon=True,
            )
            rt.start()
            for rel in mids + leafs:
                threading.Thread(
                    target=rel.serve, args=(1,), daemon=True
                ).start()
            clients = {
                cid: FederatedClient(
                    "127.0.0.1", leafs[cid // 2].port, client_id=cid,
                    timeout=60,
                )
                for cid in range(n_clients)
            }
            results, errors = _run_clients(
                clients, models, n_samples=n_samples
            )
            rt.join(timeout=90)
            assert not errors, errors
        finally:
            for rel in mids + leafs:
                rel.close()
    weights = [float(n_samples[i]) for i in range(n_clients)]
    tree = [[[0, 1], [2, 3]], [[4, 5], [6, 7]]]
    want = aggregate_tree(models, weights, tree)
    assert len(root_aggs) == 1 and root_aggs[0] is not None
    assert wire.flat_crc32(root_aggs[0]) == wire.flat_crc32(want)
    for cid in range(n_clients):
        assert wire.flat_crc32(results[cid]) == wire.flat_crc32(want)
    # Sanity: the depth-3 replay differs from flat all-N by reduction-
    # order ulps only.
    flat_ref = aggregate_flat(models, weights)
    for k in want:
        np.testing.assert_allclose(want[k], flat_ref[k], rtol=1e-5,
                                   atol=1e-6)


# ---------------------------------------------------- server fleet plumbing
def test_bounded_pool_and_backlog_sizing():
    with AggregationServer(port=0, num_clients=256, timeout=5) as server:
        # Bounded handler pool: 2x the fleet + slack, never unbounded.
        assert server._pool._max_workers == 2 * 256 + 8
    with AggregationServer(port=0, num_clients=2, timeout=5) as server:
        assert server._pool._max_workers == 12


def test_reply_via_refused_under_dp_and_secure():
    with AggregationServer(
        port=0, num_clients=2, timeout=1, dp_clip=1.0
    ) as server:
        server.reply_via = lambda agg, info: agg
        with pytest.raises(ValueError, match="reply_via"):
            server.serve_round(deadline=0.2)
    with AggregationServer(
        port=0, num_clients=2, timeout=1, secure_agg=True
    ) as server:
        server.reply_via = lambda agg, info: agg
        with pytest.raises(ValueError, match="reply_via"):
            server.serve_round(deadline=0.2)


def test_dense_fallback_reason_logged_once(rng):
    """A client that cannot stream logs its one-line reason exactly once
    per reason (old peers would otherwise say it every round)."""
    models = [_leaves(rng) for _ in range(1)]
    with AggregationServer(
        port=0, num_clients=1, timeout=30, stream_chunk_bytes=1 << 10
    ) as server:
        fc = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=30, stream=False
        )
        for _ in range(2):
            t = threading.Thread(target=server.serve_round, daemon=True)
            t.start()
            fc.exchange(models[0])
            t.join(timeout=30)
        assert fc._fallback_logged == {"--no-stream-upload"}
        assert server.stream_totals["stream_fallbacks"] == 2
        assert server.stream_totals["stream_replies"] == 0


def test_relay_cli_parser_wiring():
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        build_parser,
    )

    args = build_parser().parse_args(
        [
            "relay", "--port", "0", "--parent-port", "12345",
            "--relay-id", "3", "--num-clients", "8", "--rounds", "2",
            "--stream-chunk-mb", "1",
        ]
    )
    assert args.relay_id == 3 and args.num_clients == 8
    assert args.fn.__name__ == "cmd_relay"
    assert args.stream_upload is True


@pytest.mark.slow
def test_fleet_128_clients_depth2_live(rng):
    """Scale margin beyond the acceptance floor: 128 clients, 16 relays."""
    models, results, groups, root_aggs, _peak = _run_tree(
        rng, 128, 16, leaf_shape=(32,), chunk=128
    )
    want = aggregate_tree(models, None, groups)
    assert wire.flat_crc32(root_aggs[0]) == wire.flat_crc32(want)
    assert {wire.flat_crc32(results[c]) for c in range(128)} == {
        wire.flat_crc32(want)
    }
