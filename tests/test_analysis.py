"""The `fedtpu check` static-analysis subsystem (analysis/): per-rule
fixture snippets (positive + pragma-suppressed), baseline semantics,
the seeded-mutation self-test (a temp copy of the real tree with one
invariant broken per mutation must exit nonzero), the repo
self-scan-clean contract, and the runtime lock-order detector."""

import argparse
import json
import os
import shutil
import textwrap
import threading

import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.analysis import (
    all_rules,
    run_check,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.analysis import (
    lockorder,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.check import (
    cmd_check,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_NAME = (
    "detecting_cyber_attacks_with_distilled_large_language_models_in_"
    "distributed_networks_tpu"
)


# ------------------------------------------------------------ fixture trees
def _mini_tree(tmp_path, files: dict) -> str:
    """Write a throwaway package tree: {relpath: source} under
    tmp/pkgx/ with an __init__.py per directory."""
    root = tmp_path / "mini"
    for rel, src in files.items():
        path = root / "pkgx" / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
        d = path.parent
        while d != root:
            init = d / "__init__.py"
            if not init.exists():
                init.write_text("")
            d = d.parent
    return str(root)


def _findings(root, rules):
    return run_check(root, rules=rules, baseline_path=None).new


# ------------------------------------------------------------- wire rules
WIRE_OK = """
    A_MAGIC = b"AAAA"
    B_MAGIC = b"BBBB"
    _X_DOMAIN = b"fedtpu-x-v1"
    _Y_DOMAIN = b"fedtpu-y-v1"

    def encode_a(x):
        return A_MAGIC + encode_b(x)

    def decode_a(x):
        return x[len(A_MAGIC):]

    def encode_b(x):
        return B_MAGIC

    def decode_b(x):
        return x[len(B_MAGIC):]
"""


def test_wire_domain_unique_flags_duplicate_and_unversioned(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "comm/wire.py": """
                A_MAGIC = b"AAAA"
                B_MAGIC = b"AAAA"
                _X_DOMAIN = b"fedtpu-x-v1"
                _Y_DOMAIN = b"fedtpu-y"
                LONG_MAGIC = b"TOOLONG"
            """
        },
    )
    found = _findings(root, ["wire-domain-unique"])
    messages = "\n".join(f.message for f in found)
    assert "B_MAGIC duplicates the byte value of A_MAGIC" in messages
    assert "-v<N>' version suffix" in messages and "_Y_DOMAIN" in messages
    assert "LONG_MAGIC is 7 bytes" in messages


def test_wire_domain_unique_spans_stream_domains_table(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "comm/wire.py": """
                _HDR_DOMAIN = b"fedtpu-hdr-v1"
                _STREAM_DOMAINS = {
                    "up": (_HDR_DOMAIN,),
                    "down": (b"fedtpu-hdr-v1",),
                }
                A_MAGIC = b"AAAA"
            """
        },
    )
    found = _findings(root, ["wire-domain-unique"])
    assert any(
        "duplicates the byte value of _HDR_DOMAIN" in f.message for f in found
    )


def test_wire_domain_clean_tree_passes(tmp_path):
    root = _mini_tree(tmp_path, {"comm/wire.py": WIRE_OK})
    assert _findings(root, ["wire-domain-unique"]) == []


def test_wire_meta_key_unique_flags_duplicate_empty_and_stray(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "comm/wire.py": """
                STREAM_META_KEY = "stream"
                REHOME_META_KEY = "stream"
                EMPTY_META_KEY = ""
            """,
            "comm/client.py": """
                LOCAL_META_KEY = "local"
            """,
        },
    )
    found = _findings(root, ["wire-meta-key-unique"])
    messages = "\n".join(f.message for f in found)
    assert (
        "REHOME_META_KEY duplicates the meta-key string of "
        "STREAM_META_KEY" in messages
    )
    assert "EMPTY_META_KEY must be a non-empty string" in messages
    assert "LOCAL_META_KEY declared outside the wire layer" in messages


def test_wire_meta_key_clean_tree_and_lost_anchor(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "comm/wire.py": """
                A_META_KEY = "a"
            """,
            "obs/trace.py": """
                TRACE_META_KEY = "trace"
            """,
        },
    )
    assert _findings(root, ["wire-meta-key-unique"]) == []
    bare = _mini_tree(
        tmp_path / "bare", {"comm/wire.py": "A_MAGIC = b'AAAA'\n"}
    )
    found = _findings(bare, ["wire-meta-key-unique"])
    assert any("lost its anchor" in f.message for f in found)


def test_wire_magic_coverage_flags_one_sided_and_adhoc(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "comm/wire.py": """
                A_MAGIC = b"AAAA"
                ORPHAN_MAGIC = b"ORPH"

                def encode_a(x):
                    return A_MAGIC

                def decode_a(x):
                    return x[len(A_MAGIC):]

                def encode_orphan():
                    return ORPHAN_MAGIC
            """,
            "comm/server.py": """
                from . import wire

                def dispatch(data):
                    if data[:4] == wire.A_MAGIC:
                        return wire.decode_a(data)
                    if data[:4] == b"ADHC":
                        return None
            """,
        },
    )
    found = _findings(root, ["wire-magic-coverage"])
    messages = "\n".join(f.message for f in found)
    assert "ORPHAN_MAGIC is referenced from 1 function scope" in messages
    assert "b'ADHC' outside the wire layer" in messages
    assert "A_MAGIC" not in messages


def test_wire_magic_dead_frame_type_flagged(tmp_path):
    # Encode+decode exist in wire.py but nothing outside ever dispatches.
    root = _mini_tree(
        tmp_path,
        {
            "comm/wire.py": """
                DEAD_MAGIC = b"DEAD"

                def encode_dead():
                    return DEAD_MAGIC

                def decode_dead(x):
                    return x[len(DEAD_MAGIC):]
            """,
            "comm/other.py": "VALUE = 1\n",
        },
    )
    found = _findings(root, ["wire-magic-coverage"])
    assert any("never dispatched" in f.message for f in found)


def test_wire_stream_direction_required_outside_wire(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "comm/wire.py": "def encode_stream_chunk(s, d, direction='up'):\n    return d\n",
            "comm/client.py": """
                from .wire import encode_stream_chunk

                def good(d):
                    return encode_stream_chunk(0, d, direction="up")

                def bad(d):
                    return encode_stream_chunk(0, d)

                def allowed(d):
                    return encode_stream_chunk(0, d)  # fedtpu: allow(wire-stream-direction): test
            """,
        },
    )
    result = run_check(
        root, rules=["wire-stream-direction"], baseline_path=None
    )
    assert len(result.new) == 1
    assert "encode_stream_chunk() called without" in result.new[0].message
    assert result.allowed == 1


# ---------------------------------------------------------- determinism
def test_determinism_flags_entropy_in_contract_modules(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "data/partition.py": """
                import os
                import random
                import time

                import numpy as np

                def bad_partition(items):
                    random.shuffle(items)
                    t = time.time()
                    k = np.random.rand()
                    n = os.urandom(4)
                    for x in set(items):
                        yield x, t, k, n

                def fine(items, seed):
                    rng = np.random.default_rng(seed)
                    rng2 = random.Random(seed)
                    t0 = time.monotonic()
                    for x in sorted(set(items)):
                        yield x, rng.integers(3), t0, rng2.random()
            """,
            "train/engine.py": """
                import time

                def outside_scope():
                    return time.time()  # not a crc-contract module
            """,
        },
    )
    found = _findings(root, ["determinism"])
    assert len(found) == 5
    assert all(f.path.endswith("data/partition.py") for f in found)
    kinds = "\n".join(f.message for f in found)
    assert "random.shuffle" in kinds and "wall clock" in kinds
    assert "np.random.rand" in kinds and "os.urandom" in kinds
    assert "iteration directly over a set" in kinds


def test_determinism_pragma_suppresses_with_reason(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "faults/proxy.py": """
                import time

                def span_stamp():
                    # fedtpu: allow(determinism): span timestamp only
                    return time.time()
            """
        },
    )
    result = run_check(root, rules=["determinism"], baseline_path=None)
    assert result.new == [] and result.allowed == 1


# ------------------------------------------------------------- unguarded
THREADED_BAD = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def start(self):
            threading.Thread(target=self._run).start()

        def _run(self):
            self.count += 1

        def bump(self):
            self.count += 1
"""


def test_unguarded_cross_thread_write_flagged(tmp_path):
    root = _mini_tree(tmp_path, {"comm/w.py": THREADED_BAD})
    found = _findings(root, ["unguarded"])
    assert len(found) == 2  # both the thread-side and main-side writes
    assert all("Worker.count" in f.message for f in found)


def test_unguarded_lock_guard_and_pragma_pass(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "comm/w.py": """
                import threading

                class Worker:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.count = 0
                        self.noted = 0

                    def start(self):
                        threading.Thread(target=self._run).start()

                    def _run(self):
                        with self._lock:
                            self.count += 1
                        self.noted += 1  # fedtpu: allow(unguarded): test-only

                    def bump(self):
                        with self._lock:
                            self.count += 1

                    def note(self):
                        with self._lock:
                            self.noted += 1
            """
        },
    )
    result = run_check(root, rules=["unguarded"], baseline_path=None)
    assert result.new == [] and result.allowed == 1


def test_unguarded_pool_selfrace_rmw_flagged(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "serving/w.py": """
                from concurrent.futures import ThreadPoolExecutor

                class Stats:
                    def __init__(self):
                        self.pool = ThreadPoolExecutor(4)
                        self.hits = 0

                    def handle(self, conn):
                        self.pool.submit(self._work, conn)

                    def _work(self, conn):
                        self.hits += 1
            """
        },
    )
    found = _findings(root, ["unguarded"])
    assert len(found) == 1
    assert "concurrently with itself" in found[0].message


def test_unguarded_mutator_calls_count_as_writes(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "comm/w.py": """
                import threading

                class Acc:
                    def __init__(self):
                        self.items = []

                    def start(self):
                        threading.Thread(target=self._run).start()

                    def _run(self):
                        self.items.append(1)

                    def push(self, x):
                        self.items.append(x)
            """
        },
    )
    found = _findings(root, ["unguarded"])
    assert len(found) == 2 and all("Acc.items" in f.message for f in found)


# ------------------------------------------------------------- obs rules
def test_obs_span_vocab_flags_off_vocabulary_names(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "obs/trace.py": """
                SPAN_NAMES = (
                    "round",
                    "agg",
                )
            """,
            "comm/server.py": """
                def emit(tracer):
                    tracer.record("round", t_start=0, dur_s=0)
                    tracer.record("bogus-span", t_start=0, dur_s=0)
                    with tracer.span("agg"):
                        pass

                def emit2(tracer):
                    from ..obs.trace import maybe_span
                    with maybe_span(tracer, "unknown-span"):
                        pass
            """,
        },
    )
    found = _findings(root, ["obs-span-vocab"])
    assert sorted(f.message.split("'")[1] for f in found) == [
        "bogus-span",
        "unknown-span",
    ]


def test_obs_metric_once_kind_suffix_and_module_checks(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "serving/a.py": """
                def setup(m):
                    m.counter("x_total")
                    m.counter("bad_name")
                    m.gauge("depth")
            """,
            "control/b.py": """
                def setup(m):
                    m.gauge("x_total")
                    m.gauge("depth")
            """,
        },
    )
    found = _findings(root, ["obs-metric-once"])
    messages = "\n".join(f.message for f in found)
    assert "'x_total' registered as counter here but as gauge" in messages
    assert "counter 'bad_name' does not end in '_total'" in messages
    assert "'depth' registered from multiple modules" in messages


def test_bench_headline_asserted_fields_must_be_produced(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "bench.py": """
                def check(rec):
                    missing = [
                        k
                        for k in ("produced_headline", "ghost_headline")
                        if k not in rec
                    ]
                    return missing

                def build():
                    rec = {"produced_headline": 1.0}
                    return rec
            """
        },
    )
    # bench.py must sit at the scanned root, not inside the package dir.
    os.rename(
        os.path.join(root, "pkgx", "bench.py"), os.path.join(root, "bench.py")
    )
    found = _findings(root, ["bench-headline"])
    assert len(found) == 1 and "ghost_headline" in found[0].message


# ----------------------------------------------------- baseline semantics
def test_baseline_suppresses_and_reports_stale(tmp_path):
    root = _mini_tree(
        tmp_path,
        {
            "faults/proxy.py": """
                import time

                def stamp():
                    return time.time()
            """
        },
    )
    finding = _findings(root, ["determinism"])[0]
    baseline = tmp_path / "BASELINE.json"
    baseline.write_text(
        json.dumps(
            {
                "findings": [
                    {
                        "rule": finding.rule,
                        "path": finding.path,
                        "message": finding.message,
                        "reason": "fixture",
                    },
                    {
                        "rule": "determinism",
                        "path": "faults/gone.py",
                        "message": "no longer fires",
                        "reason": "stale entry",
                    },
                ]
            }
        )
    )
    result = run_check(
        root, rules=["determinism"], baseline_path=str(baseline)
    )
    assert result.new == [] and len(result.baselined) == 1
    assert result.exit_code == 0
    assert len(result.stale_baseline) == 1


def test_prune_baseline_rewrites_minus_stale_only(tmp_path, capsys):
    """`fedtpu check --prune-baseline`: stale entries are REMOVED from
    the baseline file, live entries and the review comment survive, and
    a re-run against the pruned baseline is clean with zero stale."""
    root = _mini_tree(
        tmp_path,
        {
            "faults/proxy.py": """
                import time

                def stamp():
                    return time.time()
            """
        },
    )
    finding = _findings(root, ["determinism"])[0]
    baseline = tmp_path / "BASELINE.json"
    live_entry = {
        "rule": finding.rule,
        "path": finding.path,
        "message": finding.message,
        "reason": "fixture",
    }
    baseline.write_text(
        json.dumps(
            {
                "comment": "review note must survive the prune",
                "findings": [
                    live_entry,
                    {
                        "rule": "determinism",
                        "path": "faults/gone.py",
                        "message": "no longer fires",
                        "reason": "stale entry",
                    },
                    {
                        "rule": "determinism",
                        "path": "faults/also_gone.py",
                        "message": "also gone",
                        "reason": "second stale entry",
                    },
                ],
            }
        )
    )
    args = argparse.Namespace(
        root=root,
        rules="determinism",
        baseline=str(baseline),
        prune_baseline=True,
        json=False,
        list_rules=False,
    )
    assert cmd_check(args) == 0
    out = capsys.readouterr().out
    assert "pruned 2 stale baseline entries" in out
    data = json.loads(baseline.read_text())
    assert data["comment"] == "review note must survive the prune"
    assert data["findings"] == [live_entry]
    # The pruned baseline stays clean: still suppresses the live
    # finding, reports ZERO stale.
    result = run_check(
        root, rules=["determinism"], baseline_path=str(baseline)
    )
    assert result.exit_code == 0
    assert len(result.baselined) == 1
    assert result.stale_baseline == []
    # A second prune is a no-op (removes 0).
    assert cmd_check(args) == 0
    assert "pruned 0 stale baseline entries" in capsys.readouterr().out
    assert json.loads(baseline.read_text())["findings"] == [live_entry]


def test_prune_baseline_without_file_errors(tmp_path, capsys):
    root = _mini_tree(tmp_path, {"comm/a.py": "X = 1\n"})
    args = argparse.Namespace(
        root=root,
        rules="determinism",
        baseline=None,
        prune_baseline=True,
        json=False,
        list_rules=False,
    )
    assert cmd_check(args) == 2
    assert "no baseline file" in capsys.readouterr().err


def test_baseline_entry_without_reason_rejected(tmp_path):
    baseline = tmp_path / "BASELINE.json"
    baseline.write_text(
        json.dumps(
            {
                "findings": [
                    {"rule": "determinism", "path": "x.py", "message": "m"}
                ]
            }
        )
    )
    root = _mini_tree(tmp_path, {"comm/a.py": "X = 1\n"})
    with pytest.raises(ValueError, match="no reason"):
        run_check(root, rules=["determinism"], baseline_path=str(baseline))


# ------------------------------------------------- seeded-mutation self-test
@pytest.fixture()
def repo_copy(tmp_path):
    """The real package + bench.py + baseline copied to a temp root —
    the mutation tests break ONE invariant each and expect `fedtpu
    check` to exit nonzero on the copy."""
    dst = tmp_path / "copy"
    dst.mkdir()
    shutil.copytree(
        os.path.join(REPO_ROOT, PKG_NAME),
        dst / PKG_NAME,
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    shutil.copy(
        os.path.join(REPO_ROOT, "bench.py"), dst / "bench.py"
    )
    shutil.copy(
        os.path.join(REPO_ROOT, "ANALYSIS_BASELINE.json"),
        dst / "ANALYSIS_BASELINE.json",
    )
    return dst


def _mutate(root, rel, old, new=None, append=None):
    path = os.path.join(root, PKG_NAME, rel)
    src = open(path).read()
    if old is not None:
        assert old in src, f"mutation anchor {old!r} missing from {rel}"
        src = src.replace(old, new)
    if append:
        src += "\n" + textwrap.dedent(append)
    open(path, "w").write(src)


def test_repo_copy_scans_clean(repo_copy):
    result = run_check(str(repo_copy))
    assert result.new == [], [f.render() for f in result.new]
    assert result.exit_code == 0


def test_mutation_duplicate_hmac_domain_fails(repo_copy):
    # The PR-7 reflection hole, re-introduced: the reply-direction chunk
    # domain collapsed onto the upload-direction one.
    _mutate(
        repo_copy,
        "comm/wire.py",
        'b"fedtpu-stream-rchk-v1"',
        'b"fedtpu-stream-chk-v1"',
    )
    result = run_check(str(repo_copy))
    assert result.exit_code == 1
    assert any(
        f.rule == "wire-domain-unique" and "duplicates" in f.message
        for f in result.new
    )


def test_mutation_duplicate_meta_key_fails(repo_copy):
    # Two capabilities collapsing onto one upload-meta field: the PR-14
    # subtree contributor record silently shadowing the streamed-reply
    # advert.
    _mutate(
        repo_copy,
        "comm/wire.py",
        'SUBTREE_IDS_META_KEY = "subtree_ids"',
        'SUBTREE_IDS_META_KEY = "stream_reply"',
    )
    result = run_check(str(repo_copy))
    assert result.exit_code == 1
    assert any(
        f.rule == "wire-meta-key-unique" and "duplicates" in f.message
        for f in result.new
    )


def test_mutation_wall_clock_in_fold_path_fails(repo_copy):
    _mutate(
        repo_copy,
        "comm/stream_agg.py",
        "t0 = time.monotonic()",
        "t0 = time.time()",
    )
    # Exercised through the real CLI entry (argparse namespace) so the
    # exit-code contract is what's pinned, not just the library result.
    rc = cmd_check(
        argparse.Namespace(
            root=str(repo_copy),
            json=False,
            baseline=None,
            rules="determinism",
            list_rules=False,
        )
    )
    assert rc == 1


def test_mutation_unguarded_cross_thread_write_fails(repo_copy):
    _mutate(
        repo_copy,
        "comm/server.py",
        None,
        append="""
        class _MutationProbe:
            def __init__(self):
                self.n = 0

            def start(self):
                threading.Thread(target=self._work).start()

            def _work(self):
                self.n += 1

            def bump(self):
                self.n += 1
        """,
    )
    result = run_check(str(repo_copy))
    assert result.exit_code == 1
    assert any(
        f.rule == "unguarded" and "_MutationProbe.n" in f.message
        for f in result.new
    )


def test_mutation_off_vocabulary_span_fails(repo_copy):
    _mutate(
        repo_copy,
        "comm/relay.py",
        None,
        append="""
        def _mutation_probe(tracer):
            tracer.record("not-a-span", t_start=0.0, dur_s=0.0)
        """,
    )
    result = run_check(str(repo_copy))
    assert result.exit_code == 1
    assert any(
        f.rule == "obs-span-vocab" and "not-a-span" in f.message
        for f in result.new
    )


def test_mutation_missing_stream_direction_fails(repo_copy):
    _mutate(
        repo_copy,
        "comm/client.py",
        'direction="up",\n        )',
        ")",
    )
    result = run_check(str(repo_copy))
    assert result.exit_code == 1
    assert any(f.rule == "wire-stream-direction" for f in result.new)


def test_mutation_ghost_headline_field_fails(repo_copy):
    path = os.path.join(repo_copy, "bench.py")
    src = open(path).read()
    anchor = '"fleet_rounds_per_hour",'
    assert anchor in src
    src = src.replace(
        anchor, anchor + ' "ghost_headline_field_s",', 1
    )
    open(path, "w").write(src)
    result = run_check(str(repo_copy))
    assert result.exit_code == 1
    assert any(
        f.rule == "bench-headline" and "ghost_headline_field_s" in f.message
        for f in result.new
    )


# -------------------------------------------------------- repo self-scan
def test_repo_self_scan_clean():
    """The shipping tree passes its own checker with the reviewed
    baseline — the contract the tier-1 verify recipe runs."""
    result = run_check(REPO_ROOT)
    assert result.new == [], "\n".join(f.render() for f in result.new)
    assert result.exit_code == 0
    # The reviewed baseline must not rot: every entry still matches a
    # live finding.
    assert result.stale_baseline == [], result.stale_baseline


def test_cli_parser_wires_check_subcommand():
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        build_parser,
    )

    args = build_parser().parse_args(["check", "--json", "--rules", "determinism"])
    assert args.fn is cmd_check and args.rules == "determinism"


def test_cmd_check_list_rules(capsys):
    rc = cmd_check(
        argparse.Namespace(
            list_rules=True, root=None, json=False, baseline=None, rules=None
        )
    )
    out = capsys.readouterr().out
    assert rc == 0
    for rule in all_rules():
        assert rule in out


# -------------------------------------------------- lock-order detector
def test_lockorder_detects_abba_cycle():
    det = lockorder.LockOrderDetector()
    a = det.lock("siteA")
    b = det.lock("siteB")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    report = det.report()
    assert report.cycles == [["siteA", "siteB"]]
    assert "ABBA" in report.render()


def test_lockorder_consistent_order_is_clean():
    det = lockorder.LockOrderDetector()
    a = det.lock("siteA")
    b = det.lock("siteB")
    for _ in range(3):
        with a:
            with b:
                pass
    report = det.report()
    assert report.cycles == []
    assert report.edges == {("siteA", "siteB"): 3}


def test_lockorder_cross_thread_cycle_detected():
    det = lockorder.LockOrderDetector()
    a = det.lock("siteA")
    b = det.lock("siteB")
    order = threading.Barrier(2, timeout=5)

    def ab():
        with a:
            with b:
                pass
        order.wait()

    def ba():
        order.wait()  # strictly after ab() released both: no deadlock
        with b:
            with a:
                pass

    t1 = threading.Thread(target=ab)
    t2 = threading.Thread(target=ba)
    t1.start(), t2.start()
    t1.join(timeout=5), t2.join(timeout=5)
    assert det.report().cycles == [["siteA", "siteB"]]


def test_lockorder_same_site_nesting_reported_not_failed():
    det = lockorder.LockOrderDetector()
    first = det.lock("shard")
    second = det.lock("shard")
    with first:
        with second:
            pass
    report = det.report()
    assert report.cycles == []
    assert report.same_site_edges == {"shard": 1}


def test_lockorder_reentrant_rlock_records_no_edge():
    det = lockorder.LockOrderDetector()
    r = det.rlock("outer")
    with r:
        with r:
            pass
    report = det.report()
    assert report.edges == {} and report.cycles == []


def test_lockorder_cross_thread_release_clears_holder_stack():
    """A Lock may legally be released by a thread other than its
    acquirer (handoff). The acquirer's held-stack must be cleared, or
    every later acquire in that thread records phantom edges — and one
    reverse edge fabricates an ABBA cycle that fails the session."""
    det = lockorder.LockOrderDetector()
    handoff = det.lock("handoff")
    other = det.lock("other")
    acquired = threading.Event()
    release_done = threading.Event()
    edges_after = {}

    def acquirer():
        handoff.acquire()
        acquired.set()
        assert release_done.wait(timeout=5)
        # If the stale entry survived, this records handoff -> other.
        with other:
            pass
        edges_after.update(det.report().edges)

    t = threading.Thread(target=acquirer)
    t.start()
    assert acquired.wait(timeout=5)
    handoff.release()  # cross-thread release (main thread)
    release_done.set()
    t.join(timeout=5)
    assert edges_after == {}, edges_after


def test_lockorder_condition_interplay():
    det = lockorder.LockOrderDetector()
    cond = threading.Condition(det.lock("cond"))
    hits = []

    def waiter():
        with cond:
            hits.append(cond.wait(timeout=5))

    t = threading.Thread(target=waiter)
    t.start()
    import time as _time

    deadline = _time.monotonic() + 5
    while not hits and _time.monotonic() < deadline:
        with cond:
            cond.notify_all()
        _time.sleep(0.01)
    t.join(timeout=5)
    assert hits == [True]
    assert det.report().cycles == []


def test_lockorder_session_arming_state():
    """Under the conftest arming (the fast lane's default) the factories
    are patched; with FEDTPU_LOCKORDER=0 they must be pristine."""
    armed = lockorder.armed_detector()
    if os.environ.get("FEDTPU_LOCKORDER", "1").lower() in ("", "0", "false"):
        assert armed is None
    else:
        assert armed is not None
        # Repo-created locks are tracked: the obs metrics registry is
        # package code constructing threading.Lock() at class init.
        from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.metrics import (
            MetricsRegistry,
        )

        reg = MetricsRegistry()
        assert isinstance(reg._lock, lockorder._TrackedLock)
        assert "obs/metrics.py" in reg._lock.site
        # Test-file-created locks are NOT tracked (outside the package).
        assert not isinstance(threading.Lock(), lockorder._TrackedLock)
