"""Tokenizer parity vs transformers.BertTokenizer (same vocab file, offline)."""

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    WordPieceTokenizer,
    basic_tokenize,
    build_domain_vocab,
    default_tokenizer,
    make_synthetic_flows,
    texts_from_dataframe,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
    TokenizedSplit,
    batch_iterator,
    pad_split_to_batch,
    stack_clients,
)


@pytest.fixture(scope="module")
def corpus():
    df = make_synthetic_flows(200, seed=5)
    return texts_from_dataframe(df)


@pytest.fixture(scope="module")
def tok():
    return default_tokenizer()


def test_basic_tokenize():
    assert basic_tokenize("Destination port is 443.") == [
        "destination", "port", "is", "443", ".",
    ]
    assert basic_tokenize("Flow bytes/s: -1.5e+07!") == [
        "flow", "bytes", "/", "s", ":", "-", "1", ".", "5e", "+", "07", "!",
    ]
    assert basic_tokenize("  \t\n  ") == []
    assert basic_tokenize("Héllo") == ["hello"]  # accent strip


def test_domain_vocab_covers_template_with_zero_unk(tok, corpus):
    for text in corpus:
        ids = tok.encode(text)
        assert tok.unk_id not in ids, text


def test_encode_structure(tok):
    ids = tok.encode("Destination port is 443.", max_len=128)
    assert ids[0] == tok.cls_id and ids[-1] == tok.sep_id
    toks = tok.tokenize("port is 80")
    assert toks == ["port", "is", "8", "##0"]


def test_truncation(tok):
    long_text = "packet " * 500
    ids = tok.encode(long_text, max_len=16)
    assert len(ids) == 16
    assert ids[0] == tok.cls_id and ids[-1] == tok.sep_id


def test_batch_encode_shapes_and_mask(tok, corpus):
    enc = tok.batch_encode(corpus[:10], max_len=128)
    assert enc["input_ids"].shape == (10, 128)
    assert enc["input_ids"].dtype == np.int32
    lens = enc["attention_mask"].sum(axis=1)
    assert (lens > 10).all() and (lens <= 128).all()
    # mask exactly covers non-pad positions
    assert ((enc["input_ids"] != tok.pad_id) == enc["attention_mask"].astype(bool)).all()


def test_parity_vs_hf_bert_tokenizer(tok, corpus, tmp_path):
    transformers = pytest.importorskip("transformers")
    vocab_path = tmp_path / "vocab.txt"
    tok.save_vocab(str(vocab_path))
    hf = transformers.BertTokenizer(str(vocab_path), do_lower_case=True)
    probes = corpus[:25] + [
        "Flow bytes per second is -1.5e+07.",
        "UNKNOWNWORD xyzzy 99999999999999999999",
        "Héllo,   world!!  ",
    ]
    for text in probes:
        ours = tok.encode(text, max_len=128)
        theirs = hf.encode(text, add_special_tokens=True, max_length=128, truncation=True)
        assert ours == theirs, text
    # batch path vs HF padded path (reference client1.py:38-45 semantics)
    enc = tok.batch_encode(probes, max_len=128)
    hf_enc = hf(probes, add_special_tokens=True, max_length=128,
                padding="max_length", truncation=True)
    np.testing.assert_array_equal(enc["input_ids"], np.array(hf_enc["input_ids"], np.int32))
    np.testing.assert_array_equal(
        enc["attention_mask"], np.array(hf_enc["attention_mask"], np.int32)
    )


def test_vocab_file_round_trip(tok, tmp_path):
    p = tmp_path / "v.txt"
    tok.save_vocab(str(p))
    tok2 = WordPieceTokenizer.from_vocab_file(str(p))
    assert tok2.vocab == tok.vocab


def test_corpus_vocab_extension(corpus):
    vocab = build_domain_vocab(corpus)
    tok = WordPieceTokenizer(vocab)
    # whole template words became single tokens
    assert "destination" in tok.vocab and "microseconds" in tok.vocab


def _mk_split(n=37, L=16, seed=0):
    rng = np.random.default_rng(seed)
    return TokenizedSplit(
        rng.integers(1, 50, (n, L)).astype(np.int32),
        np.ones((n, L), np.int32),
        rng.integers(0, 2, n).astype(np.int32),
    )


def test_batch_iterator_static_shapes():
    s = _mk_split(37)
    batches = list(batch_iterator(s, 8, shuffle=True, seed=1))
    assert len(batches) == 4  # drop remainder
    assert all(b["input_ids"].shape == (8, 16) for b in batches)
    # shuffle deterministic by seed
    b2 = list(batch_iterator(s, 8, shuffle=True, seed=1))
    np.testing.assert_array_equal(batches[0]["labels"], b2[0]["labels"])


def test_pad_split_to_batch():
    s = _mk_split(37)
    padded, valid = pad_split_to_batch(s, 8)
    assert len(padded) == 40 and valid.sum() == 37
    np.testing.assert_array_equal(padded.input_ids[:37], s.input_ids)


def test_stack_clients():
    a, b = _mk_split(20, seed=1), _mk_split(30, seed=2)
    stacked = stack_clients([a, b])
    assert stacked.input_ids.shape == (2, 20, 16)
    np.testing.assert_array_equal(stacked.labels[1], b.labels[:20])
