"""Delayed ground-truth plane (ISSUE 18): the append-only label journal
(labels/store.py), the deterministic scored-vs-truth join
(labels/join.py), the supervised promotion rung stacked after the
shadow gate, label-aware drift (ErrorRateMonitor) and drift-scaled
cohort sizing (control/drift.py), the ranked-candidate shadow
comparator, the recorded-arrival load replay, and the K-class data
plane's K = 2 bit-identity.

Contracts pinned here:

* The journal tolerates the REAL arrival discipline: duplicates count,
  conflicts resolve last-writer-wins by caller-supplied timestamp (a
  strictly-older conflict never overwrites), labels at or under the
  watermark still apply but count as late, the watermark only moves
  forward, and ``load()`` rebuilds bit-identical state from the file.
* The supervised gate FAILS CLOSED: too few joined flows, coverage
  under the floor, or an uncomputable side are refusals, never passes
  — and a live controller round REJECTS on an empty journal, then
  PROMOTES the same candidate evidence once the delayed labels arrive.
* The K = 2 route of the class-counts plane renders metrics
  bit-identical to the binary path (same floats, same dict).
* Aggregate shadow-gate evidence covers rank 0 only; secondary ranked
  candidates ride the same mirrored traffic without diluting it.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
    FederatedClient,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    ControlConfig,
    ExperimentConfig,
    LabelsConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.control import (
    Controller,
    DriftMonitor,
    ErrorRateMonitor,
    drift_cohort_fraction,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.labels import (
    LabelGate,
    LabelStore,
    evaluate_supervised,
    join_records,
    journal_path,
    supervised_verdict,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.trace import (
    append_jsonl_line,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.registry import (
    ModelRegistry,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
    load_arrival_trace,
    run_load,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.shadow.compare import (
    PAIR_SCHEMA,
    ShadowCompare,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.shadow.gate import (
    pairs_path,
)

TRACE_FIXTURE = os.path.join(
    os.path.dirname(__file__), "data", "arrival_bursty.trace"
)


# ------------------------------------------------------------- the journal
def test_journal_lww_duplicates_conflicts_late_watermark(tmp_path):
    store = LabelStore(str(tmp_path / "journal.jsonl"))
    assert store.ingest("r1", 1, ts=1.0)
    # Same label again: a duplicate, not a conflict; state unchanged.
    assert not store.ingest("r1", 1, ts=2.0)
    # Conflicting re-label with a NEWER ts: last writer wins.
    store.ingest("r1", 0, ts=3.0)
    assert store.get("r1") == 0
    # Conflicting re-label with an OLDER ts: counted, never overwrites.
    store.ingest("r1", 1, ts=2.5)
    assert store.get("r1") == 0
    # The watermark is monotone: a stale advance is a no-op.
    assert store.advance_watermark(5.0) == 5.0
    assert store.advance_watermark(4.0) == 5.0
    assert store.watermark == 5.0
    # A label at/under the watermark still applies but counts as late.
    store.ingest("r2", 1, ts=4.0)
    assert store.get("r2") == 1
    s = store.status()
    assert s["labels"] == 2
    assert s["duplicates"] == 1
    assert s["conflicts"] == 2
    assert s["late"] == 1
    assert s["watermark"] == 5.0


def test_journal_load_replays_bit_identical_state(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    a = LabelStore(path)
    a.ingest("r1", 1, ts=1.0)
    a.ingest("r2", 0, ts=2.0)
    a.advance_watermark(3.0)
    a.ingest("r1", 0, ts=4.0)  # conflict, LWW
    a.ingest("r3", 1, ts=2.5)  # late (under the watermark)
    # Torn tail + foreign line: the replay must skip both.
    with open(path, "a") as f:
        f.write('{"schema": "other-v9", "x": 1}\n')
        f.write('{"schema": "fedtpu-label-v1", "rid": "r9"')  # torn
    b = LabelStore(path)
    b.load()
    assert b.labels_map() == a.labels_map() == {"r1": 0, "r2": 0, "r3": 1}
    assert b.watermark == a.watermark == 3.0
    sa, sb = a.status(), b.status()
    for k in ("labels", "conflicts", "late", "watermark"):
        assert sb[k] == sa[k], k


# ---------------------------------------------------------------- the join
def test_supervised_verdict_arithmetic():
    # K-class labels binarize as != 0: (pred, label) = tp, fp, fn, tn.
    v = supervised_verdict([(1, 1), (1, 0), (0, 3), (0, 0)])
    assert (v["tp"], v["fp"], v["fn"], v["tn"]) == (1, 1, 1, 1)
    assert v["accuracy"] == 0.5 and v["error"] == 0.5
    assert v["fpr"] == 0.5 and v["fnr"] == 0.5
    assert v["per_class"] == {"0": 2, "1": 1, "3": 1}
    empty = supervised_verdict([])
    assert empty["n"] == 0 and empty["error"] is None


def test_join_records_coverage_and_sides():
    labels = {"a": 1, "b": 0, "c": 1}
    records = [
        {"rid": "a", "serving_prob": 0.9, "shadow_prob": 0.2},
        {"rid": "zz", "serving_prob": 0.9, "shadow_prob": 0.9},  # unlabeled
        {"serving_prob": 0.5, "shadow_prob": 0.5},  # no rid: total only
        {"rid": "b", "serving_prob": 0.1},  # one-sided record
        {"rid": "c", "serving_prob": 0.8, "shadow_prob": 0.9, "cand": 1},
    ]
    rep = join_records(records, labels)
    assert rep["total"] == 5 and rep["joined"] == 3
    assert rep["coverage"] == pytest.approx(3 / 5)
    assert rep["models"]["serving"]["n"] == 3
    assert rep["models"]["candidate"]["n"] == 2  # the one-sided miss
    assert rep["per_candidate_joined"] == {"1": 1}
    # The scored-JSONL shape: one model, a "prob" field.
    rep2 = join_records(
        [{"rid": "a", "prob": 0.9}, {"rid": "b", "prob": 0.8}],
        labels,
        sides={"serving": "prob"},
    )
    assert rep2["joined"] == 2
    assert rep2["models"]["serving"]["fp"] == 1  # b: pred 1, label 0


def test_evaluate_supervised_fails_closed_then_rules():
    def rep(joined, total, s_err, c_err):
        return {
            "joined": joined,
            "total": total,
            "coverage": joined / total if total else 0.0,
            "models": {
                "serving": {"error": s_err},
                "candidate": {"error": c_err},
            },
        }

    kw = dict(min_joined=32, coverage_floor=0.05, max_regression=0.0)
    ok, why = evaluate_supervised(rep(8, 100, 0.0, 0.0), **kw)
    assert not ok and "insufficient" in why
    ok, why = evaluate_supervised(rep(40, 4000, 0.0, 0.0), **kw)
    assert not ok and "coverage" in why
    ok, why = evaluate_supervised(rep(40, 100, 0.0, None), **kw)
    assert not ok and "uncomputable" in why
    ok, why = evaluate_supervised(rep(40, 100, 0.01, 0.05), **kw)
    assert not ok and "regression" in why
    ok, why = evaluate_supervised(rep(40, 100, 0.05, 0.05), **kw)
    assert ok and "agreement" in why
    # A tolerated regression budget moves the bar, same arithmetic.
    ok, _ = evaluate_supervised(
        rep(40, 100, 0.01, 0.05),
        min_joined=32,
        coverage_floor=0.05,
        max_regression=0.1,
    )
    assert ok


def _write_pairs(root, aid, rows):
    """rows: (rid, serving_prob, shadow_prob, cand_rank_or_None)."""
    path = pairs_path(root, aid)
    for i, (rid, sp, cp, cand) in enumerate(rows):
        rec = {
            "schema": PAIR_SCHEMA,
            "mid": i + 1,
            "serving_prob": sp,
            "shadow_prob": cp,
            "flip": int((sp >= 0.5) != (cp >= 0.5)),
            "rid": rid,
        }
        if cand:
            rec["cand"] = cand
        append_jsonl_line(path, json.dumps(rec))


def test_label_gate_fails_closed_without_evidence(tmp_path):
    gate = LabelGate(str(tmp_path), min_joined=4)
    ok, verdict = gate.evaluate("ghost")
    assert not ok and "insufficient" in verdict["reason"]
    assert verdict["joined"] == 0 and verdict["total"] == 0


def test_label_gate_rules_on_primary_pairs_only(tmp_path):
    """Secondary ranked candidates tag their pairs with ``cand``; the
    gated verdict must cover the rank-0 candidate's pairs alone — a
    regressing SECONDARY must not fail the primary (and vice versa)."""
    root = str(tmp_path)
    aid = "cand-x"
    rows = [(f"r{i}", 0.9, 0.9, None) for i in range(40)]
    # 40 rank-1 pairs, every one a wrong answer on an attack flow: if
    # the join counted them, candidate error would jump to 0.5.
    rows += [(f"r{i}", 0.9, 0.1, 1) for i in range(40)]
    _write_pairs(root, aid, rows)
    store = LabelStore(journal_path(root))
    for i in range(40):
        store.ingest(f"r{i}", 1, ts=float(i))
    ok, verdict = LabelGate(
        root, min_joined=16, coverage_floor=0.05
    ).evaluate(aid)
    assert ok, verdict["reason"]
    assert verdict["joined"] == 40 and verdict["total"] == 40
    assert verdict["candidate_error"] == 0.0


# ------------------------------------------------- label-aware drift plane
def test_drift_cohort_fraction_pins_both_ends_and_midpoint():
    kw = dict(threshold=0.25, min_frac=0.5, max_frac=1.0)
    assert drift_cohort_fraction(0.25, **kw) == pytest.approx(0.5)
    assert drift_cohort_fraction(0.50, **kw) == pytest.approx(1.0)
    assert drift_cohort_fraction(0.375, **kw) == pytest.approx(0.75)
    # Clamped outside the span; degenerate band returns min_frac.
    assert drift_cohort_fraction(0.10, **kw) == pytest.approx(0.5)
    assert drift_cohort_fraction(9.99, **kw) == pytest.approx(1.0)
    assert drift_cohort_fraction(
        0.9, threshold=0.25, min_frac=0.8, max_frac=0.8
    ) == pytest.approx(0.8)


def test_error_rate_monitor_lifecycle():
    em = ErrorRateMonitor(reference_error=0.02, margin=0.05, min_joined=64)
    em.observe(1, 32)
    assert em.check() is None  # too few joined flows
    em.observe(1, 32)
    assert em.check() is None  # 2/64 under reference + margin
    em.observe(10, 64)
    verdict = em.check()  # 12/128 = 0.094 >= 0.02 + 0.05
    assert verdict is not None and verdict["method"] == "error_rate"
    assert verdict["scores"] == 128
    assert verdict["drift"] == pytest.approx(12 / 128 - 0.02, abs=1e-6)
    assert em.observed_joined == 0  # fired verdict resets the window
    # Verdict-dict ingestion (labels/join.py shape) feeds the same path.
    em.observe_verdict({"n": 64, "error": 0.5})
    assert em.check() is not None
    # No reference: never fires, regardless of evidence.
    cold = ErrorRateMonitor(margin=0.05, min_joined=8)
    cold.observe(8, 8)
    assert not cold.has_reference and cold.check() is None
    with pytest.raises(ValueError):
        em.observe(5, 3)
    with pytest.raises(ValueError):
        ErrorRateMonitor(margin=0.0)


def test_labels_config_validates_and_round_trips():
    cfg = ExperimentConfig.from_dict(
        {"labels": {"min_joined": 8, "coverage_floor": 0.2}}
    )
    assert cfg.labels.min_joined == 8
    assert cfg.labels.coverage_floor == 0.2
    assert cfg.labels.journal is None
    with pytest.raises(ValueError):
        LabelsConfig(coverage_floor=1.5)
    with pytest.raises(ValueError):
        LabelsConfig(threshold=1.0)
    with pytest.raises(ValueError):
        LabelsConfig(min_joined=0)
    with pytest.raises(ValueError):
        LabelsConfig(max_regression=-0.1)
    with pytest.raises(ValueError):
        ControlConfig(cohort_min_frac=0.0)
    with pytest.raises(ValueError):
        ControlConfig(cohort_min_frac=0.8, cohort_max_frac=0.5)


# ------------------------------------------------- ranked shadow comparator
def test_shadow_compare_aggregates_rank_zero_only(tmp_path):
    pairs_jsonl = str(tmp_path / "pairs.jsonl")
    compare = ShadowCompare(
        threshold=0.5, candidates=("cand-a", "cand-b"),
        pairs_jsonl=pairs_jsonl,
    )
    compare.register_rid(1, "rid-1")
    compare.note_serving(1, 0.9)
    compare.note_shadow(1, 0.9)  # rank 0, agrees
    compare.register_rid(2, "rid-2")
    compare.note_serving(2, 0.9)
    compare.note_shadow(2, 0.1, 1)  # rank 1, flips
    s = compare.snapshot()
    # The gate's aggregate evidence: the rank-1 flip never dilutes it.
    assert s["pairs"] == 1 and s["flips"] == 0
    pc = s["per_candidate"]
    assert pc["0"] == {
        "candidate": "cand-a", "pairs": 1, "flips": 0, "flip_rate": 0.0,
    }
    assert pc["1"]["candidate"] == "cand-b"
    assert pc["1"]["pairs"] == 1 and pc["1"]["flips"] == 1
    recs = [json.loads(ln) for ln in open(pairs_jsonl)]
    by_mid = {r["mid"]: r for r in recs}
    assert "cand" not in by_mid[1] and by_mid[1]["rid"] == "rid-1"
    assert by_mid[2]["cand"] == 1 and by_mid[2]["rid"] == "rid-2"


# ---------------------------------------------------- recorded arrival load
def test_arrival_trace_fixture_parses_and_validates(tmp_path):
    gaps = load_arrival_trace(TRACE_FIXTURE)
    assert len(gaps) == 24
    assert sum(gaps) == pytest.approx(0.17)
    assert min(gaps) >= 0.0
    empty = tmp_path / "empty.trace"
    empty.write_text("# nothing but comments\n\n")
    with pytest.raises(ValueError):
        load_arrival_trace(str(empty))
    neg = tmp_path / "neg.trace"
    neg.write_text("0.01\n-0.5\n")
    with pytest.raises(ValueError):
        load_arrival_trace(str(neg))
    with pytest.raises(ValueError):
        run_load(
            "127.0.0.1", 1, ["x"], target_qps=10.0, arrival_trace=gaps
        )
    with pytest.raises(ValueError):
        run_load("127.0.0.1", 1, ["x"], arrival_trace=[])


def test_run_load_replays_bursty_trace_open_loop(tmp_path):
    """The recorded schedule actually paces the send side: a run whose
    requests span two trace cycles takes at least the recorded offsets
    of wall time (open loop — reply speed does not compress it)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        default_tokenizer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
        MicroBatcher,
        ScoreEngine,
        ScoringServer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
        Trainer,
    )

    tok = default_tokenizer()
    model_cfg = ModelConfig.tiny(vocab_size=len(tok.vocab))
    trainer = Trainer(model_cfg, TrainConfig(), pad_id=tok.pad_id)
    params = trainer.init_state(seed=0).params
    engine = ScoreEngine(
        model_cfg, params, pad_id=tok.pad_id, buckets=(1, 4), round_id=0
    )
    gaps = load_arrival_trace(TRACE_FIXTURE)
    batcher = MicroBatcher(max_batch=4, max_queue=64, gather_window_s=0.002)
    with ScoringServer(
        engine, tok, batcher=batcher, idle_tick_s=0.01
    ) as server:
        stats = run_load(
            "127.0.0.1",
            server.port,
            ["Destination port is 80. Flow duration is 100 microseconds."],
            concurrency=1,
            requests=48,
            arrival_trace=gaps,
            timeout=30,
        )
    assert stats["scored"] == 48 and stats["rejected"] == 0
    assert stats["arrival_trace_len"] == 24
    assert stats["arrival_cycle_s"] == pytest.approx(sum(gaps))
    # Request 47 fires one full cycle + 23 recorded gaps in: >= ~0.30 s.
    assert stats["wall_s"] >= 0.25


# --------------------------------------------------- K = 2 crc bit-identity
def test_kclass_k2_renders_bit_identical_to_binary_path():
    import jax.numpy as jnp

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.ops.metrics import (
        binary_counts,
        class_counts,
        finalize_class_metrics,
        finalize_metrics,
    )

    rng = np.random.default_rng(7)
    logits = jnp.asarray(rng.normal(size=(256, 2)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 2, size=256).astype(np.int32))
    loss = jnp.asarray(np.float32(0.7))
    mb = finalize_metrics(binary_counts(logits, y, loss))
    mk = finalize_class_metrics(class_counts(logits, y, loss))
    assert set(mb) == set(mk)
    for k in ("Accuracy", "Loss", "Precision", "Recall", "F1-Score"):
        assert mb[k] == mk[k], k  # bit-identical floats, not approx
    assert np.array_equal(mb["confusion_matrix"], mk["confusion_matrix"])
    assert mb["n"] == mk["n"] == 256


def test_kclass_counts_accumulate_full_confusion_matrix():
    import jax.numpy as jnp

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.ops.metrics import (
        class_counts,
        finalize_class_metrics,
    )

    rng = np.random.default_rng(11)
    k, n = 7, 224
    logits = jnp.asarray(rng.normal(size=(n, k)).astype(np.float32))
    y = np.asarray(rng.integers(0, k, size=n), np.int32)
    counts = class_counts(logits, jnp.asarray(y), jnp.asarray(np.float32(1.9)))
    cm = np.asarray(counts.cm)
    assert cm.shape == (k, k) and cm.sum() == n
    preds = np.asarray(np.argmax(np.asarray(logits), axis=-1))
    assert float(counts.correct) == float((preds == y).sum())
    assert cm[3].sum() == int((y == 3).sum())  # row = truth support
    m = finalize_class_metrics(counts)
    assert m["n_classes"] == k and len(m["per_class"]) == k
    assert m["Accuracy"] == pytest.approx(100.0 * (preds == y).mean())


def test_multiclass_dataset_preset_labels_strictly():
    import pandas as pd

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.datasets import (
        get_dataset,
    )

    spec = get_dataset("cicddos2019-mc")
    assert spec.n_classes == 7 and spec.classes[0] == "BENIGN"
    df = pd.DataFrame({"Label": ["BENIGN", "Syn", "DrDoS_DNS", "BENIGN"]})
    assert spec.class_labels(df).tolist() == [0, 5, 1, 0]
    assert spec.labels(df).tolist() == [0, 5, 1, 0]
    # The binary view binarizes the SAME rows as != BENIGN.
    assert spec.binary_labels(df).tolist() == [0, 1, 1, 0]
    with pytest.raises(ValueError, match="not in the declared class"):
        spec.class_labels(pd.DataFrame({"Label": ["LDAP-weird"]}))
    # Binary specs refuse the K-class accessor loudly.
    with pytest.raises(ValueError, match="not a multiclass spec"):
        get_dataset("cicids2017").class_labels(df)


# --------------------------------------------- live controller integration
def _mean_eval(params):
    w = params["w"]
    mean = float(np.asarray(w, np.float64).mean())
    acc = mean if np.isfinite(mean) else float("nan")
    rng = np.random.default_rng(7)
    return {"Accuracy": acc, "probs": rng.uniform(0, 1, 128)}


class _SeedingGate(LabelGate):
    """The real LabelGate, but mirror-pair evidence for each candidate
    is seeded at join time (the artifact id is minted mid-round, so a
    test cannot pre-write its pairs file)."""

    def __init__(self, root, writer, **kw):
        super().__init__(root, **kw)
        self._writer = writer

    def join(self, aid):
        self._writer(self.registry_root, aid)
        return super().join(aid)


def test_delayed_labels_flip_a_live_promotion_verdict(tmp_path):
    """Two live TCP rounds, identical candidate evidence: round 1 runs
    before any ground truth arrived — the supervised gate FAILS CLOSED
    and the pointer never moves; the labels then land in the journal,
    and round 2 promotes on the same join arithmetic. The label plane,
    not the candidate, is what changed."""
    root = str(tmp_path / "reg")
    registry = ModelRegistry(root)
    state = str(tmp_path / "state.jsonl")
    truth = [i % 2 for i in range(40)]

    def writer(reg_root, aid):
        if os.path.exists(pairs_path(reg_root, aid)):
            return
        _write_pairs(
            reg_root,
            aid,
            [
                (f"r{i}", 0.9 if truth[i] else 0.1, 0.9 if truth[i] else 0.1,
                 None)
                for i in range(40)
            ],
        )

    gate = _SeedingGate(
        root, writer, min_joined=16, coverage_floor=0.05, max_regression=0.0
    )
    em = ErrorRateMonitor(margin=0.05, min_joined=16)
    store = LabelStore(journal_path(root))
    errors = []
    with AggregationServer(port=0, num_clients=2, timeout=30) as server:
        controller = Controller(
            server,
            registry,
            _mean_eval,
            control=ControlConfig(round_deadline_s=20.0),
            state_path=state,
            label_gate=gate,
            error_monitor=em,
        )

        def loop(cid):
            try:
                fc = FederatedClient(
                    "127.0.0.1", server.port, client_id=cid, timeout=30
                )
                out = fc.exchange({"w": np.full(16, 0.5, np.float32)})
                # Ground truth arrives BETWEEN the rounds — delayed, the
                # way incident review actually delivers it. Wait for the
                # round-0 verdict to land before ingesting (the round
                # reply races the controller's gate evaluation).
                if cid == 0:
                    deadline = time.monotonic() + 20
                    while True:
                        try:
                            if "label_rejected" in open(state).read():
                                break
                        except OSError:
                            pass
                        assert time.monotonic() < deadline
                        time.sleep(0.02)
                    for i in range(40):
                        store.ingest(f"r{i}", truth[i], ts=float(i))
                    store.advance_watermark(40.0)
                fc.exchange({"w": out["w"] + np.float32(0.25)})
            except Exception as e:
                errors.append(e)

        threads = [
            threading.Thread(target=loop, args=(c,), daemon=True)
            for c in range(2)
        ]
        for t in threads:
            t.start()
        stats = controller.run(max_rounds=2)
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors
    assert stats.rounds_completed == 2
    assert stats.label_rejections == 1 and stats.promotions == 1
    events = [json.loads(ln) for ln in open(state)]
    kinds = [e["event"] for e in events]
    assert kinds.count("label_rejected") == 1
    assert kinds.count("promoted") == 1
    rej = next(e for e in events if e["event"] == "label_rejected")
    assert "insufficient ground truth" in rej["label_verdict"]["reason"]
    assert rej["label_verdict"]["joined"] == 0
    pro = next(e for e in events if e["event"] == "promoted")
    assert pro["label_verdict"]["joined"] == 40
    assert pro["label_verdict"]["candidate_error"] == 0.0
    # The rejected candidate is in the registry with the verdict; the
    # pointer belongs to the round-2 artifact.
    manifests = {m["id"]: m for m in registry.list()}
    rejected = [m for m in manifests.values() if m["state"] == "rejected"]
    assert len(rejected) == 1
    assert registry.serving_manifest()["round"] == 1
    # Promotion anchored the supervised drift reference on the
    # candidate's measured error (0.0 here).
    assert em.has_reference
    # A resumed controller replays the label rejection from the state.
    resumed = Controller(
        _StubRoundServer(), registry, _mean_eval, state_path=state
    )
    assert resumed.stats.label_rejections == 1
    assert resumed.stats.promotions == 1


class _StubRoundServer:
    """Minimal round engine for controller tests that never serve a
    real TCP round (resume replay, cohort arithmetic)."""

    dp_clip = 0.0

    def __init__(self, min_clients=4):
        self.min_clients = min_clients
        self.seen_quorums = []
        self.n = 0

    def serve_round(self, *, deadline=None, round_index=None):
        self.seen_quorums.append(self.min_clients)
        self.n += 1
        return {"w": np.full(8, float(self.n), np.float32)}


def test_drift_scaled_cohort_applies_for_one_round_then_restores(tmp_path):
    """A fired drift verdict's magnitude picks the NEXT round's quorum:
    severe drift (>= 2x threshold) demands cohort_max_frac of the
    fleet; the override lasts exactly one round and the server's base
    min_clients comes back even though the stub round succeeded."""
    registry = ModelRegistry(str(tmp_path / "reg"))
    state = str(tmp_path / "state.jsonl")
    dm = DriftMonitor(threshold=0.25, min_scores=64)
    server = _StubRoundServer(min_clients=4)
    controller = Controller(
        server,
        registry,
        _mean_eval,
        control=ControlConfig(
            drift_cohort=True,
            cohort_min_frac=0.25,
            cohort_max_frac=0.5,
            round_deadline_s=20.0,
        ),
        state_path=state,
        drift_monitor=dm,
        drift_poll_s=0.05,
    )
    run_t = threading.Thread(
        target=lambda: controller.run(max_rounds=2), daemon=True
    )
    run_t.start()
    deadline = time.monotonic() + 20
    while registry.serving_info() is None:
        assert time.monotonic() < deadline, "bootstrap round never promoted"
        time.sleep(0.05)
    time.sleep(0.3)  # the controller enters its drift wait
    shifted = np.zeros(10, np.int64)
    shifted[4:6] = 64  # collapsed mass: psi far beyond 2x threshold
    dm.observe(shifted)
    run_t.join(timeout=30)
    assert not run_t.is_alive()
    # Round 0 ran at the base quorum; the drift round at max_frac of it.
    assert server.seen_quorums == [4, 2]
    assert server.min_clients == 4  # restored after the cohort round
    events = [json.loads(ln) for ln in open(state)]
    trig = [e for e in events if e["event"] == "drift_trigger"]
    assert trig and trig[-1]["cohort_target"] == 2
