"""Native C++ WordPiece encoder parity vs the pure-Python implementation.

The native path must be bit-identical on ASCII input and must cleanly fall
back everywhere else (non-ASCII text, exotic vocab shapes).
"""

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    default_tokenizer,
    make_synthetic,
    make_synthetic_unsw,
    texts_from_dataframe,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.datasets import (
    UNSWNB15,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.native_tokenizer import (
    have_native,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.tokenizer import (
    WordPieceTokenizer,
    build_domain_vocab,
)

pytestmark = pytest.mark.skipif(
    not have_native(), reason="no C++ toolchain for wordpiece.so"
)


def _python_encode(tok: WordPieceTokenizer, texts, max_len):
    """Force the pure-Python path regardless of native availability."""
    n = len(texts)
    input_ids = np.full((n, max_len), tok.pad_id, dtype=np.int32)
    attention_mask = np.zeros((n, max_len), dtype=np.int32)
    for r, text in enumerate(texts):
        ids = tok.encode(text, max_len)
        input_ids[r, : len(ids)] = ids
        attention_mask[r, : len(ids)] = 1
    return {"input_ids": input_ids, "attention_mask": attention_mask}


def _assert_same(a, b):
    np.testing.assert_array_equal(a["input_ids"], b["input_ids"])
    np.testing.assert_array_equal(a["attention_mask"], b["attention_mask"])


def test_native_is_active_on_default_vocab():
    tok = default_tokenizer()
    assert tok._native_encoder() is not None


def test_parity_on_flow_templates():
    tok = default_tokenizer()
    cic = texts_from_dataframe(make_synthetic("cicids2017", 200, seed=3))
    unsw = UNSWNB15.render_texts(make_synthetic_unsw(200, seed=3))
    for texts in (cic, unsw):
        native = tok.batch_encode(texts, max_len=128)
        _assert_same(native, _python_encode(tok, texts, 128))


def test_parity_edge_cases():
    tok = default_tokenizer()
    texts = [
        "",  # empty -> [CLS] [SEP]
        "   \t\n  ",  # whitespace only
        "UPPER lower MiXeD",  # lowercasing
        "a" * 150,  # > max_input_chars_per_word -> [UNK]
        "!!!...???",  # punctuation runs split to singles
        "word" * 60,  # long sane word: char-level pieces + truncation
        "x" * 126,  # exactly fills max_len with specials
        "trailing space ",
        "0.5 microseconds. Flow bytes per second is 666666.6667.",
    ]
    native = tok.batch_encode(texts, max_len=32)
    _assert_same(native, _python_encode(tok, texts, 32))
    # Empty text really is [CLS] [SEP] + padding.
    assert native["input_ids"][0, 0] == tok.cls_id
    assert native["input_ids"][0, 1] == tok.sep_id
    assert native["attention_mask"][0].sum() == 2
    # The 150-char word became a single [UNK].
    row = native["input_ids"][3]
    assert row[1] == tok.unk_id and row[2] == tok.sep_id


def test_non_ascii_falls_back_to_python():
    tok = default_tokenizer()
    texts = ["café résumé", "plain ascii"]
    out = tok.batch_encode(texts, max_len=16)
    _assert_same(out, _python_encode(tok, texts, 16))


def test_empty_batch():
    tok = default_tokenizer()
    out = tok.batch_encode([], max_len=16)
    assert out["input_ids"].shape == (0, 16)


def test_exotic_vocab_disables_native():
    # Sparse ids -> the Python path is authoritative.
    vocab = {t: i for i, t in enumerate(build_domain_vocab())}
    vocab["weird-token"] = 10_000
    tok = WordPieceTokenizer(vocab)
    assert tok._native_encoder() is None
    out = tok.batch_encode(["destination port is 80"], max_len=16)
    assert out["input_ids"].shape == (1, 16)


def test_empty_token_in_vocab_disables_native():
    """An empty-string token would vanish from the native '\\n'-joined vocab
    blob and shift every later id — the gate must force the Python path and
    keep the encoding identical to a no-native tokenizer."""
    base = build_domain_vocab()
    vocab = {t: i for i, t in enumerate(base)}
    vocab[""] = len(base)  # dense, but unrepresentable natively
    tok = WordPieceTokenizer(vocab)
    assert tok._native_encoder() is None
    out = tok.batch_encode(["destination port is 80"], max_len=16)
    _assert_same(out, _python_encode(tok, ["destination port is 80"], 16))


def test_native_faster_than_python():
    """Soft perf check: native should beat Python comfortably on a real
    batch (skipped margin kept loose for noisy CI hosts)."""
    import time

    tok = default_tokenizer()
    texts = texts_from_dataframe(make_synthetic("cicids2017", 2000, seed=5))
    tok.batch_encode(texts[:10], max_len=128)  # build/bind outside the timer

    t0 = time.perf_counter()
    tok.batch_encode(texts, max_len=128)
    native_t = time.perf_counter() - t0

    t0 = time.perf_counter()
    _python_encode(tok, texts, 128)
    python_t = time.perf_counter() - t0
    assert native_t < python_t, (native_t, python_t)
