"""CLI orchestration: config resolution and the local/federated flows
end-to-end on synthetic data (reference artifact names must appear)."""

import json
import os

import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
    build_parser,
    main,
    resolve_config,
)


def test_parser_covers_reference_deployment_shapes():
    ap = build_parser()
    for argv in (
        ["local", "--synthetic", "400"],
        ["federated", "--num-clients", "4", "--rounds", "2"],
        ["serve", "--port", "0", "--num-clients", "2"],
        ["client", "--client-id", "1", "--port", "12345"],
        ["export-config"],
    ):
        args = ap.parse_args(argv)
        assert callable(args.fn)


def test_resolve_config_flag_overrides():
    ap = build_parser()
    args = ap.parse_args(
        [
            "federated", "--num-clients", "4", "--rounds", "3",
            "--batch-size", "8", "--epochs", "2", "--learning-rate", "1e-3",
            "--output-dir", "/tmp/x",
        ]
    )
    cfg = resolve_config(args, vocab_size=130)
    assert cfg.fed.num_clients == 4 and cfg.fed.rounds == 3
    assert cfg.mesh.clients == 4
    assert cfg.data.batch_size == 8
    assert cfg.train.epochs_per_round == 2
    assert cfg.train.learning_rate == pytest.approx(1e-3)
    assert cfg.output_dir == "/tmp/x"


def test_resolve_config_from_file_roundtrip(tmp_path):
    ap = build_parser()
    cfg0 = resolve_config(ap.parse_args(["export-config"]), vocab_size=130)
    path = tmp_path / "cfg.json"
    path.write_text(json.dumps(cfg0.to_dict()))
    cfg1 = resolve_config(
        ap.parse_args(["federated", "--config", str(path)]), vocab_size=130
    )
    assert cfg1.model == cfg0.model
    assert cfg1.data == cfg0.data


def test_export_config_prints_json(capsys):
    assert main(["export-config", "--num-clients", "3"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["fed"]["num_clients"] == 3
    assert out["model"]["n_layers"] == 2  # tiny preset


def test_local_flow_writes_reference_artifacts(tmp_path):
    rc = main(
        [
            "local", "--synthetic", "300", "--epochs", "1",
            "--output-dir", str(tmp_path), "--seed", "0",
        ]
    )
    assert rc == 0
    assert (tmp_path / "client0_local_metrics.csv").exists()
    header = (tmp_path / "client0_local_metrics.csv").read_text().splitlines()[0]
    assert header == "Accuracy,Loss,Precision,Recall,F1-Score"
    plots = os.listdir(tmp_path / "client0_plots")
    assert "client0_local_confusion_matrix.png" in plots


@pytest.mark.slow
def test_federated_seq_parallel_full_command(tmp_path, eight_devices):
    """VERDICT r2 #2 done-criterion: the full `federated --seq-parallel 2`
    command on the virtual mesh produces the standard artifact set
    (metrics CSVs, plots, checkpoint), with dropout trained ON (the tiny
    preset's defaults) through the ring path — composed with FedProx and
    head-scope personalization (round-4: the whole trainer surface runs
    under sequence parallelism)."""
    out = tmp_path / "out"
    ckpt = tmp_path / "ckpt"
    rc = main(
        [
            "federated", "--synthetic", "160", "--num-clients", "2",
            "--rounds", "1", "--epochs", "1", "--batch-size", "8",
            "--preset", "tiny", "--seq-parallel", "2", "--data-parallel", "2",
            "--prox-mu", "0.01",
            "--personalize-epochs", "1", "--personalize-scope", "head",
            "--output-dir", str(out), "--checkpoint-dir", str(ckpt),
        ]
    )
    assert rc == 0
    for c in range(2):
        assert (out / f"client{c}_local_metrics.csv").exists()
        assert (out / f"client{c}_aggregated_metrics.csv").exists()
        assert (out / f"client{c}_personalized_metrics.csv").exists()
        plots = os.listdir(out / f"client{c}_plots")
        assert f"client{c}_metrics_comparison.png" in plots
        assert f"client{c}_aggregated_roc.png" in plots
    assert ckpt.exists() and any(ckpt.iterdir())


def test_federated_flow_writes_artifacts_and_checkpoints(tmp_path, eight_devices):
    out = tmp_path / "out"
    ckpt = tmp_path / "ckpt"
    jsonl = tmp_path / "metrics.jsonl"
    spans_jsonl = tmp_path / "spans.jsonl"
    rc = main(
        [
            "federated", "--synthetic", "600", "--num-clients", "2",
            "--rounds", "1", "--epochs", "1",
            "--output-dir", str(out), "--checkpoint-dir", str(ckpt),
            "--metrics-jsonl", str(jsonl),
            "--trace-jsonl", str(spans_jsonl),
        ]
    )
    assert rc == 0
    # Mesh-tier obs spans: the round's client-local/agg phase timers
    # landed on the events-JSONL with the fed2 path identity.
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
        load_spans,
    )

    spans = load_spans([str(spans_jsonl)])
    assert {(s["span"], s.get("round")) for s in spans} >= {
        ("client-local", 0),
        ("agg", 0),
    }
    assert all(s["proc"] == "fed" for s in spans)
    # Trainer-phase spans carry the fed2 path identity; process-level
    # xla-compile spans (obs/profile.py CompileLedger) carry site/
    # signature instead.
    assert all(
        s["path"] == "fed2" for s in spans if s["span"] != "xla-compile"
    )
    assert all(
        s["site"] for s in spans if s["span"] == "xla-compile"
    )
    # Per-round JSONL reports val AND test at both phases, like the
    # reference (client1.py:383-385,398-400).
    import json

    records = [json.loads(line) for line in jsonl.read_text().splitlines()]
    assert {(r["phase"], r["split"], r["client"]) for r in records} == {
        (p, sp, c)
        for p in ("local", "aggregated")
        for sp in ("val", "test")
        for c in (0, 1)
    }
    assert all("Accuracy" in r for r in records)
    for c in range(2):
        assert (out / f"client{c}_local_metrics.csv").exists()
        assert (out / f"client{c}_aggregated_metrics.csv").exists()
        plots = os.listdir(out / f"client{c}_plots")
        assert f"client{c}_metrics_comparison.png" in plots
        assert f"client{c}_aggregated_roc.png" in plots
    # Round checkpoint landed and is resumable (round 1 == fed.rounds, so a
    # resume is a no-op that still reports).
    assert any(p.isdigit() for p in os.listdir(ckpt))
    rc2 = main(
        [
            "federated", "--synthetic", "600", "--num-clients", "2",
            "--rounds", "1", "--epochs", "1",
            "--output-dir", str(out), "--checkpoint-dir", str(ckpt),
        ]
    )
    assert rc2 == 0



def test_local_fit_logs_per_step_telemetry(tmp_path):
    """TrainConfig.log_every drives per-step loss/throughput lines (the
    reference's tqdm per-batch reporting, client1.py:101,112)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
        Trainer,
    )
    import numpy as np

    cfg = ModelConfig.tiny()
    r = np.random.default_rng(0)
    n, L = 64, cfg.max_len
    split = TokenizedSplit(
        r.integers(1, cfg.vocab_size, (n, L)).astype(np.int32),
        np.ones((n, L), np.int32),
        r.integers(0, 2, n).astype(np.int32),
    )
    import io
    import logging

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.utils.logging import (
        get_logger,
    )

    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    logger = get_logger()
    logger.addHandler(handler)
    try:
        trainer = Trainer(cfg, TrainConfig(log_every=2, epochs_per_round=1))
        state = trainer.init_state(seed=0)
        trainer.fit(state, split, batch_size=16)
    finally:
        logger.removeHandler(handler)
    out = buf.getvalue()
    assert "samples/s" in out and "Step 2:" in out


def test_attention_impl_and_remat_flags(tmp_path):
    """--attention-impl / --attention-dropout / --remat reach the model
    config; ring without --attention-dropout 0 fails as an operator error,
    and --no-remat overrides a config file."""
    import argparse
    import json as _json

    def ns(**kw):
        base = dict(
            preset="tiny", attention_impl=None, attention_dropout=None,
            remat=None, max_len=None, config=None,
        )
        base.update(kw)
        return argparse.Namespace(**base)

    cfg = resolve_config(ns(attention_impl="flash", remat=True), vocab_size=128)
    assert cfg.model.attention_impl == "flash" and cfg.model.remat is True
    # ring + default attention_dropout is now VALID (hash-mask dropout in
    # the ring, parallel/ring_attention.py).
    cfg = resolve_config(ns(attention_impl="ring"), vocab_size=128)
    assert cfg.model.attention_impl == "ring"
    assert cfg.model.attention_dropout > 0.0
    cfg = resolve_config(
        ns(attention_impl="ring", attention_dropout=0.0), vocab_size=128
    )
    assert cfg.model.attention_impl == "ring"
    assert cfg.model.attention_dropout == 0.0
    # --no-remat beats a config file's remat=true.
    cfg_file = tmp_path / "remat.json"
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        ExperimentConfig,
        ModelConfig,
        DataConfig,
    )

    m = ModelConfig.tiny(remat=True)
    cfg_file.write_text(_json.dumps(
        ExperimentConfig(model=m, data=DataConfig(max_len=m.max_len)).to_dict()
    ))
    assert resolve_config(ns(config=str(cfg_file)), vocab_size=256).model.remat
    cfg = resolve_config(ns(config=str(cfg_file), remat=False), vocab_size=256)
    assert cfg.model.remat is False


@pytest.mark.slow
def test_flash_remat_local_run_end_to_end(tmp_path):
    """A flash+remat local run trains and reports (slow: the Pallas kernel
    compiles through the CPU interpreter here; flash numerics are
    fast-lane-covered by test_flash_in_model_forward, the CLI local flow
    by test_local_flow_writes_reference_artifacts)."""
    rc = main(
        [
            "local", "--synthetic", "200", "--epochs", "1",
            "--batch-size", "8", "--attention-impl", "flash", "--remat",
            "--output-dir", str(tmp_path / "out"),
        ]
    )
    assert rc == 0
    assert (tmp_path / "out" / "client0_local_metrics.csv").exists()


def test_parser_round_pipelining_flags():
    """ISSUE 5 flags parse and land where the commands read them."""
    ap = build_parser()
    a = ap.parse_args(["serve", "--stream-chunk-mb", "0.25"])
    assert a.stream_chunk_mb == 0.25
    assert ap.parse_args(["serve"]).stream_chunk_mb is None  # default advert
    a = ap.parse_args(["client", "--client-id", "0", "--no-stream-upload"])
    assert a.stream_upload is False
    assert ap.parse_args(["client", "--client-id", "0"]).stream_upload
    a = ap.parse_args(
        ["controller", "--registry-dir", "r", "--stream-chunk-mb", "2",
         "--max-artifacts", "8"]
    )
    assert a.stream_chunk_mb == 2.0 and a.max_artifacts == 8
    a = ap.parse_args(["infer-serve", "--trace-sample", "0.1"])
    assert a.trace_sample == 0.1
    a = ap.parse_args(
        ["registry", "gc", "--registry-dir", "r", "--max-artifacts", "5"]
    )
    assert a.action == "gc" and a.max_artifacts == 5


def test_registry_gc_cli_end_to_end(tmp_path, capsys):
    """`fedtpu registry gc --max-artifacts N` prunes retired artifacts
    through the real command path."""
    import numpy as np

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.registry import (
        ModelRegistry,
    )

    root = str(tmp_path / "reg")
    reg = ModelRegistry(root)
    for i in range(4):
        aid = reg.add(
            {"w": np.full(4, float(i), np.float32)}, round_index=i
        )
        reg.promote(aid, to="serving")
    # Shrink the chain so old retirees become prunable.
    info = reg.serving_info()
    assert len(info["history"]) == 3
    rc = main(
        ["registry", "gc", "--registry-dir", root, "--max-artifacts", "4"]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "0 artifact(s) pruned" in out  # whole chain protected
    # Break protection by rolling the pointer forward past the budget.
    for i in range(4, 7):
        aid = reg.add(
            {"w": np.full(4, float(i), np.float32)}, round_index=i
        )
        reg.promote(aid, to="serving")
    rc = main(
        ["registry", "gc", "--registry-dir", root, "--max-artifacts", "2"]
    )
    assert rc == 0
    assert "pruned" in capsys.readouterr().out
    with pytest.raises(SystemExit, match="max-artifacts"):
        main(["registry", "gc", "--registry-dir", root])
