"""Data layer tests: textualization parity, imputation, partitioning, splits."""

import numpy as np
import pandas as pd
import pytest

import detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu as fedtpu
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    flow_to_text,
    load_flow_csv,
    make_client_splits,
    make_synthetic_flows,
    partition_indices,
    texts_from_dataframe,
    train_val_test_split,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.cicids import (
    sample_client_frame,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.textualize import (
    labels_from_dataframe,
)

DataConfig = fedtpu.DataConfig


def _reference_template(row):
    # Independent transcription of the reference template (client1.py:68-81)
    # used as the expected value; flow_to_text must match byte-for-byte.
    return (
        f"Destination port is {row['Destination Port']}. "
        f"Flow duration is {row['Flow Duration']} microseconds. "
        f"Total forward packets are {row['Total Fwd Packets']}. "
        f"Total backward packets are {row['Total Backward Packets']}. "
        f"Total length of forward packets is {row['Total Length of Fwd Packets']} bytes. "
        f"Total length of backward packets is {row['Total Length of Bwd Packets']} bytes. "
        f"Maximum forward packet length is {row['Fwd Packet Length Max']}. "
        f"Minimum forward packet length is {row['Fwd Packet Length Min']}. "
        f"Flow bytes per second is {row['Flow Bytes/s']}. "
        f"Flow packets per second is {row['Flow Packets/s']}."
    )


def test_flow_to_text_matches_reference_template():
    df = make_synthetic_flows(50, seed=3, inf_fraction=0, nan_fraction=0)
    expected = df.apply(_reference_template, axis=1).tolist()
    got_rowwise = [flow_to_text(row) for _, row in df.iterrows()]
    got_vectorized = texts_from_dataframe(df)
    assert got_rowwise == expected
    assert got_vectorized == expected


def test_texts_from_dataframe_empty():
    df = make_synthetic_flows(5, seed=0).iloc[0:0]
    assert texts_from_dataframe(df) == []


def test_load_flow_csv_imputes_like_reference(tmp_path):
    df = make_synthetic_flows(300, seed=1, inf_fraction=0.05, nan_fraction=0.05)
    p = tmp_path / "x.csv"
    df.to_csv(p, index=False)
    loaded = load_flow_csv(str(p))
    num = loaded.select_dtypes(include=[np.number])
    assert np.isfinite(num.to_numpy()).all()
    # Reference order: ±inf -> NaN first, then fillna with the post-replacement
    # column mean (client1.py:87-88).
    raw = pd.read_csv(p).replace([np.inf, -np.inf], np.nan)
    expected = raw.fillna(raw.mean(numeric_only=True))
    pd.testing.assert_frame_equal(loaded, expected, check_like=True)


def test_sample_partition_matches_pandas_sample():
    df = make_synthetic_flows(500, seed=2, inf_fraction=0, nan_fraction=0)
    cfg = DataConfig(data_fraction=0.1, seed_base=42)
    c0 = sample_client_frame(df, 0.1, cfg.client_seed(0))
    c1 = sample_client_frame(df, 0.1, cfg.client_seed(1))
    pd.testing.assert_frame_equal(c0, df.sample(frac=0.1, random_state=42))
    pd.testing.assert_frame_equal(c1, df.sample(frac=0.1, random_state=43))
    assert not c0.index.equals(c1.index)


def test_split_matches_sklearn():
    from sklearn.model_selection import train_test_split

    for n in (100, 101, 4515, 22573):
        tr, va, te = train_val_test_split(n, seed=42)
        items = list(range(n))
        X_train, X_temp = train_test_split(items, test_size=0.4, random_state=42)
        X_val, X_test = train_test_split(X_temp, test_size=0.5, random_state=42)
        assert list(tr) == X_train
        assert list(va) == X_val
        assert list(te) == X_test


def test_split_disjoint_and_complete():
    tr, va, te = train_val_test_split(1000, seed=7)
    all_idx = np.concatenate([tr, va, te])
    assert len(np.unique(all_idx)) == 1000


def test_disjoint_partition():
    labels = np.zeros(1000, dtype=np.int32)
    cfg = DataConfig(partition="disjoint", data_fraction=0.2)
    parts = partition_indices(labels, 4, cfg)
    assert len(parts) == 4
    flat = np.concatenate(parts)
    assert len(np.unique(flat)) == len(flat)  # disjoint
    for p in parts:
        assert len(p) == 200  # data_fraction is per-dataset: 1000 * 0.2
    with pytest.raises(ValueError, match="infeasible"):
        partition_indices(labels, 4, DataConfig(partition="disjoint", data_fraction=0.5))


def test_dirichlet_partition_skews_labels():
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 2, size=2000).astype(np.int32)
    cfg = DataConfig(partition="dirichlet", data_fraction=1.0, dirichlet_alpha=0.1, seed_base=1)
    parts = partition_indices(labels, 4, cfg)
    flat = np.concatenate(parts)
    assert len(np.unique(flat)) == len(flat)
    fracs = [labels[p].mean() if len(p) else 0.5 for p in parts]
    assert max(fracs) - min(fracs) > 0.2  # alpha=0.1 => strong skew


def test_make_client_splits_end_to_end(synthetic_csv):
    df = load_flow_csv(synthetic_csv)
    cfg = DataConfig(data_fraction=0.5, seed_base=42)
    s0 = make_client_splits(df, 0, 2, cfg)
    s1 = make_client_splits(df, 1, 2, cfg)
    n = len(s0.train) + len(s0.val) + len(s0.test)
    assert n == int(len(df) * 0.5)
    assert abs(len(s0.train) / n - 0.6) < 0.01
    assert s0.train.texts[0] != s1.train.texts[0]  # different client seeds
    assert set(np.unique(s0.train.labels)) <= {0, 1}
    # Deterministic: same config -> same split.
    s0b = make_client_splits(df, 0, 2, cfg)
    assert s0.train.texts == s0b.train.texts
    assert (s0.train.labels == s0b.train.labels).all()


def test_labels_positive_map():
    df = pd.DataFrame({"Label": ["BENIGN", "DDoS", "PortScan", "DDoS"]})
    np.testing.assert_array_equal(labels_from_dataframe(df), [0, 1, 0, 1])
