"""Reporting layer: CSV schema parity and curve-math parity vs sklearn."""

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu import (
    reporting,
)

sklearn_metrics = pytest.importorskip("sklearn.metrics")


def _fake_metrics(seed=0):
    rng = np.random.default_rng(seed)
    n = 400
    labels = rng.integers(0, 2, n)
    probs = np.clip(labels * 0.6 + rng.normal(0.3, 0.25, n), 0.0, 1.0)
    return labels, probs


def test_save_load_metrics_roundtrip(tmp_path):
    m = {
        "Accuracy": 99.9336,
        "Loss": 0.0123,
        "Precision": 1.0,
        "Recall": 0.99884,
        "F1-Score": 0.99942,
    }
    path = reporting.save_metrics(m, str(tmp_path / "client1_local_metrics.csv"))
    back = reporting.load_metrics(path)
    assert back == pytest.approx(m)
    # Header matches the reference CSV schema exactly (client1.py:339-350).
    header = open(path).readline().strip()
    assert header == "Accuracy,Loss,Precision,Recall,F1-Score"


def test_load_reference_recorded_csv(tmp_path):
    # Byte-format compatibility with the reference's recorded results files.
    p = tmp_path / "ref.csv"
    p.write_text(
        "Accuracy,Loss,Precision,Recall,F1-Score\n"
        "99.93355481727574,0.004704117158216095,1.0,0.9988399071925754,0.9994196170177677\n"
    )
    m = reporting.load_metrics(str(p))
    assert m["Accuracy"] == pytest.approx(99.93355481727574)
    assert m["F1-Score"] == pytest.approx(0.9994196170177677)


def test_roc_curve_matches_sklearn():
    labels, probs = _fake_metrics()
    fpr, tpr, thr = reporting.roc_curve(labels, probs)
    sk_fpr, sk_tpr, sk_thr = sklearn_metrics.roc_curve(
        labels, probs, drop_intermediate=False
    )
    np.testing.assert_allclose(fpr, sk_fpr, atol=1e-12)
    np.testing.assert_allclose(tpr, sk_tpr, atol=1e-12)
    assert reporting.auc(fpr, tpr) == pytest.approx(
        sklearn_metrics.roc_auc_score(labels, probs)
    )


def test_pr_curve_matches_sklearn():
    labels, probs = _fake_metrics(1)
    precision, recall, thr = reporting.precision_recall_curve(labels, probs)
    sk_p, sk_r, sk_t = sklearn_metrics.precision_recall_curve(labels, probs)
    np.testing.assert_allclose(precision, sk_p, atol=1e-12)
    np.testing.assert_allclose(recall, sk_r, atol=1e-12)
    assert reporting.average_precision(labels, probs) == pytest.approx(
        sklearn_metrics.average_precision_score(labels, probs)
    )


def test_roc_handles_degenerate_single_class():
    labels = np.zeros(10, dtype=int)
    probs = np.linspace(0, 1, 10)
    fpr, tpr, _ = reporting.roc_curve(labels, probs)
    assert np.all(tpr == 0.0)  # no positives -> tpr pinned at 0, no NaN
    assert not np.any(np.isnan(fpr))


@pytest.mark.skipif(not reporting.HAVE_MATPLOTLIB, reason="matplotlib absent")
def test_plot_evaluation_writes_reference_plot_set(tmp_path):
    labels, probs = _fake_metrics(2)
    base = {
        "Accuracy": 99.0,
        "Loss": 0.05,
        "Precision": 0.99,
        "Recall": 0.98,
        "F1-Score": 0.985,
        "confusion_matrix": np.array([[4474, 41], [0, 862]]),
        "labels": labels,
        "probs": probs,
    }
    agg = dict(base, Accuracy=99.9, confusion_matrix=np.array([[4515, 0], [3, 859]]))
    written = reporting.plot_evaluation(base, agg, str(tmp_path), client_id=1)
    names = {p.split("/")[-1] for p in written}
    assert names == {
        "client1_local_confusion_matrix.png",
        "client1_local_roc.png",
        "client1_local_pr.png",
        "client1_aggregated_confusion_matrix.png",
        "client1_aggregated_roc.png",
        "client1_aggregated_pr.png",
        "client1_metrics_comparison.png",
    }
    for p in written:
        assert (tmp_path / p.split("/")[-1]).stat().st_size > 0


@pytest.mark.skipif(not reporting.HAVE_MATPLOTLIB, reason="matplotlib absent")
def test_plot_evaluation_degraded_local_only(tmp_path):
    # aggregated=None reproduces the reference's failure path (client1.py:405-410).
    base = {
        "Accuracy": 99.0,
        "Loss": 0.05,
        "Precision": 0.99,
        "Recall": 0.98,
        "F1-Score": 0.985,
        "confusion_matrix": np.array([[10, 1], [0, 9]]),
    }
    written = reporting.plot_evaluation(base, None, str(tmp_path), client_id=2)
    names = {p.split("/")[-1] for p in written}
    assert names == {"client2_local_confusion_matrix.png"}


def test_append_metrics_jsonl(tmp_path):
    """Structured per-round records: scalars kept, arrays dropped, one JSON
    object per line, pandas-loadable."""
    import json

    import numpy as np

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.reporting import (
        append_metrics_jsonl,
    )

    path = str(tmp_path / "m" / "rounds.jsonl")
    append_metrics_jsonl(
        path,
        {
            "round": 1, "client": 0, "phase": "local",
            "Accuracy": np.float32(99.5), "Loss": 0.01,
            "probs": np.zeros(10),  # non-scalar: dropped
        },
    )
    append_metrics_jsonl(path, {"round": 1, "client": 1, "phase": "aggregated"})
    lines = [json.loads(line) for line in open(path)]
    assert len(lines) == 2
    assert lines[0]["Accuracy"] == pytest.approx(99.5)
    assert "probs" not in lines[0]
    assert all("ts" in rec for rec in lines)
    assert lines[1]["phase"] == "aggregated"
    # Stream-merge identity (obs satellite): every record self-describes
    # its schema and run, so `fedtpu obs` / the drift monitor can merge
    # several processes' streams without guessing.
    assert all(rec["schema"] == reporting.METRICS_SCHEMA for rec in lines)
    assert all(rec["run_id"] for rec in lines)
    assert lines[0]["run_id"] == lines[1]["run_id"]


def test_append_metrics_jsonl_concurrent_writers(tmp_path):
    """Two+ threads appending concurrently must never interleave partial
    lines (the server's reply threads and the serving tier's scorer share
    one stream): every line parses, none are lost. Pinned by the single
    atomic O_APPEND os.write the writer now uses — Python's buffered
    'a'-mode writes flush long lines in pieces."""
    import json
    import threading

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.reporting import (
        append_metrics_jsonl,
    )

    path = str(tmp_path / "concurrent.jsonl")
    n_threads, per_thread = 4, 200
    # Long-ish records: well past typical libc buffer flush granularity,
    # so a non-atomic writer WOULD interleave.
    filler = {f"k{i}": float(i) * 1.5 for i in range(40)}

    def writer(tid: int) -> None:
        for i in range(per_thread):
            append_metrics_jsonl(
                path, {"phase": "stress", "thread": tid, "i": i, **filler}
            )

    threads = [
        threading.Thread(target=writer, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lines = open(path).read().splitlines()
    assert len(lines) == n_threads * per_thread
    seen = set()
    for line in lines:
        rec = json.loads(line)  # every line parses — no interleaving
        seen.add((rec["thread"], rec["i"]))
    assert len(seen) == n_threads * per_thread  # and none were lost
