"""Config system tests."""

import pytest

import detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu as fedtpu
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    ExperimentConfig,
    FedConfig,
    MeshConfig,
    ModelConfig,
)


def test_defaults_are_reference_hyperparams():
    cfg = ExperimentConfig()
    assert cfg.model.dim == 768 and cfg.model.n_layers == 6 and cfg.model.n_heads == 12
    assert cfg.model.head_dropout == 0.3 and cfg.model.n_classes == 2
    assert cfg.data.batch_size == 16 and cfg.data.max_len == 128
    assert cfg.data.data_fraction == 0.1 and cfg.data.seed_base == 42
    assert cfg.train.learning_rate == 2e-5 and cfg.train.epochs_per_round == 3
    assert cfg.fed.num_clients == 2 and cfg.fed.rounds == 1


def test_client_seed_derivation_matches_reference():
    cfg = fedtpu.DataConfig()
    assert cfg.client_seed(0) == 42  # client1.py:89
    assert cfg.client_seed(1) == 43  # client2.py:84


def test_round_trip_and_tuple_restore():
    import json

    cfg = ExperimentConfig.for_clients(4, data_parallel=2)
    d = json.loads(json.dumps(cfg.to_dict()))
    cfg2 = ExperimentConfig.from_dict(d)
    assert cfg2 == cfg
    hash(cfg2.mesh)  # tuple restored -> still hashable


def test_from_dict_rejects_unknown_keys():
    with pytest.raises(ValueError, match="learning_rte"):
        ExperimentConfig.from_dict({"train": {"learning_rte": 1e-4}})
    with pytest.raises(ValueError, match="sections"):
        ExperimentConfig.from_dict({"trian": {}})


def test_inconsistent_config_rejected():
    with pytest.raises(ValueError, match="num_clients"):
        ExperimentConfig(fed=FedConfig(num_clients=3))  # not a multiple of mesh 2
    ExperimentConfig(fed=FedConfig(num_clients=8))  # 8 clients tile a 2-wide axis
    with pytest.raises(ValueError, match="max_len"):
        ExperimentConfig(model=ModelConfig(max_len=256))
    cfg = ExperimentConfig.for_clients(8)
    assert cfg.mesh.clients == 8 and cfg.fed.num_clients == 8


def test_bert_base_preset():
    m = ModelConfig.bert_base()
    assert m.n_layers == 12 and m.dim == 768
    assert ModelConfig.tiny().head_dim == 16


def test_from_checkpoint_dict_legacy_gelu_default():
    """Checkpoints recorded before the gelu field existed were trained
    under the then-default erf GELU; restoring their config must not pick
    up today's tanh default."""
    cfg = ExperimentConfig()
    d = cfg.to_dict()
    del d["model"]["gelu"]  # a pre-gelu-field checkpoint's recorded config
    assert ExperimentConfig.from_checkpoint_dict(d).model.gelu == "exact"
    # An explicitly recorded gelu always wins.
    d["model"]["gelu"] = "tanh"
    assert ExperimentConfig.from_checkpoint_dict(d).model.gelu == "tanh"
    # A config with no model section at all is also legacy-exact.
    d2 = cfg.to_dict()
    del d2["model"]
    assert ExperimentConfig.from_checkpoint_dict(d2).model.gelu == "exact"
