"""Secure aggregation (comm/secure.py): mask cancellation, uniformity of
what the server sees, and the end-to-end masked TCP round.

The reference's server reads every client's raw weights off the wire
(server.py:57-65); here the server must recover ONLY the mean."""

import struct
import threading

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
    FederatedClient,
    aggregate_flat,
    flatten_params,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.secure import (
    DEFAULT_FP_BITS,
    SecureAggError,
    aggregate_masked,
    dequantize_sum,
    dh_keypair,
    dh_pair_secret,
    mask,
    masked_upload,
    quantize,
    sum_masked,
)


def _fleet_keys(n, tag=b"t"):
    """Deterministic DH keypairs + per-client pair-secret dicts, the
    artifact each client derives from the relayed public keys."""
    pairs = [dh_keypair(entropy=tag + bytes([i])) for i in range(n)]
    secrets = [
        {j: dh_pair_secret(pairs[i][0], pairs[j][1]) for j in range(n) if j != i}
        for i in range(n)
    ]
    return pairs, secrets


def _params(rng, scale=1.0):
    return {
        "encoder": {
            "kernel": (scale * rng.normal(size=(6, 4))).astype(np.float32),
            "bias": (scale * rng.normal(size=(4,))).astype(np.float32),
        },
        "head": {"w": (scale * rng.normal(size=(4, 2))).astype(np.float32)},
    }


def _flats(rng, n, scale=1.0):
    return [flatten_params(_params(rng, scale)) for _ in range(n)]


def test_quantize_dequantize_roundtrip(rng):
    flat = flatten_params(_params(rng))
    q = quantize(flat)
    back = dequantize_sum(q, n_clients=1)
    for key in flat:
        np.testing.assert_allclose(
            back[key], flat[key], atol=2.0 / (1 << DEFAULT_FP_BITS)
        )


def test_masks_cancel_to_exact_quantized_sum(rng):
    C = 3
    flats = _flats(rng, C)
    ids = list(range(C))
    _, secrets = _fleet_keys(C)
    masked = [
        masked_upload(
            flats[i],
            pair_secrets=secrets[i],
            round_index=4,
            client_id=i,
            participants=ids,
        )
        for i in ids
    ]
    summed = sum_masked(masked)
    plain_sum = sum_masked([quantize(f) for f in flats])
    for key in summed:
        # Bit-exact modular cancellation — not approximate.
        np.testing.assert_array_equal(summed[key], plain_sum[key])


def test_secure_mean_matches_plain_fedavg(rng):
    C = 4
    flats = _flats(rng, C)
    _, secrets = _fleet_keys(C)
    masked = [
        masked_upload(
            flats[i],
            pair_secrets=secrets[i],
            round_index=0,
            client_id=i,
            participants=range(C),
        )
        for i in range(C)
    ]
    agg = aggregate_masked(masked)
    expected = aggregate_flat(flats)
    for key in expected:
        np.testing.assert_allclose(
            agg[key], expected[key], atol=2.0 / (1 << DEFAULT_FP_BITS)
        )


def test_single_upload_reveals_nothing(rng):
    """One masked upload must look nothing like the raw quantized weights —
    and two uploads of the SAME weights under different pair partners or
    rounds must differ (fresh masks per round)."""
    flat = flatten_params(_params(rng))
    _, secrets = _fleet_keys(2)
    m1 = masked_upload(
        flat, pair_secrets=secrets[0], round_index=0, client_id=0, participants=[0, 1]
    )
    q = quantize(flat)
    for key in q:
        assert not np.array_equal(m1[key], q[key])
    m2 = masked_upload(
        flat, pair_secrets=secrets[0], round_index=1, client_id=0, participants=[0, 1]
    )
    for key in q:
        assert not np.array_equal(m1[key], m2[key])
    # Deterministic per (secret, round, pair): same inputs, same masks.
    m1_again = masked_upload(
        flat, pair_secrets=secrets[0], round_index=0, client_id=0, participants=[0, 1]
    )
    for key in q:
        np.testing.assert_array_equal(m1[key], m1_again[key])


def test_missing_participant_leaves_garbage(rng):
    """Without client 2's upload the pairwise masks do NOT cancel — the
    'sum' is ring noise, which is exactly why the server enforces the full
    participant set."""
    C = 3
    flats = _flats(rng, C)
    _, secrets = _fleet_keys(C)
    masked = [
        masked_upload(
            flats[i],
            pair_secrets=secrets[i],
            round_index=0,
            client_id=i,
            participants=range(C),
        )
        for i in range(C)
    ]
    partial = dequantize_sum(sum_masked(masked[:2]), 2)
    expected = aggregate_flat(flats[:2])
    worst = max(
        np.abs(partial[k] - expected[k]).max() for k in expected
    )
    assert worst > 1.0  # uncancelled uniform masks dwarf real weights


def test_session_nonce_separates_mask_streams(rng):
    """Same secret, same round, different server session -> different
    masks: re-running the pipeline never reuses a stream (an observer
    can't difference uploads across server restarts)."""
    flat = flatten_params(_params(rng))
    _, secrets = _fleet_keys(2)
    kw = dict(
        pair_secrets=secrets[0], round_index=0, client_id=0, participants=[0, 1]
    )
    a = masked_upload(flat, session=b"A" * 16, **kw)
    b = masked_upload(flat, session=b"B" * 16, **kw)
    for key in a:
        assert not np.array_equal(a[key], b[key])
    # Two live servers draw distinct random sessions.
    with AggregationServer(port=0, num_clients=2, secure_agg=True) as s1, \
         AggregationServer(port=0, num_clients=2, secure_agg=True) as s2:
        assert s1._session != s2._session
        assert len(s1._session) == 16


def test_client_refuses_replayed_round(rng):
    """A server advertising an already-used (session, round) to a later
    exchange must be refused — masking different weights under the same
    stream is the differencing attack."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        SecureAggError,
        recv_frame,
        send_frame,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.wire import (
        KEYS_MAGIC,
        PUBKEY_MAGIC,
        ROUND_MAGIC,
        encode,
    )
    import socket as socket_mod

    session = b"S" * 16
    reply = encode({"w": np.zeros(3, np.float32)}, meta={"round_clients": [0, 1]})
    srv = socket_mod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]
    _, pub1 = dh_keypair(entropy=b"peer")

    def _fake_server():
        for _ in range(2):  # two connections, SAME advertised round
            conn, _ = srv.accept()
            conn.settimeout(10)
            try:
                send_frame(
                    conn,
                    ROUND_MAGIC + struct.pack("<Q", 3) + session
                    + bytes([0]),  # PROTO_REVEAL
                )
                hello = recv_frame(conn)  # client's DH pubkey
                assert hello.startswith(PUBKEY_MAGIC)
                pub0 = hello[len(PUBKEY_MAGIC) + 8 :]
                send_frame(
                    conn,
                    KEYS_MAGIC
                    + struct.pack("<q", 0) + pub0
                    + struct.pack("<q", 1) + pub1,
                )
                recv_frame(conn)  # masked upload
                send_frame(conn, reply)
            except Exception:
                pass  # second connection dies when the client refuses
            finally:
                conn.close()

    t = threading.Thread(target=_fake_server, daemon=True)
    t.start()
    client = FederatedClient(
        "127.0.0.1", port, client_id=0, timeout=10,
        secure_agg=True, num_clients=2, secure_protocol="reveal",
    )
    params = _params(rng)
    client.exchange(params, max_retries=1)  # first use of round 3: fine
    with pytest.raises(SecureAggError, match="replayed round 3"):
        client.exchange(params, max_retries=1)
    srv.close()


def test_mask_input_validation(rng):
    flat = quantize(flatten_params(_params(rng)))
    _, secrets = _fleet_keys(2)
    with pytest.raises(SecureAggError, match="participants"):
        mask(flat, pair_secrets=secrets[0], round_index=0, client_id=5,
             participants=[0, 1])
    with pytest.raises(SecureAggError, match=">= 2"):
        mask(flat, pair_secrets=secrets[0], round_index=0, client_id=0,
             participants=[0])
    with pytest.raises(SecureAggError, match="lacks pair secrets"):
        mask(flat, pair_secrets={}, round_index=0, client_id=0,
             participants=[0, 1])
    with pytest.raises(SecureAggError, match="expected float"):
        quantize({"a": np.arange(3, dtype=np.int32)})


def test_server_rejects_mode_mismatch(rng):
    """A raw (unmasked) upload into a secure server must be refused — and a
    masked upload into a plain server likewise — instead of silently
    averaging ring elements as if they were weights."""
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=5, secure_agg=True
    ) as server:

        def _plain_client():
            try:
                FederatedClient(
                    "127.0.0.1", server.port, client_id=0, timeout=5
                ).exchange(_params(np.random.default_rng(0)), max_retries=1)
            except ConnectionError as e:
                results["err"] = e

        t = threading.Thread(target=_plain_client, daemon=True)
        t.start()
        with pytest.raises(RuntimeError, match="secure round incomplete|0/2|clients"):
            server.serve_round(deadline=3.0)
        t.join(timeout=5)
    assert "err" in results


def test_server_constructor_guards():
    with pytest.raises(ValueError, match="unweighted"):
        AggregationServer(port=0, num_clients=2, weighted=True, secure_agg=True)
    # A quorum below 2 would make the lone survivor's "sum" its raw update.
    with pytest.raises(ValueError, match="min_clients"):
        AggregationServer(port=0, num_clients=3, min_clients=1, secure_agg=True)
    with pytest.raises(ValueError, match="num_clients"):
        FederatedClient("h", 1, client_id=0, secure_agg=True)
    # Dropout recovery: a secure quorum below the fleet is now legal.
    AggregationServer(
        port=0, num_clients=3, min_clients=2, secure_agg=True
    ).close()


@pytest.mark.parametrize("auth", [False, True])
def test_secure_tcp_round_end_to_end(rng, auth):
    """Full masked round over localhost: 3 clients upload masked weights,
    the server recovers only the mean, every client receives it."""
    C = 3
    params = [_params(rng) for _ in range(C)]
    auth_key = b"wire-auth" if auth else None
    results = {}
    with AggregationServer(
        port=0, num_clients=C, timeout=30, secure_agg=True, auth_key=auth_key
    ) as server:

        def _run_server():
            results["agg"] = server.serve_round(deadline=30)

        st = threading.Thread(target=_run_server)
        st.start()

        def _run_client(cid):
            client = FederatedClient(
                "127.0.0.1",
                server.port,
                client_id=cid,
                timeout=30,
                auth_key=auth_key,
                secure_agg=True,
                num_clients=C,
            )
            results[cid] = client.exchange(params[cid])

        threads = [
            threading.Thread(target=_run_client, args=(cid,)) for cid in range(C)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        st.join(timeout=30)

    assert "agg" in results and all(c in results for c in range(C))
    expected = aggregate_flat([flatten_params(p) for p in params])
    for key, arr in flatten_params(results[0]).items():
        np.testing.assert_allclose(
            arr, expected[key], atol=2.0 / (1 << DEFAULT_FP_BITS)
        )
    for key, arr in flatten_params(results[1]).items():
        np.testing.assert_array_equal(arr, flatten_params(results[0])[key])


def _secure_round(server, params, *, num_clients, results):
    """Run one masked round: server thread + one client thread each."""
    st = threading.Thread(
        target=lambda: results.__setitem__("agg", server.serve_round(deadline=20))
    )
    st.start()

    def _go(cid):
        results[cid] = FederatedClient(
            "127.0.0.1",
            server.port,
            client_id=cid,
            timeout=20,
            secure_agg=True,
            num_clients=num_clients,
        ).exchange(params[cid])

    ts = [threading.Thread(target=_go, args=(c,)) for c in range(len(params))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=20)
    st.join(timeout=20)


def test_consecutive_rounds_use_fresh_masks(rng):
    """The server's round advert advances every round, so the same client
    weights upload under different masks each round — the server can never
    difference two rounds' uploads to unmask a client. Both rounds must
    still aggregate correctly."""
    C = 2
    params = [_params(rng) for _ in range(C)]
    expected = aggregate_flat([flatten_params(p) for p in params])
    with AggregationServer(
        port=0, num_clients=C, timeout=20, secure_agg=True
    ) as server:
        for _ in range(2):
            results = {}
            _secure_round(server, params, num_clients=C, results=results)
            assert "agg" in results
            for key, arr in flatten_params(results[0]).items():
                np.testing.assert_allclose(
                    arr, expected[key], atol=2.0 / (1 << DEFAULT_FP_BITS)
                )
        assert server._round_counter == 2


def test_client_masks_over_keys_frame_not_config(rng):
    """The keys frame, not the client's num_clients config, defines the
    mask participant set: a client configured for a 3-party fleet served
    by a 2-party server masks over the 2-party key set (having opted into
    subset quorums via min_participants) and the round completes with the
    exact mean (num_clients is only an id-validation bound). This is the
    invariant that makes subset rounds safe — a client can never mask
    against a set different from the keys it was handed."""
    params = [_params(rng) for _ in range(2)]
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=20, secure_agg=True
    ) as server:
        st = threading.Thread(
            target=lambda: results.__setitem__(
                "agg", server.serve_round(deadline=20)
            )
        )
        st.start()

        def _go(cid):
            results[cid] = FederatedClient(
                "127.0.0.1",
                server.port,
                client_id=cid,
                timeout=20,
                secure_agg=True,
                num_clients=3,  # larger than the actual fleet
                min_participants=2,  # opt into subset quorums
            ).exchange(params[cid])

        ts = [threading.Thread(target=_go, args=(c,)) for c in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=20)
        st.join(timeout=20)
    expected = aggregate_flat([flatten_params(p) for p in params])
    for key, arr in flatten_params(results[0]).items():
        np.testing.assert_allclose(
            arr, expected[key], atol=2.0 / (1 << DEFAULT_FP_BITS)
        )


def test_reveal_residual_restores_survivor_mean(rng):
    """Unit-level reveal round: client 2 goes silent after the key
    exchange; subtracting the revealed pairs' regenerated mask streams
    from the 2-survivor ring sum restores exact cancellation and the
    survivors' mean."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.secure import (
        residual_mask_sum,
    )

    C, session, rnd_idx = 3, b"s" * 16, 4
    flats = _flats(rng, C)
    _, secrets = _fleet_keys(C)
    masked = [
        masked_upload(
            flats[i],
            pair_secrets=secrets[i],
            round_index=rnd_idx,
            client_id=i,
            participants=range(C),
            session=session,
        )
        for i in range(C)
    ]
    summed = sum_masked(masked[:2])
    revealed = {0: {2: secrets[0][2]}, 1: {2: secrets[1][2]}}
    residue = residual_mask_sum(
        summed, revealed, session=session, round_index=rnd_idx
    )
    fixed = {k: summed[k] - residue[k] for k in summed}
    got = dequantize_sum(fixed, 2)
    expected = aggregate_flat(flats[:2])
    for key in expected:
        np.testing.assert_allclose(
            got[key], expected[key], atol=2.0 / (1 << DEFAULT_FP_BITS)
        )


def _keyed_then_dead_client(port, cid, *, died, auth_key=None, tag_key=None):
    """Speak the secure protocol up to the keys frame, then vanish — the
    dropout window the reveal round exists for."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        framing,
        wire,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.client import (
        connect_with_retry,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.secure import (
        dh_keypair,
        pubkey_tag,
    )

    sock = connect_with_retry("127.0.0.1", port, timeout=10)
    try:
        sock.settimeout(10)
        if auth_key is not None:
            framing.recv_frame(sock)  # nonce challenge (unused: we die)
        adv = framing.recv_frame(sock)  # round advert
        n_magic = len(wire.ROUND_MAGIC)
        round_no = struct.unpack("<Q", adv[n_magic : n_magic + 8])[0]
        session = bytes(adv[n_magic + 8 : n_magic + 8 + 16])
        _, pub = dh_keypair()
        hello = wire.PUBKEY_MAGIC + struct.pack("<q", cid) + pub
        if auth_key is not None:
            # tag_key: the per-client identity key when the server runs
            # with a client_keys registry (the hello must verify under
            # the CLAIMED id's own key).
            hello += pubkey_tag(
                tag_key if tag_key is not None else auth_key,
                session, round_no, cid, pub,
            )
        framing.send_frame(sock, hello)
        framing.recv_frame(sock)  # keys frame — then die before uploading
    finally:
        sock.close()
        died.set()


@pytest.mark.slow
@pytest.mark.parametrize("auth", [False, True])
def test_secure_round_survives_dropout_after_keys(rng, auth):
    """VERDICT r3 #3 done-criterion: one client dies mid-secure-round
    (after the key exchange, before its upload); the reveal round lets
    the aggregation complete with the correct mean over survivors —
    --secure-agg now composes with min_clients/deadline. Auth mode also
    exercises the reveal request/response HMAC tags."""
    C = 3
    auth_key = b"reveal-auth" if auth else None
    params = [_params(rng) for _ in range(C)]
    results = {}
    died = threading.Event()
    with AggregationServer(
        port=0, num_clients=C, timeout=20, secure_agg=True, min_clients=2,
        auth_key=auth_key, secure_protocol="reveal",
    ) as server:
        st = threading.Thread(
            target=lambda: results.__setitem__(
                "agg", server.serve_round(deadline=8)
            )
        )
        st.start()
        dead = threading.Thread(
            target=_keyed_then_dead_client,
            args=(server.port, 2),
            kwargs={"died": died, "auth_key": auth_key},
        )
        dead.start()

        def _go(cid):
            results[cid] = FederatedClient(
                "127.0.0.1",
                server.port,
                client_id=cid,
                timeout=20,
                secure_agg=True,
                num_clients=C,
                auth_key=auth_key,
                secure_protocol="reveal",
            ).exchange(params[cid])

        ts = [threading.Thread(target=_go, args=(c,)) for c in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        st.join(timeout=30)
        dead.join(timeout=10)

    assert died.is_set() and "agg" in results
    expected = aggregate_flat([flatten_params(p) for p in params[:2]])
    for key, arr in flatten_params(results[0]).items():
        np.testing.assert_allclose(
            arr, expected[key], atol=2.0 / (1 << DEFAULT_FP_BITS)
        )
    np.testing.assert_array_equal(
        flatten_params(results[0])["head/w"],
        flatten_params(results[1])["head/w"],
    )


def test_secure_round_survives_dropout_before_keys(rng):
    """A client that never connects at all: the key grace window closes
    the key set at the min_clients quorum, survivors (whose
    min_participants floor matches the server's min_clients) mask over
    the subset, and the round completes as soon as they all upload."""
    C = 3
    params = [_params(rng) for _ in range(C)]
    results = {}
    with AggregationServer(
        port=0,
        num_clients=C,
        timeout=20,
        secure_agg=True,
        min_clients=2,
        key_grace=1.5,
    ) as server:
        st = threading.Thread(
            target=lambda: results.__setitem__(
                "agg", server.serve_round(deadline=15)
            )
        )
        st.start()

        def _go(cid):
            results[cid] = FederatedClient(
                "127.0.0.1",
                server.port,
                client_id=cid,
                timeout=20,
                secure_agg=True,
                num_clients=C,
                min_participants=2,  # mirror the server's min_clients
            ).exchange(params[cid])

        # Client 2 never shows up.
        ts = [threading.Thread(target=_go, args=(c,)) for c in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        st.join(timeout=30)

    expected = aggregate_flat([flatten_params(p) for p in params[:2]])
    for key, arr in flatten_params(results[0]).items():
        np.testing.assert_allclose(
            arr, expected[key], atol=2.0 / (1 << DEFAULT_FP_BITS)
        )


def test_per_client_identity_keys_round_and_impersonation(rng):
    """Per-client DH identity binding (VERDICT r3 #6): a round with
    registered per-client keys completes exactly; a malicious member
    holding the group key + its OWN key but claiming ANOTHER id fails
    closed at the server (its forged hello is rejected, the honest
    holder completes the round)."""
    group = b"group-secret"
    ckeys = {0: b"id-key-0", 1: b"id-key-1"}
    params = [_params(rng) for _ in range(2)]
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=20, secure_agg=True,
        auth_key=group, client_keys=ckeys,
    ) as server:
        st = threading.Thread(
            target=lambda: results.__setitem__(
                "agg", server.serve_round(deadline=20)
            )
        )
        st.start()

        # The attacker: group member 1's key material, claiming id 0.
        # Its hello tag can only be under b"id-key-1" (or the group key)
        # — never id 0's key — so the server must drop it.
        def _impersonate():
            from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
                framing,
                wire,
            )
            from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.client import (
                connect_with_retry,
            )
            from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.secure import (
                pubkey_tag,
            )

            sock = connect_with_retry("127.0.0.1", server.port, timeout=10)
            try:
                sock.settimeout(10)
                framing.recv_frame(sock)  # nonce
                adv = framing.recv_frame(sock)  # round advert
                n = len(wire.ROUND_MAGIC)
                round_no = struct.unpack("<Q", adv[n : n + 8])[0]
                session = bytes(adv[n + 8 : n + 8 + 16])
                _, pub = dh_keypair(entropy=b"attacker")
                # Best available forgery: claim id 0, tag with key 1.
                hello = (
                    wire.PUBKEY_MAGIC + struct.pack("<q", 0) + pub
                    + pubkey_tag(ckeys[1], session, round_no, 0, pub)
                )
                framing.send_frame(sock, hello)
                try:
                    framing.recv_frame(sock)
                    results["forged"] = "accepted"
                except Exception:
                    results["forged"] = "rejected"
            finally:
                sock.close()

        at = threading.Thread(target=_impersonate, daemon=True)
        at.start()
        at.join(timeout=15)
        assert results.get("forged") == "rejected"

        def _go(cid):
            results[cid] = FederatedClient(
                "127.0.0.1",
                server.port,
                client_id=cid,
                timeout=20,
                secure_agg=True,
                num_clients=2,
                auth_key=group,
                client_key=ckeys[cid],
            ).exchange(params[cid])

        ts = [threading.Thread(target=_go, args=(c,)) for c in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        st.join(timeout=30)
    expected = aggregate_flat([flatten_params(p) for p in params])
    for key, arr in flatten_params(results[0]).items():
        np.testing.assert_allclose(
            arr, expected[key], atol=2.0 / (1 << DEFAULT_FP_BITS)
        )


def test_unregistered_id_refused_with_client_keys():
    with pytest.raises(ValueError, match="auth_key"):
        AggregationServer(
            port=0, num_clients=2, secure_agg=True,
            client_keys={0: b"k0", 1: b"k1"},
        )
    with pytest.raises(ValueError, match="auth_key"):
        FederatedClient(
            "h", 1, client_id=0, secure_agg=True, num_clients=2,
            client_key=b"k0",
        )


def test_one_clients_keys_cannot_unmask_another_pair(rng):
    """VERDICT r2 #4 done-criterion: per-pair DH keys mean one client's
    ENTIRE key material (its private exponent, all public keys, and every
    pair secret it legitimately holds) cannot reconstruct another pair's
    mask stream — unlike the old single shared FEDTPU_MASK_SECRET, where
    any client could unmask everyone."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.secure import (
        _pair_stream,
    )

    pairs, secrets = _fleet_keys(3)
    (x0, _), (x1, pub1), (x2, pub2) = pairs
    s12 = dh_pair_secret(x1, pub2)  # the (1, 2) pair's true secret
    assert s12 == dh_pair_secret(x2, pub1)  # both ends agree
    # Everything client 0 can derive differs from the (1,2) secret ...
    derivable = {
        dh_pair_secret(x0, pub1),
        dh_pair_secret(x0, pub2),
        *secrets[0].values(),
    }
    assert s12 not in derivable
    # ... and none of it keys the (1,2) stream: the true stream's bytes
    # differ from a stream keyed by anything client 0 holds.
    true_stream = _pair_stream(s12, b"s" * 16, 7, 1, 2).integers(
        0, 2**64, size=64, dtype=np.uint64, endpoint=False
    )
    for guess in derivable:
        guess_stream = _pair_stream(guess, b"s" * 16, 7, 1, 2).integers(
            0, 2**64, size=64, dtype=np.uint64, endpoint=False
        )
        assert not np.array_equal(guess_stream, true_stream)
    # Functional consequence: client 0 cannot strip client 1's masks from
    # its upload, but client 1's own secrets regenerate them exactly.
    flat = flatten_params(_params(rng))
    q = quantize(flat)
    m1 = mask(
        q, pair_secrets=secrets[1], round_index=7, client_id=1,
        participants=[0, 1, 2], session=b"s" * 16,
    )
    key = sorted(q)[0]
    shape = q[key].shape
    # Client 1 (legitimate): subtract its own streams -> exact raw values.
    recovered = np.array(m1[key], copy=True)
    for other, sign in ((0, -1), (2, +1)):
        # client 1 is hi of pair (0,1) [subtracted on mask] and lo of
        # (1,2) [added on mask]; invert each.
        st = _pair_stream(secrets[1][other], b"s" * 16, 7,
                          min(1, other), max(1, other))
        stream = st.integers(0, 2**64, size=shape, dtype=np.uint64,
                             endpoint=False)
        recovered = recovered - stream if sign == 1 else recovered + stream
    np.testing.assert_array_equal(recovered, q[key])
    # Client 0 (attacker): its best guesses leave the upload masked.
    attacked = np.array(m1[key], copy=True)
    for guess in (dh_pair_secret(x0, pub1), dh_pair_secret(x0, pub2)):
        st = _pair_stream(guess, b"s" * 16, 7, 1, 2)
        attacked -= st.integers(0, 2**64, size=shape, dtype=np.uint64,
                                endpoint=False)
    assert not np.array_equal(attacked, q[key])


def test_dh_public_value_validation():
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.secure import (
        DH_PRIME,
        DH_PUB_LEN,
        check_dh_public,
    )

    x, pub = dh_keypair(entropy=b"ok")
    assert check_dh_public(pub) == int.from_bytes(pub, "big")
    for bad in (
        b"\x00" * DH_PUB_LEN,  # 0
        (1).to_bytes(DH_PUB_LEN, "big"),  # 1
        (DH_PRIME - 1).to_bytes(DH_PUB_LEN, "big"),  # p-1 (order 2)
        b"\xff" * DH_PUB_LEN,  # >= p
        b"short",
    ):
        with pytest.raises(SecureAggError):
            check_dh_public(bad)


def test_retry_after_wire_error_reuses_keypair_and_completes(rng):
    """A transient wire error after key distribution must not doom the
    round: the client reuses its per-(session, round) DH keypair on retry
    and the server accepts the idempotent re-hello."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        recv_frame,
        send_frame,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.wire import (
        KEYS_MAGIC,
        PUBKEY_MAGIC,
        ROUND_MAGIC,
        encode,
    )
    import socket as socket_mod

    session = b"R" * 16
    reply = encode({"w": np.zeros(3, np.float32)}, meta={"round_clients": [0, 1]})
    srv = socket_mod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]
    _, pub1 = dh_keypair(entropy=b"peer2")
    pubs = []

    def _flaky_server():
        for attempt in range(2):
            conn, _ = srv.accept()
            conn.settimeout(10)
            try:
                send_frame(
                    conn,
                    ROUND_MAGIC + struct.pack("<Q", 5) + session
                    + bytes([0]),  # PROTO_REVEAL
                )
                hello = recv_frame(conn)
                assert hello.startswith(PUBKEY_MAGIC)
                pubs.append(hello[len(PUBKEY_MAGIC) + 8 :])
                send_frame(
                    conn,
                    KEYS_MAGIC
                    + struct.pack("<q", 0) + pubs[-1]
                    + struct.pack("<q", 1) + pub1,
                )
                recv_frame(conn)  # masked upload
                if attempt == 0:
                    conn.close()  # transient failure: no reply
                    continue
                send_frame(conn, reply)
            finally:
                conn.close()

    t = threading.Thread(target=_flaky_server, daemon=True)
    t.start()
    client = FederatedClient(
        "127.0.0.1", port, client_id=0, timeout=10,
        secure_agg=True, num_clients=2, secure_protocol="reveal",
    )
    out = client.exchange(_params(rng), max_retries=3)
    assert "w" in flatten_params(out)
    # Both attempts sent the IDENTICAL public key (per-round keypair reuse).
    assert len(pubs) == 2 and pubs[0] == pubs[1]
    srv.close()


def test_min_participants_validation():
    """The quorum floor must sit in [2, num_clients]; outside secure mode
    the knob is meaningless and refused."""
    with pytest.raises(ValueError, match="min_participants"):
        FederatedClient(
            "h", 1, client_id=0, secure_agg=True, num_clients=3,
            min_participants=1,
        )
    with pytest.raises(ValueError, match="min_participants"):
        FederatedClient(
            "h", 1, client_id=0, secure_agg=True, num_clients=3,
            min_participants=4,
        )
    with pytest.raises(ValueError, match="secure"):
        FederatedClient("h", 1, client_id=0, min_participants=2)


def test_keys_frame_below_default_floor_fails_closed(rng):
    """Anti-downgrade (ADVICE r4 medium): with no explicit
    min_participants a client's floor is its FULL fleet, so a server
    handing it a shrunken participant set — the compromised-server /
    no-auth-MITM move that reduces a client's mask partners to one
    colluding member — is refused before any masked bytes go out, and
    the refusal is non-retryable (exactly one connection)."""
    import socket as socket_mod

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        recv_frame,
        send_frame,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.wire import (
        KEYS_MAGIC,
        PUBKEY_MAGIC,
        ROUND_MAGIC,
    )

    session = b"D" * 16
    _, colluder_pub = dh_keypair(entropy=b"colluder")
    accepts = []
    srv = socket_mod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    srv.settimeout(15)
    port = srv.getsockname()[1]

    def _downgrading_server():
        try:
            while True:
                conn, _ = srv.accept()
                accepts.append(1)
                conn.settimeout(10)
                try:
                    send_frame(
                        conn,
                        ROUND_MAGIC + struct.pack("<Q", 1) + session
                        + bytes([1]),  # PROTO_DOUBLE
                    )
                    hello = recv_frame(conn)
                    pub0 = hello[len(PUBKEY_MAGIC) + 8 :]
                    # 2-member set for a client expecting a 3-party fleet.
                    send_frame(
                        conn,
                        KEYS_MAGIC
                        + struct.pack("<q", 0) + pub0
                        + struct.pack("<q", 1) + colluder_pub,
                    )
                    recv_frame(conn)  # the masked upload, if any
                finally:
                    conn.close()
        except OSError:
            pass  # listener closed: test over

    t = threading.Thread(target=_downgrading_server, daemon=True)
    t.start()
    client = FederatedClient(
        "127.0.0.1", port, client_id=0, timeout=10,
        secure_agg=True, num_clients=3,  # floor defaults to the fleet: 3
    )
    with pytest.raises(SecureAggError, match="min_participants"):
        client.exchange(_params(rng), max_retries=3)
    srv.close()
    t.join(timeout=5)
    assert len(accepts) == 1  # refused WITHOUT retry


def test_reveal_frames_ride_per_client_keys():
    """Reveal request/response tags switch to the per-client identity key
    when provisioned: a group-keyed forgery (an in-group adversary trying
    to harvest a victim's pair secrets) does not parse under the client's
    own key, and vice versa."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.secure import (
        build_reveal_request,
        build_reveal_response,
        parse_reveal_request,
        parse_reveal_response,
    )

    session, rnd_no = b"s" * 16, 3
    group, own = b"group-key", b"id-key-0"
    forged = build_reveal_request(
        [1], session=session, round_index=rnd_no, auth_key=group
    )
    with pytest.raises(SecureAggError):
        parse_reveal_request(
            forged, session=session, round_index=rnd_no, auth_key=own
        )
    good = build_reveal_request(
        [1], session=session, round_index=rnd_no, auth_key=own
    )
    assert parse_reveal_request(
        good, session=session, round_index=rnd_no, auth_key=own
    ) == [1]
    resp = build_reveal_response(
        {1: b"p" * 32}, session=session, round_index=rnd_no,
        client_id=0, auth_key=own,
    )
    with pytest.raises(SecureAggError):
        parse_reveal_response(
            resp, session=session, round_index=rnd_no, client_id=0,
            expect_dead=[1], auth_key=group,
        )
    assert parse_reveal_response(
        resp, session=session, round_index=rnd_no, client_id=0,
        expect_dead=[1], auth_key=own,
    ) == {1: b"p" * 32}


def test_secure_dropout_reveal_with_per_client_keys(rng):
    """End-to-end dropout reveal under per-client identity keys: the
    reveal exchange rides each survivor's OWN key (request under the
    recipient's, response under the sender's) and the round completes
    with the survivors' exact mean."""
    C = 3
    group = b"group-secret"
    ckeys = {i: b"id-key-%d" % i for i in range(C)}
    params = [_params(rng) for _ in range(C)]
    results = {}
    died = threading.Event()
    with AggregationServer(
        port=0, num_clients=C, timeout=20, secure_agg=True, min_clients=2,
        auth_key=group, client_keys=ckeys, secure_protocol="reveal",
    ) as server:
        st = threading.Thread(
            target=lambda: results.__setitem__(
                "agg", server.serve_round(deadline=8)
            )
        )
        st.start()
        dead = threading.Thread(
            target=_keyed_then_dead_client,
            args=(server.port, 2),
            kwargs={"died": died, "auth_key": group, "tag_key": ckeys[2]},
        )
        dead.start()

        def _go(cid):
            results[cid] = FederatedClient(
                "127.0.0.1",
                server.port,
                client_id=cid,
                timeout=20,
                secure_agg=True,
                num_clients=C,
                auth_key=group,
                client_key=ckeys[cid],
                secure_protocol="reveal",
            ).exchange(params[cid])

        ts = [threading.Thread(target=_go, args=(c,)) for c in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        st.join(timeout=30)
        dead.join(timeout=10)

    assert died.is_set() and "agg" in results
    expected = aggregate_flat([flatten_params(p) for p in params[:2]])
    for key, arr in flatten_params(results[0]).items():
        np.testing.assert_allclose(
            arr, expected[key], atol=2.0 / (1 << DEFAULT_FP_BITS)
        )


def _double_scripted_client(
    port, cid, *, die_after, died, params=None, results=None
):
    """Speak the double-masking protocol up to ``die_after`` ("shares":
    dealt but never uploaded; "upload": uploaded but vanished before the
    unmask round) then drop the connection — the two dropout windows the
    Shamir construction recovers from."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        framing,
        shamir,
        wire,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        secure as sec,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.client import (
        connect_with_retry,
    )
    import os as os_mod

    sock = connect_with_retry("127.0.0.1", port, timeout=10)
    try:
        sock.settimeout(10)
        adv = framing.recv_frame(sock)
        nm = len(wire.ROUND_MAGIC)
        round_no = struct.unpack("<Q", adv[nm : nm + 8])[0]
        session = bytes(adv[nm + 8 : nm + 8 + 16])
        assert adv[-1] == sec.PROTO_DOUBLE
        sk_seed = os_mod.urandom(sec.SEED_LEN)
        priv, pub = dh_keypair(entropy=sk_seed)
        framing.send_frame(
            sock, wire.PUBKEY_MAGIC + struct.pack("<q", cid) + pub
        )
        keys = framing.recv_frame(sock)
        entry = 8 + sec.DH_PUB_LEN
        pubs = {}
        for off in range(len(wire.KEYS_MAGIC), len(keys), entry):
            (kcid,) = struct.unpack("<q", keys[off : off + 8])
            pubs[kcid] = keys[off + 8 : off + entry]
        participants = sorted(pubs)
        pair_secrets = {
            p: dh_pair_secret(priv, pubs[p]) for p in participants if p != cid
        }
        t = sec.majority_threshold(len(participants))
        b_seed = os_mod.urandom(sec.SEED_LEN)
        xs = [sec.share_x(p) for p in participants]
        shares_b = shamir.split(b_seed, xs, t)
        shares_sk = shamir.split(sk_seed, xs, t)
        blobs = {
            p: sec.encrypt_share_blob(
                pair_secrets[p], session, round_no, cid, p,
                shares_b[sec.share_x(p)], shares_sk[sec.share_x(p)],
            )
            for p in participants
            if p != cid
        }
        framing.send_frame(
            sock,
            sec.build_shares_frame(
                cid,
                sec.b_seed_commitment(b_seed, session, round_no, cid),
                blobs,
                threshold=t,
                session=session,
                round_index=round_no,
            ),
        )
        shareset = framing.recv_frame(sock)
        if die_after == "shares":
            return
        u2, _ = sec.parse_shareset_frame(
            shareset, session=session, round_index=round_no
        )
        upload = sec.masked_upload(
            flatten_params(params),
            pair_secrets=pair_secrets,
            round_index=round_no,
            client_id=cid,
            participants=sorted(u2),
            session=session,
        )
        sec.apply_self_stream(
            upload, b_seed, session, round_no, cid, add=True
        )
        framing.send_frame(
            sock,
            wire.encode(
                upload,
                meta={
                    "client_id": cid,
                    "n_samples": 1,
                    "secure": True,
                    "fp_bits": sec.DEFAULT_FP_BITS,
                    "round": round_no,
                    "participants": len(u2),
                },
            ),
        )
        # die before answering the unmask request
    finally:
        sock.close()
        died.set()


def _run_double_round(C, dead, rng):
    """One double-mask round with the ``dead`` clients — a list of
    ``(cid, die_after)`` — scripted to drop at their phase; the rest are
    real FederatedClients. Returns (params, results dict)."""
    params = [_params(rng) for _ in range(C)]
    results = {}
    dead_ids = {cid for cid, _ in dead}
    events = {cid: threading.Event() for cid in dead_ids}
    with AggregationServer(
        port=0, num_clients=C, timeout=25, secure_agg=True, min_clients=2,
    ) as server:
        st = threading.Thread(
            target=lambda: results.__setitem__(
                # A dead-before-upload client makes the server wait the
                # full upload deadline before recovery — keep it short.
                "agg", server.serve_round(deadline=6)
            )
        )
        st.start()
        scripted = [
            threading.Thread(
                target=_double_scripted_client,
                args=(server.port, cid),
                kwargs={
                    "die_after": die_after,
                    "died": events[cid],
                    "params": params[cid],
                },
            )
            for cid, die_after in dead
        ]
        for t in scripted:
            t.start()

        def _go(cid):
            results[cid] = FederatedClient(
                "127.0.0.1",
                server.port,
                client_id=cid,
                timeout=25,
                secure_agg=True,
                num_clients=C,
                min_participants=2,
            ).exchange(params[cid])

        ts = [
            threading.Thread(target=_go, args=(c,))
            for c in range(C)
            if c not in dead_ids
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=40)
        st.join(timeout=40)
        for t in scripted:
            t.join(timeout=10)
    assert all(e.is_set() for e in events.values()) and "agg" in results, (
        sorted(results)
    )
    return params, results


def test_double_mask_dropout_after_shares(rng):
    """Double-masking dropout window 1: client 2 deals its shares then
    never uploads. Survivors' responses reconstruct the dead client's DH
    key seed (verified against its registered public key), its pair-mask
    residue comes off the ring sum, and the round completes with the
    survivors' exact mean."""
    C = 3
    params, results = _run_double_round(C, [(2, "shares")], rng)
    expected = aggregate_flat([flatten_params(p) for p in params[:2]])
    for key, arr in flatten_params(results[0]).items():
        np.testing.assert_allclose(
            arr, expected[key], atol=2.0 / (1 << DEFAULT_FP_BITS)
        )


def test_double_mask_dropout_during_unmask(rng):
    """VERDICT r4 #3 done-criterion: a client drops DURING the unmask
    (reveal) phase — client 2 uploads, then vanishes before answering the
    unmask request — and the round still completes, INCLUDING the dead
    client's contribution: the remaining holders meet the Shamir
    threshold for its self-mask seed. The reveal-round variant failed
    this outright (old comm/secure.py threat model)."""
    C = 3
    params, results = _run_double_round(C, [(2, "upload")], rng)
    expected = aggregate_flat([flatten_params(p) for p in params])
    for key, arr in flatten_params(results[0]).items():
        np.testing.assert_allclose(
            arr, expected[key], atol=2.0 / (1 << DEFAULT_FP_BITS)
        )


def test_unmask_request_overlap_and_partition_refused():
    """The either/or rule's teeth: a request naming one id both alive and
    dead (the both-kinds share harvest) is refused at parse, and an
    honest client also refuses a partition that does not cover U2
    exactly or claims the client itself did not contribute."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.secure import (
        build_unmask_request,
        parse_unmask_request,
    )

    kw = dict(session=b"s" * 16, round_index=1)
    with pytest.raises(SecureAggError, match="both alive and dead"):
        parse_unmask_request(build_unmask_request([0, 1], [1], **kw), **kw)
    client = FederatedClient(
        "h", 1, client_id=0, secure_agg=True, num_clients=3,
    )
    share_st = {"u2": [0, 1, 2], "holder_shares": {}, "own_b_share": b"x" * 32}
    with pytest.raises(SecureAggError, match="did not contribute"):
        client._answer_unmask(
            None, build_unmask_request([1, 2], [], **kw), share_st,
            b"s" * 16, 1,
        )
    with pytest.raises(SecureAggError, match="partition"):
        client._answer_unmask(
            None, build_unmask_request([0, 1], [], **kw), share_st,
            b"s" * 16, 1,
        )


def test_shamir_roundtrip_and_threshold():
    """Any t of n shares reconstruct; fewer yield garbage."""
    import itertools

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        shamir,
    )

    secret = bytes(range(32))
    shares = shamir.split(secret, [1, 2, 3, 4, 5], 3)
    for combo in itertools.combinations([1, 2, 3, 4, 5], 3):
        assert shamir.combine({x: shares[x] for x in combo}) == secret
    assert shamir.combine(shares) == secret  # all five: same polynomial
    assert shamir.combine({1: shares[1], 2: shares[2]}) != secret
    with pytest.raises(shamir.ShamirError):
        shamir.split(secret, [1, 1, 2], 2)  # duplicate x
    with pytest.raises(shamir.ShamirError):
        shamir.split(secret, [0, 1], 2)  # x=0 would leak the secret


def test_topk_client_refused_cleanly_by_secure_server(rng):
    """Contract pin (VERDICT r4 weak #5): sparse-delta (topk) uploads do
    not compose with secure aggregation — masked uploads are uniform
    ring elements with no sparsity. A topk client pointed at a secure
    server gets a clean, NON-RETRYABLE refusal naming the fix (one
    failed probe attempt, then the mode diagnosis — not a burned retry
    budget), and the plain client gets the same diagnosis."""
    unexpected: list = []

    def _serve_expect_failure(server):
        # Neither client ever completes an upload, so the round MUST fail;
        # swallow the expected quorum error — an unhandled exception here
        # would bleed a PytestUnhandledThreadExceptionWarning into
        # whatever test is running when the deadline fires.
        try:
            server.serve_round(deadline=15)
            unexpected.append("serve_round unexpectedly succeeded")
        except (RuntimeError, OSError):
            pass

    with AggregationServer(
        port=0, num_clients=2, timeout=20, secure_agg=True
    ) as server:
        st = threading.Thread(
            target=_serve_expect_failure, args=(server,), daemon=True
        )
        st.start()
        topk = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=10,
            compression="topk:0.05",
        )
        with pytest.raises(SecureAggError, match="drop topk"):
            topk.exchange(_params(rng), max_retries=5)
        plain = FederatedClient(
            "127.0.0.1", server.port, client_id=1, timeout=10
        )
        with pytest.raises(SecureAggError, match="--secure-agg"):
            plain.exchange(_params(rng), max_retries=5)
    # The context exit closed the listener; serve_round notices the dead
    # socket and exits promptly (comm/server.py) — join, don't leak.
    st.join(timeout=20)
    assert not st.is_alive(), "serve_round thread leaked past listener close"
    assert not unexpected


def _served_answer_unmask(client, request, share_st, session, round_no):
    """Run one _answer_unmask over a socketpair with a scripted server
    side (recv the response, send a dummy final reply) — the transport
    legs a successful answer needs."""
    import socket as socket_mod

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        framing,
    )

    a, b = socket_mod.socketpair()
    captured = {}

    def server_side():
        captured["response"] = bytes(framing.recv_frame(b))
        framing.send_frame(b, b"final-reply")

    t = threading.Thread(target=server_side, daemon=True)
    t.start()
    try:
        reply = client._answer_unmask(a, request, share_st, session, round_no)
    finally:
        t.join(timeout=10)
        a.close()
        b.close()
    return reply, captured.get("response")


def test_unmask_partition_pinned_across_retries():
    """Advisor-high comm/client.py: the answer-then-drop replay. A
    malicious server gets one (alive, dead) partition answered, drops the
    connection, and on the retry relays a DIFFERENT partition moving a
    victim from alive to dead — harvesting both its b-share and its
    key-seed share would unmask the victim's upload. The first answered
    partition is pinned per (session, round); the conflicting request
    must die with a non-retryable SecureAggError, while an identical
    re-request (an honest retry) still answers."""
    import os as os_mod

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.secure import (
        build_unmask_request,
    )

    session, round_no = b"s" * 16, 1
    kw = dict(session=session, round_index=round_no)
    client = FederatedClient(
        "h", 1, client_id=0, secure_agg=True, num_clients=3,
        min_participants=2,
    )
    share_st = {
        "u2": [0, 1, 2],
        "own_b_share": os_mod.urandom(32),
        "holder_shares": {
            1: (os_mod.urandom(32), os_mod.urandom(32)),
            2: (os_mod.urandom(32), os_mod.urandom(32)),
        },
    }
    first = build_unmask_request([0, 1, 2], [], **kw)
    reply, response = _served_answer_unmask(
        client, first, share_st, session, round_no
    )
    assert reply == b"final-reply" and response is not None
    assert share_st["unmask_partition"] == ((0, 1, 2), ())
    # Honest retry (identical partition): still answered.
    reply2, _ = _served_answer_unmask(
        client, first, share_st, session, round_no
    )
    assert reply2 == b"final-reply"
    # Malicious retry: client 2 moved alive -> dead. No socket I/O may
    # happen (the sk-share must never leave this process) — sock=None
    # proves the refusal fires before any send.
    moved = build_unmask_request([0, 1], [2], **kw)
    with pytest.raises(SecureAggError, match="partition changed"):
        client._answer_unmask(None, moved, share_st, session, round_no)
    # The pin survives the refused attempt unchanged.
    assert share_st["unmask_partition"] == ((0, 1, 2), ())


def test_shareset_u2_pinned_across_retries():
    """Advisor-medium comm/client.py: U2/holder shares are pinned across
    retries of one round like ``participants``. A retried connection
    whose relay presents a smaller (but floor-passing) share-complete
    set — the server steering the client between mask partitions to
    difference its uploads — must fail closed with SecureAggError."""
    import os as os_mod
    import socket as socket_mod

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        framing,
        shamir,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        secure as sec,
    )

    session, round_no = b"u" * 16, 2
    C = 3
    pairs, secrets = _fleet_keys(C)
    client = FederatedClient(
        "h", 1, client_id=0, secure_agg=True, num_clients=C,
        min_participants=2,
    )
    participants = [0, 1, 2]
    t = sec.majority_threshold(C)  # 2
    sk_seed = os_mod.urandom(sec.SEED_LEN)

    def dealer_entries(u2):
        """The relayed shareset entries: every OTHER dealer in u2 deals
        holder 0 a share blob (the test plays the dealers)."""
        entries = {}
        xs = [sec.share_x(p) for p in participants]
        for d in u2:
            if d == 0:
                continue
            shares_b = shamir.split(os_mod.urandom(sec.SEED_LEN), xs, t)
            shares_sk = shamir.split(os_mod.urandom(sec.SEED_LEN), xs, t)
            entries[d] = sec.encrypt_share_blob(
                secrets[d][0], session, round_no, d, 0,
                shares_b[sec.share_x(0)], shares_sk[sec.share_x(0)],
            )
        return entries

    def run_attempt(u2, entries):
        a, b = socket_mod.socketpair()
        errors = []

        def relay():
            try:
                framing.recv_frame(b)  # the client's shares frame
                framing.send_frame(
                    b,
                    sec.build_shareset_frame(
                        u2, entries, session=session, round_index=round_no
                    ),
                )
            except Exception as e:  # surfaced via the client-side raise
                errors.append(e)

        th = threading.Thread(target=relay, daemon=True)
        th.start()
        try:
            return client._double_share_exchange(
                a, participants, secrets[0], sk_seed, session, round_no
            )
        finally:
            th.join(timeout=10)
            a.close()
            b.close()

    st = run_attempt([0, 1, 2], dealer_entries([0, 1, 2]))
    assert st["u2"] == [0, 1, 2] and sorted(st["holder_shares"]) == [1, 2]
    pinned_shares = dict(st["holder_shares"])
    # Retry relays U2 = {0, 1}: len 2 passes the min_participants floor
    # AND the Shamir threshold — only the pin stops the partition switch.
    with pytest.raises(SecureAggError, match="share-complete set changed"):
        run_attempt([0, 1], dealer_entries([0, 1]))
    # Same U2 but re-dealt (different) shares is the same attack — and
    # the refusal must say WHICH dealers changed, not print two
    # identical U2 sets as "changed".
    with pytest.raises(SecureAggError, match="re-dealt different shares"):
        run_attempt([0, 1, 2], dealer_entries([0, 1, 2]))
    # The pinned state survives the refused retries unchanged.
    assert st["u2"] == [0, 1, 2] and st["holder_shares"] == pinned_shares


def test_secure_quorum_floor_survives_one_member_cohort():
    """Advisor-low comm/server.py: the Poisson-cohort clamp must not drag
    the secure-agg quorum below 2 — a 1-member cohort's "sum" is that
    client's raw update. quorum = max(2, min(min_clients, |cohort|))
    when secure aggregation is on; plain rounds keep the liveness
    clamp."""
    with AggregationServer(
        port=0, num_clients=3, min_clients=2, secure_agg=True, timeout=5
    ) as server:
        assert server._round_quorum(None) == 2
        assert server._round_quorum({0, 1, 2}) == 2
        assert server._round_quorum({1}) == 2  # the degenerate cohort
        assert server._round_quorum(set()) == 2  # (empty cohorts no-op earlier)
    with AggregationServer(
        port=0, num_clients=3, min_clients=1, timeout=5
    ) as server:  # no secure-agg: the cohort clamp is pure liveness
        assert server._round_quorum({1}) == 1
        assert server._round_quorum(None) == 1
    # Constructor guard unchanged: an explicit sub-2 floor under secure
    # aggregation is refused outright.
    with pytest.raises(ValueError, match="min_clients >= 2"):
        AggregationServer(port=0, num_clients=2, min_clients=1, secure_agg=True)


@pytest.mark.slow
def test_double_mask_combined_dropouts_at_threshold(rng):
    """Both recovery mechanisms in ONE round at the exact Shamir
    threshold: C=5 (t = majority = 3); client 4 deals shares then never
    uploads (pair-mask recovery), client 3 uploads then dies before the
    unmask round (self-mask recovery from the remaining holders), and
    exactly t=3 survivors answer. The round completes with the mean over
    the FOUR contributors — including the one that died during
    unmasking."""
    C = 5
    params, results = _run_double_round(
        C, [(4, "shares"), (3, "upload")], rng
    )
    # Contributors: 0, 1, 2 AND the unmask-phase casualty 3.
    expected = aggregate_flat([flatten_params(p) for p in params[:4]])
    for key, arr in flatten_params(results[0]).items():
        np.testing.assert_allclose(
            arr, expected[key], atol=2.0 / (1 << DEFAULT_FP_BITS)
        )
