"""Profiling/MFU accounting (SURVEY.md §5: the reference has no profiling
beyond timestamped prints; the build adds FLOPs/MFU accounting and
jax.profiler traces)."""

import os
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    ModelConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.utils.profiling import (
    device_peak_flops,
    forward_flops,
    mfu,
    trace,
    train_step_flops,
)


def test_train_step_is_3x_forward():
    cfg = ModelConfig.tiny()
    assert train_step_flops(cfg, 8) == pytest.approx(3 * forward_flops(cfg, 8))


def test_forward_flops_scaling():
    cfg = ModelConfig.tiny()
    # Linear in batch.
    assert forward_flops(cfg, 16) == pytest.approx(2 * forward_flops(cfg, 8))
    # Doubling layers doubles the encoder term.
    deep = cfg.replace(n_layers=4)
    head = 2 * cfg.dim * cfg.n_classes
    assert forward_flops(deep, 1) - head == pytest.approx(
        2 * (forward_flops(cfg, 1) - head)
    )


def test_forward_flops_matches_xla_cost_analysis():
    """The analytic count must track XLA's own cost model on the real
    forward. Analytic excludes elementwise work (softmax/LN/GELU), so XLA's
    number is an upper bound that should sit within ~2x on a
    matmul-dominated config."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.distilbert import (
        DDoSClassifier,
        init_params,
    )

    cfg = ModelConfig.tiny(dim=64, n_heads=4, hidden_dim=256, max_len=64,
                           max_position_embeddings=64)
    model = DDoSClassifier(cfg)
    params = init_params(model, cfg, jax.random.key(0))
    B = 4
    ids = jnp.zeros((B, cfg.max_len), jnp.int32)
    mask = jnp.ones((B, cfg.max_len), jnp.int32)

    def fwd(p):
        return model.apply({"params": p}, ids, mask, True)

    compiled = jax.jit(fwd).lower(params).compile()
    analysis = compiled.cost_analysis()
    analysis = analysis[0] if isinstance(analysis, list) else analysis
    xla_flops = float(analysis["flops"])
    ours = forward_flops(cfg, B)
    assert ours <= xla_flops * 1.05  # we must not overcount real matmul work
    assert xla_flops <= ours * 2.0, (xla_flops, ours)


def test_device_peak_flops_table():
    for kind, tflops in [
        ("TPU v2", 45.0),
        ("TPU v3", 123.0),
        ("TPU v4", 275.0),
        ("TPU v5e", 197.0),
        ("TPU v5 lite", 197.0),
        ("TPU v5p", 459.0),
        ("TPU v6e", 918.0),
        ("TPU v6 lite", 918.0),
    ]:
        dev = SimpleNamespace(device_kind=kind)
        assert device_peak_flops(dev) == pytest.approx(tflops * 1e12), kind
    assert device_peak_flops(SimpleNamespace(device_kind="cpu")) is None
    assert device_peak_flops(SimpleNamespace(device_kind="")) is None


def test_mfu_math():
    # 1e12 FLOPs/step at 0.01 s/step on a 275 TFLOP chip = ~36.4% MFU.
    assert mfu(1e12, 0.01, peak_flops_per_device=275e12) == pytest.approx(
        1e12 / (0.01 * 275e12)
    )
    # Two devices halve utilization for the same step time.
    assert mfu(1e12, 0.01, n_devices=2, peak_flops_per_device=275e12) == (
        pytest.approx(1e12 / (0.01 * 275e12) / 2)
    )
    assert mfu(1e12, 0.01, peak_flops_per_device=None) is None or isinstance(
        mfu(1e12, 0.01, peak_flops_per_device=None), float
    )


@pytest.mark.slow
def test_trace_noop_and_real(tmp_path):
    with trace(None):
        pass  # no-op path needs no profiler at all

    out = tmp_path / "prof"
    with trace(str(out)):
        jnp.dot(jnp.ones((8, 8)), jnp.ones((8, 8))).block_until_ready()
    files = [
        os.path.join(r, f) for r, _, fs in os.walk(out) for f in fs
    ]
    assert files, "jax.profiler.trace wrote nothing"
