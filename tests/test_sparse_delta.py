"""Sparse round-delta exchange (topk wire compression + error feedback).

The reference ships the full 245 MB state dict every round (reference
client1.py:285-286); bf16/int8 cut that 2-4x. The topk tier sends round
*deltas* keeping only the largest-magnitude fraction of entries (~100x at
the default 1%), with the dropped mass accumulated client-side so it is
carried into later rounds, not lost. Round 1 (and any retry or
server-restart recovery) is dense — always-correct fallback.
"""

import json
import struct
import threading

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
    FederatedClient,
    WireError,
    decode,
    encode,
    flatten_params,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    wire,
)


# ----------------------------------------------------------------- wire unit
def test_parse_compression():
    assert wire.parse_compression("topk") == ("topk", wire.DEFAULT_TOPK_FRAC)
    assert wire.parse_compression("topk:0.05") == ("topk", 0.05)
    assert wire.parse_compression("bf16") == ("bf16", None)
    for bad in ("topk:0", "topk:1.5", "topk:x", "topkx", "gzip"):
        with pytest.raises(WireError):
            wire.parse_compression(bad)


def test_sparsify_densify_exact_on_sparse_input(rng):
    """A tensor that is already k-sparse survives the round trip exactly."""
    a = np.zeros((16, 32), np.float32)
    idx = rng.choice(a.size, size=5, replace=False)
    a.reshape(-1)[idx] = rng.normal(size=5).astype(np.float32)
    out = wire.densify_topk(wire.sparsify_topk(a, 5 / a.size), a.shape)
    np.testing.assert_array_equal(out, a)


def test_sparsify_keeps_largest_magnitudes(rng):
    a = rng.normal(size=100).astype(np.float32)
    out = wire.densify_topk(wire.sparsify_topk(a, 0.1), a.shape)
    kept = np.nonzero(out)[0]
    assert len(kept) == 10
    # The kept set is exactly the 10 largest |values|.
    want = np.sort(np.argsort(np.abs(a))[-10:])
    np.testing.assert_array_equal(kept, want)
    np.testing.assert_array_equal(out[kept], a[kept])


def test_densify_rejects_corrupt_payloads():
    a = np.arange(8, dtype=np.float32)
    raw = wire.sparsify_topk(a, 0.5)
    with pytest.raises(WireError, match="count field"):
        wire.densify_topk(raw[:2], (8,))
    with pytest.raises(WireError, match="expected"):
        wire.densify_topk(raw + b"x", (8,))
    # Out-of-bounds index (attacker-controlled payload).
    bad = bytearray(raw)
    bad[4:8] = (99).to_bytes(4, "little")
    with pytest.raises(WireError, match="bounds"):
        wire.densify_topk(bytes(bad), (8,))


def test_densify_rejects_giant_claimed_shape():
    """A ~50-byte payload claiming a multi-TB dense shape must be rejected
    BEFORE any allocation — the shape is attacker-controlled and, unlike
    the dense encodings, not backed by payload bytes (memory-amplification
    DoS on the unauthenticated server)."""
    raw = (
        struct.pack("<I", 1)
        + np.int32(0).tobytes()
        + np.float32(1.0).tobytes()
    )
    with pytest.raises(WireError, match="dense size"):
        wire.densify_topk(raw, (1_000_000_000_000,))


def test_decode_rejects_summed_topk_claims():
    """Per-MESSAGE cap: many topk tensors each under the per-tensor cap
    but summing past it must be rejected before any allocation."""
    big = (wire.MAX_DENSE_TENSOR_BYTES // 4,)
    empty = struct.pack("<I", 0)  # k = 0: a few payload bytes per tensor
    msg = encode(
        {
            "a": wire.PreEncoded("topk", empty, big),
            "b": wire.PreEncoded("topk", empty, big),
        }
    )
    with pytest.raises(WireError, match="dense bytes"):
        decode(msg)


def test_decode_rejects_hostile_tensor_tables():
    """Attacker-controlled headers whose cap math would raise
    OverflowError (dim too large for int64) or AttributeError (tensor
    entry not a dict) must surface as WireError, not kill a server
    thread."""
    empty_crc = wire.native.crc32(np.frombuffer(b"", np.uint8))
    base = {"payload_nbytes": 0, "payload_crc32": empty_crc, "meta": {}}
    hostile_tables = [
        ["x"],  # not a dict
        [  # dim overflows int64 inside the summed-claim computation
            {
                "key": "w", "dtype": "float32", "shape": [10**30],
                "enc": "topk", "offset": 0, "nbytes": 0,
            }
        ],
    ]
    for tensors in hostile_tables:
        hb = json.dumps({**base, "tensors": tensors}).encode()
        msg = wire.MAGIC + struct.pack("<II", wire.VERSION, len(hb)) + hb
        with pytest.raises(WireError):
            decode(msg)


def test_probe_rediscovers_delta_capable_server(rng):
    """After giving up on sparse mode (pre-delta or lossy server), the
    client re-advertises wants_delta once every PROBE_EVERY rounds, and a
    probe reply with a matching crc re-arms sparse mode — no client
    restart needed when the server becomes lossless."""
    params = {"w": rng.normal(size=(6, 3)).astype(np.float32)}
    client = FederatedClient(
        "127.0.0.1", 1, client_id=0, compression="topk:0.5"
    )
    client._finish_topk({"w": params["w"]}, {"agg_round": 0}, None, None)
    assert client._gave_up_delta
    wants = []
    for _ in range(client.PROBE_EVERY + 1):
        meta: dict = {}
        client._prepare_topk_upload(params, 1, meta)
        wants.append(meta["wants_delta"])
    assert wants[: client.PROBE_EVERY - 1] == [False] * (client.PROBE_EVERY - 1)
    assert wants[client.PROBE_EVERY - 1] is True
    agg = {"w": params["w"]}
    client._finish_topk(
        agg,
        {"agg_round": 3, "agg_crc": wire.flat_crc32(flatten_params(agg))},
        None,
        None,
    )
    assert not client._gave_up_delta
    meta = {}
    client._prepare_topk_upload(params, 1, meta)
    assert meta["delta"] is True


def test_densify_rejects_k_exceeding_size():
    a = np.arange(8, dtype=np.float32)
    raw = wire.sparsify_topk(a, 1.0)  # k = 8
    with pytest.raises(WireError, match="exceeds"):
        wire.densify_topk(raw, (4,))


def test_encode_topk_payload_shrinks_and_decodes(rng):
    params = {"w": rng.normal(size=(100, 100)).astype(np.float32)}
    dense = encode(params, compression="none")
    sparse = encode(params, compression="topk:0.01")
    # u32 count + 100 * (int32 idx + fp32 val) vs 10000 * 4 bytes.
    assert len(sparse) < 0.05 * len(dense)
    out, _ = decode(sparse)
    kept = np.nonzero(out["w"].reshape(-1))[0]
    assert len(kept) == 100
    np.testing.assert_array_equal(
        out["w"].reshape(-1)[kept], params["w"].reshape(-1)[kept]
    )


# --------------------------------------------------------------- end to end
def _serve_rounds(server, n, results, key="aggs"):
    def _run():
        results[key] = [server.serve_round(deadline=30) for _ in range(n)]

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    return t


def test_single_client_sparse_rounds_track_target(rng):
    """One client 'trains' toward a fixed target across rounds (half the
    remaining gap per round), exchanging sparse deltas from round 2 on.
    The aggregate must keep approaching the target — dropped mass is
    carried by the error-feedback residual, not lost."""
    target = {"w": rng.normal(size=(40, 25)).astype(np.float32)}
    local = {"w": np.zeros_like(target["w"])}
    gaps = []
    with AggregationServer(port=0, num_clients=1, timeout=30) as server:
        client = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=30,
            compression="topk:0.1",
        )
        results = {}
        t = _serve_rounds(server, 5, results)
        for _ in range(5):
            local = {"w": local["w"] + 0.5 * (target["w"] - local["w"])}
            agg = client.exchange(local)
            local = {"w": np.asarray(agg["w"], np.float32)}
            gaps.append(float(np.abs(local["w"] - target["w"]).max()))
        t.join(timeout=30)
    # Round 1 is dense: gap halves exactly. Later rounds are 10%-sparse
    # deltas; the EF residual must keep the trajectory converging (the
    # trajectory is not strictly monotone — a coordinate whose residual
    # waited several rounds overshoots slightly when finally selected —
    # but net progress must continue well past the dense round).
    assert gaps[0] == pytest.approx(
        float(np.abs(target["w"]).max()) / 2, rel=1e-5
    )
    assert gaps[-1] < 0.45 * gaps[0], f"sparse rounds stalled: {gaps}"


def _both_exchange(clients, locals_):
    """Run both clients' exchange() concurrently (the server waits for the
    full fleet) and return their aggregates."""
    out = [None, None]
    errs = [None, None]

    def _one(c):
        try:
            out[c] = clients[c].exchange(locals_[c])
        except Exception as e:  # surfaced in the main thread
            errs[c] = e

    ths = [threading.Thread(target=_one, args=(c,)) for c in range(2)]
    for th in ths:
        th.start()
    for th in ths:
        th.join(timeout=90)
    for e in errs:
        if e is not None:
            raise e
    return out


def test_two_client_sparse_rounds_agree_and_mix_dense(rng):
    """2 clients, 3 rounds: round 1 dense, round 2 sparse for both. Before
    round 3, client 1 is replaced by a fresh instance (a mid-experiment
    join with no delta base), so round 3 genuinely mixes one sparse-delta
    and one dense upload in a single aggregation — the server's
    absolute-reconstruction branch with n_sparse < len(ids)."""
    p = [
        {"w": rng.normal(size=(30, 10)).astype(np.float32)},
        {"w": rng.normal(size=(30, 10)).astype(np.float32)},
    ]
    results = {}
    with AggregationServer(port=0, num_clients=2, timeout=30) as server:
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=c, timeout=30,
                compression="topk:0.2",
            )
            for c in range(2)
        ]
        t = _serve_rounds(server, 3, results)
        # Round 1: both dense (no base yet); exact mean.
        aggs1 = _both_exchange(clients, p)
        np.testing.assert_array_equal(aggs1[0]["w"], aggs1[1]["w"])
        np.testing.assert_allclose(
            aggs1[0]["w"], 0.5 * (p[0]["w"] + p[1]["w"]), rtol=1e-6
        )
        assert all(cl._base is not None for cl in clients)
        # Round 2: both sparse.
        locals2 = [
            {"w": np.asarray(aggs1[c]["w"], np.float32) * np.float32(1.01)}
            for c in range(2)
        ]
        aggs2 = _both_exchange(clients, locals2)
        np.testing.assert_array_equal(aggs2[0]["w"], aggs2[1]["w"])
        assert not np.allclose(aggs2[0]["w"], aggs1[0]["w"])
        # Fresh client 1: no base -> its round-3 upload is dense while
        # client 0's stays sparse.
        clients[1] = FederatedClient(
            "127.0.0.1", server.port, client_id=1, timeout=30,
            compression="topk:0.2",
        )
        locals3 = [
            {"w": np.asarray(aggs2[c]["w"], np.float32) * np.float32(1.01)}
            for c in range(2)
        ]
        base2 = np.asarray(clients[0]._base["w"])
        res2 = np.asarray(clients[0]._residual["w"]).copy()
        aggs3 = _both_exchange(clients, locals3)
        t.join(timeout=30)

    np.testing.assert_array_equal(aggs3[0]["w"], aggs3[1]["w"])
    # Mixed-round math: client 0's absolute = base + densify(topk(delta)),
    # client 1's = its dense params; the aggregate is their mean.
    delta0 = locals3[0]["w"] - base2 + res2
    sent0 = wire.densify_topk(wire.sparsify_topk(delta0, 0.2), delta0.shape)
    expected = 0.5 * ((base2 + sent0) + locals3[1]["w"])
    np.testing.assert_allclose(aggs3[0]["w"], expected, rtol=1e-5)


def test_server_restart_forces_dense_resend(rng):
    """A restarted server has no delta base: the sparse attempt is
    rejected, and the client's retry falls back to a dense upload that
    completes the round correctly."""
    params = {"w": rng.normal(size=(12, 4)).astype(np.float32)}
    with AggregationServer(port=0, num_clients=1, timeout=30) as server:
        client = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=30,
            compression="topk:0.25",
        )
        results = {}
        t = _serve_rounds(server, 1, results)
        client.exchange(params)  # round 1 dense; client now holds a base
        t.join(timeout=30)
    assert client._base is not None

    fresh = {"w": params["w"] * 2.0}
    with AggregationServer(port=0, num_clients=1, timeout=30) as server2:
        client.port = server2.port  # same client state, restarted server
        results = {}
        t = _serve_rounds(server2, 1, results)
        agg = client.exchange(fresh, max_retries=3)
        t.join(timeout=30)
    # The dense fallback carried the full weights despite the stale base.
    np.testing.assert_allclose(agg["w"], fresh["w"], rtol=1e-6)
    # And the client rebased onto the new server's round counter.
    assert client._base_round == 0


def test_topk_refuses_secure_agg():
    with pytest.raises(ValueError, match="secure"):
        FederatedClient(
            "127.0.0.1", 1, client_id=0, compression="topk",
            secure_agg=True, num_clients=2,
        )
    with pytest.raises(ValueError, match="upload-side"):
        AggregationServer(port=0, num_clients=1, compression="topk")


def test_residual_carries_dropped_mass(rng):
    """Unit-level EF check: what round r drops, round r+1's intended delta
    still contains (via the residual), so no coordinate's drift is ever
    permanently discarded. Follows the exchange() contract: the client
    adopts the returned aggregate before its next upload."""
    base = {"w": np.zeros(10, np.float32)}
    client = FederatedClient(
        "127.0.0.1", 1, client_id=0, compression="topk:0.1"
    )
    client._base = dict(flatten_params(base))
    client._base_round = 0
    local = {"w": np.asarray([5, 4, 3, 2, 1, 0, 0, 0, 0, 0], np.float32)}
    meta: dict = {}
    upload, comp, delta, sent = client._prepare_topk_upload(local, 1, meta)
    assert meta["delta"] is True and meta["base_agg_round"] == 0
    assert all(isinstance(v, wire.PreEncoded) for v in upload.values())
    # k=1 keeps only the 5.0 coordinate.
    np.testing.assert_array_equal(
        sent["w"], [5, 0, 0, 0, 0, 0, 0, 0, 0, 0]
    )
    # Simulate the server reply (aggregate = base + sent for one client,
    # stamped with the exact-base crc contract).
    agg = {"w": sent["w"]}
    client._finish_topk(
        agg, {"agg_round": 1, "agg_crc": wire.flat_crc32(flatten_params(agg))},
        delta, sent,
    )
    np.testing.assert_array_equal(
        client._residual["w"], [0, 4, 3, 2, 1, 0, 0, 0, 0, 0]
    )
    np.testing.assert_array_equal(client._base["w"], sent["w"])
    # Contract: the client adopts the aggregate. With no further local
    # movement, the next intended delta is exactly the carried residual,
    # and 4 — dropped last round — is now the top coordinate.
    adopted = {"w": np.asarray(client._base["w"])}
    meta2: dict = {}
    _, _, delta2, sent2 = client._prepare_topk_upload(adopted, 1, meta2)
    np.testing.assert_array_equal(
        delta2["w"], [0, 4, 3, 2, 1, 0, 0, 0, 0, 0]
    )
    np.testing.assert_array_equal(
        sent2["w"], [0, 4, 0, 0, 0, 0, 0, 0, 0, 0]
    )


def test_residual_survives_dense_fallback(rng):
    """A round that goes dense (retry fallback, fresh base) must NOT
    discard error-feedback mass accumulated over prior sparse rounds —
    the residual holds drift from earlier local training that was dropped
    by top-k and then discarded when the client adopted the aggregate."""
    client = FederatedClient(
        "127.0.0.1", 1, client_id=0, compression="topk:0.1"
    )
    client._base = {"w": np.zeros(10, np.float32)}
    client._base_round = 0
    carried = np.asarray([0, 4, 3, 2, 1, 0, 0, 0, 0, 0], np.float32)
    client._residual = {"w": carried.copy()}
    # A dense round completes (delta_flat/sent_flat are None).
    agg = {"w": np.ones(10, np.float32)}
    client._finish_topk(
        agg,
        {"agg_round": 1, "agg_crc": wire.flat_crc32(flatten_params(agg))},
        None,
        None,
    )
    np.testing.assert_array_equal(client._residual["w"], carried)
    # The next sparse delta still carries it: local == base, so the
    # intended delta is exactly the retained residual.
    meta: dict = {}
    _, _, delta, sent = client._prepare_topk_upload(
        {"w": np.asarray(client._base["w"]).copy()}, 1, meta
    )
    assert meta["delta"] is True
    np.testing.assert_array_equal(delta["w"], carried)
    np.testing.assert_array_equal(
        sent["w"], [0, 4, 0, 0, 0, 0, 0, 0, 0, 0]
    )
    # But a residual that no longer matches the architecture is dropped.
    client._residual = {"stale": carried.copy()}
    _, _, delta2, _ = client._prepare_topk_upload(
        {"w": np.asarray(client._base["w"]).copy()}, 1, {}
    )
    np.testing.assert_array_equal(delta2["w"], np.zeros(10, np.float32))
    assert client._residual is None


def test_reply_omits_agg_crc_without_delta_clients(rng):
    """agg_crc is a full fp32 pass over the model; a round with no
    delta-capable client must not pay it (and plain clients don't need
    it). A topk client's first — dense — upload advertises wants_delta,
    which is covered by the e2e tests adopting a base."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        client as client_mod,
        framing,
    )

    params = {"w": rng.normal(size=(8, 4)).astype(np.float32)}
    with AggregationServer(port=0, num_clients=1, timeout=30) as server:
        results = {}
        t = _serve_rounds(server, 1, results)
        sock = client_mod.connect_with_retry(
            "127.0.0.1", server.port, timeout=30
        )
        try:
            sock.settimeout(30)
            framing.send_frame(
                sock, encode(params, meta={"client_id": 0, "n_samples": 1})
            )
            _, meta = decode(framing.recv_frame(sock))
        finally:
            sock.close()
        t.join(timeout=30)
    assert "agg_crc" not in meta
    assert meta["agg_round"] == 0


def test_lossy_reply_compression_keeps_clients_dense(rng):
    """serve --compression int8 (lossy reply): the decoded aggregate can't
    match the server's exact fp32 base, so topk clients must refuse to
    rebase (staying dense) instead of silently reconstructing against a
    base the server doesn't hold."""
    params = {"w": rng.normal(size=(16, 8)).astype(np.float32)}
    with AggregationServer(
        port=0, num_clients=1, timeout=30, compression="int8"
    ) as server:
        client = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=30,
            compression="topk:0.25",
        )
        results = {}
        t = _serve_rounds(server, 2, results)
        client.exchange(params)
        assert client._base is None  # refused the quantized base
        agg2 = client.exchange(params)  # round 2 went dense again
        t.join(timeout=30)
    np.testing.assert_allclose(
        agg2["w"], params["w"], rtol=5e-2, atol=1e-1
    )
