"""Sparse round-delta exchange (topk wire compression + error feedback).

The reference ships the full 245 MB state dict every round (reference
client1.py:285-286); bf16/int8 cut that 2-4x. The topk tier sends round
*deltas* keeping only the largest-magnitude fraction of entries (~100x at
the default 1%), with the dropped mass accumulated client-side so it is
carried into later rounds, not lost. Round 1 (and any retry or
server-restart recovery) is dense — always-correct fallback.
"""

import threading

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
    FederatedClient,
    WireError,
    decode,
    encode,
    flatten_params,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    wire,
)


# ----------------------------------------------------------------- wire unit
def test_parse_compression():
    assert wire.parse_compression("topk") == ("topk", wire.DEFAULT_TOPK_FRAC)
    assert wire.parse_compression("topk:0.05") == ("topk", 0.05)
    assert wire.parse_compression("bf16") == ("bf16", None)
    for bad in ("topk:0", "topk:1.5", "topk:x", "topkx", "gzip"):
        with pytest.raises(WireError):
            wire.parse_compression(bad)


def test_sparsify_densify_exact_on_sparse_input(rng):
    """A tensor that is already k-sparse survives the round trip exactly."""
    a = np.zeros((16, 32), np.float32)
    idx = rng.choice(a.size, size=5, replace=False)
    a.reshape(-1)[idx] = rng.normal(size=5).astype(np.float32)
    out = wire.densify_topk(wire.sparsify_topk(a, 5 / a.size), a.shape)
    np.testing.assert_array_equal(out, a)


def test_sparsify_keeps_largest_magnitudes(rng):
    a = rng.normal(size=100).astype(np.float32)
    out = wire.densify_topk(wire.sparsify_topk(a, 0.1), a.shape)
    kept = np.nonzero(out)[0]
    assert len(kept) == 10
    # The kept set is exactly the 10 largest |values|.
    want = np.sort(np.argsort(np.abs(a))[-10:])
    np.testing.assert_array_equal(kept, want)
    np.testing.assert_array_equal(out[kept], a[kept])


def test_densify_rejects_corrupt_payloads():
    a = np.arange(8, dtype=np.float32)
    raw = wire.sparsify_topk(a, 0.5)
    with pytest.raises(WireError, match="count field"):
        wire.densify_topk(raw[:2], (8,))
    with pytest.raises(WireError, match="expected"):
        wire.densify_topk(raw + b"x", (8,))
    # Out-of-bounds index (attacker-controlled payload).
    bad = bytearray(raw)
    bad[4:8] = (99).to_bytes(4, "little")
    with pytest.raises(WireError, match="bounds"):
        wire.densify_topk(bytes(bad), (8,))


def test_encode_topk_payload_shrinks_and_decodes(rng):
    params = {"w": rng.normal(size=(100, 100)).astype(np.float32)}
    dense = encode(params, compression="none")
    sparse = encode(params, compression="topk:0.01")
    # u32 count + 100 * (int32 idx + fp32 val) vs 10000 * 4 bytes.
    assert len(sparse) < 0.05 * len(dense)
    out, _ = decode(sparse)
    kept = np.nonzero(out["w"].reshape(-1))[0]
    assert len(kept) == 100
    np.testing.assert_array_equal(
        out["w"].reshape(-1)[kept], params["w"].reshape(-1)[kept]
    )


# --------------------------------------------------------------- end to end
def _serve_rounds(server, n, results, key="aggs"):
    def _run():
        results[key] = [server.serve_round(deadline=30) for _ in range(n)]

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    return t


def test_single_client_sparse_rounds_track_target(rng):
    """One client 'trains' toward a fixed target across rounds (half the
    remaining gap per round), exchanging sparse deltas from round 2 on.
    The aggregate must keep approaching the target — dropped mass is
    carried by the error-feedback residual, not lost."""
    target = {"w": rng.normal(size=(40, 25)).astype(np.float32)}
    local = {"w": np.zeros_like(target["w"])}
    gaps = []
    with AggregationServer(port=0, num_clients=1, timeout=30) as server:
        client = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=30,
            compression="topk:0.1",
        )
        results = {}
        t = _serve_rounds(server, 5, results)
        for _ in range(5):
            local = {"w": local["w"] + 0.5 * (target["w"] - local["w"])}
            agg = client.exchange(local)
            local = {"w": np.asarray(agg["w"], np.float32)}
            gaps.append(float(np.abs(local["w"] - target["w"]).max()))
        t.join(timeout=30)
    # Round 1 is dense: gap halves exactly. Later rounds are 10%-sparse
    # deltas; the EF residual must keep the trajectory converging (the
    # trajectory is not strictly monotone — a coordinate whose residual
    # waited several rounds overshoots slightly when finally selected —
    # but net progress must continue well past the dense round).
    assert gaps[0] == pytest.approx(
        float(np.abs(target["w"]).max()) / 2, rel=1e-5
    )
    assert gaps[-1] < 0.45 * gaps[0], f"sparse rounds stalled: {gaps}"


def test_two_client_sparse_rounds_agree_and_mix_dense(rng):
    """2 clients, 3 rounds: round 1 dense, then sparse deltas. Both receive
    identical aggregates every round; a mid-experiment fresh client (no
    base) mixes its dense upload into a sparse round."""
    p = [
        {"w": rng.normal(size=(30, 10)).astype(np.float32)},
        {"w": rng.normal(size=(30, 10)).astype(np.float32)},
    ]
    results = {}
    with AggregationServer(port=0, num_clients=2, timeout=30) as server:
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=c, timeout=30,
                compression="topk:0.2",
            )
            for c in range(2)
        ]
        t = _serve_rounds(server, 3, results)

        def _rounds(c):
            out = []
            local = p[c]
            for _ in range(3):
                agg = _sync_exchange(clients[c], local)
                local = {"w": np.asarray(agg["w"], np.float32) * 1.01}
                out.append(agg)
            results[c] = out

        barrier = threading.Barrier(2)

        def _sync_exchange(cl, params):
            barrier.wait(timeout=30)
            return cl.exchange(params)

        ths = [threading.Thread(target=_rounds, args=(c,)) for c in range(2)]
        for th in ths:
            th.start()
        for th in ths:
            th.join(timeout=90)
        t.join(timeout=30)

    assert 0 in results and 1 in results
    for r in range(3):
        np.testing.assert_array_equal(results[0][r]["w"], results[1][r]["w"])
    # Round 1 is the exact dense mean.
    np.testing.assert_allclose(
        results[0][0]["w"], 0.5 * (p[0]["w"] + p[1]["w"]), rtol=1e-6
    )
    # Sparse rounds moved the aggregate (deltas were nonzero).
    assert not np.allclose(results[0][1]["w"], results[0][0]["w"])


def test_server_restart_forces_dense_resend(rng):
    """A restarted server has no delta base: the sparse attempt is
    rejected, and the client's retry falls back to a dense upload that
    completes the round correctly."""
    params = {"w": rng.normal(size=(12, 4)).astype(np.float32)}
    with AggregationServer(port=0, num_clients=1, timeout=30) as server:
        client = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=30,
            compression="topk:0.25",
        )
        results = {}
        t = _serve_rounds(server, 1, results)
        client.exchange(params)  # round 1 dense; client now holds a base
        t.join(timeout=30)
    assert client._base is not None

    fresh = {"w": params["w"] * 2.0}
    with AggregationServer(port=0, num_clients=1, timeout=30) as server2:
        client.port = server2.port  # same client state, restarted server
        results = {}
        t = _serve_rounds(server2, 1, results)
        agg = client.exchange(fresh, max_retries=3)
        t.join(timeout=30)
    # The dense fallback carried the full weights despite the stale base.
    np.testing.assert_allclose(agg["w"], fresh["w"], rtol=1e-6)
    # And the client rebased onto the new server's round counter.
    assert client._base_round == 0


def test_topk_refuses_secure_agg():
    with pytest.raises(ValueError, match="secure"):
        FederatedClient(
            "127.0.0.1", 1, client_id=0, compression="topk",
            secure_agg=True, num_clients=2,
        )
    with pytest.raises(ValueError, match="upload-side"):
        AggregationServer(port=0, num_clients=1, compression="topk")


def test_residual_carries_dropped_mass(rng):
    """Unit-level EF check: what round r drops, round r+1's intended delta
    still contains (via the residual), so no coordinate's drift is ever
    permanently discarded. Follows the exchange() contract: the client
    adopts the returned aggregate before its next upload."""
    base = {"w": np.zeros(10, np.float32)}
    client = FederatedClient(
        "127.0.0.1", 1, client_id=0, compression="topk:0.1"
    )
    client._base = dict(flatten_params(base))
    client._base_round = 0
    local = {"w": np.asarray([5, 4, 3, 2, 1, 0, 0, 0, 0, 0], np.float32)}
    meta: dict = {}
    upload, comp, delta, sent = client._prepare_topk_upload(local, 1, meta)
    assert meta["delta"] is True and meta["base_agg_round"] == 0
    assert all(isinstance(v, wire.PreEncoded) for v in upload.values())
    # k=1 keeps only the 5.0 coordinate.
    np.testing.assert_array_equal(
        sent["w"], [5, 0, 0, 0, 0, 0, 0, 0, 0, 0]
    )
    # Simulate the server reply (aggregate = base + sent for one client,
    # stamped with the exact-base crc contract).
    agg = {"w": sent["w"]}
    client._finish_topk(
        agg, {"agg_round": 1, "agg_crc": wire.flat_crc32(flatten_params(agg))},
        delta, sent,
    )
    np.testing.assert_array_equal(
        client._residual["w"], [0, 4, 3, 2, 1, 0, 0, 0, 0, 0]
    )
    np.testing.assert_array_equal(client._base["w"], sent["w"])
    # Contract: the client adopts the aggregate. With no further local
    # movement, the next intended delta is exactly the carried residual,
    # and 4 — dropped last round — is now the top coordinate.
    adopted = {"w": np.asarray(client._base["w"])}
    meta2: dict = {}
    _, _, delta2, sent2 = client._prepare_topk_upload(adopted, 1, meta2)
    np.testing.assert_array_equal(
        delta2["w"], [0, 4, 3, 2, 1, 0, 0, 0, 0, 0]
    )
    np.testing.assert_array_equal(
        sent2["w"], [0, 4, 0, 0, 0, 0, 0, 0, 0, 0]
    )


def test_lossy_reply_compression_keeps_clients_dense(rng):
    """serve --compression int8 (lossy reply): the decoded aggregate can't
    match the server's exact fp32 base, so topk clients must refuse to
    rebase (staying dense) instead of silently reconstructing against a
    base the server doesn't hold."""
    params = {"w": rng.normal(size=(16, 8)).astype(np.float32)}
    with AggregationServer(
        port=0, num_clients=1, timeout=30, compression="int8"
    ) as server:
        client = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=30,
            compression="topk:0.25",
        )
        results = {}
        t = _serve_rounds(server, 2, results)
        client.exchange(params)
        assert client._base is None  # refused the quantized base
        agg2 = client.exchange(params)  # round 2 went dense again
        t.join(timeout=30)
    np.testing.assert_allclose(
        agg2["w"], params["w"], rtol=5e-2, atol=1e-1
    )
