"""Knowledge distillation (teacher -> student). The reference only consumes
a pre-distilled DistilBERT (client1.py:56); producing one is new capability.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
    DistillConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    default_tokenizer,
    make_client_splits,
    make_synthetic_flows,
    tokenize_client,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.distill import (
    DistillTrainer,
    distillation_loss,
    init_student_from_teacher,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
    Trainer,
)

MAX_LEN = 64


def test_distillation_loss_alpha_zero_is_plain_ce(rng):
    s = jnp.asarray(rng.standard_normal((8, 2)), jnp.float32)
    t = jnp.asarray(rng.standard_normal((8, 2)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, 8), jnp.int32)
    got = distillation_loss(s, t, y, temperature=3.0, alpha=0.0)
    want = optax.softmax_cross_entropy_with_integer_labels(s, y).mean()
    np.testing.assert_allclose(float(got), float(want), rtol=1e-6)


def test_distillation_loss_zero_kl_when_matching(rng):
    s = jnp.asarray(rng.standard_normal((8, 2)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, 8), jnp.int32)
    got = distillation_loss(s, s, y, temperature=2.0, alpha=1.0)
    np.testing.assert_allclose(float(got), 0.0, atol=1e-6)


def test_distillation_loss_gradient_pulls_toward_teacher():
    """With alpha=1, the KD gradient moves student logits toward the
    teacher's distribution."""
    t = jnp.array([[4.0, 0.0]])
    y = jnp.array([0], jnp.int32)

    def f(s):
        return distillation_loss(s, t, y, temperature=1.0, alpha=1.0)

    g = jax.grad(f)(jnp.array([[0.0, 4.0]]))
    # Student puts too little mass on class 0: gradient must be negative on
    # logit 0 (increase it) and positive on logit 1.
    assert float(g[0, 0]) < 0 < float(g[0, 1])


def test_config_validation():
    with pytest.raises(ValueError, match="alpha"):
        DistillConfig(alpha=1.5)
    with pytest.raises(ValueError, match="temperature"):
        DistillConfig(temperature=0.0)


def _cfg_pair(tok):
    student = ModelConfig.tiny(
        vocab_size=len(tok), max_len=MAX_LEN, max_position_embeddings=MAX_LEN,
        dim=64, n_layers=2, n_heads=4, hidden_dim=128,
    )
    teacher = student.replace(n_layers=4)
    return student, teacher


def test_init_student_from_teacher_layer_mapping(rng):
    tok = default_tokenizer()
    student_cfg, teacher_cfg = _cfg_pair(tok)
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.distilbert import (
        DDoSClassifier,
        init_params,
    )

    t_params = init_params(DDoSClassifier(teacher_cfg), teacher_cfg, jax.random.key(0))
    s_params = init_params(DDoSClassifier(student_cfg), student_cfg, jax.random.key(1))
    out = init_student_from_teacher(s_params, t_params, stride=2)

    # layer_i <- teacher layer_{2i}; embeddings + head copied.
    for i in range(2):
        np.testing.assert_array_equal(
            np.asarray(out["encoder"][f"layer_{i}"]["lin1"]["kernel"]),
            np.asarray(t_params["encoder"][f"layer_{2 * i}"]["lin1"]["kernel"]),
        )
    np.testing.assert_array_equal(
        np.asarray(out["encoder"]["embeddings"]["word_embeddings"]["embedding"]),
        np.asarray(t_params["encoder"]["embeddings"]["word_embeddings"]["embedding"]),
    )
    np.testing.assert_array_equal(
        np.asarray(out["classifier"]["kernel"]),
        np.asarray(t_params["classifier"]["kernel"]),
    )

    # Out-of-range stride raises.
    with pytest.raises(ValueError, match="stride"):
        init_student_from_teacher(s_params, t_params, stride=4)


def test_width_mismatch_rejected(rng):
    tok = default_tokenizer()
    student_cfg, _ = _cfg_pair(tok)
    fat_teacher = student_cfg.replace(dim=128, n_layers=4)
    with pytest.raises(ValueError, match="dim"):
        DistillTrainer(
            student_cfg, fat_teacher, TrainConfig(), DistillConfig()
        )


@pytest.mark.slow
def test_distill_end_to_end_student_learns(rng):
    """Teacher trains on synthetic flows; the distilled student matches its
    accuracy at half depth."""
    tok = default_tokenizer()
    student_cfg, teacher_cfg = _cfg_pair(tok)
    df = make_synthetic_flows(1200, seed=11)
    data_cfg = DataConfig(data_fraction=0.6, max_len=MAX_LEN)
    client = tokenize_client(
        make_client_splits(df, 0, 1, data_cfg), tok, max_len=MAX_LEN
    )
    tcfg = TrainConfig(learning_rate=1e-3, epochs_per_round=2, seed=0)

    teacher = Trainer(teacher_cfg, tcfg)
    t_state = teacher.init_state()
    t_state, _ = teacher.fit(t_state, client.train, batch_size=16)
    t_metrics = teacher.evaluate(t_state.params, client.test)
    assert t_metrics["Accuracy"] > 90.0

    # Teacher-initialized student: starts near-converged (KD loss small),
    # stays accurate after distillation.
    d = DistillTrainer(
        student_cfg, teacher_cfg, tcfg, DistillConfig(alpha=0.5, temperature=2.0)
    )
    s_state = d.init_student_state(t_state.params)
    s_state, kd_losses = d.distill(
        s_state, t_state.params, client.train, batch_size=16, epochs=2
    )
    assert kd_losses[0] < 0.2, "teacher init should start near the teacher"
    s_metrics = d.evaluate(s_state.params, client.test)
    assert s_metrics["Accuracy"] > 90.0, s_metrics

    # From-scratch student: KD loss must actually decrease across epochs.
    d2 = DistillTrainer(
        student_cfg, teacher_cfg, tcfg,
        DistillConfig(alpha=0.5, temperature=2.0, init_from_teacher=False),
    )
    s2 = d2.init_student_state(t_state.params)
    s2, kd2 = d2.distill(s2, t_state.params, client.train, batch_size=16, epochs=2)
    assert kd2[-1] < kd2[0]
    assert d2.evaluate(s2.params, client.test)["Accuracy"] > 90.0


@pytest.mark.slow
def test_distill_from_federated_checkpoint(tmp_path):
    """The end-to-end 'distilled LLMs in distributed networks' pipeline:
    federate a model, then distill its aggregate into a student via
    --teacher-checkpoint, then deploy the student with predict."""
    import os

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        main,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        write_synthetic_csv,
    )

    fed_ckpt = str(tmp_path / "fed")
    assert (
        main(
            [
                "federated", "--synthetic", "400", "--num-clients", "2",
                "--rounds", "1", "--epochs", "1", "--batch-size", "16",
                "--checkpoint-dir", fed_ckpt,
                "--output-dir", str(tmp_path / "fedout"),
            ]
        )
        == 0
    )
    student_ckpt = str(tmp_path / "student")
    out = str(tmp_path / "distout")
    assert (
        main(
            [
                "distill", "--synthetic", "400", "--epochs", "1",
                "--batch-size", "16",
                "--teacher-checkpoint", fed_ckpt,
                "--checkpoint-dir", student_ckpt,
                "--output-dir", out,
            ]
        )
        == 0
    )
    assert os.path.exists(os.path.join(out, "teacher_metrics.csv"))
    assert os.path.exists(os.path.join(out, "student_metrics.csv"))

    csv = str(tmp_path / "flows.csv")
    write_synthetic_csv(csv, n_rows=40, seed=9)
    preds = str(tmp_path / "p.csv")
    assert (
        main(
            ["predict", "--csv", csv, "--checkpoint-dir", student_ckpt,
             "--output", preds]
        )
        == 0
    )
    assert os.path.exists(preds)


@pytest.mark.slow
def test_distill_from_local_checkpoint_same_arch(tmp_path):
    """Local-teacher path: the checkpoint's recorded config (tiny, 2
    layers) must override the 2x-deep default teacher hint — the restore
    template is rebuilt from it rather than failing a shape mismatch."""
    import os

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        main,
    )

    teacher_ckpt = str(tmp_path / "teacher")
    assert (
        main(
            [
                "local", "--synthetic", "300", "--epochs", "1",
                "--batch-size", "16", "--checkpoint-dir", teacher_ckpt,
                "--output-dir", str(tmp_path / "t"),
            ]
        )
        == 0
    )
    out = str(tmp_path / "dist")
    assert (
        main(
            [
                "distill", "--synthetic", "300", "--epochs", "1",
                "--batch-size", "16",
                "--teacher-checkpoint", teacher_ckpt,
                "--output-dir", out,
            ]
        )
        == 0
    )
    assert os.path.exists(os.path.join(out, "student_metrics.csv"))
