"""Attention impl parity: flash (Pallas, interpret on CPU) and ring
(shard_map sequence parallelism) must match the XLA dot-attention path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    ModelConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.distilbert import (
    DDoSClassifier,
    init_params,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.ops.attention import (
    dot_product_attention,
    make_attention_bias,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.ops.flash_attention import (
    flash_attention,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.ring_attention import (
    ring_attention_sharded,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.mesh import shard_map



def _qkv(rng, b=2, h=2, l=64, d=16, dtype=jnp.float32):
    shape = (b, h, l, d)
    q = jnp.asarray(rng.normal(size=shape), dtype)
    k = jnp.asarray(rng.normal(size=shape), dtype)
    v = jnp.asarray(rng.normal(size=shape), dtype)
    return q, k, v


def _mask_bias(rng, b=2, l=64):
    mask = (rng.random((b, l)) > 0.2).astype(np.int32)
    mask[:, 0] = 1  # CLS always visible
    return make_attention_bias(jnp.asarray(mask))


def test_flash_matches_dot_forward(rng):
    q, k, v = _qkv(rng)
    bias = _mask_bias(rng)
    ref = dot_product_attention(q, k, v, bias)
    out = flash_attention(q, k, v, bias, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_matches_dot_no_bias(rng):
    q, k, v = _qkv(rng, l=32)
    ref = dot_product_attention(q, k, v, None)
    out = flash_attention(q, k, v, None, block_q=8, block_k=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_gradients_match_dot(rng):
    q, k, v = _qkv(rng, b=1, h=2, l=32, d=8)
    bias = _mask_bias(rng, b=1, l=32)

    def loss_dot(q, k, v):
        return (dot_product_attention(q, k, v, bias) ** 2).sum()

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, bias, block_q=8, block_k=8) ** 2).sum()

    g_ref = jax.grad(loss_dot, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_rejects_full_bias(rng):
    q, k, v = _qkv(rng, l=16)
    full_bias = jnp.zeros((2, 2, 16, 16))
    with pytest.raises(ValueError, match="key-position bias"):
        flash_attention(q, k, v, full_bias, block_q=8, block_k=8)


def test_flash_in_model_forward(rng):
    """attention_impl='flash' through the full classifier equals 'dot'."""
    base = ModelConfig.tiny(attention_dropout=0.0)
    flash_cfg = base.replace(attention_impl="flash")
    model_dot = DDoSClassifier(base)
    model_flash = DDoSClassifier(flash_cfg)
    params = init_params(model_dot, base, jax.random.key(0))
    ids = jnp.asarray(rng.integers(0, base.vocab_size, (2, base.max_len)), jnp.int32)
    mask = jnp.ones((2, base.max_len), jnp.int32)
    out_dot = model_dot.apply({"params": params}, ids, mask, True)
    out_flash = model_flash.apply({"params": params}, ids, mask, True)
    np.testing.assert_allclose(
        np.asarray(out_flash), np.asarray(out_dot), atol=2e-4
    )


def test_ring_matches_dot(rng, eight_devices):
    from jax.sharding import Mesh

    mesh = Mesh(np.array(eight_devices[:2]), ("seq",))
    q, k, v = _qkv(rng, b=1, h=2, l=32, d=8)
    bias = _mask_bias(rng, b=1, l=32)
    ref = dot_product_attention(q, k, v, bias)
    out = ring_attention_sharded(q, k, v, bias, mesh=mesh, axis_name="seq")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_blockwise_local_matches_dot_and_ring(rng, eight_devices):
    """blockwise_attention_local (the BENCH_MODE=ring kernel: ring
    schedule minus transport) matches the dot path and the real sharded
    ring bit-for-bit-close on the same inputs."""
    from jax.sharding import Mesh

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.ring_attention import (
        blockwise_attention_local,
    )

    q, k, v = _qkv(rng, b=1, h=2, l=32, d=8)
    bias = _mask_bias(rng, b=1, l=32)
    ref = dot_product_attention(q, k, v, bias)
    out = blockwise_attention_local(q, k, v, bias, n_chunks=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    mesh = Mesh(np.array(eight_devices[:4]), ("seq",))
    ring = ring_attention_sharded(q, k, v, bias, mesh=mesh, axis_name="seq")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ring), atol=2e-6)
    # No-bias path too.
    out_nb = blockwise_attention_local(q, k, v, n_chunks=8)
    np.testing.assert_allclose(
        np.asarray(out_nb),
        np.asarray(dot_product_attention(q, k, v, None)),
        atol=2e-5,
    )


def test_ring_no_bias_matches_dot(rng, eight_devices):
    from jax.sharding import Mesh

    mesh = Mesh(np.array(eight_devices[:2]), ("seq",))
    q, k, v = _qkv(rng, b=1, h=1, l=16, d=8)
    ref = dot_product_attention(q, k, v, None)
    out = ring_attention_sharded(q, k, v, mesh=mesh, axis_name="seq")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gradients_match_dot(rng, eight_devices):
    from jax.sharding import Mesh

    mesh = Mesh(np.array(eight_devices[:2]), ("seq",))
    q, k, v = _qkv(rng, b=1, h=1, l=16, d=8)
    bias = _mask_bias(rng, b=1, l=16)

    def loss_dot(q, k, v):
        return (dot_product_attention(q, k, v, bias) ** 2).sum()

    def loss_ring(q, k, v):
        return (
            ring_attention_sharded(q, k, v, bias, mesh=mesh, axis_name="seq") ** 2
        ).sum()

    g_ref = jax.grad(loss_dot, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.slow
def test_ring_model_forward_matches_dot(rng, eight_devices):
    """Full classifier under a sequence-sharded shard_map (ring attention,
    shard-offset positions, global CLS pooling) equals the unsharded dot
    path."""
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(eight_devices[:2]), ("seq",))
    base = ModelConfig.tiny(
        attention_dropout=0.0, max_len=64, max_position_embeddings=64
    )
    ring_cfg = base.replace(attention_impl="ring", ring_axis="seq")
    model_dot = DDoSClassifier(base)
    model_ring = DDoSClassifier(ring_cfg)
    params = init_params(model_dot, base, jax.random.key(0))
    ids = jnp.asarray(rng.integers(0, base.vocab_size, (2, 64)), jnp.int32)
    mask_np = (rng.random((2, 64)) > 0.3).astype(np.int32)
    mask_np[:, 0] = 1
    mask = jnp.asarray(mask_np)

    ref = model_dot.apply({"params": params}, ids, mask, True)
    out = shard_map(
        lambda p, i, m: model_ring.apply({"params": p}, i, m, True),
        mesh=mesh,
        in_specs=(P(), P(None, "seq"), P(None, "seq")),
        out_specs=P(),
    )(params, ids, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


@pytest.mark.slow
def test_ring_sequence_parallel_training_matches_dot(rng, eight_devices):
    """Long-context TRAINING parity: gradients of the full classifier under
    sequence-sharded ring attention (shard_map, K/V ppermute ring) equal the
    unsharded dot path, and a short Adam loop actually learns through it."""
    import optax
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(eight_devices[:2]), ("seq",))
    # Only attention_dropout=0.0 is required (ring impl validation); the
    # other dropouts are inert under deterministic=True.
    base = ModelConfig.tiny(
        attention_dropout=0.0, max_len=64, max_position_embeddings=64
    )
    ring_cfg = base.replace(attention_impl="ring", ring_axis="seq")
    model_dot = DDoSClassifier(base)
    model_ring = DDoSClassifier(ring_cfg)
    params = init_params(model_dot, base, jax.random.key(0))
    B = 4
    ids = jnp.asarray(rng.integers(0, base.vocab_size, (B, 64)), jnp.int32)
    # Random padding mask: the grad path through make_attention_bias and
    # the shard-offset handling must be part of the parity check.
    mask_np = (rng.random((B, 64)) > 0.3).astype(np.int32)
    mask_np[:, 0] = 1
    mask = jnp.asarray(mask_np)
    labels = jnp.asarray(rng.integers(0, 2, B), jnp.int32)

    fwd_ring = shard_map(
        lambda p, i, m: model_ring.apply({"params": p}, i, m, True),
        mesh=mesh,
        in_specs=(P(), P(None, "seq"), P(None, "seq")),
        out_specs=P(),
    )

    def loss_dot(p):
        lg = model_dot.apply({"params": p}, ids, mask, True)
        return optax.softmax_cross_entropy_with_integer_labels(lg, labels).mean()

    def loss_ring(p):
        return optax.softmax_cross_entropy_with_integer_labels(
            fwd_ring(p, ids, mask), labels
        ).mean()

    g_dot = jax.grad(loss_dot)(params)
    g_ring = jax.grad(loss_ring)(params)
    for a, b in zip(jax.tree.leaves(g_dot), jax.tree.leaves(g_ring)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)

    # A few Adam steps through the sequence-parallel path must reduce loss.
    opt = optax.adam(1e-3)
    ost = opt.init(params)

    @jax.jit
    def step(p, o):
        l, g = jax.value_and_grad(loss_ring)(p)
        u, o = opt.update(g, o, p)
        return optax.apply_updates(p, u), o, l

    losses = []
    p = params
    for _ in range(5):
        p, ost, l = step(p, ost)
        losses.append(float(l))
    assert losses[-1] < losses[0], losses


def test_ring_rejects_query_bias(rng, eight_devices):
    from jax.sharding import Mesh

    mesh = Mesh(np.array(eight_devices[:2]), ("seq",))
    q, k, v = _qkv(rng, b=1, h=1, l=16, d=8)
    causal = jnp.zeros((1, 1, 16, 16))
    with pytest.raises(ValueError, match="key-position bias"):
        ring_attention_sharded(q, k, v, causal, mesh=mesh, axis_name="seq")


def test_ring_config_initializes_and_runs_outside_shard_map(rng):
    """attention_impl='ring' must work through the normal Trainer path:
    init_params and unsharded eval trace outside shard_map and fall back to
    the identical unsharded math."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        TrainConfig,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
        Trainer,
    )

    cfg = ModelConfig.tiny(attention_impl="ring", attention_dropout=0.0)
    trainer = Trainer(cfg, TrainConfig())
    state = trainer.init_state(seed=0)  # would raise NameError before the fix
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, cfg.max_len)), jnp.int32)
    mask = jnp.ones((2, cfg.max_len), jnp.int32)
    ref = DDoSClassifier(cfg.replace(attention_impl="dot", attention_dropout=0.0)).apply(
        {"params": state.params}, ids, mask, True
    )
    out = trainer.model.apply({"params": state.params}, ids, mask, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_dropout_matches_unsharded_and_is_invariant(eight_devices):
    """Ring attention dropout (global-coordinate hash masks): the sampled
    output is identical at any seq shard count, deterministic per key, and
    different keys give different masks. (The former ring+dropout config
    rejection is obsolete — every impl supports attention dropout now.)"""
    import numpy as np
    from jax.sharding import Mesh

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.ring_attention import (
        ring_attention_sharded,
    )

    r = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(r.normal(size=(1, 2, 16, 8)).astype(np.float32))
        for _ in range(3)
    )
    key = jax.random.key(2)

    def run(n, key=key):
        mesh = Mesh(np.array(jax.devices()[:n]).reshape(n), ("seq",))
        return np.asarray(
            ring_attention_sharded(
                q, k, v, mesh=mesh,
                dropout_rate=0.3, dropout_rng=key, deterministic=False,
            )
        )

    o1, o2, o4 = run(1), run(2), run(4)
    np.testing.assert_allclose(o2, o1, atol=1e-5)
    np.testing.assert_allclose(o4, o1, atol=1e-5)
    np.testing.assert_array_equal(run(2), run(2))  # deterministic per key
    assert not np.allclose(o1, run(2, jax.random.key(3)))  # key matters
    # Clean (no-dropout) output differs from the dropped one.
    clean = np.asarray(
        ring_attention_sharded(
            q, k, v,
            mesh=Mesh(np.array(jax.devices()[:2]).reshape(2), ("seq",)),
        )
    )
    assert not np.allclose(clean, o1, atol=1e-5)


def test_flash_handles_non_multiple_block_lengths():
    """L=384 doesn't tile into the default 256/512 blocks — the kernel must
    snap to a divisor (gcd -> 128) instead of erroring, and still match the
    dot path."""
    import numpy as np

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.ops.attention import (
        dot_product_attention,
        make_attention_bias,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.ops.flash_attention import (
        flash_attention,
    )

    rng = np.random.default_rng(0)
    B, H, L, D = 2, 2, 384, 16
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, H, L, D)).astype(np.float32))
        for _ in range(3)
    )
    mask = np.ones((B, L), np.int32)
    mask[1, 300:] = 0
    bias = make_attention_bias(jnp.asarray(mask))
    out = flash_attention(q, k, v, bias)
    ref = dot_product_attention(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_degenerate_length_falls_back_to_dot(rng):
    """Prime / odd lengths whose gcd with the default blocks is degenerate
    must take the XLA dot path (block-1 Pallas grids are pathological),
    still matching dot numerics exactly."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.ops.flash_attention import (
        DEFAULT_BLOCK_K,
        DEFAULT_BLOCK_Q,
        fits_blocks,
    )

    assert fits_blocks(64, 64, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)  # <= block
    assert fits_blocks(2048, 2048, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    assert not fits_blocks(1031, 1031, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)  # prime
    assert not fits_blocks(768, 1031, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    # 768 = 256*3: q fits; k gcd(768, 512)=256 >= 128: fits.
    assert fits_blocks(768, 768, DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)

    q, k, v = _qkv(rng, l=521)  # prime length > default blocks
    bias = _mask_bias(rng, l=521)
    ref = dot_product_attention(q, k, v, bias)
    out = flash_attention(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)
    # And gradients flow through the fallback.
    g = jax.grad(lambda q: flash_attention(q, k, v, bias).sum())(q)
    gref = jax.grad(lambda q: dot_product_attention(q, k, v, bias).sum())(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref), atol=1e-5)


@pytest.mark.slow
def test_flash_dropout_deterministic_and_unbiased(rng):
    """Flash attention dropout: same rng -> same output; different rng ->
    different mask; averaging over many seeds recovers the no-dropout
    output (inverted-dropout unbiasedness) and the keep rate matches."""
    q, k, v = _qkv(rng, b=1, h=2, l=32, d=8)
    bias = _mask_bias(rng, b=1, l=32)
    base = flash_attention(q, k, v, bias, block_q=16, block_k=16)
    key = jax.random.key(0)

    def drop(key):
        return flash_attention(
            q, k, v, bias, dropout_rate=0.4, dropout_rng=key,
            deterministic=False, block_q=16, block_k=16,
        )

    out1, out2 = drop(key), drop(key)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert not np.allclose(np.asarray(out1), np.asarray(drop(jax.random.key(1))))
    assert not np.allclose(np.asarray(out1), np.asarray(base))
    # E[dropout(w)] = w: the seed-average converges to the clean output.
    # 64 seeds put ~sqrt(p/(1-p))/8 ~ 0.1 of per-element noise on the mean,
    # so bound the max loosely and the average error tightly.
    outs = np.stack(
        [np.asarray(drop(jax.random.key(s))) for s in range(64)]
    )
    err = np.abs(outs.mean(0) - np.asarray(base))
    # Rows whose softmax concentrates on one key carry per-seed noise of
    # the full |v| scale, so bound the bulk, not the max.
    assert err.mean() < 0.05, err.mean()
    assert np.quantile(err, 0.9) < 0.2, np.quantile(err, 0.9)
    # And the mask itself keeps at the configured rate.
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.ops.flash_attention import (
        _keep_mask,
    )

    keeps = np.mean(
        [
            np.asarray(
                _keep_mask(
                    jax.random.bits(jax.random.key(s), (2,), jnp.uint32),
                    jnp.int32(0), jnp.int32(1), 0, 0, 32, 32, 0.4,
                )
            ).mean()
            for s in range(16)
        ]
    )
    np.testing.assert_allclose(keeps, 0.6, atol=0.03)
    # deterministic=True ignores the rate entirely.
    out_det = flash_attention(
        q, k, v, bias, dropout_rate=0.4, dropout_rng=key,
        deterministic=True, block_q=16, block_k=16,
    )
    np.testing.assert_allclose(np.asarray(out_det), np.asarray(base), atol=1e-6)


@pytest.mark.slow
def test_flash_dropout_gradients_check(rng):
    """The Pallas backward regenerates the identical dropout mask from the
    (seed, position) hash: reverse-mode grads must match finite differences
    (the mask is locally constant, so f is differentiable at the check
    point)."""
    from jax.test_util import check_grads

    q, k, v = _qkv(rng, b=1, h=1, l=16, d=8)
    bias = _mask_bias(rng, b=1, l=16)
    key = jax.random.key(3)

    def f(q, k, v, bias):
        return flash_attention(
            q, k, v, bias, dropout_rate=0.3, dropout_rng=key,
            deterministic=False, block_q=8, block_k=8,
        ).sum()

    # Fast-lane determinism: same key -> identical value; different key ->
    # different mask (the 64-seed unbiasedness statistics run in the slow
    # lane).
    assert float(f(q, k, v, bias)) == float(f(q, k, v, bias))
    alt = flash_attention(
        q, k, v, bias, dropout_rate=0.3, dropout_rng=jax.random.key(4),
        deterministic=False, block_q=8, block_k=8,
    ).sum()
    assert float(f(q, k, v, bias)) != float(alt)
    check_grads(f, (q, k, v, bias), order=1, modes=["rev"], atol=2e-2, rtol=2e-2)


def test_flash_pallas_backward_matches_dot_large_blocks(rng):
    """Grad parity on a multi-block case (several q and k blocks per head),
    including the key-bias gradient."""
    q, k, v = _qkv(rng, b=2, h=2, l=64, d=16)
    bias = _mask_bias(rng, b=2, l=64)

    def loss(fn):
        def inner(q, k, v, bias):
            return (fn(q, k, v, bias) * 0.37).sum()
        return inner

    flash_fn = loss(lambda *a: flash_attention(*a, block_q=16, block_k=16))
    dot_fn = loss(dot_product_attention)
    g_flash = jax.grad(flash_fn, argnums=(0, 1, 2, 3))(q, k, v, bias)
    g_dot = jax.grad(dot_fn, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for a, b in zip(g_flash, g_dot):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
