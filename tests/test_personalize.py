"""Personalization: FedAvg + local fine-tuning (scope 'full' = FedAvg+FT,
scope 'head' = FedPer) — a third evaluation phase beyond the reference's
local/aggregated pair; each client adapts the aggregate to its own shard.

Fast lane: the engine-level frozen-encoder proof + the CLI e2e third-phase
artifact run. Slow lane: the trainer-level conflicting-clients win,
bit-frozen encoder, and scope override-direction proofs.
"""

import os

import numpy as np
import pytest

import jax

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
    TokenizedSplit,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.federated import (
    FederatedTrainer,
)

ML = 16


def _cfg(**fed_kw):
    return ExperimentConfig(
        model=ModelConfig.tiny(max_len=ML, max_position_embeddings=ML),
        data=DataConfig(max_len=ML, batch_size=8, eval_batch_size=8),
        train=TrainConfig(learning_rate=1e-3, epochs_per_round=1, seed=0),
        fed=FedConfig(num_clients=2, **fed_kw),
        mesh=MeshConfig(clients=2, data=1),
    )


def _clientwise_data(seed=0, n=48):
    """Two clients with OPPOSITE label rules for the same token pattern —
    the aggregate cannot satisfy both, so personalization must help."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(1, 200, (2, n, ML)).astype(np.int32)
    mask = np.ones((2, n, ML), np.int32)
    feature = ids[:, :, 1] % 2  # a trivially learnable per-row bit
    labels = np.stack([feature[0], 1 - feature[1]]).astype(np.int32)
    return TokenizedSplit(ids, mask, labels)


@pytest.mark.slow
def test_personalize_full_beats_aggregate_on_conflicting_clients(eight_devices):
    train = _clientwise_data()
    cfg = _cfg(personalize_epochs=3, personalize_scope="full")
    trainer = FederatedTrainer(cfg)
    state = trainer.init_state(seed=0)
    state, _ = trainer.fit_local(state, train, epochs=3)
    state = trainer.aggregate(state)

    prepared = trainer.prepare_eval(
        [
            TokenizedSplit(train.input_ids[c], train.attention_mask[c], train.labels[c])
            for c in range(2)
        ]
    )
    agg_m = trainer.evaluate_clients(state.params, prepared=prepared)

    pstate, losses = trainer.personalize(state, train)
    pers_m = trainer.evaluate_clients(pstate.params, prepared=prepared)
    assert losses.shape[-1] == 2
    # Conflicting label rules: the shared aggregate can't fit both clients;
    # per-client fine-tuning must (weakly) improve each one and give a
    # clear net win.
    for c in range(2):
        assert pers_m[c]["Accuracy"] >= agg_m[c]["Accuracy"] - 1.0
    assert sum(pers_m[c]["Accuracy"] for c in range(2)) > sum(
        agg_m[c]["Accuracy"] for c in range(2)
    )
    # Personalized replicas DIVERGE (no closing aggregate).
    leaf = np.asarray(jax.tree.leaves(pstate.params)[0])
    assert not np.allclose(leaf[0], leaf[1])


@pytest.mark.slow
def test_personalize_head_freezes_encoder(eight_devices):
    train = _clientwise_data(seed=1)
    cfg = _cfg(personalize_epochs=2, personalize_scope="head")
    trainer = FederatedTrainer(cfg)
    state = trainer.init_state(seed=0)
    state, _ = trainer.fit_local(state, train, epochs=1)
    state = trainer.aggregate(state)

    pstate, _ = trainer.personalize(state, train)
    # FedPer: the shared encoder is bit-frozen; only the head moved.
    for a, b in zip(
        jax.tree.leaves(state.params["encoder"]),
        jax.tree.leaves(pstate.params["encoder"]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    moved = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(state.params["classifier"]),
            jax.tree.leaves(pstate.params["classifier"]),
        )
    )
    assert moved


@pytest.mark.slow
def test_personalize_full_overrides_head_base_config(eight_devices):
    """The scope override works in BOTH directions: scope='full' on a
    linear-probing base config (trainable='head') must unfreeze the
    encoder."""
    import dataclasses

    train = _clientwise_data(seed=2, n=16)
    cfg = _cfg(personalize_epochs=1, personalize_scope="full")
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, trainable="head")
    )
    trainer = FederatedTrainer(cfg)
    state = trainer.init_state(seed=0)
    pstate, _ = trainer.personalize(state, train)
    moved = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(state.params["encoder"]),
            jax.tree.leaves(pstate.params["encoder"]),
        )
    )
    assert moved, "scope='full' left the encoder frozen"


def test_trainable_head_engine_scope():
    """TrainConfig.trainable='head' works standalone in the single-client
    engine (linear probing)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
        Trainer,
    )

    cfg = ModelConfig.tiny(max_len=ML, max_position_embeddings=ML)
    rng = np.random.default_rng(0)
    split = TokenizedSplit(
        rng.integers(1, 200, (24, ML)).astype(np.int32),
        np.ones((24, ML), np.int32),
        rng.integers(0, 2, 24).astype(np.int32),
    )
    tr = Trainer(cfg, TrainConfig(learning_rate=1e-3, trainable="head", epochs_per_round=1))
    st = tr.init_state(seed=0)
    before = jax.tree.map(np.asarray, st.params)
    st, _ = tr.fit(st, split, batch_size=8)
    for a, b in zip(
        jax.tree.leaves(before["encoder"]), jax.tree.leaves(st.params["encoder"])
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not all(
        np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(before["classifier"]),
            jax.tree.leaves(st.params["classifier"]),
        )
    )
    with pytest.raises(ValueError, match="trainable"):
        TrainConfig(trainable="encoder")


@pytest.mark.slow
def test_cli_personalize_writes_third_metrics_csv(tmp_path, eight_devices):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        main,
    )

    out = tmp_path / "out"
    rc = main(
        [
            "federated", "--synthetic", "300", "--num-clients", "2",
            "--rounds", "1", "--epochs", "1", "--batch-size", "8",
            "--personalize-epochs", "1", "--personalize-scope", "head",
            "--output-dir", str(out),
        ]
    )
    assert rc == 0
    for c in range(2):
        assert (out / f"client{c}_local_metrics.csv").exists()
        assert (out / f"client{c}_aggregated_metrics.csv").exists()
        assert (out / f"client{c}_personalized_metrics.csv").exists()
