"""Wire efficiency (ISSUE 17): quantized streamed uploads, sparse relay
upward deltas, and the batched fold engines.

Contracts pinned here:

* The int8c codec round-trips within its per-chunk quantization step,
  handles denormal/inf/NaN chunks deterministically, and rejects
  malformed or poisoned payloads (non-finite scales) as WireError.
* Every fold engine (naive, blocked) is bit-exact against the reference
  ascending-id accumulation ``acc += float32(w_i) * leaf_i`` over
  shuffled arrival orders — the crc contract the streaming aggregator's
  batched fold must keep.
* A LIVE mixed fleet (int8 + bf16 + old-peer fp32 clients in one round)
  negotiates per-client upgrades one reply behind and the server's fold
  is crc-equal to the deterministic dequantization replay.
* ``--wire-dtype`` refuses the combinations that cannot keep their
  contracts (secure-agg, compressed uploads) and stays fp32 against a
  non-advertising server.
* Quantized uploads compose with central DP: the server holds lossy
  streamed leaves until the trailer, dequantizes, and RE-CLIPS before
  the fold (containment), bit-equal to the numpy replay.
* A relay with ``upward_topk`` goes dense on round 1, adopts the root
  aggregate as its delta base, and uploads sparse topk deltas upward
  from round 2 — with the root's aggregate bit-equal to the replay and
  the upward bytes collapsing.
* Server-side strategy optimizer state survives a restart via
  ``strategy_state_path``: the restarted root continues the momentum
  trajectory instead of re-adopting the mean.
"""

import threading

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
    FederatedClient,
    RelayAggregator,
    StreamAgg,
    WireError,
    aggregate_flat,
    wire,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.quant import (
    QUANT_CHUNK_ELEMS,
    dequantize_int8c,
    int8c_nbytes,
    quantize_int8c,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.ops import (
    fold,
)

pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnraisableExceptionWarning"
)


def _leaves(rng, n=4, shape=(32, 9), scale=1.0):
    return {
        f"w{i:02d}": rng.normal(size=shape).astype(np.float32) * scale
        for i in range(n)
    }


def _serve_rounds(server, n, results, key="aggs"):
    def _run():
        results[key] = [server.serve_round(deadline=30) for _ in range(n)]

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    return t


def _run_clients(clients, uploads, n_samples=None):
    results, errors = {}, []

    def go(cid):
        try:
            kw = {}
            if n_samples is not None:
                kw["n_samples"] = n_samples[cid]
            results[cid] = clients[cid].exchange(uploads[cid], **kw)
        except Exception as e:  # noqa: BLE001 - surfaced via the list
            errors.append((cid, e))

    threads = [
        threading.Thread(target=go, args=(cid,), daemon=True)
        for cid in clients
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    return results, errors


def _rt_int8(flat):
    """The deterministic server-side view of an int8-quantized upload."""
    return {
        k: dequantize_int8c(quantize_int8c(v), np.asarray(v).shape)
        for k, v in flat.items()
    }


def _rt_bf16(flat):
    return {
        k: wire.native.unpack_bf16(
            np.ascontiguousarray(wire.native.pack_bf16(v)),
            shape=np.asarray(v).shape,
        )
        for k, v in flat.items()
    }


# ------------------------------------------------------------ int8c codec
def test_int8c_roundtrip_within_quant_step(rng):
    for size in (1, 7, QUANT_CHUNK_ELEMS, QUANT_CHUNK_ELEMS + 1, 3 * 4096 + 5):
        arr = (rng.normal(size=size) * 3.0).astype(np.float32)
        raw = quantize_int8c(arr)
        assert len(raw) == int8c_nbytes(size)
        out = dequantize_int8c(raw, arr.shape)
        # Per chunk the max error is half the quantization step
        # (scale = amax/127; rint rounds to the nearest level).
        nchunks = -(-size // QUANT_CHUNK_ELEMS)
        pad = nchunks * QUANT_CHUNK_ELEMS - size
        a2 = np.pad(arr, (0, pad)).reshape(nchunks, QUANT_CHUNK_ELEMS)
        step = np.abs(a2).max(axis=1) / 127.0
        err = np.abs(
            np.pad(out - arr, (0, pad)).reshape(nchunks, QUANT_CHUNK_ELEMS)
        ).max(axis=1)
        assert np.all(err <= step / 2 + 1e-7)


def test_int8c_deterministic_and_shape_preserving(rng):
    arr = rng.normal(size=(33, 129)).astype(np.float32)
    raw1, raw2 = quantize_int8c(arr), quantize_int8c(arr)
    assert raw1 == raw2
    out1 = dequantize_int8c(raw1, arr.shape)
    out2 = dequantize_int8c(raw2, arr.shape)
    assert out1.shape == arr.shape
    np.testing.assert_array_equal(out1, out2)


def test_int8c_edge_chunks_stay_finite():
    # All-zero chunk: scale falls back to 1.0, decodes to exact zeros.
    zeros = np.zeros(10, np.float32)
    np.testing.assert_array_equal(
        dequantize_int8c(quantize_int8c(zeros), zeros.shape), zeros
    )
    # Denormal-only chunk: amax/127 underflows toward 0 — the fallback
    # keeps both directions finite (values quantize to 0 at scale 1.0).
    den = np.full(5, np.float32(1e-42))
    out = dequantize_int8c(quantize_int8c(den), den.shape)
    assert np.all(np.isfinite(out))
    # inf/NaN chunk: scale is non-finite -> fallback 1.0; NaN -> 0,
    # +/-inf saturate to +/-127. Deterministic, never NaN out.
    ugly = np.array([np.inf, -np.inf, np.nan, 2.5, -300.0], np.float32)
    out = dequantize_int8c(quantize_int8c(ugly), ugly.shape)
    np.testing.assert_array_equal(
        out, np.array([127.0, -127.0, 0.0, 2.0, -127.0], np.float32)
    )


def test_int8c_rejects_malformed_payloads(rng):
    arr = rng.normal(size=100).astype(np.float32)
    raw = quantize_int8c(arr)
    with pytest.raises(WireError, match="bytes"):
        dequantize_int8c(raw + b"x", arr.shape)
    with pytest.raises(WireError, match="bytes"):
        dequantize_int8c(raw[:-1], arr.shape)
    # Poisoned scale (NaN / negative): one crafted upload must not be
    # able to feed non-finite values into the round's running fold.
    for bad in (np.float32(np.nan), np.float32(-1.0), np.float32(0.0)):
        poisoned = bad.tobytes() + raw[4:]
        with pytest.raises(WireError, match="scale"):
            dequantize_int8c(poisoned, arr.shape)


# ------------------------------------------------------------ fold engines
def test_fold_engines_bit_exact_property(rng):
    """naive / blocked / fold_ordered agree BIT-exactly with the
    reference ascending accumulation — across sizes straddling the cache
    block, ill-conditioned scales, and shuffled upload arrival orders
    (arrival never changes fold order; StreamAgg sorts by id)."""
    for _ in range(6):
        k = int(rng.integers(1, 9))
        n = int(rng.integers(1, 3 * fold.FOLD_BLOCK_ELEMS))
        shape = (n,) if n % 2 else (2, n // 2)
        leaves = [
            (rng.normal(size=shape) * 10.0 ** rng.integers(-4, 5)).astype(
                np.float32
            )
            for _ in range(k)
        ]
        weights = [np.float32(w) for w in rng.random(k) + 0.05]
        ref = np.zeros(shape, np.float32)
        for w, a in zip(weights, leaves):
            ref += np.float32(w) * a
        flat = [a.reshape(-1) for a in leaves]
        np.testing.assert_array_equal(fold.fold_naive(flat, weights).reshape(shape), ref)
        np.testing.assert_array_equal(
            fold.fold_blocked(flat, weights).reshape(shape), ref
        )
        # Odd block size: partial tail blocks must not change any bit.
        np.testing.assert_array_equal(
            fold.fold_blocked(flat, weights, block=1000).reshape(shape), ref
        )
        np.testing.assert_array_equal(
            fold.fold_ordered(leaves, weights, engine="blocked"), ref
        )
        np.testing.assert_array_equal(
            fold.fold_ordered(leaves, weights, engine="naive"), ref
        )


def test_streamagg_batched_fold_one_crc_over_arrival_orders(rng):
    """The StreamAgg fold (now batched through fold_ordered) still yields
    ONE crc over shuffled arrival orders, equal to the barrier mean."""
    n = 8
    keys = [f"w{i}" for i in range(3)]
    models = [
        {k: rng.normal(size=(64, 33)).astype(np.float32) for k in keys}
        for _ in range(n)
    ]
    weights = [float(w) for w in rng.integers(1, 9, size=n)]

    def crc(order):
        st = StreamAgg()
        for cid in order:
            st.register(cid, keys=keys, n_samples=weights[cid])
        st.freeze(list(range(n)), weights)
        for cid in order:
            st.add_dense(cid, models[cid])
        return wire.flat_crc32(st.finalize(list(range(n)), weights))

    orders = [list(range(n))]
    for _ in range(3):
        o = list(range(n))
        rng.shuffle(o)
        orders.append(o)
    crcs = {crc(o) for o in orders}
    assert len(crcs) == 1
    want = aggregate_flat(models, weights)
    assert crcs == {wire.flat_crc32(want)}


def test_fold_engine_env_override(monkeypatch):
    monkeypatch.setenv("FEDTPU_FOLD_ENGINE", "gpu")
    with pytest.raises(ValueError, match="FEDTPU_FOLD_ENGINE"):
        fold._pick_engine()
    monkeypatch.setenv("FEDTPU_FOLD_ENGINE", "naive")
    assert fold._pick_engine() == "naive"
    monkeypatch.delenv("FEDTPU_FOLD_ENGINE")
    assert fold._pick_engine() in ("blocked", "pallas")


# ------------------------------------------------- wire-dtype negotiation
def test_wire_dtype_refusal_matrix():
    # Lossy dtypes refuse secure-agg (masked ring elements cannot be
    # re-quantized) and any compressed upload (one encoding per wire).
    with pytest.raises(ValueError, match="secure"):
        FederatedClient(
            "127.0.0.1", 1, client_id=0, wire_dtype="int8",
            secure_agg=True, num_clients=2,
        )
    for comp in ("topk:0.1", "bf16", "int8"):
        with pytest.raises(ValueError, match="compression"):
            FederatedClient(
                "127.0.0.1", 1, client_id=0, wire_dtype="bf16",
                compression=comp,
            )
    with pytest.raises(ValueError, match="wire_dtype"):
        FederatedClient("127.0.0.1", 1, client_id=0, wire_dtype="fp16")
    # fp32 (the default) composes with everything — no constructor error.
    FederatedClient(
        "127.0.0.1", 1, client_id=0, wire_dtype="fp32",
        compression="topk:0.1",
    )


def test_wire_dtype_stays_fp32_against_old_server(rng):
    """A non-streaming server never adverts decodable encodings: the
    int8 client keeps the fp32 wire and the aggregate is exact."""
    models = [_leaves(rng, n=2)]
    results = {}
    with AggregationServer(
        port=0, num_clients=1, timeout=30, stream_chunk_bytes=0
    ) as server:
        client = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=30,
            wire_dtype="int8",
        )
        t = _serve_rounds(server, 2, results)
        for _ in range(2):
            agg = client.exchange(models[0])
            assert client.last_wire_dtype == "fp32"
            assert wire.flat_crc32(agg) == wire.flat_crc32(
                aggregate_flat(models)
            )
        t.join(timeout=30)


def test_mixed_fleet_quantized_round_crc_pinned(rng):
    """int8 + bf16 + old-peer fp32 clients in one live streamed fleet:
    round 1 is all-fp32 (negotiation is one reply behind), round 2 the
    capable clients upgrade, and the server's fold is crc-equal to the
    deterministic dequantization replay — ``fleet_crc_exact`` extends to
    quantized rounds."""
    models1 = [_leaves(rng, n=3, shape=(40, 30)) for _ in range(3)]
    models2 = [_leaves(rng, n=3, shape=(40, 30)) for _ in range(3)]
    results = {}
    with AggregationServer(
        port=0, num_clients=3, timeout=30, stream_chunk_bytes=1 << 10
    ) as server:
        clients = {
            0: FederatedClient(
                "127.0.0.1", server.port, client_id=0, timeout=30,
                wire_dtype="int8",
            ),
            1: FederatedClient(
                "127.0.0.1", server.port, client_id=1, timeout=30,
            ),
            2: FederatedClient(
                "127.0.0.1", server.port, client_id=2, timeout=30,
                wire_dtype="bf16",
            ),
        }
        t = _serve_rounds(server, 2, results)
        r1, errors = _run_clients(clients, models1)
        assert not errors, errors
        # Round 1: nobody had the advert yet — all fp32, exact mean.
        assert {c.last_wire_dtype for c in clients.values()} == {"fp32"}
        want1 = aggregate_flat(models1)
        for cid in clients:
            assert wire.flat_crc32(r1[cid]) == wire.flat_crc32(want1)
        fp32_bytes = clients[0].last_upload_bytes
        r2, errors = _run_clients(clients, models2)
        t.join(timeout=60)
        assert not errors, errors
        assert clients[0].last_wire_dtype == "int8"
        assert clients[1].last_wire_dtype == "fp32"
        assert clients[2].last_wire_dtype == "bf16"
        # The acceptance floor: int8 streamed uploads >= 3x smaller.
        assert clients[0].last_upload_bytes * 3 < fp32_bytes
        # Deterministic replay: the server folded each client's DECODED
        # leaves — identical to quant/dequant (or bf16) round-trips.
        want2 = aggregate_flat(
            [_rt_int8(models2[0]), models2[1], _rt_bf16(models2[2])]
        )
        for cid in clients:
            assert wire.flat_crc32(r2[cid]) == wire.flat_crc32(want2)
        assert server.stream_totals["fold_engine"] == fold.engine_name()


def test_reply_dtype_refusal_matrix():
    """The reply leg mirrors the upload leg's composition rules: lossy
    reply dtypes refuse secure-agg (the unmask release is bit-exact by
    contract) and any reply compression (one encoder per leg)."""
    with pytest.raises(ValueError, match="reply_dtype"):
        AggregationServer(port=0, num_clients=1, reply_dtype="fp16")
    with pytest.raises(ValueError, match="secure"):
        AggregationServer(
            port=0, num_clients=2, secure_agg=True, reply_dtype="bf16"
        )
    with pytest.raises(ValueError, match="two encoders"):
        AggregationServer(
            port=0, num_clients=1, compression="bf16", reply_dtype="int8"
        )
    # fp32 (the default) composes with everything.
    with AggregationServer(
        port=0, num_clients=1, secure_agg=False, reply_dtype="fp32"
    ):
        pass


def test_reply_dtype_quantizes_streamed_replies_capability_gated(rng):
    """``serve --reply-dtype bf16``: a streaming client that adverts
    decodable reply encodings gets the quantized streamed reply (its
    aggregate is the bf16 round-trip of the fold — deterministic
    dequantization replay), while an old peer that never streams keeps
    the dense fp32 reply, exact — in the SAME round."""
    models = [_leaves(rng, n=3), _leaves(rng, n=3)]
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=30,
        stream_chunk_bytes=1 << 10, reply_dtype="bf16",
    ) as server:
        clients = {
            0: FederatedClient(
                "127.0.0.1", server.port, client_id=0, timeout=30,
            ),
            # "Old SDK": never streams, so it neither adverts reply
            # encodings nor receives a streamed (quantizable) reply.
            1: FederatedClient(
                "127.0.0.1", server.port, client_id=1, timeout=30,
                stream=False,
            ),
        }
        t = _serve_rounds(server, 1, results)
        aggs, errors = _run_clients(clients, models)
        t.join(timeout=60)
        assert not errors, errors
    exact = aggregate_flat(models)
    # Streaming client: every reply leaf rode the wire as bf16.
    assert wire.flat_crc32(aggs[0]) == wire.flat_crc32(_rt_bf16(exact))
    assert wire.flat_crc32(aggs[0]) != wire.flat_crc32(exact)
    # Dense client: byte-exact fp32, byte-identical to a quant-less round.
    assert wire.flat_crc32(aggs[1]) == wire.flat_crc32(exact)


def test_quantized_dp_upload_is_reclipped(rng):
    """int8 + central DP: the server holds the lossy streamed delta
    until the trailer, dequantizes, re-clips, and only then folds —
    bit-equal to the numpy replay (containment, not refusal)."""
    clip = 0.05
    base0 = _leaves(rng, n=2, shape=(30, 20))
    p1 = {k: v + rng.normal(size=v.shape).astype(np.float32) for k, v in base0.items()}
    results = {}
    with AggregationServer(
        port=0, num_clients=1, timeout=30, dp_clip=clip,
        stream_chunk_bytes=1 << 10,
    ) as server:
        client = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=30,
            wire_dtype="int8", dp=True,
        )
        t = _serve_rounds(server, 2, results)
        agg1 = client.exchange(p1, round_base=base0)
        # Round 2: the upload is the quantized clipped delta.
        p2 = {
            k: np.asarray(v, np.float32)
            + rng.normal(size=v.shape).astype(np.float32)
            for k, v in agg1.items()
        }
        agg2 = client.exchange(p2, round_base=agg1)
        t.join(timeout=30)
        assert client.last_wire_dtype == "int8"
    # Replay: client clips, the wire quantizes, the server dequantizes
    # and RE-clips (quantization error can push the norm back over the
    # bound) before folding onto the round base.
    delta = {
        k: np.asarray(p2[k], np.float32) - np.asarray(agg1[k], np.float32)
        for k in p2
    }
    clipped, _, _ = wire.clip_flat(delta, clip)
    rt = _rt_int8(clipped)
    if wire.flat_l2_norm(rt) > clip:
        rt, _, _ = wire.clip_flat(rt, clip)
    expected = {
        k: np.float32(1.0) * (np.asarray(agg1[k], np.float32) + rt[k])
        for k in rt
    }
    assert wire.flat_crc32(agg2) == wire.flat_crc32(expected)


# ----------------------------------------------------- sparse upward hops
def test_relay_upward_topk_refuses_topk_leaf_compression():
    with pytest.raises(ValueError, match="upward"):
        RelayAggregator(
            "127.0.0.1", 0, parent_host="127.0.0.1", parent_port=1,
            relay_id=0, num_clients=1, compression="topk:0.1",
            upward_topk=0.1,
        )
    with pytest.raises(WireError):
        RelayAggregator(
            "127.0.0.1", 0, parent_host="127.0.0.1", parent_port=1,
            relay_id=0, num_clients=1, upward_topk=1.5,
        )


def test_relay_sparse_upward_round2_base_agreement(rng):
    """Relay with upward_topk behind a lossless root: round 1 goes up
    dense (no base), the relay adopts the root aggregate as its delta
    base, and the round-2 upward hop is a topk delta — with the root's
    round-2 aggregate bit-equal to the replay and upward bytes
    collapsing even though the LEAVES uploaded dense."""
    frac = 0.05
    models1 = [_leaves(rng, n=3, shape=(64, 32)) for _ in range(2)]
    models2 = [_leaves(rng, n=3, shape=(64, 32)) for _ in range(2)]
    root_out = {}
    with AggregationServer(
        port=0, num_clients=1, weighted=True, timeout=30,
        stream_chunk_bytes=1 << 10,
    ) as root:
        relay = RelayAggregator(
            "127.0.0.1", 0, parent_host="127.0.0.1",
            parent_port=root.port, relay_id=0, num_clients=2,
            timeout=30, stream_chunk_bytes=1 << 10, upward_topk=frac,
        )
        try:
            rt = _serve_rounds(root, 2, root_out)
            threading.Thread(
                target=relay.serve, args=(2,), daemon=True
            ).start()
            clients = {
                cid: FederatedClient(
                    "127.0.0.1", relay.port, client_id=cid, timeout=30
                )
                for cid in range(2)
            }
            r1, errors = _run_clients(clients, models1)
            assert not errors, errors
            ub1 = relay.upward_bytes
            assert ub1 > 0
            # The relay's parent leg adopted the root aggregate as base.
            assert relay.parent._base is not None
            r2, errors = _run_clients(clients, models2)
            rt.join(timeout=60)
            assert not errors, errors
            ub2 = relay.upward_bytes - ub1
        finally:
            relay.close()
    # Round 1 is the plain subtree mean, bit-exact through the tree.
    want1 = aggregate_flat(models1)
    assert wire.flat_crc32(r1[0]) == wire.flat_crc32(want1)
    # Round-2 replay: subtree partial folds dense; the upward hop sends
    # topk(partial - base) per leaf (error-feedback residual is zero on
    # the first sparse round); the root reconstructs base + densify.
    partial2 = aggregate_flat(models2)
    sent = {}
    for k in sorted(partial2):
        d = partial2[k] - np.asarray(want1[k], np.float32)
        sent[k] = wire.densify_topk(wire.sparsify_topk(d, frac), d.shape)
    expected2 = {
        k: np.float32(1.0) * (np.asarray(want1[k], np.float32) + sent[k])
        for k in sorted(partial2)
    }
    for cid in (0, 1):
        assert wire.flat_crc32(r2[cid]) == wire.flat_crc32(expected2)
    # The whole point: the upward hop collapsed (>= 3x at frac=0.05).
    assert ub2 * 3 < ub1, (ub1, ub2)


# --------------------------------------------- strategy-state persistence
def test_strategy_state_survives_server_restart(rng, tmp_path):
    """PR 16 residual closed: a restarted root with strategy_state_path
    resumes the momentum trajectory (prev global + optimizer state)
    instead of re-adopting the bare mean."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.strategies import (
        make_strategy,
    )

    path = str(tmp_path / "strategy_state.npz")
    ms = [_leaves(rng, n=2, shape=(12, 6)) for _ in range(3)]
    results = {}
    with AggregationServer(
        port=0, num_clients=1, timeout=30, strategy="momentum",
        strategy_state_path=path,
    ) as srv1:
        client = FederatedClient(
            "127.0.0.1", srv1.port, client_id=0, timeout=30
        )
        t = _serve_rounds(srv1, 2, results)
        a1 = client.exchange(ms[0])
        a2 = client.exchange(ms[1])
        t.join(timeout=30)
    # close() drained the persist thread: the snapshot is on disk.
    assert (tmp_path / "strategy_state.npz").exists()

    with AggregationServer(
        port=0, num_clients=1, timeout=30, strategy="momentum",
        strategy_state_path=path,
    ) as srv2:
        # The restart restored the post-strategy global and advanced the
        # round counter past the persisted round.
        assert srv2._last_agg is not None
        assert srv2._round_counter == srv2._last_agg_round + 1
        client = FederatedClient(
            "127.0.0.1", srv2.port, client_id=0, timeout=30
        )
        t = _serve_rounds(srv2, 1, results, key="r3")
        a3 = client.exchange(ms[2])
        t.join(timeout=30)

    # Replay the CONTINUOUS trajectory with one strategy instance.
    s = make_strategy("momentum")
    e1 = s.apply(None, ms[0], round_no=0)
    e2 = s.apply(e1, ms[1], round_no=1)
    e3 = s.apply(e2, ms[2], round_no=2)
    assert wire.flat_crc32(a1) == wire.flat_crc32(e1)
    assert wire.flat_crc32(a2) == wire.flat_crc32(e2)
    assert wire.flat_crc32(a3) == wire.flat_crc32(e3)
    # And the trajectory genuinely differs from re-adopting the mean —
    # the failure mode this satellite closes.
    assert wire.flat_crc32(a3) != wire.flat_crc32(ms[2])


def test_strategy_state_mismatch_starts_fresh(rng, tmp_path):
    """A persisted snapshot from a DIFFERENT strategy is ignored (warn +
    fresh start), never misapplied."""
    path = str(tmp_path / "strategy_state.npz")
    ms = [_leaves(rng, n=2, shape=(8, 4)) for _ in range(2)]
    results = {}
    with AggregationServer(
        port=0, num_clients=1, timeout=30, strategy="momentum",
        strategy_state_path=path,
    ) as srv1:
        client = FederatedClient(
            "127.0.0.1", srv1.port, client_id=0, timeout=30
        )
        t = _serve_rounds(srv1, 2, results)
        client.exchange(ms[0])
        client.exchange(ms[1])
        t.join(timeout=30)
    with AggregationServer(
        port=0, num_clients=1, timeout=30, strategy="fedavg",
        strategy_state_path=path,
    ) as srv2:
        assert srv2._last_agg is None
        assert srv2._round_counter == 0
