"""The predict command: batch inference from trained checkpoints.

The reference trains and evaluates (client1.py:379-400) but never ships a
way to run the detector on new traffic; predict is that deployment step.
Covers both checkpoint flavors (local TrainState, federated FedState) and
the unlabeled-CSV path.
"""

import os

import numpy as np
import pandas as pd
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
    main,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    write_synthetic_csv,
)


@pytest.fixture(scope="module")
def flows_csv(tmp_path_factory):
    path = tmp_path_factory.mktemp("predict") / "flows.csv"
    write_synthetic_csv(str(path), n_rows=400, seed=21)
    return str(path)


def _read(path):
    df = pd.read_csv(path)
    assert list(df.columns) == ["prob_attack", "prediction", "label_name"]
    assert df["prob_attack"].between(0.0, 1.0).all()
    assert set(df["prediction"].unique()) <= {0, 1}
    return df


@pytest.fixture(scope="module")
def local_ckpt(tmp_path_factory):
    """One trained local checkpoint shared by every predict test (training
    is the expensive part; predict reads it read-only)."""
    root = tmp_path_factory.mktemp("predict_ckpt")
    ckpt = str(root / "ckpt")
    assert (
        main(
            [
                "local", "--synthetic", "600", "--epochs", "3",
                "--data-fraction", "1.0",
                "--learning-rate", "1e-3",  # random-init tiny model: the
                # reference's 2e-5 assumes a pretrained encoder
                "--batch-size", "16", "--checkpoint-dir", ckpt,
                "--output-dir", str(root / "reports"),
            ]
        )
        == 0
    )
    return ckpt


def test_predict_requires_weights(flows_csv, tmp_path):
    with pytest.raises(SystemExit, match="trained weights"):
        main(["predict", "--csv", flows_csv, "--output", str(tmp_path / "p.csv")])


def test_predict_from_local_checkpoint(flows_csv, local_ckpt, tmp_path):
    out = str(tmp_path / "preds.csv")
    assert main(["predict", "--csv", flows_csv, "--checkpoint-dir", local_ckpt, "--output", out]) == 0
    df = _read(out)
    assert len(df) == 400
    # A trained tiny model on separable synthetic flows must not be
    # degenerate (everything one class).
    assert 0 < df["prediction"].sum() < len(df)


@pytest.mark.slow
def test_predict_from_federated_checkpoint(flows_csv, tmp_path):
    ckpt = str(tmp_path / "fedckpt")
    out = str(tmp_path / "fedpreds.csv")
    assert (
        main(
            [
                "federated", "--synthetic", "600", "--num-clients", "2",
                "--rounds", "1", "--epochs", "1", "--batch-size", "16",
                "--checkpoint-dir", ckpt,
                "--output-dir", str(tmp_path / "fedreports"),
            ]
        )
        == 0
    )
    assert main(["predict", "--csv", flows_csv, "--checkpoint-dir", ckpt, "--output", out]) == 0
    df = _read(out)
    assert len(df) == 400


def test_predict_unlabeled_csv_and_threshold(flows_csv, local_ckpt, tmp_path):
    unlabeled = str(tmp_path / "unlabeled.csv")
    pd.read_csv(flows_csv).drop(columns=["Label"]).to_csv(unlabeled, index=False)
    out = str(tmp_path / "u.csv")
    assert main(["predict", "--csv", unlabeled, "--checkpoint-dir", local_ckpt, "--output", out]) == 0
    df = _read(out)
    assert len(df) == 400

    # threshold 1.01 can never flag anything; 0.0 flags everything.
    out_hi = str(tmp_path / "hi.csv")
    main(
        ["predict", "--csv", unlabeled, "--checkpoint-dir", local_ckpt,
         "--output", out_hi, "--threshold", "1.01"]
    )
    assert pd.read_csv(out_hi)["prediction"].sum() == 0


def test_predict_missing_checkpoint_errors(flows_csv, tmp_path):
    empty = str(tmp_path / "nothing")
    os.makedirs(empty)
    with pytest.raises((SystemExit, FileNotFoundError)):
        main(
            ["predict", "--csv", flows_csv, "--checkpoint-dir", empty,
             "--output", str(tmp_path / "x.csv")]
        )


def test_predict_nonexistent_checkpoint_dir_not_created(flows_csv, tmp_path):
    """A mistyped --checkpoint-dir must error without creating the path."""
    bogus = str(tmp_path / "no" / "such" / "run")
    with pytest.raises(SystemExit, match="does not exist"):
        main(
            ["predict", "--csv", flows_csv, "--checkpoint-dir", bogus,
             "--output", str(tmp_path / "x.csv")]
        )
    assert not os.path.exists(bogus)


def test_predict_rejects_training_data_flags(flows_csv, tmp_path):
    with pytest.raises(SystemExit, match="training-data option"):
        main(
            ["predict", "--csv", flows_csv, "--stream",
             "--checkpoint-dir", str(tmp_path), "--output", str(tmp_path / "x.csv")]
        )
