"""Engine tests: the minimum end-to-end slice — synthetic flows in,
reference-schema metrics out, and the model actually learns."""

import numpy as np
import pytest

import jax

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    default_tokenizer,
    load_flow_csv,
    make_client_splits,
    tokenize_client,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train import (
    Trainer,
)

MAX_LEN = 64


@pytest.fixture(scope="module")
def tok():
    return default_tokenizer()


@pytest.fixture(scope="module")
def client_data(tok):
    import detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data as d

    df = d.make_synthetic_flows(1500, seed=9)
    cfg = DataConfig(data_fraction=0.6, max_len=MAX_LEN)
    splits = make_client_splits(df, 0, 1, cfg)
    return tokenize_client(splits, tok, max_len=MAX_LEN)


@pytest.fixture(scope="module")
def trainer(tok):
    mcfg = ModelConfig.tiny(
        vocab_size=len(tok), max_len=MAX_LEN, max_position_embeddings=MAX_LEN,
        dim=64, n_layers=2, n_heads=4, hidden_dim=128,
    )
    tcfg = TrainConfig(learning_rate=1e-3, epochs_per_round=2, seed=0)
    return Trainer(mcfg, tcfg, pad_id=tok.pad_id)


def test_end_to_end_learns(trainer, client_data):
    state = trainer.init_state()
    before = trainer.evaluate(state.params, client_data.test)
    state, losses = trainer.fit(state, client_data.train, batch_size=16)
    after = trainer.evaluate(state.params, client_data.test)
    assert losses[-1] < losses[0]
    assert after["Accuracy"] > 90.0, after
    assert after["Accuracy"] >= before["Accuracy"]
    # reference metric schema
    for k in ("Accuracy", "Loss", "Precision", "Recall", "F1-Score"):
        assert k in after
    cm = after["confusion_matrix"]
    assert cm.sum() == after["n"] == len(client_data.test)


@pytest.mark.slow
def test_warmup_ramps_then_reaches_full_lr(tok):
    """Per-step update magnitudes must ramp over the warmup window and reach
    the constant-LR magnitude once the window has passed; the ramp is keyed
    on the global step so a mid-training optimizer reset does not restart
    it (reference fresh-Adam-per-round semantics, FedConfig docstring)."""
    mcfg = ModelConfig.tiny(vocab_size=len(tok), max_len=MAX_LEN,
                            max_position_embeddings=MAX_LEN)
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(0, mcfg.vocab_size, (8, MAX_LEN)).astype(np.int32),
        "attention_mask": np.ones((8, MAX_LEN), np.int32),
        "labels": rng.integers(0, 2, 8).astype(np.int32),
    }

    def step_norms(warmup, n_steps):
        tr = Trainer(mcfg, TrainConfig(learning_rate=1e-3, warmup_steps=warmup, seed=0))
        state = tr.init_state(seed=0)
        norms = []
        for _ in range(n_steps):
            before = jax.tree.map(lambda x: np.asarray(x).copy(), state.params)
            state, _ = tr.train_step(state, batch)
            norms.append(sum(
                float(np.abs(np.asarray(a) - b).sum())
                for a, b in zip(
                    jax.tree.leaves(state.params), jax.tree.leaves(before)
                )
            ))
        return norms

    warm = step_norms(warmup=4, n_steps=6)
    const = step_norms(warmup=0, n_steps=1)
    # Ramp: strictly increasing through the window, starting well below
    # the constant-LR magnitude (first factor = 1/4).
    assert warm[0] < const[0] * 0.5
    assert warm[0] < warm[1] < warm[2] < warm[3]
    # Post-window steps run at full LR (same order of magnitude as the
    # constant-LR first step; Adam normalizes update scale).
    assert warm[4] > const[0] * 0.5


def test_eval_counts_every_example_once(trainer, client_data):
    """Padded eval must count each of the N examples exactly once even when
    N % batch_size != 0."""
    state = trainer.init_state()
    n = len(client_data.val)
    assert n % 16 != 0 or n % 7 != 0
    m7 = trainer.evaluate(state.params, client_data.val, batch_size=7)
    m16 = trainer.evaluate(state.params, client_data.val, batch_size=16)
    assert m7["n"] == m16["n"] == n
    np.testing.assert_allclose(m7["Accuracy"], m16["Accuracy"], atol=1e-4)
    np.testing.assert_array_equal(m7["confusion_matrix"], m16["confusion_matrix"])
    assert len(m7["probs"]) == n and len(m7["labels"]) == n


def test_training_is_deterministic(trainer, client_data):
    s1, l1 = trainer.fit(trainer.init_state(seed=5), client_data.train, epochs=1)
    s2, l2 = trainer.fit(trainer.init_state(seed=5), client_data.train, epochs=1)
    assert l1 == l2
    leaves1 = jax.tree.leaves(s1.params)
    leaves2 = jax.tree.leaves(s2.params)
    for a, b in zip(leaves1, leaves2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_warm_start_continues(trainer, client_data):
    state, _ = trainer.fit(trainer.init_state(), client_data.train, epochs=1)
    state2 = trainer.init_state(params=state.params)
    assert int(state2.step) == 0
    _, losses = trainer.fit(state2, client_data.train, epochs=1)
    assert losses[0] < 0.5  # warm-started, not from scratch


@pytest.mark.slow
def test_grad_accum_trains(tok, client_data):
    """grad_accum_steps=2 with bs=8 (effective batch 16) must train to the
    same regime as the plain bs=16 path."""
    mcfg = ModelConfig.tiny(
        vocab_size=len(tok), max_len=MAX_LEN, max_position_embeddings=MAX_LEN,
        dim=64, n_layers=2, n_heads=4, hidden_dim=128,
    )
    base = Trainer(mcfg, TrainConfig(learning_rate=1e-3, seed=1), pad_id=tok.pad_id)
    accum = Trainer(
        mcfg, TrainConfig(learning_rate=1e-3, grad_accum_steps=2, seed=1),
        pad_id=tok.pad_id,
    )
    s_base, _ = base.fit(base.init_state(), client_data.train, batch_size=16, epochs=2)
    s_accum, _ = accum.fit(accum.init_state(), client_data.train, batch_size=8, epochs=2)
    m_base = base.evaluate(s_base.params, client_data.test, collect_probs=False)
    m_accum = accum.evaluate(s_accum.params, client_data.test, collect_probs=False)
    assert m_base["Accuracy"] > 85.0
    assert m_accum["Accuracy"] > 85.0


def test_train_remainder_trains_final_short_batch():
    """DataConfig.drop_remainder=False (Trainer(drop_remainder=False))
    runs the reference DataLoader's drop_last=False semantics: the final
    short batch takes a real step (state.step counts it) and its loss
    enters the epoch average."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
    )

    cfg = ModelConfig.tiny()
    n, bs = 20, 8
    r = np.random.default_rng(0)
    split = TokenizedSplit(
        r.integers(1, cfg.vocab_size, (n, cfg.max_len)).astype(np.int32),
        np.ones((n, cfg.max_len), np.int32),
        r.integers(0, 2, n).astype(np.int32),
    )
    dropped = Trainer(cfg, TrainConfig(epochs_per_round=1))
    s1 = dropped.init_state(seed=0)
    s1, _ = dropped.fit(s1, split, batch_size=bs)
    assert int(s1.step) == n // bs  # 2 full batches, tail dropped

    full = Trainer(cfg, TrainConfig(epochs_per_round=1), drop_remainder=False)
    s2 = full.init_state(seed=0)
    s2, losses = full.fit(s2, split, batch_size=bs)
    assert int(s2.step) == -(-n // bs)  # 3 steps: the 4-row tail trained
    # The extra step moved the params (the tail actually trained).
    diff = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params))
    )
    assert diff
