"""export-hf: trained checkpoint -> HF DistilBERT layout round trip.

The reference's artifact format IS the HF key space (its ``.pth`` state
dicts and required ``./distilbert-base-uncased`` input, client1.py:56,388);
export-hf lets a reference user consume models trained here."""

import json
import os

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
    main,
)


@pytest.fixture(scope="module")
def trained_ckpt(tmp_path_factory):
    d = tmp_path_factory.mktemp("export")
    ckpt = str(d / "ckpt")
    assert (
        main(
            [
                "local", "--synthetic", "300", "--epochs", "1",
                "--batch-size", "16", "--checkpoint-dir", ckpt,
                "--output-dir", str(d / "reports"),
            ]
        )
        == 0
    )
    return ckpt


def test_export_hf_layout_and_roundtrip(trained_ckpt, tmp_path):
    out = str(tmp_path / "hf")
    assert (
        main(["export-hf", "--checkpoint-dir", trained_ckpt, "--out", out]) == 0
    )
    assert sorted(os.listdir(out)) == ["config.json", "model.safetensors", "vocab.txt"]
    hf_cfg = json.load(open(os.path.join(out, "config.json")))
    assert hf_cfg["model_type"] == "distilbert"
    # tiny preset trains under exact GELU; the export must declare it, and
    # config_from_hf_dir must read it back (tanh would be "gelu_new").
    assert hf_cfg["activation"] == "gelu"
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.hf_convert import (
        config_from_hf_dir,
    )

    assert config_from_hf_dir(out).gelu == "exact"

    # Our own --hf-dir loader reads the export back bit-for-bit.
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.hf_convert import (
        load_hf_dir,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        ModelConfig,
    )

    cfg = ModelConfig.tiny(
        vocab_size=hf_cfg["vocab_size"],
        dim=hf_cfg["dim"],
        n_layers=hf_cfg["n_layers"],
        n_heads=hf_cfg["n_heads"],
        hidden_dim=hf_cfg["hidden_dim"],
        max_position_embeddings=hf_cfg["max_position_embeddings"],
    )
    params, _ = load_hf_dir(out, cfg=cfg)
    leaves = [np.asarray(x) for x in __import__("jax").tree.leaves(params)]
    assert all(np.isfinite(a).all() for a in leaves)

    # transformers itself loads the exported encoder.
    transformers = pytest.importorskip("transformers")
    model = transformers.DistilBertModel.from_pretrained(out)
    assert model.config.dim == hf_cfg["dim"]

    # predict consumes the export via --hf-dir (the head is trained).
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        write_synthetic_csv,
    )

    csv = str(tmp_path / "flows.csv")
    write_synthetic_csv(csv, n_rows=40, seed=5)
    preds = str(tmp_path / "p.csv")
    assert (
        main(["predict", "--csv", csv, "--hf-dir", out, "--output", preds]) == 0
    )
    assert os.path.exists(preds)


def test_export_hf_requires_checkpoint(tmp_path):
    with pytest.raises((SystemExit, FileNotFoundError)):
        main(
            ["export-hf", "--checkpoint-dir", str(tmp_path / "none"),
             "--out", str(tmp_path / "o")]
        )


def test_export_declares_checkpoint_trained_activation(tmp_path):
    """The checkpoint's recorded config (not the CLI preset at export time)
    decides config.json's activation: tiny defaults to exact GELU, so a
    --gelu tanh training run must export "gelu_new" even when export-hf is
    invoked without --gelu."""
    ckpt = str(tmp_path / "ckpt")
    assert (
        main(
            [
                "local", "--synthetic", "200", "--epochs", "1", "--gelu",
                "tanh", "--checkpoint-dir", ckpt,
                "--output-dir", str(tmp_path / "r"),
            ]
        )
        == 0
    )
    out = str(tmp_path / "hf")
    assert (
        main(["export-hf", "--checkpoint-dir", ckpt, "--out", out]) == 0
    )
    hf_cfg = json.load(open(os.path.join(out, "config.json")))
    assert hf_cfg["activation"] == "gelu_new"
