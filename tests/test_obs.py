"""Cross-tier observability (obs/): span-ID propagation over a live
loopback round, the Prometheus /metrics endpoint, per-round timeline
attribution, and the Chrome trace-event export.

All host-side (sockets + JSONL + stdlib HTTP) — no JAX programs — so the
whole module stays in the fast lane.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.client import (
    FederatedClient,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.server import (
    AggregationServer,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
    MetricsRegistry,
    MetricsServer,
    Tracer,
    chrome_trace,
    default_registry,
    export_chrome_trace,
    group_rounds,
    load_spans,
    round_summaries,
    timeline_table,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.trace import (
    SCHEMA,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
    protocol,
)

N_CLIENTS = 2
LOCAL_SLEEP_S = 0.12  # simulated local training; dominates the round wall


@pytest.fixture(scope="module")
def live_round(tmp_path_factory):
    """One traced loopback round: a real AggregationServer + N real
    FederatedClients, every process writing its own span JSONL — the
    exact multi-file layout `fedtpu obs` merges."""
    trace_dir = tmp_path_factory.mktemp("obs-spans")
    server = AggregationServer(
        port=0,
        num_clients=N_CLIENTS,
        timeout=30,
        tracer=Tracer(str(trace_dir / "server.jsonl"), proc="server"),
    )
    result: dict = {}

    def run_server():
        result["agg"] = server.serve_round()

    def run_client(cid: int):
        fc = FederatedClient(
            "127.0.0.1",
            server.port,
            client_id=cid,
            timeout=30,
            tracer=Tracer(
                str(trace_dir / f"client{cid}.jsonl"), proc=f"client-{cid}"
            ),
        )
        t0 = time.time()
        time.sleep(LOCAL_SLEEP_S)  # stand-in for the local training phase
        fc.note_local_phase(t0, time.time() - t0, client=cid)
        fc.exchange({"w": np.full(64, cid + 1.0, np.float32)}, n_samples=10)
        result[f"trace{cid}"] = fc.last_trace

    st = threading.Thread(target=run_server)
    cts = [
        threading.Thread(target=run_client, args=(c,))
        for c in range(N_CLIENTS)
    ]
    st.start()
    for t in cts:
        t.start()
    for t in cts:
        t.join(timeout=60)
    st.join(timeout=60)
    server.close()
    spans = load_spans(trace_dir=str(trace_dir))
    return {
        "dir": str(trace_dir),
        "spans": spans,
        "server": server,
        **result,
    }


# ------------------------------------------------------- span propagation
def test_span_ids_propagate_across_the_wire(live_round):
    """The acceptance contract: server and every client agree on the
    round's (trace, round) identity — the id crossed the wire in the
    reply meta, not via any shared process state."""
    spans = live_round["spans"]
    assert spans, "no spans written"
    traced = [s for s in spans if s.get("trace")]
    trace_ids = {s["trace"] for s in traced}
    assert len(trace_ids) == 1  # one round -> exactly one trace id
    (tid,) = trace_ids
    # Both clients adopted the server's id (returned via last_trace too).
    for c in range(N_CLIENTS):
        assert live_round[f"trace{c}"] == (tid, 0)
    # Every tier's file contributed spans under that identity.
    procs = {s["proc"] for s in traced}
    assert procs == {"server", *(f"client-{c}" for c in range(N_CLIENTS))}
    by_proc = {p: {s["span"] for s in traced if s["proc"] == p} for p in procs}
    assert {"round", "agg", "wire-reply"} <= by_proc["server"]
    for c in range(N_CLIENTS):
        assert by_proc[f"client-{c}"] == {
            "client-local", "wire-upload", "wire-reply",
        }
    # All spans agree on the round index and carry the schema tag.
    assert {s.get("round") for s in traced} == {0}
    assert all(s["schema"] == SCHEMA for s in spans)
    assert all(s.get("run_id") for s in spans)


def test_untraced_client_still_interoperates():
    """A client with no tracer against a tracing server: the exchange is
    unchanged (the trace rides optional meta) and the client still
    LEARNS the round identity via last_trace."""
    server = AggregationServer(port=0, num_clients=1, timeout=30)
    out = {}
    st = threading.Thread(target=lambda: out.update(agg=server.serve_round()))
    st.start()
    fc = FederatedClient("127.0.0.1", server.port, client_id=0, timeout=30)
    agg = fc.exchange({"w": np.ones(8, np.float32)})
    st.join(timeout=60)
    server.close()
    np.testing.assert_allclose(agg["w"], np.ones(8))
    trace_id, rnd = fc.last_trace
    assert isinstance(trace_id, str) and len(trace_id) == 16
    assert rnd == 0


# ------------------------------------------------------------- timeline
def test_timeline_attributes_round_wall(live_round):
    """compute + upload + wait + agg + reply reconstructs each client's
    measured round wall within 10% (the acceptance bound), and the
    simulated local phase is attributed to compute."""
    summaries = round_summaries(live_round["spans"])
    assert len(summaries) == 1
    b = summaries[0]
    assert b["round"] == 0
    assert len(b["clients"]) == N_CLIENTS
    for proc, row in b["clients"].items():
        assert row["measured_s"] > 0
        err = abs(row["attributed_s"] - row["measured_s"]) / row["measured_s"]
        assert err < 0.10, (proc, row)
        # The 120 ms simulated local phase landed in compute, not wait.
        assert row["compute_s"] == pytest.approx(LOCAL_SLEEP_S, rel=0.5)
    assert b["slowest_span"] is not None
    table = timeline_table(live_round["spans"])
    assert "compute" in table and "wait" in table and "slowest span" in table
    for c in range(N_CLIENTS):
        assert f"client-{c}" in table


def test_server_phase_seconds_accounting(live_round):
    """The always-on comm/compute breakdown (bench.py's comm_phase_*
    headline source): wait/agg/reply are all populated and wait dominates
    a round whose wall is the clients' local phases."""
    phases = live_round["server"].phase_seconds
    assert set(phases) == {"wait", "agg", "reply"}
    assert phases["wait"] >= LOCAL_SLEEP_S  # straggler wait >= local sim
    assert phases["agg"] > 0 and phases["reply"] > 0
    assert phases["wait"] > phases["agg"]


# ---------------------------------------------------------- chrome export
def test_chrome_trace_export_roundtrips(live_round, tmp_path):
    path = export_chrome_trace(
        live_round["spans"], str(tmp_path / "trace.json")
    )
    with open(path) as f:
        doc = json.load(f)  # the acceptance check: valid JSON round-trip
    events = doc["traceEvents"]
    xs = [e for e in events if e.get("ph") == "X"]
    assert len(xs) == len(live_round["spans"])
    for e in xs:
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
    # Metadata names every process lane.
    names = {
        e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e["name"] == "process_name"
    }
    assert names == {"server", *(f"client-{c}" for c in range(N_CLIENTS))}


def test_client_phase_spans_monotonic_non_overlapping(live_round):
    """Per client: client-local -> wire-upload -> wire-reply are strictly
    ordered and non-overlapping (the phases are sequential by
    construction; overlap would mean the clocks/durations are wrong)."""
    spans = live_round["spans"]
    for c in range(N_CLIENTS):
        mine = sorted(
            (s for s in spans if s.get("proc") == f"client-{c}"),
            key=lambda s: s["ts"],
        )
        assert [s["span"] for s in mine] == [
            "client-local", "wire-upload", "wire-reply",
        ]
        for prev, nxt in zip(mine, mine[1:]):
            # 2 ms slack: ts comes from time.time(), durations from the
            # monotonic clock; sub-ms skew between them is expected.
            assert nxt["ts"] >= prev["ts"] + prev["dur_s"] - 2e-3


# ------------------------------------------------------------- /metrics
def test_prometheus_endpoint_scrapes_and_parses():
    reg = MetricsRegistry()
    reg.counter("demo_rounds_total", help="rounds").inc(3)
    reg.gauge("demo_queue_depth").set(7)
    h = reg.histogram("demo_wait_seconds", buckets=(0.01, 0.1, 1.0))
    h.observe(0.05)
    h.observe(2.0)
    reg.counter(
        "demo_rejects_total", labels={"kind": "deadline"}
    ).inc()
    with MetricsServer(0, host="127.0.0.1", registry=reg) as srv:
        body = (
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10
            )
            .read()
            .decode()
        )
    assert "# TYPE demo_rounds_total counter" in body
    assert "demo_rounds_total 3" in body
    assert "demo_queue_depth 7" in body
    assert 'demo_rejects_total{kind="deadline"} 1' in body
    assert 'demo_wait_seconds_bucket{le="+Inf"} 2' in body
    assert "demo_wait_seconds_count 2" in body
    # Every sample line parses as `name[{labels}] value` with a float
    # value — the exposition-format contract a scraper depends on.
    for line in body.strip().splitlines():
        if line.startswith("#"):
            continue
        name_part, value = line.rsplit(" ", 1)
        float(value)
        assert name_part.startswith("demo_")


def test_metrics_json_twin_endpoint_matches_text_rendering():
    """ISSUE 11 satellite: /metrics.json serves the SAME numbers as the
    Prometheus text format — machine-readable, schema-tagged, no
    exposition-format parser needed (the scrape hub's input)."""
    reg = MetricsRegistry()
    reg.counter("demo_rounds_total", help="rounds").inc(3)
    reg.gauge("demo_queue_depth").set(7)
    h = reg.histogram("demo_wait_seconds", buckets=(0.01, 0.1, 1.0))
    h.observe(0.05)
    h.observe(2.0)
    reg.counter("demo_rejects_total", labels={"kind": "deadline"}).inc()
    with MetricsServer(0, host="127.0.0.1", registry=reg) as srv:
        raw = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics.json", timeout=10
        )
        assert raw.headers["Content-Type"] == "application/json"
        doc = json.loads(raw.read())
    assert doc["schema"] == "fedtpu-metrics-v1"
    fams = doc["families"]
    assert fams["demo_rounds_total"]["type"] == "counter"
    assert fams["demo_rounds_total"]["samples"][0]["value"] == 3
    assert fams["demo_queue_depth"]["samples"][0]["value"] == 7
    # Labeled sample keeps its labels as a dict.
    (rej,) = fams["demo_rejects_total"]["samples"]
    assert rej["labels"] == {"kind": "deadline"} and rej["value"] == 1
    # Histogram buckets are CUMULATIVE [edge, count] pairs ending +Inf —
    # identical numbers to the text rendering's _bucket lines.
    (hs,) = fams["demo_wait_seconds"]["samples"]
    assert hs["buckets"] == [["0.01", 0], ["0.1", 1], ["1", 1], ["+Inf", 2]]
    assert hs["count"] == 2 and hs["sum"] == pytest.approx(2.05)
    # Twin consistency: every text sample value appears in the JSON.
    text = reg.render()
    assert 'demo_wait_seconds_bucket{le="+Inf"} 2' in text
    assert "demo_rounds_total 3" in text


def test_new_health_span_names_registered():
    """The PR-10 spans are IN the closed vocabulary (the obs-span-vocab
    static pass anchors on this tuple) and the timeline renders them as
    extra rows."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
        SPAN_NAMES,
    )

    assert {"slo-eval", "postmortem-dump", "drift-trigger"} <= set(
        SPAN_NAMES
    )
    # The REAL emission shapes: slo-eval and postmortem-dump carry NO
    # (trace, round) — they happen outside any round's identity — and
    # drift-trigger carries only the round index. The timeline must
    # render all three anyway (the unscoped trailing section / the
    # per-round extra rows), not silently drop them.
    t_spans = [
        {
            "schema": SCHEMA, "proc": "obs-hub", "span": "slo-eval",
            "ts": 1.0, "dur_s": 0.002, "firing": 1, "up": 1,
        },
        {
            "schema": SCHEMA, "proc": "server", "span": "postmortem-dump",
            "ts": 2.0, "dur_s": 0.01, "reason": "round-failure",
            "bundle": "b.json",
        },
        {
            "schema": SCHEMA, "proc": "controller", "span": "drift-trigger",
            "ts": 3.0, "dur_s": 0.0, "round": 1, "drift": 0.31,
        },
        # An anchoring round so the per-round half renders too.
        {
            "schema": SCHEMA, "proc": "server", "span": "round",
            "ts": 0.5, "dur_s": 1.0, "trace": "aa", "round": 1,
        },
    ]
    table = timeline_table(t_spans)
    assert "slo-eval" in table and "firing=1" in table
    assert "postmortem-dump" in table and "reason=round-failure" in table
    assert "drift-trigger" in table
    assert "unscoped health-plane spans" in table


def test_http_404_off_path():
    reg = MetricsRegistry()
    with MetricsServer(0, host="127.0.0.1", registry=reg) as srv:
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=10
            )


def test_round_engine_feeds_default_registry(live_round):
    """The FL server's counters land on the process default registry —
    what `serve --metrics-port` exposes without extra wiring — and a
    live HTTP scrape of that registry sees the round that just ran."""
    with MetricsServer(0, host="127.0.0.1") as srv:
        body = (
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10
            )
            .read()
            .decode()
        )
    for needle in (
        "fedtpu_server_rounds_total",
        "fedtpu_server_uploads_total",
        "fedtpu_server_wire_bytes_received_total",
        'fedtpu_server_round_phase_seconds_total{phase="agg"}',
    ):
        assert needle in body

    def sample(name: str) -> float:
        for line in body.splitlines():
            if line.startswith(name + " "):
                return float(line.rsplit(" ", 1)[1])
        raise AssertionError(f"{name} not rendered")

    assert sample("fedtpu_server_rounds_total") >= 1
    assert sample("fedtpu_server_uploads_total") >= N_CLIENTS


# ------------------------------------------------- scoring-protocol trace
def test_scoring_protocol_trace_echo():
    req = protocol.parse_request(
        protocol.build_request(7, text="flow", trace="abcd1234abcd1234")
    )
    assert req["trace"] == "abcd1234abcd1234"
    rep = protocol.parse_reply(
        protocol.build_reply(
            7,
            prob=0.25,
            threshold=0.5,
            round_id=3,
            batch_size=4,
            bucket=8,
            queue_ms=1.5,
            trace=req["trace"],
        )
    )
    assert rep["trace"] == "abcd1234abcd1234"
    # Omitted everywhere: old peers' frames carry no trace key at all.
    assert "trace" not in protocol.parse_request(
        protocol.build_request(8, text="flow")
    )
    with pytest.raises(Exception):
        protocol.parse_request(
            protocol.SCORE_REQ_MAGIC
            + json.dumps({"id": 9, "text": "x", "trace": 42}).encode()
        )


# ------------------------------------------------------------------ CLI
def test_obs_cli_timeline_and_export(live_round, tmp_path, capsys):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        main,
    )

    assert main(["obs", "timeline", "--trace-dir", live_round["dir"]]) == 0
    out = capsys.readouterr().out
    assert "round 0" in out and "compute" in out
    out_path = str(tmp_path / "chrome.json")
    assert (
        main(
            [
                "obs", "export", "--trace-dir", live_round["dir"],
                "--out", out_path,
            ]
        )
        == 0
    )
    with open(out_path) as f:
        doc = json.load(f)
    assert doc["traceEvents"]
    capsys.readouterr()  # drain the export's "wrote ..." line
    # JSON timeline for machines.
    assert (
        main(["obs", "timeline", "--trace-dir", live_round["dir"], "--json"])
        == 0
    )
    rounds = json.loads(capsys.readouterr().out)
    assert rounds and rounds[0]["round"] == 0


def test_obs_cli_refuses_empty_inputs(tmp_path):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        main,
    )

    with pytest.raises(SystemExit):
        main(["obs", "timeline", "--trace-dir", str(tmp_path)])


# ------------------------------------------------------------ grouping
def test_group_rounds_and_foreign_lines(tmp_path):
    """The merger must group on (trace, round) and skip foreign lines
    (metrics-JSONL records, truncated tails) instead of crashing."""
    p = tmp_path / "mixed.jsonl"
    t = Tracer(str(p), proc="x")
    t.record("round", t_start=1.0, dur_s=0.5, trace="aa", round=1)
    t.record("round", t_start=2.0, dur_s=0.5, trace="bb", round=2)
    with open(p, "a") as f:
        f.write(json.dumps({"phase": "serve_batch", "score_hist": [1]}) + "\n")
        f.write('{"truncated": \n')  # partial tail from a crashed writer
    spans = load_spans([str(p)])
    assert len(spans) == 2
    groups = group_rounds(spans)
    assert set(groups) == {("aa", 1), ("bb", 2)}
    assert chrome_trace(spans)["traceEvents"]


# ------------------------------------------- round pipelining attribution
def test_wire_overlap_span_and_timeline_row(tmp_path):
    """ISSUE 5: a streamed round's server emits a wire-overlap span —
    fold work that ran DURING the wire phase, with overlap_frac and
    peak_agg_bytes — the timeline surfaces it next to the exposed agg,
    and the client's wire-upload span carries its chunk/overlap attrs."""
    trace_dir = tmp_path / "stream-spans"
    trace_dir.mkdir()
    server = AggregationServer(
        port=0, num_clients=2, timeout=30, stream_chunk_bytes=8192,
        tracer=Tracer(str(trace_dir / "server.jsonl"), proc="server"),
    )
    out = {}

    def run_server():
        out["r0"] = server.serve_round()
        out["r1"] = server.serve_round()

    def run_client(cid):
        fc = FederatedClient(
            "127.0.0.1", server.port, client_id=cid, timeout=30,
            tracer=Tracer(
                str(trace_dir / f"client{cid}.jsonl"), proc=f"client-{cid}"
            ),
        )
        p = {"w": np.full(40_000, cid + 1.0, np.float32)}
        agg = fc.exchange(p, n_samples=1)
        # Buffered like the real round loop's reply-wait prefetch span.
        fc.note_phase("batch-prefetch", time.time(), 0.01, client=cid)
        fc.exchange({k: v + 1.0 for k, v in agg.items()}, n_samples=1)

    st = threading.Thread(target=run_server)
    cts = [
        threading.Thread(target=run_client, args=(c,)) for c in range(2)
    ]
    st.start()
    for t in cts:
        t.start()
    for t in cts:
        t.join(timeout=60)
    st.join(timeout=60)
    server.close()
    assert server.stream_totals["stream_uploads"] == 2

    spans = load_spans(trace_dir=str(trace_dir))
    overlaps = [s for s in spans if s["span"] == "wire-overlap"]
    assert len(overlaps) == 1  # only the streamed round overlapped
    ov = overlaps[0]
    assert ov["round"] == 1 and ov["proc"] == "server"
    assert ov["folded_bytes"] > 0 and 0.0 < ov["overlap_frac"] <= 1.0
    assert ov["peak_agg_bytes"] > 0
    # The streamed wire-upload spans carry the pipelining attrs.
    ups = [
        s for s in spans
        if s["span"] == "wire-upload" and s.get("round") == 1
    ]
    assert len(ups) == 2
    assert all(u["chunks"] > 1 and u["overlap_s"] >= 0.0 for u in ups)
    # batch-prefetch spans adopted the round identity on the next flush.
    pf = [s for s in spans if s["span"] == "batch-prefetch"]
    assert len(pf) == 2 and all(s.get("round") == 1 for s in pf)

    summaries = round_summaries(spans)
    by_round = {b["round"]: b for b in summaries}
    assert by_round[1]["overlap_s"] > 0.0
    assert by_round[1]["overlap_frac"] == ov["overlap_frac"]
    assert by_round[0]["overlap_s"] == 0.0
    table = timeline_table(spans)
    assert "wire-overlap" in table and "folded during the wire phase" in table
    assert "batch-prefetch" in table


# --------------------------------------------------------- live tailing
def test_tail_spans_follows_appends_and_new_files(tmp_path):
    """ISSUE 9 satellite: the follow-mode reader yields spans as they
    are APPENDED — pre-existing spans only under from_start, files that
    appear mid-tail picked up from their start, foreign/partial lines
    skipped."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
        tail_spans,
    )

    d = tmp_path / "tail"
    d.mkdir()
    pre = Tracer(str(d / "pre.jsonl"), proc="early")
    pre.record("round", t_start=1.0, dur_s=0.5, trace="aa", round=1)

    got: list[dict] = []
    stop_at = [8]

    def collect(**kw):
        for rec in tail_spans(
            trace_dir=str(d), poll_s=0.05,
            stop=lambda: len(got) >= stop_at[0], **kw
        ):
            got.append(rec)

    # Without from_start: the pre-existing span is NOT replayed.
    stop_at[0] = 2
    t = threading.Thread(target=collect, daemon=True)
    t.start()
    time.sleep(0.2)
    pre.record("agg", t_start=2.0, dur_s=0.1, trace="aa", round=1)
    late = Tracer(str(d / "late.jsonl"), proc="late")  # appears mid-tail
    late.record("router-forward", t_start=3.0, dur_s=0.01, replica=0)
    with open(d / "pre.jsonl", "a") as f:
        f.write('{"not": "a span"}\n')  # foreign line: skipped
    t.join(timeout=10)
    assert not t.is_alive()
    assert {r["span"] for r in got} == {"agg", "router-forward"}
    # With from_start: history replays first.
    got.clear()
    stop_at[0] = 3
    t = threading.Thread(
        target=collect, kwargs={"from_start": True}, daemon=True
    )
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    assert {r["span"] for r in got} == {"round", "agg", "router-forward"}
    # Per-file append order is preserved (cross-file order is by name).
    pre_spans = [r["span"] for r in got if r["proc"] == "early"]
    assert pre_spans == ["round", "agg"]


def test_obs_cli_tail_filters_and_format(tmp_path, capsys):
    """`fedtpu obs tail`: one line per span with proc/span/duration,
    --round and --trace-id filters applied, bounded by --max-seconds;
    an empty directory is NOT an error (tailing it is the point)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        main,
    )

    d = tmp_path / "tailcli"
    d.mkdir()
    t = Tracer(str(d / "s.jsonl"), proc="server")
    t.record("round", t_start=1.0, dur_s=0.5, trace="aa", round=1)
    t.record("agg", t_start=2.0, dur_s=0.25, trace="aa", round=1)
    t.record("replica-drain", t_start=3.0, dur_s=0.1, round=2, replica=1)
    assert (
        main(
            [
                "obs", "tail", "--trace-dir", str(d), "--from-start",
                "--max-seconds", "0.3", "--poll", "0.05",
            ]
        )
        == 0
    )
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if ln.strip()]
    assert len(lines) == 3
    assert "server" in lines[0] and "round" in lines[0]
    assert "trace=aa" in lines[1]
    assert "replica=1" in lines[2] and "replica-drain" in lines[2]
    # --round filter
    assert (
        main(
            [
                "obs", "tail", "--trace-dir", str(d), "--from-start",
                "--round", "2", "--max-seconds", "0.3", "--poll", "0.05",
            ]
        )
        == 0
    )
    lines = [
        ln for ln in capsys.readouterr().out.splitlines() if ln.strip()
    ]
    assert len(lines) == 1 and "replica-drain" in lines[0]
    # --trace-id filter
    assert (
        main(
            [
                "obs", "tail", "--trace-dir", str(d), "--from-start",
                "--trace-id", "aa", "--max-seconds", "0.3", "--poll",
                "0.05",
            ]
        )
        == 0
    )
    lines = [
        ln for ln in capsys.readouterr().out.splitlines() if ln.strip()
    ]
    assert len(lines) == 2
    # An empty dir tails cleanly (no spans yet — not an error).
    empty = tmp_path / "empty"
    empty.mkdir()
    assert (
        main(
            [
                "obs", "tail", "--trace-dir", str(empty),
                "--max-seconds", "0.2", "--poll", "0.05",
            ]
        )
        == 0
    )
    assert capsys.readouterr().out.strip() == ""
