"""strategies/: pluggable server aggregation (ISSUE 16).

The contracts pinned here:

* registry — spec strings parse/build/reject exactly as `--strategy`
  documents them;
* math — FedAvg/FedProx are identities on the folded mean, Momentum and
  FedOpt match a hand-rolled optax reference bit-for-bit (same
  make_server_optimizer transform, same fp32 casts, same key order),
  HeadBoost boosts exactly the matching leaves;
* state — server-opt strategies reset on first round / shape change;
  StreamAgg's per-client strategy stats die with a dropped client;
* replay — a live loopback round per strategy stays crc-pinned
  bit-exact against the strategy replay over the clean survivor mean
  (the pure-transform contract that extends the crc gates);
* composition — the FedProx client step threads through the FSDP mesh
  trainer with the replicated engine's trajectory.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
    wire,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.stream_agg import (
    StreamAgg,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    FedConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.strategies import (
    STRATEGIES,
    FedAvg,
    FedOpt,
    FedProx,
    HeadBoost,
    Momentum,
    make_strategy,
    parse_strategy,
)


def _flat(rng, scale=1.0):
    return {
        "encoder/w": (scale * rng.normal(size=(4, 3))).astype(np.float32),
        "classifier/w": (scale * rng.normal(size=(3, 2))).astype(np.float32),
        "classifier/b": (scale * rng.normal(size=(2,))).astype(np.float32),
    }


# ------------------------------------------------------------------ registry
def test_parse_strategy_specs():
    assert parse_strategy("fedavg") == ("fedavg", {})
    assert parse_strategy("fedprox:mu=1.0") == ("fedprox", {"mu": 1.0})
    name, kw = parse_strategy("fedopt:opt=yogi,lr=0.05")
    assert name == "fedopt"
    assert kw == {"opt": "yogi", "lr": 0.05}  # strings stay, floats parse
    with pytest.raises(ValueError, match="unknown strategy"):
        parse_strategy("sgd")
    with pytest.raises(ValueError, match="bad strategy param"):
        parse_strategy("fedprox:mu")
    with pytest.raises(ValueError, match="bad strategy param"):
        parse_strategy("fedprox:=1.0")


def test_make_strategy_defaults_and_rejections():
    assert make_strategy(None).name == "fedavg"
    s = make_strategy("momentum:lr=0.5,momentum=0.8")
    assert (s.name, s.lr, s.momentum) == ("momentum", 0.5, 0.8)
    assert make_strategy(s) is s  # passthrough
    with pytest.raises(ValueError, match="rejected params"):
        make_strategy("fedprox:nu=1.0")  # unknown kwarg -> operator error
    assert sorted(STRATEGIES) == [
        "fedavg", "fedopt", "fedprox", "headboost", "momentum",
    ]


def test_param_validation():
    with pytest.raises(ValueError, match="mu"):
        FedProx(mu=0.0)
    with pytest.raises(ValueError, match="gamma"):
        HeadBoost(gamma=-1.0)
    with pytest.raises(ValueError, match="match"):
        HeadBoost(match="")
    with pytest.raises(ValueError, match="adam|yogi"):
        FedOpt(opt="sgd")
    with pytest.raises(ValueError, match="lr"):
        FedOpt(lr=0.0)
    with pytest.raises(ValueError, match="momentum"):
        Momentum(momentum=1.0)


# ---------------------------------------------------------------- identities
def test_fedavg_and_fedprox_are_identity_on_the_mean():
    rng = np.random.default_rng(0)
    prev, mean = _flat(rng), _flat(rng, 2.0)
    assert FedAvg().apply(prev, mean) is mean  # the historical fold
    prox = FedProx(mu=0.3)
    assert prox.apply(prev, mean) is mean  # server side untouched
    assert prox.client_mu() == pytest.approx(0.3)  # the client half
    assert FedAvg().client_mu() == 0.0
    assert prox.describe() == {"name": "fedprox", "params": {"mu": 0.3}}


def test_momentum_lr1_m0_reduces_to_the_mean():
    rng = np.random.default_rng(1)
    prev, mean = _flat(rng), _flat(rng, 2.0)
    out = Momentum(lr=1.0, momentum=0.0).apply(prev, mean)
    for k in mean:
        np.testing.assert_allclose(out[k], mean[k], rtol=1e-6)


def test_momentum_compounds_identical_round_deltas():
    """Heavy-ball memory: the same mean-vs-prev delta pushed twice must
    move the global further the second round."""
    strat = Momentum(lr=1.0, momentum=0.9)
    prev = {"w": np.zeros(4, np.float32)}
    delta = np.full(4, 0.01, np.float32)
    g1 = strat.apply(prev, {"w": prev["w"] + delta}, round_no=1)
    step1 = np.abs(g1["w"] - prev["w"]).mean()
    g2 = strat.apply(g1, {"w": g1["w"] + delta}, round_no=2)
    step2 = np.abs(g2["w"] - g1["w"]).mean()
    assert step2 > step1 * 1.5


@pytest.mark.parametrize(
    "strat, fed_kw",
    [
        (Momentum(lr=0.7, momentum=0.9),
         dict(server_opt="momentum", server_lr=0.7, server_momentum=0.9)),
        (FedOpt(opt="adam", lr=0.1),
         dict(server_opt="adam", server_lr=0.1)),
        (FedOpt(opt="yogi", lr=0.1),
         dict(server_opt="yogi", server_lr=0.1)),
    ],
)
def test_server_opt_matches_optax_reference_bitexact(strat, fed_kw):
    """Two rounds vs a hand-rolled loop over the SAME
    make_server_optimizer transform: pseudo-gradient prev - mean,
    persistent state, fp32 casts in sorted-key order — bit-for-bit."""
    import optax

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.fedavg import (
        make_server_optimizer,
    )

    rng = np.random.default_rng(2)
    tx = make_server_optimizer(FedConfig(**fed_kw))
    prev = strat.apply(None, _flat(rng))  # round 1: mean adopted as-is
    ref_prev, opt_state = dict(prev), None
    for rnd in (2, 3):
        mean = _flat(rng, 1.0 + 0.1 * rnd)
        live = strat.apply(prev, mean, round_no=rnd)
        p32 = {k: np.asarray(ref_prev[k], np.float32) for k in sorted(mean)}
        g = {k: p32[k] - np.asarray(mean[k], np.float32) for k in sorted(mean)}
        if opt_state is None:
            opt_state = tx.init(p32)
        updates, opt_state = tx.update(g, opt_state, p32)
        ref = optax.apply_updates(p32, updates)
        ref_prev = {k: np.asarray(ref[k], np.float32) for k in sorted(ref)}
        for k in mean:
            np.testing.assert_array_equal(live[k], ref_prev[k])
        prev = live


def test_server_opt_resets_on_first_round_and_shape_change():
    rng = np.random.default_rng(3)
    strat = FedOpt(opt="adam", lr=0.1)
    mean = _flat(rng)
    out = strat.apply(None, mean)  # no global yet: the mean IS the global
    assert out is mean and strat._opt_state is None
    strat.apply(out, _flat(rng, 2.0), round_no=2)
    assert strat._opt_state is not None
    # Shape change (model swap): adopt the new mean, restart the state.
    grown = {"w": np.ones((8, 8), np.float32)}
    out = strat.apply(mean, grown, round_no=3)
    assert out is grown and strat._opt_state is None


def test_headboost_boosts_exactly_the_matching_leaves():
    prev = {
        "classifier/w": np.zeros(3, np.float32),
        "encoder/w": np.zeros(3, np.float32),
    }
    mean = {
        "classifier/w": np.ones(3, np.float32),
        "encoder/w": np.ones(3, np.float32),
    }
    out = HeadBoost(gamma=2.0).apply(prev, mean)
    np.testing.assert_array_equal(out["classifier/w"], np.full(3, 2.0))
    np.testing.assert_array_equal(out["encoder/w"], np.ones(3))
    # No previous global to measure an update against: exact FedAvg.
    assert HeadBoost(gamma=2.0).apply(None, mean) is mean
    # No leaf matches: exact FedAvg values.
    out = HeadBoost(gamma=2.0, match="does-not-exist").apply(prev, mean)
    for k in mean:
        np.testing.assert_array_equal(out[k], mean[k])


# ------------------------------------------------- StreamAgg strategy stats
def _register_dense(agg, cid, flat, n_samples):
    agg.register(
        cid, keys=tuple(sorted(flat)), n_samples=n_samples
    )
    agg.add_dense(cid, flat)


def test_stream_agg_client_stats_snapshot_and_weights():
    rng = np.random.default_rng(4)
    agg = StreamAgg()
    _register_dense(agg, 0, _flat(rng), 40)  # honest
    _register_dense(agg, 1, _flat(rng), 10)  # lazy: 0.25x the rows
    stats = agg.client_stats()
    assert sorted(stats) == [0, 1]
    assert stats[0]["weight"] == 40.0 and stats[1]["weight"] == 10.0
    assert stats[0]["bytes"] > 0 and stats[0]["scale"] == 1.0
    stats[0]["weight"] = -1  # snapshot copy: the round's view is frozen
    assert agg.client_stats()[0]["weight"] == 40.0


def test_stream_agg_drop_before_fold_purges_strategy_stats():
    rng = np.random.default_rng(5)
    agg = StreamAgg()
    _register_dense(agg, 0, _flat(rng), 10)
    _register_dense(agg, 1, _flat(rng), 10)
    assert agg.drop_client(1) is True  # nothing folded: clean removal
    assert sorted(agg.client_stats()) == [0]
    agg.stats()  # invariant: strategy stats ⊆ intents (would assert)
    mean = agg.finalize([0], [10.0])  # single survivor round
    strat = Momentum(lr=1.0, momentum=0.9)
    out = strat.apply(None, mean)
    assert out is mean  # first-global adoption, crc-preserving


def test_stream_agg_poisoned_drop_still_purges_strategy_stats():
    """A folded contributor dying poisons the round — but the strategy
    view must not keep the ghost: stats die with the intent even on the
    failure path (the stats() invariant)."""
    rng = np.random.default_rng(6)
    agg = StreamAgg()
    _register_dense(agg, 0, _flat(rng), 10)
    _register_dense(agg, 1, _flat(rng), 10)
    agg.freeze([0, 1], [10.0, 10.0])  # both complete: every leaf folds
    assert agg.drop_client(0) is False
    assert agg.poisoned and "leaf folds already consumed" in agg.poisoned
    assert sorted(agg.client_stats()) == [1]
    agg.stats()  # invariant holds on the poisoned path too


def test_all_lazy_fleet_weights_still_normalize():
    """Every client lazy (tiny but nonzero sample counts): the fold
    normalizes over the small weights and the strategies see the round
    through client_stats unchanged."""
    rng = np.random.default_rng(7)
    agg = StreamAgg()
    flats = [_flat(rng), _flat(rng), _flat(rng)]
    for cid, f in enumerate(flats):
        _register_dense(agg, cid, f, 2)  # all-lazy: equal tiny shards
    mean = agg.finalize([0, 1, 2], [2.0, 2.0, 2.0])
    expected = {
        k: (flats[0][k] / 3 + flats[1][k] / 3 + flats[2][k] / 3)
        for k in flats[0]
    }
    for k in expected:
        np.testing.assert_allclose(mean[k], expected[k], rtol=1e-5)
    stats = agg.client_stats()
    assert [stats[c]["weight"] for c in (0, 1, 2)] == [2.0, 2.0, 2.0]


# ----------------------------------------------------- server wiring guards
def test_server_refuses_strategy_with_secure_agg_and_dp():
    with pytest.raises(ValueError, match="secure aggregation"):
        AggregationServer(
            num_clients=2, secure_agg=True, strategy="momentum"
        )
    with pytest.raises(ValueError, match="central DP"):
        AggregationServer(num_clients=2, dp_clip=1.0, strategy="fedopt")
    with pytest.raises(ValueError, match="unknown strategy"):
        AggregationServer(num_clients=2, strategy="sgd")


def test_server_set_strategy_swaps_between_rounds():
    with AggregationServer(port=0, num_clients=1) as server:
        assert server.strategy.name == "fedavg"
        server.set_strategy("headboost:gamma=1.5")
        assert server.strategy.name == "headboost"
        assert server.strategy.gamma == pytest.approx(1.5)
    with AggregationServer(port=0, num_clients=2, dp_clip=1.0) as server:
        with pytest.raises(ValueError, match="secure-agg/DP"):
            server.set_strategy("momentum")


def test_root_refuses_relay_with_mismatched_strategy():
    """Split-brain guard: a relay stamping a different strategy id on
    its upward upload is refused loudly (the meta check fires before
    any round state is touched)."""
    with AggregationServer(port=0, num_clients=2) as server:
        with pytest.raises(wire.WireError, match="split-brain"):
            server._register_tree_meta(
                None, None, 7, {wire.STRATEGY_META_KEY: "momentum"}
            )
        # Matching stamp (dict form, as the relay sends it) passes.
        assert server._register_tree_meta(
            None, None, 7, {wire.STRATEGY_META_KEY: {"name": "fedavg"}}
        )
        # Absent stamp = old peer, accepted as-is.
        assert server._register_tree_meta(None, None, 7, {})


# ------------------------------------------------ live rounds, crc-pinned
def _live_round_bitexact(tmp_path, spec):
    """Two live loopback rounds: the transformed aggregate must be
    crc-pinned bit-exact against the strategy replay over the clean
    survivor mean — round 2 exercises the stateful prev-global path
    (momentum memory, adam moments, head deltas)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.faults.scenario import (
        CellSpec,
        ScenarioConfig,
        run_cell,
    )

    cfg = ScenarioConfig(
        num_clients=3, rounds=2, payload_kb=24, deadline_s=6.0,
        personas=("lazy",), partitions=("iid",),
    )
    res = run_cell(
        CellSpec(
            name=f"lazy|iid|{spec}",
            personas=("lazy", "honest", "honest"),
            partition="iid",
            strategy=spec,
        ),
        cfg,
        str(tmp_path),
    )
    assert [r.ok for r in res.rounds] == [True, True], res.notes
    for r in res.rounds:
        assert r.bitexact is True, (spec, r, res.notes)
    assert res.rounds[-1].contributors == [0, 1, 2]


def test_live_round_bitexact_momentum(tmp_path):
    """The fast lane's one live strategy cell: momentum is the fully
    stateful representative (server optimizer memory across rounds)."""
    _live_round_bitexact(tmp_path, "momentum:lr=1.0,momentum=0.6")


@pytest.mark.slow
@pytest.mark.parametrize(
    "spec",
    ["fedprox:mu=0.5", "fedopt:opt=yogi,lr=0.1", "headboost:gamma=2.0"],
)
def test_live_round_bitexact_per_strategy(tmp_path, spec):
    _live_round_bitexact(tmp_path, spec)


# --------------------------------------------------- FedProx client engine
def _batch(mcfg, rng, B=8):
    L = mcfg.max_len
    return {
        "input_ids": rng.integers(
            0, mcfg.vocab_size, (B, L)
        ).astype(np.int32),
        "attention_mask": np.ones((B, L), np.int32),
        "labels": rng.integers(0, 2, B).astype(np.int32),
    }


@pytest.mark.slow
def test_prox_step_vanishes_at_anchor_and_pulls_at_large_mu():
    """At params == anchor the proximal gradient mu*(p - anchor) is
    exactly zero, so the first prox step matches the plain step; a large
    mu then keeps the trajectory measurably closer to the anchor."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train import (
        Trainer,
    )

    mcfg = ModelConfig.tiny()
    rng = np.random.default_rng(8)
    batch = _batch(mcfg, rng)

    def run(mu, steps):
        tr = Trainer(
            mcfg, TrainConfig(learning_rate=1e-3, seed=0, prox_mu=mu)
        )
        state = tr.init_state(seed=0)
        anchor = jax.tree.map(jnp.copy, state.params)
        for _ in range(steps):
            if mu > 0.0:
                state, _ = tr.train_step(state, batch, anchor)
            else:
                state, _ = tr.train_step(state, batch)
        dist = sum(
            float(np.abs(np.asarray(p) - np.asarray(a)).sum())
            for p, a in zip(
                jax.tree.leaves(state.params), jax.tree.leaves(anchor)
            )
        )
        return tr.host_params(state), dist

    # One mu for both halves keeps this at two compiled programs: the
    # prox gradient mu*(p - anchor) is exactly zero at p == anchor no
    # matter how large mu is.
    plain, d_plain = run(0.0, 1)
    prox, _ = run(50.0, 1)
    for a, b in zip(jax.tree.leaves(plain), jax.tree.leaves(prox)):
        np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5)
    _, d_free = run(0.0, 5)
    _, d_anchored = run(50.0, 5)
    assert d_anchored < d_free * 0.9, (d_anchored, d_free)


def test_adopted_aggregate_becomes_the_next_prox_anchor():
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train import (
        Trainer,
    )

    mcfg = ModelConfig.tiny()
    tr = Trainer(mcfg, TrainConfig(learning_rate=1e-3, seed=0, prox_mu=0.1))
    state = tr.init_state(seed=0)
    assert tr._prox_anchor is None
    agg = jax.tree.map(
        lambda p: np.asarray(p) + 0.5, tr.host_params(state)
    )
    state = tr.adopt_aggregate(state, agg)
    anchor = tr._round_anchor(state)
    for a, p in zip(jax.tree.leaves(anchor), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(p))


@pytest.mark.slow
def test_fsdp_prox_trajectory_matches_replicated(eight_devices):
    """`--fsdp --strategy fedprox` composition: the prox term rides the
    RAW (shard-at-rest) params outside the remat region, so the FSDP
    trajectory must track the replicated engine's within reduction-order
    ulps — and the prox pull must actually be active (differ from the
    mu=0 trajectory)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        default_tokenizer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.mesh import (
        make_host_mesh,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train import (
        Trainer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.client_mesh import (
        FsdpMeshTrainer,
    )

    tok = default_tokenizer()
    L = 32
    mcfg = ModelConfig.tiny(
        vocab_size=len(tok.vocab), max_len=L, max_position_embeddings=2 * L
    )
    tcfg = TrainConfig(
        prng_impl="threefry2x32", learning_rate=1e-3, epochs_per_round=1,
        log_every=0, seed=0, prox_mu=0.05,
    )
    rng = np.random.default_rng(9)
    split = TokenizedSplit(
        rng.integers(0, mcfg.vocab_size, (48, L)).astype(np.int32),
        np.ones((48, L), np.int32),
        rng.integers(0, 2, 48).astype(np.int32),
    )

    def run(trainer):
        state, losses = trainer.fit(
            trainer.init_state(), split, batch_size=8
        )
        return trainer.host_params(state), losses

    h_plain, l_plain = run(Trainer(mcfg, tcfg, pad_id=tok.pad_id))
    h_fsdp, l_fsdp = run(
        FsdpMeshTrainer(
            mcfg, tcfg, mesh=make_host_mesh(2), pad_id=tok.pad_id
        )
    )
    np.testing.assert_allclose(l_plain, l_fsdp, rtol=1e-5)
    # Wider than the mu=0 pin (2e-6, test_mesh_fsdp): the prox-grad
    # term's reduce-scatter rounding feeds Adam's rsqrt every step, so
    # the reduction-order ulps compound over the epoch. Still fp32
    # noise, not divergence — the per-epoch loss above is equal.
    for a, b in zip(jax.tree.leaves(h_plain), jax.tree.leaves(h_fsdp)):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=1e-4)
    import dataclasses

    h_free = run(
        Trainer(
            mcfg, dataclasses.replace(tcfg, prox_mu=0.0), pad_id=tok.pad_id
        )
    )
    deltas = [
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(jax.tree.leaves(h_plain), jax.tree.leaves(h_free))
    ]
    assert max(deltas) > 0.0  # mu=0.05 measurably bends the trajectory
