"""Control plane (control/ + registry/ + serving pointer): drift math,
the drift monitor's JSONL tail, and the end-to-end unattended loop —
consecutive live TCP rounds with no human re-run, the eval gate blocking
a corrupted candidate (pointer unchanged), the serving tier scoring via
the promoted artifact only, and a drift verdict triggering a round."""

import json
import threading
import time

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
    FederatedClient,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    ControlConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.control import (
    Controller,
    DriftMonitor,
    ks_distance,
    psi,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.registry import (
    ModelRegistry,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.fedeval import (
    eval_gate,
    reference_histogram,
)

# ---------------------------------------------------------------- drift math
def test_psi_and_ks_distances():
    ref = [100, 50, 10, 5, 5, 5, 5, 10, 50, 100]
    assert psi(ref, ref) == pytest.approx(0.0, abs=1e-9)
    assert ks_distance(ref, ref) == pytest.approx(0.0, abs=1e-12)
    # Scale invariance: 3x the traffic, same distribution.
    assert psi(ref, [3 * c for c in ref]) == pytest.approx(0.0, abs=1e-9)
    shifted = [0, 0, 0, 0, 170, 170, 0, 0, 0, 0]
    assert psi(ref, shifted) > 0.25
    assert ks_distance(ref, shifted) > 0.25
    with pytest.raises(ValueError):
        psi(ref, [1, 2, 3])  # bin count mismatch
    with pytest.raises(ValueError):
        psi([0] * 10, ref)  # reference with no mass


def test_reference_histogram_binning():
    h = reference_histogram([0.05, 0.95, 0.96, 1.0, 0.0], bins=10)
    assert h.tolist() == [2, 0, 0, 0, 0, 0, 0, 0, 0, 3]
    assert h.sum() == 5


def test_eval_gate_verdicts():
    ok, _ = eval_gate({"Accuracy": 0.9}, None)
    assert ok  # bootstrap
    ok, _ = eval_gate({"Accuracy": 0.9}, {"Accuracy": 0.8})
    assert ok
    ok, reason = eval_gate({"Accuracy": 0.7}, {"Accuracy": 0.8})
    assert not ok and "regression" in reason
    ok, _ = eval_gate(
        {"Accuracy": 0.75}, {"Accuracy": 0.8}, min_delta=0.1
    )
    assert ok  # inside the tolerated delta
    # Corruption fails CLOSED: NaN or missing metric never promotes.
    ok, _ = eval_gate({"Accuracy": float("nan")}, None)
    assert not ok
    ok, _ = eval_gate({}, {"Accuracy": 0.5})
    assert not ok


# ------------------------------------------------------------- drift monitor
def test_drift_monitor_fires_on_shift_and_stays_quiet_on_iid():
    ref = [400, 200, 50, 20, 10, 10, 20, 50, 200, 400]
    dm = DriftMonitor(reference=ref, threshold=0.25, min_scores=200)
    # IID traffic (the reference distribution itself, rescaled): quiet.
    dm.observe([40, 20, 5, 2, 1, 1, 2, 5, 20, 40])
    assert dm.check() is None
    dm.observe([200, 100, 25, 10, 5, 5, 10, 25, 100, 200])
    assert dm.check() is None
    # Injected shift: mass collapses to the middle bins.
    dm.reset_window()
    dm.observe([0, 0, 0, 150, 150, 150, 150, 0, 0, 0])
    verdict = dm.check()
    assert verdict is not None
    assert verdict["drift"] >= 0.25 and verdict["scores"] == 600
    assert dm.observed_scores == 0  # fired verdict resets the window


def test_drift_monitor_needs_min_scores():
    dm = DriftMonitor(reference=[10, 10], threshold=0.1, min_scores=100)
    dm.observe([99, 0])
    assert dm.check() is None  # massively shifted but too few scores
    dm.observe([99, 0])
    assert dm.check() is not None


def test_drift_monitor_tails_serving_jsonl(tmp_path):
    """The cross-process wiring: infer-serve appends serve_batch records
    with score_hist; the monitor ingests incrementally and tolerates a
    partially-flushed trailing line."""
    path = str(tmp_path / "metrics.jsonl")
    ref = [500, 0, 0, 0, 0, 0, 0, 0, 0, 500]
    dm = DriftMonitor(path, reference=ref, threshold=0.25, min_scores=64)
    assert dm.poll() is None  # file doesn't exist yet

    def rec(hist):
        return json.dumps({"phase": "serve_batch", "score_hist": hist})

    with open(path, "w") as f:
        f.write(rec([16, 0, 0, 0, 0, 0, 0, 0, 0, 16]) + "\n")
        f.write(json.dumps({"phase": "serve_summary"}) + "\n")  # ignored
    assert dm.poll() is None and dm.observed_scores == 32
    with open(path, "a") as f:
        f.write(rec([0, 0, 0, 0, 32, 32, 0, 0, 0, 0]) + "\n")
        f.write('{"phase": "serve_batch", "score_hi')  # torn tail
    assert dm.poll() is not None  # 96 >= 64 scores, shifted
    assert dm.observed_scores == 0
    with open(path, "a") as f:  # complete the torn line
        f.write('st": [16, 0, 0, 0, 0, 0, 0, 0, 0, 16]}\n')
    assert dm.poll() is None  # ingested, but below min_scores again
    assert dm.observed_scores == 32


# -------------------------------------------------------------- live helpers
def _mean_eval(params):
    """Synthetic held-out eval: 'accuracy' tracks the mean weight (the
    fleet's uploads push it up each round), with probs for the reference
    histogram. A NaN aggregate yields a NaN metric — exactly what a real
    eval of corrupted params produces."""
    w = params["w"]
    mean = float(np.asarray(w, np.float64).mean())
    acc = mean if np.isfinite(mean) else float("nan")
    rng = np.random.default_rng(7)
    return {"Accuracy": acc, "probs": rng.uniform(0, 1, 128)}


# ------------------------------------------------------------- e2e: rounds
def test_controller_runs_consecutive_live_rounds_unattended(tmp_path):
    """Two consecutive live TCP rounds with no human re-run: the
    controller owns the cadence, every round lands as an artifact, the
    improving candidate promotes each time, and the state JSONL replays
    into a resumed controller."""
    registry = ModelRegistry(str(tmp_path / "reg"))
    state = str(tmp_path / "state.jsonl")
    errors = []
    with AggregationServer(port=0, num_clients=2, timeout=30) as server:
        controller = Controller(
            server,
            registry,
            _mean_eval,
            control=ControlConfig(round_deadline_s=20.0),
            state_path=state,
        )

        def uploads(r, cid, cur):
            base = np.zeros(16, np.float32) if cur is None else cur["w"]
            return {"w": base + np.float32(0.1 * (r + 1))}

        def loop(cid):
            try:
                fc = FederatedClient(
                    "127.0.0.1", server.port, client_id=cid, timeout=30
                )
                cur = None
                for r in range(2):
                    cur = fc.exchange(uploads(r, cid, cur))
            except Exception as e:
                errors.append(e)

        threads = [
            threading.Thread(target=loop, args=(c,), daemon=True)
            for c in range(2)
        ]
        for t in threads:
            t.start()
        stats = controller.run(max_rounds=2)
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors
    assert stats.rounds_completed == 2
    assert stats.promotions == 2 and stats.gate_rejections == 0
    arts = registry.list()
    assert len(arts) == 2
    serving = registry.serving_manifest()
    assert serving["round"] == 1  # the second (better) round serves
    assert serving["eval_hist"] is not None
    events = [json.loads(ln) for ln in open(state)]
    assert [e["event"] for e in events if e["event"] == "promoted"] == [
        "promoted",
        "promoted",
    ]
    # A restarted controller resumes mid-campaign: round counter continues.
    with AggregationServer(port=0, num_clients=2, timeout=5) as server2:
        resumed = Controller(
            server2, registry, _mean_eval, state_path=state
        )
    assert resumed._next_round == 2
    assert resumed.stats.promotions == 2


def test_eval_gate_blocks_corrupted_candidate_live(tmp_path):
    """Round 1 promotes; round 2's fleet uploads a NaN-corrupted model.
    The gate must reject it: serving pointer unchanged, candidate marked
    rejected, the refusal logged in the controller state (the automatic
    rollback-on-regression contract)."""
    registry = ModelRegistry(str(tmp_path / "reg"))
    state = str(tmp_path / "state.jsonl")
    errors = []
    with AggregationServer(port=0, num_clients=2, timeout=30) as server:
        controller = Controller(
            server,
            registry,
            _mean_eval,
            control=ControlConfig(round_deadline_s=20.0),
            state_path=state,
        )

        def loop(cid):
            try:
                fc = FederatedClient(
                    "127.0.0.1", server.port, client_id=cid, timeout=30
                )
                good = {"w": np.full(16, 0.5, np.float32)}
                fc.exchange(good)
                corrupt = {"w": np.full(16, np.nan, np.float32)}
                fc.exchange(corrupt)
            except Exception as e:
                errors.append(e)

        threads = [
            threading.Thread(target=loop, args=(c,), daemon=True)
            for c in range(2)
        ]
        for t in threads:
            t.start()
        stats = controller.run(max_rounds=2)
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors
    assert stats.rounds_completed == 2
    assert stats.promotions == 1 and stats.gate_rejections == 1
    serving = registry.serving_info()
    good_id = serving["artifact"]
    manifests = {m["id"]: m for m in registry.list()}
    assert manifests[good_id]["round"] == 0  # pointer never moved
    rejected = [m for m in manifests.values() if m["state"] == "rejected"]
    assert len(rejected) == 1 and rejected[0]["round"] == 1
    events = [json.loads(ln) for ln in open(state)]
    rej = [e for e in events if e["event"] == "gate_rejected"]
    assert len(rej) == 1
    assert rej[0]["incumbent"] == good_id
    assert "not finite" in rej[0]["reason"]


def test_drift_verdict_triggers_the_next_round(tmp_path):
    """Purely drift-driven cadence (no clock): after the bootstrap round
    promotes, the controller idles until a shifted score distribution is
    injected into the monitor — then exactly one more round runs, tagged
    with the drift trigger."""
    registry = ModelRegistry(str(tmp_path / "reg"))
    state = str(tmp_path / "state.jsonl")
    dm = DriftMonitor(threshold=0.25, min_scores=64)
    errors = []
    with AggregationServer(port=0, num_clients=2, timeout=30) as server:
        controller = Controller(
            server,
            registry,
            _mean_eval,
            control=ControlConfig(round_deadline_s=20.0),
            state_path=state,
            drift_monitor=dm,
            drift_poll_s=0.05,
        )

        def loop(cid):
            try:
                fc = FederatedClient(
                    "127.0.0.1", server.port, client_id=cid, timeout=30
                )
                out = fc.exchange({"w": np.full(16, 0.5, np.float32)})
                out = fc.exchange(
                    {"w": out["w"] + np.float32(0.1)}
                )
            except Exception as e:
                errors.append(e)

        threads = [
            threading.Thread(target=loop, args=(c,), daemon=True)
            for c in range(2)
        ]
        for t in threads:
            t.start()
        run_t = threading.Thread(
            target=lambda: controller.run(max_rounds=2), daemon=True
        )
        run_t.start()
        # Wait for the bootstrap promotion (it installs the drift
        # reference), then inject live-traffic shift.
        deadline = time.monotonic() + 20
        while registry.serving_info() is None:
            assert time.monotonic() < deadline, "bootstrap round never promoted"
            time.sleep(0.05)
        time.sleep(0.3)  # let the controller enter its drift wait
        assert controller.stats.rounds_completed == 1
        shifted = np.zeros(10, np.int64)
        shifted[4:6] = 64
        dm.observe(shifted)
        run_t.join(timeout=30)
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors
    assert controller.stats.rounds_completed == 2
    assert controller.stats.drift_triggers == 1
    events = [json.loads(ln) for ln in open(state)]
    assert any(e["event"] == "drift_trigger" for e in events)
    second = [
        e for e in events if e["event"] == "promoted" and e["round"] == 1
    ]
    assert second and second[0]["trigger"] == "drift"


# ------------------------------------------- serving follows the pointer
def test_serving_tier_scores_via_promoted_artifact_only(tmp_path):
    """A live scoring process over a RegistryWatcher: an unpromoted
    candidate never reaches traffic; promotion hot-swaps within one poll;
    rollback swaps back — all with no serving restart."""
    import dataclasses

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        default_tokenizer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
        RegistryWatcher,
        ScoreEngine,
        ScoringClient,
        ScoringServer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
        Trainer,
    )

    tok = default_tokenizer()
    model_cfg = ModelConfig.tiny(vocab_size=len(tok.vocab))
    trainer = Trainer(model_cfg, TrainConfig(), pad_id=tok.pad_id)
    params_a = trainer.init_state(seed=0).params
    params_b = trainer.init_state(seed=1).params

    registry = ModelRegistry(str(tmp_path / "reg"))
    mc = dataclasses.asdict(model_cfg)
    a = registry.add(params_a, round_index=0, model_config=mc)
    registry.promote(a, to="serving")

    engine = ScoreEngine(
        model_cfg,
        registry.load_params(a),
        pad_id=tok.pad_id,
        buckets=(1, 4),
        round_id=0,
    )
    watcher = RegistryWatcher(registry, poll_interval_s=0.05)
    watcher.prime(a)
    text = "Destination port is 80. Flow duration is 100 microseconds."
    with ScoringServer(
        engine, tok, batcher=None, watcher=watcher, idle_tick_s=0.01
    ) as server:
        with ScoringClient("127.0.0.1", server.port) as cli:
            r1 = cli.score(text=text)
            assert r1["round"] == 0
            # A CANDIDATE lands in the registry: must NOT be served.
            b = registry.add(params_b, round_index=1, model_config=mc)
            time.sleep(0.3)
            r2 = cli.score(text=text)
            assert r2["round"] == 0 and r2["prob"] == r1["prob"]
            assert watcher.reload_count == 0
            # Promotion: the pointer swap reaches traffic within a poll.
            registry.promote(b, to="serving")
            deadline = time.monotonic() + 10
            while watcher.reload_count == 0:
                assert time.monotonic() < deadline, "promotion never served"
                time.sleep(0.05)
            r3 = cli.score(text=text)
            assert r3["round"] == 1 and r3["prob"] != r1["prob"]
            # Rollback: one atomic swap back, again with no restart.
            registry.rollback()
            deadline = time.monotonic() + 10
            while watcher.reload_count == 1:
                assert time.monotonic() < deadline, "rollback never served"
                time.sleep(0.05)
            r4 = cli.score(text=text)
            assert r4["round"] == 0 and r4["prob"] == r1["prob"]


def test_drift_monitor_survives_malformed_jsonl_counts(tmp_path):
    """A corrupt record (negative counts) in the tailed JSONL must be
    skipped at ingestion — never accumulate and crash the controller's
    poll loop at verdict time."""
    path = str(tmp_path / "metrics.jsonl")
    dm = DriftMonitor(
        path, reference=[10, 10], threshold=0.1, min_scores=8
    )
    with open(path, "w") as f:
        f.write(
            json.dumps(
                {"phase": "serve_batch", "score_hist": [-1, 300]}
            )
            + "\n"
        )
        f.write(
            json.dumps({"phase": "serve_batch", "score_hist": [8, 0]})
            + "\n"
        )
    verdict = dm.poll()  # must not raise; only the clean record counts
    assert dm.observed_scores == 0 if verdict else True
    assert verdict is not None and verdict["scores"] == 8


def test_round_engine_errors_do_not_kill_the_campaign(tmp_path):
    """A WireError escaping serve_round (malformed upload surviving to
    aggregation) is a failed ROUND, not a dead daemon — same contract the
    serve CLI loop has always had."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.wire import (
        WireError,
    )

    class FlakyServer:
        dp_clip = 0.0

        def __init__(self):
            self.calls = 0

        def serve_round(self, *, deadline=None, round_index=None):
            self.calls += 1
            if self.calls == 1:
                raise WireError("model 1 key set differs from model 0")
            return {"w": np.full(8, 0.5, np.float32)}

    registry = ModelRegistry(str(tmp_path / "reg"))
    ctl = Controller(
        FlakyServer(),
        registry,
        _mean_eval,
        state_path=str(tmp_path / "state.jsonl"),
    )
    stats = ctl.run(max_rounds=2)
    assert stats.rounds_failed == 1 and stats.rounds_completed == 1
    assert registry.serving_info() is not None


def test_drift_wait_without_reference_falls_back_to_the_clock(tmp_path):
    """A serving artifact with no eval histogram (mesh-tier publish +
    hand promote) must not idle a drift-driven campaign forever: the
    controller runs a clock round, whose promotion re-anchors drift."""
    registry = ModelRegistry(str(tmp_path / "reg"))
    a = registry.add({"w": np.zeros(8, np.float32)}, round_index=0)
    registry.promote(a, to="serving")
    assert registry.serving_manifest()["eval_hist"] is None

    class OneShotServer:
        dp_clip = 0.0

        def serve_round(self, *, deadline=None, round_index=None):
            return {"w": np.full(8, 0.5, np.float32)}

    dm = DriftMonitor(threshold=0.25, min_scores=8)
    ctl = Controller(
        OneShotServer(),
        registry,
        _mean_eval,
        state_path=str(tmp_path / "state.jsonl"),
        drift_monitor=dm,
        drift_poll_s=0.05,
    )
    assert not dm.has_reference
    stats = ctl.run(max_rounds=1)  # would hang forever without the fallback
    assert stats.rounds_completed == 1
    assert dm.has_reference  # the promoted round re-anchored the monitor


def test_eval_or_registry_errors_do_not_kill_the_campaign(tmp_path):
    """A post-round failure (eval of a foreign-architecture aggregate, a
    failed registry write) is one bad CYCLE, not a dead daemon: the
    pointer stays put and the next cycle proceeds."""
    calls = [0]

    def flaky_eval(params):
        calls[0] += 1
        if calls[0] == 1:
            raise TypeError("foreign architecture: missing encoder scope")
        return _mean_eval(params)

    class Srv:
        dp_clip = 0.0

        def __init__(self):
            self.n = 0

        def serve_round(self, *, deadline=None, round_index=None):
            self.n += 1
            return {"w": np.full(8, float(self.n), np.float32)}

    registry = ModelRegistry(str(tmp_path / "reg"))
    state = str(tmp_path / "state.jsonl")
    ctl = Controller(Srv(), registry, flaky_eval, state_path=state)
    stats = ctl.run(max_rounds=2)
    assert stats.rounds_completed == 2 and stats.promotions == 1
    assert registry.serving_info() is not None
    events = [json.loads(ln) for ln in open(state)]
    assert [e["event"] for e in events] == ["cycle_error", "promoted"]
    # Resume replay counts the errored cycle consistently.
    resumed = Controller(Srv(), registry, flaky_eval, state_path=state)
    assert resumed.stats.rounds_attempted == 2
    assert resumed.stats.rounds_completed == 2
