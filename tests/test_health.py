"""Fleet health plane (ISSUE 11): SLO burn-rate fire/clear state
machines over synthetic histogram deltas (no sleeps), the live loopback
multi-daemon scrape hub, the failure flight recorder's dump-on-failure
contract, drift localization, and the `fedtpu obs health|postmortem`
CLIs.

All host-side (sockets + stdlib HTTP + JSONL) — no JAX programs — so the
whole module stays in the fast lane.
"""

import json
import os
import socket
import threading

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.server import (
    AggregationServer,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.control.drift import (
    DriftMonitor,
    psi,
    psi_contributions,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
    SLO,
    AlertManager,
    FlightRecorder,
    MetricsRegistry,
    MetricsServer,
    ScrapeHub,
    Target,
    Tracer,
    default_slos,
    list_bundles,
    load_bundle,
    parse_target,
    set_global_recorder,
    slos_from_spec,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.flight import (
    BUNDLE_SCHEMA,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.slo import (
    extract_bad_total,
)


def _latency_families(good: int, bad: int) -> dict:
    """A fedtpu_server_round_seconds snapshot: ``good`` observations at
    or under the 0.5 s edge, ``bad`` above it (cumulative buckets, the
    obs/metrics.py snapshot shape)."""
    total = good + bad
    return {
        "fedtpu_server_round_seconds": {
            "type": "histogram",
            "help": "",
            "samples": [
                {
                    "labels": {},
                    "buckets": [
                        ["0.1", 0],
                        ["0.5", good],
                        ["5", total],
                        ["+Inf", total],
                    ],
                    "sum": 1.0,
                    "count": total,
                }
            ],
        }
    }


def _ratio_families(bad: int, total: int) -> dict:
    return {
        "fedtpu_server_stream_fallbacks_total": {
            "type": "counter",
            "help": "",
            "samples": [{"labels": {}, "value": bad}],
        },
        "fedtpu_server_uploads_total": {
            "type": "counter",
            "help": "",
            "samples": [{"labels": {}, "value": total}],
        },
    }


_SLO = SLO(
    name="round-duration",
    metric="fedtpu_server_round_seconds",
    kind="latency",
    le=0.5,
    objective=0.9,
    windows=((120.0, 6.0), (30.0, 6.0)),
)


# ------------------------------------------------- burn-rate state machine
def test_burn_alert_fires_and_clears_on_synthetic_deltas(tmp_path):
    """The acceptance state machine, sleep-free: cumulative snapshots in,
    fire when EVERY window breaches, clear when the short window drains;
    fire/clear land on the alerts-JSONL as atomic JSON lines."""
    sink = tmp_path / "alerts.jsonl"
    am = AlertManager((_SLO,), sink_path=str(sink))
    am.ingest(_latency_families(good=5, bad=0), now=0.0)
    assert am.evaluate(now=0.0) == []  # single point: no delta, no burn
    # 4 bad events inside both windows: bad_frac 1.0 / budget 0.1 = 10x.
    am.ingest(_latency_families(good=5, bad=4), now=10.0)
    events = am.evaluate(now=10.0)
    assert [e["event"] for e in events] == ["fire"]
    assert events[0]["slo"] == "round-duration"
    assert events[0]["severity"] == "page"
    assert all(v >= 6.0 for v in events[0]["burn"].values())
    assert am.fired_total == 1
    # Still firing while the short window holds bad events: no new event.
    am.ingest(_latency_families(good=5, bad=5), now=20.0)
    assert am.evaluate(now=20.0) == []
    # 40s later: fresh good traffic only inside the 30s window -> clear
    # (the long window still remembers the burn; clear is short-window).
    am.ingest(_latency_families(good=20, bad=5), now=60.0)
    events = am.evaluate(now=60.0)
    assert [e["event"] for e in events] == ["clear"]
    assert am.cleared_total == 1
    lines = [json.loads(ln) for ln in sink.read_text().splitlines()]
    assert [r["event"] for r in lines] == ["fire", "clear"]
    assert all(r["schema"] == "fedtpu-alert-v1" for r in lines)


def test_burn_alert_needs_every_window_to_breach():
    """Multi-window AND: a burst that already left the short window must
    NOT fire (that is the whole point of the two-window pattern)."""
    am = AlertManager((_SLO,))
    am.ingest(_latency_families(good=0, bad=0), now=0.0)
    am.ingest(_latency_families(good=0, bad=4), now=10.0)
    # 80s later the bad burst is outside the 30s window (only good
    # events in it) but still inside the 120s one.
    am.ingest(_latency_families(good=50, bad=4), now=90.0)
    assert am.evaluate(now=90.0) == []
    assert am.states()[0]["firing"] is False


def test_ratio_slo_and_no_traffic_burns_nothing():
    slo = SLO(
        name="stream-fallback-ratio",
        metric="fedtpu_server_stream_fallbacks_total",
        kind="ratio",
        total="fedtpu_server_uploads_total",
        objective=0.9,
        windows=((120.0, 2.0), (30.0, 2.0)),
        severity="ticket",
    )
    am = AlertManager((slo,))
    am.ingest(_ratio_families(bad=0, total=10), now=0.0)
    am.evaluate(now=0.0)
    am.ingest(_ratio_families(bad=8, total=20), now=10.0)
    events = am.evaluate(now=10.0)
    assert [e["event"] for e in events] == ["fire"]
    assert events[0]["severity"] == "ticket"
    # A trafficless window (no new uploads at all) burns nothing: the
    # firing alert clears once bad events STOP, by definition.
    am.ingest(_ratio_families(bad=8, total=20), now=60.0)
    assert [e["event"] for e in am.evaluate(now=60.0)] == ["clear"]


def test_counter_reset_drops_history_instead_of_phantom_burn():
    am = AlertManager((_SLO,))
    am.ingest(_latency_families(good=50, bad=5), now=0.0)
    am.evaluate(now=0.0)
    # Daemon restart: cumulative counts fall. The series must restart,
    # not compute negative/phantom deltas.
    am.ingest(_latency_families(good=1, bad=0), now=10.0)
    assert am.evaluate(now=10.0) == []
    am.ingest(_latency_families(good=5, bad=0), now=20.0)
    assert am.evaluate(now=20.0) == []
    assert am.states()[0]["firing"] is False


def test_page_fire_trips_flight_recorder(tmp_path):
    rec = FlightRecorder(
        str(tmp_path / "flight"), proc="hub", min_interval_s=0.0
    )
    am = AlertManager((_SLO,), recorder=rec)
    am.ingest(_latency_families(good=0, bad=0), now=0.0)
    am.ingest(_latency_families(good=0, bad=4), now=10.0)
    am.evaluate(now=10.0)
    bundles = list_bundles(str(tmp_path / "flight"))
    assert len(bundles) == 1 and bundles[0]["reason"] == "slo-page"
    b = load_bundle(bundles[0]["path"])
    assert b["schema"] == BUNDLE_SCHEMA
    # The firing alert itself rides in the bundle.
    assert any(a["event"] == "fire" for a in b["alerts"])


def test_slo_validation_and_spec_roundtrip():
    with pytest.raises(ValueError):
        SLO(name="x", metric="m", kind="latency")  # latency needs le
    with pytest.raises(ValueError):
        SLO(name="x", metric="m", kind="ratio")  # ratio needs total
    with pytest.raises(ValueError):
        SLO(name="x", metric="m", le=1.0, objective=1.0)
    with pytest.raises(ValueError):
        SLO(name="x", metric="m", le=1.0, windows=())
    with pytest.raises(ValueError):
        AlertManager((_SLO, _SLO))  # duplicate names
    spec = [
        {
            "name": "a",
            "metric": "fedtpu_server_round_seconds",
            "le": 1.0,
            "windows": [[60.0, 2.0], [10.0, 2.0]],
        }
    ]
    (slo,) = slos_from_spec(spec)
    assert slo.windows == ((60.0, 2.0), (10.0, 2.0))
    assert slo.shortest_window == (10.0, 2.0)
    # Families the target never exports are "not my tier", not an error.
    assert extract_bad_total(slo, {}) is None
    assert default_slos()  # the stock objectives construct


# --------------------------------------------------------- scrape hub
@pytest.fixture(scope="module")
def live_fleet(tmp_path_factory):
    """Two live /metrics.json daemons on private registries (an FL
    server shape and a router shape) + one dead target. Module-scoped
    (HTTP server teardown costs ~1 s each): every test builds its own
    hub, and the first test below is the only one reading absolute
    counter values."""
    tmp_path = tmp_path_factory.mktemp("health-fleet")
    reg_serve = MetricsRegistry()
    reg_serve.counter("fedtpu_server_rounds_total").inc(3)
    reg_serve.counter("fedtpu_server_uploads_total").inc(6)
    h = reg_serve.histogram(
        "fedtpu_server_round_seconds", buckets=(0.1, 0.5, 5.0)
    )
    h.observe(0.2)
    reg_route = MetricsRegistry()
    reg_route.counter("fedtpu_router_forwarded_total").inc(100)
    reg_route.counter(
        "fedtpu_router_ejects_total", labels={"replica": "0"}
    ).inc(1)
    reg_route.gauge(
        "fedtpu_router_inflight", labels={"replica": "0"}
    ).set(2)
    reg_route.gauge(
        "fedtpu_router_inflight", labels={"replica": "1"}
    ).set(1)
    srv_a = MetricsServer(0, host="127.0.0.1", registry=reg_serve).start()
    srv_b = MetricsServer(0, host="127.0.0.1", registry=reg_route).start()
    # A port nothing listens on: the down target.
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()
    yield {
        "serve_reg": reg_serve,
        "serve": srv_a,
        "route": srv_b,
        "dead_port": dead_port,
        "dir": tmp_path,
    }
    srv_a.close()
    srv_b.close()


def test_scrape_hub_merges_live_multi_daemon_fleet(live_fleet):
    """The acceptance scrape test: one poll over two LIVE daemons + one
    dead target -> a merged snapshot keyed by (tier, instance) with
    up/down, per-target scrape lag, per-tier summaries, and the fleet
    snapshot JSONL on disk."""
    snap_path = live_fleet["dir"] / "fleet.jsonl"
    hub = ScrapeHub(
        [
            Target("serve", "127.0.0.1", live_fleet["serve"].port),
            Target("route", "127.0.0.1", live_fleet["route"].port),
            Target("relay", "127.0.0.1", live_fleet["dead_port"]),
        ],
        slos=(_SLO,),
        snapshot_jsonl=str(snap_path),
    )
    snap = hub.poll(now=0.0)
    by_key = {(t["tier"], t["instance"]): t for t in snap["targets"]}
    assert len(by_key) == 3
    serve_row = by_key[("serve", f"127.0.0.1:{live_fleet['serve'].port}")]
    route_row = by_key[("route", f"127.0.0.1:{live_fleet['route'].port}")]
    dead_row = by_key[("relay", f"127.0.0.1:{live_fleet['dead_port']}")]
    assert serve_row["up"] and route_row["up"] and not dead_row["up"]
    assert dead_row["error"]
    assert serve_row["summary"]["counters"][
        "fedtpu_server_rounds_total"
    ] == 3
    assert route_row["summary"]["gauges"]["fedtpu_router_inflight"] == {
        "replica=0": 2.0,
        "replica=1": 1.0,
    }
    for row in (serve_row, route_row):
        assert row["scrape_lag_ms"] is not None and row["scrape_lag_ms"] >= 0
    assert snap["scrape_lag_ms"] is not None
    assert hub.last_scrape_lag_ms == snap["scrape_lag_ms"]
    # Round cadence needs a second poll: 3 more rounds over 60s.
    live_fleet["serve_reg"].counter("fedtpu_server_rounds_total").inc(3)
    snap2 = hub.poll(now=60.0)
    serve_row2 = [t for t in snap2["targets"] if t["tier"] == "serve"][0]
    assert serve_row2["cadence"]["fedtpu_server_rounds_total"] == (
        pytest.approx(0.05)
    )  # 3 rounds / 60 s
    # The merged snapshot JSONL: one record per poll, schema-tagged.
    recs = [
        json.loads(ln) for ln in snap_path.read_text().splitlines()
    ]
    assert len(recs) == 2
    assert all(r["schema"] == "fedtpu-fleet-v1" for r in recs)
    assert {t["tier"] for t in recs[0]["targets"]} == {
        "serve", "route", "relay",
    }
    # Rendering: every tier + the DOWN marker + SLO block on one screen.
    screen = hub.render_status(snap2)
    assert "serve" in screen and "route" in screen and "DOWN" in screen
    assert "SLO burn" in screen and "round-duration" in screen
    assert "rounds/min" in screen


def test_scrape_hub_slo_fire_over_live_scrapes(live_fleet, tmp_path):
    """Burn alerts fire from actually-scraped deltas, the slo-eval span
    is emitted per poll, and alert events ride the snapshot record."""
    trace_path = tmp_path / "hub.jsonl"
    hub = ScrapeHub(
        [Target("serve", "127.0.0.1", live_fleet["serve"].port)],
        slos=(_SLO,),
        alerts_jsonl=str(tmp_path / "alerts.jsonl"),
        tracer=Tracer(str(trace_path), proc="obs-hub"),
    )
    hub.poll(now=0.0)
    h = live_fleet["serve_reg"].histogram(
        "fedtpu_server_round_seconds", buckets=(0.1, 0.5, 5.0)
    )
    for _ in range(4):
        h.observe(2.0)  # bad: above the 0.5s objective bound
    snap = hub.poll(now=10.0)
    assert [e["event"] for e in snap["events"]] == ["fire"]
    assert [s for s in snap["slo"] if s["firing"]]
    spans = [
        json.loads(ln) for ln in trace_path.read_text().splitlines()
    ]
    evals = [s for s in spans if s["span"] == "slo-eval"]
    assert len(evals) == 2
    assert evals[-1]["firing"] == 1 and evals[-1]["up"] == 1
    assert evals[-1]["scrape_lag_ms"] is not None


def test_scrape_hub_tails_events_jsonl_for_drift_and_postmortems(
    live_fleet, tmp_path
):
    """events=PATH targets surface span-level state: the controller's
    drift-trigger localization and flight-recorder dumps."""
    events = tmp_path / "ctl.jsonl"
    t = Tracer(str(events), proc="controller")
    t.record(
        "drift-trigger", t_start=1.0, dur_s=0.0, round=4,
        drift=0.31, method="psi",
        top_bins=[{"bin": 9, "psi": 0.25}],
    )
    t.record(
        "postmortem-dump", t_start=2.0, dur_s=0.01,
        reason="round-failure", bundle="b.json", spans=12,
    )
    t.record(
        "round", t_start=3.0, dur_s=1.2, trace="aa", round=4, failed=True,
    )
    hub = ScrapeHub(
        [
            Target(
                "controller",
                "127.0.0.1",
                live_fleet["serve"].port,
                events_jsonl=str(events),
            )
        ],
        slos=(_SLO,),
    )
    snap = hub.poll(now=0.0)
    row = snap["targets"][0]
    assert row["last_drift"]["drift"] == 0.31
    assert row["last_drift"]["top_bins"][0]["bin"] == 9
    assert row["postmortems"] == 1
    assert row["last_round_failed"] is True
    screen = hub.render_status(snap)
    assert "drift psi=0.31" in screen and "top_bins" in screen
    assert "postmortem bundle" in screen
    assert "LAST ROUND FAILED" in screen
    # render_status(None) — the no-scrape path — shows the same row
    # shape (one _row builder for both).
    assert "LAST ROUND FAILED" in hub.render_status(None)


def test_parse_target_shapes():
    t = parse_target("serve=127.0.0.1:9100")
    assert (t.tier, t.host, t.port, t.events_jsonl) == (
        "serve", "127.0.0.1", 9100, None,
    )
    assert t.url.endswith("/metrics.json")
    t = parse_target("route=10.0.0.2:9102,events=/var/log/r.jsonl")
    assert t.events_jsonl == "/var/log/r.jsonl"
    for bad in ("serve", "serve=127.0.0.1", "serve=h:x", "s=h:1,foo=bar"):
        with pytest.raises(ValueError):
            parse_target(bad)
    with pytest.raises(ValueError):
        ScrapeHub([])  # no targets
    with pytest.raises(ValueError):
        tgt = Target("serve", "127.0.0.1", 1)
        ScrapeHub([tgt, tgt])  # duplicate keys


# ------------------------------------------------------ flight recorder
def test_flight_recorder_dumps_on_live_round_failure(tmp_path):
    """The acceptance regression: a quorum-missed LIVE round dumps a
    postmortem bundle carrying the failed round's span, the trigger
    context, and the process /metrics state — with the recorder
    installed exactly as the CLI installs it (global)."""
    flight_dir = tmp_path / "flight"
    tracer = Tracer(str(tmp_path / "server.jsonl"), proc="server")
    rec = FlightRecorder(
        str(flight_dir), proc="server", tracer=tracer, min_interval_s=0.0
    )
    set_global_recorder(rec)
    try:
        server = AggregationServer(port=0, num_clients=2, timeout=30)
        server.tracer = tracer
        with pytest.raises(RuntimeError):
            server.serve_round(deadline=0.3)  # nobody connects
        server.close()
    finally:
        set_global_recorder(None)
    bundles = list_bundles(str(flight_dir))
    assert len(bundles) == 1
    assert bundles[0]["reason"] == "round-failure"
    b = load_bundle(bundles[0]["path"])
    assert b["extra"]["round"] == 0 and b["extra"]["expected"] == 2
    ring_spans = [s["span"] for s in b["spans"]]
    assert "round" in ring_spans  # the failed round itself is in the ring
    failed = [s for s in b["spans"] if s["span"] == "round"][-1]
    assert failed.get("failed") is True
    # Dump-time /metrics pull: the failure counter is in the bundle.
    fams = b["metrics_now"]["families"]
    assert fams["fedtpu_server_round_failures_total"]["samples"][0][
        "value"
    ] >= 1
    # The dump emitted its own vocabulary span.
    spans = [
        json.loads(ln)
        for ln in (tmp_path / "server.jsonl").read_text().splitlines()
    ]
    assert any(s["span"] == "postmortem-dump" for s in spans)


def test_flight_recorder_ring_bound_and_rate_limit(tmp_path):
    rec = FlightRecorder(
        str(tmp_path), proc="x", ring=4, min_interval_s=3600.0,
        max_bundles=2,
    )
    for i in range(10):
        rec.note_span({"span": "round", "ts": float(i), "dur_s": 0.0})
    p1 = rec.maybe_dump("round-failure")
    assert p1 is not None
    b = load_bundle(p1)
    assert len(b["spans"]) == 4  # bounded ring keeps the newest 4
    assert [s["ts"] for s in b["spans"]] == [6.0, 7.0, 8.0, 9.0]
    # Storm guard: same reason inside the interval is suppressed...
    assert rec.maybe_dump("round-failure") is None
    # ...a different reason is not, and dump() never rate-limits.
    assert rec.maybe_dump("eject-storm") is not None
    rec.dump("round-failure")
    # Directory bound: oldest pruned beyond max_bundles.
    assert len(list_bundles(str(tmp_path))) == 2


def test_flight_recorder_restart_never_overwrites_prior_bundles(tmp_path):
    """A restarted daemon (exactly what follows a failure) reuses the
    same --flight-dir; its sequence must seed PAST the previous run's
    bundles instead of os.replace()-ing the evidence."""
    first = FlightRecorder(str(tmp_path), proc="relay-0", min_interval_s=0.0)
    p1 = first.dump("round-failure")
    # Process restart: a fresh recorder over the same directory.
    second = FlightRecorder(str(tmp_path), proc="relay-0", min_interval_s=0.0)
    p2 = second.dump("round-failure")
    assert p1 != p2 and os.path.exists(p1) and os.path.exists(p2)
    assert len(list_bundles(str(tmp_path))) == 2
    # A different proc sharing the directory has its own sequence, and
    # its prune budget must NEVER count or delete the siblings' files —
    # even at max_bundles=1 with a dash-prefix name collision around.
    other = FlightRecorder(
        str(tmp_path), proc="server", min_interval_s=0.0, max_bundles=1
    )
    other.dump("round-failure")
    bundles = list_bundles(str(tmp_path))
    assert len(bundles) == 3
    assert sum(1 for b in bundles if b["proc"] == "relay-0") == 2


def test_flight_recorder_skips_torn_bundle(tmp_path):
    rec = FlightRecorder(str(tmp_path), proc="x", min_interval_s=0.0)
    rec.dump("round-failure")
    (tmp_path / "postmortem-x-9999-torn.json").write_text('{"half":')
    bundles = list_bundles(str(tmp_path))
    assert len(bundles) == 1 and bundles[0]["reason"] == "round-failure"


def test_router_eject_storm_dumps_postmortem(tmp_path):
    """N ejects inside the window -> ONE bundle (the storm guard), with
    the eject context attached."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.router.core import (
        ScoringRouter,
    )

    rec = FlightRecorder(
        str(tmp_path / "flight"), proc="router", min_interval_s=3600.0
    )
    set_global_recorder(rec)
    try:
        router = ScoringRouter(
            [("127.0.0.1", 1)],
            port=0,
            eject_storm_n=2,
            eject_storm_window_s=60.0,
        )
        rep = router.replicas[0]
        for _ in range(3):
            # Install a live socket so _eject has a connection to tear
            # down; three ejects, storm threshold 2.
            a, b = socket.socketpair()
            with rep.lock:
                rep.sock = a
                rep.healthy = True
            router._eject(rep, a, "probe timeout")
            b.close()
        router.close()
    finally:
        set_global_recorder(None)
    bundles = list_bundles(str(tmp_path / "flight"))
    assert len(bundles) == 1  # storm-guarded: one bundle, not three
    b = load_bundle(bundles[0]["path"])
    assert b["reason"] == "eject-storm"
    assert b["extra"]["ejects_in_window"] >= 2


# -------------------------------------------------- drift localization
def test_psi_contributions_decompose_psi_exactly():
    ref = [100, 100, 100, 100]
    obs = [100, 100, 40, 160]
    terms = psi_contributions(ref, obs, top_k=4)
    assert terms  # something moved
    # The per-bin terms sum to the PSI (same smoothing arithmetic).
    assert sum(t["psi"] for t in terms) == pytest.approx(
        psi(ref, obs), abs=1e-5
    )
    # Largest contribution first; bin 2 (shrunk 100->40) dominates.
    assert terms[0]["psi"] >= terms[-1]["psi"]
    assert {t["bin"] for t in terms[:2]} == {2, 3}
    assert terms[0]["expected_frac"] == pytest.approx(0.25, abs=1e-3)
    # Identical histograms contribute nothing.
    assert psi_contributions(ref, ref) == []
    with pytest.raises(ValueError):
        psi_contributions([1, 2], [1, 2, 3])


def test_drift_verdict_carries_top_bins():
    """The drift record (controller state JSONL + drift-trigger span
    attrs) says WHICH score region moved."""
    mon = DriftMonitor(
        reference=[100, 100, 100, 100], threshold=0.05, min_scores=100
    )
    mon.observe([10, 10, 10, 370])
    verdict = mon.check()
    assert verdict is not None
    assert verdict["top_bins"][0]["bin"] == 3  # the hot tail moved
    assert verdict["top_bins"][0]["observed_frac"] > verdict["top_bins"][
        0
    ]["expected_frac"]


# ------------------------------------------------------------------ CLI
def test_obs_health_cli_renders_and_exit_codes(live_fleet, capsys):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        main,
    )

    rc = main(
        [
            "obs", "health", "--interval", "0.05",
            "--target", f"serve=127.0.0.1:{live_fleet['serve'].port}",
            "--target", f"route=127.0.0.1:{live_fleet['route'].port}",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0  # everything up, nothing firing
    assert "fedtpu fleet health" in out
    assert "serve" in out and "route" in out
    assert "2/2 targets up" in out
    # A down target flips the exit code (the cron-able verdict).
    rc = main(
        [
            "obs", "health", "--interval", "0.05",
            "--target", f"serve=127.0.0.1:{live_fleet['serve'].port}",
            "--target", f"relay=127.0.0.1:{live_fleet['dead_port']}",
        ]
    )
    capsys.readouterr()
    assert rc == 1
    # --json emits the schema-tagged health VERDICT (the raw snapshot
    # stream lives in --snapshot-jsonl); --flight-dir arms the HUB's
    # recorder (the process that evaluates SLOs is the one that can
    # dump on a page).
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        obs as cli_obs,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        build_parser,
    )

    args = build_parser().parse_args(
        [
            "obs", "health", "--json", "--interval", "0.05",
            "--target", f"serve=127.0.0.1:{live_fleet['serve'].port}",
            "--flight-dir", str(live_fleet["dir"] / "hub-flight"),
        ]
    )
    hub = cli_obs._build_hub(args)
    assert hub.alerts._recorder is not None
    assert hub.alerts._recorder.proc == "obs-hub"
    rc = main(
        [
            "obs", "health", "--json", "--interval", "0.05",
            "--target", f"serve=127.0.0.1:{live_fleet['serve'].port}",
        ]
    )
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["schema"] == "fedtpu-health-v1"
    assert doc["healthy"] is True and doc["targets_up"] == 1
    assert doc["slo_firing"] == [] and doc["targets_down"] == []
    # Missing --target is an operator error.
    with pytest.raises(SystemExit):
        main(["obs", "health"])
    with pytest.raises(SystemExit):
        main(["obs", "health", "--target", "not-a-target"])


def test_obs_watch_cli_live_refresh(live_fleet, capsys):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        main,
    )

    rc = main(
        [
            "obs", "watch",
            "--target", f"serve=127.0.0.1:{live_fleet['serve'].port}",
            "--interval", "0.05", "--max-seconds", "0.2",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("fedtpu fleet health") >= 2  # actually refreshed


def test_obs_postmortem_cli_lists_and_inspects(tmp_path, capsys):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        main,
    )

    flight = tmp_path / "flight"
    rec = FlightRecorder(str(flight), proc="server", min_interval_s=0.0)
    rec.note_span(
        {
            "schema": "fedtpu-obs-v1", "proc": "server", "span": "round",
            "ts": 1.0, "dur_s": 0.4, "failed": True,
        }
    )
    rec.note_alert(
        {
            "event": "fire", "slo": "round-duration", "instance": "i",
            "burn": {"30s": 9.0},
        }
    )
    path = rec.dump("round-failure", extra={"round": 7})
    assert main(["obs", "postmortem", "--flight-dir", str(flight)]) == 0
    out = capsys.readouterr().out
    assert "round-failure" in out and "server" in out
    name = os.path.basename(path)
    assert (
        main(
            [
                "obs", "postmortem", "--flight-dir", str(flight),
                "--bundle", name,
            ]
        )
        == 0
    )
    out = capsys.readouterr().out
    assert "reason   round-failure" in out
    assert '"round": 7' in out
    assert "fire round-duration" in out
    assert "failed=True" in out
    # --json round-trips the whole bundle.
    assert (
        main(
            [
                "obs", "postmortem", "--flight-dir", str(flight),
                "--bundle", name, "--json",
            ]
        )
        == 0
    )
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == BUNDLE_SCHEMA and doc["extra"]["round"] == 7
    # An empty dir lists cleanly; a bad bundle name is an error.
    empty = tmp_path / "empty"
    empty.mkdir()
    assert main(["obs", "postmortem", "--flight-dir", str(empty)]) == 0
    capsys.readouterr()
    with pytest.raises(SystemExit):
        main(
            [
                "obs", "postmortem", "--flight-dir", str(flight),
                "--bundle", "nope.json",
            ]
        )


def test_flight_dir_flag_arms_recorder_via_obs_setup(tmp_path):
    """The daemons' --flight-dir wiring: _obs_setup installs the global
    recorder (and clears it when absent — the stale-state rule)."""
    import argparse

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.common import (
        _obs_setup,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
        get_global_recorder,
    )

    args = argparse.Namespace(
        trace_jsonl=None,
        metrics_port=0,
        flight_dir=str(tmp_path / "flight"),
    )
    _obs_setup(args, proc="server")
    rec = get_global_recorder()
    assert rec is not None and rec.proc == "server"
    # No flight_dir: the next invocation disarms the recorder.
    _obs_setup(
        argparse.Namespace(
            trace_jsonl=None, metrics_port=0, flight_dir=None
        ),
        proc="server",
    )
    assert get_global_recorder() is None
