"""Ragged-client SPMD federation: unequal clients train on 100% of their
rows (no fleet-min truncation), with the stacked lockstep program matching
N independent per-client runs + FedAvg — the reference's actual semantics
(each process consumes all of its own differently-sized sample,
client1.py:89 vs client2.py:84; server.py:73-76 averages the results)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    StackedClients,
    stack_clients_ragged,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
    TokenizedSplit,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train import (
    FederatedTrainer,
    federated_batches_ragged,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
    loss_fn,
    make_optimizer,
)

MAX_LEN = 16


def _split(n, seed, vocab=250):
    r = np.random.default_rng(seed)
    ids = r.integers(1, vocab, size=(n, MAX_LEN), dtype=np.int64).astype(np.int32)
    mask = np.ones((n, MAX_LEN), np.int32)
    labels = r.integers(0, 2, size=n).astype(np.int32)
    return TokenizedSplit(ids, mask, labels)


def _cfg(clients=2, **fed_kw):
    # Zero dropout everywhere: the manual-parity comparison must not depend
    # on PRNG folding details between the stacked and independent paths.
    return ExperimentConfig(
        model=ModelConfig.tiny(
            max_len=MAX_LEN,
            max_position_embeddings=MAX_LEN,
            dropout=0.0,
            attention_dropout=0.0,
            head_dropout=0.0,
        ),
        data=DataConfig(max_len=MAX_LEN, batch_size=8),
        train=TrainConfig(learning_rate=1e-3, epochs_per_round=1, seed=0),
        fed=FedConfig(num_clients=clients, **fed_kw),
        mesh=MeshConfig(clients=clients, data=1),
    )


def test_stack_clients_ragged_shapes():
    splits = [_split(13, 0), _split(5, 1), _split(8, 2)]
    st = stack_clients_ragged(splits, pad_id=0)
    assert st.split.input_ids.shape == (3, 13, MAX_LEN)
    np.testing.assert_array_equal(st.n_rows, [13, 5, 8])
    np.testing.assert_array_equal(st.row_valid.sum(axis=1), [13, 5, 8])
    # Pad rows: PAD ids, zero attention, zero labels, invalid.
    assert (st.split.input_ids[1, 5:] == 0).all()
    assert (st.split.attention_mask[1, 5:] == 0).all()
    assert (st.row_valid[1, 5:] == 0).all()
    # Real rows survive untouched.
    np.testing.assert_array_equal(st.split.input_ids[2, :8], splits[2].input_ids)
    # target_rows must cover the local max.
    with pytest.raises(ValueError, match="target_rows"):
        stack_clients_ragged(splits, target_rows=10)
    assert stack_clients_ragged(splits, target_rows=20).split.labels.shape == (3, 20)


def test_ragged_batches_cover_every_row_once():
    splits = [_split(13, 0), _split(5, 1), _split(30, 2)]
    st = stack_clients_ragged(splits)
    bs = 8
    batches = list(federated_batches_ragged(st, bs, seed=0, epoch=0))
    assert len(batches) == -(-30 // bs)  # fleet max, ceil
    for b in batches:
        assert b["input_ids"].shape == (3, bs, MAX_LEN)
        assert b["valid"].shape == (3, bs)
    # Every client's real rows appear exactly once per epoch (valid rows
    # reassemble the original split, no duplicates, no omissions).
    for c, split in enumerate(splits):
        seen = np.concatenate(
            [b["input_ids"][c][b["valid"][c] == 1] for b in batches]
        )
        assert len(seen) == len(split)
        order = np.lexsort(seen.T)
        ref_order = np.lexsort(split.input_ids.T)
        np.testing.assert_array_equal(seen[order], split.input_ids[ref_order])
    # Determinism + epoch decorrelation (same keying as the dense path).
    again = list(federated_batches_ragged(st, bs, seed=0, epoch=0))
    np.testing.assert_array_equal(batches[0]["labels"], again[0]["labels"])
    other = list(federated_batches_ragged(st, bs, seed=0, epoch=1))
    assert not np.array_equal(batches[0]["labels"][2], other[0]["labels"][2])


def test_ragged_batches_reject_short_n_batches():
    """A caller-supplied n_batches below a client's own epoch length must
    raise a clear error naming the client, not a numpy broadcast error."""
    splits = [_split(13, 0), _split(30, 1)]
    st = stack_clients_ragged(splits)
    with pytest.raises(ValueError, match=r"client 1's own epoch length"):
        list(federated_batches_ragged(st, 8, seed=0, epoch=0, n_batches=2))
    # At or above the max it degrades to extra all-padding lockstep steps.
    batches = list(federated_batches_ragged(st, 8, seed=0, epoch=0, n_batches=5))
    assert len(batches) == 5
    assert batches[4]["valid"].sum() == 0


@pytest.mark.slow
def test_ragged_spmd_matches_manual_per_client_runs(eight_devices):
    """The VERDICT-1 'done' criterion: a ragged fleet's stacked lockstep
    training + weighted FedAvg equals N manual independent per-client runs
    (each on 100% of its own rows) + their sample-weighted mean."""
    sizes = [24, 9]
    bs = 8
    cfg = _cfg(clients=2)
    splits = [_split(n, 100 + i) for i, n in enumerate(sizes)]
    st = stack_clients_ragged(splits)

    trainer = FederatedTrainer(cfg)
    state = trainer.init_state(seed=0)
    params0 = jax.tree.map(lambda x: np.asarray(x)[0], state.params)

    state, losses = trainer.fit_local(state, st)
    assert losses.shape == (1, 2)

    # Manual runs: same batch schedule (the generator is the spec), same
    # optimizer, plain unmasked loss over each batch's real rows only.
    opt = make_optimizer(cfg.train)
    rng = jax.random.key(0, impl=cfg.train.prng_impl)
    manual_params, manual_losses = [], []
    for c in range(2):
        p = jax.tree.map(jnp.asarray, params0)
        opt_state = opt.init(p)
        blosses = []
        for b in federated_batches_ragged(st, bs, seed=cfg.train.seed, epoch=0):
            keep = b["valid"][c] == 1
            if not keep.any():
                continue
            sub = {
                "input_ids": jnp.asarray(b["input_ids"][c][keep]),
                "attention_mask": jnp.asarray(b["attention_mask"][c][keep]),
                "labels": jnp.asarray(b["labels"][c][keep]),
            }
            loss, grads = jax.value_and_grad(
                lambda q: loss_fn(trainer.model, q, sub, rng)
            )(p)
            updates, opt_state = opt.update(grads, opt_state, p)
            p = optax.apply_updates(p, updates)
            blosses.append(float(loss))
        manual_params.append(jax.tree.map(np.asarray, p))
        manual_losses.append(np.mean(blosses))

    # Reported per-client epoch losses = each client's own batch average.
    np.testing.assert_allclose(losses[0], manual_losses, rtol=2e-5, atol=1e-6)

    # Per-client trained params match the independent runs.
    for c in range(2):
        got = jax.tree.map(lambda x: np.asarray(x)[c], state.params)
        for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(manual_params[c])):
            np.testing.assert_allclose(g, w, rtol=2e-4, atol=2e-6)

    # Weighted FedAvg = sample-weighted mean of the manual runs.
    state = trainer.aggregate(state, weights=np.asarray(sizes, np.float64))
    agg = jax.tree.map(lambda x: np.asarray(x)[0], state.params)
    wts = np.asarray(sizes, np.float64) / np.sum(sizes)
    for leaf, (a, b) in zip(
        jax.tree.leaves(agg),
        zip(jax.tree.leaves(manual_params[0]), jax.tree.leaves(manual_params[1])),
    ):
        np.testing.assert_allclose(
            leaf, wts[0] * a + wts[1] * b, rtol=2e-4, atol=2e-6
        )


@pytest.mark.slow
def test_zero_row_client_is_gated_not_fatal(eight_devices):
    """A client with an empty split (extreme Dirichlet skew) idles behind
    masks: its params stay at init through local training, and the auto
    weights exclude it from the aggregate instead of crashing the fleet
    (the dense path raised; reference would hang, server.py:69-71)."""
    cfg = _cfg(clients=2)
    splits = [_split(20, 0), _split(0, 1)]
    st = stack_clients_ragged(splits)
    trainer = FederatedTrainer(cfg)
    state = trainer.init_state(seed=0)
    p0 = jax.tree.map(lambda x: np.asarray(x), state.params)

    eval_splits = [_split(12, 7), _split(12, 8)]
    state, hist = trainer.run(state, st, eval_splits, rounds=1)

    # Auto weights [20, 0]: the aggregate IS client 0's trained params.
    assert len(hist) == 1
    agg = jax.tree.map(np.asarray, state.params)
    for leaf0, leaf in zip(jax.tree.leaves(p0), jax.tree.leaves(agg)):
        # Client 1 never trained; client 0 did. Post-FedAvg both rows hold
        # the aggregate == client 0's trained params (!= init).
        np.testing.assert_allclose(leaf[0], leaf[1], rtol=1e-6, atol=1e-7)
    changed = any(
        not np.allclose(a[0], b[0])
        for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(agg))
    )
    assert changed

    # All-empty fleets still fail loudly.
    empty = stack_clients_ragged([_split(0, 0), _split(0, 1)])
    with pytest.raises(ValueError, match="empty"):
        trainer.fit_local(trainer.init_state(seed=1), empty)


@pytest.mark.slow
def test_zero_row_client_aggregate_equals_solo_run(eight_devices):
    """With auto weights, a 2-client fleet where one client is empty must
    aggregate to exactly what client 0 trained to (weight [n, 0])."""
    cfg = _cfg(clients=2)
    splits = [_split(20, 0), _split(0, 1)]
    st = stack_clients_ragged(splits)
    trainer = FederatedTrainer(cfg)
    state = trainer.init_state(seed=0)
    state, _ = trainer.fit_local(state, st)
    trained0 = jax.tree.map(lambda x: np.asarray(x)[0], state.params)
    state = trainer.aggregate(state, weights=np.array([20.0, 0.0]))
    agg = jax.tree.map(lambda x: np.asarray(x)[0], state.params)
    for a, b in zip(jax.tree.leaves(agg), jax.tree.leaves(trained0)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_resolve_weighted_auto():
    assert FedConfig().weighted is None
    assert FedConfig().resolve_weighted() is True
    assert FedConfig(weighted=False).resolve_weighted() is False
    assert FedConfig(weighted=True).resolve_weighted() is True
    # DP forces the uniform mean under auto; explicit True still errors.
    assert (
        FedConfig(dp_clip=1.0, dp_noise_multiplier=1.0).resolve_weighted()
        is False
    )
    with pytest.raises(ValueError, match="weighted"):
        FedConfig(weighted=True, dp_clip=1.0)


@pytest.mark.slow
def test_run_auto_weights_from_ragged_stack(eight_devices):
    """run() with a ragged stack and the weighted=None default derives
    true-n_train weights: the aggregate equals the explicit-weights run."""
    cfg = _cfg(clients=2)
    splits = [_split(24, 3), _split(9, 4)]
    eval_splits = [_split(8, 5), _split(8, 6)]
    st = stack_clients_ragged(splits)

    t1 = FederatedTrainer(cfg)
    s1, _ = t1.run(t1.init_state(seed=0), st, eval_splits, rounds=1)

    t2 = FederatedTrainer(cfg)
    s2, _ = t2.run(
        t2.init_state(seed=0),
        st,
        eval_splits,
        rounds=1,
        weights=np.array([24.0, 9.0]),
    )
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


@pytest.mark.slow
def test_ragged_warmup_rides_per_client_step_count(eight_devices):
    """LR warmup must advance on each client's OWN executed steps: a short
    client idling behind masks keeps its ramp frozen, matching its
    independent run (keying on the global lockstep counter would compress
    its schedule)."""
    sizes = [24, 9]
    bs = 8
    cfg = _cfg(clients=2)
    cfg = ExperimentConfig(
        model=cfg.model,
        data=cfg.data,
        train=TrainConfig(
            learning_rate=1e-3, epochs_per_round=2, seed=0, warmup_steps=10
        ),
        fed=cfg.fed,
        mesh=cfg.mesh,
    )
    splits = [_split(n, 200 + i) for i, n in enumerate(sizes)]
    st = stack_clients_ragged(splits)
    trainer = FederatedTrainer(cfg)
    state = trainer.init_state(seed=0)
    params0 = jax.tree.map(lambda x: np.asarray(x)[0], state.params)
    state, _ = trainer.fit_local(state, st)

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
        apply_warmup,
    )

    opt = make_optimizer(cfg.train)
    rng = jax.random.key(0, impl=cfg.train.prng_impl)
    for c in range(2):
        p = jax.tree.map(jnp.asarray, params0)
        opt_state = opt.init(p)
        own_step = 0
        for epoch in range(2):
            for b in federated_batches_ragged(
                st, bs, seed=cfg.train.seed, epoch=epoch
            ):
                keep = b["valid"][c] == 1
                if not keep.any():
                    continue
                sub = {
                    "input_ids": jnp.asarray(b["input_ids"][c][keep]),
                    "attention_mask": jnp.asarray(b["attention_mask"][c][keep]),
                    "labels": jnp.asarray(b["labels"][c][keep]),
                }
                _, grads = jax.value_and_grad(
                    lambda q: loss_fn(trainer.model, q, sub, rng)
                )(p)
                updates, opt_state = opt.update(grads, opt_state, p)
                updates = apply_warmup(
                    updates, jnp.int32(own_step), cfg.train.warmup_steps
                )
                p = optax.apply_updates(p, updates)
                own_step += 1
        got = jax.tree.map(lambda x: np.asarray(x)[c], state.params)
        for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(p)):
            np.testing.assert_allclose(g, np.asarray(w), rtol=2e-4, atol=2e-6)


@pytest.mark.slow
def test_zero_row_client_masked_from_uniform_mean(eight_devices):
    """Under the uniform mean (weighted=False) a zero-row client must be
    masked out of the aggregate, not average its init params in."""
    cfg = _cfg(clients=2, weighted=False, min_client_fraction=0.5)
    splits = [_split(20, 0), _split(0, 1)]
    st = stack_clients_ragged(splits)
    trainer = FederatedTrainer(cfg)
    state = trainer.init_state(seed=0)
    eval_splits = [_split(8, 5), _split(8, 6)]
    state, _ = trainer.run(state, st, eval_splits, rounds=1)
    agg = jax.tree.map(lambda x: np.asarray(x)[0], state.params)

    # Reference: client 0 alone (the empty client contributes nothing).
    t2 = FederatedTrainer(cfg)
    s2 = t2.init_state(seed=0)
    s2, _ = t2.fit_local(s2, st)
    solo = jax.tree.map(lambda x: np.asarray(x)[0], s2.params)
    for a, b in zip(jax.tree.leaves(agg), jax.tree.leaves(solo)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
