"""Test harness: fake an 8-device CPU mesh before JAX backend init.

The TPU analogue of a fake backend (SURVEY.md §4): multi-client federation is
validated on virtual CPU devices; real-TPU runs happen in bench.py only.

NOTE: this environment's sitecustomize force-registers a TPU ('axon') platform
and overwrites JAX_PLATFORMS, so env vars alone don't stick — the config must
be updated post-import, pre-backend-init.
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

try:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass  # backend already initialized (e.g. single-test rerun) — tests skip if <8

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]


@pytest.fixture(scope="session")
def synthetic_csv(tmp_path_factory):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        write_synthetic_csv,
    )

    path = tmp_path_factory.mktemp("data") / "flows.csv"
    write_synthetic_csv(str(path), n_rows=1200, seed=7)
    return str(path)


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


# ----------------------------------------------------- lock-order detector
# The runtime half of `fedtpu check`'s concurrency pass (analysis/
# lockorder.py): every threading.Lock/RLock the package creates during
# the session is wrapped, acquisition-order edges are collected per
# creation site, and a cycle (two code paths taking the same two lock
# sites in opposite orders — the ABBA deadlock class) FAILS the session.
# FEDTPU_LOCKORDER=0 disarms. Same-site nesting (e.g. per-client locks
# acquired in a pinned order) is reported, not failed.
_LOCKORDER = {"armed": False}


def pytest_configure(config):
    if os.environ.get("FEDTPU_LOCKORDER", "1").lower() in ("", "0", "false"):
        return
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.analysis import (
        lockorder,
    )

    lockorder.arm()
    _LOCKORDER["armed"] = True


def pytest_sessionfinish(session, exitstatus):
    if not _LOCKORDER["armed"]:
        return
    _LOCKORDER["armed"] = False
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.analysis import (
        lockorder,
    )

    report = lockorder.disarm()
    if report is None:
        return
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    out = tr.write_line if tr is not None else print
    out(report.render())
    if report.cycles:
        out(
            "lock-order cycles detected — failing the session "
            "(see analysis/lockorder.py; FEDTPU_LOCKORDER=0 disarms)"
        )
        session.exitstatus = 1
