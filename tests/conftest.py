"""Test harness: fake an 8-device CPU mesh before JAX backend init.

The TPU analogue of a fake backend (SURVEY.md §4): multi-client federation is
validated on virtual CPU devices; real-TPU runs happen in bench.py only.

NOTE: this environment's sitecustomize force-registers a TPU ('axon') platform
and overwrites JAX_PLATFORMS, so env vars alone don't stick — the config must
be updated post-import, pre-backend-init.
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

try:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:
    pass  # backend already initialized (e.g. single-test rerun) — tests skip if <8

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]


@pytest.fixture(scope="session")
def synthetic_csv(tmp_path_factory):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        write_synthetic_csv,
    )

    path = tmp_path_factory.mktemp("data") / "flows.csv"
    write_synthetic_csv(str(path), n_rows=1200, seed=7)
    return str(path)


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
