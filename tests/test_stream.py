"""Pipelined federated rounds (ISSUE 5): chunk-streamed uploads
(comm/wire.py "Streamed uploads" + framing.PipelinedSender), streaming
server-side chunk aggregation (comm/stream_agg.py), and the client's
reply-wait batch prefetch (train/batches.EpochPrefetcher).

The load-bearing contract everywhere: the streamed/incremental result is
BIT-EXACT with the barrier path — same fp32 ops in the same
ascending-client-id order per leaf — so the base crc every DP/resync
test pins is unchanged by pipelining."""

import socket
import threading
import time

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
    FederatedClient,
    StreamAgg,
    StreamAggPoisoned,
    WireError,
    aggregate_flat,
    flatten_params,
    framing,
    wire,
)

pytestmark = pytest.mark.filterwarnings("ignore::pytest.PytestUnraisableExceptionWarning")


def _leaves(rng, n=6, shape=(64, 97), scale=1.0):
    """Flat separator-free keys: exchange() returns these unchanged."""
    return {
        f"w{i:02d}": rng.normal(size=shape).astype(np.float32) * scale
        for i in range(n)
    }


# --------------------------------------------------------- wire: streams
def test_stream_plan_and_header_roundtrip(rng):
    flat = wire.flatten_lazy(
        {"enc": {"k": rng.normal(size=(8, 4)).astype(np.float32)},
         "b": rng.normal(size=7).astype(np.float32),
         "step": np.int32(3)}
    )
    tensors, nbytes = wire.plan_stream(flat)
    # Contiguous extents, sorted keys — the invariant the receiver's
    # one-pass decode depends on.
    assert [t["key"] for t in tensors] == sorted(flat)
    off = 0
    for t in tensors:
        assert t["offset"] == off
        off += t["nbytes"]
    assert off == nbytes
    hdr = wire.encode_stream_header(
        tensors, meta={"client_id": 5}, chunk_bytes=1024,
        payload_nbytes=nbytes,
    )
    t2, meta, chunk, total = wire.decode_stream_header(hdr)
    assert meta == {"client_id": 5} and chunk == 1024 and total == nbytes
    assert [t["key"] for t in t2] == [t["key"] for t in tensors]
    # Leaf payloads decode back to the exact arrays via the SHARED
    # per-leaf decoder (decode_tensor_entry).
    for t in t2:
        raw = wire.encode_stream_leaf(flat[t["key"]], t["enc"])
        assert len(raw) == t["nbytes"]
        np.testing.assert_array_equal(
            wire.decode_tensor_entry(t, raw), np.asarray(flat[t["key"]])
        )


def test_stream_header_rejects_non_contiguous_and_topk(rng):
    flat = {"a": rng.normal(size=4).astype(np.float32),
            "b": rng.normal(size=4).astype(np.float32)}
    tensors, nbytes = wire.plan_stream(flat)
    broken = [dict(t) for t in tensors]
    broken[1]["offset"] += 4  # gap
    hdr = wire.encode_stream_header(
        broken, chunk_bytes=64, payload_nbytes=nbytes + 4
    )
    with pytest.raises(WireError, match="contiguous"):
        wire.decode_stream_header(hdr)
    with pytest.raises(WireError, match="topk"):
        wire.plan_stream(flat, "topk")


def test_stream_chunk_and_trailer_auth_and_ordering(rng):
    key, nonce = b"secret", b"\x01" * 16
    data = rng.integers(0, 256, 1000).astype(np.uint8).tobytes()
    frame = wire.encode_stream_chunk(3, data, auth_key=key, nonce=nonce)
    got = wire.decode_stream_chunk(
        frame, expect_seq=3, auth_key=key, nonce=nonce
    )
    assert bytes(got) == data
    with pytest.raises(WireError, match="out of order"):
        wire.decode_stream_chunk(
            frame, expect_seq=4, auth_key=key, nonce=nonce
        )
    # A bit flip (or wrong connection nonce) fails the PER-CHUNK tag —
    # what lets the server fold authenticated bytes immediately.
    bad = bytearray(frame)
    bad[20] ^= 1
    with pytest.raises(WireError, match="HMAC"):
        wire.decode_stream_chunk(
            bytes(bad), expect_seq=3, auth_key=key, nonce=nonce
        )
    with pytest.raises(WireError, match="HMAC"):
        wire.decode_stream_chunk(
            frame, expect_seq=3, auth_key=key, nonce=b"\x02" * 16
        )
    end = wire.encode_stream_end(7, auth_key=key, nonce=nonce)
    wire.decode_stream_end(end, expect_chunks=7, auth_key=key, nonce=nonce)
    with pytest.raises(WireError, match="trailer claims"):
        wire.decode_stream_end(
            end, expect_chunks=8, auth_key=key, nonce=nonce
        )


def test_pipelined_sender_overlaps_and_surfaces_errors(rng):
    import socket

    a, b = socket.socketpair()
    try:
        sender = framing.PipelinedSender(a)
        payloads = [
            rng.integers(0, 256, 5000).astype(np.uint8).tobytes()
            for _ in range(4)
        ]
        for p in payloads:
            sender.send(p)
        sender.close()
        for p in payloads:
            assert bytes(framing.recv_frame(b, send_ack=False)) == p
    finally:
        a.close(), b.close()
    # Dead socket: the wire thread's error re-raises on close (and on a
    # later send), never hangs the producer.
    c, d = socket.socketpair()
    d.close()
    sender = framing.PipelinedSender(c)
    sender.send(b"x" * (1 << 20))
    with pytest.raises((OSError, ConnectionError, WireError)):
        for _ in range(50):
            sender.send(b"x" * (1 << 20))
        sender.close()
    c.close()


# ------------------------------------------------- StreamAgg unit parity
@pytest.mark.parametrize("weighted", [False, True])
def test_stream_agg_matches_barrier_bit_exactly(rng, weighted):
    """Leaves arriving in scrambled order, folded eagerly, equal the
    barrier aggregate_flat BYTE for byte — the crc contract."""
    n_clients, keys = 3, [f"k{i}" for i in range(5)]
    models = [
        {k: rng.normal(size=(33, 17)).astype(np.float32) for k in keys}
        for _ in range(n_clients)
    ]
    weights = [3.0, 1.0, 2.5] if weighted else None
    st = StreamAgg()
    for cid in range(n_clients):
        st.register(cid, keys=tuple(sorted(keys)), n_samples=1.0)
    st.freeze(list(range(n_clients)), weights)
    order = [(c, k) for c in range(n_clients) for k in keys]
    rng.shuffle(order)
    for c, k in order:
        st.add_leaf(c, k, models[c][k])
    got = st.finalize(list(range(n_clients)), weights)
    want = aggregate_flat(models, weights)
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_array_equal(got[k], want[k])
    assert wire.flat_crc32(got) == wire.flat_crc32(want)
    # Eager folding freed per-leaf state: peak stays well under the
    # barrier's full N x model residency.
    model_bytes = sum(v.nbytes for v in models[0].values())
    assert st.peak_bytes < n_clients * model_bytes


def test_stream_agg_non_eager_is_the_barrier(rng):
    models = [_leaves(rng, n=3, shape=(16, 8)) for _ in range(2)]
    st = StreamAgg(eager=False)
    for cid, m in enumerate(models):
        st.register(cid, keys=tuple(sorted(m)), n_samples=1.0)
        st.add_dense(cid, m)
    assert st.fold_ids is None  # nothing folds before finalize
    got = st.finalize([0, 1], None)
    want = aggregate_flat(models)
    for k in want:
        np.testing.assert_array_equal(got[k], want[k])
    # Barrier residency: both full models were co-resident.
    model_bytes = sum(v.nbytes for v in models[0].values())
    assert st.peak_bytes >= 2 * model_bytes


def test_stream_agg_delta_uploads_fold_against_base(rng):
    """A dense sparse-delta upload folds as base + float32(delta) —
    byte-identical to the barrier's absolute reconstruction."""
    base = _leaves(rng, n=3, shape=(8, 5))
    delta = {k: rng.normal(size=v.shape).astype(np.float32) * 0.01
             for k, v in base.items()}
    dense = _leaves(rng, n=3, shape=(8, 5), scale=0.5)
    st = StreamAgg(base=base)
    st.register(0, keys=tuple(sorted(base)), n_samples=1.0, delta=True)
    st.register(1, keys=tuple(sorted(base)), n_samples=1.0)
    st.add_dense(0, delta)
    st.add_dense(1, dense)
    got = st.finalize([0, 1], None)
    absolute = {k: base[k] + np.asarray(delta[k], np.float32) for k in base}
    want = aggregate_flat([absolute, dense])
    for k in want:
        np.testing.assert_array_equal(got[k], want[k])


def test_stream_agg_pre_fold_death_refreezes_over_survivors(rng):
    """A member that registered an intent but died before any fold
    un-freezes the set; finalize over the survivors IS the barrier mean
    (the exact pre-streaming straggler semantics)."""
    models = [_leaves(rng, n=2, shape=(4, 3)) for _ in range(3)]
    st = StreamAgg()
    for cid in range(3):
        st.register(cid, keys=tuple(sorted(models[0])), n_samples=1.0)
    st.freeze([0, 1, 2], None)
    assert st.fold_ids == [0, 1, 2]
    assert st.drop_client(2)  # nothing folded yet -> clean drop
    assert st.fold_ids is None
    st.add_dense(0, models[0])
    st.add_dense(1, models[1])
    got = st.finalize([0, 1], None)
    want = aggregate_flat(models[:2])
    for k in want:
        np.testing.assert_array_equal(got[k], want[k])


def test_stream_agg_folded_death_poisons_duplicate_refused(rng):
    models = [_leaves(rng, n=2, shape=(4, 3)) for _ in range(2)]
    st = StreamAgg()
    for cid in range(2):
        st.register(cid, keys=tuple(sorted(models[0])), n_samples=1.0)
        st.add_dense(cid, models[cid])
    st.freeze([0, 1], None)  # folds everything immediately
    # Duplicate (poison=False): refused, round intact.
    assert not st.drop_client(1, poison=False)
    assert st.poisoned is None
    got = st.finalize([0, 1], None)
    want = aggregate_flat(models)
    for k in want:
        np.testing.assert_array_equal(got[k], want[k])
    # Death (poison=True) after folds: the round cannot reach a correct
    # mean any more.
    st2 = StreamAgg()
    for cid in range(2):
        st2.register(cid, keys=tuple(sorted(models[0])), n_samples=1.0)
        st2.add_dense(cid, models[cid])
    st2.freeze([0, 1], None)
    assert not st2.drop_client(1)
    with pytest.raises(StreamAggPoisoned):
        st2.finalize([0, 1], None)


# ------------------------------------------------ live loopback A/B round
def _run_fleet(server, params_by_cid, rounds, *, stream=True, dp=False,
               bases=None):
    """Drive a fleet of clients through ``rounds`` exchanges against an
    already-serving loop; returns per-client final aggregates + clients."""
    results, clients = {}, {}

    def _loop(cid):
        fc = FederatedClient(
            "127.0.0.1", server.port, client_id=cid, timeout=30,
            stream=stream, dp=dp,
        )
        clients[cid] = fc
        cur = params_by_cid[cid]
        base = bases[cid] if bases else None
        for r in range(rounds):
            up = {k: v + np.float32(0.01 * (r + 1)) for k, v in cur.items()}
            if dp:
                cur = fc.exchange(up, n_samples=1, round_base=cur)
            else:
                cur = fc.exchange(up, n_samples=10 * (cid + 1))
        results[cid] = cur

    ts = [
        threading.Thread(target=_loop, args=(c,))
        for c in params_by_cid
    ]
    for t in ts:
        t.start()
    aggs = [server.serve_round() for _ in range(rounds)]
    for t in ts:
        t.join(timeout=60)
    return results, aggs, clients


def test_streamed_round_crc_parity_live_ab(rng):
    """THE acceptance A/B: the same two-round exchange against a
    streaming server (chunked uploads, eager folds) and a barrier server
    (stream_chunk_bytes=0) produces BIT-IDENTICAL aggregates — crc
    pinned — while the streaming server actually streamed and folded
    during the wire phase."""
    p = [_leaves(rng), _leaves(rng, scale=2.0)]
    outs = {}
    for arm, chunk in (("stream", 16384), ("barrier", 0)):
        with AggregationServer(
            port=0, num_clients=2, timeout=30, stream_chunk_bytes=chunk
        ) as server:
            results, aggs, clients = _run_fleet(
                server, {0: dict(p[0]), 1: dict(p[1])}, rounds=2
            )
            outs[arm] = (results, aggs)
            if arm == "stream":
                # Round 1 negotiates (dense), round 2 streams — both
                # clients, in many chunks, folded during the wire phase.
                assert server.stream_totals["stream_uploads"] == 2
                assert clients[0]._server_stream == 16384
                assert server.comm_overlap_frac() > 0.0
                # Streamed-round aggregation state never held both full
                # models (the barrier's O(N x model)).
                model_bytes = sum(v.nbytes for v in p[0].values())
                assert (
                    server.stream_totals["last_round_peak_bytes"]
                    <= 2 * model_bytes
                )
            else:
                assert server.stream_totals["stream_uploads"] == 0
                assert clients[0]._server_stream is None
    for r in range(2):
        s, b = outs["stream"][1][r], outs["barrier"][1][r]
        assert wire.flat_crc32(s) == wire.flat_crc32(b)
        for k in b:
            np.testing.assert_array_equal(s[k], b[k])
    for cid in (0, 1):
        for k, v in outs["barrier"][0][cid].items():
            np.testing.assert_array_equal(outs["stream"][0][cid][k], v)


def test_mixed_old_new_peer_interop_round(rng):
    """An old peer (stream=False: single dense frames, ignores the
    advert) and a streaming client mix in ONE round; the fold is the
    exact barrier mean of both — the capability bit is per-client, never
    fleet-wide."""
    p0, p1 = _leaves(rng, n=4), _leaves(rng, n=4, scale=3.0)
    results, clients = {}, {}
    with AggregationServer(
        port=0, num_clients=2, timeout=30, stream_chunk_bytes=8192
    ) as server:
        def _loop(cid, stream):
            fc = FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=30,
                stream=stream,
            )
            clients[cid] = fc
            cur = {0: p0, 1: p1}[cid]
            for r in range(2):
                up = {k: v + np.float32(0.01) for k, v in cur.items()}
                cur = fc.exchange(up)
            results[cid] = cur

        ts = [
            threading.Thread(target=_loop, args=(0, False)),
            threading.Thread(target=_loop, args=(1, True)),
        ]
        for t in ts:
            t.start()
        aggs = [server.serve_round() for _ in range(2)]
        for t in ts:
            t.join(timeout=60)
        # Only the new peer streamed in round 2 (the old peer sees the
        # advert too but its capability bit keeps it single-frame).
        assert server.stream_totals["stream_uploads"] == 1
        assert clients[0].stream is False
        assert clients[1]._server_stream == 8192
    up1 = [{k: v + np.float32(0.01) for k, v in p.items()} for p in (p0, p1)]
    want1 = aggregate_flat(up1)
    up2 = [{k: v + np.float32(0.01) for k, v in want1.items()}] * 2
    want2 = aggregate_flat(up2)
    for k in want2:
        np.testing.assert_array_equal(aggs[0][k], want1[k])
        np.testing.assert_array_equal(aggs[1][k], want2[k])
        np.testing.assert_array_equal(results[0][k], results[1][k])


def test_streamed_dp_round_base_crc_parity(rng):
    """Plain central-DP rounds with streamed delta uploads: the noiseless
    two-round trajectory is BIT-IDENTICAL to the barrier server's — the
    dp_base_crc agreement (the contract every resync test pins) is
    untouched by pipelining."""
    init = _leaves(rng, n=4, shape=(16, 9), scale=0.01)
    outs = {}
    for arm, chunk in (("stream", 8192), ("barrier", 0)):
        with AggregationServer(
            port=0, num_clients=2, timeout=30, dp_clip=1e6,
            dp_noise_multiplier=0.0, stream_chunk_bytes=chunk,
        ) as server:
            results, aggs, clients = _run_fleet(
                server,
                {0: dict(init), 1: dict(init)},
                rounds=2,
                dp=True,
            )
            outs[arm] = results
            if arm == "stream":
                assert server.stream_totals["stream_uploads"] == 2
    for cid in (0, 1):
        s = flatten_params(outs["stream"][cid])
        b = flatten_params(outs["barrier"][cid])
        assert wire.flat_crc32(s) == wire.flat_crc32(b)
        for k in b:
            np.testing.assert_array_equal(s[k], b[k])


def test_streamed_dp_server_clip_fails_closed_after_folds(rng, monkeypatch):
    """A streamed DP upload exceeding its declared clip can only be
    re-clipped while none of its leaves folded; with a single-client
    round (folds run as each leaf completes, before the trailer reveals
    the norm) the round must FAIL CLOSED — never widen the mechanism's
    sensitivity."""
    base = _leaves(rng, n=4, shape=(16, 9))
    big = {k: v + rng.normal(size=v.shape).astype(np.float32) * 100.0
           for k, v in base.items()}
    # First a clean round so the client adopts the stream advert.
    with AggregationServer(
        port=0, num_clients=1, min_clients=1, timeout=20, dp_clip=1.0,
        dp_noise_multiplier=0.0, stream_chunk_bytes=4096,
    ) as server:
        fc = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=10, dp=True
        )
        results = {}

        def _r1():
            results["out"] = fc.exchange(
                {k: v + np.float32(1e-4) for k, v in base.items()},
                round_base=base, max_retries=1,
            )

        t = threading.Thread(target=_r1)
        t.start()
        agg1 = server.serve_round()
        t.join(timeout=30)
        assert fc._server_stream == 4096 and agg1 is not None
        new_base = {
            k: np.asarray(v, np.float32)
            for k, v in flatten_params(results["out"]).items()
        }
        # Cheat: skip the client-side clip so the oversized delta hits
        # the wire unclipped. (clip_flat is client-side only here — the
        # streamed server path computes its own norm inline.)
        monkeypatch.setattr(
            wire, "clip_flat",
            lambda flat, clip: (
                {k: np.asarray(v, np.float32) for k, v in flat.items()},
                0.0, 1.0,
            ),
        )
        errors = {}

        def _r2():
            try:
                fc.exchange(
                    {k: new_base[k] + big[k] for k in new_base},
                    round_base=new_base, max_retries=1,
                )
            except Exception as e:
                errors["e"] = e

        t2 = threading.Thread(target=_r2)
        t2.start()
        with pytest.raises(RuntimeError):
            server.serve_round(deadline=4)
        t2.join(timeout=30)
        assert "e" in errors  # client sees the failed round, not silence


def test_secure_agg_round_never_streams(rng):
    """Secure aggregation keeps the single-frame barrier by design: the
    server never adverts streaming (masked sums need the full
    contributor set resolved first), and the round's math is unchanged."""
    base = {"w": rng.normal(size=(6, 3)).astype(np.float32)}
    deltas = [
        {"w": rng.normal(size=(6, 3)).astype(np.float32) * 0.05}
        for _ in range(2)
    ]
    params = [{"w": base["w"] + d["w"]} for d in deltas]
    results, clients = {}, {}
    with AggregationServer(
        port=0, num_clients=2, timeout=20, secure_agg=True, dp_clip=10.0,
        dp_noise_multiplier=0.0, stream_chunk_bytes=1 << 20,
    ) as server:
        def _go(i):
            fc = FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=20,
                dp=True, secure_agg=True, num_clients=2,
            )
            clients[i] = fc
            results[i] = fc.exchange(
                params[i], n_samples=1, round_base=base
            )

        ts = [threading.Thread(target=_go, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        server.serve_round()
        for t in ts:
            t.join(timeout=30)
        assert server.stream_totals["stream_uploads"] == 0
    # No advert ever reached the clients (secure replies carry none).
    assert clients[0]._server_stream is None
    want = base["w"] + 0.5 * (deltas[0]["w"] + deltas[1]["w"])
    np.testing.assert_allclose(
        flatten_params(results[0])["w"], want, atol=1e-5
    )
    np.testing.assert_array_equal(
        flatten_params(results[0])["w"], flatten_params(results[1])["w"]
    )


def test_streamed_stale_client_resync_round(rng):
    """The DP stranded-client resync (PR 3) under streamed uploads: a
    stale client's streamed upload is excluded, the catch-up SEQUENCE
    heals it, and the next full round's base-crc agreement holds —
    folds froze over the same staleness partition serve_round used."""
    base = _leaves(rng, n=3, shape=(6, 3), scale=0.0)

    def _step(b, scale):
        return {
            k: b[k] + rng.normal(size=b[k].shape).astype(np.float32) * scale
            for k in b
        }

    def _serve(server, results, deadline=20):
        def _go():
            try:
                results["agg"] = server.serve_round(deadline=deadline)
            except RuntimeError as e:
                results["agg"], results["err"] = None, e

        t = threading.Thread(target=_go)
        t.start()
        return t

    def _run(clients, params, bases, results):
        def _go(i):
            results[i] = clients[i].exchange(
                params[i], n_samples=1, round_base=bases[i]
            )

        ts = [
            threading.Thread(target=_go, args=(i,))
            for i in range(len(clients))
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)

    results = {}
    with AggregationServer(
        port=0, num_clients=2, min_clients=1, timeout=20,
        dp_clip=1e6, dp_noise_multiplier=0.0, stream_chunk_bytes=2048,
    ) as server:
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=20, dp=True
            )
            for i in range(2)
        ]
        # Round 1: shared init (dense — no advert adopted yet).
        st = _serve(server, results)
        _run(clients, [_step(base, 0.01), _step(base, 0.02)],
             [base, base], results)
        st.join(timeout=30)
        base1 = {k: np.asarray(v, np.float32)
                 for k, v in flatten_params(results[0]).items()}
        # Round 2: client 0 misses it; client 1 STREAMS its delta.
        st = _serve(server, results, deadline=4)
        out1 = clients[1].exchange(
            _step(base1, 0.015), round_base=base1
        )
        st.join(timeout=30)
        assert server.stream_totals["stream_uploads"] >= 1
        base2 = {k: np.asarray(v, np.float32)
                 for k, v in flatten_params(out1).items()}
        # Round 3: client 0 rejoins STALE (streamed stale upload is
        # excluded from the frozen fold set); both land bit-identical.
        st = _serve(server, results)
        _run(clients, [_step(base1, 0.01), _step(base2, 0.02)],
             [base1, base2], results)
        st.join(timeout=30)
        r0, r1 = flatten_params(results[0]), flatten_params(results[1])
        for key in r0:
            np.testing.assert_array_equal(r0[key], r1[key])
        # Round 4: full fleet from the resynced base — crc agreement.
        base3 = {k: np.asarray(v, np.float32) for k, v in r0.items()}
        st = _serve(server, results)
        _run(clients, [_step(base3, 0.01), _step(base3, 0.02)],
             [base3, base3], results)
        st.join(timeout=30)
        assert results["agg"] is not None
        np.testing.assert_array_equal(
            flatten_params(results[0])["w00"],
            flatten_params(results[1])["w00"],
        )


def test_duplicate_upload_after_folds_keeps_the_round_alive(rng):
    """A client re-uploading after folds consumed its first upload must
    not poison the round: the original stands, the duplicate is refused,
    and the round's aggregate is the barrier mean of the FIRST uploads."""
    p = [_leaves(rng, n=3, shape=(8, 4)), _leaves(rng, n=3, shape=(8, 4))]
    with AggregationServer(
        port=0, num_clients=2, timeout=20, stream_chunk_bytes=1 << 20
    ) as server:
        results = {}

        def _c(cid):
            fc = FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=20
            )
            results[cid] = fc.exchange(p[cid])

        ts = [threading.Thread(target=_c, args=(c,)) for c in (0, 1)]
        for t in ts:
            t.start()
        agg = server.serve_round()
        for t in ts:
            t.join(timeout=30)
    want = aggregate_flat([flatten_params(p[0]), flatten_params(p[1])])
    for k in want:
        np.testing.assert_array_equal(agg[k], want[k])


def test_dense_retry_supersedes_in_flight_stream(rng):
    """A client whose streamed upload stalls mid-chunk retries with a
    dense frame on a fresh connection (attempt 2 is always dense). The
    retry must supersede the half-open stream — one intent, the retry's
    values — and the stalled handler's death afterwards must neither
    poison the round nor strip the retry's state."""
    models = [_leaves(rng, n=4, shape=(32, 33)),
              _leaves(rng, n=4, shape=(32, 33), scale=2.0)]
    flat0 = {k: np.asarray(v) for k, v in models[0].items()}
    with AggregationServer(
        port=0, num_clients=2, timeout=30, stream_chunk_bytes=2048
    ) as server:
        aggs = []
        srv = threading.Thread(target=lambda: aggs.append(server.serve_round()))
        srv.start()
        # Half-open stream from client 0 carrying GARBAGE values: header
        # plus most chunks, never the trailer.
        garbage = {k: v * np.float32(100.0) for k, v in flat0.items()}
        tensors, payload_nbytes = wire.plan_stream(garbage)
        blob = b"".join(
            wire.encode_stream_leaf(garbage[t["key"]], t["enc"])
            for t in tensors
        )
        stalled = socket.create_connection(
            ("127.0.0.1", server.port), timeout=30
        )
        framing.send_frame(
            stalled,
            wire.encode_stream_header(
                tensors,
                meta={"client_id": 0, "n_samples": 1},
                chunk_bytes=2048,
                payload_nbytes=payload_nbytes,
            ),
        )
        n_sent = (len(blob) // 2048) // 2 + 1
        for seq in range(n_sent):
            framing.send_frame(
                stalled,
                wire.encode_stream_chunk(
                    seq, blob[seq * 2048 : (seq + 1) * 2048]
                ),
                await_ack=False,
            )
        time.sleep(0.5)  # let the handler register + consume the chunks
        results = {}

        def _c(cid, params):
            fc = FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=30,
                stream=False,
            )
            results[cid] = fc.exchange(params, n_samples=1)

        t0 = threading.Thread(target=_c, args=(0, dict(models[0])))
        t0.start()  # the dense retry takes over client 0's slot
        time.sleep(0.5)
        stalled.close()  # stalled handler dies AFTER the takeover
        time.sleep(0.2)
        t1 = threading.Thread(target=_c, args=(1, dict(models[1])))
        t1.start()
        for t in (t0, t1, srv):
            t.join(timeout=60)
    assert aggs, "round failed (streamed state poisoned the retry?)"
    want = aggregate_flat(
        [flatten_params(models[0]), flatten_params(models[1])]
    )
    for k in want:
        np.testing.assert_array_equal(aggs[0][k], want[k])
    for k, v in want.items():
        np.testing.assert_array_equal(results[0][k], v)


def test_dense_retry_completes_partially_folded_stream(rng):
    """The POST-fold flavor of the supersede: client 1's dense upload is
    in, client 0's stream froze the fold set and its early leaves already
    folded when the socket stalls. The dense retry re-sends the same
    upload, so its leaves must complete the remaining folds — the round
    finishes with the exact barrier mean instead of raising out of
    finalize (a WireError would escape serve()'s RuntimeError guard and
    kill every remaining round)."""
    models = [_leaves(rng, n=4, shape=(32, 33)),
              _leaves(rng, n=4, shape=(32, 33), scale=2.0)]
    flat0 = {k: np.asarray(v) for k, v in models[0].items()}
    with AggregationServer(
        port=0, num_clients=2, timeout=30, stream_chunk_bytes=2048
    ) as server:
        aggs = []
        srv = threading.Thread(target=lambda: aggs.append(server.serve_round()))
        srv.start()
        results = {}

        def _c(cid, params):
            fc = FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=30,
                stream=False,
            )
            results[cid] = fc.exchange(params, n_samples=1)

        t1 = threading.Thread(target=_c, args=(1, dict(models[1])))
        t1.start()  # complete dense upload -> client 1's leaves all pend
        time.sleep(0.5)
        # Client 0 streams its TRUE values but stalls halfway: with both
        # intents in, the fold set freezes and every leaf completed so
        # far folds immediately (client 1's copies are already present).
        tensors, payload_nbytes = wire.plan_stream(flat0)
        blob = b"".join(
            wire.encode_stream_leaf(flat0[t["key"]], t["enc"])
            for t in tensors
        )
        stalled = socket.create_connection(
            ("127.0.0.1", server.port), timeout=30
        )
        framing.send_frame(
            stalled,
            wire.encode_stream_header(
                tensors,
                meta={"client_id": 0, "n_samples": 1},
                chunk_bytes=2048,
                payload_nbytes=payload_nbytes,
            ),
        )
        n_sent = (len(blob) // 2048) // 2 + 1
        for seq in range(n_sent):
            framing.send_frame(
                stalled,
                wire.encode_stream_chunk(
                    seq, blob[seq * 2048 : (seq + 1) * 2048]
                ),
                await_ack=False,
            )
        time.sleep(0.5)  # early leaves fold (client 1 complete)
        t0 = threading.Thread(target=_c, args=(0, dict(models[0])))
        t0.start()  # the dense retry supersedes the half-folded stream
        time.sleep(0.5)
        stalled.close()
        for t in (t0, t1, srv):
            t.join(timeout=60)
        early = server.stream_totals["early_bytes"]
    assert aggs, "round failed: retry did not complete the folded stream"
    assert early > 0, "scenario never folded during the wire phase"
    want = aggregate_flat(
        [flatten_params(models[0]), flatten_params(models[1])]
    )
    for k in want:
        np.testing.assert_array_equal(aggs[0][k], want[k])
        np.testing.assert_array_equal(results[0][k], want[k])
        np.testing.assert_array_equal(results[1][k], want[k])


def test_streamed_retry_completes_partially_folded_stream(rng):
    """Streamed twin of the dense-retry heal: a client whose streamed
    upload half-folded before its socket died retries with ANOTHER
    stream (a restarted client loop with the advert already cached).
    The retry's plan matches the original intent, so its leaves must be
    ADOPTED to complete the remaining folds — not drained into a round
    that then stalls to deadline failure."""
    models = [_leaves(rng, n=4, shape=(32, 33)),
              _leaves(rng, n=4, shape=(32, 33), scale=2.0)]
    flat0 = {k: np.asarray(v) for k, v in models[0].items()}
    with AggregationServer(
        port=0, num_clients=2, timeout=30, stream_chunk_bytes=2048
    ) as server:
        aggs = []
        srv = threading.Thread(target=lambda: aggs.append(server.serve_round()))
        srv.start()
        results = {}

        def _c(cid, params, stream):
            fc = FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=30,
                stream=stream,
            )
            if stream:
                fc._server_stream = 2048  # advert cached from a past round
            results[cid] = fc.exchange(params, n_samples=1)

        t1 = threading.Thread(target=_c, args=(1, dict(models[1]), False))
        t1.start()
        time.sleep(0.5)
        tensors, payload_nbytes = wire.plan_stream(flat0)
        blob = b"".join(
            wire.encode_stream_leaf(flat0[t["key"]], t["enc"])
            for t in tensors
        )
        stalled = socket.create_connection(
            ("127.0.0.1", server.port), timeout=30
        )
        framing.send_frame(
            stalled,
            wire.encode_stream_header(
                tensors,
                meta={"client_id": 0, "n_samples": 1},
                chunk_bytes=2048,
                payload_nbytes=payload_nbytes,
            ),
        )
        n_sent = (len(blob) // 2048) // 2 + 1
        for seq in range(n_sent):
            framing.send_frame(
                stalled,
                wire.encode_stream_chunk(
                    seq, blob[seq * 2048 : (seq + 1) * 2048]
                ),
                await_ack=False,
            )
        time.sleep(0.5)  # early leaves fold (client 1 complete)
        t0 = threading.Thread(target=_c, args=(0, dict(models[0]), True))
        t0.start()  # the STREAMED retry must be adopted, not drained
        time.sleep(0.5)
        stalled.close()
        for t in (t0, t1, srv):
            t.join(timeout=60)
        early = server.stream_totals["early_bytes"]
    assert aggs, "round failed: streamed retry was drained, not adopted"
    assert early > 0, "scenario never folded during the wire phase"
    want = aggregate_flat(
        [flatten_params(models[0]), flatten_params(models[1])]
    )
    for k in want:
        np.testing.assert_array_equal(aggs[0][k], want[k])
        np.testing.assert_array_equal(results[0][k], want[k])


def test_quorum_round_survives_mid_stream_death(rng):
    """min_clients < num_clients: streaming must not change the barrier
    failure semantics. An eager fold commits to the full contributor
    set, so one mid-stream death after folds began would fail a round
    the barrier shape completes over the survivors — quorum rounds
    therefore hold every upload and fold only at close. One client
    dying mid-upload costs only that client."""
    models = [_leaves(rng, n=4, shape=(32, 33)),
              _leaves(rng, n=4, shape=(32, 33), scale=2.0)]
    flat0 = {k: np.asarray(v) for k, v in models[0].items()}
    with AggregationServer(
        port=0, num_clients=2, min_clients=1, timeout=30,
        stream_chunk_bytes=2048,
    ) as server:
        aggs = []
        srv = threading.Thread(
            target=lambda: aggs.append(server.serve_round(deadline=5))
        )
        srv.start()
        results = {}

        def _c(cid, params):
            fc = FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=30,
            )
            results[cid] = fc.exchange(params, n_samples=1)

        t1 = threading.Thread(target=_c, args=(1, dict(models[1])))
        t1.start()  # the survivor's upload completes
        time.sleep(0.5)
        # Client 0 streams its header plus half the chunks, then dies.
        tensors, payload_nbytes = wire.plan_stream(flat0)
        blob = b"".join(
            wire.encode_stream_leaf(flat0[t["key"]], t["enc"])
            for t in tensors
        )
        dying = socket.create_connection(
            ("127.0.0.1", server.port), timeout=30
        )
        framing.send_frame(
            dying,
            wire.encode_stream_header(
                tensors,
                meta={"client_id": 0, "n_samples": 1},
                chunk_bytes=2048,
                payload_nbytes=payload_nbytes,
            ),
        )
        n_sent = (len(blob) // 2048) // 2 + 1
        for seq in range(n_sent):
            framing.send_frame(
                dying,
                wire.encode_stream_chunk(
                    seq, blob[seq * 2048 : (seq + 1) * 2048]
                ),
                await_ack=False,
            )
        time.sleep(0.5)  # intent + chunks land, nothing may fold
        dying.close()
        for t in (t1, srv):
            t.join(timeout=60)
        assert server.stream_totals["early_bytes"] == 0, (
            "quorum round folded during the wire phase"
        )
    assert aggs and aggs[0] is not None, (
        "mid-stream death failed a quorum round the barrier shape survives"
    )
    want = flatten_params(models[1])  # the mean over the lone survivor
    for k in want:
        np.testing.assert_array_equal(aggs[0][k], want[k])
        np.testing.assert_array_equal(results[1][k], want[k])


def test_streamed_lossy_dp_round_reclips_like_the_dense_path(rng, monkeypatch):
    """DP + lossy (bf16) compression: the decoded norm can exceed the
    clip even for an honestly-clipped upload, and the dense path's
    answer is a silent server-side re-clip. The streamed path must HOLD
    a lossy-encoded DP upload's leaves and join the fold at trailer
    time after the exact same clip — never fail the round closed the
    way a post-fold re-clip would. Client-side clipping is skipped (on
    the named client threads only) so the server-side re-clip triggers
    deterministically in both arms; the two-round trajectory must stay
    bit-identical between them."""
    init = _leaves(rng, n=4, shape=(16, 9), scale=0.01)
    deltas = {
        cid: [
            {
                k: rng.normal(size=v.shape).astype(np.float32) * 3.0
                for k, v in init.items()
            }
            for _ in range(2)
        ]
        for cid in (0, 1)
    }
    real_clip = wire.clip_flat

    def _skip_on_client_threads(flat, clip):
        if threading.current_thread().name.startswith("noclip"):
            return (
                {k: np.asarray(v, np.float32) for k, v in flat.items()},
                wire.flat_l2_norm(flat),
                1.0,
            )
        return real_clip(flat, clip)

    monkeypatch.setattr(wire, "clip_flat", _skip_on_client_threads)
    outs = {}
    for arm, chunk in (("stream", 8192), ("barrier", 0)):
        with AggregationServer(
            port=0, num_clients=2, timeout=30, dp_clip=1.0,
            dp_noise_multiplier=0.0, stream_chunk_bytes=chunk,
        ) as server:
            results = {}

            def _loop(cid):
                fc = FederatedClient(
                    "127.0.0.1", server.port, client_id=cid, timeout=30,
                    dp=True, compression="bf16",
                )
                cur = dict(init)
                for r in range(2):
                    up = {k: v + deltas[cid][r][k] for k, v in cur.items()}
                    cur = fc.exchange(up, n_samples=1, round_base=cur)
                results[cid] = cur

            ts = [
                threading.Thread(
                    target=_loop, args=(c,), name=f"noclip-{c}"
                )
                for c in (0, 1)
            ]
            for t in ts:
                t.start()
            for _ in range(2):
                server.serve_round()
            for t in ts:
                t.join(timeout=60)
            if arm == "stream":
                # Round 1 is dense (the advert arrives with its reply);
                # round 2 streams from both clients and exercises the
                # held-leaves re-clip.
                assert server.stream_totals["stream_uploads"] == 2
        outs[arm] = results
    for cid in (0, 1):
        s = flatten_params(outs["stream"][cid])
        b = flatten_params(outs["barrier"][cid])
        assert wire.flat_crc32(s) == wire.flat_crc32(b)
        for k in b:
            np.testing.assert_array_equal(s[k], b[k])


def test_empty_stream_chunk_is_refused(rng):
    """Zero-length STRC chunks make no receive progress; an endless
    supply would pin the handler thread in a no-progress loop. The
    server must drop the connection on the first one — and the round
    must still complete once the real client uploads."""
    flat = {k: np.asarray(v) for k, v in _leaves(rng, n=2, shape=(16, 17)).items()}
    with AggregationServer(
        port=0, num_clients=1, timeout=30, stream_chunk_bytes=2048
    ) as server:
        aggs = []
        srv = threading.Thread(target=lambda: aggs.append(server.serve_round()))
        srv.start()
        tensors, payload_nbytes = wire.plan_stream(flat)
        evil = socket.create_connection(("127.0.0.1", server.port), timeout=10)
        framing.send_frame(
            evil,
            wire.encode_stream_header(
                tensors,
                meta={"client_id": 0, "n_samples": 1},
                chunk_bytes=2048,
                payload_nbytes=payload_nbytes,
            ),
        )
        framing.send_frame(
            evil, wire.encode_stream_chunk(0, b""), await_ack=False
        )
        evil.settimeout(10)
        assert evil.recv(1) == b"", "server kept the empty-chunk stream open"
        evil.close()
        fc = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=30, stream=False,
        )
        out = fc.exchange(dict(flat), n_samples=1)
        srv.join(timeout=60)
    assert aggs
    for k in flat:
        np.testing.assert_array_equal(out[k], flat[k])
        np.testing.assert_array_equal(aggs[0][k], flat[k])


def test_stream_chunk_size_must_leave_frame_headroom():
    """A chunk size so large the STRC envelope would push the frame over
    framing.MAX_FRAME is refused up front — otherwise every streamed
    attempt would fail at the transport and silently pay a dense retry."""
    cap = framing.MAX_FRAME - wire.STREAM_CHUNK_OVERHEAD
    with pytest.raises(ValueError, match="stream_chunk_bytes"):
        AggregationServer(
            port=0, num_clients=1, timeout=5, stream_chunk_bytes=cap + 1
        )


# -------------------------------------------- reply-wait batch prefetch
def test_epoch_prefetcher_yields_identical_batches(rng):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.batches import (
        EpochPrefetcher,
        federated_batches,
    )

    from types import SimpleNamespace

    stacked = SimpleNamespace(
        input_ids=rng.integers(0, 100, (2, 40, 8)).astype(np.int32),
        attention_mask=np.ones((2, 40, 8), np.int32),
        labels=rng.integers(0, 2, (2, 40)).astype(np.int32),
    )

    def factory():
        return federated_batches(stacked, 8, seed=7, epoch=3)

    direct = list(factory())
    pf = EpochPrefetcher(factory, k=2)
    got = list(pf.batches())
    assert pf.n_prefetched == 2 and pf.busy_s >= 0.0
    assert len(got) == len(direct)
    for a, b in zip(got, direct):
        for key in b:
            np.testing.assert_array_equal(a[key], b[key])
    # k beyond the epoch: everything prefetched, sequence unchanged.
    pf = EpochPrefetcher(factory, k=1000)
    got = list(pf.batches())
    assert len(got) == len(direct)
    # A factory error surfaces on consume, never kills the daemon thread.
    def boom():
        raise RuntimeError("input pipeline died")

    pf = EpochPrefetcher(boom, k=1)
    with pytest.raises(RuntimeError, match="input pipeline died"):
        list(pf.batches())


def test_trainer_prefetch_epoch_preserves_batch_sequence(rng):
    """engine.Trainer: an armed prefetch serves the SAME batch sequence
    epoch_batches would build live (determinism is the contract that
    lets the TCP client arm it blindly before every exchange)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
        Trainer,
    )

    split = TokenizedSplit(
        rng.integers(0, 50, (37, 8)).astype(np.int32),
        np.ones((37, 8), np.int32),
        rng.integers(0, 2, 37).astype(np.int32),
    )
    trainer = Trainer(ModelConfig.tiny(vocab_size=64), TrainConfig())
    live = list(trainer.epoch_batches(split, epoch=2, batch_size=8))
    pf = trainer.prefetch_epoch(split, 2, 8)
    assert pf is not None
    via_prefetch = list(trainer.epoch_batches(split, epoch=2, batch_size=8))
    assert not trainer._prefetch.armed  # consumed
    assert len(via_prefetch) == len(live)
    for a, b in zip(via_prefetch, live):
        for key in b:
            np.testing.assert_array_equal(a[key], b[key])
    # A mismatched key (different epoch) is never consumed wrong — the
    # live iterator serves the epoch — and the stale armed buffer is
    # DROPPED rather than pinned until the next arm.
    trainer.prefetch_epoch(split, 5, 8)
    live3 = list(trainer.epoch_batches(split, epoch=3, batch_size=8))
    assert not trainer._prefetch.armed
    assert len(live3) == len(live)


def test_streamed_round_with_auth(rng):
    """Auth mode end-to-end over streams: the STRH header passes the
    freshness check (role + connection nonce), every chunk's HMAC is
    bound to the nonce and sequence, and the fold result matches the
    barrier mean bit-exactly."""
    key = b"fleet-secret"
    p = [_leaves(rng, n=4), _leaves(rng, n=4, scale=2.0)]
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=30, auth_key=key,
        stream_chunk_bytes=8192,
    ) as server:
        def _loop(cid):
            fc = FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=30,
                auth_key=key,
            )
            cur = p[cid]
            for _ in range(2):
                up = {k: v + np.float32(0.01) for k, v in cur.items()}
                cur = fc.exchange(up)
            results[cid] = cur

        ts = [threading.Thread(target=_loop, args=(c,)) for c in (0, 1)]
        for t in ts:
            t.start()
        aggs = [server.serve_round() for _ in range(2)]
        for t in ts:
            t.join(timeout=60)
        assert server.stream_totals["stream_uploads"] == 2
    up1 = [{k: v + np.float32(0.01) for k, v in m.items()} for m in p]
    want1 = aggregate_flat(up1)
    want2 = aggregate_flat(
        [{k: v + np.float32(0.01) for k, v in want1.items()}] * 2
    )
    for k in want2:
        np.testing.assert_array_equal(aggs[1][k], want2[k])
        np.testing.assert_array_equal(results[0][k], results[1][k])
