"""Multi-host federation (parallel/multihost.py).

The single-process degenerate paths run inline; the real thing — two OS
processes, each owning one client's private data, joined by
jax.distributed with FedAvg crossing the process boundary — runs as a
subprocess integration test through the actual CLI (the TPU-native
replacement for the reference's three-process TCP topology,
server.py:116-137).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.multihost import (
    global_array_from_replicated,
    global_batch,
    initialize,
    local_client_slice,
    make_global_mesh,
    make_global_seq_mesh,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.mesh import (
    FedShardings,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_initialize_noop_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert initialize() is False
    assert initialize(num_processes=1) is False


def test_single_process_mesh_and_slice(eight_devices):
    mesh = make_global_mesh(4, 2)
    assert mesh.devices.shape == (4, 2)
    assert local_client_slice(mesh) == slice(0, 4)


def test_single_process_seq_mesh_and_slice(eight_devices):
    """3-axis global mesh (single-process degenerate) + the client slice
    on a 3-axis mesh — the fast-lane anchor for the multi-host fedseq
    composition (the live 2-process run is the slow-lane proof)."""
    mesh = make_global_seq_mesh(2, 2, 2)
    assert mesh.devices.shape == (2, 2, 2)
    assert mesh.axis_names == ("clients", "data", "seq")
    assert local_client_slice(mesh) == slice(0, 2)


def test_single_process_global_batch_is_device_put(eight_devices):
    mesh = make_global_mesh(4, 2)
    sh = FedShardings(mesh)
    local = {"x": np.arange(4 * 6 * 2, dtype=np.int32).reshape(4, 6, 2)}
    out = global_batch(sh.batch, local, 4)
    np.testing.assert_array_equal(np.asarray(out["x"]), local["x"])
    arr = global_array_from_replicated(sh.client, np.ones((4, 3), np.float32))
    assert arr.shape == (4, 3)


_WORKER = """
import sys, os
sys.path.insert(0, {repo!r})
pid = int(sys.argv[1]); port = sys.argv[2]; out = sys.argv[3]
extra = sys.argv[4:]
# Two local devices per process: XLA_FLAGS covers JAX versions without the
# jax_num_cpu_devices option (it is read at backend init, which happens
# after jax.distributed.initialize inside main()).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=2"
).strip()
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import main
rc = main([
    "federated",
    "--coordinator", f"127.0.0.1:{{port}}",
    "--num-processes", "2", "--process-id", str(pid),
    "--num-clients", "2", "--data-parallel", "2",
    "--rounds", "1", "--epochs", "1",
    "--synthetic", "320", "--data-fraction", "0.5", "--partition", "disjoint",
    "--batch-size", "8", "--max-len", "32",
    "--output-dir", out,
    *extra,
])
print(f"proc {{pid}} rc {{rc}}", flush=True)
sys.exit(rc)
"""


def _launch_pair(tmp_path, out, extra=()):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=REPO))
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port), str(out), *extra],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=str(tmp_path),
        )
        for i in range(2)
    ]
    outputs = []
    try:
        for p in procs:
            outputs.append(p.communicate(timeout=300)[0])
    finally:
        for p in procs:
            p.kill()
    for i, (p, o) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"proc {i} failed:\n{o[-3000:]}"
    return outputs


@pytest.mark.slow
def test_two_process_federated_cli(tmp_path):
    """Full multi-host flow through the CLI: bootstrap, global mesh, each
    process feeding its own client, FedAvg over DCN, process 0 reporting."""
    out = tmp_path / "out"
    outputs = _launch_pair(tmp_path, out)
    # Process 0 wrote the full fleet's reports — INCLUDING the prob-based
    # ROC/PR artifacts (multi-host probs gather in evaluate_clients).
    for c in range(2):
        assert (out / f"client{c}_aggregated_metrics.csv").exists(), outputs[0][-2000:]
        plots = {p.name for p in (out / f"client{c}_plots").iterdir()}
        assert f"client{c}_aggregated_roc.png" in plots, plots
        assert f"client{c}_aggregated_pr.png" in plots, plots
    # Both processes logged identical (replicated) round metrics.
    def _fed_lines(o):
        return [l for l in o.splitlines() if "aggregated" in l and "round" in l]

    assert _fed_lines(outputs[0]) and (
        _fed_lines(outputs[0]) == _fed_lines(outputs[1])
    )


@pytest.mark.slow
def test_two_process_stream_matches_in_memory(tmp_path):
    """--stream under multi-host: each process streams only its own
    client's tokens from the shared CSV; the run's reports must be
    byte-identical to the in-memory multi-host run (same plan, same
    tokens, same training)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        write_synthetic_csv,
    )

    csv = tmp_path / "flows.csv"
    write_synthetic_csv(str(csv), n_rows=400, seed=13)
    common = ("--csv", str(csv), "--partition", "disjoint")
    out_mem = tmp_path / "out_mem"
    _launch_pair(tmp_path, out_mem, common)
    out_stream = tmp_path / "out_stream"
    _launch_pair(tmp_path, out_stream, common + ("--stream",))
    for c in range(2):
        for kind in ("local", "aggregated"):
            a = (out_mem / f"client{c}_{kind}_metrics.csv").read_bytes()
            b = (out_stream / f"client{c}_{kind}_metrics.csv").read_bytes()
            assert a == b, (c, kind, a, b)


@pytest.mark.slow
def test_two_process_checkpoint_resume(tmp_path):
    """Multi-host checkpoint/resume: round 1 saves a sharded checkpoint
    (every process participates); a fresh launch resumes from it instead of
    retraining round 1."""
    out = tmp_path / "out"
    ckpt = tmp_path / "ckpt"
    _launch_pair(tmp_path, out, ("--checkpoint-dir", str(ckpt)))
    assert any(ckpt.iterdir()), "no checkpoint written"

    out2 = tmp_path / "out2"
    outputs = _launch_pair(tmp_path, out2, ("--checkpoint-dir", str(ckpt)))
    for o in outputs:
        assert "resumed from round 1" in o, o[-2000:]
    # A fully-resumed run trained nothing: aggregated reports only, no
    # fabricated local-model CSVs.
    assert (out2 / "client0_aggregated_metrics.csv").exists()
    assert not (out2 / "client0_local_metrics.csv").exists()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_seq_parallel_cli(tmp_path):
    """VERDICT r4 #1 done-criterion: the flagship 3-axis FedSeqTrainer
    spanning two OS processes — clients over DCN, each client's seq ring
    inside its own host's devices. Full CLI flow: bootstrap, global
    clients x data x seq mesh, ring-attention local training, FedAvg
    across processes, identical replicated round metrics on both hosts,
    process 0 writing the fleet's artifacts."""
    out = tmp_path / "out"
    outputs = _launch_pair(
        tmp_path,
        out,
        ("--data-parallel", "1", "--seq-parallel", "2"),
    )
    # The 3-axis multi-host mesh actually ran (not a silent 2-axis
    # fallback), with the rings placed on-host.
    assert "[FEDSEQ] mesh 2x1x2" in outputs[0], outputs[0][-2000:]
    assert "rings on-host" in outputs[0]
    for c in range(2):
        assert (out / f"client{c}_aggregated_metrics.csv").exists(), (
            outputs[0][-2000:]
        )

    def _fed_lines(o):
        return [l for l in o.splitlines() if "aggregated" in l and "round" in l]

    assert _fed_lines(outputs[0]) and (
        _fed_lines(outputs[0]) == _fed_lines(outputs[1])
    )


@pytest.mark.slow
def test_two_process_dp_fedavg(tmp_path):
    """Multi-host DP-FedAvg: the fresh noise seed must be agreed across
    processes (allgather of process 0's entropy) — divergent seeds would
    produce divergent 'aggregated' replicas, which the identical-round-
    metrics check below would catch."""
    out = tmp_path / "out"
    outputs = _launch_pair(
        tmp_path, out, ("--dp-clip", "5.0", "--dp-noise-multiplier", "0.05")
    )

    def _lines(o, tag):
        return [l for l in o.splitlines() if tag in l]

    # Both processes ran the DP boundary and report identical norm stats
    # (computed from replicated values — identical iff the noise agreed).
    dp0, dp1 = _lines(outputs[0], "[DP]"), _lines(outputs[1], "[DP]")
    assert dp0 and len(dp0) == len(dp1)
    assert [l.split("[DP]")[1] for l in dp0] == [l.split("[DP]")[1] for l in dp1]
    agg0 = [
        l.split("aggregated")[1]
        for l in _lines(outputs[0], "aggregated")
        if "round" in l
    ]
    agg1 = [
        l.split("aggregated")[1]
        for l in _lines(outputs[1], "aggregated")
        if "round" in l
    ]
    assert agg0 and agg0 == agg1


@pytest.mark.slow
def test_two_process_server_opt(tmp_path):
    """Multi-host FedOpt: the server-optimizer state must be a global
    replicated array (not host-local), or the jitted aggregate rejects the
    device placement; identical round metrics on both hosts prove the
    server step agreed."""
    out = tmp_path / "out"
    outputs = _launch_pair(
        tmp_path, out, ("--server-opt", "momentum", "--server-lr", "1.0")
    )
    agg = [
        [
            l.split("aggregated")[1]
            for l in o.splitlines()
            if "aggregated" in l and "round" in l
        ]
        for o in outputs
    ]
    assert agg[0] and agg[0] == agg[1]
