"""TCP client warm start + multi-round loop (reference parity: the
``client{N}_model.pth`` re-launch pattern, client1.py:375-377,388,403)."""

import os
import threading

import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
    main,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
)


def _serve(server, rounds, errs):
    try:
        server.serve(rounds=rounds)
    except Exception as e:  # surfaced by the asserting test thread
        errs.append(e)


@pytest.mark.slow
def test_client_multi_round_with_checkpoints(tmp_path):
    """One client per round slot (num_clients=1 keeps the test single
    process): two in-process rounds, post-train and post-aggregate saves,
    then a warm-started re-launch (the reference's only multi-round
    mechanism)."""
    ckpt = str(tmp_path / "ckpt")
    out = str(tmp_path / "out")
    errs: list = []
    with AggregationServer(port=0, num_clients=1, timeout=60) as server:
        t = threading.Thread(target=_serve, args=(server, 2, errs), daemon=True)
        t.start()
        rc = main(
            [
                "client", "--client-id", "0", "--port", str(server.port),
                "--host", "127.0.0.1", "--synthetic", "300", "--epochs", "1",
                "--rounds", "2", "--checkpoint-dir", ckpt,
                "--output-dir", out, "--timeout", "60",
            ]
        )
        t.join(timeout=60)
    assert rc == 0 and not errs
    # Aggregated (not just local) artifacts prove the exchange rounds ran.
    assert os.path.exists(os.path.join(out, "client0_aggregated_metrics.csv"))
    saved = [p for p in os.listdir(ckpt) if p.isdigit()]
    assert len(saved) >= 2  # post-train + post-aggregate (x2 rounds, GC'd to 3)
    latest_after_run1 = max(int(p) for p in saved)

    # Re-launch: warm start from the saved aggregate, one more round.
    errs2: list = []
    with AggregationServer(port=0, num_clients=1, timeout=60) as server:
        t = threading.Thread(target=_serve, args=(server, 1, errs2), daemon=True)
        t.start()
        rc2 = main(
            [
                "client", "--client-id", "0", "--port", str(server.port),
                "--host", "127.0.0.1", "--synthetic", "300", "--epochs", "1",
                "--checkpoint-dir", ckpt, "--output-dir", out,
                "--timeout", "60",
            ]
        )
        t.join(timeout=60)
    assert rc2 == 0 and not errs2
    # The re-launched round's saves must land at NEW step ids — orbax
    # silently skips duplicate steps, which would drop the round's state.
    latest_after_run2 = max(
        int(p) for p in os.listdir(ckpt) if p.isdigit()
    )
    assert latest_after_run2 > latest_after_run1


@pytest.mark.slow
def test_client_degrades_without_server(tmp_path):
    """No server at all: the client still exits 0 with local-only reports
    (the reference's degraded path, client1.py:405-410)."""
    out = str(tmp_path / "out")
    rc = main(
        [
            "client", "--client-id", "0", "--port", "1",  # nothing listens
            "--host", "127.0.0.1", "--synthetic", "200", "--epochs", "1",
            "--output-dir", out, "--timeout", "2",
        ]
    )
    assert rc == 0
    assert os.path.exists(os.path.join(out, "client0_local_metrics.csv"))
    assert not os.path.exists(os.path.join(out, "client0_aggregated_metrics.csv"))
