"""Dataset registry: CIC-DDoS2019 / UNSW-NB15 schemas + mixed corpus
(BASELINE.json config 5 — the reference supports only CICIDS2017,
client1.py:84-93)."""

import numpy as np
import pandas as pd
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    Corpus,
    concat_corpora,
    corpus_from_frame,
    default_tokenizer,
    detect_dataset,
    get_dataset,
    load_mixed_corpus,
    make_all_client_splits,
    make_all_client_splits_from_corpus,
    make_synthetic,
    make_synthetic_ddos2019,
    make_synthetic_unsw,
    parse_source_arg,
    tokenize_client,
    write_synthetic_csv,
)


def test_registry_names():
    for name in ("cicids2017", "cicddos2019", "unswnb15"):
        assert get_dataset(name).name == name
    with pytest.raises(ValueError, match="unknown dataset"):
        get_dataset("kdd99")


def test_unsw_template_rendering():
    spec = get_dataset("unswnb15")
    df = pd.DataFrame(
        {
            "dur": [0.5], "proto": ["tcp"], "service": ["http"],
            "spkts": [10], "dpkts": [8], "sbytes": [1200], "dbytes": [900],
            "rate": [36.0], "sload": [19200.0], "dload": [14400.0],
            "label": [0],
        }
    )
    (text,) = spec.render_texts(df)
    assert text == (
        "Protocol is tcp. Service is http. Flow duration is 0.5 seconds. "
        "Source to destination packets are 10. "
        "Destination to source packets are 8. "
        "Source to destination bytes are 1200 bytes. "
        "Destination to source bytes are 900 bytes. "
        "Packet rate is 36.0 per second. "
        "Source load is 19200.0 bits per second. "
        "Destination load is 14400.0 bits per second."
    )
    assert spec.binary_labels(df).tolist() == [0]


def test_label_semantics_per_kind():
    ddos2019 = get_dataset("cicddos2019")
    df = pd.DataFrame({"Label": ["BENIGN", "DrDoS_DNS", "Syn"]})
    assert ddos2019.binary_labels(df).tolist() == [0, 1, 1]

    cicids = get_dataset("cicids2017")
    df = pd.DataFrame({"Label": ["BENIGN", "DDoS", "PortScan"]})
    # Reference semantics: only the exact positive value maps to 1
    # (client1.py:91).
    assert cicids.binary_labels(df).tolist() == [0, 1, 0]

    unsw = get_dataset("unswnb15")
    df = pd.DataFrame({"label": [0, 1, 1]})
    assert unsw.binary_labels(df).tolist() == [0, 1, 1]


def test_missing_columns_raise():
    spec = get_dataset("unswnb15")
    with pytest.raises(KeyError, match="missing template columns"):
        spec.render_texts(pd.DataFrame({"dur": [1.0]}))
    with pytest.raises(KeyError, match="no label column"):
        spec.binary_labels(pd.DataFrame({"dur": [1.0]}))


def test_detect_dataset():
    assert detect_dataset(make_synthetic("cicids2017", 50, seed=0)).name == "cicids2017"
    assert (
        detect_dataset(make_synthetic_ddos2019(50, seed=0)).name == "cicddos2019"
    )
    assert detect_dataset(make_synthetic_unsw(50, seed=0)).name == "unswnb15"
    with pytest.raises(ValueError, match="cannot detect"):
        detect_dataset(pd.DataFrame({"x": [1]}))


def test_detect_dataset_real_cicids2017_label_vocabulary():
    """Real CICIDS2017 exports carry many non-DDoS attack labels; they must
    stay under CICIDS2017 semantics (only 'DDoS' -> 1, reference
    client1.py:91), not get misread as CIC-DDoS2019."""
    df = pd.DataFrame(
        {"Label": ["BENIGN", "DDoS", "PortScan", "Bot", "DoS Hulk",
                   "FTP-Patator", "Heartbleed"]}
    )
    spec = detect_dataset(df)
    assert spec.name == "cicids2017"
    assert spec.binary_labels(df).tolist() == [0, 1, 0, 0, 0, 0, 0]
    # DrDoS-family labels flip the detection.
    assert detect_dataset(pd.DataFrame({"Label": ["BENIGN", "Syn"]})).name == (
        "cicddos2019"
    )


def test_default_vocab_ids_are_stable():
    """New UNSW words append after the original id range: the first 130 ids
    of the default vocab (pre-UNSW configs/checkpoints) must be unchanged."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.tokenizer import (
        EXTRA_TEMPLATE_WORDS,
        SPECIAL_TOKENS,
        TEMPLATE_WORDS,
        build_domain_vocab,
    )
    import string

    vocab = build_domain_vocab()
    legacy = list(SPECIAL_TOKENS) + [w for w in TEMPLATE_WORDS]
    for c in string.ascii_lowercase + string.digits:
        legacy.extend([c, "##" + c])
    legacy.extend(c for c in string.punctuation if c not in legacy)
    # Dedup preserving order (mirrors build_domain_vocab's _add).
    seen: list[str] = []
    for tok in legacy:
        if tok not in seen:
            seen.append(tok)
    assert vocab[: len(seen)] == seen
    assert set(EXTRA_TEMPLATE_WORDS) <= set(vocab[len(seen):])


def test_synthetic_generators_are_separable_and_labeled():
    df = make_synthetic_ddos2019(400, attack_fraction=0.25, seed=3)
    labels = get_dataset("cicddos2019").binary_labels(df)
    assert labels.sum() == 100
    assert set(df["Label"]) > {"BENIGN"}  # real attack-class names present

    df = make_synthetic_unsw(400, attack_fraction=0.25, seed=3)
    labels = get_dataset("unswnb15").binary_labels(df)
    assert labels.sum() == 100
    # Attack rows are statistically separable on the templated columns.
    assert df.loc[labels == 1, "rate"].min() > df.loc[labels == 0, "rate"].max()


def test_corpus_concat_rebases_source_ids():
    a = corpus_from_frame(make_synthetic("cicids2017", 30, seed=0), get_dataset("cicids2017"))
    b = corpus_from_frame(make_synthetic_unsw(20, seed=0), get_dataset("unswnb15"))
    mixed = concat_corpora([a, b])
    assert len(mixed) == 50
    assert mixed.source_names == ("cicids2017", "unswnb15")
    assert mixed.source[:30].tolist() == [0] * 30
    assert mixed.source[30:].tolist() == [1] * 20


def test_corpus_length_mismatch_raises():
    with pytest.raises(ValueError, match="length mismatch"):
        Corpus(["a"], np.zeros(2, np.int32), np.zeros(1, np.int32))


def test_parse_source_arg():
    assert parse_source_arg("unswnb15=/tmp/u.csv") == ("unswnb15", "/tmp/u.csv")
    assert parse_source_arg("/tmp/plain.csv") == (None, "/tmp/plain.csv")
    with pytest.raises(ValueError, match="unknown dataset"):
        parse_source_arg("bogus=/tmp/x.csv")


def test_mixed_corpus_end_to_end(tmp_path):
    """Two schemas on disk -> auto-detected mixed corpus -> disjoint
    2-client splits -> tokenized static-shape arrays."""
    p1 = tmp_path / "ddos2019.csv"
    p2 = tmp_path / "unsw.csv"
    write_synthetic_csv(str(p1), dataset="cicddos2019", n_rows=300, seed=1)
    write_synthetic_csv(str(p2), dataset="unswnb15", n_rows=300, seed=2)

    corpus = load_mixed_corpus([(None, str(p1)), (None, str(p2))])
    assert corpus.source_names == ("cicddos2019", "unswnb15")
    assert len(corpus) == 600

    cfg = DataConfig(partition="disjoint", data_fraction=0.5, max_len=64)
    splits = make_all_client_splits_from_corpus(corpus, 2, cfg)
    assert len(splits) == 2
    # Disjoint: both clients together cover the corpus exactly once.
    n_total = sum(len(s.train) + len(s.val) + len(s.test) for s in splits)
    assert n_total == 600

    tok = default_tokenizer()
    client = tokenize_client(splits[0], tok, max_len=64)
    assert client.train.input_ids.shape[1] == 64
    # Both schemas' text tokenizes without [UNK].
    assert not (client.train.input_ids == tok.unk_id).any()


def test_corpus_sample_partition_matches_fraction():
    corpus = corpus_from_frame(
        make_synthetic_unsw(200, seed=5), get_dataset("unswnb15")
    )
    cfg = DataConfig(partition="sample", data_fraction=0.1, max_len=32)
    splits = make_all_client_splits_from_corpus(corpus, 3, cfg)
    for s in splits:
        assert len(s.train) + len(s.val) + len(s.test) == 20


def test_frame_path_honors_dataset_config():
    """make_all_client_splits with dataset='unswnb15' partitions on the 0/1
    label column and renders the UNSW template."""
    df = make_synthetic_unsw(200, seed=4)
    cfg = DataConfig(dataset="unswnb15", partition="disjoint", data_fraction=0.5, max_len=32)
    splits = make_all_client_splits(df, 2, cfg)
    assert splits[0].train.texts[0].startswith("Protocol is ")
    all_labels = np.concatenate(
        [np.concatenate([s.train.labels, s.val.labels, s.test.labels]) for s in splits]
    )
    assert set(all_labels.tolist()) == {0, 1}
