"""Position-keyed hash dropout (ops/hash_dropout.py): the mask primitive
behind seq-shard-invariant dropout (models/distilbert.py _seq_dropout,
parallel/ring_attention.py attention dropout)."""

import numpy as np

import jax
import jax.numpy as jnp

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.ops.hash_dropout import (
    hash_dropout,
    hash_keep_mask,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.mesh import shard_map



def _seed(i=0):
    return jax.random.bits(jax.random.key(i), (2,), jnp.uint32)


def test_keep_rate_and_determinism():
    m = hash_keep_mask(_seed(), (64, 64), 0.3)
    m2 = hash_keep_mask(_seed(), (64, 64), 0.3)
    np.testing.assert_array_equal(np.asarray(m), np.asarray(m2))
    np.testing.assert_allclose(np.asarray(m).mean(), 0.7, atol=0.03)
    # Different seeds -> different masks; rate 0 keeps everything.
    assert not np.array_equal(np.asarray(m), np.asarray(hash_keep_mask(_seed(1), (64, 64), 0.3)))
    assert np.asarray(hash_keep_mask(_seed(), (8, 8), 0.0)).all()


def test_offset_slices_reproduce_global_mask():
    """THE invariance property: a shard hashing positions [k, k+Ls) along
    the offset axis reproduces exactly the global mask's slice — so any
    seq shard count samples the same mask."""
    full = np.asarray(hash_keep_mask(_seed(), (4, 32, 8), 0.4, offsets={}))
    for n_shards in (2, 4):
        ls = 32 // n_shards
        parts = [
            np.asarray(
                hash_keep_mask(
                    _seed(), (4, ls, 8), 0.4, offsets={1: i * ls}
                )
            )
            for i in range(n_shards)
        ]
        np.testing.assert_array_equal(np.concatenate(parts, axis=1), full)


def test_batch_axis_offsets_give_data_shards_independent_masks():
    """Rows on different data shards must not reuse one mask: the axis-0
    (batch) offset reproduces the global mask's row slices, which are
    mutually distinct — the models' _drop_offsets wiring depends on it."""
    full = np.asarray(hash_keep_mask(_seed(), (8, 16, 4), 0.4, offsets={}))
    top = np.asarray(hash_keep_mask(_seed(), (4, 16, 4), 0.4, offsets={0: 0}))
    bot = np.asarray(hash_keep_mask(_seed(), (4, 16, 4), 0.4, offsets={0: 4}))
    np.testing.assert_array_equal(np.concatenate([top, bot], axis=0), full)
    assert not np.array_equal(top, bot)


def test_hash_dropout_scales_and_zeroes():
    x = jnp.ones((16, 16), jnp.float32)
    key = jax.random.key(5)
    y = np.asarray(hash_dropout(x, 0.25, key))
    kept = y > 0
    np.testing.assert_allclose(y[kept], 1.0 / 0.75, rtol=1e-6)
    np.testing.assert_allclose(kept.mean(), 0.75, atol=0.08)
    # deterministic=True and rate 0 are identity.
    np.testing.assert_array_equal(
        np.asarray(hash_dropout(x, 0.25, key, deterministic=True)), np.asarray(x)
    )
    np.testing.assert_array_equal(
        np.asarray(hash_dropout(x, 0.0, key)), np.asarray(x)
    )


import pytest


@pytest.mark.slow
def test_model_seq_dropout_invariance_via_ring(eight_devices):
    """End-to-end through the model: the same forward (dropout ON) under
    shard_map at seq=1 vs seq=4 produces identical logits. (Slow: three
    full-model shard_map compiles; the mask-level invariance runs in the
    fast lane, test_offset_slices_reproduce_global_mask.)"""
    from jax.sharding import Mesh, PartitionSpec as P

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        ModelConfig,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.distilbert import (
        DDoSClassifier,
        init_params,
    )

    L = 16
    cfg = ModelConfig.tiny(
        max_len=L,
        max_position_embeddings=L,
        dropout=0.2,
        attention_dropout=0.2,
        head_dropout=0.3,
        attention_impl="ring",
        ring_axis="seq",
    )
    model = DDoSClassifier(cfg)
    params = init_params(model, cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(1, 200, (4, L)).astype(np.int32))
    mask = jnp.ones((4, L), jnp.int32)
    key = jax.random.key(9)

    def logits_at(n_seq):
        mesh = Mesh(
            np.array(jax.devices()[:n_seq]).reshape(n_seq), ("seq",)
        )
        fn = shard_map(
            lambda i, m: model.apply(
                {"params": params}, i, m, False, rngs={"dropout": key}
            ),
            mesh=mesh,
            in_specs=(P(None, "seq"), P(None, "seq")),
            out_specs=P(),
        )
        return np.asarray(fn(ids, mask))

    l1, l2, l4 = logits_at(1), logits_at(2), logits_at(4)
    np.testing.assert_allclose(l2, l1, atol=1e-5)
    np.testing.assert_allclose(l4, l1, atol=1e-5)
    # And dropout is active: deterministic forward differs.
    det = model.apply({"params": params}, ids, mask, True)
    assert not np.allclose(l1, np.asarray(det), atol=1e-5)
