"""Device performance plane (ISSUE 12): the XLA compile ledger's
per-(site, signature) accounting + recompile flagging, strided fenced
step-time attribution (and its zero-overhead off path), memory
watermarks degrading gracefully on stats-less backends, the
analytic-vs-XLA FLOPs cross-check, the serving engine's ledger dedupe,
the AlertManager --alert-cmd notification fan-out, and the
`fedtpu obs profile` CLI."""

import json

import jax
import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
    TokenizedSplit,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
    SLO,
    AlertManager,
    FlightRecorder,
    MetricsRegistry,
    set_global_recorder,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.profile import (
    FLOPS_RATIO_TOLERANCE,
    CompileLedger,
    StepProfiler,
    device_memory_stats,
    flops_ratio_ok,
    maybe_step_profiler,
    note_memory,
    profiled_step_iter,
    run_profile_session,
    set_profile_stride,
    xla_cost_flops,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
    Trainer,
)


def _tiny_split(n: int = 16, seed: int = 0) -> TokenizedSplit:
    cfg = ModelConfig.tiny()
    r = np.random.default_rng(seed)
    return TokenizedSplit(
        r.integers(1, cfg.vocab_size, (n, cfg.max_len)).astype(np.int32),
        np.ones((n, cfg.max_len), np.int32),
        r.integers(0, 2, n).astype(np.int32),
    )


# ------------------------------------------------------------ compile ledger
def test_ledger_counts_per_site_and_signature():
    """One note per traced shape: a repeat call at a warm shape counts
    nothing, a new shape counts one, and the timed wrapper attributes
    the compiling call's wall seconds to the ledger."""
    reg = MetricsRegistry()
    led = CompileLedger(registry=reg)
    note = led.hook("t.step")

    @jax.jit
    def f(x):
        note(tuple(x.shape))
        return x * 2

    ft = led.timed("t.step", f)
    ft(np.ones((2,), np.float32))
    ft(np.ones((2,), np.float32))  # warm: no new trace
    ft(np.ones((3,), np.float32))
    assert led.compile_counts("t.step") == {(2,): 1, (3,): 1}
    rep = led.report()
    assert rep["sites"]["t.step"]["compiles"] == 2
    assert rep["sites"]["t.step"]["signatures"] == 2
    # The wrapper timed both compiling calls: wall seconds attributed.
    assert rep["sites"]["t.step"]["trace_s"] > 0.0
    assert rep["recompiles"] == []
    # /metrics families carry the same counts.
    snap = reg.snapshot()["families"]
    assert snap["fedtpu_xla_compiles_total"]["samples"][0]["value"] == 2.0


def test_recompile_storm_exactly_one_event_per_new_signature():
    """The seeded recompile-storm contract: after mark_warm, each NEW
    signature is flagged exactly once — repeats of a flagged shape and
    of pre-warm shapes never re-flag."""
    reg = MetricsRegistry()
    led = CompileLedger(registry=reg)
    note = led.hook("t.step")

    @jax.jit
    def f(x):
        note(tuple(x.shape))
        return x + 1

    f(np.ones((2,), np.float32))
    led.mark_warm("t.step")
    # The storm: three novel shapes, each traced once, called twice.
    for n in (4, 5, 6, 4, 5, 6, 2):
        f(np.ones((n,), np.float32))
    events = led.recompiles("t.step")
    assert [e["signature"] for e in events] == [(4,), (5,), (6,)]
    snap = reg.snapshot()["families"]
    assert (
        snap["fedtpu_xla_recompiles_total"]["samples"][0]["value"] == 3.0
    )


def test_recompile_trips_flight_recorder(tmp_path):
    """A recompile at a warm site is a flight-recorder trigger: the
    installed recorder dumps an xla-recompile postmortem bundle."""
    rec = FlightRecorder(str(tmp_path), proc="prof", min_interval_s=0.0)
    set_global_recorder(rec)
    try:
        led = CompileLedger(registry=MetricsRegistry())
        note = led.hook("t.step")

        @jax.jit
        def f(x):
            note(tuple(x.shape))
            return x

        f(np.ones((2,), np.float32))
        led.mark_warm()
        f(np.ones((3,), np.float32))
    finally:
        set_global_recorder(None)
    bundles = list(tmp_path.glob("postmortem-*.json"))
    assert len(bundles) == 1
    b = json.loads(bundles[0].read_text())
    assert b["reason"] == "xla-recompile"
    assert b["extra"]["site"] == "t.step"


def test_ledger_untimed_site_counts_without_wrapper():
    """A site registered with only the trace hook (no timed wrapper)
    still counts compiles — trace seconds just stay unattributed."""
    led = CompileLedger(registry=MetricsRegistry())
    note = led.hook("bare")

    @jax.jit
    def f(x):
        note(tuple(x.shape))
        return x

    f(np.ones((2,), np.float32))
    assert led.compile_counts("bare") == {(2,): 1}
    assert led.report()["sites"]["bare"]["trace_s"] == 0.0


# -------------------------------------------------------- step attribution
def test_step_profiler_zero_stride_is_off():
    """Stride 0 is the zero-overhead path: disabled, never samples,
    registers NO metric families, and the module-level hook returns
    None so hot loops keep the literal unprofiled shape."""
    reg = MetricsRegistry()
    prof = StepProfiler(0, site="train", registry=reg)
    assert not prof.enabled
    assert all(not prof.tick() for _ in range(5))
    assert prof.summary() == {}
    assert prof.span_attrs() == {}
    assert reg.snapshot()["families"] == {}
    set_profile_stride(0)
    assert maybe_step_profiler("train") is None
    # The loop shim passes straight through with no profiler.
    assert [b for b, s in profiled_step_iter(None, iter([1, 2, 3]))] == [
        1, 2, 3,
    ]


def test_step_profiler_stride_sampling_and_summary():
    reg = MetricsRegistry()
    prof = StepProfiler(2, site="train", registry=reg)
    assert [prof.tick() for _ in range(5)] == [
        True, False, True, False, True,
    ]
    for dt in (0.010, 0.020, 0.030):
        prof.note_host(0.001)
        prof.note_dispatch(0.002)
        prof._note("device", dt)
    s = prof.summary()
    assert s["device"]["n"] == 3
    assert s["device"]["p50"] == pytest.approx(0.020)
    attrs = prof.span_attrs()
    assert attrs["step_device_ms_p50"] == pytest.approx(20.0)
    assert attrs["step_sampled"] == 3
    fam = reg.snapshot()["families"]["fedtpu_train_step_seconds"]
    by_phase = {
        s["labels"]["phase"]: s["count"] for s in fam["samples"]
    }
    assert by_phase == {"host": 3, "dispatch": 3, "device": 3}


def test_step_profiler_window_attrs_reset_per_fit():
    """begin_window CLEARS the sample lists (a long-lived daemon must
    never fill the bound once and silently stop reporting)."""
    prof = StepProfiler(1, site="train", registry=MetricsRegistry())
    prof._note("device", 1.0)
    prof.begin_window()
    assert prof.span_attrs() == {}  # nothing sampled THIS window
    prof._note("device", 0.004)
    attrs = prof.span_attrs()
    assert attrs["step_device_ms_p50"] == pytest.approx(4.0)
    assert attrs["step_sampled"] == 1
    # Even after max_samples windows, a fresh window still reports.
    prof._samples["device"].extend([0.001] * prof._max_samples)
    prof.begin_window()
    prof._note("device", 0.002)
    assert prof.span_attrs()["step_sampled"] == 1


def test_engine_fit_records_all_three_phases():
    """The real fit loop under a stride-1 profiler: host batch-prep,
    dispatch, and fenced device-execute all sampled; attrs exposed for
    the client-local span."""
    cfg = ModelConfig.tiny()
    trainer = Trainer(cfg, TrainConfig(epochs_per_round=1))
    trainer.step_profiler = StepProfiler(
        1, site="train", registry=MetricsRegistry()
    )
    state = trainer.init_state(seed=0)
    state, _ = trainer.fit(state, _tiny_split(16), batch_size=8)
    s = trainer.step_profiler.summary()
    assert set(s) == {"host", "dispatch", "device"}
    assert s["device"]["n"] == 2  # 16 rows / bs 8, every step sampled
    attrs = trainer.step_profile_attrs()
    assert attrs["step_sampled"] == 2
    assert "step_device_ms_p50" in attrs
    # Profiling off: the attrs helper degrades to {}.
    bare = Trainer(cfg, TrainConfig(epochs_per_round=1))
    assert bare.step_profiler is None
    assert bare.step_profile_attrs() == {}


# ------------------------------------------------------- memory watermarks
def test_note_memory_graceful_on_statsless_backend(monkeypatch):
    """A backend whose memory_stats() is None/missing records the phase
    as unavailable — no gauges, no exception (the CPU tier-1 lane)."""
    import detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.profile as prof_mod

    class _Dev:
        def memory_stats(self):
            return None

    reg = MetricsRegistry()
    assert note_memory("t-none", device=_Dev(), registry=reg) is None
    assert prof_mod.memory_report()["t-none"] == {"available": False}
    assert reg.snapshot()["families"] == {}


def test_note_memory_records_watermark_gauges():
    import detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.profile as prof_mod

    class _Dev:
        def __init__(self, in_use, peak):
            self._s = {"bytes_in_use": in_use, "peak_bytes_in_use": peak}

        def memory_stats(self):
            return self._s

    reg = MetricsRegistry()
    snap = note_memory("t-dev", device=_Dev(100, 150), registry=reg)
    assert snap["bytes_in_use"] == 100.0 and snap["peak_bytes"] == 150.0
    # Watermark semantics: a later lower reading keeps the high peak.
    snap = note_memory("t-dev", device=_Dev(50, 60), registry=reg)
    assert snap["peak_bytes"] == 150.0
    fams = reg.snapshot()["families"]
    assert (
        fams["fedtpu_device_bytes_in_use"]["samples"][0]["value"] == 50.0
    )
    assert (
        fams["fedtpu_device_peak_bytes"]["samples"][0]["value"] == 150.0
    )
    assert prof_mod.peak_device_bytes() >= 150.0


def test_device_memory_stats_never_raises():
    class _Raises:
        def memory_stats(self):
            raise RuntimeError("backend says no")

    assert device_memory_stats(_Raises()) is None
    assert device_memory_stats(object()) is None


# ------------------------------------------------------ FLOPs cross-check
def test_xla_cost_flops_vs_analytic_within_tolerance():
    """The MFU anchor: XLA's own cost-model FLOPs for the compiled tiny
    train step sit inside the documented tolerance of the analytic
    model (utils/profiling.train_step_flops)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.utils.profiling import (
        train_step_flops,
    )

    cfg = ModelConfig.tiny()
    trainer = Trainer(cfg, TrainConfig())
    state = trainer.init_state(seed=0)
    r = np.random.default_rng(0)
    batch = {
        "input_ids": r.integers(
            0, cfg.vocab_size, (4, cfg.max_len)
        ).astype(np.int32),
        "attention_mask": np.ones((4, cfg.max_len), np.int32),
        "labels": r.integers(0, 2, 4).astype(np.int32),
    }
    xla = xla_cost_flops(trainer.train_step, state, batch)
    if xla is None:
        pytest.skip("backend exposes no cost model")
    ratio = xla / train_step_flops(cfg, 4)
    lo, hi = FLOPS_RATIO_TOLERANCE
    assert lo <= ratio <= hi
    assert flops_ratio_ok(ratio)
    assert flops_ratio_ok(None)  # no cost model is not a failure
    assert not flops_ratio_ok(hi * 2)


def test_xla_cost_flops_unlowerable_returns_none():
    assert xla_cost_flops(lambda x: x, 1) is None


# ------------------------------------------------- serving ledger dedupe
def test_serving_engine_rides_shared_ledger():
    """The serving tier's compile_counts now IS a CompileLedger view:
    same numbers as the pre-ledger dict, per-engine isolation, site
    marked warm by warmup(), zero recompiles through the bucket storm."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.distilbert import (
        DDoSClassifier,
        init_params,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving.engine import (
        ScoreEngine,
    )

    cfg = ModelConfig.tiny()
    params = init_params(DDoSClassifier(cfg), cfg, jax.random.key(0))
    eng = ScoreEngine(cfg, params, buckets=(1, 4))
    eng.warmup()
    L = cfg.max_len
    assert eng.compile_counts == {(1, L): 1, (4, L): 1}
    r = np.random.default_rng(0)
    for n in (1, 2, 3, 4, 1):
        ids = r.integers(0, cfg.vocab_size, (n, L)).astype(np.int32)
        eng.score(ids, np.ones((n, L), np.int32))
    assert eng.compile_counts == {(1, L): 1, (4, L): 1}
    assert eng.ledger.recompiles() == []
    # A second engine's counts are its own (private ledger).
    eng2 = ScoreEngine(cfg, params, buckets=(1,))
    assert eng2.compile_counts == {}


# ------------------------------------------------------- alert-cmd fan-out
_SLO = SLO(
    name="round-duration",
    metric="fedtpu_server_round_seconds",
    kind="latency",
    le=0.5,
    objective=0.9,
    windows=((120.0, 6.0), (30.0, 6.0)),
)


def _latency_families(good: int, bad: int) -> dict:
    total = good + bad
    return {
        "fedtpu_server_round_seconds": {
            "type": "histogram",
            "help": "",
            "samples": [
                {
                    "labels": {},
                    "buckets": [
                        ["0.1", 0],
                        ["0.5", good],
                        ["5", total],
                        ["+Inf", total],
                    ],
                    "sum": 1.0,
                    "count": total,
                }
            ],
        }
    }


def _fire_once(am: AlertManager, *, t0: float = 0.0) -> list:
    am.ingest(_latency_families(good=5, bad=0), now=t0)
    am.evaluate(now=t0)
    am.ingest(_latency_families(good=5, bad=4), now=t0 + 10.0)
    return am.evaluate(now=t0 + 10.0)


def test_alert_cmd_runs_on_page_fire(tmp_path):
    """--alert-cmd: one spawn per page fire, the event JSON on stdin."""
    out = tmp_path / "paged.jsonl"
    am = AlertManager(
        (_SLO,), alert_cmd=f"cat >> {out}", alert_cmd_interval_s=0.0
    )
    events = _fire_once(am)
    assert [e["event"] for e in events] == ["fire"]
    # Popen is fire-and-forget; wait for the pager to land.
    import time as _t

    deadline = _t.monotonic() + 5.0
    while _t.monotonic() < deadline and not out.exists():
        _t.sleep(0.02)
    while _t.monotonic() < deadline and not out.read_text().strip():
        _t.sleep(0.02)
    rec = json.loads(out.read_text().splitlines()[0])
    assert rec["event"] == "fire" and rec["slo"] == "round-duration"
    assert am.notified_total == 1


def test_alert_cmd_rate_limited_on_event_clock():
    """Two page fires inside the interval -> one spawn (the limiter
    rides the events' own ts — the injectable clock — so the test needs
    no sleeps): two SLOs breach on the same snapshots, the second
    page is suppressed."""
    slo2 = SLO(
        name="round-duration-strict",
        metric="fedtpu_server_round_seconds",
        kind="latency",
        le=0.5,
        objective=0.95,
        windows=((120.0, 6.0), (30.0, 6.0)),
    )
    am = AlertManager(
        (_SLO, slo2), alert_cmd="true", alert_cmd_interval_s=300.0
    )
    events = _fire_once(am)
    assert [e["event"] for e in events] == ["fire", "fire"]
    assert am.fired_total == 2
    assert am.notified_total == 1
    assert am.notify_suppressed_total == 1


def test_alert_cmd_oserror_never_kills_the_loop(monkeypatch):
    """A broken pager (Popen raising) is swallowed; the state machine
    and the fire event survive untouched."""
    import subprocess

    def _boom(*a, **kw):
        raise OSError("no shell for you")

    monkeypatch.setattr(subprocess, "Popen", _boom)
    am = AlertManager((_SLO,), alert_cmd="whatever", alert_cmd_interval_s=0.0)
    events = _fire_once(am)
    assert [e["event"] for e in events] == ["fire"]
    assert am.notified_total == 0


def test_alert_cmd_ignores_non_page_events(tmp_path):
    """Ticket-severity fires and clears never page."""
    ticket = SLO(
        name="t",
        metric="fedtpu_server_round_seconds",
        kind="latency",
        le=0.5,
        objective=0.9,
        windows=((120.0, 6.0), (30.0, 6.0)),
        severity="ticket",
    )
    am = AlertManager(
        (ticket,), alert_cmd="false", alert_cmd_interval_s=0.0
    )
    events = _fire_once(am)
    assert [e["event"] for e in events] == ["fire"]
    assert am.notified_total == 0 and am.notify_suppressed_total == 0


# --------------------------------------------------------- session + CLI
def test_run_profile_session_tiny_end_to_end():
    rep = run_profile_session(
        ModelConfig.tiny(), TrainConfig(), steps=4, batch_size=4, stride=1
    )
    assert rep["recompiles"] == []
    assert rep["flops_ratio_ok"]
    assert set(rep["step"]) == {"host", "dispatch", "device"}
    assert rep["serving"]["recompiles"] == 0
    assert rep["serving"]["compiles"] == 2  # the (1, 4) bucket ladder
    # Memory phases visited (available or gracefully not).
    assert "post-first-step" in rep["memory"]
    assert "post-round" in rep["memory"]
    assert rep["flops_tolerance"] == list(FLOPS_RATIO_TOLERANCE)


def test_obs_profile_cli_json(capsys):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        build_parser,
    )

    args = build_parser().parse_args(
        [
            "obs", "profile", "--preset", "tiny", "--steps", "2",
            "--batch-size", "4", "--json",
        ]
    )
    rc = args.fn(args)
    out = capsys.readouterr().out
    rep = json.loads(out[out.index("{"):])
    assert rc == 0
    assert rep["serving"]["recompiles"] == 0
    assert rep["flops_ratio_ok"]


def test_obs_profile_cli_renders_report(capsys):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        build_parser,
    )

    args = build_parser().parse_args(
        ["obs", "profile", "--preset", "tiny", "--steps", "2",
         "--batch-size", "4"]
    )
    rc = args.fn(args)
    out = capsys.readouterr().out
    assert rc == 0
    assert "compile ledger" in out
    assert "flops cross-check" in out
    assert "serving bucketed path" in out


def test_xla_compile_span_in_vocabulary_and_timeline():
    """The new span is IN the closed vocabulary (the obs-span-vocab
    static pass anchors on SPAN_NAMES) and the timeline renders it in
    the unscoped trailing section rather than dropping it."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
        SPAN_NAMES,
        timeline_table,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.trace import (
        SCHEMA,
    )

    assert "xla-compile" in SPAN_NAMES
    spans = [
        {
            "schema": SCHEMA, "proc": "client-0", "span": "xla-compile",
            "ts": 1.0, "dur_s": 0.8, "site": "engine.train_step",
            "signature": "(16, 128)", "recompile": True,
        },
    ]
    table = timeline_table(spans)
    assert "xla-compile" in table
    assert "site=engine.train_step" in table


def test_profile_stride_config_flag_round_trip():
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        ObsConfig,
    )

    assert ObsConfig().profile_stride == 0
    assert ObsConfig(profile_stride=8).profile_stride == 8
    with pytest.raises(ValueError):
        ObsConfig(profile_stride=-1)


@pytest.mark.slow
def test_client_local_span_attrs_via_federated_fit(tmp_path):
    """The dense federated fit loop stamps sampled step attrs on its
    client-local span when a profiler is armed."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        DataConfig,
        ExperimentConfig,
        FedConfig,
        MeshConfig,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        stack_clients,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
        Tracer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.federated import (
        FederatedTrainer,
    )

    model = ModelConfig.tiny()
    cfg = ExperimentConfig(
        model=model,
        data=DataConfig(max_len=model.max_len, batch_size=4),
        train=TrainConfig(epochs_per_round=1),
        fed=FedConfig(num_clients=2, rounds=1),
        mesh=MeshConfig(clients=1, data=1),
    )
    trainer = FederatedTrainer(cfg)
    path = tmp_path / "spans.jsonl"
    trainer.tracer = Tracer(str(path), proc="fed")
    trainer.step_profiler = StepProfiler(
        1, site="train", registry=MetricsRegistry()
    )
    state = trainer.init_state(seed=0)
    stacked = stack_clients([_tiny_split(8, 1), _tiny_split(8, 2)])
    trainer.fit_local(state, stacked)
    recs = [json.loads(ln) for ln in path.read_text().splitlines()]
    local = [r for r in recs if r["span"] == "client-local"]
    assert len(local) == 1
    assert local[0]["step_sampled"] >= 1
    assert "step_device_ms_p50" in local[0]
