"""Serving replica fleet (ISSUE 9): the router tier (router/), the
pipelined/async SDK, the stats wire frame, and rolling hot-reload.

Contracts pinned here:

* Probabilities through the router are BIT-IDENTICAL to the replica's
  own replies (the id rewrite touches only the id bytes).
* Least-in-flight routing spreads live traffic across healthy replicas;
  drained or ejected replicas leave the pick set and readmit cleanly.
* A registry promotion against a running fleet rolling-reloads every
  replica under load with ZERO dropped requests (the bench's
  ``router_rolling_reload_dropped == 0`` contract, test-scale), emits
  ``replica-drain`` spans, and records per-replica reload events on the
  registry's audit trail.
* The pipelined and async clients match replies to requests by id —
  out-of-order replies resolve the right futures.
* ``run_load(target_qps=...)`` paces the request schedule open-loop.
"""

import json
import threading
import time

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.wire import (
    WireError,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    default_tokenizer,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.router import (
    FleetReplica,
    ScoringRouter,
    ServingFleet,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
    AsyncScoringClient,
    PipelinedScoringClient,
    ScoringClient,
    fetch_stats,
    protocol,
    run_load,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
    Trainer,
)

TEXTS = [
    f"Destination port is {p}. Flow duration is {d} microseconds. "
    f"Total forward packets are {n}."
    for p, d, n in [
        (80, 100, 3),
        (443, 2500, 9),
        (8080, 7, 1),
        (53, 120000, 44),
    ]
]


@pytest.fixture(scope="module")
def tiny_setup():
    tok = default_tokenizer()
    model_cfg = ModelConfig.tiny(vocab_size=len(tok.vocab))
    trainer = Trainer(model_cfg, TrainConfig(), pad_id=tok.pad_id)
    params = trainer.init_state(seed=0).params
    params2 = trainer.init_state(seed=1).params
    return tok, model_cfg, trainer, params, params2


def _replica(tiny_setup, replica_id=0, *, params=None, round_id=1, **kw):
    tok, model_cfg, _trainer, p1, _p2 = tiny_setup
    kw.setdefault("buckets", (1, 4))
    kw.setdefault("gather_window_s", 0.002)
    return FleetReplica(
        replica_id,
        model_cfg,
        params if params is not None else p1,
        tok,
        round_id=round_id,
        **kw,
    ).start()


@pytest.fixture(scope="module")
def shared_replica(tiny_setup):
    """One warm no-auth replica reused by every single-replica test —
    each engine spin-up pays the bucket jit, so tests share it."""
    rep = _replica(tiny_setup, replica_id=7)
    yield rep
    rep.close()


def _expected_probs(tiny_setup, texts):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
    )

    tok, model_cfg, trainer, params, _ = tiny_setup
    enc = tok.batch_encode(texts, max_len=model_cfg.max_len)
    split = TokenizedSplit(
        enc["input_ids"],
        enc["attention_mask"],
        np.zeros(len(texts), np.int32),
    )
    return trainer.evaluate(params, split, batch_size=4)["probs"]


# ----------------------------------------------------------- stats frame
def test_stats_frame_roundtrip_and_replica_id(tiny_setup, shared_replica):
    """The in-band stats probe answers from the reader thread with the
    replica's identity stamped — the router's health/telemetry source."""
    with ScoringClient("127.0.0.1", shared_replica.port) as cli:
        cli.score(text=TEXTS[0])
        s = cli.stats()
    assert s["replica"] == 7
    assert s["scored"] >= 1
    assert s["round"] == 1


def test_frame_id_and_rewrite_unit():
    """The router's id remap: fast-path splice and JSON fallback both
    preserve every non-id byte's VALUE; non-scoring frames refuse."""
    rep = protocol.build_reply(
        3,
        prob=0.123456789012345,
        threshold=0.5,
        round_id=9,
        batch_size=2,
        bucket=4,
        queue_ms=1.25,
    )
    out = protocol.rewrite_id(rep, 77)
    body = protocol.parse_reply(out)
    assert body["id"] == 77
    assert body["prob"] == 0.123456789012345  # bit-exact double
    assert protocol.frame_id(out) == 77
    # Rejects and stats frames remap too (everything the router relays).
    rej = protocol.rewrite_id(
        protocol.build_reject(5, code=503, reason="x"), 6
    )
    assert protocol.parse_reject(rej)["id"] == 6
    st = protocol.rewrite_id(protocol.build_stats_request(1), 2)
    assert protocol.parse_stats_request(st)["id"] == 2
    # Non-canonical body (id not leading) takes the JSON fallback.
    weird = rep[:4] + json.dumps(
        {"prob": 0.5, "id": 3, "prediction": 1, "round": 0, "batch_size": 1}
    ).encode()
    assert protocol.frame_id(weird) == 3
    assert protocol.parse_reply(protocol.rewrite_id(weird, 8))["id"] == 8
    with pytest.raises(WireError):
        protocol.frame_id(b"XXXX{}")
    with pytest.raises(WireError):
        protocol.rewrite_id(b"XXXX{}", 1)


# ------------------------------------------------------------ the router
def test_router_routes_bit_exact_spreads_and_drains(tiny_setup):
    """Two replicas behind the router: replies through the router are
    bit-identical to the predict pipeline's probabilities, concurrent
    load reaches BOTH replicas (least-in-flight), and a drained replica
    leaves the pick set until readmitted."""
    reps = [_replica(tiny_setup, i) for i in range(2)]
    router = ScoringRouter(
        [("127.0.0.1", r.port) for r in reps], probe_interval_s=0.2
    )
    try:
        router.start()
        want = _expected_probs(tiny_setup, TEXTS)
        with ScoringClient("127.0.0.1", router.port) as cli:
            for text, p in zip(TEXTS, want):
                reply = cli.score(text=text)
                assert reply["prob"] == float(np.float32(p))
                assert reply["round"] == 1
        # Concurrent fan-out: both replicas score.
        stats = run_load(
            "127.0.0.1", router.port, TEXTS, concurrency=4,
            requests=32, pipeline=4,
        )
        assert stats["scored"] == 32 and stats["rejected"] == 0
        per_rep = [
            fetch_stats("127.0.0.1", r.port)["scored"] for r in reps
        ]
        assert all(n > 0 for n in per_rep), per_rep
        # Drain replica 0: new traffic avoids it; readmit restores it.
        router.drain(0)
        assert router.wait_drained(0, timeout=10.0)
        before = fetch_stats("127.0.0.1", reps[0].port)["scored"]
        run_load(
            "127.0.0.1", router.port, TEXTS, concurrency=2, requests=8
        )
        assert fetch_stats("127.0.0.1", reps[0].port)["scored"] == before
        router.undrain(0)
        with ScoringClient("127.0.0.1", router.port) as cli:
            s = cli.stats()
        assert s["kind"] == "router" and s["healthy"] == 2
        assert not s["backends"][0]["draining"]
        # Fast-lane eject anchor: kill replica 1 — the router ejects it
        # and the survivor keeps serving (the full eject/readmit-with-
        # replacement flow rides the slow lane).
        reps[1].close()
        deadline = time.monotonic() + 10.0
        while router.stats()["healthy"] > 1:
            assert time.monotonic() < deadline, "eject never happened"
            time.sleep(0.05)
        with ScoringClient("127.0.0.1", router.port) as cli:
            assert cli.score(text=TEXTS[0])["round"] == 1
        assert router.stats()["backends"][1]["ejects"] >= 1
    finally:
        router.close()
        for r in reps:
            r.close()


@pytest.mark.slow
def test_router_ejects_dead_replica_and_readmits(tiny_setup):
    """Killing a replica ejects it (traffic keeps flowing on the
    survivor); a replacement on the same port is readmitted by the
    prober and serves again."""
    reps = [_replica(tiny_setup, i) for i in range(2)]
    port0 = reps[0].port
    router = ScoringRouter(
        [("127.0.0.1", r.port) for r in reps],
        probe_interval_s=0.1,
        probe_timeout_s=0.5,
    )
    try:
        router.start()
        reps[0].close()  # replica 0 dies
        deadline = time.monotonic() + 10.0
        while router.stats()["healthy"] > 1:
            assert time.monotonic() < deadline, "eject never happened"
            time.sleep(0.05)
        assert router.stats()["backends"][0]["ejects"] >= 1
        # Survivor keeps serving through the router.
        with ScoringClient("127.0.0.1", router.port) as cli:
            assert cli.score(text=TEXTS[0])["round"] == 1
        # Replacement replica on the SAME port -> readmitted.
        tok, model_cfg, _t, params, _p2 = tiny_setup
        from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
            MicroBatcher,
            ScoreEngine,
            ScoringServer,
        )

        engine = ScoreEngine(
            model_cfg, params, pad_id=tok.pad_id, buckets=(1, 4),
            round_id=5,
        )
        replacement = ScoringServer(
            engine,
            tok,
            port=port0,
            batcher=MicroBatcher(max_batch=4, gather_window_s=0.002),
            replica_id=0,
            warmup=False,
        ).start()
        try:
            deadline = time.monotonic() + 10.0
            while router.stats()["healthy"] < 2:
                assert time.monotonic() < deadline, "readmit never happened"
                time.sleep(0.05)
            # The readmitted replica's round shows via the probe stats.
            deadline = time.monotonic() + 5.0
            while router.stats()["backends"][0]["round"] != 5:
                assert time.monotonic() < deadline
                time.sleep(0.05)
        finally:
            replacement.close()
    finally:
        router.close()
        for r in reps:
            r.close()


def test_router_auth_end_to_end(tiny_setup):
    """With a key, the chain is authenticated at every hop: keyed sync
    AND async clients -> router -> keyed replica works; a keyless client
    is refused at the router exactly as at a bare replica."""
    import asyncio

    key = b"router-secret"
    rep = _replica(tiny_setup, 0, auth_key=key)
    router = ScoringRouter(
        [("127.0.0.1", rep.port)], auth_key=key, probe_interval_s=0.2
    )
    try:
        router.start()
        with ScoringClient(
            "127.0.0.1", router.port, auth_key=key
        ) as cli:
            assert cli.score(text=TEXTS[0])["round"] == 1
        with pytest.raises(WireError, match="auth"):
            with ScoringClient("127.0.0.1", router.port) as bad:
                bad.score(text=TEXTS[0])

        async def go():
            acli = await AsyncScoringClient.connect(
                "127.0.0.1", router.port, auth_key=key
            )
            try:
                return await acli.score(text=TEXTS[1])
            finally:
                await acli.close()

        assert asyncio.run(go())["round"] == 1
    finally:
        router.close()
        rep.close()


def test_malformed_body_gets_400_not_connection_drop(shared_replica):
    """A well-framed request whose body fails validation is answered
    with an explicit 400 reject — on a router deployment many clients
    share the backend connection, so a drop would sever them all."""
    import socket as _socket

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        framing,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.wire import (
        SCORE_REQ_MAGIC,
    )

    sock = _socket.create_connection(("127.0.0.1", shared_replica.port))
    try:
        bad = SCORE_REQ_MAGIC + b'{"id":9,"text":5}'  # wrong-typed body
        framing.send_frame(sock, bad, await_ack=False)
        reply = bytes(framing.recv_frame(sock, send_ack=False))
        body = protocol.parse_reject(reply)
        assert body["id"] == 9 and body["code"] == 400
        # The connection SURVIVED: a good request still scores.
        framing.send_frame(
            sock,
            protocol.build_request(10, text=TEXTS[0]),
            await_ack=False,
        )
        good = protocol.parse_reply(
            bytes(framing.recv_frame(sock, send_ack=False))
        )
        assert good["id"] == 10
    finally:
        sock.close()


# ------------------------------------------------------- pipelined/async
def test_pipelined_client_matches_replies_by_id(tiny_setup, shared_replica):
    """Many requests in flight on one connection resolve to the RIGHT
    replies (id-matched), bit-equal to the predict pipeline."""
    want = _expected_probs(tiny_setup, TEXTS)
    with PipelinedScoringClient("127.0.0.1", shared_replica.port) as cli:
        futs = [
            cli.submit(text=TEXTS[i % len(TEXTS)]) for i in range(16)
        ]
        for i, fut in enumerate(futs):
            reply = fut.result(timeout=30)
            assert reply["prob"] == float(
                np.float32(want[i % len(TEXTS)])
            )
        # stats pipelines like any request.
        assert cli.stats(timeout=10)["scored"] >= 16


def test_async_client_concurrent_scores_bit_exact(tiny_setup, shared_replica):
    """The asyncio SDK: concurrent tasks on one connection, id-matched,
    bit-equal to the sync path; stats works."""
    import asyncio

    want = _expected_probs(tiny_setup, TEXTS)

    async def go():
        cli = await AsyncScoringClient.connect(
            "127.0.0.1", shared_replica.port
        )
        try:
            replies = await asyncio.gather(
                *(cli.score(text=t) for t in TEXTS)
            )
            stats = await cli.stats()
        finally:
            await cli.close()
        return replies, stats

    replies, stats = asyncio.run(go())
    for reply, p in zip(replies, want):
        assert reply["prob"] == float(np.float32(p))
    assert stats["scored"] >= len(TEXTS)


def test_run_load_target_qps_paces_open_loop(shared_replica):
    """target_qps issues requests on the fleet-wide schedule: the run's
    wall tracks requests/qps (not the closed loop's equilibrium) and
    every request completes."""
    qps = 40.0
    n = 80
    stats = run_load(
        "127.0.0.1", shared_replica.port, TEXTS, concurrency=4,
        requests=n, target_qps=qps,
    )
    assert stats["scored"] == n and stats["rejected"] == 0
    # Schedule spans n/qps = 2 s; allow generous slack for the box.
    assert stats["wall_s"] >= n / qps * 0.9
    assert stats["flows_per_sec"] <= qps * 1.2


# -------------------------------------------------------- rolling reload
@pytest.mark.slow
def test_rolling_reload_zero_drop_spans_and_audit(tiny_setup, tmp_path):
    """The acceptance-shaped promotion: a registry pointer move against
    a fleet under closed-loop load swaps every replica to the new round
    with ZERO rejects, emits replica-drain spans, and records one
    registry reload event per replica. An architecture-mismatched
    artifact promoted first is refused fleet-wide (pointer guard)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
        Tracer,
        load_spans,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.registry import (
        ModelRegistry,
    )

    tok, model_cfg, _trainer, params, params2 = tiny_setup
    registry = ModelRegistry(str(tmp_path / "registry"))
    aid1 = registry.add(params, round_index=1, model_config=model_cfg)
    registry.promote(aid1, to="serving")
    tracer = Tracer(str(tmp_path / "fleet.jsonl"), proc="fleet")
    reps = [_replica(tiny_setup, i) for i in range(2)]
    fleet = ServingFleet(
        reps,
        registry=registry,
        probe_interval_s=0.2,
        reload_poll_s=0.1,
        tracer=tracer,
    ).start()
    try:
        # (1) Architecture guard: a mismatched artifact never swaps in.
        bad_cfg = model_cfg.replace(n_layers=model_cfg.n_layers + 1)
        bad_trainer = Trainer(bad_cfg, TrainConfig(), pad_id=tok.pad_id)
        bad_aid = registry.add(
            bad_trainer.init_state(seed=3).params,
            round_index=9,
            model_config=bad_cfg,
        )
        registry.promote(bad_aid, to="serving")
        time.sleep(0.5)
        assert fleet.stats()["reloads"] == 0
        assert [r.round_id for r in reps] == [1, 1]
        # (2) The real promotion, fired under load: zero drops.
        out = {}

        def loadgen():
            out["stats"] = run_load(
                "127.0.0.1", fleet.port, TEXTS, concurrency=4,
                requests=96, pipeline=4, timeout=60,
            )

        lt = threading.Thread(target=loadgen, daemon=True)
        lt.start()
        aid2 = registry.add(params2, round_index=2, model_config=model_cfg)
        registry.promote(aid2, to="serving")
        lt.join(timeout=90)
        assert not lt.is_alive()
        deadline = time.monotonic() + 15.0
        while fleet.stats()["reloads"] < 1:
            assert time.monotonic() < deadline, "rolling reload never ran"
            time.sleep(0.05)
        assert out["stats"]["rejected"] == 0
        assert out["stats"]["scored"] == 96
        assert [r.round_id for r in reps] == [2, 2]
        with ScoringClient("127.0.0.1", fleet.port) as cli:
            assert cli.score(text=TEXTS[0])["round"] == 2
        assert fleet.stats()["serving_artifact"] == aid2
    finally:
        fleet.close()
        for r in reps:
            r.close()
    # (3) Spans + audit trail.
    spans = load_spans([str(tmp_path / "fleet.jsonl")])
    drains = [s for s in spans if s["span"] == "replica-drain"]
    assert {s["replica"] for s in drains} == {0, 1}
    assert all(s["artifact"] == aid2 and s["round"] == 2 for s in drains)
    events = [
        json.loads(line)
        for line in (tmp_path / "registry" / "events.jsonl")
        .read_text()
        .splitlines()
    ]
    reloads = [e for e in events if e["event"] == "reload"]
    assert {e["consumer"] for e in reloads} == {"replica-0", "replica-1"}
    assert all(e["artifact"] == aid2 for e in reloads)


# ------------------------------------------------------------------- CLI
def test_router_cli_parser_wiring():
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        build_parser,
    )

    ap = build_parser()
    a = ap.parse_args(
        ["route", "--backend", "10.0.0.1:12380", "--backend",
         "10.0.0.2:12380", "--probe-interval", "0.5"]
    )
    assert a.fn.__name__ == "cmd_route"
    assert a.backend == ["10.0.0.1:12380", "10.0.0.2:12380"]
    assert a.probe_interval == 0.5
    a = ap.parse_args(
        ["fleet", "--registry-dir", "/tmp/reg", "--replicas", "4"]
    )
    assert a.fn.__name__ == "cmd_fleet" and a.replicas == 4
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.router import (
        _parse_backends,
    )

    assert _parse_backends(["host:1", ":2", "8.8.8.8:99"]) == [
        ("host", 1), ("127.0.0.1", 2), ("8.8.8.8", 99),
    ]
    with pytest.raises(SystemExit):
        _parse_backends(["nope"])
    with pytest.raises(SystemExit):
        _parse_backends([])
