"""FedAvg collective properties on a faked multi-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel import (
    FedShardings,
    fedavg,
    make_fedavg_step,
    make_mesh,
)


def _tree(C, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(size=(C, 4, 3)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(C, 3)).astype(np.float32)),
        "nested": {"k": jnp.asarray(rng.normal(size=(C, 2)).astype(np.float32))},
    }


def test_fedavg_identity_on_identical_models():
    base = _tree(1, seed=1)
    stacked = jax.tree.map(lambda x: jnp.broadcast_to(x[0][None], (4, *x.shape[1:])), base)
    out = fedavg(stacked)
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)


def test_fedavg_is_arithmetic_mean():
    t = _tree(3, seed=2)
    out = fedavg(t)
    for leaf, orig in zip(jax.tree.leaves(out), jax.tree.leaves(t)):
        expected = np.asarray(orig).mean(axis=0)
        for c in range(3):
            np.testing.assert_allclose(np.asarray(leaf)[c], expected, atol=1e-6)


def test_fedavg_weighted():
    t = _tree(2, seed=3)
    w = jnp.asarray([3.0, 1.0])
    out = fedavg(t, weights=w)
    for leaf, orig in zip(jax.tree.leaves(out), jax.tree.leaves(t)):
        o = np.asarray(orig)
        expected = (3 * o[0] + o[1]) / 4
        np.testing.assert_allclose(np.asarray(leaf)[0], expected, atol=1e-6)


def test_fedavg_masked_excludes_clients():
    t = _tree(4, seed=4)
    mask = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    out = fedavg(t, mask=mask)
    for leaf, orig in zip(jax.tree.leaves(out), jax.tree.leaves(t)):
        o = np.asarray(orig)
        expected = (o[0] + o[2]) / 2
        np.testing.assert_allclose(np.asarray(leaf)[1], expected, atol=1e-6)


def test_fedavg_on_mesh_collective(eight_devices):
    """Sharded over a real (faked-CPU) clients axis, the jitted step must
    produce the replicated mean on every client shard."""
    mesh = make_mesh(4, 2, devices=eight_devices)
    sh = FedShardings(mesh)
    t = _tree(4, seed=5)
    t_sharded = jax.device_put(t, sh.client)
    step = make_fedavg_step(sh)
    out = step(t_sharded, None, None)
    assert out["w"].sharding.spec == sh.client.spec
    expected = np.asarray(t["w"]).mean(axis=0)
    for c in range(4):
        np.testing.assert_allclose(np.asarray(out["w"])[c], expected, atol=1e-6)


def test_fedavg_matches_reference_inplace_mean():
    """Element-wise parity with the reference's aggregation loop
    (server.py:72-76: base += other; base /= N)."""
    t = _tree(3, seed=6)
    ours = fedavg(t)
    models = [jax.tree.map(lambda x, c=c: np.asarray(x)[c].copy(), t) for c in range(3)]
    ref = jax.tree_util.tree_map(
        lambda *xs: sum(xs[1:], xs[0].copy()) / len(xs), *models
    )
    for leaf, rleaf in zip(jax.tree.leaves(ours), jax.tree.leaves(ref)):
        np.testing.assert_allclose(np.asarray(leaf)[0], rleaf, atol=1e-6)


def test_mesh_requires_enough_devices(eight_devices):
    with pytest.raises(ValueError, match="needs 16 devices"):
        make_mesh(8, 2, devices=eight_devices)


def test_fit_clients_axis():
    """Replica stacking: largest clients-axis size dividing the logical
    client count that fits beside the data axis (the fast-lane unit check
    behind the slow-lane more-clients-than-mesh integration test)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.mesh import (
        fit_clients_axis,
    )

    assert fit_clients_axis(4, 2, 8) == 4   # 4x2 fits 8 devices
    assert fit_clients_axis(8, 2, 8) == 4   # 8 clients -> 4 rows, 2 each
    assert fit_clients_axis(64, 1, 8) == 8  # 8 replicas per row
    assert fit_clients_axis(3, 2, 8) == 3   # odd counts: 3x2 = 6 <= 8
    with pytest.raises(ValueError, match="data axis"):
        fit_clients_axis(4, 16, 8)
