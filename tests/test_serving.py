"""Online scoring service (serving/): protocol, batcher, bucketed engine,
and the end-to-end acceptance flow — concurrent clients coalescing into
one bucket dispatch, bit-for-bit parity with the predict pipeline,
explicit deadline rejects, hot checkpoint reload mid-traffic, and
exactly one XLA compilation per (bucket, seq) shape."""

import threading
import time

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
    ExperimentConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    default_tokenizer,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.datasets import (
    get_dataset,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
    TokenizedSplit,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
    CheckpointWatcher,
    MicroBatcher,
    ScoreEngine,
    ScoreRejected,
    ScoreRequest,
    ScoringClient,
    ScoringServer,
    protocol,
    run_load,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
    Trainer,
)

TEXTS = [
    f"Destination port is {p}. Flow duration is {d} microseconds. "
    f"Total forward packets are {n}."
    for p, d, n in [
        (80, 100, 3),
        (443, 2500, 9),
        (8080, 7, 1),
        (53, 120000, 44),
        (22, 31, 2),
        (3389, 9999, 17),
    ]
]


@pytest.fixture(scope="module")
def tiny_setup():
    tok = default_tokenizer()
    model_cfg = ModelConfig.tiny(vocab_size=len(tok.vocab))
    trainer = Trainer(model_cfg, TrainConfig(), pad_id=tok.pad_id)
    params = trainer.init_state(seed=0).params
    return tok, model_cfg, trainer, params


def _expected_probs(tok, trainer, params, texts, batch_size=16):
    """The predict pipeline's probabilities (cli/predict.py feed shape)."""
    enc = tok.batch_encode(texts, max_len=trainer.model_cfg.max_len)
    split = TokenizedSplit(
        enc["input_ids"],
        enc["attention_mask"],
        np.zeros(len(texts), np.int32),
    )
    return trainer.evaluate(params, split, batch_size=batch_size)["probs"]


# ----------------------------------------------------------------- protocol
def test_protocol_roundtrip_and_validation():
    req = protocol.parse_request(
        protocol.build_request(7, text="hello", deadline_ms=12.5)
    )
    assert req == {"id": 7, "text": "hello", "deadline_ms": 12.5}
    rep = protocol.parse_reply(
        protocol.build_reply(
            7,
            prob=0.25,
            threshold=0.5,
            round_id=3,
            batch_size=4,
            bucket=8,
            queue_ms=1.5,
        )
    )
    assert rep["prob"] == 0.25 and rep["prediction"] == 0 and rep["round"] == 3
    rej = protocol.parse_reject(
        protocol.build_reject(9, code=503, reason="queue full")
    )
    assert rej["code"] == 503 and protocol.is_reject(
        protocol.build_reject(9, code=503, reason="x")
    )
    with pytest.raises(ValueError):
        protocol.build_request(1)  # neither text nor features
    with pytest.raises(ValueError):
        protocol.build_request(1, text="a", features={"b": 1})
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.wire import (
        WireError,
    )

    with pytest.raises(WireError):
        protocol.parse_request(b"XXXX{}")
    with pytest.raises(WireError):
        protocol.parse_request(protocol.build_reply(
            1, prob=0.1, threshold=0.5, round_id=0, batch_size=1,
            bucket=1, queue_ms=0.0,
        ))
    # Wrong-TYPED fields are network input too: each must fail as a
    # WireError (clean connection drop), never a TypeError in a reader.
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.wire import (
        SCORE_REQ_MAGIC,
    )

    for bad in (
        b'{"id": null, "text": "x"}',
        b'{"id": true, "text": "x"}',
        b'{"id": 1, "text": 5}',
        b'{"id": 1, "features": [1, 2]}',
        b'{"id": 1, "text": "x", "deadline_ms": "abc"}',
        b'[1, 2, 3]',
    ):
        with pytest.raises(WireError):
            protocol.parse_request(SCORE_REQ_MAGIC + bad)


def test_protocol_prob_crosses_bit_exact():
    """float32 -> JSON double -> parse is lossless (the wire leg of the
    bit-for-bit predict-parity guarantee)."""
    for bits in (0.1, 1 / 3, 0.9999999, 1e-30):
        p32 = np.float32(bits)
        body = protocol.parse_reply(
            protocol.build_reply(
                1, prob=float(p32), threshold=0.5, round_id=0,
                batch_size=1, bucket=1, queue_ms=0.0,
            )
        )
        assert body["prob"] == float(p32)


def test_protocol_class_probs_optional_and_backcompat():
    """K-class serving scores: ``class_probs`` is an OPTIONAL reply key
    — present only when the server passes it, absent replies are
    byte-identical to the pre-K-class wire, and old readers (which only
    look at ``prob``) parse both frames unchanged."""
    kw = dict(
        prob=0.25, threshold=0.5, round_id=3, batch_size=4, bucket=8,
        queue_ms=1.5,
    )
    plain = protocol.build_reply(7, **kw)
    kclass = protocol.build_reply(7, class_probs=[0.75, 0.05, 0.2], **kw)
    assert plain == protocol.build_reply(7, class_probs=None, **kw)
    old_view = protocol.parse_reply(plain)
    assert "class_probs" not in old_view
    new_view = protocol.parse_reply(kclass)
    assert new_view["class_probs"] == [0.75, 0.05, 0.2]
    assert new_view["prob"] == old_view["prob"] == 0.25


def test_kclass_scores_ride_the_serving_wire(tiny_setup):
    """A K=3 head puts the full per-class softmax on the scoring wire:
    the reply's ``class_probs`` sums to 1, its scalar ``prob`` is
    1 - P(class 0) (the eval path's P(any attack)), and the binary
    engine's replies carry no ``class_probs`` key at all."""
    tok, model_cfg, _trainer, params2 = tiny_setup
    cfg3 = model_cfg.replace(n_classes=3)
    trainer3 = Trainer(cfg3, TrainConfig(), pad_id=tok.pad_id)
    eng = ScoreEngine(
        cfg3, trainer3.init_state(seed=0).params, pad_id=tok.pad_id,
        buckets=(1, 4), round_id=1,
    )
    server = ScoringServer(
        eng, tok, batcher=MicroBatcher(
            max_batch=4, max_queue=16, gather_window_s=0.002
        ),
    )
    with server:
        with ScoringClient("127.0.0.1", server.port, timeout=30) as c:
            reply = c.score(text=TEXTS[0])
    cp = reply["class_probs"]
    assert len(cp) == 3
    assert abs(sum(cp) - 1.0) < 1e-6
    assert reply["prob"] == pytest.approx(1.0 - cp[0], abs=1e-9)

    eng2 = ScoreEngine(
        model_cfg, params2, pad_id=tok.pad_id, buckets=(1, 4), round_id=1
    )
    server2 = ScoringServer(
        eng2, tok, batcher=MicroBatcher(
            max_batch=4, max_queue=16, gather_window_s=0.002
        ),
    )
    with server2:
        with ScoringClient("127.0.0.1", server2.port, timeout=30) as c:
            assert "class_probs" not in c.score(text=TEXTS[0])


# ------------------------------------------------------------------ batcher
def _req(i, deadline_s=None):
    return ScoreRequest(
        req_id=i,
        input_ids=np.zeros(4, np.int32),
        attention_mask=np.zeros(4, np.int32),
        reply=lambda **kw: None,
        reject=lambda code, reason: None,
        deadline_s=deadline_s,
    )


def test_batcher_coalesces_within_window():
    b = MicroBatcher(max_batch=8, max_queue=16, gather_window_s=0.2)
    for i in range(3):
        assert b.submit(_req(i))
    batch = b.next_batch(timeout=1.0)
    assert [r.req_id for r in batch] == [0, 1, 2]
    assert b.next_batch(timeout=0.01) == []


def test_batcher_caps_at_max_batch_and_bounds_queue():
    b = MicroBatcher(max_batch=2, max_queue=4, gather_window_s=0.05)
    admitted = [b.submit(_req(i)) for i in range(6)]
    assert admitted == [True] * 4 + [False] * 2  # bounded admission
    assert len(b.next_batch(timeout=0.5)) == 2  # capped at max_batch
    assert len(b.next_batch(timeout=0.5)) == 2
    with pytest.raises(ValueError):
        MicroBatcher(max_batch=8, max_queue=4)


def test_request_expiry():
    r = _req(0, deadline_s=0.0)
    assert r.expired()
    assert not _req(1).expired()  # no deadline = never expires
    r2 = _req(2, deadline_s=30.0)
    assert not r2.expired()


# ------------------------------------------------------------------- engine
def test_engine_bucketing_and_single_compile_per_shape(tiny_setup):
    tok, model_cfg, trainer, params = tiny_setup
    eng = ScoreEngine(
        model_cfg, params, pad_id=tok.pad_id, buckets=(1, 4, 8), round_id=1
    )
    L = model_cfg.max_len
    enc = tok.batch_encode(TEXTS, max_len=L)
    # Mixed-size storm: sizes map onto buckets 1/4/4/8, repeated — only
    # the first hit of each bucket may trace.
    for n in (1, 3, 4, 6, 1, 2, 5, 6, 3, 1):
        probs, class_probs, bucket, rid = eng.score(
            enc["input_ids"][:n], enc["attention_mask"][:n]
        )
        assert probs.shape == (n,) and rid == 1
        assert class_probs.shape == (n, model_cfg.n_classes)
        assert bucket == min(b for b in (1, 4, 8) if b >= n)
    assert eng.compile_counts == {(1, L): 1, (4, L): 1, (8, L): 1}
    with pytest.raises(ValueError):
        eng.score(enc["input_ids"][:9] if len(TEXTS) >= 9 else
                  np.zeros((9, L), np.int32), np.zeros((9, L), np.int32))


def test_engine_probs_match_predict_pipeline_bitwise(tiny_setup):
    tok, model_cfg, trainer, params = tiny_setup
    eng = ScoreEngine(model_cfg, params, pad_id=tok.pad_id, buckets=(1, 4, 8))
    enc = tok.batch_encode(TEXTS[:3], max_len=model_cfg.max_len)
    got, _, _, _ = eng.score(enc["input_ids"], enc["attention_mask"])
    want = _expected_probs(tok, trainer, params, TEXTS[:3])
    np.testing.assert_array_equal(got, want)


def test_engine_swap_changes_round_and_weights(tiny_setup):
    tok, model_cfg, trainer, params = tiny_setup
    eng = ScoreEngine(model_cfg, params, pad_id=tok.pad_id, buckets=(4,))
    enc = tok.batch_encode(TEXTS[:2], max_len=model_cfg.max_len)
    before, _, _, rid0 = eng.score(enc["input_ids"], enc["attention_mask"])
    new_params = trainer.init_state(seed=1).params
    eng.swap(new_params, round_id=rid0 + 1)
    after, _, _, rid1 = eng.score(enc["input_ids"], enc["attention_mask"])
    assert rid1 == rid0 + 1
    assert not np.array_equal(before, after)
    # Same shapes: the swap must not retrace.
    assert all(v == 1 for v in eng.compile_counts.values())


# ---------------------------------------------------------------------- e2e
@pytest.mark.slow
def test_scoring_service_end_to_end(tiny_setup, tmp_path):
    """The acceptance flow in one service lifetime: three concurrent
    clients coalesce into a shared bucket batch (telemetry batch_size >
    1) with probabilities bit-for-bit equal to the predict pipeline's; an
    over-deadline request gets the explicit reject frame (not a hang); a
    checkpoint written mid-test is hot-reloaded and served with the new
    round id; and a mixed-size request storm leaves exactly one XLA
    compilation per (bucket, seq) shape."""
    import jax

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.predict import (
        _restore_predict_params,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving.reload import (
        checkpoint_restorer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.checkpoint import (
        Checkpointer,
    )

    tok, model_cfg, trainer, _ = tiny_setup
    cfg = ExperimentConfig(
        model=model_cfg,
        data=DataConfig(max_len=model_cfg.max_len),
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    state1 = trainer.init_state(seed=3)
    state2 = trainer.init_state(seed=4)
    meta = {"kind": "local", "config": cfg.to_dict()}
    with Checkpointer(cfg.checkpoint_dir) as ckpt:
        ckpt.save(1, state1, meta={**meta, "round": 1})
        ckpt.wait()

    # Serve FROM the checkpoint through the predict-path restore.
    restored_cfg, restored_params = _restore_predict_params(
        cfg, tok, trainer
    )
    assert restored_cfg == model_cfg
    buckets = (1, 4, 8)
    eng = ScoreEngine(
        model_cfg, restored_params, pad_id=tok.pad_id, buckets=buckets,
        round_id=1,
    )
    watcher = CheckpointWatcher(
        cfg.checkpoint_dir, checkpoint_restorer(cfg, tok),
        poll_interval_s=0.0,
    )
    server = ScoringServer(
        eng,
        tok,
        spec=get_dataset("cicids2017"),
        batcher=MicroBatcher(max_batch=8, max_queue=64, gather_window_s=0.25),
        watcher=watcher,
        idle_tick_s=0.02,
        metrics_jsonl=str(tmp_path / "metrics.jsonl"),
    )
    expected1 = _expected_probs(tok, trainer, state1.params, TEXTS[:3])
    with server:
        # --- 3 concurrent clients -> one coalesced bucket batch --------
        barrier = threading.Barrier(3)
        replies = {}

        def go(i):
            with ScoringClient("127.0.0.1", server.port, timeout=30) as c:
                barrier.wait()
                replies[i] = c.score(text=TEXTS[i])

        threads = [threading.Thread(target=go, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert sorted(replies) == [0, 1, 2]
        # Bit-for-bit parity with the predict pipeline (float32 -> JSON
        # double is exact; see serving/protocol.py).
        for i in range(3):
            assert replies[i]["prob"] == float(expected1[i]), (i, replies[i])
            assert replies[i]["round"] == 1
        # Coalescing evidence: the three requests shared a batch.
        assert max(r["batch_size"] for r in replies.values()) > 1
        assert all(r["bucket"] == 4 for r in replies.values())

        # --- over-deadline request -> explicit reject, not a hang ------
        with ScoringClient("127.0.0.1", server.port, timeout=30) as c:
            with pytest.raises(ScoreRejected) as exc:
                c.score(text=TEXTS[0], deadline_ms=0.0)
            assert exc.value.code == protocol.REJECT_DEADLINE
        assert server.stats()["rejects"]["deadline"] == 1

        # --- checkpoint written mid-test -> hot reload, new round id ---
        with Checkpointer(cfg.checkpoint_dir) as ckpt:
            ckpt.save(2, state2, meta={**meta, "round": 2})
            ckpt.wait()
        expected2 = _expected_probs(tok, trainer, state2.params, TEXTS[:3])
        deadline = time.monotonic() + 30.0
        reply = None
        with ScoringClient("127.0.0.1", server.port, timeout=30) as c:
            while time.monotonic() < deadline:
                reply = c.score(text=TEXTS[0])
                if reply["round"] == 2:
                    break
                time.sleep(0.05)
        assert reply is not None and reply["round"] == 2, reply
        assert reply["prob"] == float(expected2[0])
        assert watcher.reload_count == 1

        # --- mixed-size storm: still one compile per (bucket, seq) -----
        stats = run_load(
            "127.0.0.1", server.port, TEXTS, concurrency=5, requests=25,
        )
        assert stats["scored"] == 25 and stats["rejected"] == 0
        assert stats["p50_ms"] > 0.0 and stats["p99_ms"] >= stats["p50_ms"]
        L = model_cfg.max_len
        assert eng.compile_counts == {(b, L): 1 for b in buckets}
        final = server.stats()
        assert final["scored"] >= 29 and final["round"] == 2
    # The metrics-JSONL channel carried per-batch records + the summary.
    import json

    records = [
        json.loads(line)
        for line in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    phases = {r["phase"] for r in records}
    assert {"serve_batch", "serve_summary"} <= phases
    assert any(
        r["phase"] == "serve_batch" and r["batch_size"] > 1 for r in records
    )
    jax.clear_caches()


def test_overload_is_rejected_not_queued(tiny_setup):
    """Admission control: with the queue bound at 1 and the scorer wedged
    (a poison request whose reply callback blocks it), excess requests
    get the 503-style reject frame immediately instead of queueing into
    unbounded latency."""
    tok, model_cfg, trainer, params = tiny_setup
    eng = ScoreEngine(model_cfg, params, pad_id=tok.pad_id, buckets=(1,))
    server = ScoringServer(
        eng,
        tok,
        batcher=MicroBatcher(max_batch=1, max_queue=1, gather_window_s=0.0),
        idle_tick_s=0.01,
        warmup=True,
    )
    L = model_cfg.max_len
    wedge = threading.Event()
    with server:
        # Wedge the single scorer thread: it dequeues this request,
        # scores it, and blocks inside its reply callback.
        server.batcher.submit(
            ScoreRequest(
                req_id=0,
                input_ids=np.zeros(L, np.int32),
                attention_mask=np.zeros(L, np.int32),
                reply=lambda **kw: wedge.wait(timeout=20),
                reject=lambda code, reason: None,
            )
        )
        deadline = time.monotonic() + 10.0
        while server.batcher.qsize() > 0 and time.monotonic() < deadline:
            time.sleep(0.01)  # scorer has taken the poison request
        outcomes = {}

        def go(i):
            try:
                with ScoringClient(
                    "127.0.0.1", server.port, timeout=30
                ) as c:
                    outcomes[i] = c.score(text=TEXTS[i % len(TEXTS)])
            except ScoreRejected as e:
                outcomes[i] = e

        threads = [
            threading.Thread(target=go, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        # All six submissions resolve at ADMISSION (5 shed, 1 queued)
        # while the scorer is still wedged; only then release it so the
        # queued request can be served and its client thread can join.
        deadline = time.monotonic() + 15.0
        while (
            server.stats()["rejects"]["overloaded"] < 5
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        wedge.set()
        for t in threads:
            t.join(timeout=30)
        rejected = [
            o for o in outcomes.values() if isinstance(o, ScoreRejected)
        ]
        assert len(rejected) == 5, outcomes  # 1 queue slot, 5 shed
        assert all(
            r.code == protocol.REJECT_OVERLOADED for r in rejected
        )
        assert server.stats()["rejects"]["overloaded"] == 5


def test_infer_serve_parser_wiring():
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        build_parser,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.serving import (
        _parse_buckets,
        cmd_infer_serve,
    )

    args = build_parser().parse_args(
        ["infer-serve", "--checkpoint-dir", "/tmp/x", "--buckets", "1,16",
         "--max-wait-ms", "2", "--default-deadline-ms", "250"]
    )
    assert args.fn is cmd_infer_serve
    assert _parse_buckets(args.buckets) == (1, 16)
    assert args.default_deadline_ms == 250.0
    with pytest.raises(SystemExit):
        _parse_buckets("fast,slow")
    with pytest.raises(SystemExit):
        _parse_buckets("0,8")


# ----------------------------------------------------------------- auth
def test_scoring_port_auth_challenge_response(tiny_setup):
    """The FL tier's HMAC + per-connection nonce challenge reused on the
    scoring port (--auth): the right key scores, a wrong key is dropped
    before any request is read, and a keyless client gets an error that
    names the fix instead of a hang."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.wire import (
        WireError,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
        ScoreEngine,
        ScoringClient,
        ScoringServer,
        run_load,
    )

    tok, model_cfg, trainer, params = tiny_setup
    key = b"scoring-secret"
    engine = ScoreEngine(model_cfg, params, pad_id=tok.pad_id, buckets=(1, 4))
    with ScoringServer(
        engine, tok, idle_tick_s=0.01, auth_key=key
    ) as server:
        # Right key: the handshake is invisible to the scoring flow.
        with ScoringClient(
            "127.0.0.1", server.port, auth_key=key
        ) as cli:
            reply = cli.score(text=TEXTS[0])
            assert 0.0 <= reply["prob"] <= 1.0
        # No key: the challenge frame arrives where the reply was
        # expected — a clear refusal, not a stall.
        with ScoringClient("127.0.0.1", server.port) as bare:
            with pytest.raises(WireError, match="auth"):
                bare.score(text=TEXTS[0])
        # Wrong key: the server drops the connection after the bad proof.
        with pytest.raises((ConnectionError, OSError, WireError)):
            with ScoringClient(
                "127.0.0.1", server.port, auth_key=b"wrong", timeout=5
            ) as thief:
                thief.score(text=TEXTS[0])
        # The load generator authenticates too (SDK passthrough).
        stats = run_load(
            "127.0.0.1",
            server.port,
            TEXTS,
            concurrency=2,
            auth_key=key,
        )
        assert stats["scored"] == len(TEXTS)
        assert server.stats()["rejects"]["auth"] >= 2


def test_scoring_auth_client_against_open_server_fails_fast(tiny_setup):
    """An auth-configured client dialing an OPEN server must fail with a
    clear message (no challenge ever comes), bounded by its timeout."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.wire import (
        WireError,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
        ScoreEngine,
        ScoringClient,
        ScoringServer,
    )

    tok, model_cfg, trainer, params = tiny_setup
    engine = ScoreEngine(model_cfg, params, pad_id=tok.pad_id, buckets=(1,))
    with ScoringServer(engine, tok, idle_tick_s=0.01) as server:
        with pytest.raises(WireError, match="no auth challenge"):
            ScoringClient(
                "127.0.0.1", server.port, auth_key=b"k", timeout=2
            )


def test_serve_batch_jsonl_carries_score_histogram(tiny_setup, tmp_path):
    """The drift monitor's input: every serve_batch record carries the
    batch's binned score histogram, and the cumulative histogram rides
    stats() — counts must equal flows scored."""
    import json as _json

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
        ScoreEngine,
        ScoringClient,
        ScoringServer,
    )

    tok, model_cfg, trainer, params = tiny_setup
    path = str(tmp_path / "metrics.jsonl")
    engine = ScoreEngine(model_cfg, params, pad_id=tok.pad_id, buckets=(1, 4))
    with ScoringServer(
        engine, tok, idle_tick_s=0.01, metrics_jsonl=path
    ) as server:
        with ScoringClient("127.0.0.1", server.port) as cli:
            for t in TEXTS:
                cli.score(text=t)
        s = server.stats()
    assert sum(s["score_hist"]) == len(TEXTS)
    assert len(s["score_hist"]) == 10
    records = [_json.loads(ln) for ln in open(path)]
    batch_hists = [
        r["score_hist"] for r in records if r.get("phase") == "serve_batch"
    ]
    assert batch_hists and all(len(h) == 10 for h in batch_hists)
    assert sum(sum(h) for h in batch_hists) == len(TEXTS)


def test_serve_batch_span_sampling_is_counter_strided(tiny_setup, tmp_path):
    """--trace-sample RATE (ISSUE 5 satellite): a high-rate scorer emits
    one serve-batch span per ~1/RATE coalesced batches via the batch
    COUNTER — deterministic, no RNG — and each sampled span carries
    sampled_batches so consumers can re-scale. rate=1.0 keeps the
    one-span-per-batch behavior, field omitted."""
    import json as _json

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
        Tracer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
        ScoreEngine,
        ScoringClient,
        ScoringServer,
    )

    tok, model_cfg, trainer, params = tiny_setup

    def run(rate, name):
        path = str(tmp_path / f"{name}.jsonl")
        engine = ScoreEngine(
            model_cfg, params, pad_id=tok.pad_id, buckets=(1,)
        )
        with ScoringServer(
            engine, tok, idle_tick_s=0.01, warmup=False,
            tracer=Tracer(path, proc="serve"), trace_sample=rate,
        ) as server:
            with ScoringClient("127.0.0.1", server.port) as cli:
                # Sequential single requests over bucket (1,): exactly
                # one coalesced batch per request, deterministically.
                for t in TEXTS:
                    cli.score(text=t)
            n_batches = server.stats()["batches"]
        spans = [
            _json.loads(ln)
            for ln in open(path)
            if _json.loads(ln).get("span") == "serve-batch"
        ]
        return n_batches, spans

    n, spans = run(1.0, "full")
    assert n == len(TEXTS) and len(spans) == n
    assert all("sampled_batches" not in s for s in spans)

    n, spans = run(1 / 3, "sampled")
    assert n == len(TEXTS)
    # Batches 1, 4, ... emit: ceil(6/3) = 2 spans, stride recorded.
    assert len(spans) == -(-n // 3)
    assert all(s["sampled_batches"] == 3 for s in spans)

    with pytest.raises(ValueError, match="trace_sample"):
        ScoringServer(
            ScoreEngine(model_cfg, params, pad_id=tok.pad_id, buckets=(1,)),
            tok, trace_sample=0.0,
        )
