"""Multi-chip TCP client (cli/comm.py --data-parallel/--seq-parallel):
the separate-process tier's local phase over the host's own device mesh.

The identity contract (ISSUE 2): with ``--data-parallel N`` the client
runs the single-client engine's OWN jitted programs, batch rows sharded
over N devices — same threefry PRNG streams, same shuffles, same math.
Params agree with the single-device client to float32 reduction-order
ulps (per-shard partial sums round differently than one sequential
reduction), which is below metric resolution: final metrics are equal,
and the wire/masking machinery operates on the host-gathered vector
unchanged (byte-identical round-1 DP bases; the server's dp_base_crc
equality check binds a meshed and a single-device client in one round).
"""

import csv
import json
import os
import threading

import numpy as np
import pytest

import jax

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
    main,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    default_tokenizer,
    make_synthetic,
    make_all_client_splits,
    tokenize_client,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.mesh import (
    make_host_mesh,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.client_mesh import (
    FedSeqClientTrainer,
    MeshTrainer,
    make_client_trainer,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
    Trainer,
)

L = 32


@pytest.fixture(scope="module")
def tok():
    return default_tokenizer()


def _cfg(tok, *, data=1, seq=1, prng="threefry2x32"):
    model = ModelConfig.tiny(
        vocab_size=len(tok.vocab), max_len=L, max_position_embeddings=2 * L
    )
    return ExperimentConfig(
        model=model,
        data=DataConfig(max_len=L, batch_size=8, data_fraction=0.3),
        train=TrainConfig(
            prng_impl=prng,
            epochs_per_round=1,
            learning_rate=1e-3,
            log_every=0,
        ),
        fed=FedConfig(num_clients=1),
        mesh=MeshConfig(clients=1, data=data, seq=seq),
    )


@pytest.fixture(scope="module")
def client_data(tok):
    cfg = _cfg(tok)
    df = make_synthetic("cicids2017", 400, seed=42)
    splits = make_all_client_splits(df, 1, cfg.data)
    return tokenize_client(splits[0], tok, max_len=L)


def test_make_client_trainer_dispatch(tok, eight_devices):
    assert isinstance(make_client_trainer(_cfg(tok)), Trainer)
    t = make_client_trainer(_cfg(tok, data=2))
    assert isinstance(t, MeshTrainer)
    assert t.mesh.shape["data"] == 2
    t = make_client_trainer(_cfg(tok, data=2, seq=2))
    assert isinstance(t, FedSeqClientTrainer)
    assert dict(t.mesh.shape) == {"clients": 1, "data": 2, "seq": 2}
    with pytest.raises(ValueError, match="batch_size"):
        make_client_trainer(_cfg(tok, data=3))  # 8 % 3 != 0
    with pytest.raises(ValueError, match="devices"):
        MeshTrainer(
            _cfg(tok).model,
            _cfg(tok).train,
            mesh=make_host_mesh(99),
        )


def test_mesh_trainer_matches_single_device_trajectory(
    tok, client_data, eight_devices
):
    """The headline identity: MeshTrainer over 2 data shards vs the plain
    engine — same threefry trajectory, equal final metrics, params within
    reduction-order ulps. (N=4 behaves identically — covered by the slow
    lane's seq/TCP variants; one shard count keeps this anchor cheap.)"""
    cfg = _cfg(tok)
    plain = Trainer(cfg.model, cfg.train, pad_id=tok.pad_id)
    s0, _ = plain.fit(plain.init_state(), client_data.train, batch_size=8)
    m0 = plain.evaluate_state(s0, client_data.test)
    h0 = plain.host_params(s0)
    for n in (2,):
        meshed = MeshTrainer(
            cfg.model, cfg.train, mesh=make_host_mesh(n), pad_id=tok.pad_id
        )
        sn, _ = meshed.fit(
            meshed.init_state(), client_data.train, batch_size=8
        )
        mn = meshed.evaluate_state(sn, client_data.test)
        for k in ("Accuracy", "Precision", "Recall", "F1-Score"):
            assert m0[k] == mn[k], (n, k, m0[k], mn[k])
        np.testing.assert_allclose(m0["Loss"], mn["Loss"], rtol=1e-5)
        np.testing.assert_array_equal(
            m0["confusion_matrix"], mn["confusion_matrix"]
        )
        hn = meshed.host_params(sn)
        for a, b in zip(jax.tree.leaves(h0), jax.tree.leaves(hn)):
            np.testing.assert_allclose(a, b, atol=2e-6, rtol=1e-5)


def test_mesh_trainer_gather_scatter_is_byte_exact(tok, eight_devices):
    """The wire boundary: init -> gather and adopt-aggregate -> gather are
    byte-exact round trips, so the masking/noising machinery sees the
    identical flat vector a single-device client would produce (round-1
    DP bases byte-identical; the server's dp_base_crc equality check
    across a mixed single-device/meshed fleet can hold)."""
    cfg = _cfg(tok)
    plain = Trainer(cfg.model, cfg.train, pad_id=tok.pad_id)
    meshed = MeshTrainer(
        cfg.model, cfg.train, mesh=make_host_mesh(2), pad_id=tok.pad_id
    )
    p0 = plain.host_params(plain.init_state())
    pm = meshed.host_params(meshed.init_state())
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(pm)):
        np.testing.assert_array_equal(a, b)
    # Scatter an "aggregate" onto the mesh and gather it back: byte-exact.
    rng = np.random.default_rng(7)
    agg = jax.tree.map(
        lambda x: (x + rng.normal(0, 0.01, x.shape)).astype(x.dtype), p0
    )
    state = meshed.adopt_aggregate(meshed.init_state(), agg)
    back = meshed.host_params(state)
    for a, b in zip(jax.tree.leaves(agg), jax.tree.leaves(back)):
        np.testing.assert_array_equal(a, b)
    assert int(state.step) == 0


def _write_cfg(tmp_path, cfg, name):
    path = str(tmp_path / name)
    with open(path, "w") as f:
        json.dump(cfg.to_dict(), f)
    return path


def _read_metrics_csv(path):
    with open(path) as f:
        return dict(next(iter(csv.DictReader(f))))


def _run_client(argv, results, key):
    try:
        results[key] = main(argv)
    except BaseException as e:  # surfaced by the asserting main thread
        results[key] = e


def test_client_data_parallel_tcp_round_matches_single_device(
    tok, tmp_path, eight_devices
):
    """The acceptance run: live server + `client --data-parallel 2` vs the
    single-device client on identical config/data — final local AND
    aggregated metrics threefry-identical (same CSV values; Loss to float
    repr resolution)."""
    cfg = _cfg(tok)
    cfg_path = _write_cfg(tmp_path, cfg, "cfg.json")
    outs = {}
    for name, extra in (("single", []), ("dp2", ["--data-parallel", "2"])):
        out = str(tmp_path / name)
        outs[name] = out
        with AggregationServer(port=0, num_clients=1, timeout=60) as server:
            errs: list = []

            def _serve():
                try:
                    server.serve(rounds=1)
                except Exception as e:
                    errs.append(e)

            t = threading.Thread(target=_serve, daemon=True)
            t.start()
            rc = main(
                [
                    "client", "--client-id", "0", "--host", "127.0.0.1",
                    "--port", str(server.port), "--config", cfg_path,
                    "--synthetic", "400", "--output-dir", out,
                    "--timeout", "60", *extra,
                ]
            )
            t.join(timeout=60)
        assert rc == 0 and not errs, (rc, errs)
    for phase in ("local", "aggregated"):
        a = _read_metrics_csv(
            os.path.join(outs["single"], f"client0_{phase}_metrics.csv")
        )
        b = _read_metrics_csv(
            os.path.join(outs["dp2"], f"client0_{phase}_metrics.csv")
        )
        assert set(a) == set(b)
        for k in a:
            if k == "Loss":
                np.testing.assert_allclose(
                    float(a[k]), float(b[k]), rtol=1e-5, err_msg=(phase, k)
                )
            else:
                assert a[k] == b[k], (phase, k, a[k], b[k])


def test_client_data_parallel_composes_with_secure_agg_and_dp(
    tok, tmp_path, eight_devices, monkeypatch
):
    """--secure-agg + --dp with a MIXED fleet: client 0 single-device,
    client 1 --data-parallel 2, one live secure DP round. The server's
    dp_base_crc equality check REJECTS a round whose clients upload
    different round bases, so completion proves the meshed client's
    host-gathered base is byte-identical to the single-device client's;
    masking and noising ride the identical machinery (comm/client.py is
    untouched by the mesh — one host gather feeds it)."""
    monkeypatch.delenv("FEDTPU_SECRET", raising=False)
    monkeypatch.delenv("FEDTPU_CLIENT_SECRET", raising=False)
    cfg = _cfg(tok)
    cfg = ExperimentConfig(
        model=cfg.model,
        data=cfg.data,
        train=cfg.train,
        fed=FedConfig(num_clients=2),
        mesh=MeshConfig(clients=2, data=1),
    )
    cfg_path = _write_cfg(tmp_path, cfg, "cfg2.json")
    out = str(tmp_path / "compose")
    with AggregationServer(
        port=0,
        num_clients=2,
        timeout=90,
        secure_agg=True,
        dp_clip=1.0,
        dp_noise_multiplier=0.05,
    ) as server:
        errs: list = []

        def _serve():
            try:
                server.serve(rounds=1)
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=_serve, daemon=True)
        t.start()
        results: dict = {}
        base = [
            "--host", "127.0.0.1", "--port", str(server.port),
            "--config", cfg_path, "--synthetic", "400",
            "--output-dir", out, "--timeout", "90",
            "--secure-agg", "--dp",
        ]
        c1 = threading.Thread(
            target=_run_client,
            args=(
                ["client", "--client-id", "1", "--data-parallel", "2", *base],
                results,
                "dp2",
            ),
            daemon=True,
        )
        c1.start()
        results["single"] = main(["client", "--client-id", "0", *base])
        c1.join(timeout=120)
        t.join(timeout=60)
    assert results["single"] == 0 and results["dp2"] == 0, results
    assert not errs, errs
    # Aggregated artifacts from BOTH clients prove the masked+noised round
    # completed through the mixed fleet.
    for c in (0, 1):
        assert os.path.exists(
            os.path.join(out, f"client{c}_aggregated_metrics.csv")
        )


@pytest.mark.slow
def test_client_seq_parallel_tcp_round(tok, tmp_path, eight_devices):
    """`client --data-parallel 2 --seq-parallel 2`: the C=1 fedseq
    composition (ring attention) behind the TCP round loop — live server,
    full artifact set, sane metrics."""
    cfg = _cfg(tok, data=2, seq=2)
    cfg_path = _write_cfg(tmp_path, cfg, "cfg_seq.json")
    out = str(tmp_path / "seq")
    with AggregationServer(port=0, num_clients=1, timeout=90) as server:
        errs: list = []

        def _serve():
            try:
                server.serve(rounds=1)
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=_serve, daemon=True)
        t.start()
        rc = main(
            [
                "client", "--client-id", "0", "--host", "127.0.0.1",
                "--port", str(server.port), "--config", cfg_path,
                "--synthetic", "400", "--output-dir", out,
                "--timeout", "90", "--data-parallel", "2",
                "--seq-parallel", "2",
            ]
        )
        t.join(timeout=60)
    assert rc == 0 and not errs, (rc, errs)
    for phase in ("local", "aggregated"):
        m = _read_metrics_csv(
            os.path.join(out, f"client0_{phase}_metrics.csv")
        )
        assert 0.0 <= float(m["Accuracy"]) <= 100.0


def test_seq_client_trainer_roundtrip(tok, client_data, eight_devices):
    """In-process fedseq client adapter: fit advances, evaluate accepts
    both the live state and an unstacked host aggregate, gather/adopt are
    byte-exact round trips (fast-lane anchor for the slow TCP e2e)."""
    trainer = make_client_trainer(_cfg(tok, data=2, seq=2), pad_id=tok.pad_id)
    state = trainer.init_state()
    state, losses = trainer.fit(state, client_data.train, batch_size=8)
    assert len(losses) == 1 and np.isfinite(losses[0])
    m_state = trainer.evaluate_state(state, client_data.test)
    host = trainer.host_params(state)
    m_host = trainer.evaluate(host, client_data.test)
    assert m_state["Accuracy"] == m_host["Accuracy"]
    adopted = trainer.adopt_aggregate(state, host)
    back = trainer.host_params(adopted)
    for a, b in zip(jax.tree.leaves(host), jax.tree.leaves(back)):
        np.testing.assert_array_equal(a, b)
    assert int(adopted.step) == int(state.step)
