"""Sharded scorer (serving/engine.py ``mesh=``): params split per-leaf
at rest, gathered at use by a separate jitted program — probs must be
BIT-identical to the replicated engine's, pad rows must not perturb
sibling rows, the bucket ladder must hold its edges (n == largest
bucket, n == 1, n > largest), and a hot swap / rolling reload must
reuse every warm program (0 recompiles, gather program included)."""

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    default_tokenizer,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.mesh import (
    device_tree_bytes,
    make_host_mesh,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
    ScoreEngine,
    ScoringClient,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
    Trainer,
)

BUCKETS = (1, 4, 8)

TEXTS = [
    f"Destination port is {p}. Flow duration is {d} microseconds. "
    f"Total forward packets are {n}."
    for p, d, n in [(80, 100, 3), (443, 2500, 9), (8080, 7, 1)]
]


@pytest.fixture(scope="module")
def setup(eight_devices):
    tok = default_tokenizer()
    model_cfg = ModelConfig.tiny(vocab_size=len(tok.vocab))
    trainer = Trainer(model_cfg, TrainConfig(), pad_id=tok.pad_id)
    # Host-side master copy: both engines place from the same numpy
    # bytes, so any probs difference is the engines', not placement's.
    import jax

    params = jax.tree.map(
        np.asarray, trainer.init_state(seed=0).params
    )
    mesh = make_host_mesh(2, devices=eight_devices[:2])
    return tok, model_cfg, trainer, params, mesh


@pytest.fixture(scope="module")
def engines(setup):
    tok, model_cfg, _trainer, params, mesh = setup
    rep = ScoreEngine(
        model_cfg, params, pad_id=tok.pad_id, buckets=BUCKETS, round_id=1
    )
    shard = ScoreEngine(
        model_cfg,
        params,
        pad_id=tok.pad_id,
        buckets=BUCKETS,
        round_id=1,
        mesh=mesh,
    )
    return rep, shard


def _ragged_batch(model_cfg, n, seed=0):
    rng = np.random.default_rng(seed)
    L = model_cfg.max_len
    ids = rng.integers(1, model_cfg.vocab_size, size=(n, L), dtype=np.int32)
    mask = np.ones_like(ids)
    mask[:, L // 2:] = 0  # ragged lengths: real pad territory per row
    return ids, mask


def test_sharded_probs_bit_identical_to_replicated(engines, setup):
    """The serving crc contract at the bucket edges: a lone probe
    (n == 1), an exactly-full largest bucket (n == 8, zero pad rows),
    and a padded mid-size (n == 5) all return the replicated engine's
    exact bits — scalar score AND per-class softmax."""
    _tok, model_cfg, _trainer, _params, _mesh = setup
    rep, shard = engines
    for n in (1, BUCKETS[-1], 5):
        ids, mask = _ragged_batch(model_cfg, n, seed=n)
        p0, cp0, b0, _ = rep.score(ids, mask)
        p1, cp1, b1, _ = shard.score(ids, mask)
        assert b0 == b1
        np.testing.assert_array_equal(p0, p1)
        np.testing.assert_array_equal(cp0, cp1)


def test_sharded_static_bytes_are_split_per_chip(engines):
    """Shard-at-rest accounting: the sharded engine's params occupy
    ~1/N of the replicated engine's bytes on any one chip (<= 0.6 at
    N=2 — the bench gate's shape; replicated leaves keep full size)."""
    rep, shard = engines
    rep_bytes = device_tree_bytes(rep.snapshot()[0])
    shard_bytes = device_tree_bytes(shard.snapshot()[0])
    assert rep_bytes > 0
    assert shard_bytes / rep_bytes <= 0.6


def test_sharded_pad_rows_do_not_perturb_probs(engines, setup):
    """Per-row independence under sharding: the same 3 rows score the
    same bits whether padded up with PAD rows (n=3 -> bucket 4) or
    riding in a full batch of 8 real rows (bucket 8, no pads)."""
    _tok, model_cfg, _trainer, _params, _mesh = setup
    _rep, shard = engines
    ids, mask = _ragged_batch(model_cfg, 8, seed=3)
    alone, cp_alone, _, _ = shard.score(ids[:3], mask[:3])
    full, cp_full, _, _ = shard.score(ids, mask)
    np.testing.assert_array_equal(alone, full[:3])
    np.testing.assert_array_equal(cp_alone, cp_full[:3])


def test_sharded_bucket_overflow_raises(engines, setup):
    _tok, model_cfg, _trainer, _params, _mesh = setup
    _rep, shard = engines
    ids, mask = _ragged_batch(model_cfg, BUCKETS[-1] + 1)
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        shard.score(ids, mask)


def test_sharded_swap_reuses_warm_programs(setup):
    """A hot swap re-places onto the same shape-deterministic layout:
    after warmup, swapping new params and re-scoring every bucket must
    trace NOTHING — bucket programs and the gather program alike."""
    tok, model_cfg, trainer, params, mesh = setup
    eng = ScoreEngine(
        model_cfg, params, pad_id=tok.pad_id, buckets=BUCKETS, mesh=mesh
    )
    eng.warmup()
    import jax

    new_params = jax.tree.map(
        lambda a: np.asarray(a) + np.float32(1e-3), params
    )
    eng.swap(new_params, round_id=2)
    for n in (1, 3, 8):
        ids, mask = _ragged_batch(model_cfg, n, seed=n)
        _, _, _, rid = eng.score(ids, mask)
        assert rid == 2
    assert eng.ledger.recompiles() == []
    assert all(v == 1 for v in eng.compile_counts.values())
    # The gather program compiled exactly once too (its own ledger site).
    assert eng.ledger.compile_counts("serving.gather") == {("gather",): 1}


def test_sharded_replica_rolling_reload_keeps_warm(setup):
    """Fleet composition: a SHARDED FleetReplica behind ServingFleet
    survives a rolling reload — drain→swap lands the new params on the
    same shard layout, the round advances on the wire, and no warm
    bucket retraces."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.router.fleet import (
        FleetReplica,
        ServingFleet,
    )

    tok, model_cfg, trainer, params, mesh = setup
    rep = FleetReplica(
        0,
        model_cfg,
        params,
        tok,
        round_id=1,
        buckets=(1, 4),
        gather_window_s=0.002,
        mesh=mesh,
    ).start()
    fleet = ServingFleet([rep], probe_interval_s=0.2).start()
    try:
        rep.engine.warmup()
        with ScoringClient("127.0.0.1", fleet.port) as cli:
            assert cli.score(text=TEXTS[0])["round"] == 1
            import jax

            new_params = jax.tree.map(
                lambda a: np.asarray(a) + np.float32(1e-3), params
            )
            sweep = fleet.rolling_reload(new_params, round_id=2)
            assert [s["replica"] for s in sweep["replicas"]] == [0]
            assert cli.score(text=TEXTS[1])["round"] == 2
        assert rep.engine.ledger.recompiles() == []
    finally:
        fleet.close()
