"""FedOpt server optimizers (parallel/fedavg.py::make_server_optimizer +
the FederatedTrainer server aggregation step).

The reference's aggregation is an unweighted mean, full stop
(server.py:67-79). FedOpt (Reddi et al.) treats the round's mean update
as a pseudo-gradient and applies a server optimizer: FedAvgM (momentum)
and FedAdam. At server_lr=1 / momentum=0 the step must reduce exactly to
plain FedAvg.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel import (
    make_mesh,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train import (
    FederatedTrainer,
)


def _cfg(clients=2, **fed_kw):
    model = ModelConfig.tiny()
    return ExperimentConfig(
        model=model,
        data=DataConfig(max_len=model.max_len, batch_size=4),
        train=TrainConfig(learning_rate=1e-3, epochs_per_round=1, seed=0),
        fed=FedConfig(num_clients=clients, **fed_kw),
        mesh=MeshConfig(clients=clients, data=1),
    )


def _batch(cfg, clients, B=4, seed=0):
    rng = np.random.default_rng(seed)
    L = cfg.model.max_len
    return {
        "input_ids": rng.integers(
            0, cfg.model.vocab_size, (clients, B, L)
        ).astype(np.int32),
        "attention_mask": np.ones((clients, B, L), np.int32),
        "labels": rng.integers(0, 2, (clients, B)).astype(np.int32),
    }


def _trainer(eight_devices, **fed_kw):
    cfg = _cfg(clients=2, **fed_kw)
    mesh = make_mesh(2, 1, devices=eight_devices[:2])
    t = FederatedTrainer(cfg, mesh=mesh)
    return t, t.init_state(seed=0)


def test_config_validation():
    with pytest.raises(ValueError, match="server_opt"):
        FedConfig(server_opt="sgd")
    with pytest.raises(ValueError, match="server_lr"):
        FedConfig(server_opt="adam", server_lr=0.0)


@pytest.mark.slow
def test_momentum_lr1_m0_equals_plain_fedavg(eight_devices):
    """server_opt=momentum at lr=1, momentum=0 must be bit-close to plain
    FedAvg: new global == mean of client params."""
    t_fed, s_fed = _trainer(eight_devices)
    t_srv, s_srv = _trainer(
        eight_devices, server_opt="momentum", server_lr=1.0, server_momentum=0.0
    )
    assert s_fed.server_opt is None and s_srv.server_opt is not None

    batch = _batch(t_fed.cfg, 2)
    s_fed, _ = t_fed.train_step(s_fed, batch)
    s_srv, _ = t_srv.train_step(s_srv, batch)
    anchor = t_srv.round_anchor(s_srv)
    assert t_fed.round_anchor(s_fed) is None

    s_fed = t_fed.aggregate(s_fed)
    s_srv = t_srv.aggregate(s_srv, anchor=anchor)
    for a, b in zip(jax.tree.leaves(s_fed.params), jax.tree.leaves(s_srv.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_momentum_accumulates_across_rounds(eight_devices):
    """Two rounds with the same client delta: FedAvgM's second global step
    must be larger than its first (heavy-ball memory), and the server state
    must survive the per-round client-optimizer reset."""
    trainer, state = _trainer(
        eight_devices, server_opt="momentum", server_lr=1.0, server_momentum=0.9
    )
    delta = jax.tree.map(jnp.ones_like, state.params)

    def push(state):
        anchor = trainer.round_anchor(state)
        before = jax.tree.leaves(anchor)[0]
        pushed = state._replace(
            params=jax.tree.map(lambda p, d: p + 0.01 * d, state.params, delta)
        )
        out = trainer.aggregate(pushed, anchor=anchor)
        after = jax.tree.leaves(out.params)[0]
        return out, float(np.abs(np.asarray(after - before)).mean())

    state = trainer.reset_optimizer(state)  # must not clear server state
    assert state.server_opt is not None
    state, step1 = push(state)
    state, step2 = push(state)
    assert step2 > step1 * 1.5  # momentum compounds identical deltas


def test_fedadam_round_replicates_and_is_finite(eight_devices):
    trainer, state = _trainer(
        eight_devices, server_opt="adam", server_lr=0.05
    )
    batch = _batch(trainer.cfg, 2)
    anchor = trainer.round_anchor(state)
    state, _ = trainer.train_step(state, batch)
    state = trainer.aggregate(state, anchor=anchor)
    leaf = np.asarray(jax.tree.leaves(state.params)[0])
    np.testing.assert_allclose(leaf[1], leaf[0], rtol=1e-6)
    assert np.isfinite(leaf).all()
    assert state.server_opt is not None


@pytest.mark.slow
def test_server_opt_composes_with_dp(eight_devices):
    trainer, state = _trainer(
        eight_devices,
        server_opt="momentum",
        server_lr=1.0,
        server_momentum=0.5,
        dp_clip=1.0,
        dp_noise_multiplier=0.1,
    )
    batch = _batch(trainer.cfg, 2)
    anchor = trainer.round_anchor(state)
    state, _ = trainer.train_step(state, batch)
    state = trainer.aggregate(state, anchor=anchor, round_index=0)
    leaf = np.asarray(jax.tree.leaves(state.params)[0])
    np.testing.assert_allclose(leaf[1], leaf[0], rtol=1e-6)
    assert np.isfinite(leaf).all()


@pytest.mark.slow
def test_run_loop_with_server_opt(eight_devices):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
    )

    trainer, state = _trainer(
        eight_devices, server_opt="momentum", rounds=2
    )
    rng = np.random.default_rng(0)
    cfg = trainer.cfg
    L = cfg.model.max_len
    train = TokenizedSplit(
        rng.integers(0, cfg.model.vocab_size, (2, 16, L)).astype(np.int32),
        np.ones((2, 16, L), np.int32),
        rng.integers(0, 2, (2, 16)).astype(np.int32),
    )
    evals = [
        TokenizedSplit(
            rng.integers(0, cfg.model.vocab_size, (8, L)).astype(np.int32),
            np.ones((8, L), np.int32),
            rng.integers(0, 2, 8).astype(np.int32),
        )
        for _ in range(2)
    ]
    state, history = trainer.run(state, train, evals, rounds=2)
    assert len(history) == 2
    assert state.server_opt is not None


def test_server_state_checkpoints_and_restores(eight_devices, tmp_path):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.checkpoint import (
        Checkpointer,
    )

    trainer, state = _trainer(
        eight_devices, server_opt="momentum", server_lr=1.0, server_momentum=0.9
    )
    anchor = trainer.round_anchor(state)
    pushed = state._replace(
        params=jax.tree.map(lambda p: p + 0.01, state.params)
    )
    state = trainer.aggregate(pushed, anchor=anchor)  # non-trivial momentum
    with Checkpointer(str(tmp_path / "ck")) as ckpt:
        ckpt.save(1, state)
        ckpt.wait()
        template = trainer.init_state(seed=0)
        restored = ckpt.restore(template, step=1)
    for a, b in zip(
        jax.tree.leaves(state.server_opt), jax.tree.leaves(restored.server_opt)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cli_flags_resolve():
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        build_parser,
        resolve_config,
    )

    args = build_parser().parse_args(
        ["federated", "--num-clients", "2", "--server-opt", "momentum",
         "--server-lr", "0.5", "--server-momentum", "0.8"]
    )
    cfg = resolve_config(args, vocab_size=130)
    assert cfg.fed.server_opt == "momentum"
    assert cfg.fed.server_lr == pytest.approx(0.5)
    assert cfg.fed.server_momentum == pytest.approx(0.8)
