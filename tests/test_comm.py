"""Cross-host comm backend: wire format, native byte-path, framing, and a
2-client loopback federated round (the reference's full client/server flow,
minus pickle, minus the polling race)."""

import socket
import threading
import zlib

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
    FederatedClient,
    WireError,
    aggregate_flat,
    decode,
    encode,
    flatten_params,
    unflatten_params,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    framing,
    native,
)


def _params(rng, scale=1.0):
    return {
        "encoder": {
            "layer_0": {"kernel": rng.normal(size=(8, 8)).astype(np.float32) * scale},
            "bias": rng.normal(size=(8,)).astype(np.float32) * scale,
        },
        "classifier": {"kernel": rng.normal(size=(8, 2)).astype(np.float32) * scale},
        "step": np.int32(7),
    }


# ----------------------------------------------------------------- wire
def test_flatten_unflatten_roundtrip(rng):
    p = _params(rng)
    flat = flatten_params(p)
    assert set(flat) == {
        "encoder/layer_0/kernel",
        "encoder/bias",
        "classifier/kernel",
        "step",
    }
    back = unflatten_params(flat)
    np.testing.assert_array_equal(
        back["encoder"]["layer_0"]["kernel"], p["encoder"]["layer_0"]["kernel"]
    )
    assert back["step"] == 7


def test_encode_decode_exact_roundtrip(rng):
    p = _params(rng)
    blob = encode(p, meta={"client_id": 3, "n_samples": 100})
    params, meta = decode(blob)
    assert meta == {"client_id": 3, "n_samples": 100}
    for key, arr in flatten_params(params).items():
        np.testing.assert_array_equal(arr, flatten_params(p)[key])


def test_encode_bf16_compression_halves_float_payload(rng):
    # Big enough that the payload dwarfs the JSON manifest.
    p = {"w": rng.normal(size=(256, 256)).astype(np.float32),
         "b": rng.normal(size=(256,)).astype(np.float32),
         "step": np.int32(1)}
    raw = encode(p)
    packed = encode(p, compression="bf16")
    assert len(packed) < 0.6 * len(raw)
    params, _ = decode(packed)
    for key, arr in flatten_params(params).items():
        orig = flatten_params(p)[key]
        if orig.dtype == np.float32:
            # bf16 keeps ~8 mantissa bits.
            np.testing.assert_allclose(arr, orig, rtol=1e-2)
        else:
            np.testing.assert_array_equal(arr, orig)  # ints stay exact


def test_encode_int8_compression_quarters_float_payload(rng):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.wire import (
        dequantize_int8,
        quantize_int8,
    )

    p = {"w": rng.normal(size=(256, 256)).astype(np.float32),
         "b": rng.normal(size=(256,)).astype(np.float32),
         "step": np.int32(1)}
    raw = encode(p)
    packed = encode(p, compression="int8")
    assert len(packed) < 0.35 * len(raw)
    params, _ = decode(packed)
    for key, arr in flatten_params(params).items():
        orig = flatten_params(p)[key]
        if orig.dtype == np.float32:
            # Per-row symmetric quantization: error <= row amax / 254.
            rows = orig.reshape(orig.shape[0] if orig.ndim >= 2 else 1, -1)
            bound = (np.abs(rows).max(axis=1) / 254.0 + 1e-7)[:, None]
            err = np.abs(arr.reshape(rows.shape) - rows)
            assert (err <= bound).all()
        else:
            np.testing.assert_array_equal(arr, orig)  # ints stay exact

    # Edge shapes: scalars, 1-D, zero rows/cols, all-zero tensors round-trip.
    for edge in (
        np.float32(3.5).reshape(()),
        np.zeros((4, 8), np.float32),
        rng.normal(size=(5,)).astype(np.float32),
        np.zeros((0, 8), np.float32),
        np.zeros((4, 0), np.float32),
    ):
        back = dequantize_int8(quantize_int8(edge), tuple(np.shape(edge)))
        assert back.shape == np.shape(edge)
        if edge.size:
            np.testing.assert_allclose(
                back, edge, atol=np.abs(edge).max() / 200 + 1e-7
            )


def test_decode_rejects_tampered_payload(rng):
    blob = bytearray(encode(_params(rng)))
    blob[-3] ^= 0x40  # flip one bit in the payload
    with pytest.raises(WireError, match="CRC"):
        decode(bytes(blob))


def test_decode_wraps_malformed_header_as_wire_error(rng):
    """Inconsistent header fields must surface as WireError (the server's
    upload handler catches WireError; a bare ValueError would kill its
    thread and hang the round)."""
    import json
    import struct

    p = {"w": rng.normal(size=(8,)).astype(np.float32)}
    blob = encode(p)
    hlen = struct.unpack("<II", blob[4:12])[1]
    header = json.loads(blob[12 : 12 + hlen])
    header["tensors"][0]["shape"] = [3, 3]  # disagrees with nbytes
    hb = json.dumps(header, separators=(",", ":")).encode()
    bad = blob[:4] + struct.pack("<II", 1, len(hb)) + hb + blob[12 + hlen :]
    with pytest.raises(WireError, match="malformed tensor table"):
        decode(bad)
    header["tensors"] = None  # wrong type entirely
    hb = json.dumps(header, separators=(",", ":")).encode()
    bad = blob[:4] + struct.pack("<II", 1, len(hb)) + hb + blob[12 + hlen :]
    with pytest.raises(WireError):
        decode(bad)


def test_decode_rejects_garbage():
    with pytest.raises(WireError, match="magic"):
        decode(b"\x00" * 64)
    # A pickle-looking blob is rejected at the magic check — by construction
    # nothing in this format ever reaches an unpickler.
    import pickle

    with pytest.raises(WireError, match="magic"):
        decode(pickle.dumps({"a": 1}))


# ---------------------------------------------------------------- native
def test_native_crc_matches_zlib(rng):
    data = rng.integers(0, 256, 100_003).astype(np.uint8).tobytes()
    assert native.crc32(np.frombuffer(data, np.uint8)) == zlib.crc32(data)


def test_bf16_pack_matches_jax_cast(rng):
    import jax.numpy as jnp

    x = rng.normal(size=4096).astype(np.float32)
    x[0], x[1], x[2] = np.inf, -np.inf, np.nan
    packed = native.pack_bf16(x)
    ref_bits = np.asarray(jnp.asarray(x).astype(jnp.bfloat16)).view(np.uint16)
    nan_mask = np.isnan(x)
    np.testing.assert_array_equal(packed[~nan_mask], ref_bits[~nan_mask])
    # NaNs stay NaN (payload bits may differ).
    assert np.all(np.isnan(native.unpack_bf16(packed[nan_mask])))


def test_bf16_python_fallback_matches_native(rng):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.utils import (
        native as native_loader,
    )

    x = rng.normal(size=1024).astype(np.float32)
    via_native = native.pack_bf16(x)
    saved = native_loader._CACHE.get("fedwire.so")
    native_loader._CACHE["fedwire.so"] = None  # force numpy path
    try:
        via_python = native.pack_bf16(x)
    finally:
        native_loader._CACHE["fedwire.so"] = saved
    np.testing.assert_array_equal(via_native, via_python)


def test_xor_roundtrip(rng):
    a = rng.integers(0, 256, 999).astype(np.uint8)
    b = rng.integers(0, 256, 999).astype(np.uint8)
    work = b.copy()
    native.xor_bytes(a, work)  # delta
    assert not np.array_equal(work, b)
    native.xor_bytes(a, work)  # apply (self-inverse)
    np.testing.assert_array_equal(work, b)


# ----------------------------------------------------------- aggregation
def test_aggregate_flat_is_mean(rng):
    a = flatten_params(_params(rng))
    b = flatten_params(_params(rng, scale=3.0))
    agg = aggregate_flat([a, b])
    for key in a:
        np.testing.assert_allclose(
            agg[key],
            (np.asarray(a[key], np.float32) + np.asarray(b[key], np.float32)) / 2,
            rtol=1e-6,
        )


def test_aggregate_flat_weighted(rng):
    a = {"w": np.full((4,), 1.0, np.float32)}
    b = {"w": np.full((4,), 5.0, np.float32)}
    agg = aggregate_flat([a, b], weights=[3.0, 1.0])
    np.testing.assert_allclose(agg["w"], np.full((4,), 2.0), rtol=1e-6)


def test_aggregate_identity_property(rng):
    m = flatten_params(_params(rng))
    agg = aggregate_flat([m, m, m])
    for key in m:
        np.testing.assert_allclose(agg[key], np.asarray(m[key], np.float32), rtol=1e-6)


# -------------------------------------------------------------- framing
def test_framing_roundtrip_loopback(rng):
    payload = rng.integers(0, 256, 3 * (1 << 20) + 17).astype(np.uint8).tobytes()
    server = socket.socket()
    server.bind(("127.0.0.1", 0))
    server.listen(1)
    port = server.getsockname()[1]
    received = {}

    def _serve():
        conn, _ = server.accept()
        received["payload"] = framing.recv_frame(conn)
        conn.close()

    t = threading.Thread(target=_serve)
    t.start()
    client = socket.create_connection(("127.0.0.1", port), timeout=10)
    framing.send_frame(client, payload)
    t.join(timeout=10)
    client.close()
    server.close()
    assert received["payload"] == payload


# ----------------------------------------------- end-to-end FL round (TCP)
@pytest.mark.parametrize("compression", ["none", "bf16", "int8"])
def test_two_client_round_loopback(rng, compression):
    """The reference's whole distributed flow on loopback: 2 clients upload,
    server FedAvgs, both receive the identical aggregate."""
    p0 = _params(rng)
    p1 = _params(rng, scale=2.0)
    results = {}

    with AggregationServer(
        port=0, num_clients=2, timeout=30, compression=compression
    ) as server:

        def _run_server():
            results["agg"] = server.serve_round(deadline=30)

        st = threading.Thread(target=_run_server)
        st.start()

        def _run_client(cid, params):
            client = FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=30,
                compression=compression,
            )
            results[cid] = client.exchange(params, n_samples=10 * (cid + 1))

        c0 = threading.Thread(target=_run_client, args=(0, p0))
        c1 = threading.Thread(target=_run_client, args=(1, p1))
        c0.start(), c1.start()
        c0.join(timeout=30), c1.join(timeout=30)
        st.join(timeout=30)

    assert "agg" in results and 0 in results and 1 in results
    tol = {
        "none": dict(rtol=1e-6),
        "bf16": dict(rtol=1e-2, atol=1e-2),
        # int8 quantizes upload AND reply: ~2 steps of the row max each way.
        "int8": dict(rtol=5e-2, atol=1e-1),
    }[compression]
    expected = aggregate_flat([flatten_params(p0), flatten_params(p1)])
    for key, arr in flatten_params(results[0]).items():
        np.testing.assert_allclose(arr, expected[key], **tol)
    # Both clients got the same bytes back.
    for key, arr in flatten_params(results[1]).items():
        np.testing.assert_array_equal(arr, flatten_params(results[0])[key])


def test_round_times_out_below_quorum(rng):
    with AggregationServer(port=0, num_clients=2, timeout=5) as server:
        def _one_client():
            FederatedClient(
                "127.0.0.1", server.port, client_id=0, timeout=5
            ).exchange(_params(rng), max_retries=1)

        t = threading.Thread(target=_one_client, daemon=True)
        t.start()
        with pytest.raises(RuntimeError, match="1/2 clients"):
            server.serve_round(deadline=2.0)


# ------------------------------------------------------------- wire auth
def test_wire_hmac_roundtrip_and_rejections(rng):
    """HMAC-SHA256 frame auth: keyed decode accepts only valid-tag messages
    (the reference accepts weights from anyone who can connect,
    server.py:57-65)."""
    key = b"shared-secret"
    p = _params(rng)
    msg = encode(p, auth_key=key, meta={"client_id": 3})

    back, meta = decode(msg, auth_key=key)
    np.testing.assert_array_equal(
        back["encoder"]["layer_0"]["kernel"], p["encoder"]["layer_0"]["kernel"]
    )
    assert meta["client_id"] == 3

    # Keyless decoder tolerates (and ignores) the tag.
    back2, _ = decode(msg)
    np.testing.assert_array_equal(
        back2["classifier"]["kernel"], p["classifier"]["kernel"]
    )

    # Tampered payload byte -> rejected.
    bad = bytearray(msg)
    bad[len(bad) - 50] ^= 0x01
    with pytest.raises(WireError, match="HMAC|CRC"):
        decode(bytes(bad), auth_key=key)

    # Wrong key -> rejected.
    with pytest.raises(WireError, match="HMAC"):
        decode(msg, auth_key=b"other-secret")

    # Unauthenticated message to a keyed decoder -> rejected.
    plain = encode(p)
    with pytest.raises(WireError, match="unauthenticated"):
        decode(plain, auth_key=key)

    # Tampered tag itself -> rejected.
    clipped = bytearray(msg)
    clipped[-1] ^= 0xFF
    with pytest.raises(WireError, match="HMAC"):
        decode(bytes(clipped), auth_key=key)


def test_tcp_round_with_auth(rng):
    """One authenticated 2-client TCP round end-to-end."""
    key = b"fleet-secret"
    with AggregationServer(
        port=0, num_clients=2, timeout=20.0, auth_key=key
    ) as server:
        t = threading.Thread(target=lambda: server.serve(rounds=1), daemon=True)
        t.start()
        results = {}

        def _client(cid):
            results[cid] = FederatedClient(
                "127.0.0.1", server.port, client_id=cid, timeout=20.0,
                auth_key=key,
            ).exchange(_params(rng, scale=cid + 1.0), max_retries=2)

        threads = [threading.Thread(target=_client, args=(c,)) for c in (0, 1)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=30)
        t.join(timeout=30)
    assert set(results) == {0, 1}
    a = results[0]["encoder"]["layer_0"]["kernel"]
    np.testing.assert_array_equal(a, results[1]["encoder"]["layer_0"]["kernel"])


def test_auth_rejects_replayed_upload(rng):
    """A captured authenticated upload replayed into a new round carries a
    stale nonce: the server must reject it and the round must fail rather
    than aggregate attacker-chosen weights."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        framing as fr,
    )

    key = b"k"
    stale = encode(
        _params(rng),
        meta={"client_id": 0, "n_samples": 1, "role": "client",
              "nonce": "00" * 16},
        auth_key=key,
    )
    with AggregationServer(
        port=0, num_clients=1, min_clients=1, timeout=6.0, auth_key=key
    ) as server:
        errors = {}

        def _round():
            try:
                server.serve_round(deadline=6.0)
            except RuntimeError as e:
                errors["e"] = e

        t = threading.Thread(target=_round, daemon=True)
        t.start()
        sock = socket.create_connection(("127.0.0.1", server.port), timeout=5)
        sock.settimeout(5)
        chal = fr.recv_frame(sock)
        assert chal.startswith(b"NONC")
        fr.send_frame(sock, stale)  # replay: valid HMAC, wrong nonce
        t.join(timeout=12)
        sock.close()
    assert "e" in errors and "0/1" in str(errors["e"])
