"""Model registry (registry/): content addressing, the candidate ->
shadow -> serving state machine, atomic pointer swap under a concurrent
reader, and rollback."""

import json
import os
import threading

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.registry import (
    ModelRegistry,
    RegistryError,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.registry.store import (
    artifact_id,
)


def _params(seed, shape=(8, 4)):
    rng = np.random.default_rng(seed)
    return {
        "encoder": {"w": rng.normal(size=shape).astype(np.float32)},
        "head": {"b": rng.normal(size=shape[1]).astype(np.float32)},
    }


@pytest.fixture()
def registry(tmp_path):
    return ModelRegistry(str(tmp_path / "registry"))


# ------------------------------------------------------------- addressing
def test_content_addressing_dedups_and_roundtrips(registry):
    p = _params(0)
    a = registry.add(p, round_index=1, metrics={"Accuracy": 0.9})
    assert registry.add(p, round_index=99) == a  # identical bytes dedup
    assert a == artifact_id(p)
    assert artifact_id(_params(1)) != a  # different params, different id
    back = registry.load_params(a)
    np.testing.assert_array_equal(back["encoder"]["w"], p["encoder"]["w"])
    np.testing.assert_array_equal(back["head"]["b"], p["head"]["b"])
    m = registry.manifest(a)
    assert m["state"] == "candidate"
    assert m["round"] == 1
    assert m["metrics"]["Accuracy"] == pytest.approx(0.9)


def test_flat_and_nested_params_share_an_address(registry):
    """serve_round hands the controller FLAT '/'-joined params; the same
    model registered nested must address (and load) identically."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.wire import (
        flatten_params,
    )

    nested = _params(3)
    flat = flatten_params(nested)
    assert artifact_id(nested) == artifact_id(flat)
    a = registry.add(flat, round_index=0)
    back = registry.load_params(a)
    np.testing.assert_array_equal(
        back["encoder"]["w"], nested["encoder"]["w"]
    )


# ----------------------------------------------------------- state machine
def test_promotion_ladder_and_pointer(registry):
    a1 = registry.add(_params(0), round_index=0, metrics={"Accuracy": 0.8})
    assert registry.serving_info() is None
    registry.promote(a1)  # candidate -> shadow
    assert registry.manifest(a1)["state"] == "shadow"
    assert registry.serving_info() is None  # shadow never serves
    registry.promote(a1)  # shadow -> serving (pointer swap)
    info = registry.serving_info()
    assert info["artifact"] == a1 and info["history"] == []
    with pytest.raises(RegistryError):
        registry.promote(a1)  # already serving

    a2 = registry.add(_params(1), round_index=1, metrics={"Accuracy": 0.9})
    registry.promote(a2, to="serving")
    assert registry.serving_info()["artifact"] == a2
    assert registry.serving_info()["history"] == [a1]
    assert registry.manifest(a1)["state"] == "retired"
    assert registry.serving_manifest()["id"] == a2


def test_rejected_candidate_never_reaches_the_pointer(registry):
    a1 = registry.add(_params(0), round_index=0)
    registry.promote(a1, to="serving")
    a2 = registry.add(_params(1), round_index=1)
    registry.reject(a2, reason="gate regression")
    assert registry.manifest(a2)["state"] == "rejected"
    assert registry.serving_info()["artifact"] == a1
    with pytest.raises(RegistryError):
        registry.promote(a2)  # rejected artifacts need an explicit revival


def test_rollback_swaps_back_and_chains(registry):
    ids = [
        registry.add(_params(i), round_index=i) for i in range(3)
    ]
    for a in ids:
        registry.promote(a, to="serving")
    assert registry.serving_info()["artifact"] == ids[2]
    m = registry.rollback()
    assert m["id"] == ids[1]
    assert registry.serving_info()["artifact"] == ids[1]
    assert registry.manifest(ids[2])["state"] == "retired"
    m = registry.rollback()  # chain continues to the first artifact
    assert m["id"] == ids[0]
    with pytest.raises(RegistryError):
        registry.rollback()  # no predecessor left


def test_rollback_without_serving_fails(registry):
    with pytest.raises(RegistryError):
        registry.rollback()


# ------------------------------------------------------------- concurrency
def test_pointer_swap_is_atomic_under_a_concurrent_reader(registry):
    """A scoring process reads the pointer between batches; promotions
    must never expose a torn/partial read — every read is either the old
    pointer or the new one, always naming a loadable artifact."""
    ids = [registry.add(_params(i), round_index=i) for i in range(6)]
    registry.promote(ids[0], to="serving")
    stop = threading.Event()
    bad: list = []
    reads = [0]

    def reader():
        while not stop.is_set():
            try:
                info = registry.serving_info()
                if info is None or info["artifact"] not in ids:
                    bad.append(info)
                    return
                # The named artifact must be fully readable at all times.
                registry.manifest(info["artifact"])
                reads[0] += 1
            except Exception as e:  # torn read = failure
                bad.append(e)
                return

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    for a in ids[1:]:
        registry.promote(a, to="serving")
    for _ in range(3):
        registry.rollback()
    stop.set()
    t.join(timeout=10)
    assert not bad, bad
    assert reads[0] > 0


# ------------------------------------------------------------------ events
def test_events_jsonl_records_the_lifecycle(registry):
    a1 = registry.add(_params(0), round_index=0)
    registry.promote(a1, to="serving")
    a2 = registry.add(_params(1), round_index=1)
    registry.reject(a2, reason="worse")
    events = [
        json.loads(line)
        for line in open(os.path.join(registry.root, "events.jsonl"))
    ]
    kinds = [e["event"] for e in events]
    assert kinds == ["added", "serving", "added", "rejected"]
    assert events[3]["reason"] == "worse"


# ---------------------------------------------------------------------- gc
def test_gc_prunes_retired_rejected_never_the_rollback_chain(registry):
    """max_artifacts pruning (ISSUE 5 satellite): oldest retired/rejected
    artifacts go first; the serving artifact, every id on the rollback
    history, and live candidates/shadows are untouchable — gc refuses to
    break `registry rollback` rather than honor the number."""
    ids = [registry.add(_params(i), round_index=i) for i in range(6)]
    # ids[0..3] serve in turn: 0..2 end up retired ON the rollback chain.
    for a in ids[:4]:
        registry.promote(a, to="serving")
    registry.reject(ids[4], reason="worse")  # prunable
    # ids[5] stays a live candidate — never prunable.
    # Roll back once: ids[3] retired but NOT on the history any more?
    # No — rolled_back_from is not in history; it IS prunable.
    registry.rollback()  # serving -> ids[2], ids[3] retired off-chain
    info = registry.serving_info()
    assert info["artifact"] == ids[2]
    chain = set(info.get("history", []))
    assert chain == {ids[0], ids[1]}

    removed = registry.gc(max_artifacts=4)
    # Eligible: ids[3] (retired, off-chain) and ids[4] (rejected) —
    # exactly the two needed to land on the budget, oldest first.
    assert removed == [ids[3], ids[4]]
    kept = {m["id"] for m in registry.list()}
    assert kept == {ids[0], ids[1], ids[2], ids[5]}
    # A budget below the protected set prunes nothing: the serving
    # artifact + chain are untouchable, the candidate is a live state.
    assert registry.gc(max_artifacts=1) == []
    assert {m["id"] for m in registry.list()} == kept
    # The whole rollback chain still works after gc.
    registry.rollback()
    assert registry.serving_info()["artifact"] == ids[1]
    registry.rollback()
    assert registry.serving_info()["artifact"] == ids[0]
    # The event trail records the prune.
    events = [
        json.loads(line)
        for line in open(os.path.join(registry.root, "events.jsonl"))
    ]
    gc_events = [e for e in events if e["event"] == "gc"]
    assert len(gc_events) == 1 and gc_events[0]["removed"] == removed
    with pytest.raises(RegistryError, match="max_artifacts"):
        registry.gc(max_artifacts=0)


def test_gc_never_reports_a_failed_deletion_as_pruned(registry, monkeypatch):
    """A deletion rmtree cannot complete (permissions, held-open file on
    a non-POSIX mount) must NOT be counted as pruned: the events trail
    would permanently misreport it as garbage-collected while the
    artifact remains on disk and in list(). gc skips it, warns, and
    keeps it counting toward the budget."""
    import shutil as _shutil

    registry.add(_params(0), round_index=0)  # live candidate, protected
    victim = registry.add(_params(9), round_index=9)
    registry.reject(victim, reason="worse")
    real_rmtree = _shutil.rmtree

    def _stuck(path, **kw):
        if os.path.basename(path) == victim:
            return  # deletion silently fails, dir stays on disk
        return real_rmtree(path, **kw)

    monkeypatch.setattr(_shutil, "rmtree", _stuck)
    removed = registry.gc(max_artifacts=1)
    assert victim not in removed
    assert victim in {m["id"] for m in registry.list()}
    events = [
        json.loads(line)
        for line in open(os.path.join(registry.root, "events.jsonl"))
    ]
    for e in events:
        if e["event"] == "gc":
            assert victim not in e["removed"]
    # Once the obstruction clears, the same artifact prunes normally.
    monkeypatch.setattr(_shutil, "rmtree", real_rmtree)
    assert victim in registry.gc(max_artifacts=1)
    assert victim not in {m["id"] for m in registry.list()}


def test_controller_gc_budget_bounds_the_registry(tmp_path):
    """ControlConfig.max_artifacts: the unattended loop prunes after
    every promotion, so a long campaign's registry stays bounded while
    the serving pointer and its rollback chain survive."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        ControlConfig,
    )

    with pytest.raises(ValueError, match="max_artifacts"):
        ControlConfig(max_artifacts=0)
    reg = ModelRegistry(str(tmp_path / "gc-registry"))
    # Simulate the controller's per-round add->promote->gc cadence.
    budget = 3
    for i in range(7):
        aid = reg.add(_params(100 + i), round_index=i)
        reg.promote(aid, to="serving")
        reg.gc(max_artifacts=budget)
    manifests = reg.list()
    # Serving + its (possibly long) history chain are all protected, so
    # the registry can exceed the budget only by protected ids.
    info = reg.serving_info()
    protected = {info["artifact"], *info.get("history", [])}
    unprotected = [m for m in manifests if m["id"] not in protected]
    assert all(
        m.get("state") not in ("retired", "rejected") for m in unprotected
    )
