"""Shadow evaluation plane (ISSUE 13): live-traffic mirroring
(shadow/mirror.py), paired disagreement accounting (shadow/compare.py),
the fail-closed promotion gate (shadow/gate.py), the registry shadow
pointer, the fleet manager's shadow lifecycle, the SCORE_RELOAD
out-of-process reload choreography, and the controller's adaptive
cadence + SLO actuation satellites.

Contracts pinned here:

* Mirrored pairs are BIT-EXACT: the shadow side of a pair equals the
  predict pipeline's probability for the shadow params, the serving
  side the incumbent's — the mirror ships the same request bytes.
* A full mirror queue drops the COPY; the live reply is never delayed
  or failed. A dead shadow backend degrades to pass-through.
* The gate promotes an agreeing candidate and REJECTS a regressing one
  with the verdict recorded on the registry event; the serving pointer
  never moves on a gate miss. Timeout with no evidence fails closed.
* ``ScoringRouter.reload_replica`` drives a drain-then-reload-now sweep
  over out-of-process replicas via the SCORE_RELOAD frame, while the
  in-process rolling-reload path keeps sending ZERO reload frames.
"""

import json
import threading
import time

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    wire,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    ControlConfig,
    ModelConfig,
    ShadowConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.control import (
    Controller,
    SloActuator,
    cadence_interval_s,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    default_tokenizer,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.registry import (
    ModelRegistry,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.router import (
    FleetReplica,
    ScoringRouter,
    ServingFleet,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
    ScoringClient,
    protocol,
    run_load,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.shadow import (
    ShadowCompare,
    ShadowGate,
    ShadowMirror,
    evaluate_status,
    pairs_path,
    read_status,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
    Trainer,
)

TEXTS = [
    f"Destination port is {p}. Flow duration is {d} microseconds. "
    f"Total forward packets are {n}."
    for p, d, n in [
        (80, 100, 3),
        (443, 2500, 9),
        (8080, 7, 1),
        (53, 120000, 44),
    ]
]


@pytest.fixture(scope="module")
def tiny_setup():
    tok = default_tokenizer()
    model_cfg = ModelConfig.tiny(vocab_size=len(tok.vocab))
    trainer = Trainer(model_cfg, TrainConfig(), pad_id=tok.pad_id)
    params = trainer.init_state(seed=0).params
    flat = wire.flatten_params(params)
    # Agreeing candidate: one leaf nudged 1e-6 — distinct artifact id,
    # indistinguishable scores.
    agree = dict(flat)
    k0 = sorted(agree)[0]
    agree[k0] = np.asarray(agree[k0]) + np.float32(1e-6)
    # Regressing candidate: classifier bias slammed so P(attack) ~ 0 —
    # every pair against a ~0.5-scoring incumbent flips, deterministically.
    bad = dict(flat)
    bad["classifier/bias"] = np.asarray([10.0, -10.0], np.float32)
    return (
        tok,
        model_cfg,
        trainer,
        params,
        wire.unflatten_params(agree),
        wire.unflatten_params(bad),
    )


def _replica(tiny_setup, replica_id=0, *, params=None, round_id=1, **kw):
    tok, model_cfg, _t, p1, _pa, _pb = tiny_setup
    kw.setdefault("buckets", (1, 4))
    kw.setdefault("gather_window_s", 0.002)
    return FleetReplica(
        replica_id,
        model_cfg,
        params if params is not None else p1,
        tok,
        round_id=round_id,
        **kw,
    ).start()


def _expected_probs(tiny_setup, texts, params):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
    )

    tok, model_cfg, trainer, _p, _pa, _pb = tiny_setup
    enc = tok.batch_encode(texts, max_len=model_cfg.max_len)
    split = TokenizedSplit(
        enc["input_ids"],
        enc["attention_mask"],
        np.zeros(len(texts), np.int32),
    )
    return trainer.evaluate(params, split, batch_size=4)["probs"]


# -------------------------------------------------------------- compare unit
def test_compare_pairs_either_order_and_stats(tmp_path):
    """Pairs complete regardless of arrival order; flips, |dprob|, and
    the paired JSONL/status artifacts all agree with hand arithmetic."""
    pairs = str(tmp_path / "pairs.jsonl")
    status = str(tmp_path / "status.json")
    c = ShadowCompare(
        threshold=0.5, bins=10, pairs_jsonl=pairs, status_path=status,
        status_every=1,
    )
    c.note_serving(1, 0.9)
    c.note_shadow(1, 0.91)  # agree (both >= 0.5)
    c.note_shadow(2, 0.2)  # shadow first
    c.note_serving(2, 0.8)  # flip
    c.note_serving(3, 0.4)
    c.abandon(3)  # shed before the shadow side arrived
    s = c.snapshot()
    assert s["pairs"] == 2 and s["flips"] == 1
    assert s["flip_rate"] == pytest.approx(0.5)
    assert s["mean_abs_dprob"] == pytest.approx((0.01 + 0.6) / 2)
    assert s["abandoned"] == 1 and s["pending"] == 0
    assert sum(s["hist_serving"]) == 2 and sum(s["hist_shadow"]) == 2
    recs = [json.loads(ln) for ln in open(pairs)]
    assert [r["flip"] for r in recs] == [0, 1]
    assert recs[0]["serving_prob"] == 0.9  # exact doubles round-trip
    on_disk = json.load(open(status))
    assert on_disk["pairs"] == 2  # the atomic cross-process surface
    # Duplicate one-sided arrival keeps the first value, stays half-open.
    c.note_serving(9, 0.7)
    c.note_serving(9, 0.1)
    assert c.snapshot()["pending"] == 1


def test_compare_bounded_pending_drops_oldest():
    c = ShadowCompare(max_pending=2)
    c.note_serving(1, 0.5)
    c.note_serving(2, 0.5)
    c.note_serving(3, 0.5)  # evicts mid 1
    s = c.snapshot()
    assert s["pending"] == 2 and s["pending_dropped"] == 1
    c.note_shadow(1, 0.5)  # its other half: now just a half-open orphan
    c.note_shadow(3, 0.5)  # still paired fine
    s = c.snapshot()
    assert s["pairs"] == 1


def test_evaluate_status_verdicts_both_directions():
    """The gate arithmetic: agree promotes, each disagreement axis (and
    missing evidence) fails closed."""
    base = {"pairs": 100, "flip_rate": 0.0, "psi": 0.01}
    ok, reason = evaluate_status(
        base, min_pairs=50, max_flip_rate=0.02, psi_threshold=0.25
    )
    assert ok and "agreement" in reason
    ok, reason = evaluate_status(
        {**base, "pairs": 10},
        min_pairs=50, max_flip_rate=0.02, psi_threshold=0.25,
    )
    assert not ok and "insufficient" in reason
    ok, reason = evaluate_status(
        {**base, "flip_rate": 0.5},
        min_pairs=50, max_flip_rate=0.02, psi_threshold=0.25,
    )
    assert not ok and "flip_rate" in reason
    ok, reason = evaluate_status(
        {**base, "psi": 1.7},
        min_pairs=50, max_flip_rate=0.02, psi_threshold=0.25,
    )
    assert not ok and "psi" in reason
    ok, reason = evaluate_status(
        {**base, "psi": None},
        min_pairs=50, max_flip_rate=0.02, psi_threshold=0.25,
    )
    assert not ok  # uncomputable distance fails closed


def test_gate_timeout_fails_closed_injectable_clock(tmp_path):
    """No evidence inside the gate's patience = rejection, measured on
    an injected clock — no wall time passes in this test."""
    clock = [0.0]
    sleeps = []

    def fake_sleep(s):
        sleeps.append(s)
        clock[0] += s

    gate = ShadowGate(
        str(tmp_path),
        min_pairs=8,
        timeout_s=5.0,
        poll_s=1.0,
        clock=lambda: clock[0],
        sleep=fake_sleep,
    )
    ok, verdict = gate.wait("cafebabe")
    assert not ok
    assert "timeout" in verdict["reason"] and "failing closed" in verdict["reason"]
    assert verdict["pairs"] == 0
    assert len(sleeps) == 5  # 5 x 1s polls then the deadline


def test_shadow_config_validation():
    ShadowConfig(sample=4)
    with pytest.raises(ValueError):
        ShadowConfig(sample=-1)
    with pytest.raises(ValueError):
        ShadowConfig(max_flip_rate=1.5)
    with pytest.raises(ValueError):
        ShadowConfig(min_pairs=0)
    with pytest.raises(ValueError):
        ShadowConfig(threshold=0.0)


# ------------------------------------------------------ registry shadow ptr
def test_registry_shadow_pointer_lifecycle(tmp_path):
    """promote(to='shadow') announces the evaluation; leaving the state
    (serving, rejected) clears it; an unrelated artifact's transitions
    never tear down a live shadow pointer. reject(verdict=) records the
    measured disagreement on the audit trail."""
    r = ModelRegistry(str(tmp_path / "reg"))
    a = r.add({"w": np.zeros(4, np.float32)}, round_index=0)
    b = r.add({"w": np.ones(4, np.float32)}, round_index=1)
    assert r.shadow_info() is None
    r.promote(a, to="shadow")
    assert r.shadow_info()["artifact"] == a
    # Unrelated artifact promoted to serving: shadow pointer untouched.
    r.promote(b, to="serving")
    assert r.shadow_info()["artifact"] == a
    r.promote(a, to="serving")
    assert r.shadow_info() is None  # left the state by promotion
    # The incumbent can never shadow-evaluate against itself.
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.registry.store import (
        RegistryError,
    )

    with pytest.raises(RegistryError, match="serving"):
        r.promote(a, to="shadow")
    c = r.add({"w": np.full(4, 2.0, np.float32)}, round_index=2)
    r.promote(c, to="shadow")
    verdict = {"pairs": 80, "flip_rate": 0.4, "psi": 1.2, "ok": False}
    r.reject(c, reason="live disagreement", verdict=verdict)
    assert r.shadow_info() is None  # left the state by rejection
    events = [
        json.loads(ln)
        for ln in (tmp_path / "reg" / "events.jsonl").read_text().splitlines()
    ]
    rej = [e for e in events if e["event"] == "rejected"][-1]
    assert rej["artifact"] == c and rej["verdict"]["flip_rate"] == 0.4


# ----------------------------------------------------------- live mirroring
@pytest.mark.slow
def test_mirror_pairs_bit_exact_live(tiny_setup, tmp_path):
    """Router + incumbent replica + shadow replica on DIFFERENT params:
    every mirrored pair's serving side equals the reply the live client
    received (and the incumbent pipeline's probability) bit-for-bit, and
    the shadow side equals scoring the same text on the shadow replica
    directly — the mirror ships the same request bytes both ways.
    Singleton buckets on the shadow replica pin the batch shape, so the
    comparison is structural, not timing-dependent."""
    tok, model_cfg, _t, p1, _pa, p_bad = tiny_setup
    serve_rep = _replica(tiny_setup, 0)
    shadow_rep = _replica(
        tiny_setup, 9, params=p_bad, round_id=2, buckets=(1,)
    )
    # Direct sequential scores on the shadow replica: the reference the
    # mirrored copies must reproduce bit-for-bit (same bytes, same
    # singleton bucket program).
    with ScoringClient("127.0.0.1", shadow_rep.port) as cli:
        direct_shadow = [cli.score(text=t)["prob"] for t in TEXTS]
    compare = ShadowCompare(
        threshold=0.5, bins=10,
        pairs_jsonl=str(tmp_path / "pairs.jsonl"),
    )
    router = ScoringRouter(
        [("127.0.0.1", serve_rep.port)], probe_interval_s=0.2
    )
    mirror = ShadowMirror(
        "127.0.0.1", shadow_rep.port, sample=1, compare=compare
    ).start()
    try:
        router.start()
        router.set_mirror(mirror)
        want_serve = _expected_probs(tiny_setup, TEXTS, p1)
        live_replies = []
        with ScoringClient("127.0.0.1", router.port) as cli:
            for text, p in zip(TEXTS, want_serve):
                reply = cli.score(text=text)
                assert reply["prob"] == float(np.float32(p))
                live_replies.append(reply["prob"])
        deadline = time.monotonic() + 15.0

        def _pair_recs():
            try:
                with open(str(tmp_path / "pairs.jsonl")) as f:
                    return [json.loads(ln) for ln in f]
            except FileNotFoundError:
                return []

        # Wait for the FILE too, not just the in-memory counter: the
        # compare increments pairs under its lock but appends the JSONL
        # line after releasing it (I/O outside the pairing lock by
        # design), so the last record can trail the counter briefly.
        while (
            compare.snapshot()["pairs"] < len(TEXTS)
            or len(_pair_recs()) < len(TEXTS)
        ):
            assert time.monotonic() < deadline, compare.snapshot()
            time.sleep(0.05)
        recs = _pair_recs()
        assert len(recs) == len(TEXTS)
        by_mid = sorted(recs, key=lambda r: r["mid"])
        for rec, live, direct in zip(by_mid, live_replies, direct_shadow):
            assert rec["serving_prob"] == live  # the pair IS the reply
            assert rec["shadow_prob"] == direct  # bit-exact either side
            # The saturated candidate scores ~0: a flip wherever the
            # incumbent answered "attack".
            assert rec["shadow_prob"] < 1e-6
            assert rec["flip"] == int(rec["serving_prob"] >= 0.5)
        s = compare.snapshot()
        assert s["psi"] is not None
    finally:
        router.set_mirror(None)
        mirror.close()
        router.close()
        serve_rep.close()
        shadow_rep.close()


@pytest.mark.slow
def test_mirror_full_queue_drops_copy_not_live_reply(tiny_setup):
    """A shadow backend that accepts but never answers + a 1-slot mirror
    queue: live replies keep flowing at full speed, dropped mirror
    copies are counted, and no live request is rejected."""
    import socket as _socket

    serve_rep = _replica(tiny_setup, 0)
    # A sink that accepts and reads nothing: the mirror's worker blocks
    # on backpressure eventually, so admit()'s bounded queue fills.
    sink = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    sink.bind(("127.0.0.1", 0))
    sink.listen(8)
    sink_conns = []

    def sink_accept():
        while True:
            try:
                conn, _ = sink.accept()
            except OSError:
                return
            sink_conns.append(conn)

    threading.Thread(target=sink_accept, daemon=True).start()
    compare = ShadowCompare()
    router = ScoringRouter(
        [("127.0.0.1", serve_rep.port)], probe_interval_s=0.2
    )
    mirror = ShadowMirror(
        "127.0.0.1",
        sink.getsockname()[1],
        sample=1,
        compare=compare,
        max_queue=1,
    ).start()
    try:
        router.start()
        router.set_mirror(mirror)
        stats = run_load(
            "127.0.0.1", router.port, TEXTS, concurrency=4,
            requests=64, pipeline=4, timeout=30,
        )
        assert stats["scored"] == 64 and stats["rejected"] == 0
        ms = mirror.stats()
        assert ms["seen"] == 64
        # The 1-slot queue sheds copies under load; nothing live paid.
        assert ms["dropped"] + ms["mirrored"] == 64
        assert ms["dropped"] > 0
    finally:
        router.set_mirror(None)
        mirror.close()
        router.close()
        serve_rep.close()
        try:
            sink.close()
        except OSError:
            pass
        for c in sink_conns:
            try:
                c.close()
            except OSError:
                pass


@pytest.mark.slow
def test_mirror_dead_shadow_is_pass_through(tiny_setup):
    """A shadow backend that refuses connections entirely: live scoring
    is untouched, errors are counted, nothing raises on the hot path."""
    import socket as _socket

    # Reserve a port that is closed by the time the mirror dials it.
    probe = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()
    serve_rep = _replica(tiny_setup, 0)
    compare = ShadowCompare()
    router = ScoringRouter(
        [("127.0.0.1", serve_rep.port)], probe_interval_s=0.2
    )
    mirror = ShadowMirror(
        "127.0.0.1", dead_port, sample=1, compare=compare,
        redial_interval_s=0.05,
    ).start()
    try:
        router.start()
        router.set_mirror(mirror)
        stats = run_load(
            "127.0.0.1", router.port, TEXTS, concurrency=2,
            requests=16, timeout=30,
        )
        assert stats["scored"] == 16 and stats["rejected"] == 0
        deadline = time.monotonic() + 10.0
        while mirror.stats()["errors"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        assert compare.snapshot()["pairs"] == 0
    finally:
        router.set_mirror(None)
        mirror.close()
        router.close()
        serve_rep.close()


def test_mirror_sample_stride_is_deterministic(tiny_setup):
    """--shadow-sample N mirrors exactly every Nth admitted request via
    the counter — no RNG, so the sampled set is a pure function of
    arrival order."""
    compare = ShadowCompare()
    mirror = ShadowMirror(
        "127.0.0.1", 1, sample=4, compare=compare, max_queue=64
    )
    # admit() alone (no worker started): pure sampling arithmetic.
    frame = protocol.build_request(1, text="x")
    mids = [mirror.admit(frame) for _ in range(16)]
    assert [m is not None for m in mids] == [
        i % 4 == 0 for i in range(16)
    ]
    assert mirror.stats()["mirrored"] == 4


# -------------------------------------------- fleet lifecycle + gated e2e
@pytest.mark.slow
def test_fleet_shadow_gate_promotes_and_rejects_e2e(tiny_setup, tmp_path):
    """The acceptance-shaped flow: an agreeing candidate enters shadow,
    accumulates live pairs under load, passes the gate, and promotes
    (fleet rolling-reloads, shadow plane torn down); a regressing
    candidate is REJECTED with the verdict on the registry event and the
    pointer never moves. Spans: shadow-compare + shadow-gate emitted."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
        Tracer,
        load_spans,
    )

    tok, model_cfg, _t, p1, p_agree, p_bad = tiny_setup
    registry = ModelRegistry(str(tmp_path / "reg"))
    aid1 = registry.add(p1, round_index=1, model_config=model_cfg)
    registry.promote(aid1, to="serving")
    tracer = Tracer(str(tmp_path / "shadow.jsonl"), proc="fleet")
    reps = [_replica(tiny_setup, i) for i in range(2)]

    def shadow_factory(params, *, round_id):
        return _replica(
            tiny_setup, 9, params=params, round_id=round_id
        )

    fleet = ServingFleet(
        reps,
        registry=registry,
        probe_interval_s=0.2,
        reload_poll_s=0.05,
        shadow_factory=shadow_factory,
        shadow_sample=1,
        tracer=tracer,
    ).start()
    min_pairs = 16
    root = str(tmp_path / "reg")

    def wait_armed(aid):
        deadline = time.monotonic() + 20.0
        while fleet.stats()["shadow_artifact"] != aid:
            assert time.monotonic() < deadline, "shadow never armed"
            time.sleep(0.05)

    def drive(aid):
        stop = threading.Event()
        dropped = [0]

        def loader():
            while not stop.is_set():
                s = run_load(
                    "127.0.0.1", fleet.port, TEXTS, concurrency=4,
                    requests=32, pipeline=4, timeout=30,
                )
                dropped[0] += s["rejected"]

        lt = threading.Thread(target=loader, daemon=True)
        lt.start()
        try:
            gate = ShadowGate(
                root, min_pairs=min_pairs, timeout_s=60.0, poll_s=0.1,
                tracer=tracer,
            )
            ok, verdict = gate.wait(aid)
        finally:
            stop.set()
            lt.join(timeout=60.0)
        assert dropped[0] == 0  # mirroring never cost a live request
        return ok, verdict

    try:
        # (1) The agreeing candidate promotes through the gate.
        aid2 = registry.add(p_agree, round_index=2, model_config=model_cfg)
        registry.promote(aid2, to="shadow")
        wait_armed(aid2)
        ok, verdict = drive(aid2)
        assert ok and verdict["pairs"] >= min_pairs
        assert verdict["flip_rate"] == 0.0
        registry.promote(aid2, to="serving")
        deadline = time.monotonic() + 20.0
        while fleet.stats()["reloads"] < 1:
            assert time.monotonic() < deadline, "rolling reload never ran"
            time.sleep(0.05)
        deadline = time.monotonic() + 10.0
        while fleet.stats()["shadow_artifact"] is not None:
            assert time.monotonic() < deadline, "shadow never torn down"
            time.sleep(0.05)
        assert [r.round_id for r in reps] == [2, 2]
        # (2) The regressing candidate is held out of serving.
        aid3 = registry.add(p_bad, round_index=3, model_config=model_cfg)
        registry.promote(aid3, to="shadow")
        wait_armed(aid3)
        ok3, verdict3 = drive(aid3)
        assert not ok3
        # The saturated candidate disagrees massively on SOME axis —
        # flips wherever the incumbent answers "attack", and a huge PSI
        # regardless (its whole score mass sits in the bottom bin).
        assert (
            verdict3["flip_rate"] > 0.02
            or (verdict3["psi"] is not None and verdict3["psi"] > 0.25)
        )
        registry.reject(aid3, reason=verdict3["reason"], verdict=verdict3)
        assert registry.serving_info()["artifact"] == aid2
        assert registry.manifest(aid3)["state"] == "rejected"
        # Paired evidence on disk for the post-hoc report.
        assert read_status(root, aid3)["pairs"] >= min_pairs
        assert len(open(pairs_path(root, aid3)).readlines()) >= min_pairs
        # (3) Operator re-promote of the rejected artifact: the re-armed
        # plane starts from ZERO evidence — the gate must never rule on
        # the previous evaluation's stale status within one poll.
        deadline = time.monotonic() + 10.0
        while fleet.stats()["shadow_artifact"] is not None:
            assert time.monotonic() < deadline, "shadow never torn down"
            time.sleep(0.05)
        registry.promote(aid3, to="shadow")
        wait_armed(aid3)
        st = read_status(root, aid3)
        assert st is None or st["pairs"] == 0
    finally:
        fleet.close()
        for r in reps:
            r.close()
    events = [
        json.loads(ln)
        for ln in (tmp_path / "reg" / "events.jsonl").read_text().splitlines()
    ]
    rej = [e for e in events if e["event"] == "rejected"][-1]
    assert rej["artifact"] == aid3
    # WHY, on the audit trail: the measured verdict rides the event.
    assert rej["verdict"]["pairs"] >= min_pairs
    assert "disagreement" in rej["reason"]
    spans = load_spans([str(tmp_path / "shadow.jsonl")])
    names = {s["span"] for s in spans}
    assert "shadow-compare" in names and "shadow-gate" in names
    gates = [s for s in spans if s["span"] == "shadow-gate"]
    assert {g["artifact"] for g in gates} == {aid2, aid3}
    assert {g["passed"] for g in gates} == {True, False}
    mirrors = [s for s in spans if s["span"] == "shadow-mirror"]
    assert mirrors  # the mirror's strided spans landed too


def test_controller_shadow_gate_integration(tmp_path):
    """Controller + a stub gate: a passing verdict promotes through
    shadow -> serving; a failing one records shadow_rejected with the
    verdict, leaves the pointer on the incumbent, and the state JSONL
    replays the tallies."""

    class Srv:
        dp_clip = 0.0

        def __init__(self):
            self.n = 0

        def serve_round(self, *, deadline=None, round_index=None):
            self.n += 1
            return {"w": np.full(8, float(self.n), np.float32)}

    class StubGate:
        def __init__(self, outcomes):
            self.outcomes = list(outcomes)
            self.asked = []

        def wait(self, aid):
            self.asked.append(aid)
            ok = self.outcomes.pop(0)
            return ok, {
                "ok": ok,
                "reason": "stub",
                "pairs": 99,
                "flip_rate": 0.0 if ok else 1.0,
                "psi": 0.0 if ok else 9.9,
            }

    def eval_fn(params):
        w = float(np.asarray(params["w"]).mean())
        rng = np.random.default_rng(3)
        return {"Accuracy": w, "probs": rng.uniform(0, 1, 64)}

    registry = ModelRegistry(str(tmp_path / "reg"))
    state = str(tmp_path / "state.jsonl")
    gate = StubGate([True, False])
    ctl = Controller(
        Srv(), registry, eval_fn, state_path=state, shadow_gate=gate
    )
    out1 = ctl.run_cycle()
    assert out1["event"] == "promoted"
    first = registry.serving_info()["artifact"]
    out2 = ctl.run_cycle()  # better eval, but the LIVE gate refuses
    assert out2["event"] == "shadow_rejected"
    assert out2["shadow_verdict"]["flip_rate"] == 1.0
    assert registry.serving_info()["artifact"] == first  # pointer held
    assert ctl.stats.promotions == 1 and ctl.stats.shadow_rejections == 1
    assert len(gate.asked) == 2
    rejected = [
        m for m in registry.list() if m["state"] == "rejected"
    ]
    assert len(rejected) == 1
    # Resume replay keeps the tallies consistent.
    resumed = Controller(
        Srv(), registry, eval_fn, state_path=state
    )
    assert resumed.stats.shadow_rejections == 1
    assert resumed.stats.rounds_completed == 2


# --------------------------------------------------- SCORE_RELOAD satellite
def test_reload_frame_codecs_roundtrip():
    req = protocol.build_reload_request(7)
    assert protocol.is_reload_request(req)
    assert protocol.parse_reload_request(req)["id"] == 7
    rep = protocol.build_reload_reply(7, reloaded=True, round_id=3)
    assert protocol.is_reload_reply(rep)
    body = protocol.parse_reload_reply(rep)
    assert body == {"id": 7, "reloaded": True, "round": 3}
    # The router remaps reload frames like everything else it relays.
    assert protocol.frame_id(protocol.rewrite_id(req, 42)) == 42
    with pytest.raises(wire.WireError):
        protocol.parse_reload_reply(req)


def test_router_reload_replica_drives_out_of_process_adoption(
    tiny_setup, tmp_path
):
    """A replica the router cannot hot-swap (its own RegistryWatcher, as
    a subprocess replica would run): promote an artifact, then
    ``rolling_remote_reload`` — the SCORE_RELOAD frame forces the
    watcher poll NOW and the reply reports the adopted round."""
    import dataclasses

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.serving import (
        MicroBatcher,
        RegistryWatcher,
        ScoreEngine,
        ScoringServer,
    )

    tok, model_cfg, _t, p1, p_agree, _pb = tiny_setup
    registry = ModelRegistry(str(tmp_path / "reg"))
    mc = dataclasses.asdict(model_cfg)
    a = registry.add(p1, round_index=1, model_config=mc)
    registry.promote(a, to="serving")
    engine = ScoreEngine(
        model_cfg, registry.load_params(a), pad_id=tok.pad_id,
        buckets=(1, 4), round_id=1,
    )
    # A LONG poll interval: without the force path, adoption would take
    # ~an hour — the prompt reply below proves SCORE_RELOAD bypassed it.
    watcher = RegistryWatcher(registry, poll_interval_s=3600.0)
    watcher.prime(a)
    server = ScoringServer(
        engine, tok,
        batcher=MicroBatcher(max_batch=4, gather_window_s=0.002),
        watcher=watcher, idle_tick_s=0.01, replica_id=0, warmup=False,
    ).start()
    router = ScoringRouter(
        [("127.0.0.1", server.port)], probe_interval_s=0.2
    )
    try:
        router.start()
        with ScoringClient("127.0.0.1", router.port) as cli:
            assert cli.score(text=TEXTS[0])["round"] == 1
        b = registry.add(p_agree, round_index=2, model_config=mc)
        registry.promote(b, to="serving")
        out = router.rolling_remote_reload(reload_timeout_s=30.0)
        rep0 = out["replicas"][0]
        assert rep0["answered"] and rep0["reloaded"]
        assert rep0["round"] == 2
        assert watcher.reload_count == 1
        with ScoringClient("127.0.0.1", router.port) as cli:
            assert cli.score(text=TEXTS[0])["round"] == 2
        stats = ScoringClient("127.0.0.1", server.port)
        try:
            assert stats.stats()["reload_frames"] == 1
        finally:
            stats.close()
    finally:
        router.close()
        server.close()


@pytest.mark.slow
def test_in_process_rolling_reload_sends_no_reload_frames(
    tiny_setup, tmp_path
):
    """Regression for the existing zero-drop path: the in-process fleet
    manager drives engine hot-swaps directly — the new SCORE_RELOAD
    choreography must not ride it (reload_frames stays 0) and the swap
    still lands with zero drops."""
    tok, model_cfg, _t, p1, p_agree, _pb = tiny_setup
    registry = ModelRegistry(str(tmp_path / "reg"))
    aid1 = registry.add(p1, round_index=1, model_config=model_cfg)
    registry.promote(aid1, to="serving")
    reps = [_replica(tiny_setup, i) for i in range(2)]
    fleet = ServingFleet(
        reps, registry=registry, probe_interval_s=0.2, reload_poll_s=0.05
    ).start()
    try:
        aid2 = registry.add(p_agree, round_index=2, model_config=model_cfg)
        registry.promote(aid2, to="serving")
        deadline = time.monotonic() + 20.0
        while fleet.stats()["reloads"] < 1:
            assert time.monotonic() < deadline, "rolling reload never ran"
            time.sleep(0.05)
        stats = run_load(
            "127.0.0.1", fleet.port, TEXTS, concurrency=2, requests=16
        )
        assert stats["rejected"] == 0
        assert [r.round_id for r in reps] == [2, 2]
        for rep in reps:
            assert rep.server.stats()["reload_frames"] == 0
    finally:
        fleet.close()
        for r in reps:
            r.close()


# ------------------------------------------------- controller satellites
def test_cadence_interval_pure_function():
    """Drift magnitude -> inter-round interval: max at the bare
    threshold, min at 2x threshold and beyond, linear between, and the
    degenerate configs degrade to min."""
    kw = dict(threshold=0.25, min_s=5.0, max_s=65.0)
    assert cadence_interval_s(0.25, **kw) == 65.0
    assert cadence_interval_s(0.50, **kw) == 5.0
    assert cadence_interval_s(9.99, **kw) == 5.0
    mid = cadence_interval_s(0.375, **kw)
    assert mid == pytest.approx(35.0)
    assert cadence_interval_s(0.1, **kw) == 65.0  # below threshold clamps
    assert cadence_interval_s(0.5, threshold=0.25, min_s=5.0, max_s=None) == 5.0
    assert cadence_interval_s(0.5, threshold=0.25, min_s=10.0, max_s=3.0) == 10.0


def test_adaptive_cadence_records_interval_on_drift_span(tmp_path):
    """A synthetic drift verdict through _wait_for_trigger: the chosen
    interval rides the drift-trigger span + state record and becomes
    the next throttle; a clock-fallback trigger relaxes it back."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.control import (
        DriftMonitor,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
        Tracer,
        load_spans,
    )

    registry = ModelRegistry(str(tmp_path / "reg"))
    a = registry.add({"w": np.zeros(4, np.float32)}, round_index=0)
    registry.promote(a, to="serving")

    class Srv:
        dp_clip = 0.0

        def serve_round(self, *, deadline=None, round_index=None):
            return {"w": np.full(4, 0.5, np.float32)}

    # Threshold 7.0: the first synthetic shift (psi ~7.31) fires BARELY
    # over it -> a relaxed, near-max interval; the full collapse below
    # (psi ~17, >= 2x threshold) floors at min. No wall-clock anywhere:
    # _wait_for_trigger returns immediately on a ready verdict because
    # min_interval applies only after a round started.
    dm = DriftMonitor(
        reference=[100, 0, 0, 0, 0, 0, 0, 0, 0, 100],
        threshold=7.0,
        min_scores=8,
    )
    tracer = Tracer(str(tmp_path / "ctl.jsonl"), proc="controller")
    ctl = Controller(
        Srv(),
        registry,
        lambda p: {"Accuracy": 0.9},
        control=ControlConfig(
            adaptive_cadence=True, min_interval_s=1.0, max_interval_s=30.0
        ),
        state_path=str(tmp_path / "state.jsonl"),
        drift_monitor=dm,
        drift_poll_s=0.01,
        tracer=tracer,
    )
    dm.observe([0, 0, 0, 40, 40, 0, 0, 0, 0, 120])
    stop = threading.Event()
    trig = ctl._wait_for_trigger(stop)
    assert trig == "drift"
    assert ctl._interval_override is not None
    chosen = ctl._interval_override
    assert 1.0 < chosen <= 30.0  # mild verdict -> relaxed cadence
    spans = load_spans([str(tmp_path / "ctl.jsonl")])
    dspan = [s for s in spans if s["span"] == "drift-trigger"][-1]
    assert dspan["next_interval_s"] == pytest.approx(chosen, abs=1e-3)
    events = [
        json.loads(ln) for ln in open(str(tmp_path / "state.jsonl"))
    ]
    drec = [e for e in events if e["event"] == "drift_trigger"][-1]
    assert drec["next_interval_s"] == pytest.approx(chosen, abs=1e-3)
    # Massive shift -> urgent: the override collapses to the min.
    dm.observe([0, 0, 0, 0, 500, 500, 0, 0, 0, 0])
    assert ctl._wait_for_trigger(stop) == "drift"
    assert ctl._interval_override < chosen
    assert ctl._interval_override == 1.0


def test_slo_actuator_tightens_until_clear(tmp_path):
    """Fire/clear events from a synthetic alerts-JSONL: the straggler
    deadline tightens by the factor while firing and restores on clear.
    Pure event arithmetic — no clocks, no sleeps."""
    alerts = str(tmp_path / "alerts.jsonl")
    act = SloActuator(alerts, factor=0.5)
    assert act.poll() is False  # missing file = quiet
    assert act.effective_deadline(20.0) == 20.0
    assert act.effective_deadline(None) is None

    def emit(event, slo="round-duration", instance="server:1"):
        with open(alerts, "a") as f:
            f.write(
                json.dumps(
                    {
                        "schema": "fedtpu-alert-v1",
                        "event": event,
                        "slo": slo,
                        "instance": instance,
                        "severity": "page",
                    }
                )
                + "\n"
            )

    emit("fire")
    assert act.poll() is True
    assert act.effective_deadline(20.0) == 10.0
    assert act.effective_deadline(None) is None  # nothing to tighten
    emit("fire", slo="scoring-queue-p99")  # unrelated SLO: ignored
    emit("clear")
    assert act.poll() is False
    assert act.effective_deadline(20.0) == 20.0
    # Two instances fire independently; both must clear.
    emit("fire", instance="a")
    emit("fire", instance="b")
    emit("clear", instance="a")
    assert act.poll() is True
    emit("clear", instance="b")
    assert act.poll() is False
    with pytest.raises(ValueError):
        SloActuator(alerts, factor=0.0)


def test_controller_slo_actuation_tightens_round_deadline(tmp_path):
    """The controller hands the TIGHTENED deadline to the round engine
    while the alert fires, and the configured one after it clears."""
    alerts = str(tmp_path / "alerts.jsonl")

    def emit(event):
        with open(alerts, "a") as f:
            f.write(
                json.dumps(
                    {
                        "event": event,
                        "slo": "round-duration",
                        "instance": "server:1",
                    }
                )
                + "\n"
            )

    seen = []

    class Srv:
        dp_clip = 0.0

        def __init__(self):
            self.n = 0

        def serve_round(self, *, deadline=None, round_index=None):
            seen.append(deadline)
            self.n += 1
            return {"w": np.full(4, float(self.n), np.float32)}

    registry = ModelRegistry(str(tmp_path / "reg"))
    ctl = Controller(
        Srv(),
        registry,
        lambda p: {"Accuracy": float(np.asarray(p["w"]).mean())},
        control=ControlConfig(
            round_deadline_s=40.0, slo_deadline_factor=0.25
        ),
        state_path=str(tmp_path / "state.jsonl"),
        slo_actuator=SloActuator(alerts, factor=0.25),
    )
    ctl.run_cycle()
    assert seen == [40.0]
    emit("fire")
    out = ctl.run_cycle()
    assert seen[-1] == 10.0  # tightened while firing
    assert out.get("slo_tightened") is True
    emit("clear")
    ctl.run_cycle()
    assert seen[-1] == 40.0  # restored on clear


# ------------------------------------------------------------------- CLI
def test_shadow_cli_parser_wiring(tmp_path, capsys):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.parser import (
        build_parser,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli.shadow import (
        cmd_shadow,
    )

    ap = build_parser()
    a = ap.parse_args(
        ["shadow", "status", "--registry-dir", str(tmp_path / "reg")]
    )
    assert a.fn.__name__ == "cmd_shadow" and a.action == "status"
    a = ap.parse_args(
        ["fleet", "--registry-dir", "/tmp/r", "--shadow-sample", "8"]
    )
    assert a.shadow_sample == 8
    a = ap.parse_args(
        [
            "controller", "--registry-dir", "/tmp/r", "--shadow-gate",
            "--shadow-min-pairs", "32", "--shadow-timeout", "9",
            "--adaptive-cadence", "--slo-alerts-jsonl", "/tmp/a.jsonl",
            "--slo-deadline-factor", "0.3",
        ]
    )
    assert a.shadow_gate and a.shadow_min_pairs == 32
    assert a.adaptive_cadence and a.slo_deadline_factor == 0.3
    # status/report run against a real (empty, then populated) registry.
    registry = ModelRegistry(str(tmp_path / "reg"))
    a = ap.parse_args(
        ["shadow", "status", "--registry-dir", str(tmp_path / "reg")]
    )
    assert cmd_shadow(a) == 0
    out = capsys.readouterr().out
    assert "nothing is under shadow evaluation" in out
    aid = registry.add({"w": np.zeros(4, np.float32)}, round_index=0)
    registry.promote(aid, to="shadow")
    a = ap.parse_args(
        [
            "shadow", "status", "--registry-dir", str(tmp_path / "reg"),
            "--json",
        ]
    )
    assert cmd_shadow(a) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["shadow"]["artifact"] == aid and rec["status"] is None


def test_shadow_vocab_registered():
    """The shadow plane's spans are in the closed obs vocabulary (the
    static pass anchors on SPAN_NAMES) and the timeline's unscoped
    section renders them."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs import (
        SPAN_NAMES,
        timeline_table,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.trace import (
        SCHEMA,
    )

    for name in ("shadow-mirror", "shadow-compare", "shadow-gate"):
        assert name in SPAN_NAMES
    spans = [
        {
            "schema": SCHEMA, "proc": "fleet", "span": "shadow-mirror",
            "ts": 0.5, "dur_s": 0.0, "mirrored": 128,
        },
        {
            "schema": SCHEMA, "proc": "fleet", "span": "shadow-compare",
            "ts": 1.0, "dur_s": 0.0, "pairs": 64, "flip_rate": 0.0,
        },
        {
            "schema": SCHEMA, "proc": "controller", "span": "shadow-gate",
            "ts": 2.0, "dur_s": 3.0, "artifact": "abc", "passed": True,
            "pairs": 64,
        },
    ]
    table = timeline_table(spans)
    assert "shadow-compare" in table and "shadow-gate" in table
    assert "shadow-mirror" in table and "mirrored=128" in table
    assert "pairs=64" in table and "passed=True" in table
