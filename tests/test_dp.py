"""DP-FedAvg (parallel/dp.py): clipping, noise calibration, masking,
the RDP accountant, and the federated-trainer integration.

The reference has no privacy mechanism — clients ship raw state dicts
(client1.py:276-295) — so these tests pin this framework's own semantics:
noiseless DP with a huge clip must be bit-equivalent to plain FedAvg, and
the Gaussian mechanism must be calibrated to clip / n_participants.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel import (
    FedShardings,
    fedavg,
    make_mesh,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.dp import (
    client_update_norms,
    dp_epsilon,
    dp_fedavg,
    make_dp_fedavg_step,
)


def _stack(C, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(scale * rng.normal(size=(C, 4, 3)).astype(np.float32)),
        "b": jnp.asarray(scale * rng.normal(size=(C, 3)).astype(np.float32)),
    }


def _anchor_like(stacked, seed=1):
    """Anchor with identical rows (the previous round's replicated mean)."""
    rng = np.random.default_rng(seed)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(
            jnp.asarray(rng.normal(size=x.shape[1:]).astype(np.float32))[None],
            x.shape,
        ),
        stacked,
    )


def _key():
    return jax.random.key(0)


def test_update_norms_match_numpy():
    stacked = _stack(3, seed=2)
    anchor = _anchor_like(stacked, seed=3)
    norms = np.asarray(client_update_norms(stacked, anchor))
    for c in range(3):
        sq = sum(
            np.sum((np.asarray(l)[c] - np.asarray(a)[c]) ** 2)
            for l, a in zip(jax.tree.leaves(stacked), jax.tree.leaves(anchor))
        )
        np.testing.assert_allclose(norms[c], math.sqrt(sq), rtol=1e-5)


def test_noiseless_huge_clip_matches_plain_fedavg():
    stacked = _stack(4, seed=4)
    anchor = _anchor_like(stacked, seed=5)
    out, _ = dp_fedavg(
        stacked, anchor, _key(), None, clip=1e9, noise_multiplier=0.0
    )
    plain = fedavg(stacked)
    for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(plain)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_clipping_bounds_the_aggregate_update():
    stacked = _stack(4, seed=6, scale=50.0)  # huge updates, all clipped
    anchor = _anchor_like(stacked, seed=7)
    clip = 1.0
    out, norms = dp_fedavg(
        stacked, anchor, _key(), None, clip=clip, noise_multiplier=0.0
    )
    assert np.all(np.asarray(norms) > clip)  # they were indeed oversized
    # ||mean of clipped updates|| <= clip, so the applied global update is too.
    agg_sq = sum(
        np.sum((np.asarray(l)[0] - np.asarray(a)[0]) ** 2)
        for l, a in zip(jax.tree.leaves(out), jax.tree.leaves(anchor))
    )
    assert math.sqrt(agg_sq) <= clip + 1e-5


def test_per_client_clip_scaling_exact():
    """One client under the clip, one over: the mean must use the raw
    update for the first and the rescaled update for the second."""
    C = 2
    anchor = {"w": jnp.zeros((C, 8), jnp.float32)}
    small = np.full(8, 0.1, np.float32)  # norm ~0.283 < clip
    big = np.full(8, 10.0, np.float32)  # norm ~28.3 > clip
    stacked = {"w": jnp.asarray(np.stack([small, big]))}
    clip = 1.0
    out, norms = dp_fedavg(
        stacked, anchor, _key(), None, clip=clip, noise_multiplier=0.0
    )
    big_norm = float(np.linalg.norm(big))
    expected = (small + big * (clip / big_norm)) / 2
    np.testing.assert_allclose(np.asarray(out["w"])[0], expected, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(out["w"])[1], expected, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(norms), [np.linalg.norm(small), big_norm], rtol=1e-5
    )


def test_noise_is_deterministic_per_key_and_calibrated():
    """With params == anchor (zero updates) the output is anchor + noise;
    its empirical std must match noise_multiplier * clip / n."""
    C, D = 4, 20000
    anchor = {"w": jnp.zeros((C, D), jnp.float32)}
    stacked = {"w": jnp.zeros((C, D), jnp.float32)}
    clip, mult = 2.0, 1.5
    out1, _ = dp_fedavg(
        stacked, anchor, _key(), None, clip=clip, noise_multiplier=mult
    )
    out2, _ = dp_fedavg(
        stacked, anchor, _key(), None, clip=clip, noise_multiplier=mult
    )
    np.testing.assert_array_equal(np.asarray(out1["w"]), np.asarray(out2["w"]))
    out3, _ = dp_fedavg(
        stacked,
        anchor,
        jax.random.key(99),
        None,
        clip=clip,
        noise_multiplier=mult,
    )
    assert not np.array_equal(np.asarray(out1["w"]), np.asarray(out3["w"]))

    noise = np.asarray(out1["w"])[0]
    expected_std = mult * clip / C
    assert abs(noise.std() - expected_std) / expected_std < 0.05
    # every client received the identical noised global
    for c in range(1, C):
        np.testing.assert_array_equal(np.asarray(out1["w"])[c], noise)


def test_masked_clients_excluded_and_noise_rescaled():
    C = 4
    anchor = {"w": jnp.zeros((C, 6), jnp.float32)}
    deltas = np.arange(C * 6, dtype=np.float32).reshape(C, 6) / 100.0
    stacked = {"w": jnp.asarray(deltas)}
    mask = jnp.asarray([1.0, 0.0, 1.0, 0.0])
    out, _ = dp_fedavg(
        stacked, anchor, _key(), mask, clip=1e9, noise_multiplier=0.0
    )
    expected = (deltas[0] + deltas[2]) / 2
    np.testing.assert_allclose(np.asarray(out["w"])[1], expected, rtol=1e-5)

    # Noise std uses n = survivors (2), not C (4).
    D = 20000
    zeros = {"w": jnp.zeros((C, D), jnp.float32)}
    clip, mult = 1.0, 1.0
    noisy, _ = dp_fedavg(
        zeros, zeros, _key(), mask, clip=clip, noise_multiplier=mult
    )
    std = np.asarray(noisy["w"])[0].std()
    expected_std = mult * clip / 2
    assert abs(std - expected_std) / expected_std < 0.05


def test_dp_step_on_mesh_collective(eight_devices):
    mesh = make_mesh(4, 2, devices=eight_devices)
    sh = FedShardings(mesh)
    stacked = jax.device_put(_stack(4, seed=8), sh.client)
    anchor = jax.device_put(_anchor_like(stacked, seed=9), sh.client)
    step = make_dp_fedavg_step(sh, clip=0.5, noise_multiplier=0.1)
    out, norms = step(stacked, anchor, _key(), jnp.ones((4,), jnp.float32))
    assert out["w"].sharding.spec == sh.client.spec
    assert norms.shape == (4,)
    rows = np.asarray(out["w"])
    for c in range(1, 4):
        np.testing.assert_allclose(rows[c], rows[0], atol=1e-6)
    assert np.all(np.isfinite(rows))


# --------------------------------------------------------------- accountant


def test_dp_epsilon_monotonicity_and_edges():
    assert dp_epsilon(0, 1.0, 1e-5) == 0.0
    assert dp_epsilon(5, 0.0, 1e-5) == math.inf
    e1 = dp_epsilon(1, 1.0, 1e-5)
    e_more_noise = dp_epsilon(1, 4.0, 1e-5)
    e_more_rounds = dp_epsilon(10, 1.0, 1e-5)
    assert 0 < e_more_noise < e1 < e_more_rounds
    # Gaussian mechanism sanity: sigma=1, delta=1e-5 lands in the classic
    # single-digit-epsilon regime.
    assert 1.0 < e1 < 10.0
    with pytest.raises(ValueError, match="delta"):
        dp_epsilon(1, 1.0, 0.0)
    with pytest.raises(ValueError, match="rounds"):
        dp_epsilon(-1, 1.0, 1e-5)


# ------------------------------------------------------------ config guards


def test_config_rejects_noise_without_clip():
    with pytest.raises(ValueError, match="dp_clip"):
        FedConfig(dp_noise_multiplier=1.0)


def test_config_rejects_weighted_dp():
    with pytest.raises(ValueError, match="uniform mean"):
        FedConfig(dp_clip=1.0, weighted=True)


# ------------------------------------------------- FederatedTrainer rounds


def _tiny_cfg(clients=4, **fed_kw):
    model = ModelConfig.tiny()
    return ExperimentConfig(
        model=model,
        data=DataConfig(max_len=model.max_len, batch_size=4),
        train=TrainConfig(learning_rate=1e-3, epochs_per_round=1, seed=0),
        fed=FedConfig(num_clients=clients, **fed_kw),
        mesh=MeshConfig(clients=clients, data=1),
    )


def _tiny_batch(cfg, clients, B=4):
    rng = np.random.default_rng(0)
    L = cfg.model.max_len
    return {
        "input_ids": rng.integers(
            0, cfg.model.vocab_size, (clients, B, L)
        ).astype(np.int32),
        "attention_mask": np.ones((clients, B, L), np.int32),
        "labels": rng.integers(0, 2, (clients, B)).astype(np.int32),
    }


@pytest.mark.slow
def test_trainer_dp_round_replicates_and_stays_finite(eight_devices):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train import (
        FederatedTrainer,
    )

    cfg = _tiny_cfg(clients=4, dp_clip=0.5, dp_noise_multiplier=0.3)
    mesh = make_mesh(4, 1, devices=eight_devices[:4])
    trainer = FederatedTrainer(cfg, mesh=mesh)
    state = trainer.init_state(seed=0)
    anchor = trainer.round_anchor(state)
    assert anchor is not None
    state, _ = trainer.train_step(state, _tiny_batch(cfg, 4))
    state = trainer.aggregate(state, anchor=anchor, round_index=0)
    leaf = np.asarray(jax.tree.leaves(state.params)[0])
    for c in range(1, 4):
        np.testing.assert_allclose(leaf[c], leaf[0], rtol=1e-6)
    assert all(
        np.all(np.isfinite(np.asarray(l))) for l in jax.tree.leaves(state.params)
    )


def test_trainer_dp_requires_anchor(eight_devices):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train import (
        FederatedTrainer,
    )

    cfg = _tiny_cfg(clients=2, dp_clip=1.0)
    mesh = make_mesh(2, 1, devices=eight_devices[:2])
    trainer = FederatedTrainer(cfg, mesh=mesh)
    state = trainer.init_state(seed=0)
    with pytest.raises(ValueError, match="round_anchor"):
        trainer.aggregate(state)


@pytest.mark.slow
def test_trainer_dp_noise_is_fresh_entropy_unless_pinned(eight_devices):
    """Default dp_seed=None must draw fresh OS entropy per trainer (noise
    derived from the public config seed could be regenerated and
    subtracted, voiding the guarantee); pinning dp_seed reproduces it."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train import (
        FederatedTrainer,
    )

    def agg_leaf(fed_kw):
        cfg = _tiny_cfg(clients=2, dp_clip=1.0, dp_noise_multiplier=1.0, **fed_kw)
        mesh = make_mesh(2, 1, devices=eight_devices[:2])
        trainer = FederatedTrainer(cfg, mesh=mesh)
        state = trainer.init_state(seed=0)
        anchor = trainer.round_anchor(state)
        state = trainer.aggregate(state, anchor=anchor, round_index=0)
        return np.asarray(jax.tree.leaves(state.params)[0])

    fresh_a, fresh_b = agg_leaf({}), agg_leaf({})
    assert not np.array_equal(fresh_a, fresh_b)
    pinned_a, pinned_b = agg_leaf({"dp_seed": 7}), agg_leaf({"dp_seed": 7})
    np.testing.assert_array_equal(pinned_a, pinned_b)


def test_trainer_without_dp_has_no_anchor(eight_devices):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train import (
        FederatedTrainer,
    )

    cfg = _tiny_cfg(clients=2)
    mesh = make_mesh(2, 1, devices=eight_devices[:2])
    trainer = FederatedTrainer(cfg, mesh=mesh)
    state = trainer.init_state(seed=0)
    assert trainer.round_anchor(state) is None


def test_sgm_rdp_alpha2_closed_form():
    """Integer-order SGM RDP at alpha=2 has the exact closed form
    RDP(2) = log(1 + q^2 (e^(1/sigma^2) - 1)); the log-space series must
    reproduce it across (q, sigma)."""
    import math

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.dp import (
        sgm_rdp,
    )

    for q in (0.01, 0.1, 0.5, 0.9):
        for sigma in (0.5, 1.0, 2.0, 5.0):
            want = math.log(1.0 + q * q * (math.exp(1.0 / sigma**2) - 1.0))
            assert abs(sgm_rdp(2, q, sigma) - want) < 1e-12, (q, sigma)
    # q=1 collapses to the plain Gaussian RDP alpha/(2 sigma^2).
    assert abs(sgm_rdp(7, 1.0, 1.3) - 7 / (2 * 1.3**2)) < 1e-12
    # Large alpha must not overflow (log-space evaluation).
    assert sgm_rdp(511, 0.05, 1.0) < float("inf")
    with pytest.raises(ValueError, match="integer order"):
        sgm_rdp(1, 0.1, 1.0)


def test_dp_epsilon_subsampling_amplification():
    """The subsampled accountant must (a) reduce to the full bound at q=1,
    (b) beat it strictly for q < 1 (privacy amplification), (c) stay
    monotone in q, T, and 1/sigma, and (d) vanish as q -> 0."""
    full = dp_epsilon(100, 1.0, 1e-5)
    at_q1 = dp_epsilon(100, 1.0, 1e-5, sampling_rate=1.0)
    assert at_q1 == full
    # Integer orders only for q<1: in regimes where the optimal order is
    # >= 2 (here sigma=4 -> alpha* ~ 3), q ~ 1 lands within a whisker of
    # the full bound. (At sigma=1/T=100 the optimal order is fractional
    # ~1.5, where the integer-order SGM bound is inherently ~14% looser.)
    full4 = dp_epsilon(100, 4.0, 1e-5)
    near = dp_epsilon(100, 4.0, 1e-5, sampling_rate=0.999999)
    assert abs(near - full4) / full4 < 0.05
    sub = dp_epsilon(100, 1.0, 1e-5, sampling_rate=0.1)
    assert sub < 0.5 * full  # amplification is large at q=0.1
    assert dp_epsilon(100, 1.0, 1e-5, sampling_rate=0.01) < sub
    assert dp_epsilon(200, 1.0, 1e-5, sampling_rate=0.1) > sub  # more rounds
    assert dp_epsilon(100, 2.0, 1e-5, sampling_rate=0.1) < sub  # more noise
    # q -> 0: amplification drives epsilon far below the full bound (the
    # log(1/delta)/(alpha-1) conversion term floors it near ~0.7 here).
    assert dp_epsilon(100, 1.0, 1e-5, sampling_rate=1e-4) < 0.01 * full
    with pytest.raises(ValueError, match="sampling_rate"):
        dp_epsilon(10, 1.0, 1e-5, sampling_rate=0.0)


def test_sgm_rdp_matches_independent_series():
    """Cross-check the log-space series against a direct float evaluation
    in a regime where the direct sum cannot overflow."""
    import math

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.dp import (
        sgm_rdp,
    )

    q, sigma = 0.2, 2.0
    for alpha in (2, 3, 5, 8, 16):
        direct = sum(
            math.comb(alpha, k)
            * (1 - q) ** (alpha - k)
            * q**k
            * math.exp(k * (k - 1) / (2 * sigma**2))
            for k in range(alpha + 1)
        )
        want = math.log(direct) / (alpha - 1)
        assert abs(sgm_rdp(alpha, q, sigma) - want) < 1e-12, alpha


def test_dp_epsilon_never_worse_than_full_bound():
    """q < 1 must never report a LARGER epsilon than full participation
    (the full Gaussian bound stays valid under subsampling and covers the
    fractional-order regime the integer-order SGM bound cannot reach)."""
    for sigma in (0.7, 1.0, 4.0):
        full = dp_epsilon(100, sigma, 1e-5)
        for q in (0.9, 0.99, 0.999999):
            assert dp_epsilon(100, sigma, 1e-5, sampling_rate=q) <= full


def test_dp_epsilon_both_adjacency_bounds_pinned():
    """Both adjacency bounds the run banner prints, value-pinned for a
    known (q, sigma, T) triple. Replace-one adjacency doubles the mean's
    sensitivity (2*clip/n), equivalent to halving the effective noise
    multiplier — the same mechanism reads ~3-4x weaker in epsilon."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.dp import (
        dp_epsilon_both,
    )

    e_zeroed, e_replace = dp_epsilon_both(10, 1.0, 1e-5, sampling_rate=0.25)
    assert abs(e_zeroed - 7.914871206627728) < 1e-9
    assert abs(e_replace - 26.21441811260802) < 1e-9
    # The replace-one figure IS the zeroed bound at half the multiplier.
    assert e_replace == dp_epsilon(10, 0.5, 1e-5, sampling_rate=0.25)
    # Full participation variant, also pinned.
    f_zeroed, f_replace = dp_epsilon_both(3, 2.0, 1e-5)
    assert abs(f_zeroed - 4.530759175449132) < 1e-9
    assert abs(f_replace - 9.811759094632224) < 1e-9
    assert e_replace > e_zeroed and f_replace > f_zeroed


def test_poisson_mode_resolution_and_exact_rate():
    """participation_mode='auto' resolves to the Poisson sampler exactly
    when DP is on, and the accountant's q is then the nominal Bernoulli
    rate (exact) rather than the ceil-rounded cohort approximation."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        FedConfig,
    )

    dp_kw = dict(dp_clip=1.0, dp_noise_multiplier=1.0)
    auto_dp = FedConfig(
        num_clients=4, participation=0.3, min_client_fraction=0.25, **dp_kw
    )
    assert auto_dp.resolve_participation_mode() == "poisson"
    assert auto_dp.dp_sampling_rate() == (0.3, True)
    # No DP: auto keeps the classic fixed-size sampler, approx accounting.
    plain = FedConfig(
        num_clients=4, participation=0.26, min_client_fraction=0.25
    )
    assert plain.resolve_participation_mode() == "fixed"
    assert plain.dp_sampling_rate() == (0.5, False)  # ceil(4*0.26)/4
    # Explicit modes override auto in both directions.
    forced_fixed = FedConfig(
        num_clients=4, participation=0.26, min_client_fraction=0.25,
        participation_mode="fixed", **dp_kw,
    )
    assert forced_fixed.resolve_participation_mode() == "fixed"
    assert forced_fixed.dp_sampling_rate() == (0.5, False)
    forced_poisson = FedConfig(
        num_clients=4, participation=0.3, min_client_fraction=0.25,
        participation_mode="poisson",
    )
    assert forced_poisson.resolve_participation_mode() == "poisson"
    # Full participation: no sampling, q exact at 1.
    assert FedConfig(num_clients=4, **dp_kw).dp_sampling_rate() == (1.0, True)
    with pytest.raises(ValueError, match="participation_mode"):
        FedConfig(num_clients=4, participation_mode="bogus")


def test_poisson_sampler_bernoulli_and_deterministic(eight_devices):
    """The Poisson mask draws each client independently at rate q —
    variable cohort sizes (including empty), seeded-deterministic per
    round, long-run mean ~= q."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.federated import (
        FederatedTrainer,
    )

    cfg = _tiny_cfg(
        clients=2, participation=0.4, min_client_fraction=0.4,
        participation_mode="poisson",
    )
    mesh = make_mesh(2, 1, devices=eight_devices[:2])
    trainer = FederatedTrainer(cfg, mesh=mesh)
    masks = np.stack(
        [trainer.participation_mask(r) for r in range(2000)]
    )
    assert set(np.unique(masks)) <= {0.0, 1.0}
    sizes = masks.sum(axis=1)
    assert 0.0 in sizes and 2.0 in sizes  # genuinely variable cohorts
    assert abs(masks.mean() - 0.4) < 0.03  # Bernoulli(q) per client
    np.testing.assert_array_equal(
        trainer.participation_mask(7), trainer.participation_mask(7)
    )


@pytest.mark.slow
def test_poisson_empty_cohort_round_is_noop(eight_devices):
    """A DP run under the Poisson sampler survives empty-cohort rounds:
    aggregation is skipped (no crash, params carried forward) — the
    branch the fixed sampler's min-fraction check would have aborted."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.federated import (
        FederatedTrainer,
    )

    cfg = _tiny_cfg(
        clients=2,
        rounds=3,
        participation=0.05,  # empty cohorts near-certain
        min_client_fraction=0.05,
        dp_clip=0.5,
        dp_noise_multiplier=0.3,
        dp_seed=0,
    )
    mesh = make_mesh(2, 1, devices=eight_devices[:2])
    trainer = FederatedTrainer(cfg, mesh=mesh)
    assert cfg.fed.resolve_participation_mode() == "poisson"
    # At least one of the 3 rounds must draw an empty cohort under this
    # seed (verify explicitly so the test can't silently stop covering
    # the skip branch).
    assert any(
        trainer.participation_mask(r).sum() == 0 for r in range(cfg.fed.rounds)
    )
    rng = np.random.default_rng(0)
    n, L = 8, cfg.model.max_len
    train = TokenizedSplit(
        rng.integers(1, 200, (2, n, L)).astype(np.int32),
        np.ones((2, n, L), np.int32),
        rng.integers(0, 2, (2, n)).astype(np.int32),
    )
    evals = [
        TokenizedSplit(
            train.input_ids[c], train.attention_mask[c], train.labels[c]
        )
        for c in range(2)
    ]
    state = trainer.init_state(seed=0)
    state, history = trainer.run(state, train, evals)
    assert len(history) == cfg.fed.rounds  # no round crashed


def test_effective_participation_feeds_accountant():
    """ceil-rounded cohorts: --participation 0.26 of 4 clients samples 2
    (q=0.5); the accountant and the sampler must agree on that rate."""
    import numpy as np

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        FedConfig,
    )

    fed = FedConfig(
        num_clients=4, participation=0.26, min_client_fraction=0.25
    )
    assert fed.cohort_size() == 2
    assert fed.effective_participation() == 0.5
    assert FedConfig(num_clients=4).effective_participation() == 1.0
    # The sampler draws exactly cohort_size clients.
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.federated import (
        FederatedTrainer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        DataConfig,
        ExperimentConfig,
        MeshConfig,
        ModelConfig,
        TrainConfig,
    )

    cfg = ExperimentConfig(
        model=ModelConfig.tiny(),
        data=DataConfig(max_len=ModelConfig.tiny().max_len),
        train=TrainConfig(),
        fed=fed,
        mesh=MeshConfig(clients=4, data=1),
    )
    t = FederatedTrainer(cfg)
    mask = t.participation_mask(0)
    assert mask is not None and int(np.asarray(mask).sum()) == 2
    # Overstating privacy: nominal 0.26 would claim a tighter epsilon than
    # the executed q=0.5 run actually provides.
    assert dp_epsilon(50, 1.0, 1e-5, sampling_rate=0.26) < dp_epsilon(
        50, 1.0, 1e-5, sampling_rate=0.5
    )


def test_poisson_ragged_empty_effective_cohort_is_noop(eight_devices):
    """ADVICE r4: a non-empty Poisson draw whose every member is
    STRUCTURALLY absent (base_mask — ragged fleets where some clients
    hold no data) is the same benign, data-independent sampling event as
    an empty draw: a no-op round, not a zero-survivor abort. A crash
    (faults) wiping the effective cohort still aborts loudly."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.federated import (
        FederatedTrainer,
    )

    cfg = _tiny_cfg(
        clients=2, participation=0.4, min_client_fraction=0.4,
        participation_mode="poisson",
    )
    mesh = make_mesh(2, 1, devices=eight_devices[:2])
    trainer = FederatedTrainer(cfg, mesh=mesh)
    r = next(
        r for r in range(1000)
        if float(trainer.participation_mask(r).sum()) == 1.0
    )
    draw = trainer.participation_mask(r)
    state = trainer.init_state(seed=0)
    # The one drawn client holds no data: benign no-op, params untouched.
    out = trainer.round_aggregate(
        state, round_index=r, base_mask=1.0 - draw
    )
    assert out is state
    # Same shape of emptiness via faults = a crashed cohort: abort.
    with pytest.raises(RuntimeError, match="survived"):
        trainer.round_aggregate(
            state,
            round_index=r,
            base_mask=np.ones(2),
            faults=np.zeros(2),
        )
