"""Central DP on the TCP tier (comm/server.py dp_clip): clipped
round-delta uploads, server-side Gaussian noise on the mean, delta
replies — privacy reachable from `serve`/`client`, composing with
secure aggregation. The reference's TCP deployment has no privacy
mechanism of any kind (reference server.py:57-65)."""

import struct
import threading

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
    FederatedClient,
    flatten_params,
    framing,
    wire,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.client import (
    connect_with_retry,
)


def _serve_one(server, results, deadline=20):
    def _go():
        # Expected round failures land in results["err"], never escape
        # the thread (a bare lambda would bleed
        # PytestUnhandledThreadExceptionWarning into later tests).
        try:
            results["agg"] = server.serve_round(deadline=deadline)
        except RuntimeError as e:
            results["agg"] = None
            results["err"] = e

    t = threading.Thread(target=_go)
    t.start()
    return t


def _run_clients(clients, params_list, bases, results, n_samples=1):
    def _go(i):
        results[i] = clients[i].exchange(
            params_list[i], n_samples=n_samples, round_base=bases[i]
        )

    ts = [threading.Thread(target=_go, args=(i,)) for i in range(len(clients))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    return ts


def test_plain_dp_round_is_clipped_mean_of_deltas(rng):
    """Noiseless DP round: the returned aggregate is exactly
    base + mean(clip(delta_i)) — client 1's oversized delta is clipped,
    client 0's small one passes through."""
    base = {"w": np.zeros((8, 4), np.float32), "b": np.zeros(4, np.float32)}
    small = {"w": rng.normal(size=(8, 4)).astype(np.float32) * 0.01,
             "b": rng.normal(size=4).astype(np.float32) * 0.01}
    big = {"w": rng.normal(size=(8, 4)).astype(np.float32) * 100.0,
           "b": rng.normal(size=4).astype(np.float32) * 100.0}
    clip = 1.0
    params = [
        {k: base[k] + small[k] for k in base},
        {k: base[k] + big[k] for k in base},
    ]
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=20, dp_clip=clip,
        dp_noise_multiplier=0.0,
    ) as server:
        st = _serve_one(server, results)
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=20, dp=True
            )
            for i in range(2)
        ]
        _run_clients(clients, params, [base, base], results)
        st.join(timeout=30)

    def _clip(d):
        n = np.sqrt(sum(float((v.astype(np.float64) ** 2).sum()) for v in d.values()))
        s = min(1.0, clip / n)
        return {k: v * np.float32(s) for k, v in d.items()}

    cs, cb = _clip(small), _clip(big)
    for key in base:
        want = base[key] + 0.5 * (cs[key] + cb[key])
        np.testing.assert_allclose(
            flatten_params(results[0])[key], want, atol=1e-5
        )
        np.testing.assert_array_equal(
            flatten_params(results[0])[key], flatten_params(results[1])[key]
        )
    # The server's reply itself was a delta (never absolute weights).
    agg_delta = results["agg"]
    np.testing.assert_allclose(
        agg_delta["w"], 0.5 * (cs["w"] + cb["w"]), atol=1e-5
    )


def test_dp_noise_is_calibrated(rng):
    """With params == base (zero delta), the aggregate's deviation from
    the base IS the Gaussian noise: per-coordinate std must match
    multiplier * clip / n."""
    base = {"w": np.zeros((200, 100), np.float32)}
    clip, mult = 2.0, 0.5
    results = {}
    with AggregationServer(
        port=0, num_clients=1, timeout=20, dp_clip=clip,
        dp_noise_multiplier=mult,
    ) as server:
        st = _serve_one(server, results)
        client = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=20, dp=True
        )
        _run_clients([client], [dict(base)], [base], results)
        st.join(timeout=30)
    noise = flatten_params(results[0])["w"]
    sigma = mult * clip / 1
    assert abs(float(noise.std()) - sigma) < 0.1 * sigma
    # 4-sigma bound: the 3-sigma version false-failed ~0.3% of runs.
    assert abs(float(noise.mean())) < 4 * sigma / np.sqrt(noise.size)


def test_dp_base_mismatch_fails_the_round(rng):
    """Clients starting from different bases must be refused — a stale
    base would shift the mean by an unbounded gap."""
    b0 = {"w": np.zeros((4, 4), np.float32)}
    b1 = {"w": np.ones((4, 4), np.float32)}
    params = [dict(b0), dict(b1)]
    errs = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=10, dp_clip=1.0
    ) as server:

        def _go(i, base):
            try:
                FederatedClient(
                    "127.0.0.1", server.port, client_id=i, timeout=10,
                    dp=True,
                ).exchange(params[i], round_base=base, max_retries=1)
            except (ConnectionError, wire.WireError) as e:
                errs[i] = e

        ts = [
            threading.Thread(target=_go, args=(i, b), daemon=True)
            for i, b in enumerate([b0, b1])
        ]
        for t in ts:
            t.start()
        with pytest.raises(RuntimeError, match="base mismatch"):
            server.serve_round(deadline=8)
        for t in ts:
            t.join(timeout=15)
    assert set(errs) == {0, 1}


def test_server_enforces_the_clip(rng):
    """A client that skips its clip cannot widen the sensitivity: the
    server re-clips the decoded delta before aggregating (plain mode)."""
    base_crc = wire.flat_crc32({"w": np.zeros(4, np.float32)})
    huge = {"w": np.full(4, 100.0, np.float32)}  # norm 200 >> clip 1
    results = {}
    with AggregationServer(
        port=0, num_clients=1, timeout=10, dp_clip=1.0
    ) as server:
        st = _serve_one(server, results, deadline=10)
        sock = connect_with_retry("127.0.0.1", server.port, timeout=10)
        try:
            sock.settimeout(10)
            adv = framing.recv_frame(sock)
            assert bytes(adv[:4]) == wire.DP_MAGIC
            clip, _, q = struct.unpack("<ddd", adv[4:])
            assert clip == 1.0 and q == 1.0
            framing.send_frame(
                sock, wire.DPID_MAGIC + struct.pack("<q", 0)
            )
            verdict = framing.recv_frame(sock)
            assert bytes(verdict[:4]) == wire.DPCOHORT_MAGIC
            assert verdict[-1] == 1
            framing.send_frame(
                sock,
                wire.encode(
                    huge,
                    meta={
                        "client_id": 0, "n_samples": 1,
                        "dp": True, "dp_base_crc": base_crc,
                    },
                ),
            )
            reply, meta = wire.decode(framing.recv_frame(sock))
        finally:
            sock.close()
        st.join(timeout=20)
    assert meta["dp_reply"] == "delta"
    got = np.asarray(reply["w"], np.float32)
    assert np.sqrt(float((got**2).sum())) == pytest.approx(1.0, rel=1e-5)


@pytest.mark.parametrize("auth", [False, True])
def test_secure_dp_composition(rng, auth):
    """--secure-agg + central DP: masked clipped-delta uploads, noise on
    the recovered sum — the server sees neither weights nor individual
    deltas, yet the noiseless mean matches the plain-DP math to
    fixed-point tolerance."""
    auth_key = b"dp-secure" if auth else None
    base = {"w": rng.normal(size=(6, 3)).astype(np.float32)}
    deltas = [
        {"w": rng.normal(size=(6, 3)).astype(np.float32) * 0.05}
        for _ in range(2)
    ]
    params = [{"w": base["w"] + d["w"]} for d in deltas]
    clip = 10.0  # no clipping bites: the mean must be the exact delta mean
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=20, secure_agg=True,
        dp_clip=clip, dp_noise_multiplier=0.0, auth_key=auth_key,
    ) as server:
        st = _serve_one(server, results)
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=20,
                dp=True, secure_agg=True, num_clients=2, auth_key=auth_key,
            )
            for i in range(2)
        ]
        _run_clients(clients, params, [base, base], results)
        st.join(timeout=30)
    want = base["w"] + 0.5 * (deltas[0]["w"] + deltas[1]["w"])
    np.testing.assert_allclose(
        flatten_params(results[0])["w"], want, atol=1e-5
    )
    np.testing.assert_array_equal(
        flatten_params(results[0])["w"], flatten_params(results[1])["w"]
    )


def test_dp_constructor_and_mode_guards():
    with pytest.raises(ValueError, match="dp_clip"):
        AggregationServer(port=0, num_clients=2, dp_noise_multiplier=1.0)
    with pytest.raises(ValueError, match="uniform mean"):
        AggregationServer(
            port=0, num_clients=2, weighted=True, dp_clip=1.0
        )
    with pytest.raises(ValueError, match="topk"):
        FederatedClient(
            "h", 1, client_id=0, dp=True, compression="topk"
        )
    with pytest.raises(ValueError, match="round_base"):
        FederatedClient("h", 1, client_id=0, dp=True).exchange(
            {"w": np.zeros(2, np.float32)}
        )


def test_plain_client_rejected_by_dp_server(rng):
    """A non-DP client's absolute upload must be refused by a DP server
    (mode mismatch), not silently averaged as a 'delta'."""
    params = {"w": np.ones(4, np.float32)}
    errs = {}
    with AggregationServer(
        port=0, num_clients=1, timeout=6, dp_clip=1.0
    ) as server:

        def _client():
            try:
                FederatedClient(
                    "127.0.0.1", server.port, client_id=0, timeout=6
                ).exchange(params, max_retries=1)
            except (ConnectionError, wire.WireError) as e:
                errs["c"] = e

        ct = threading.Thread(target=_client, daemon=True)
        ct.start()
        # The round itself fails (no valid DP upload ever registered) —
        # asserted on the MAIN thread so a regression can't be swallowed.
        with pytest.raises(RuntimeError, match="clients"):
            server.serve_round(deadline=5)
        ct.join(timeout=15)
    assert "c" in errs


def test_dp_client_fails_fast_against_non_dp_server(rng):
    """--dp against a server without --dp-clip: no advert ever comes; the
    client must raise a non-retryable ModeError instead of burning its
    full retry budget at ~30s per attempt."""
    import time

    with AggregationServer(port=0, num_clients=1, timeout=5) as server:
        client = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=5, dp=True
        )
        t0 = time.monotonic()
        with pytest.raises(wire.ModeError, match="DP advert"):
            client.exchange(
                {"w": np.zeros(2, np.float32)},
                round_base={"w": np.zeros(2, np.float32)},
                max_retries=5,
            )
        # One advert-wait (<= min(timeout, 30) = 5s), not five.
        assert time.monotonic() - t0 < 12.0


class _ScriptedRng:
    """Deterministic stand-in for the server's cohort RNG: .random()
    yields the scripted values in order (normal draws unaffected)."""

    def __init__(self, values, real):
        self._values = list(values)
        self._real = real

    def random(self):
        return self._values.pop(0) if self._values else self._real.random()

    def standard_normal(self, *a, **kw):
        return self._real.standard_normal(*a, **kw)


@pytest.mark.parametrize("auth", [None, b"dp-skip-auth"])
def test_poisson_cohort_mixed_round(rng, auth):
    """VERDICT r4 #4: Poisson cohort sampling on the TCP tier. Client 0
    is sampled, client 1 sits out; the round aggregates client 0's
    clipped delta alone, and BOTH clients receive the identical reply —
    the sitting-out client's base keeps tracking the fleet's. Auth mode
    additionally exercises the authenticated sit-out ack (key knowledge
    required before the server registers a skip connection)."""
    base = {"w": np.zeros((6, 3), np.float32)}
    d0 = {"w": rng.normal(size=(6, 3)).astype(np.float32) * 0.01}
    params = [
        {"w": base["w"] + d0["w"]},
        {"w": base["w"] + np.float32(7.0)},  # never aggregated
    ]
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=20, dp_clip=1.0,
        dp_participation=0.5, min_clients=1, auth_key=auth,
    ) as server:
        # Scripted draw: client 0 in (0.1 < q=0.5), client 1 out (0.9).
        server._dp_rng = _ScriptedRng([0.1, 0.9], np.random.default_rng(0))
        st = _serve_one(server, results)
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=20, dp=True,
                auth_key=auth,
            )
            for i in range(2)
        ]
        _run_clients(clients, params, [base, base], results)
        st.join(timeout=30)
    # Noiseless (multiplier 0): the aggregate is base + clip(d0)/1.
    n = np.sqrt(float((d0["w"].astype(np.float64) ** 2).sum()))
    want = base["w"] + d0["w"] * np.float32(min(1.0, 1.0 / n))
    np.testing.assert_allclose(flatten_params(results[0])["w"], want, atol=1e-5)
    # The sitting-out client received the identical aggregate.
    np.testing.assert_array_equal(
        flatten_params(results[0])["w"], flatten_params(results[1])["w"]
    )
    # Client 1's own (never-uploaded) params did not contaminate the mean.
    assert float(np.abs(flatten_params(results[0])["w"]).max()) < 1.0


def test_poisson_empty_cohort_round_is_clean_noop(rng):
    """VERDICT r4 #4 done-criterion: an empty TCP cohort is a clean
    no-op — serve_round returns None (no release), and every client gets
    a noop reply telling it to keep its round base."""
    base = {"w": np.ones((4, 2), np.float32)}
    params = [
        {"w": base["w"] + np.float32(0.5)},
        {"w": base["w"] - np.float32(0.25)},
    ]
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=20, dp_clip=1.0,
        dp_participation=0.5, min_clients=1,
    ) as server:
        server._dp_rng = _ScriptedRng([0.9, 0.9], np.random.default_rng(0))
        st = _serve_one(server, results)
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=20, dp=True
            )
            for i in range(2)
        ]
        _run_clients(clients, params, [base, base], results)
        st.join(timeout=30)
    assert results["agg"] is None  # nothing aggregated, nothing released
    for i in range(2):
        np.testing.assert_array_equal(
            flatten_params(results[i])["w"], base["w"]
        )


def test_upload_from_non_sampled_client_rejected(rng):
    """A client ignoring its sit-out instruction cannot contribute: the
    server refuses uploads from outside the round's cohort (the
    subsampled accountant's sensitivity assumption holds by force)."""
    base_crc = wire.flat_crc32({"w": np.zeros(2, np.float32)})
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=10, dp_clip=1.0,
        dp_participation=0.5, min_clients=1,
    ) as server:
        server._dp_rng = _ScriptedRng([0.9, 0.1], np.random.default_rng(0))
        st = _serve_one(server, results, deadline=6)
        sock = connect_with_retry("127.0.0.1", server.port, timeout=10)
        try:
            sock.settimeout(10)
            framing.recv_frame(sock)  # mode advert
            framing.send_frame(sock, wire.DPID_MAGIC + struct.pack("<q", 0))
            verdict = framing.recv_frame(sock)
            assert verdict[-1] == 0  # told to sit out
            # Upload anyway (claiming id 0): the server never reads it as
            # a model — the frame's ACK never comes and the connection is
            # dropped at round close, so the rogue upload cannot land.
            with pytest.raises((ConnectionError, OSError)):
                framing.send_frame(
                    sock,
                    wire.encode(
                        {"w": np.zeros(2, np.float32)},
                        meta={
                            "client_id": 0, "n_samples": 1,
                            "dp": True, "dp_base_crc": base_crc,
                        },
                    ),
                )
                framing.recv_frame(sock)
        finally:
            sock.close()
        st.join(timeout=20)


def test_dp_participation_banner_exact():
    """The serve banner under q < 1 reads '(accountant exact)' — the TCP
    tier's Poisson sampler matches the subsampled-Gaussian accountant's
    assumption, so the amplified epsilon is exact."""
    import logging

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        main,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.dp import (
        dp_epsilon,
    )

    # The fedtpu logger does not propagate to root (caplog can't see it);
    # capture with a handler of our own.
    msgs: list[str] = []

    class _Cap(logging.Handler):
        def emit(self, record):
            msgs.append(record.getMessage())

    logger = logging.getLogger("fedtpu")
    h = _Cap()
    logger.addHandler(h)
    try:
        rc = main(
            [
                "serve", "--port", "0", "--num-clients", "2",
                "--dp-clip", "0.5", "--dp-noise-multiplier", "1.0",
                "--dp-participation", "0.2", "--rounds", "1",
                "--timeout", "0.3",
            ]
        )
    finally:
        logger.removeHandler(h)
    assert rc == 0
    banner = [m for m in msgs if "[DP]" in m]
    assert banner, msgs
    assert "Poisson cohort sampling q=0.2 (accountant exact" in banner[0]
    assert "hidden cohort" in banner[0]
    # Amplification actually credited: the banner epsilon must match the
    # subsampled accountant, which is strictly below the q=1 bound.
    eps_q = dp_epsilon(1, 1.0, 1e-5, sampling_rate=0.2)
    eps_full = dp_epsilon(1, 1.0, 1e-5)
    assert eps_q < eps_full
    assert f"({eps_q:.3g}, 1e-05)-DP under zeroed-contribution" in banner[0]


def test_secure_dp_banner_states_honest_clipping():
    """VERDICT r4 weak #3: the secure+DP serve banner must say the
    guarantee is honest-client-only (masked uploads cannot be re-clipped
    server-side)."""
    import logging

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        main,
    )

    msgs: list[str] = []

    class _Cap(logging.Handler):
        def emit(self, record):
            msgs.append(record.getMessage())

    logger = logging.getLogger("fedtpu")
    h = _Cap()
    logger.addHandler(h)
    try:
        rc = main(
            [
                "serve", "--port", "0", "--num-clients", "2",
                "--secure-agg", "--dp-clip", "0.5",
                "--dp-noise-multiplier", "1.0",
                "--rounds", "1", "--timeout", "0.3",
            ]
        )
    finally:
        logger.removeHandler(h)
    assert rc == 0
    banner = [m for m in msgs if "[DP]" in m]
    assert banner, msgs
    assert "HONEST-CLIENT-ONLY" in banner[0]
    assert "cannot be re-clipped server-side" in banner[0]


def test_plain_client_diagnoses_dp_server(rng):
    """A plain client against a --dp-clip server gets a clean ModeError
    naming the fix after one failed probe attempt (the server speaks
    first, so the retry peek can see the DP advert) — not a burned
    retry budget."""
    with AggregationServer(
        port=0, num_clients=2, timeout=10, dp_clip=1.0
    ) as server:

        def _round():
            # The round legitimately fails after the test closes the
            # server (no client ever uploads); swallow the expected
            # RuntimeError so it cannot bleed a
            # PytestUnhandledThreadExceptionWarning into LATER tests
            # (the daemon thread outlives this one's window).
            try:
                server.serve_round(deadline=12)
            except RuntimeError:
                pass

        st = threading.Thread(target=_round, daemon=True)
        st.start()
        plain = FederatedClient(
            "127.0.0.1", server.port, client_id=0, timeout=10
        )
        with pytest.raises(wire.ModeError, match="--dp"):
            plain.exchange({"w": np.zeros(2, np.float32)}, max_retries=5)


def test_stranded_client_resyncs_via_composed_catchup_delta(rng):
    """VERDICT r5 missing #1 closed: a delta-only DP client that missed a
    round's reply (stale base) used to fail every later round's base-crc
    agreement forever. The server now retains the post-noise round deltas
    (already DP outputs — retention is free post-processing) and answers
    the rejoining client with the COMPOSED catch-up, landing it on the
    fleet's current base; its stale upload is excluded from the mean."""
    base = {"w": np.zeros((6, 3), np.float32), "b": np.zeros(3, np.float32)}

    def _step(b, scale):
        return {k: b[k] + rng.normal(size=b[k].shape).astype(np.float32) * scale
                for k in b}

    results = {}
    with AggregationServer(
        port=0, num_clients=2, min_clients=1, timeout=20,
        dp_clip=1e6,  # big clip: deltas pass through un-clipped
        dp_noise_multiplier=0.0,
    ) as server:
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=20, dp=True
            )
            for i in range(2)
        ]
        # Round 1: both clients participate from the shared init.
        st = _serve_one(server, results)
        bases = [base, base]
        params = [_step(base, 0.01), _step(base, 0.02)]
        _run_clients(clients, params, bases, results)
        st.join(timeout=30)
        base1 = {k: np.asarray(v, np.float32)
                 for k, v in flatten_params(results[0]).items()}
        np.testing.assert_array_equal(
            flatten_params(results[0])["w"], flatten_params(results[1])["w"]
        )
        # Round 2: client 0 misses it entirely (crash before upload); the
        # round proceeds on client 1 alone after the deadline.
        st = _serve_one(server, results, deadline=4)
        out1 = clients[1].exchange(
            _step(base1, 0.015), round_base=base1
        )
        st.join(timeout=30)
        base2 = {k: np.asarray(v, np.float32)
                 for k, v in flatten_params(out1).items()}
        assert not np.array_equal(base2["w"], base1["w"])
        # Round 3: client 0 rejoins STALE (its base is still base1).
        # Its upload is excluded; its reply is the catch-up sequence
        # (round 2's delta, then round 3's — replayed in order), so both
        # clients land on the BIT-IDENTICAL new base.
        st = _serve_one(server, results)
        params3 = [_step(base1, 0.01), _step(base2, 0.02)]
        _run_clients(clients, params3, [base1, base2], results)
        st.join(timeout=30)
        r0 = flatten_params(results[0])
        r1 = flatten_params(results[1])
        for key in r0:
            # Exact, not allclose: sequential replay must reproduce the
            # fleet's fp32 additions bit for bit, or round 4's crc
            # agreement below could never hold.
            np.testing.assert_array_equal(r0[key], r1[key])
        # The round-3 mean is client 1's delta alone (the stale upload
        # was excluded): new base = base2 + delta3(client 1).
        d3 = {
            k: np.asarray(flatten_params(params3[1])[k], np.float32)
            - base2[k]
            for k in base2
        }
        for key in r1:
            np.testing.assert_allclose(
                r1[key], base2[key] + d3[key], atol=1e-4
            )
        # Round 4: BOTH clients now participate from the resynced base —
        # the crc agreement must hold (a composed, ulps-off resync would
        # fail this round for the whole fleet, forever).
        base3 = {k: np.asarray(v, np.float32) for k, v in r0.items()}
        st = _serve_one(server, results)
        params4 = [_step(base3, 0.01), _step(base3, 0.02)]
        _run_clients(clients, params4, [base3, base3], results)
        st.join(timeout=30)
        assert results["agg"] is not None  # round succeeded, 2 contributors
        np.testing.assert_array_equal(
            flatten_params(results[0])["w"], flatten_params(results[1])["w"]
        )


def test_stale_base_outside_resync_window_still_fails(rng):
    """A client staler than the retained-delta window (here: a base the
    server never released) must fail the round exactly as before — the
    resync path never guesses."""
    base = {"w": np.zeros((4, 2), np.float32)}
    alien = {"w": np.ones((4, 2), np.float32) * 7}
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=20, dp_clip=1.0,
        dp_noise_multiplier=0.0,
    ) as server:
        st = _serve_one(server, results, deadline=6)
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=5, dp=True,
            )
            for i in range(2)
        ]
        bases = [base, alien]
        params = [
            {k: base[k] + 0.01 for k in base},
            {k: alien[k] + 0.01 for k in alien},
        ]
        errs = {}

        def _go(i):
            try:
                clients[i].exchange(
                    params[i], round_base=bases[i], max_retries=1
                )
            except Exception as e:
                errs[i] = e

        ts = [threading.Thread(target=_go, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        st.join(timeout=30)
    assert results.get("agg") is None
    assert errs  # both clients see the failed round


def test_zero_delta_rounds_do_not_poison_resync_history(rng):
    """A noiseless round where every client uploads its base exactly (zero
    mean delta) leaves the fleet's base crc unchanged; retaining that
    round in the resync history would make every CURRENT client's next
    declaration collide with it and misclassify the whole fleet as stale,
    failing all later rounds. Zero-delta rounds are not retained."""
    base = {"w": np.ones((4, 2), np.float32)}
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=20, dp_clip=1.0,
        dp_noise_multiplier=0.0,
    ) as server:
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=20, dp=True
            )
            for i in range(2)
        ]
        for _ in range(2):  # round 2 used to fail on the collided crc
            st = _serve_one(server, results)
            _run_clients(clients, [base, base], [base, base], results)
            st.join(timeout=30)
            for i in range(2):
                np.testing.assert_array_equal(
                    flatten_params(results[i])["w"], base["w"]
                )
        assert server._dp_history == []  # nothing retained, nothing stale


def test_fleetwide_missed_reply_is_consensus_not_stale(rng):
    """If EVERY client misses a round's reply (fleet-wide network blip),
    the next round's uploads all declare the same RETAINED base crc. That
    consensus is the fleet base — the round must proceed from it exactly
    as the pre-resync server did, not misclassify everyone as stale and
    brick the campaign."""
    base = {"w": np.zeros((4, 2), np.float32)}

    def _step(b, scale):
        return {k: b[k] + rng.normal(size=b[k].shape).astype(np.float32) * scale
                for k in b}

    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=20, dp_clip=1e6,
        dp_noise_multiplier=0.0,
    ) as server:
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=20, dp=True
            )
            for i in range(2)
        ]
        # Round 1 completes server-side; pretend NO client adopted the
        # reply (we simply discard it and keep training from base).
        st = _serve_one(server, results)
        _run_clients(clients, [_step(base, 0.01), _step(base, 0.02)], [base, base], results)
        st.join(timeout=30)
        assert len(server._dp_history) == 1
        # Round 2: both clients still at the ORIGINAL base. Must succeed.
        st = _serve_one(server, results)
        params2 = [_step(base, 0.03), _step(base, 0.04)]
        _run_clients(clients, params2, [base, base], results)
        st.join(timeout=30)
    r0 = flatten_params(results[0])
    r1 = flatten_params(results[1])
    np.testing.assert_array_equal(r0["w"], r1["w"])
    # The round-2 aggregate is base + mean(round-2 deltas) — a normal
    # round from the consensus base, no catch-up applied.
    d = 0.5 * sum(
        np.asarray(flatten_params(p)["w"], np.float32) - base["w"]
        for p in params2
    )
    np.testing.assert_allclose(r0["w"], base["w"] + d, atol=1e-5)


def test_stale_client_heals_even_at_default_full_quorum(rng):
    """With the DEFAULT quorum (min_clients == num_clients), excluding a
    stale upload always drops the round below quorum — the round fails,
    but the stale client must STILL receive its catch-up (of retained
    rounds) so the RETRIED round succeeds from a common base. Without
    this, the default-config fleet would wedge forever."""
    base0 = {"w": np.zeros((4, 2), np.float32)}

    def _step(b, scale):
        return {k: b[k] + rng.normal(size=b[k].shape).astype(np.float32) * scale
                for k in b}

    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=20, dp_clip=1e6,
        dp_noise_multiplier=0.0,  # min_clients defaults to num_clients=2
    ) as server:
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=20, dp=True
            )
            for i in range(2)
        ]
        # Round 1 completes; client 0 DISCARDS the reply (stays at base0).
        st = _serve_one(server, results)
        _run_clients(
            clients, [_step(base0, 0.01), _step(base0, 0.02)],
            [base0, base0], results,
        )
        st.join(timeout=30)
        base1 = {k: np.asarray(v, np.float32)
                 for k, v in flatten_params(results[1]).items()}
        # Round 2: client 0 is stale. The round FAILS (1 < quorum 2) but
        # client 0's exchange still returns — the catch-up heals it.
        round_err = {}

        def _round2():
            try:
                server.serve_round(deadline=20)
            except RuntimeError as e:
                round_err["e"] = e

        st2 = threading.Thread(target=_round2)
        st2.start()
        healed = {}
        c1_err = {}

        def _c0():
            healed["base"] = clients[0].exchange(
                _step(base0, 0.01), round_base=base0, max_retries=1
            )

        def _c1():
            try:
                # One attempt only: the failed round closes this
                # connection; round 3 below is driven explicitly.
                clients[1].exchange(
                    _step(base1, 0.02), round_base=base1, max_retries=1
                )
            except ConnectionError as e:
                c1_err["e"] = e

        t0, t1 = threading.Thread(target=_c0), threading.Thread(target=_c1)
        t0.start(), t1.start()
        t0.join(timeout=30), t1.join(timeout=30)
        st2.join(timeout=30)
        assert "e" in round_err and "quorum" in str(round_err["e"])
        assert "e" in c1_err  # the current client's round genuinely failed
        # Client 0 is now bit-exactly on the fleet base.
        for k in base1:
            np.testing.assert_array_equal(
                flatten_params(healed["base"])[k], base1[k]
            )
        # Round 3: both clients from the common base — succeeds at the
        # full default quorum.
        st3 = _serve_one(server, results)
        _run_clients(
            clients, [_step(base1, 0.01), _step(base1, 0.02)],
            [base1, base1], results,
        )
        st3.join(timeout=30)
        assert results["agg"] is not None
        np.testing.assert_array_equal(
            flatten_params(results[0])["w"], flatten_params(results[1])["w"]
        )


def test_stale_client_sitting_out_a_sampled_round_stays_resyncable(rng):
    """Poisson-sampling hole closed: a STALE client (missed reply) that
    then sits a sampled round out must NOT apply that round's delta to
    its stale base (a compound base the retained history never saw —
    permanently unresyncable). It keeps its base and resyncs on its next
    contributing round."""

    class _FixedDraws:
        """Deterministic cohort draws + zero noise for the test server."""

        def __init__(self, seq):
            self.seq = list(seq)

        def random(self):
            return self.seq.pop(0)

        def standard_normal(self, shape, dtype=None):
            return np.zeros(shape, dtype or np.float64)

    base0 = {"w": np.zeros((4, 2), np.float32)}

    def _step(b, scale):
        return {k: b[k] + rng.normal(size=b[k].shape).astype(np.float32) * scale
                for k in b}

    results = {}
    with AggregationServer(
        port=0, num_clients=2, min_clients=1, timeout=20, dp_clip=1e6,
        dp_noise_multiplier=0.0, dp_participation=0.5,
    ) as server:
        # Draw plan (one draw per client per round): round 1 both in,
        # round 2 only client 1, round 3 both in.
        server._dp_rng = _FixedDraws([0.1, 0.1, 0.9, 0.1, 0.1, 0.1])
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=20, dp=True
            )
            for i in range(2)
        ]
        # Round 1: both contribute; client 0 DISCARDS the reply (stale).
        st = _serve_one(server, results)
        _run_clients(
            clients, [_step(base0, 0.01), _step(base0, 0.02)],
            [base0, base0], results,
        )
        st.join(timeout=30)
        base1 = {k: np.asarray(v, np.float32)
                 for k, v in flatten_params(results[1]).items()}
        # Round 2: client 0 sits out (not sampled) but still connects;
        # the round's delta targets base1, which client 0 does not hold —
        # it must KEEP base0, not compound.
        st = _serve_one(server, results)
        _run_clients(
            clients, [_step(base0, 0.01), _step(base1, 0.02)],
            [base0, base1], results,
        )
        st.join(timeout=30)
        for k in base0:
            np.testing.assert_array_equal(
                flatten_params(results[0])[k], base0[k]
            )
        base2 = {k: np.asarray(v, np.float32)
                 for k, v in flatten_params(results[1]).items()}
        assert not np.array_equal(base2["w"], base1["w"])
        # Round 3: client 0 contributes from base0 — still inside the
        # retained window, so it resyncs onto the exact fleet base.
        st = _serve_one(server, results)
        _run_clients(
            clients, [_step(base0, 0.01), _step(base2, 0.02)],
            [base0, base2], results,
        )
        st.join(timeout=30)
        np.testing.assert_array_equal(
            flatten_params(results[0])["w"], flatten_params(results[1])["w"]
        )


def test_dp_resync_history_survives_server_restart(rng, tmp_path):
    """ROADMAP's last resync residual, closed: the retained post-noise
    deltas persist to disk (``dp_history_path``), so a server RESTART
    between rounds no longer re-strands stale clients — the rejoining
    client heals from the RELOADED history bit-exactly (npz is lossless
    fp32; ulps-off healing would fail every later round's crc
    agreement)."""
    hist = str(tmp_path / "dp_history.npz")
    base = {"w": np.zeros((6, 3), np.float32), "b": np.zeros(3, np.float32)}

    def _step(b, scale):
        return {k: b[k] + rng.normal(size=b[k].shape).astype(np.float32) * scale
                for k in b}

    def _server():
        return AggregationServer(
            port=0, num_clients=2, min_clients=1, timeout=20,
            dp_clip=1e6, dp_noise_multiplier=0.0, dp_history_path=hist,
        )

    results = {}
    with _server() as server:
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=20, dp=True
            )
            for i in range(2)
        ]
        # Round 1: both clients from the shared init.
        st = _serve_one(server, results)
        _run_clients(
            clients, [_step(base, 0.01), _step(base, 0.02)],
            [base, base], results,
        )
        st.join(timeout=30)
        base1 = {k: np.asarray(v, np.float32)
                 for k, v in flatten_params(results[0]).items()}
        # Round 2: client 0 misses it entirely; client 1 advances alone.
        st = _serve_one(server, results, deadline=4)
        out1 = clients[1].exchange(_step(base1, 0.015), round_base=base1)
        st.join(timeout=30)
        base2 = {k: np.asarray(v, np.float32)
                 for k, v in flatten_params(out1).items()}
        assert not np.array_equal(base2["w"], base1["w"])

    # ---- RESTART: a fresh process-equivalent server on the same path.
    with _server() as server:
        assert len(server._dp_history) == 2  # both rounds reloaded
        clients = [
            FederatedClient(
                "127.0.0.1", server.port, client_id=i, timeout=20, dp=True
            )
            for i in range(2)
        ]
        # Round 3: client 0 rejoins STALE at base1. Pre-persistence, a
        # restarted server had no history and failed this round with a
        # base-crc mismatch; now the reloaded window heals it.
        st = _serve_one(server, results)
        _run_clients(
            clients, [_step(base1, 0.01), _step(base2, 0.02)],
            [base1, base2], results,
        )
        st.join(timeout=30)
        r0 = flatten_params(results[0])
        r1 = flatten_params(results[1])
        for key in r0:
            # Exact: the replayed catch-up must land on the fleet's fp32
            # base bit for bit.
            np.testing.assert_array_equal(r0[key], r1[key])
        # Round 4: both clients from the common healed base — the crc
        # agreement holds, proving the heal was bit-exact.
        base3 = {k: np.asarray(v, np.float32) for k, v in r0.items()}
        st = _serve_one(server, results)
        _run_clients(
            clients, [_step(base3, 0.01), _step(base3, 0.02)],
            [base3, base3], results,
        )
        st.join(timeout=30)
        assert results["agg"] is not None
        np.testing.assert_array_equal(
            flatten_params(results[0])["w"], flatten_params(results[1])["w"]
        )


def test_dp_history_corrupt_file_starts_empty(rng, tmp_path):
    """A corrupt persisted window must not kill the server: it logs,
    starts empty, and stale clients outside the (now empty) window fail
    their rounds exactly as a fresh deployment would. Two corruption
    shapes: garbage bytes (ValueError path) and a TRUNCATED npz that
    kept the zip magic (zipfile.BadZipFile — a crash mid-write)."""
    import io

    garbage = tmp_path / "garbage.npz"
    garbage.write_bytes(b"not an npz at all")
    buf = io.BytesIO()
    np.savez(buf, a=np.zeros(64, np.float32))
    truncated = tmp_path / "truncated.npz"
    truncated.write_bytes(buf.getvalue()[: len(buf.getvalue()) // 2])
    for hist in (garbage, truncated):
        with AggregationServer(
            port=0, num_clients=2, min_clients=1, timeout=5,
            dp_clip=1.0, dp_noise_multiplier=0.0,
            dp_history_path=str(hist),
        ) as server:
            assert server._dp_history == []
