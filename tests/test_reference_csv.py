"""Reference-CSV text-template parity, pinned in the suite.

VERDICT round 5 verified by hand that ``load_flow_csv`` +
``texts_from_dataframe`` reproduce the reference's ``features_to_text``
(client1.py:68-81) byte-for-byte on the real bundled ``CICIDS2017.csv``
rows — but no test pinned it. This fixture embeds ten rows in the real
file's shape (the full 79-column header with its space-prefix quirks,
``Infinity``/empty cells exercising the ±inf->NaN->column-mean
imputation, reference client1.py:86-88) and asserts the rendered
template output against literal expected strings, self-contained — no
runtime dependency on the reference mount."""

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.cicids import (
    load_flow_csv,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.textualize import (
    FLOW_TEXT_COLUMNS,
    flow_to_text,
    labels_from_dataframe,
    texts_from_dataframe,
)

#: The real CICIDS2017 export's 79-column header line, verbatim quirks
#: included (leading spaces on most names, the duplicate-derived
#: ``Fwd Header Length.1``).
_HEADER = (
    " Destination Port, Flow Duration, Total Fwd Packets, Total Backward"
    " Packets,Total Length of Fwd Packets, Total Length of Bwd Packets,"
    " Fwd Packet Length Max, Fwd Packet Length Min, Fwd Packet Length Mean,"
    " Fwd Packet Length Std,Bwd Packet Length Max, Bwd Packet Length Min,"
    " Bwd Packet Length Mean, Bwd Packet Length Std,Flow Bytes/s, Flow"
    " Packets/s, Flow IAT Mean, Flow IAT Std, Flow IAT Max, Flow IAT Min,"
    "Fwd IAT Total, Fwd IAT Mean, Fwd IAT Std, Fwd IAT Max, Fwd IAT Min,"
    "Bwd IAT Total, Bwd IAT Mean, Bwd IAT Std, Bwd IAT Max, Bwd IAT Min,"
    "Fwd PSH Flags, Bwd PSH Flags, Fwd URG Flags, Bwd URG Flags, Fwd"
    " Header Length, Bwd Header Length,Fwd Packets/s, Bwd Packets/s, Min"
    " Packet Length, Max Packet Length, Packet Length Mean, Packet Length"
    " Std, Packet Length Variance,FIN Flag Count, SYN Flag Count, RST"
    " Flag Count, PSH Flag Count, ACK Flag Count, URG Flag Count, CWE"
    " Flag Count, ECE Flag Count, Down/Up Ratio, Average Packet Size, Avg"
    " Fwd Segment Size, Avg Bwd Segment Size, Fwd Header Length.1,Fwd Avg"
    " Bytes/Bulk, Fwd Avg Packets/Bulk, Fwd Avg Bulk Rate, Bwd Avg"
    " Bytes/Bulk, Bwd Avg Packets/Bulk,Bwd Avg Bulk Rate,Subflow Fwd"
    " Packets, Subflow Fwd Bytes, Subflow Bwd Packets, Subflow Bwd Bytes,"
    "Init_Win_bytes_forward, Init_Win_bytes_backward, act_data_pkt_fwd,"
    " min_seg_size_forward,Active Mean, Active Std, Active Max, Active"
    " Min,Idle Mean, Idle Std, Idle Max, Idle Min, Label"
)

#: Ten rows' template-column values (plus Label), real-file value shapes:
#: integer counts, 4-decimal rates, ``Infinity`` (row 6) and an empty
#: cell (row 7) for the imputation path.
_ROWS = [
    (54865, 3, 2, 0, 12, 0, 6, 6, "4000000.0", "666666.6667", "BENIGN"),
    (55054, 109, 1, 1, 6, 6, 6, 6, "110091.7431", "18348.62385", "BENIGN"),
    (55055, 52, 1, 1, 6, 6, 6, 6, "230769.2308", "38461.53846", "BENIGN"),
    (46236, 34, 1, 1, 6, 6, 6, 6, "352941.1765", "58823.52941", "BENIGN"),
    (54863, 3, 2, 0, 12, 0, 6, 6, "4000000.0", "666666.6667", "BENIGN"),
    (80, 10265, 6, 4, 352, 196, 176, 0, "Infinity", "974.1841208", "DDoS"),
    (80, 1022, 3, 4, 26, 11607, 20, 0, "11382.58317", "", "DDoS"),
    (443, 117573, 46, 62, 1988, 127536, 580, 0, "1101.476326", "918.5782628", "BENIGN"),
    (53, 128, 2, 2, 70, 342, 35, 35, "3218750.0", "31250.0", "BENIGN"),
    (8080, 5, 2, 0, 0, 0, 0, 0, "0.0", "400000.0", "BENIGN"),
]

#: Expected rendered sentences, pinned as literals (NOT recomputed from
#: the template — that would be circular). Rows 6/7 carry the imputed
#: column means: mean of the nine finite Flow Bytes/s values
#: (11925036.209896 / 9 = 1325004.0233217778) and of the nine present
#: Flow Packets/s values (1882119.26753236 / 9 = 209123.30972262222).
_EXPECTED = [
    "Destination port is 54865. Flow duration is 3 microseconds. Total forward packets are 2. Total backward packets are 0. Total length of forward packets is 12 bytes. Total length of backward packets is 0 bytes. Maximum forward packet length is 6. Minimum forward packet length is 6. Flow bytes per second is 4000000.0. Flow packets per second is 666666.6667.",
    "Destination port is 55054. Flow duration is 109 microseconds. Total forward packets are 1. Total backward packets are 1. Total length of forward packets is 6 bytes. Total length of backward packets is 6 bytes. Maximum forward packet length is 6. Minimum forward packet length is 6. Flow bytes per second is 110091.7431. Flow packets per second is 18348.62385.",
    "Destination port is 55055. Flow duration is 52 microseconds. Total forward packets are 1. Total backward packets are 1. Total length of forward packets is 6 bytes. Total length of backward packets is 6 bytes. Maximum forward packet length is 6. Minimum forward packet length is 6. Flow bytes per second is 230769.2308. Flow packets per second is 38461.53846.",
    "Destination port is 46236. Flow duration is 34 microseconds. Total forward packets are 1. Total backward packets are 1. Total length of forward packets is 6 bytes. Total length of backward packets is 6 bytes. Maximum forward packet length is 6. Minimum forward packet length is 6. Flow bytes per second is 352941.1765. Flow packets per second is 58823.52941.",
    "Destination port is 54863. Flow duration is 3 microseconds. Total forward packets are 2. Total backward packets are 0. Total length of forward packets is 12 bytes. Total length of backward packets is 0 bytes. Maximum forward packet length is 6. Minimum forward packet length is 6. Flow bytes per second is 4000000.0. Flow packets per second is 666666.6667.",
    "Destination port is 80. Flow duration is 10265 microseconds. Total forward packets are 6. Total backward packets are 4. Total length of forward packets is 352 bytes. Total length of backward packets is 196 bytes. Maximum forward packet length is 176. Minimum forward packet length is 0. Flow bytes per second is 1325004.0233217778. Flow packets per second is 974.1841208.",
    "Destination port is 80. Flow duration is 1022 microseconds. Total forward packets are 3. Total backward packets are 4. Total length of forward packets is 26 bytes. Total length of backward packets is 11607 bytes. Maximum forward packet length is 20. Minimum forward packet length is 0. Flow bytes per second is 11382.58317. Flow packets per second is 209123.30972262222.",
    "Destination port is 443. Flow duration is 117573 microseconds. Total forward packets are 46. Total backward packets are 62. Total length of forward packets is 1988 bytes. Total length of backward packets is 127536 bytes. Maximum forward packet length is 580. Minimum forward packet length is 0. Flow bytes per second is 1101.476326. Flow packets per second is 918.5782628.",
    "Destination port is 53. Flow duration is 128 microseconds. Total forward packets are 2. Total backward packets are 2. Total length of forward packets is 70 bytes. Total length of backward packets is 342 bytes. Maximum forward packet length is 35. Minimum forward packet length is 35. Flow bytes per second is 3218750.0. Flow packets per second is 31250.0.",
    "Destination port is 8080. Flow duration is 5 microseconds. Total forward packets are 2. Total backward packets are 0. Total length of forward packets is 0 bytes. Total length of backward packets is 0 bytes. Maximum forward packet length is 0. Minimum forward packet length is 0. Flow bytes per second is 0.0. Flow packets per second is 400000.0.",
]


def _fixture_csv_path(tmp_path):
    cols = [c.strip() for c in _HEADER.split(",")]
    tmpl = list(FLOW_TEXT_COLUMNS)
    lines = [_HEADER]
    for row in _ROWS:
        vals = dict(zip(tmpl + ["Label"], row))
        lines.append(",".join(str(vals.get(c, 0)) for c in cols))
    path = tmp_path / "cicids_fixture.csv"
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def test_features_to_text_byte_parity_on_reference_shaped_rows(tmp_path):
    """Load -> impute -> render must reproduce the pinned byte-exact
    sentences (the reference's features_to_text semantics, including the
    imputed means flowing into the rendered text), and the per-row
    renderer (the serving features path) must agree with the vectorized
    one."""
    df = load_flow_csv(_fixture_csv_path(tmp_path))
    assert len(df.columns) == 79  # whole real header survived the strip
    texts = texts_from_dataframe(df)
    assert texts == _EXPECTED
    # Imputation really fired: no non-finite values remain in the
    # rendered numeric columns.
    for col in FLOW_TEXT_COLUMNS:
        assert np.isfinite(df[col].to_numpy(np.float64)).all(), col
    # flow_to_text (per-row, the serving/feature-request path) is
    # byte-identical to the vectorized renderer.
    for row, want in zip(df.to_dict("records"), _EXPECTED):
        assert flow_to_text(row) == want
    # Reference label map: 'DDoS' -> 1 else 0 (client1.py:91).
    assert labels_from_dataframe(df).tolist() == [0] * 5 + [1, 1] + [0] * 3


@pytest.mark.slow
def test_reference_shaped_csv_trains_on_degenerate_single_class(tmp_path):
    """The reference's bundled stub is all-BENIGN; the pipeline must
    survive that degenerate single-class case end to end: load ->
    render -> tokenize -> a train step + eval with finite outputs."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        default_tokenizer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
        Trainer,
    )

    df = load_flow_csv(_fixture_csv_path(tmp_path))
    benign = df[df["Label"] == "BENIGN"]  # the stub's shape: one class
    texts = texts_from_dataframe(benign)
    labels = labels_from_dataframe(benign)
    assert (labels == 0).all()
    tok = default_tokenizer()
    model_cfg = ModelConfig.tiny(vocab_size=len(tok.vocab))
    enc = tok.batch_encode(texts, max_len=model_cfg.max_len)
    split = TokenizedSplit(
        enc["input_ids"], enc["attention_mask"], labels.astype(np.int32)
    )
    trainer = Trainer(
        model_cfg, TrainConfig(), pad_id=tok.pad_id, drop_remainder=False
    )
    state = trainer.init_state(seed=0)
    state, losses = trainer.fit(state, split, batch_size=4, epochs=1)
    assert losses and np.isfinite(losses[0])
    metrics = trainer.evaluate(state.params, split, batch_size=4)
    assert np.isfinite(metrics["Loss"])
    assert len(metrics["probs"]) == len(benign)
