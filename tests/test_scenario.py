"""faults/scenario.py: the persona x partition matrix over the live wire.

Fast-lane cells (one short live campaign per persona, tiny payloads,
tight deadlines) pinning the PR 6 robustness contract: every
quorum-satisfiable round succeeds over survivors, the aggregate is
crc-pinned BIT-EXACT with the clean barrier mean over the same survivor
set, and the obs timeline attributes drops/straggler-wait correctly.
"""

import json

import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.faults.scenario import (
    CellSpec,
    ScenarioConfig,
    build_matrix,
    comparison_grid,
    contract_violations,
    run_cell,
    write_jsonl,
)


def _cfg(**kw):
    kw.setdefault("num_clients", 3)
    kw.setdefault("rounds", 1)
    kw.setdefault("payload_kb", 24)
    kw.setdefault("deadline_s", 6.0)
    kw.setdefault("partitions", ("iid",))
    return ScenarioConfig(**kw)


def _one_persona_cell(persona, partition="iid", **cell_kw):
    return CellSpec(
        name=f"{persona}|{partition}",
        personas=(persona, "honest", "honest"),
        partition=partition,
        **cell_kw,
    )


def _assert_contract(res, expect_contributors):
    assert [r.ok for r in res.rounds] == [True] * len(res.rounds)
    for r in res.rounds:
        assert r.bitexact is True, (r, res.notes)
    assert res.rounds[-1].contributors == expect_contributors


def test_lazy_round_bitexact_survivor_mean(tmp_path):
    cfg = _cfg(personas=("lazy",))
    res = run_cell(_one_persona_cell("lazy"), cfg, str(tmp_path))
    _assert_contract(res, [0, 1, 2])
    assert res.rounds[0].dropped == []


def test_slow_round_is_straggler_with_measured_wait(tmp_path):
    """The throttled client still contributes; the obs timeline charges
    the OTHER clients a straggler wait for it."""
    cfg = _cfg(personas=("slow",), payload_kb=48)
    res = run_cell(_one_persona_cell("slow"), cfg, str(tmp_path))
    _assert_contract(res, [0, 1, 2])
    assert res.rounds[0].straggler_wait_s > 0.3


def test_intermittent_reset_retry_converges(tmp_path):
    """Dies mid-upload on the first dial, retries, contributes — the
    aggregate stays bit-exact with the clean mean over all three."""
    cfg = _cfg(personas=("intermittent",), deadline_s=8.0)
    res = run_cell(_one_persona_cell("intermittent"), cfg, str(tmp_path))
    _assert_contract(res, [0, 1, 2])


def test_stale_round_drop_attribution(tmp_path):
    """The stale persona sits round 2 out: the obs timeline must
    attribute the drop to client 0 exactly, and the round must close
    bit-exactly over the survivors."""
    cfg = _cfg(personas=("stale",), rounds=2, deadline_s=4.0)
    res = run_cell(_one_persona_cell("stale"), cfg, str(tmp_path))
    assert [r.ok for r in res.rounds] == [True, True]
    assert res.rounds[0].contributors == [0, 1, 2]
    assert res.rounds[1].contributors == [1, 2]
    assert res.rounds[1].dropped == [0]
    assert res.rounds[1].bitexact is True  # survivor mean, crc-pinned


def test_flaky_net_round_converges(tmp_path):
    cfg = _cfg(personas=("flaky-net",), deadline_s=8.0)
    res = run_cell(_one_persona_cell("flaky-net"), cfg, str(tmp_path))
    _assert_contract(res, [0, 1, 2])


def test_auth_cell_and_streamed_round(tmp_path):
    """Two rounds under HMAC auth with the stream advert on: round 2's
    uploads are chunk-streamed (stream_uploads > 0) and both rounds stay
    crc-exact — the acceptance matrix's auth + streamed cells."""
    cfg = _cfg(personas=("lazy",), rounds=2, deadline_s=6.0)
    res = run_cell(
        _one_persona_cell("lazy", auth=True), cfg, str(tmp_path)
    )
    _assert_contract(res, [0, 1, 2])
    assert res.stream_uploads >= 2  # the honest clients streamed round 2


def test_dirichlet_cell_weighted_mean_differs_from_iid(tmp_path):
    """Partition genuinely matters: the dirichlet cell's shard sizes
    weight the mean differently from the IID cell's equal shards."""
    cfg = _cfg(
        personas=("lazy",), partitions=("iid", "dirichlet"),
        dirichlet_alpha=0.1,
    )
    iid = run_cell(_one_persona_cell("lazy", "iid"), cfg, str(tmp_path))
    dir_ = run_cell(
        _one_persona_cell("lazy", "dirichlet"), cfg, str(tmp_path)
    )
    _assert_contract(iid, [0, 1, 2])
    _assert_contract(dir_, [0, 1, 2])
    sizes_iid = [c["rows"] for c in iid.manifest["clients"]]
    sizes_dir = [c["rows"] for c in dir_.manifest["clients"]]
    assert len(set(sizes_iid)) == 1  # IID: equal disjoint shards
    assert len(set(sizes_dir)) > 1  # dirichlet: skewed shard sizes
    assert iid.rounds[0].live_crc != dir_.rounds[0].live_crc


def test_matrix_build_and_reports(tmp_path):
    """build_matrix covers persona x partition + the auth cell; the grid
    and JSONL emitters round-trip a result set without running rounds."""
    cfg = _cfg(
        personas=("lazy", "slow"), partitions=("iid", "dirichlet"),
    )
    cells = build_matrix(cfg)
    assert len(cells) == 5  # 2x2 + auth
    assert cells[-1].auth
    assert {c.partition for c in cells} == {"iid", "dirichlet"}
    with pytest.raises(ValueError, match="unknown partition"):
        build_matrix(_cfg(personas=("lazy",), partitions=("weird",)))
    # Emitters over a real (tiny) result.
    res = run_cell(
        _one_persona_cell("lazy"), _cfg(personas=("lazy",)), str(tmp_path)
    )
    grid = comparison_grid([res], _cfg(personas=("lazy",)))
    assert "lazy" in grid and "crc" in grid
    path = write_jsonl([res], str(tmp_path / "scenario.jsonl"))
    rec = json.loads(open(path).read().splitlines()[0])
    assert rec["cell"] == "lazy|iid"
    assert rec["rounds"][0]["bitexact"] is True
    assert rec["manifest"]["clients"][0]["rows"] > 0
    assert contract_violations([res]) == []


def test_contract_violation_reported_for_failed_round(tmp_path):
    """A genuinely quorum-impossible cell (every client stale in the
    same round) must surface as a contract violation, not silently
    pass."""
    cfg = _cfg(personas=("stale",), rounds=2, deadline_s=2.0)
    spec = CellSpec(
        name="allstale|iid",
        personas=("stale", "stale", "stale"),
        partition="iid",
    )
    res = run_cell(spec, cfg, str(tmp_path))
    # Round 2 (index 1) has zero uploads; quorum=1 cannot be met.
    assert res.rounds[1].ok is False
    v = contract_violations([res])
    assert any("round 1" in x for x in v)


def test_dead_relay_cell_rehomes_and_stays_bitexact(tmp_path):
    """The dead-relay cell (PR 14): a depth-2 tree with a seeded
    mid-round relay kill — the victim subtree's clients re-home to the
    surviving relay, the root completes a DEGRADED round, the aggregate
    is crc-pinned bit-exact vs aggregate_tree over the recorded actual
    assignment, and the re-home is visible on the obs timeline as a
    second wire-upload span (rehome_failed=1)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.faults.scenario import (
        run_dead_relay_cell,
    )

    cfg = _cfg(num_clients=4, deadline_s=4.0, dead_relay_cell=True)
    res = run_dead_relay_cell(cfg, str(tmp_path))
    assert res.spec.name == "dead-relay|iid"
    assert [r.ok for r in res.rounds] == [True]
    assert res.rounds[0].bitexact is True, res.notes
    assert res.rounds[0].contributors == [0, 1, 2, 3]
    notes = "\n".join(res.notes)
    assert "rehomes" in notes
    assert "rehome wire-upload spans: 2" in notes, res.notes
    # The matrix runner appends it behind the flag and the grid renders
    # its row; contract_violations stays empty for the green cell.
    assert contract_violations([res]) == []
    grid = comparison_grid([res], cfg)
    assert "dead-relay" in grid and "mid-round kill" in grid
