"""Checkpoint/resume: full-state round trip, sharded restore, warm start."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.checkpoint import (
    Checkpointer,
    maybe_warm_start,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
    Trainer,
)


def _tiny_trainer():
    return Trainer(ModelConfig.tiny(), TrainConfig(seed=3))


def _tiny_batch(cfg, rng, bs=8):
    return {
        "input_ids": rng.integers(0, cfg.vocab_size, (bs, cfg.max_len)).astype(np.int32),
        "attention_mask": np.ones((bs, cfg.max_len), np.int32),
        "labels": rng.integers(0, 2, bs).astype(np.int32),
    }


def _assert_tree_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        a,
        b,
    )


def test_single_client_roundtrip(tmp_path, rng):
    trainer = _tiny_trainer()
    state = trainer.init_state(seed=0)
    batch = _tiny_batch(trainer.model_cfg, rng)
    for _ in range(3):
        state, _ = trainer.train_step(state, batch)

    with Checkpointer(str(tmp_path / "ckpt")) as ckpt:
        ckpt.save(int(state.step), state, meta={"round": 1})
        ckpt.wait()
        template = trainer.init_state(seed=0)
        restored = ckpt.restore(template)
        assert ckpt.restore_meta() == {"round": 1}

    # Full fidelity: params, opt_state (Adam moments), step, and the PRNG key.
    _assert_tree_equal(restored.params, state.params)
    _assert_tree_equal(restored.opt_state, state.opt_state)
    assert int(restored.step) == int(state.step) == 3
    np.testing.assert_array_equal(
        jax.random.key_data(restored.rng), jax.random.key_data(state.rng)
    )

    # Resumed training continues identically to uninterrupted training.
    cont_a, loss_a = trainer.train_step(state, batch)
    cont_b, loss_b = trainer.train_step(restored, batch)
    assert float(loss_a) == pytest.approx(float(loss_b), rel=1e-6)


def test_federated_sharded_roundtrip(tmp_path, eight_devices):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.federated import (
        FederatedTrainer,
    )

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        DataConfig,
    )

    cfg = ExperimentConfig.for_clients(
        2,
        model=ModelConfig.tiny(),
        data=DataConfig(max_len=ModelConfig.tiny().max_len),
        mesh=MeshConfig(clients=2, data=1),
    )
    trainer = FederatedTrainer(cfg)
    state = trainer.init_state(seed=1)

    with Checkpointer(str(tmp_path / "fed")) as ckpt:
        ckpt.save(0, state, meta={"round": 0, "config": cfg.to_dict()})
        ckpt.wait()
        template = trainer.init_state(seed=1)
        restored = ckpt.restore(template)
        meta = ckpt.restore_meta()

    _assert_tree_equal(restored.params, state.params)
    _assert_tree_equal(restored.opt_state, state.opt_state)
    np.testing.assert_array_equal(
        jax.random.key_data(restored.rngs), jax.random.key_data(state.rngs)
    )
    # Restore lands on the template's sharding (clients axis), not host-replicated.
    leaf = jax.tree.leaves(restored.params)[0]
    assert leaf.sharding == jax.tree.leaves(template.params)[0].sharding
    assert meta["round"] == 0
    assert meta["config"]["fed"]["num_clients"] == 2


def test_max_to_keep_garbage_collects(tmp_path, rng):
    trainer = _tiny_trainer()
    state = trainer.init_state(seed=0)
    with Checkpointer(str(tmp_path / "gc"), max_to_keep=2) as ckpt:
        for step in range(4):
            ckpt.save(step, state)
        ckpt.wait()
        assert ckpt.latest_step() == 3
        restored = ckpt.restore(trainer.init_state(seed=0), step=3)
        with pytest.raises(Exception):
            ckpt.restore(trainer.init_state(seed=0), step=0)  # GC'd
    _assert_tree_equal(restored.params, state.params)


def test_warm_start_absent_and_present(tmp_path, rng):
    trainer = _tiny_trainer()
    template = trainer.init_state(seed=0)

    # Reference behavior when no .pth exists (client1.py:375-377): fresh start.
    state, step = maybe_warm_start(str(tmp_path / "nope"), template)
    assert state is None and step is None

    trained = trainer.init_state(seed=0)
    batch = _tiny_batch(trainer.model_cfg, rng)
    trained, _ = trainer.train_step(trained, batch)
    with Checkpointer(str(tmp_path / "warm")) as ckpt:
        ckpt.save(7, trained)
        ckpt.wait()

    state, step = maybe_warm_start(str(tmp_path / "warm"), template)
    assert step == 7
    _assert_tree_equal(state.params, trained.params)


def test_warm_start_incompatible_checkpoint_degrades_to_fresh(tmp_path, rng):
    """A checkpoint saved under a different model shape (e.g. the default
    vocab grew between runs) must warm-start as None, not abort — warm start
    is an optimization (reference client1.py:375-377 proceeds from scratch
    when no compatible .pth exists)."""
    old = Trainer(ModelConfig.tiny(vocab_size=100), TrainConfig(seed=3))
    state = old.init_state(seed=0)
    with Checkpointer(str(tmp_path / "old")) as ckpt:
        ckpt.save(4, state)
        ckpt.wait()

    new = Trainer(ModelConfig.tiny(vocab_size=140), TrainConfig(seed=3))
    template = new.init_state(seed=0)
    restored, step = maybe_warm_start(str(tmp_path / "old"), template)
    assert restored is None and step is None


def test_prng_impl_is_plumbed():
    """TrainConfig.prng_impl selects the dropout-key generator (rbg default
    — the cheap TPU impl bench.py measures — threefry on request)."""
    for impl in ("rbg", "threefry2x32"):
        tr = Trainer(ModelConfig.tiny(), TrainConfig(seed=0, prng_impl=impl))
        st = tr.init_state(seed=0)
        assert str(jax.random.key_impl(st.rng)) == impl
    with pytest.raises(ValueError, match="unknown prng_impl"):
        TrainConfig(prng_impl="bogus")


def test_restore_empty_dir_raises(tmp_path):
    trainer = _tiny_trainer()
    with Checkpointer(str(tmp_path / "empty")) as ckpt:
        with pytest.raises(FileNotFoundError):
            ckpt.restore(trainer.init_state(seed=0))
