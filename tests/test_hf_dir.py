"""--hf-dir: training from a pretrained HF DistilBERT checkpoint directory
(the reference's hard-required ./distilbert-base-uncased, client1.py:357,
360-364)."""

import json
import os

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.hf_convert import (
    config_from_hf_dir,
    load_hf_dir,
)

transformers = pytest.importorskip("transformers")

DIM, LAYERS, HEADS, FFN, VOCAB = 48, 2, 4, 96, 160


@pytest.fixture(scope="module")
def hf_dir(tmp_path_factory):
    """A real save_pretrained checkpoint dir + BERT-style vocab.txt."""
    path = tmp_path_factory.mktemp("hf") / "distilbert-tiny"
    cfg = transformers.DistilBertConfig(
        vocab_size=VOCAB, dim=DIM, n_layers=LAYERS, n_heads=HEADS,
        hidden_dim=FFN, max_position_embeddings=128,
    )
    model = transformers.DistilBertModel(cfg)
    model.save_pretrained(str(path))

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.tokenizer import (
        build_domain_vocab,
    )

    vocab = build_domain_vocab()[:VOCAB]
    vocab += [f"[unused{i}]" for i in range(VOCAB - len(vocab))]
    assert len(vocab) == VOCAB
    with open(path / "vocab.txt", "w") as f:
        f.write("\n".join(vocab) + "\n")
    return str(path)


def test_config_from_hf_dir(hf_dir):
    cfg = config_from_hf_dir(hf_dir)
    assert (cfg.dim, cfg.n_layers, cfg.n_heads, cfg.hidden_dim) == (
        DIM, LAYERS, HEADS, FFN,
    )
    assert cfg.vocab_size == VOCAB
    assert cfg.max_len <= cfg.max_position_embeddings


def test_load_hf_dir_params_match_checkpoint(hf_dir):
    params, cfg = load_hf_dir(hf_dir)
    model = transformers.DistilBertModel.from_pretrained(hf_dir)
    want = model.state_dict()["embeddings.word_embeddings.weight"].numpy()
    np.testing.assert_allclose(
        np.asarray(params["encoder"]["embeddings"]["word_embeddings"]["embedding"]),
        want,
        rtol=1e-6,
    )
    # Fresh head (the checkpoint is a bare encoder, reference client1.py:58).
    assert params["classifier"]["kernel"].shape == (DIM, 2)


def test_load_hf_dir_missing_weights(tmp_path):
    (tmp_path / "config.json").write_text(json.dumps({
        "vocab_size": 16, "dim": 8, "n_layers": 1, "n_heads": 2,
        "hidden_dim": 16,
    }))
    with pytest.raises(FileNotFoundError, match="model.safetensors"):
        load_hf_dir(str(tmp_path))


@pytest.mark.slow
def test_cli_local_from_hf_dir(hf_dir, tmp_path, monkeypatch):
    """End-to-end: fedtpu local --hf-dir trains from the pretrained encoder
    and writes the reference artifact set."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        main,
    )

    monkeypatch.chdir(tmp_path)
    out = tmp_path / "out"
    rc = main([
        "local", "--hf-dir", hf_dir, "--synthetic", "300",
        "--data-fraction", "0.8", "--epochs", "1", "--batch-size", "8",
        "--max-len", "48", "--learning-rate", "1e-3",
        "--output-dir", str(out),
    ])
    assert rc == 0
    assert (out / "client0_local_metrics.csv").exists()


def test_hf_dir_max_len_validated_against_checkpoint_not_preset(hf_dir):
    """--max-len beyond the (discarded) tiny preset's 64-entry position
    table but within the checkpoint's must resolve, with config-file model
    knobs carried over rather than reset."""
    import argparse

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        _resolve_with_pretrained,
    )

    args = argparse.Namespace(hf_dir=hf_dir, max_len=128, preset="tiny")
    tok, cfg, params = _resolve_with_pretrained(args)
    assert cfg.model.max_len == 128  # > tiny's table (64), <= checkpoint's
    assert cfg.model.dim == DIM
    assert cfg.data.max_len == 128
    # Non-architecture knobs survive from the resolved (preset) config.
    assert cfg.model.compute_dtype == "float32"  # tiny preset's dtype
    assert params is not None


def test_cli_hf_dir_vocab_mismatch(hf_dir, tmp_path):
    import shutil

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        main,
    )

    bad = tmp_path / "bad"
    shutil.copytree(hf_dir, bad)
    with open(bad / "vocab.txt", "a") as f:
        f.write("extratoken\n")
    with pytest.raises(SystemExit, match="vocab"):
        main(["local", "--hf-dir", str(bad), "--synthetic", "50"])


def test_hf_dir_has_head_detection(hf_dir):
    """A bare DistilBertModel checkpoint has no classifier head — predict
    must be able to detect that (its head would be random noise)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.hf_convert import (
        hf_dir_has_head,
    )

    assert hf_dir_has_head(hf_dir) is False


def test_predict_rejects_bare_encoder_hf_dir(hf_dir, tmp_path):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        main,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        write_synthetic_csv,
    )

    csv = str(tmp_path / "flows.csv")
    write_synthetic_csv(csv, n_rows=20, seed=3)
    with pytest.raises(SystemExit, match="bare encoder"):
        main(
            ["predict", "--csv", csv, "--hf-dir", hf_dir,
             "--output", str(tmp_path / "p.csv")]
        )


def test_hf_to_flax_rejects_sequence_classifier_checkpoints(hf_dir):
    """An HF DistilBertForSequenceClassification state dict carries a
    pre_classifier layer this architecture lacks — converting it would
    silently drop trained weights, so it must be refused."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.hf_convert import (
        config_from_hf_dir,
        hf_to_flax,
    )

    model = transformers.DistilBertModel.from_pretrained(hf_dir)
    sd = {f"distilbert.{k}": v for k, v in model.state_dict().items()}
    sd["pre_classifier.weight"] = np.zeros((DIM, DIM), np.float32)
    sd["pre_classifier.bias"] = np.zeros((DIM,), np.float32)
    sd["classifier.weight"] = np.zeros((2, DIM), np.float32)
    sd["classifier.bias"] = np.zeros((2,), np.float32)
    with pytest.raises(ValueError, match="pre_classifier"):
        hf_to_flax(sd, config_from_hf_dir(hf_dir))


def test_pth_migration_loads_reference_artifact(hf_dir, tmp_path):
    """A reference-run .pth (distilbert.* + classifier.* state dict,
    client1.py:53-58,388) migrates directly: --pth supplies the trained
    weights, --hf-dir the tokenizer/architecture, and predict runs it."""
    import torch

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.hf_convert import (
        config_from_hf_dir,
        load_reference_pth,
    )

    torch.manual_seed(0)
    enc = transformers.DistilBertModel.from_pretrained(hf_dir)
    sd = {f"distilbert.{k}": v for k, v in enc.state_dict().items()}
    head_w = torch.randn(2, DIM)
    sd["classifier.weight"] = head_w
    sd["classifier.bias"] = torch.zeros(2)
    pth = str(tmp_path / "client1_model.pth")
    torch.save(sd, pth)

    cfg = config_from_hf_dir(hf_dir)
    params = load_reference_pth(pth, cfg)
    np.testing.assert_allclose(
        np.asarray(params["classifier"]["kernel"]),
        head_w.numpy().T,
        rtol=1e-6,
    )

    # Headless dict is not a migration artifact.
    sd_headless = {k: v for k, v in sd.items() if not k.startswith("classifier.")}
    pth2 = str(tmp_path / "headless.pth")
    torch.save(sd_headless, pth2)
    with pytest.raises(ValueError, match="classifier"):
        load_reference_pth(pth2, cfg)

    # End-to-end: predict from the migrated model (no checkpoint needed).
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        main,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        write_synthetic_csv,
    )

    csv = str(tmp_path / "flows.csv")
    write_synthetic_csv(csv, n_rows=24, seed=4)
    out = str(tmp_path / "preds.csv")
    assert (
        main(
            ["predict", "--csv", csv, "--hf-dir", hf_dir, "--pth", pth,
             "--output", out]
        )
        == 0
    )
    assert os.path.exists(out)

    # --pth without --hf-dir is refused (no tokenizer/architecture source).
    with pytest.raises(SystemExit, match="--hf-dir"):
        main(["predict", "--csv", csv, "--pth", pth, "--output", out])


@pytest.mark.slow
def test_distill_from_reference_pth(hf_dir, tmp_path):
    """Distill a migrated reference model (--pth teacher) into a shallower
    student (--student-layers): the full migration-then-compress flow."""
    import torch

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        main,
    )

    torch.manual_seed(1)
    enc = transformers.DistilBertModel.from_pretrained(hf_dir)
    sd = {f"distilbert.{k}": v for k, v in enc.state_dict().items()}
    sd["classifier.weight"] = torch.randn(2, DIM)
    sd["classifier.bias"] = torch.zeros(2)
    pth = str(tmp_path / "aggregated.pth")
    torch.save(sd, pth)

    out = str(tmp_path / "dist")
    assert (
        main(
            [
                "distill", "--synthetic", "200", "--epochs", "1",
                "--batch-size", "8", "--hf-dir", hf_dir, "--pth", pth,
                "--student-layers", "1", "--distill-epochs", "1",
                "--output-dir", out,
            ]
        )
        == 0
    )
    assert os.path.exists(os.path.join(out, "student_metrics.csv"))
    # Conflicting teacher sources are refused.
    with pytest.raises(SystemExit, match="both teacher sources"):
        main(
            ["distill", "--synthetic", "100", "--hf-dir", hf_dir,
             "--pth", pth, "--teacher-checkpoint", str(tmp_path)]
        )

def test_export_hf_from_reference_pth(hf_dir, tmp_path):
    """export-hf --pth + --hf-dir (no checkpoint dir): a reference-trained
    .pth converts straight to the HF layout — the documented migration
    path '.pth + --hf-dir -> HF layout' (cmd_export_hf's elif branch)."""
    import torch

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        main,
    )

    torch.manual_seed(1)
    enc = transformers.DistilBertModel.from_pretrained(hf_dir)
    sd = {f"distilbert.{k}": v for k, v in enc.state_dict().items()}
    head_w = torch.randn(2, DIM)
    sd["classifier.weight"] = head_w
    sd["classifier.bias"] = torch.zeros(2)
    pth = str(tmp_path / "aggregated.pth")
    torch.save(sd, pth)

    out = str(tmp_path / "hf_out")
    assert (
        main(["export-hf", "--hf-dir", hf_dir, "--pth", pth, "--out", out])
        == 0
    )
    assert sorted(os.listdir(out)) == [
        "config.json", "model.safetensors", "vocab.txt",
    ]
    # The migrated classifier head survives the round trip.
    from safetensors.numpy import load_file

    exported = load_file(os.path.join(out, "model.safetensors"))
    np.testing.assert_allclose(
        exported["classifier.weight"], head_w.numpy(), rtol=1e-6
    )
    # Both weight sources together are still refused.
    with pytest.raises(SystemExit, match="both weight sources"):
        main(
            ["export-hf", "--hf-dir", hf_dir, "--pth", pth,
             "--checkpoint-dir", str(tmp_path / "ck"), "--out", out]
        )
    # Neither source is refused too (the runtime check, not argparse).
    with pytest.raises(SystemExit, match="trained weights"):
        main(["export-hf", "--hf-dir", hf_dir, "--out", out])

def test_pth_export_hf_roundtrip_bit_exact(hf_dir, tmp_path):
    """Golden migration regression (VERDICT r2 §9): a synthetic checkpoint
    shaped exactly like the reference's saved ``.pth`` (DDoSClassifier
    state dict — ``distilbert.*`` encoder + ``classifier.*`` head,
    reference client1.py:53-58,388; server.py:77) survives
    ``--pth`` migration + ``export-hf`` with EVERY tensor bit-exact: the
    only transforms on the path are fp32 transposes, which are lossless.
    Keeps the pretrained-parity machinery pinned until real weights are
    reachable (zero-egress environment)."""
    import torch

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        main,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.hf_convert import (
        config_from_hf_dir,
        flax_to_hf,
        load_reference_pth,
    )

    torch.manual_seed(7)
    enc = transformers.DistilBertModel.from_pretrained(hf_dir)
    sd = {f"distilbert.{k}": v for k, v in enc.state_dict().items()}
    sd["classifier.weight"] = torch.randn(2, DIM)
    sd["classifier.bias"] = torch.randn(2)
    pth = str(tmp_path / "client1_model.pth")
    torch.save(sd, pth)

    # Library path: .pth -> Flax -> reference key space, key-complete and
    # bitwise identical.
    cfg = config_from_hf_dir(hf_dir)
    out_sd = flax_to_hf(load_reference_pth(pth, cfg), cfg)
    want = {k: v.numpy() for k, v in sd.items()}
    assert sorted(out_sd) == sorted(want)
    for k in want:
        assert out_sd[k].dtype == want[k].dtype == np.float32, k
        assert out_sd[k].shape == want[k].shape, k
        assert out_sd[k].tobytes() == want[k].tobytes(), f"bit drift in {k}"

    # CLI path: export-hf writes the same bytes into model.safetensors.
    out_dir = str(tmp_path / "hf_roundtrip")
    assert (
        main(["export-hf", "--hf-dir", hf_dir, "--pth", pth, "--out", out_dir])
        == 0
    )
    from safetensors.numpy import load_file

    exported = load_file(os.path.join(out_dir, "model.safetensors"))
    assert sorted(exported) == sorted(want)
    for k in want:
        assert exported[k].tobytes() == want[k].tobytes(), f"bit drift in {k}"


def test_pre_gelu_config_file_defers_to_checkpoint_activation(hf_dir, tmp_path):
    """A --config file saved before the gelu field existed must not inject
    today's library default (tanh) over the --hf-dir checkpoint's declared
    erf activation; a file that explicitly says gelu still wins."""
    import argparse

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        _resolve_with_pretrained,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        ExperimentConfig,
    )

    d = ExperimentConfig().to_dict()
    del d["model"]["gelu"]  # pre-gelu-field export-config output
    old_cfg = tmp_path / "old.json"
    old_cfg.write_text(json.dumps(d))

    def resolve(config_path):
        args = argparse.Namespace(
            hf_dir=hf_dir, config=str(config_path), preset="tiny",
            max_len=None, gelu=None,
        )
        _, cfg, _ = _resolve_with_pretrained(args, load_weights=False)
        return cfg.model.gelu

    # hf_dir's config.json declares HF's default "gelu" (erf) activation.
    assert resolve(old_cfg) == "exact"
    d["model"]["gelu"] = "tanh"
    new_cfg = tmp_path / "new.json"
    new_cfg.write_text(json.dumps(d))
    assert resolve(new_cfg) == "tanh"

def test_attention_flags_survive_hf_dir_resolution(hf_dir):
    """--attention-impl/--remat must carry into the checkpoint-derived
    model config (the overrides dict in _resolve_with_pretrained)."""
    import argparse

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
        _resolve_with_pretrained,
    )

    args = argparse.Namespace(
        hf_dir=hf_dir, preset="tiny", max_len=None, gelu=None, config=None,
        attention_impl="flash", attention_dropout=0.0, remat=True,
    )
    _, cfg, _ = _resolve_with_pretrained(args, load_weights=False)
    assert cfg.model.attention_impl == "flash"
    assert cfg.model.attention_dropout == 0.0
    assert cfg.model.remat is True
    assert cfg.model.dim == DIM  # architecture still from the checkpoint
