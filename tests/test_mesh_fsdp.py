"""FSDP client mesh (ISSUE 15): shard-at-rest params/optimizer over the
local ``data`` axis with gather-at-use (train/client_mesh.FsdpMeshTrainer).

The contracts pinned here:

* trajectory — FSDP vs replicated-mesh vs single-device under threefry:
  metrics EQUAL, params within fp32 reduction-order ulps (the grad
  reduce-scatter may sum partials in a different order than the
  all-reduce — the PR-2 documented class, allclose-pinned);
* memory — per-chip static-state bytes (params + Adam moments) scale
  ~1/N (exact addressable-shard accounting);
* wire — host-gather -> adopt (scatter onto shards) -> host-gather is
  byte/crc-exact, streamed-reply leaves scatter DIRECTLY onto their
  shard specs, and a live `--fsdp` loopback round composes with
  streamed uploads and secure-agg+DP unchanged;
* checkpoint — shard -> save -> restore -> shard is leaf-bit-exact.
"""

import csv
import json
import os
import threading

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
    main,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    wire,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    default_tokenizer,
    make_synthetic,
    make_all_client_splits,
    tokenize_client,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.mesh import (
    device_tree_bytes,
    fsdp_dim,
    fsdp_spec,
    fsdp_tree_shardings,
    make_host_mesh,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.client_mesh import (
    FsdpMeshTrainer,
    MeshTrainer,
    make_client_trainer,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
    Trainer,
)

L = 32


@pytest.fixture(scope="module")
def tok():
    return default_tokenizer()


def _cfg(tok, *, data=2, fsdp=True, prng="threefry2x32"):
    model = ModelConfig.tiny(
        vocab_size=len(tok.vocab), max_len=L, max_position_embeddings=2 * L
    )
    return ExperimentConfig(
        model=model,
        data=DataConfig(max_len=L, batch_size=8, data_fraction=0.3),
        train=TrainConfig(
            prng_impl=prng,
            epochs_per_round=1,
            learning_rate=1e-3,
            log_every=0,
        ),
        fed=FedConfig(num_clients=1),
        mesh=MeshConfig(clients=1, data=data, fsdp=fsdp),
    )


@pytest.fixture(scope="module")
def client_data(tok):
    cfg = _cfg(tok)
    df = make_synthetic("cicids2017", 400, seed=42)
    splits = make_all_client_splits(df, 1, cfg.data)
    return tokenize_client(splits[0], tok, max_len=L)


# ----------------------------------------------------------- spec builders
def test_fsdp_spec_picks_largest_divisible_dim():
    assert fsdp_dim((6, 4), 2) == 0  # largest divisible
    assert fsdp_dim((4, 6), 2) == 1
    assert fsdp_dim((3, 5), 2) is None  # nothing divides
    assert fsdp_dim((), 2) is None  # scalar
    assert fsdp_dim((8, 8), 2) == 0  # tie -> lowest index
    assert fsdp_dim((8,), 1) is None  # one shard = replicated
    assert fsdp_spec((6, 4), 2) == P("data", None)
    assert fsdp_spec((4, 6), 2) == P(None, "data")
    assert fsdp_spec((3, 5), 2) == P()
    # Deterministic: the wire tier derives the same layout independently.
    assert fsdp_spec((1024, 768), 4) == fsdp_spec((1024, 768), 4)


def test_fsdp_tree_shardings_replicates_scalars_and_keys(eight_devices):
    mesh = make_host_mesh(2)
    rng = jax.random.key(0, impl="threefry2x32")
    tree = {
        "w": np.zeros((8, 4), np.float32),
        "b": np.zeros((3,), np.float32),  # undividable
        "step": np.zeros((), np.int32),
        "rng": rng,
    }
    sh = fsdp_tree_shardings(tree, mesh)
    assert sh["w"].spec == P("data", None)
    assert sh["b"].spec == P()
    assert sh["step"].spec == P()
    assert sh["rng"].spec == P()


def test_mesh_config_validates_fsdp():
    with pytest.raises(ValueError, match="data >= 2"):
        MeshConfig(clients=1, data=1, fsdp=True)
    with pytest.raises(ValueError, match="seq"):
        MeshConfig(clients=1, data=2, seq=2, fsdp=True)


def test_make_client_trainer_dispatches_fsdp(tok, eight_devices):
    t = make_client_trainer(_cfg(tok))
    assert isinstance(t, FsdpMeshTrainer)
    assert t.n_shards == 2
    # fsdp off keeps the replicated meshed trainer
    t = make_client_trainer(_cfg(tok, fsdp=False))
    assert isinstance(t, MeshTrainer) and not isinstance(t, FsdpMeshTrainer)


# ----------------------------------------------------- trajectory + memory
def test_fsdp_matches_replicated_and_single_device_trajectory(
    tok, client_data, eight_devices
):
    """The headline identity: FSDP over 2 shards vs the plain engine —
    same threefry trajectory, equal final metrics, params within
    reduction-order ulps (the reduce-scatter vs all-reduce class)."""
    cfg = _cfg(tok)
    plain = Trainer(cfg.model, cfg.train, pad_id=tok.pad_id)
    s0, _ = plain.fit(plain.init_state(), client_data.train, batch_size=8)
    m0 = plain.evaluate_state(s0, client_data.test)
    h0 = plain.host_params(s0)
    fsdp = FsdpMeshTrainer(
        cfg.model, cfg.train, mesh=make_host_mesh(2), pad_id=tok.pad_id
    )
    sf, _ = fsdp.fit(fsdp.init_state(), client_data.train, batch_size=8)
    mf = fsdp.evaluate_state(sf, client_data.test)
    for k in ("Accuracy", "Precision", "Recall", "F1-Score"):
        assert m0[k] == mf[k], (k, m0[k], mf[k])
    np.testing.assert_allclose(m0["Loss"], mf["Loss"], rtol=1e-5)
    np.testing.assert_array_equal(
        m0["confusion_matrix"], mf["confusion_matrix"]
    )
    hf = fsdp.host_params(sf)
    for a, b in zip(jax.tree.leaves(h0), jax.tree.leaves(hf)):
        np.testing.assert_allclose(a, b, atol=2e-6, rtol=1e-5)


def test_fsdp_static_state_shards_at_rest(tok, eight_devices):
    """The memory contract: per-chip params+opt bytes scale ~1/N, and
    the leaves actually live on their shard specs (not just constrained
    transiently inside the step)."""
    cfg = _cfg(tok)
    rep = MeshTrainer(
        cfg.model, cfg.train, mesh=make_host_mesh(2), pad_id=tok.pad_id
    )
    fsdp = FsdpMeshTrainer(
        cfg.model, cfg.train, mesh=make_host_mesh(2), pad_id=tok.pad_id
    )
    sr = rep.init_state()
    sf = fsdp.init_state()
    rep_bytes = device_tree_bytes((sr.params, sr.opt_state))
    fsdp_bytes = device_tree_bytes((sf.params, sf.opt_state))
    ratio = fsdp_bytes / rep_bytes
    assert ratio <= 0.6, (fsdp_bytes, rep_bytes, ratio)
    sharded = [
        leaf
        for leaf in jax.tree.leaves(sf.params)
        if getattr(leaf.sharding, "spec", P()) != P()
    ]
    assert sharded, "no param leaf is sharded at rest"
    # The step keeps the layout: one train step in, leaves still sharded.
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(
            0, cfg.model.vocab_size, (8, L)
        ).astype(np.int32),
        "attention_mask": np.ones((8, L), np.int32),
        "labels": rng.integers(0, 2, 8).astype(np.int32),
    }
    sf2, _ = fsdp.train_step(sf, batch)
    assert device_tree_bytes((sf2.params, sf2.opt_state)) == fsdp_bytes


def test_fsdp_backward_regathers_instead_of_retaining(tok, eight_devices):
    """The peak-memory MECHANISM (invisible to the bench, which measures
    at-rest bytes outside the step): the rematted FSDP loss saves NO
    gathered full-size weight as a residual — every saved value is a
    region argument (the shards at rest) or an activation — so the
    backward RE-GATHERS. Built exactly as make_fsdp_train_step builds
    it. Guards the remat construction: wrapping only the gather (or
    using the stock except-these-names policy without the
    sharding-constraint exclusion) saves the gathered tree and fails
    this test."""
    import contextlib
    import io

    from jax.ad_checkpoint import print_saved_residuals
    from jax.sharding import NamedSharding

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
        FSDP_GATHER_NAME,
        _tag_gather,
        fsdp_remat_loss,
        loss_fn,
    )

    cfg = _cfg(tok)
    mesh = make_host_mesh(2)
    fsdp = FsdpMeshTrainer(
        cfg.model, cfg.train, mesh=mesh, pad_id=tok.pad_id
    )
    state = fsdp.init_state()
    replicated = NamedSharding(mesh, P())

    def gather(p):
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, replicated), p
        )

    tagged = _tag_gather(gather)
    loss_rm = fsdp_remat_loss(
        lambda p, batch, rng: loss_fn(fsdp.model, tagged(p), batch, rng)
    )
    rng = np.random.default_rng(1)
    batch = {
        "input_ids": jnp_like(
            rng.integers(0, cfg.model.vocab_size, (8, L)).astype(np.int32)
        ),
        "attention_mask": jnp_like(np.ones((8, L), np.int32)),
        "labels": jnp_like(rng.integers(0, 2, 8).astype(np.int32)),
    }
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        print_saved_residuals(
            loss_rm,
            state.params,
            batch,
            jax.random.key(0, impl=cfg.train.prng_impl),
        )
    leaked = [
        line
        for line in buf.getvalue().splitlines()
        if FSDP_GATHER_NAME in line and "argument" not in line
    ]
    assert not leaked, leaked


def jnp_like(arr):
    import jax.numpy as jnp

    return jnp.asarray(arr)


# ----------------------------------------------------------- wire boundary
def test_fsdp_gather_scatter_round_trip_crc_exact(tok, eight_devices):
    """The wire-exchange gather contract (the bench's fsdp_crc_exact):
    host-gather -> adopt (scatter onto shards, fresh sharded Adam) ->
    host-gather is byte- and crc-exact, so secure-agg/DP masking sees
    the identical flat vector a single-device client would produce."""
    cfg = _cfg(tok)
    plain = Trainer(cfg.model, cfg.train, pad_id=tok.pad_id)
    fsdp = FsdpMeshTrainer(
        cfg.model, cfg.train, mesh=make_host_mesh(2), pad_id=tok.pad_id
    )
    p0 = plain.host_params(plain.init_state())
    pf = fsdp.host_params(fsdp.init_state())
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(pf)):
        np.testing.assert_array_equal(a, b)
    rng = np.random.default_rng(7)
    agg = jax.tree.map(
        lambda x: (x + rng.normal(0, 0.01, x.shape)).astype(x.dtype), p0
    )
    state = fsdp.adopt_aggregate(fsdp.init_state(), agg)
    back = fsdp.host_params(state)
    for a, b in zip(jax.tree.leaves(agg), jax.tree.leaves(back)):
        np.testing.assert_array_equal(a, b)
    assert wire.flat_crc32(wire.flatten_params(agg)) == wire.flat_crc32(
        wire.flatten_params(back)
    )
    assert int(state.step) == 0


def test_fsdp_reply_leaf_sink_scatters_onto_shards(tok, eight_devices):
    """Streamed-reply leaves land DIRECTLY on their shard spec (never a
    full replica per chip), bit-identical to the host-tree path."""
    cfg = _cfg(tok)
    fsdp = FsdpMeshTrainer(
        cfg.model, cfg.train, mesh=make_host_mesh(2), pad_id=tok.pad_id
    )
    arr = np.arange(32, dtype=np.float32).reshape(8, 4)
    placed = fsdp.reply_leaf_sink("encoder/x/kernel", arr)
    assert placed.sharding.spec == P("data", None)
    np.testing.assert_array_equal(np.asarray(placed), arr)
    small = np.arange(3, dtype=np.float32)
    placed_small = fsdp.reply_leaf_sink("encoder/x/bias", small)
    assert placed_small.sharding.spec == P()
    np.testing.assert_array_equal(np.asarray(placed_small), small)


def test_fsdp_checkpoint_round_trip_bit_exact(tok, client_data, tmp_path, eight_devices):
    """shard -> save -> restore -> shard: the restore template is the
    FSDP init_state, so leaves land back on their shards (orbax
    sharding-aware restore) and the host view is leaf-bit-exact."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.checkpoint import (
        Checkpointer,
    )

    cfg = _cfg(tok)
    fsdp = FsdpMeshTrainer(
        cfg.model, cfg.train, mesh=make_host_mesh(2), pad_id=tok.pad_id
    )
    state, _ = fsdp.fit(fsdp.init_state(), client_data.train, batch_size=8)
    before = fsdp.host_params(state)
    ckpt_dir = str(tmp_path / "ck")
    with Checkpointer(ckpt_dir) as ckpt:
        ckpt.save(1, state)
        ckpt.wait()
        restored = ckpt.restore(fsdp.init_state())
    for leaf in jax.tree.leaves(restored.params):
        assert hasattr(leaf, "sharding")
    sharded = [
        leaf
        for leaf in jax.tree.leaves(restored.params)
        if getattr(leaf.sharding, "spec", P()) != P()
    ]
    assert sharded, "restore lost the shard-at-rest layout"
    after = jax.tree.map(np.asarray, restored.params)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(a, b)
    # Opt state (Adam moments) round-trips bit-exactly too.
    for a, b in zip(
        jax.tree.leaves(state.opt_state), jax.tree.leaves(restored.opt_state)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------- fedsteps parameterization
@pytest.mark.slow
def test_packed_step_spec_parameterization_matches_plain(tok, eight_devices):
    """make_packed_step(gather=, constrain=) — the FSDP-parameterized
    packed step — advances one client identically (to reduction-order
    ulps) to the plain packed step under threefry keys."""
    import jax.numpy as jnp
    import optax

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.distilbert import (
        DDoSClassifier,
        init_params,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
        loss_fn,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.fedsteps import (
        make_packed_step,
    )

    cfg = _cfg(tok)
    mesh = make_host_mesh(2)
    from jax.sharding import NamedSharding

    replicated = NamedSharding(mesh, P())
    model = DDoSClassifier(cfg.model)
    optimizer = optax.adam(1e-3)

    def objective(p, batch, step_rng, anchor):
        task = loss_fn(model, p, batch, step_rng)
        return task, task

    def gather(p):
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, replicated), p
        )

    def constrain(tree):
        shardings = fsdp_tree_shardings(tree, mesh)
        return jax.tree.map(
            jax.lax.with_sharding_constraint, tree, shardings
        )

    rng = jax.random.key(0, impl="threefry2x32")
    # Host-side master copy: the packed step DONATES its state buffers,
    # so each run must place fresh device arrays from host numpy.
    params = jax.tree.map(np.asarray, init_params(model, cfg.model, rng))
    nprng = np.random.default_rng(0)
    batch = {
        "input_ids": nprng.integers(
            0, cfg.model.vocab_size, (8, L)
        ).astype(np.int32),
        "attention_mask": np.ones((8, L), np.int32),
        "labels": nprng.integers(0, 2, 8).astype(np.int32),
    }

    def run(step, place):
        drng = jax.random.fold_in(
            jax.random.key(0, impl="threefry2x32"), 1
        )
        cstate = (
            place(params),
            place(jax.tree.map(np.asarray, optimizer.init(params))),
            jnp.zeros((), jnp.int32),
            drng,
        )
        for _ in range(3):
            cstate, task = step(cstate, batch)
        return jax.tree.map(np.asarray, cstate[0]), float(task)

    plain_step = make_packed_step(objective, optimizer, 0, 0.0)
    fsdp_step = make_packed_step(
        objective, optimizer, 0, 0.0, gather=gather, constrain=constrain
    )
    p_plain, l_plain = run(plain_step, lambda t: t)
    p_fsdp, l_fsdp = run(
        fsdp_step, lambda t: jax.device_put(t, fsdp_tree_shardings(t, mesh))
    )
    np.testing.assert_allclose(l_plain, l_fsdp, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_plain), jax.tree.leaves(p_fsdp)):
        np.testing.assert_allclose(a, b, atol=2e-6, rtol=1e-5)


@pytest.mark.slow
def test_build_federated_steps_gather_constrain_matches_plain(
    tok, eight_devices
):
    """build_federated_steps(gather=, constrain=) — the stacked FedState
    lifted to shard-at-rest over the data axis — advances every client
    lane identically (to reduction-order ulps) to the plain stacked
    step under threefry keys. The callables see STACKED [C, ...] trees:
    gather replicates over the fsdp axis only (clients stacking stays),
    constrain pins each leaf onto P('clients', *fsdp_spec(dims[1:]))."""
    import jax.numpy as jnp

    from jax.sharding import NamedSharding

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.distilbert import (
        DDoSClassifier,
        init_params,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.mesh import (
        FedShardings,
        make_mesh,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.engine import (
        make_optimizer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.fedsteps import (
        FedState,
        build_federated_steps,
    )

    C, DATA = 2, 2
    model_cfg = ModelConfig.tiny(
        vocab_size=len(tok.vocab), max_len=L, max_position_embeddings=2 * L
    )
    cfg = ExperimentConfig(
        model=model_cfg,
        data=DataConfig(max_len=L, batch_size=8),
        train=TrainConfig(
            prng_impl="threefry2x32", learning_rate=1e-3, log_every=0
        ),
        fed=FedConfig(num_clients=C),
        mesh=MeshConfig(clients=C, data=DATA, fsdp=True),
    )
    mesh = make_mesh(C, DATA, devices=eight_devices[: C * DATA])
    sh = FedShardings(mesh)

    def stacked_sharding(x):
        dims = tuple(int(d) for d in np.shape(x))
        inner = tuple(fsdp_spec(dims[1:], DATA)) if len(dims) > 1 else ()
        return NamedSharding(mesh, P("clients", *inner))

    def gather(tree):
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, sh.client), tree
        )

    def constrain(tree):
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x, stacked_sharding(x)
            ),
            tree,
        )

    model = DDoSClassifier(cfg.model)
    optimizer = make_optimizer(cfg.train)
    plain = build_federated_steps(cfg, model, optimizer, sh)
    fsdp = build_federated_steps(
        cfg, model, optimizer, sh, gather=gather, constrain=constrain
    )
    with pytest.raises(ValueError, match="pass both or neither"):
        build_federated_steps(cfg, model, optimizer, sh, gather=gather)

    rng = jax.random.key(0, impl="threefry2x32")
    p1 = jax.tree.map(np.asarray, init_params(model, cfg.model, rng))
    stacked = jax.tree.map(lambda a: np.stack([a] * C), p1)
    opt0 = jax.tree.map(np.asarray, jax.vmap(optimizer.init)(stacked))
    nprng = np.random.default_rng(0)
    batch = {
        "input_ids": nprng.integers(
            0, cfg.model.vocab_size, (C, 8, L)
        ).astype(np.int32),
        "attention_mask": np.ones((C, 8, L), np.int32),
        "labels": nprng.integers(0, 2, (C, 8)).astype(np.int32),
    }
    base_keys = jax.vmap(
        lambda i: jax.random.fold_in(
            jax.random.key(0, impl="threefry2x32"), i
        )
    )(np.arange(C))

    def run(steps, place_params):
        state = FedState(
            params=place_params(stacked),
            opt_state=place_params(opt0),
            step=jnp.zeros((), jnp.int32),
            rngs=jax.device_put(base_keys, sh.client),
        )
        losses = None
        for _ in range(3):
            state, losses = steps.train_step(state, batch)
        return (
            jax.tree.map(np.asarray, state.params),
            np.asarray(losses),
        )

    p_plain, l_plain = run(
        plain, lambda t: jax.device_put(t, sh.client)
    )
    p_fsdp, l_fsdp = run(
        fsdp,
        lambda t: jax.device_put(t, jax.tree.map(stacked_sharding, t)),
    )
    np.testing.assert_allclose(l_plain, l_fsdp, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_plain), jax.tree.leaves(p_fsdp)):
        np.testing.assert_allclose(a, b, atol=2e-6, rtol=1e-5)
    # Shard-at-rest actually held: per-chip static bytes ~1/DATA.
    rep_bytes = device_tree_bytes(jax.device_put(stacked, sh.client))
    fsdp_bytes = device_tree_bytes(
        jax.device_put(stacked, jax.tree.map(stacked_sharding, stacked))
    )
    assert fsdp_bytes / rep_bytes <= 0.6


# --------------------------------------------------------------- live wire
def _write_cfg(tmp_path, cfg, name):
    path = str(tmp_path / name)
    with open(path, "w") as f:
        json.dump(cfg.to_dict(), f)
    return path


def _read_metrics_csv(path):
    with open(path) as f:
        return dict(next(iter(csv.DictReader(f))))


def _run_client(argv, results, key):
    try:
        results[key] = main(argv)
    except BaseException as e:
        results[key] = e


def test_fsdp_client_two_round_loopback_matches_single_device(
    tok, tmp_path, eight_devices
):
    """The acceptance run: live server + `client --data-parallel 2
    --fsdp` for TWO rounds (round 2 streams the upload off the server's
    round-1 advert, and streamed replies scatter leaves onto shards) vs
    the single-device client on identical config/data — final local AND
    aggregated metrics threefry-identical. The wire-codec step profiler
    is armed (--profile-stride 1), so the wire-upload/wire-reply spans
    carry step_wire_ms_* attrs and the timeline renders the wire-codec
    row (the PR-12 device-plane residual, proven live)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.profile import (
        memory_report,
        set_profile_stride,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.timeline import (
        load_spans,
        timeline_table,
    )

    cfg = _cfg(tok)
    cfg_plain = _cfg(tok, data=1, fsdp=False)
    outs = {}
    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    try:
        for name, cfgv, extra in (
            ("single", cfg_plain, []),
            (
                "fsdp",
                cfg,
                [
                    "--data-parallel", "2", "--fsdp",
                    "--profile-stride", "1",
                    "--trace-jsonl", str(trace_dir / "client.jsonl"),
                ],
            ),
        ):
            cfg_path = _write_cfg(tmp_path, cfgv, f"cfg_{name}.json")
            out = str(tmp_path / name)
            outs[name] = out
            with AggregationServer(
                port=0, num_clients=1, timeout=60
            ) as server:
                errs: list = []

                def _serve():
                    try:
                        server.serve(rounds=2)
                    except Exception as e:
                        errs.append(e)

                t = threading.Thread(target=_serve, daemon=True)
                t.start()
                rc = main(
                    [
                        "client", "--client-id", "0", "--host", "127.0.0.1",
                        "--port", str(server.port), "--config", cfg_path,
                        "--synthetic", "400", "--output-dir", out,
                        "--timeout", "60", "--rounds", "2", *extra,
                    ]
                )
                t.join(timeout=60)
            assert rc == 0 and not errs, (rc, errs)
    finally:
        set_profile_stride(0)
    for phase in ("local", "aggregated"):
        a = _read_metrics_csv(
            os.path.join(outs["single"], f"client0_{phase}_metrics.csv")
        )
        b = _read_metrics_csv(
            os.path.join(outs["fsdp"], f"client0_{phase}_metrics.csv")
        )
        assert set(a) == set(b)
        for k in a:
            if k == "Loss":
                np.testing.assert_allclose(
                    float(a[k]), float(b[k]), rtol=1e-5, err_msg=(phase, k)
                )
            else:
                assert a[k] == b[k], (phase, k, a[k], b[k])
    # Wire-codec profiler satellite: the streamed round's spans carry
    # the sampled per-leaf pack/unpack attrs and the timeline renders
    # the row.
    spans = load_spans(trace_dir=str(trace_dir))
    wire_spans = [
        s
        for s in spans
        if s.get("span") in ("wire-upload", "wire-reply")
        and s.get("step_wire_ms_p50") is not None
    ]
    assert any(s["span"] == "wire-reply" for s in wire_spans), spans
    assert any(s["span"] == "wire-upload" for s in wire_spans), spans
    assert all(s.get("step_sampled", 0) >= 1 for s in wire_spans)
    table = timeline_table(spans)
    assert "wire-codec" in table
    # Adopt-aggregate boundary watermark (PR-12 residual closed): the
    # meshed client path stamps post-aggregate now; CPU backends record
    # the visit as unavailable rather than skipping it.
    assert "post-aggregate" in memory_report()


def test_fsdp_client_composes_with_secure_agg_and_dp(
    tok, tmp_path, eight_devices, monkeypatch
):
    """--secure-agg + --dp with a MIXED fleet: client 0 single-device,
    client 1 --data-parallel 2 --fsdp, one live secure DP round. The
    server's dp_base_crc equality check REJECTS a round whose clients
    upload different bases, so completion proves the FSDP host gather is
    byte-identical to the single-device client's."""
    monkeypatch.delenv("FEDTPU_SECRET", raising=False)
    monkeypatch.delenv("FEDTPU_CLIENT_SECRET", raising=False)
    base_cfg = _cfg(tok, data=1, fsdp=False)
    cfg = ExperimentConfig(
        model=base_cfg.model,
        data=base_cfg.data,
        train=base_cfg.train,
        fed=FedConfig(num_clients=2),
        mesh=MeshConfig(clients=2, data=1),
    )
    cfg_path = _write_cfg(tmp_path, cfg, "cfg2.json")
    out = str(tmp_path / "compose")
    with AggregationServer(
        port=0,
        num_clients=2,
        timeout=90,
        secure_agg=True,
        dp_clip=1.0,
        dp_noise_multiplier=0.05,
    ) as server:
        errs: list = []

        def _serve():
            try:
                server.serve(rounds=1)
            except Exception as e:
                errs.append(e)

        t = threading.Thread(target=_serve, daemon=True)
        t.start()
        results: dict = {}
        base = [
            "--host", "127.0.0.1", "--port", str(server.port),
            "--config", cfg_path, "--synthetic", "400",
            "--output-dir", out, "--timeout", "90",
            "--secure-agg", "--dp",
        ]
        c1 = threading.Thread(
            target=_run_client,
            args=(
                [
                    "client", "--client-id", "1",
                    "--data-parallel", "2", "--fsdp", *base,
                ],
                results,
                "fsdp",
            ),
            daemon=True,
        )
        c1.start()
        results["single"] = main(["client", "--client-id", "0", *base])
        c1.join(timeout=120)
        t.join(timeout=60)
    assert results["single"] == 0 and results["fsdp"] == 0, results
    assert not errs, errs
    for c in (0, 1):
        assert os.path.exists(
            os.path.join(out, f"client{c}_aggregated_metrics.csv")
        )


# ------------------------------------------------------------ wire profiler
def test_wire_step_profiler_site_and_attrs():
    """The 'wire' StepProfiler site: single 'wire' phase, the
    fedtpu_wire_step_seconds family, step_wire_ms_* span attrs."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.metrics import (
        MetricsRegistry,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.profile import (
        StepProfiler,
    )

    reg = MetricsRegistry()
    prof = StepProfiler(2, site="wire", registry=reg)
    assert prof.phases == ("wire",)
    sampled = [prof.tick() for _ in range(4)]
    assert sampled == [True, False, True, False]
    prof.note("wire", 0.002)
    prof.note("wire", 0.004)
    attrs = prof.span_attrs()
    assert attrs["step_wire_ms_p50"] > 0
    assert attrs["step_sampled"] == 2
    assert "fedtpu_wire_step_seconds" in reg.render()
    with pytest.raises(ValueError, match="unknown phase"):
        prof.note("device", 0.1)
    # Window reset clears the samples (long-lived client contract).
    prof.begin_window()
    assert prof.span_attrs() == {}
