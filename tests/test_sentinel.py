"""Sentinel plane (obs/sentinel.py): canary fixture + prober identity/
bit-stability, journal-tailing supervised drift, long-horizon retention
ring + regression verdicts, snapshot rotation, health verdict, and the
controller's SentinelLink poke."""

import json
import os

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.control.drift import (
    ErrorRateMonitor,
    SentinelLink,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.fleet import (
    HEALTH_SCHEMA,
    ScrapeHub,
    Target,
    health_verdict,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.sentinel import (
    CANARY_SCHEMA,
    RING_SCHEMA,
    SENTINEL_SCHEMA,
    VERDICT_SCHEMA,
    DEFAULT_TREND_FIELDS,
    CanaryProber,
    JournalTail,
    RetentionRing,
    Sentinel,
    load_canary_flows,
    parse_trend_field_spec,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.obs.trace import (
    SPAN_NAMES,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.registry import (
    ModelRegistry,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "canary_flows.jsonl")


# ------------------------------------------------------------------- fixture
class TestCanaryFixture:
    def test_loads_and_validates(self):
        flows = load_canary_flows(FIXTURE)
        assert len(flows) >= 10
        assert len({f.id for f in flows}) == len(flows)
        presets = {f.preset for f in flows}
        assert presets == {
            "cicids2017", "cicddos2019", "unswnb15", "cicddos2019-mc"
        }
        # Every preset ships benign AND attack truth.
        for p in presets:
            labels = {f.label for f in flows if f.preset == p}
            assert 0 in labels and any(v > 0 for v in labels), p

    def test_mc_preset_is_k_class(self):
        flows = load_canary_flows(FIXTURE, preset="cicddos2019-mc")
        assert {f.class_label for f in flows} >= {"BENIGN", "Syn"}
        assert max(f.label for f in flows) > 1  # class indices, not 0/1
        benign = [f for f in flows if f.label == 0]
        assert all(f.class_label == "BENIGN" for f in benign)

    def test_texts_match_dataset_templates(self):
        for f in load_canary_flows(FIXTURE):
            if f.preset == "unswnb15":
                assert f.text.startswith("Protocol is ")
            else:
                assert f.text.startswith("Destination port is ")
            assert f.text.endswith(".")

    def test_preset_filter_unknown_fails(self):
        with pytest.raises(ValueError, match="no canaries for preset"):
            load_canary_flows(FIXTURE, preset="nope")

    def test_foreign_and_torn_lines_fail_loudly(self, tmp_path):
        p = tmp_path / "c.jsonl"
        p.write_text('{"schema": "other-v1", "id": "x"}\n')
        with pytest.raises(ValueError, match=CANARY_SCHEMA):
            load_canary_flows(str(p))
        p.write_text('{"schema": "' + CANARY_SCHEMA + '", "id":\n')
        with pytest.raises(ValueError, match="not JSON"):
            load_canary_flows(str(p))

    def test_duplicate_id_and_bad_label_fail(self, tmp_path):
        rec = {
            "schema": CANARY_SCHEMA,
            "id": "a",
            "preset": "p",
            "label": 1,
            "text": "t",
        }
        p = tmp_path / "c.jsonl"
        p.write_text(json.dumps(rec) + "\n" + json.dumps(rec) + "\n")
        with pytest.raises(ValueError, match="duplicate"):
            load_canary_flows(str(p))
        bad = dict(rec, label=-1)
        p.write_text(json.dumps(bad) + "\n")
        with pytest.raises(ValueError, match="label"):
            load_canary_flows(str(p))

    def test_missing_field_fails(self, tmp_path):
        p = tmp_path / "c.jsonl"
        p.write_text(
            json.dumps(
                {"schema": CANARY_SCHEMA, "id": "a", "preset": "p", "label": 0}
            )
            + "\n"
        )
        with pytest.raises(ValueError, match="text"):
            load_canary_flows(str(p))


# -------------------------------------------------------------------- prober
def _registry_with_promotion(root, *, round_index=1, seed=0):
    reg = ModelRegistry(str(root))
    rng = np.random.default_rng(seed)
    params = {"w": rng.normal(size=(4,)).astype(np.float32)}
    aid = reg.add(params, round_index=round_index)
    reg.promote(aid, to="serving")
    return reg, aid


def _fake_probe(prob_by_id, round_id, *, latency_s=0.002):
    """probe_fn stub: fixed prob per canary id (by call order), one
    round id on every reply."""

    def fn(host, port, texts, **kw):
        return [
            (
                {
                    "id": i + 1,
                    "prob": prob_by_id[i],
                    "prediction": int(prob_by_id[i] >= 0.5),
                    "round": round_id,
                },
                latency_s,
            )
            for i in range(len(texts))
        ]

    return fn


class TestCanaryProber:
    def test_clean_pass_no_incidents(self, tmp_path):
        reg, _ = _registry_with_promotion(tmp_path / "reg")
        flows = load_canary_flows(FIXTURE, preset="cicids2017")
        probs = [0.1, 0.2, 0.9, 0.8]
        prober = CanaryProber(
            flows,
            "127.0.0.1",
            1,
            registry=reg,
            probe_fn=_fake_probe(probs, round_id=1),
        )
        for _ in range(3):  # stability across repeat passes
            r = prober.probe(now=1000.0)
            assert r["incidents"] == []
            assert r["mismatches"] == 0 and r["flips"] == 0
            assert r["probes"] == len(flows)
            assert r["latency_p99_ms"] == 2.0
        assert r["wrong_label"] == 0

    def test_flip_without_promotion_is_incident(self, tmp_path):
        reg, _ = _registry_with_promotion(tmp_path / "reg")
        flows = load_canary_flows(FIXTURE, preset="unswnb15")
        probs = [0.1, 0.9]
        fn = _fake_probe(probs, round_id=1)
        prober = CanaryProber(
            flows, "127.0.0.1", 1, registry=reg, probe_fn=fn
        )
        assert prober.probe(now=0.0)["flips"] == 0
        probs[0] = 0.1000001  # same artifact, different bits
        r = prober.probe(now=1.0)
        assert r["flips"] == 1
        assert r["incidents"][0]["kind"] == "score-flip"
        assert r["incidents"][0]["canary"] == flows[0].id

    def test_promotion_rekeys_no_false_fire(self, tmp_path):
        reg, _ = _registry_with_promotion(tmp_path / "reg", round_index=1)
        flows = load_canary_flows(FIXTURE, preset="unswnb15")
        probs = [0.1, 0.9]
        prober = CanaryProber(
            flows,
            "127.0.0.1",
            1,
            registry=reg,
            probe_fn=_fake_probe(probs, round_id=1),
        )
        assert prober.probe(now=0.0)["incidents"] == []
        # A NEW artifact is promoted and the replica swaps with it: the
        # scores legitimately change — no incident.
        rng = np.random.default_rng(7)
        aid2 = reg.add(
            {"w": rng.normal(size=(4,)).astype(np.float32)}, round_index=2
        )
        reg.promote(aid2, to="serving")
        prober._probe_fn = _fake_probe([0.4, 0.6], round_id=2)
        r = prober.probe(now=1.0)
        assert r["flips"] == 0 and r["mismatches"] == 0
        assert r["incidents"] == []

    def test_stale_pointer_fires_mismatch(self, tmp_path):
        reg, _ = _registry_with_promotion(tmp_path / "reg", round_index=1)
        flows = load_canary_flows(FIXTURE, preset="unswnb15")
        prober = CanaryProber(
            flows,
            "127.0.0.1",
            1,
            registry=reg,
            probe_fn=_fake_probe([0.1, 0.9], round_id=1),
        )
        assert prober.probe(now=0.0)["mismatches"] == 0
        # Registry advances; the replica keeps answering for round 1.
        rng = np.random.default_rng(8)
        aid2 = reg.add(
            {"w": rng.normal(size=(4,)).astype(np.float32)}, round_index=2
        )
        reg.promote(aid2, to="serving")
        r = prober.probe(now=1.0)
        assert r["mismatches"] == len(flows)
        assert all(
            i["kind"] == "pointer-mismatch"
            and i["reply_round"] == 1
            and i["expected_round"] == 2
            for i in r["incidents"]
        )

    def test_down_tier_counts_failures_never_raises(self):
        flows = load_canary_flows(FIXTURE, preset="unswnb15")

        def boom(*a, **k):
            raise ConnectionRefusedError("down")

        prober = CanaryProber(flows, "127.0.0.1", 1, probe_fn=boom)
        r = prober.probe(now=0.0)
        assert r["failures"] == len(flows)
        assert r["incidents"][0]["kind"] == "probe-failure"

    def test_rejected_reply_counts_not_flips(self):
        flows = load_canary_flows(FIXTURE, preset="unswnb15")

        def fn(host, port, texts, **kw):
            return [
                (
                    {
                        "rejected": True,
                        "code": 2,
                        "reason": "deadline",
                        "prob": float("nan"),
                        "prediction": 0,
                        "round": None,
                    },
                    0.001,
                )
                for _ in texts
            ]

        prober = CanaryProber(flows, "127.0.0.1", 1, probe_fn=fn)
        for _ in range(2):
            r = prober.probe(now=0.0)
        assert r["flips"] == 0  # NaN never enters bit-stability tracking
        assert r["failures"] == len(flows)

    def test_span_names_registered(self):
        assert "canary-probe" in SPAN_NAMES
        assert "sentinel-eval" in SPAN_NAMES
        assert "regression-fire" in SPAN_NAMES


# -------------------------------------------------------------- journal tail
def _write_lines(path, recs):
    with open(path, "a") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


class TestJournalTail:
    def _tail(self, tmp_path, **kw):
        scored = str(tmp_path / "scored.jsonl")
        journal = str(tmp_path / "journal.jsonl")
        open(scored, "w").close()
        open(journal, "w").close()
        monitor = ErrorRateMonitor(
            reference_error=0.05, margin=0.1, min_joined=8
        )
        return (
            JournalTail(scored, journal, monitor=monitor, **kw),
            scored,
            journal,
        )

    def test_joins_in_both_arrival_orders(self, tmp_path):
        tail, scored, journal = self._tail(tmp_path)
        _write_lines(
            scored,
            [{"schema": "fedtpu-scored-v1", "rid": "a", "prob": 0.9}],
        )
        _write_lines(
            journal,
            [
                {"schema": "fedtpu-label-v1", "rid": "a", "label": 1, "ts": 1.0},
                # label BEFORE its score:
                {"schema": "fedtpu-label-v1", "rid": "b", "label": 0, "ts": 2.0},
            ],
        )
        st = tail.poll(now=10.0)
        assert st["joined"] == 1 and st["unmatched_labels"] == 1
        _write_lines(
            scored,
            [{"schema": "fedtpu-scored-v1", "rid": "b", "prob": 0.2}],
        )
        st = tail.poll(now=11.0)
        assert st["joined"] == 2 and st["unmatched_labels"] == 0
        assert st["window_error"] == 0.0  # both predictions correct

    def test_watermark_advances_monotone(self, tmp_path):
        tail, _, journal = self._tail(tmp_path)
        _write_lines(
            journal,
            [
                {"schema": "fedtpu-label-v1", "watermark": 5.0},
                {"schema": "fedtpu-label-v1", "watermark": 3.0},
            ],
        )
        assert tail.poll(now=0.0)["watermark"] == 5.0

    def test_drift_fires_and_journals_verdict(self, tmp_path):
        verdicts = str(tmp_path / "verdicts.jsonl")
        tail, scored, journal = self._tail(
            tmp_path, verdicts_jsonl=verdicts
        )
        # 10 joined flows all WRONG: error 1.0 >> 0.05 + 0.1.
        _write_lines(
            scored,
            [
                {"schema": "fedtpu-scored-v1", "rid": f"r{i}", "prob": 0.9}
                for i in range(10)
            ],
        )
        _write_lines(
            journal,
            [
                {"schema": "fedtpu-label-v1", "rid": f"r{i}", "label": 0, "ts": float(i)}
                for i in range(10)
            ],
        )
        st = tail.poll(now=100.0)
        assert st["verdict"] is not None
        assert st["verdict"]["schema"] == VERDICT_SCHEMA
        assert st["verdict"]["method"] == "error_rate"
        assert st["fires"] == 1
        lines = [
            json.loads(line)
            for line in open(verdicts).read().splitlines()
        ]
        assert len(lines) == 1 and lines[0]["error"] == 1.0
        # Quiet after the fire (window reset, nothing new joined).
        assert tail.poll(now=101.0)["verdict"] is None

    def test_clean_traffic_never_fires(self, tmp_path):
        tail, scored, journal = self._tail(tmp_path)
        _write_lines(
            scored,
            [
                {"schema": "fedtpu-scored-v1", "rid": f"r{i}", "prob": 0.9}
                for i in range(20)
            ],
        )
        _write_lines(
            journal,
            [
                {"schema": "fedtpu-label-v1", "rid": f"r{i}", "label": 1, "ts": float(i)}
                for i in range(20)
            ],
        )
        st = tail.poll(now=0.0)
        assert st["verdict"] is None and st["joined"] == 20


# ------------------------------------------------------------ retention ring
class TestRetentionRing:
    def test_stride_downsampling_and_bound(self, tmp_path):
        ring = RetentionRing(
            str(tmp_path / "ring.jsonl"),
            max_records=8,
            stride=3,
            baseline_n=2,
            window_n=2,
        )
        for i in range(60):
            ring.note({"latency_p99_ms": float(i)}, now=float(i))
        recs = ring.records
        assert len(recs) == 8  # bounded
        assert all(r["schema"] == RING_SCHEMA for r in recs)
        assert all(r["ts"] % 3 == 0 for r in recs)  # every 3rd kept

    def test_disk_compaction_atomic_roll(self, tmp_path):
        path = str(tmp_path / "ring.jsonl")
        ring = RetentionRing(
            path, max_records=4, stride=1, baseline_n=2, window_n=2
        )
        for i in range(40):
            ring.note({"latency_p99_ms": 1.0}, now=float(i))
        n_lines = len(open(path).read().splitlines())
        assert n_lines <= 2 * 4  # file bounded at ~2x the ring
        assert not [
            p for p in os.listdir(tmp_path) if ".tmp." in p
        ]  # roll left no debris

    def test_restart_resumes_pinned_baseline(self, tmp_path):
        path = str(tmp_path / "ring.jsonl")
        ring = RetentionRing(
            path, max_records=16, baseline_n=3, window_n=2
        )
        for i in range(5):
            ring.note({"latency_p99_ms": 10.0}, now=float(i))
        assert ring.baseline_pinned
        ring2 = RetentionRing(
            path, max_records=16, baseline_n=3, window_n=2
        )
        assert ring2.baseline_pinned  # survived the restart
        assert len(ring2.records) == 5

    def test_trend_fires_up_once_per_excursion(self):
        ring = RetentionRing(max_records=64, baseline_n=4, window_n=4)
        for i in range(8):
            ring.note({"latency_p99_ms": 10.0}, now=float(i))
        assert ring.trend() == []  # current window still at baseline
        for i in range(8, 12):
            ring.note({"latency_p99_ms": 100.0}, now=float(i))
        fired = ring.trend()
        assert len(fired) == 1
        f = fired[0]
        assert f["field"] == "latency_p99_ms"
        assert f["baseline"] == 10.0 and f["now"] == 100.0
        assert ring.trend() == []  # one fire per excursion, not per tick
        # Recovery re-arms...
        for i in range(12, 20):
            ring.note({"latency_p99_ms": 10.0}, now=float(i))
        assert ring.trend() == []
        # ...and a second excursion fires again.
        for i in range(20, 24):
            ring.note({"latency_p99_ms": 100.0}, now=float(i))
        assert len(ring.trend()) == 1

    def test_cadence_regresses_downward(self):
        ring = RetentionRing(max_records=64, baseline_n=3, window_n=3)
        for i in range(6):
            ring.note({"round_cadence": 2.0}, now=float(i))
        for i in range(6, 9):
            ring.note({"round_cadence": 0.1}, now=float(i))
        fired = ring.trend()
        assert [f["field"] for f in fired] == ["round_cadence"]
        assert fired[0]["direction"] == "down"

    def test_no_baseline_no_verdict(self):
        ring = RetentionRing(max_records=16, baseline_n=8, window_n=4)
        for i in range(5):
            ring.note({"latency_p99_ms": 500.0}, now=float(i))
        assert ring.trend() == []  # baseline still filling

    def test_always_slow_fleet_never_self_regresses(self):
        ring = RetentionRing(max_records=64, baseline_n=4, window_n=4)
        for i in range(40):
            ring.note({"latency_p99_ms": 400.0}, now=float(i))
        assert ring.trend() == []

    def test_bad_config_fails(self):
        with pytest.raises(ValueError, match="max_records"):
            RetentionRing(max_records=2, baseline_n=8, window_n=4)
        with pytest.raises(ValueError, match="stride"):
            RetentionRing(max_records=16, stride=0)


# --------------------------------------------------- hub rotation + verdict
class TestSnapshotRotation:
    def test_bounded_snapshot_rolls_atomically(self, tmp_path):
        path = str(tmp_path / "snap.jsonl")
        hub = ScrapeHub(
            [Target(tier="serve", host="127.0.0.1", port=1)],
            snapshot_jsonl=path,
            snapshot_max_mb=0.001,  # ~1 KB: a few polls cross it
            scrape_timeout_s=0.05,
        )
        for i in range(8):
            hub.poll(now=float(i))
        assert os.path.exists(path + ".1")  # rolled generation
        live = os.path.getsize(path)
        assert live <= 2 * 1024 * 1024
        # Both generations hold intact JSON lines (atomic roll).
        for p in (path, path + ".1"):
            for line in open(p).read().splitlines():
                assert json.loads(line)["schema"] == "fedtpu-fleet-v1"

    def test_unbounded_default_unchanged(self, tmp_path):
        path = str(tmp_path / "snap.jsonl")
        hub = ScrapeHub(
            [Target(tier="serve", host="127.0.0.1", port=1)],
            snapshot_jsonl=path,
            scrape_timeout_s=0.05,
        )
        hub.poll(now=0.0)
        hub.poll(now=1.0)
        assert not os.path.exists(path + ".1")
        assert len(open(path).read().splitlines()) == 2

    def test_bad_cap_fails(self):
        with pytest.raises(ValueError, match="snapshot_max_mb"):
            ScrapeHub(
                [Target(tier="serve", host="127.0.0.1", port=1)],
                snapshot_jsonl="x.jsonl",
                snapshot_max_mb=0.0,
            )


class TestHealthVerdict:
    def test_mirrors_snapshot_judgement(self, tmp_path):
        hub = ScrapeHub(
            [Target(tier="serve", host="127.0.0.1", port=1)],
            scrape_timeout_s=0.05,
        )
        snap = hub.poll(now=0.0)
        v = health_verdict(snap)
        assert v["schema"] == HEALTH_SCHEMA
        assert v["healthy"] is False  # the target is down
        assert v["targets"] == 1 and v["targets_up"] == 0
        assert v["targets_down"][0]["tier"] == "serve"
        assert v["slo_firing"] == []
        json.dumps(v)  # fully serializable for cron/CI consumers

    def test_healthy_shape(self):
        v = health_verdict(
            {
                "ts": 1.0,
                "targets": [
                    {
                        "tier": "serve",
                        "instance": "h:1",
                        "up": True,
                        "error": None,
                    }
                ],
                "slo": [
                    {
                        "slo": "x",
                        "instance": "h:1",
                        "firing": False,
                        "severity": "page",
                        "burn": {},
                    }
                ],
                "scrape_lag_ms": 1.5,
            }
        )
        assert v["healthy"] is True
        assert v["slo_total"] == 1 and v["notable"] == []


# -------------------------------------------------------------- sentinel link
class TestSentinelLink:
    def test_skips_preexisting_verdicts(self, tmp_path):
        path = str(tmp_path / "verdicts.jsonl")
        old = {
            "schema": VERDICT_SCHEMA,
            "drift": 0.5,
            "method": "error_rate",
            "scores": 64,
        }
        _write_lines(path, [old])
        link = SentinelLink(path)
        assert link.poll() is None  # history is not a fresh trigger
        new = dict(old, drift=0.7)
        _write_lines(path, [new])
        got = link.poll()
        assert got is not None and got["drift"] == 0.7
        assert link.poll() is None  # consumed

    def test_missing_file_then_created(self, tmp_path):
        path = str(tmp_path / "nope.jsonl")
        link = SentinelLink(path)
        assert link.poll() is None
        _write_lines(
            path,
            [{"schema": VERDICT_SCHEMA, "drift": 0.1, "method": "error_rate"}],
        )
        assert link.poll()["drift"] == 0.1

    def test_foreign_and_torn_lines_skipped(self, tmp_path):
        path = str(tmp_path / "verdicts.jsonl")
        open(path, "w").close()
        link = SentinelLink(path)
        with open(path, "a") as f:
            f.write('{"schema": "other"}\n')
            f.write("not json\n")
            f.write(
                json.dumps(
                    {
                        "schema": VERDICT_SCHEMA,
                        "drift": 0.3,
                        "method": "error_rate",
                    }
                )
                + "\n"
            )
            f.write('{"torn')  # no newline — waits for the next poll
        got = link.poll()
        assert got["drift"] == 0.3 and link.seen == 1

    def test_latest_verdict_wins_per_poll(self, tmp_path):
        path = str(tmp_path / "verdicts.jsonl")
        open(path, "w").close()
        link = SentinelLink(path)
        _write_lines(
            path,
            [
                {"schema": VERDICT_SCHEMA, "drift": d, "method": "error_rate"}
                for d in (0.1, 0.2, 0.3)
            ],
        )
        assert link.poll()["drift"] == 0.3  # one trigger answers all


# ---------------------------------------------------------------- composition
class TestSentinelComposition:
    def test_tick_report_and_counters(self, tmp_path):
        flows = load_canary_flows(FIXTURE, preset="unswnb15")
        probs = [0.1, 0.9]
        fn = _fake_probe(probs, round_id=None)
        prober = CanaryProber(flows, "127.0.0.1", 1, probe_fn=fn)
        ring = RetentionRing(max_records=16, baseline_n=2, window_n=2)
        alerts = str(tmp_path / "alerts.jsonl")
        s = Sentinel(prober=prober, ring=ring, alerts_jsonl=alerts)
        r1 = s.tick(now=0.0)
        assert r1["schema"] == SENTINEL_SCHEMA and r1["tick"] == 1
        assert r1["counters"]["canary_flips"] == 0
        probs[1] = 0.90001  # unexplained flip
        r2 = s.tick(now=1.0)
        assert r2["counters"]["canary_flips"] == 1
        assert s.render_status(r2)  # renders without KeyError

    def test_regression_fire_emits_alert(self, tmp_path):
        flows = load_canary_flows(FIXTURE, preset="unswnb15")
        lat = [0.002]

        def fn(host, port, texts, **kw):
            return [
                (
                    {"prob": 0.5, "prediction": 1, "round": None},
                    lat[0],
                )
                for _ in texts
            ]

        prober = CanaryProber(flows, "127.0.0.1", 1, probe_fn=fn)
        ring = RetentionRing(max_records=32, baseline_n=3, window_n=3)
        alerts = str(tmp_path / "alerts.jsonl")
        s = Sentinel(prober=prober, ring=ring, alerts_jsonl=alerts)
        for i in range(6):
            s.tick(now=float(i))
        lat[0] = 0.2  # 100x latency step
        fired = 0
        for i in range(6, 10):
            fired += len(s.tick(now=float(i))["regressions"])
        assert fired == 1
        assert s.regression_fires == 1
        evs = [
            json.loads(line) for line in open(alerts).read().splitlines()
        ]
        assert evs[0]["slo"] == "sentinel-regression"
        assert evs[0]["severity"] == "page"
        assert evs[0]["evidence"]["field"] == "latency_p99_ms"

    def test_needs_at_least_one_rung(self):
        with pytest.raises(ValueError, match="at least one rung"):
            Sentinel()

    def test_drift_rung_feeds_counters(self, tmp_path):
        scored = str(tmp_path / "scored.jsonl")
        journal = str(tmp_path / "journal.jsonl")
        verdicts = str(tmp_path / "verdicts.jsonl")
        open(scored, "w").close()
        open(journal, "w").close()
        monitor = ErrorRateMonitor(
            reference_error=0.05, margin=0.1, min_joined=8
        )
        tail = JournalTail(
            scored, journal, monitor=monitor, verdicts_jsonl=verdicts
        )
        s = Sentinel(tail=tail, ring=None, alerts_jsonl=None)
        _write_lines(
            scored,
            [
                {"schema": "fedtpu-scored-v1", "rid": f"r{i}", "prob": 0.9}
                for i in range(10)
            ],
        )
        _write_lines(
            journal,
            [
                {"schema": "fedtpu-label-v1", "rid": f"r{i}", "label": 0, "ts": float(i)}
                for i in range(10)
            ],
        )
        r = s.tick(now=0.0)
        assert r["drift"]["verdict"] is not None
        assert r["counters"]["drift_fires"] == 1
        # The verdicts file now feeds a SentinelLink end to end.
        link_path_had_content = os.path.getsize(verdicts) > 0
        assert link_path_had_content


# -------------------------------------------------------- custom trend fields
class TestCustomTrendFields:
    def test_parse_trend_field_spec(self):
        assert parse_trend_field_spec("my_counter") == (
            "my_counter", (1.5, 0.0, "up"),
        )
        assert parse_trend_field_spec(
            "fedtpu_server_stream_fallbacks_total:down"
        ) == ("fedtpu_server_stream_fallbacks_total", (1.5, 0.0, "down"))
        with pytest.raises(ValueError, match="NAME"):
            parse_trend_field_spec(":up")
        with pytest.raises(ValueError, match="up.down"):
            parse_trend_field_spec("x:sideways")

    def test_custom_field_rides_snapshot_cadence_and_fires(self, tmp_path):
        """A --trend-field counter is pulled from the fleet snapshot's
        per-target cadence dicts (max across targets) into the ring row
        and judged by the same baseline/window arithmetic as the stock
        fields — a rate step past baseline*ratio fires exactly once."""
        name, entry = parse_trend_field_spec(
            "fedtpu_server_stream_fallbacks_total"
        )
        ring = RetentionRing(
            max_records=32, baseline_n=3, window_n=3,
            trend_fields={**DEFAULT_TREND_FIELDS, name: entry},
        )
        rate = [1.0]

        class FakeHub:
            def poll(self, *, now):
                return {
                    "targets": [
                        {"up": True, "cadence": {name: rate[0] / 2}},
                        # Hottest instance wins the row.
                        {"up": True, "cadence": {name: rate[0]}},
                        {"up": True, "cadence": {}},  # quiet: no sample
                    ],
                    "slo": [],
                }

        s = Sentinel(
            ring=ring, hub=FakeHub(),
            alerts_jsonl=str(tmp_path / "alerts.jsonl"),
        )
        for i in range(6):
            assert s.tick(now=float(i))["regressions"] == []
        rate[0] = 100.0
        fired = []
        for i in range(6, 10):
            fired += s.tick(now=float(i))["regressions"]
        assert [f["field"] for f in fired] == [name]
        assert fired[0]["direction"] == "up"
        # Baseline mean is the hottest target's 1.0 (max across targets
        # — the half-rate sibling never drags it to 0.5), and the fire
        # crossed baseline * ratio.
        assert fired[0]["baseline"] == 1.0
        assert fired[0]["now"] > 1.5
