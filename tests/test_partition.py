"""data/partition.py: seeded non-IID partitioners + the manifest.

The satellite contract (ISSUE 6): same seed => identical per-client
index sets across runs AND across both deployment tiers (the mesh tier
and the TCP tier shard through the same partition_indices), and the
manifest's label histograms sum to the source split.
"""

import json

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    make_all_client_splits,
    partition_indices,
    partition_manifest,
    quantity_skew_indices,
    save_manifest,
)


def _labels(n=400, seed=0):
    return (np.random.default_rng(seed).random(n) < 0.3).astype(np.int64)


def _dirichlet_cfg(**kw):
    kw.setdefault("partition", "dirichlet")
    kw.setdefault("data_fraction", 0.25)
    kw.setdefault("dirichlet_alpha", 0.1)
    kw.setdefault("seed_base", 11)
    return DataConfig(**kw)


def test_dirichlet_same_seed_identical_index_sets():
    """Same seed => bit-identical per-client index sets on repeated runs
    (fresh config objects, fresh rng) — the determinism the scenario
    runner's clean-run replay and the cross-tier contract both rest on."""
    labels = _labels()
    a = partition_indices(labels, 4, _dirichlet_cfg())
    b = partition_indices(labels, 4, _dirichlet_cfg())
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    # A different seed genuinely repartitions.
    c = partition_indices(labels, 4, _dirichlet_cfg(seed_base=12))
    assert any(
        len(x) != len(y) or not np.array_equal(x, y) for x, y in zip(a, c)
    )


def test_dirichlet_identical_across_deployment_tiers():
    """Both tiers funnel through make_all_client_splits (cli/common.py
    _load_client_splits serves `federated` AND `client`): the per-client
    ROW SETS it produces must equal the raw partition_indices output for
    the same config — client i holds the same rows no matter which tier
    trains it."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        make_synthetic,
    )

    df = make_synthetic("cicids2017", 240, seed=3)
    cfg = _dirichlet_cfg(data_fraction=0.25)
    labels = (df["Label"] == "DDoS").to_numpy().astype(np.int64)
    parts = partition_indices(labels, 4, cfg)
    splits = make_all_client_splits(df, 4, cfg)
    for cid, (idx, sp) in enumerate(zip(parts, splits)):
        # The split re-shuffles rows into train/val/test, so compare the
        # CLIENT'S total label multiset against its assigned rows.
        got = np.sort(
            np.concatenate(
                [sp.train.labels, sp.val.labels, sp.test.labels]
            )
        )
        np.testing.assert_array_equal(got, np.sort(labels[idx]))
        assert sp.client_id == cid


def test_manifest_histograms_sum_to_source_split(tmp_path):
    """With data_fraction covering the whole dataset (frac * C = 1), the
    dirichlet manifest's per-class histogram sums equal the source's
    class counts exactly, and assigned_rows == total_rows (allowing the
    per-class >=1 floor to never fire on this data)."""
    labels = _labels(n=500, seed=1)
    cfg = _dirichlet_cfg(data_fraction=0.25)
    parts = partition_indices(labels, 4, cfg)
    man = partition_manifest(
        [labels[i] for i in parts], cfg=cfg, total_rows=len(labels)
    )
    assert man["assigned_rows"] == len(labels)
    for cls in (0, 1):
        total = sum(
            c["label_hist"][str(cls)] for c in man["clients"]
        )
        assert total == int((labels == cls).sum())
    assert sum(c["rows"] for c in man["clients"]) == len(labels)
    # JSON round-trip (the artifact cli/common.py writes).
    path = save_manifest(man, str(tmp_path / "m" / "manifest.json"))
    with open(path) as f:
        assert json.load(f) == man


def test_quantity_skew_disjoint_and_skewed():
    """Quantity skew: disjoint shards covering frac*n*C rows, every
    client >= 1 row, sizes genuinely skewed at small alpha, and the
    label MIX stays roughly representative (it is a size skew, not a
    label skew)."""
    n = 1000
    rng = np.random.default_rng(0)
    parts = quantity_skew_indices(
        n, 5, alpha=0.3, data_fraction=0.2, rng=rng
    )
    sizes = [len(p) for p in parts]
    assert sum(sizes) == n
    assert min(sizes) >= 1
    assert max(sizes) >= 3 * min(sizes)  # alpha=0.3 must actually skew
    allidx = np.concatenate(parts)
    assert len(np.unique(allidx)) == len(allidx)  # disjoint


def test_quantity_scheme_deterministic_via_config():
    labels = _labels(n=300)
    cfg = DataConfig(
        partition="quantity", data_fraction=0.25, dirichlet_alpha=0.2,
        seed_base=5,
    )
    a = partition_indices(labels, 4, cfg)
    b = partition_indices(labels, 4, cfg)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_quantity_infeasible_fractions_refused():
    rng = np.random.default_rng(0)
    with pytest.raises(ValueError, match="infeasible"):
        quantity_skew_indices(
            100, 4, alpha=1.0, data_fraction=0.5, rng=rng
        )
    with pytest.raises(ValueError, match="one row each"):
        quantity_skew_indices(
            2, 4, alpha=1.0, data_fraction=0.25, rng=rng
        )


def test_unknown_partition_scheme_fails_at_config_time():
    with pytest.raises(ValueError, match="unknown partition"):
        DataConfig(partition="bogus")
