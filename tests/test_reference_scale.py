"""The reference-scale artifact chain (VERDICT round-1 item 7): a
30522-token vocab + DistilBERT-base encoder through
local -> federated -> export-hf -> transformers reload -> predict.

The environment has no real pretrained weights (zero egress), so this is
the closest demonstrable stand-in for the reference's pretrained run (its
hard-required ./distilbert-base-uncased, client1.py:56,357): the same
vocab size, the same architecture, the same artifact formats, every hop
exercised at full scale — only the encoder weights are random."""

import json
import os

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.cli import (
    main,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    write_synthetic_csv,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.tokenizer import (
    build_reference_scale_vocab,
)

transformers = pytest.importorskip("transformers")


def test_reference_scale_vocab_layout():
    vocab = build_reference_scale_vocab()
    assert len(vocab) == 30522
    assert vocab[0] == "[PAD]"
    assert len(set(vocab)) == 30522
    # Flow templates tokenize with zero UNKs and realistic numerals.
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.tokenizer import (
        WordPieceTokenizer,
    )

    tok = WordPieceTokenizer(vocab)
    ids = tok.encode("Flow bytes per second are 70759.2337. Flow packets per second are 36.2252.")
    assert tok.unk_id not in ids


@pytest.mark.slow
def test_reference_scale_artifact_chain(tmp_path):
    """local -> federated -> export-hf -> transformers -> predict, all at
    DistilBERT-base scale (30522 vocab, 6L/768/12H, 66M params)."""
    torch = pytest.importorskip("torch")

    # The reference's input artifact: an HF DistilBERT checkpoint dir with
    # the REAL vocab size (random weights — no egress for the real ones).
    hf = tmp_path / "distilbert-base"
    cfg = transformers.DistilBertConfig()  # stock: 30522/768/6L/12H
    torch.manual_seed(0)
    transformers.DistilBertModel(cfg).save_pretrained(str(hf))
    vocab = build_reference_scale_vocab(cfg.vocab_size)
    (hf / "vocab.txt").write_text("\n".join(vocab) + "\n")

    csv = tmp_path / "flows.csv"
    write_synthetic_csv(str(csv), n_rows=80, seed=31)

    # 1) Single-client fine-tune from the "pretrained" encoder.
    local_ckpt = tmp_path / "local_ckpt"
    assert (
        main(
            [
                "local", "--hf-dir", str(hf), "--csv", str(csv),
                "--data-fraction", "0.6", "--epochs", "1",
                "--batch-size", "8", "--max-len", "64",
                "--checkpoint-dir", str(local_ckpt),
                "--output-dir", str(tmp_path / "local_out"),
            ]
        )
        == 0
    )
    assert (tmp_path / "local_out" / "client0_local_metrics.csv").exists()

    # 2) Two-client federated round from the same encoder.
    fed_ckpt = tmp_path / "fed_ckpt"
    assert (
        main(
            [
                "federated", "--hf-dir", str(hf), "--csv", str(csv),
                "--num-clients", "2", "--rounds", "1", "--epochs", "1",
                "--partition", "disjoint", "--data-fraction", "0.4",
                "--batch-size", "8", "--max-len", "64",
                "--checkpoint-dir", str(fed_ckpt),
                "--output-dir", str(tmp_path / "fed_out"),
            ]
        )
        == 0
    )

    # 3) Export the federated aggregate to the HF layout.
    exported = tmp_path / "exported"
    assert (
        main(
            ["export-hf", "--hf-dir", str(hf), "--checkpoint-dir",
             str(fed_ckpt), "--out", str(exported)]
        )
        == 0
    )
    hf_cfg = json.load(open(exported / "config.json"))
    assert hf_cfg["vocab_size"] == 30522 and hf_cfg["dim"] == 768
    assert len((exported / "vocab.txt").read_text().splitlines()) == 30522

    # 4) transformers itself loads the exported 66M-param encoder.
    reloaded = transformers.DistilBertModel.from_pretrained(str(exported))
    assert reloaded.config.vocab_size == 30522
    emb = reloaded.state_dict()["embeddings.word_embeddings.weight"]
    assert tuple(emb.shape) == (30522, 768)

    # 5) predict consumes the exported checkpoint (trained head included).
    preds = tmp_path / "preds.csv"
    assert (
        main(
            ["predict", "--csv", str(csv), "--hf-dir", str(exported),
             "--max-len", "64", "--output", str(preds)]
        )
        == 0
    )
    import pandas as pd

    df = pd.read_csv(preds)
    assert len(df) == 80
    assert df["prob_attack"].between(0, 1).all()
    assert np.isfinite(df["prob_attack"]).all()
