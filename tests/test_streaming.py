"""Streaming CSV -> token pipeline (data/streaming.py): two passes, chunked,
must reproduce the in-memory path exactly for the index-based partitions."""

import numpy as np
import pytest

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    default_tokenizer,
    load_flow_csv,
    make_all_client_splits,
    stream_client_tokens,
    tokenize_client,
    write_synthetic_csv,
)

MAX_LEN = 48


@pytest.fixture(scope="module")
def tok():
    return default_tokenizer()


def _inmemory(csv_path, cfg, num_clients, tok):
    df = load_flow_csv(csv_path)
    splits = make_all_client_splits(df, num_clients, cfg)
    return [tokenize_client(s, tok, max_len=cfg.max_len) for s in splits]


def _assert_clients_equal(a, b):
    assert len(a) == len(b)
    for ca, cb in zip(a, b):
        for name in ("train", "val", "test"):
            sa, sb = getattr(ca, name), getattr(cb, name)
            np.testing.assert_array_equal(sa.labels, sb.labels, err_msg=name)
            np.testing.assert_array_equal(sa.input_ids, sb.input_ids, err_msg=name)
            np.testing.assert_array_equal(
                sa.attention_mask, sb.attention_mask, err_msg=name
            )


@pytest.mark.parametrize("partition", ["disjoint", "dirichlet"])
def test_streaming_matches_inmemory(tmp_path, tok, partition):
    """Clean data (no ±inf/NaN): the streamed arrays must be bit-identical
    to the in-memory path, across chunk boundaries."""
    path = tmp_path / f"{partition}.csv"
    write_synthetic_csv(
        str(path), n_rows=600, seed=5, inf_fraction=0.0, nan_fraction=0.0
    )
    cfg = DataConfig(partition=partition, data_fraction=0.3, max_len=MAX_LEN)
    want = _inmemory(str(path), cfg, 2, tok)
    got = stream_client_tokens(str(path), cfg, 2, tok, chunk_rows=97)
    _assert_clients_equal(got, want)


def test_streaming_sample_partition_matches_corpus_convention(tmp_path, tok):
    """'sample' uses index-permutation sampling (the corpus convention);
    sizes follow data_fraction and clients may overlap."""
    path = tmp_path / "s.csv"
    write_synthetic_csv(str(path), n_rows=400, seed=6)
    cfg = DataConfig(partition="sample", data_fraction=0.25, max_len=MAX_LEN)
    clients = stream_client_tokens(str(path), cfg, 3, tok, chunk_rows=111)
    for c in clients:
        assert len(c.train) + len(c.val) + len(c.test) == 100
        assert c.train.input_ids.shape[1] == MAX_LEN


def test_streaming_imputes_with_global_means(tmp_path, tok):
    """±inf/NaN rows still tokenize (imputed with pass-1 global means) and
    labels survive; rows free of bad values match the in-memory path."""
    path = tmp_path / "noisy.csv"
    write_synthetic_csv(
        str(path), n_rows=300, seed=7, inf_fraction=0.05, nan_fraction=0.05
    )
    cfg = DataConfig(partition="disjoint", data_fraction=0.5, max_len=MAX_LEN)
    want = _inmemory(str(path), cfg, 2, tok)
    got = stream_client_tokens(str(path), cfg, 2, tok, chunk_rows=64)
    for ca, cb in zip(got, want):
        for name in ("train", "val", "test"):
            sa, sb = getattr(ca, name), getattr(cb, name)
            np.testing.assert_array_equal(sa.labels, sb.labels)
            assert sa.input_ids.shape == sb.input_ids.shape
            # Identical for the vast majority of rows (the rest can differ
            # in the last float digit of an imputed value because pandas'
            # pairwise mean and the streaming chunk-sum mean round
            # differently).
            same = (sa.input_ids == sb.input_ids).all(axis=1).mean()
            assert same > 0.7, same
            # Every row tokenized (CLS at position 0, nothing left empty).
            assert (sa.input_ids[:, 0] == tok.cls_id).all()


def test_streaming_pins_whole_file_dtypes(tmp_path, tok):
    """One NaN in a LATE chunk floats the whole column under pandas'
    whole-file inference ('443' renders as '443.0' everywhere). The
    streamed reader must pin that dtype from pass 1 so early, NaN-free
    chunks tokenize identically to the in-memory path."""
    import pandas as pd

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        make_synthetic_flows,
    )

    df = make_synthetic_flows(200, seed=9, inf_fraction=0.0, nan_fraction=0.0)
    assert df["Destination Port"].dtype == np.int64
    df.loc[df.index[-1], "Destination Port"] = np.nan  # floats the column
    path = tmp_path / "late_nan.csv"
    df.to_csv(path, index=False)
    assert pd.read_csv(path)["Destination Port"].dtype == np.float64

    cfg = DataConfig(partition="disjoint", data_fraction=0.5, max_len=MAX_LEN)
    want = _inmemory(str(path), cfg, 2, tok)
    # chunk_rows=50: the NaN sits in the final chunk; earlier chunks would
    # infer int64 on their own.
    got = stream_client_tokens(str(path), cfg, 2, tok, chunk_rows=50)
    _assert_clients_equal(got, want)


def test_streaming_unsw_schema(tmp_path, tok):
    path = tmp_path / "unsw.csv"
    write_synthetic_csv(str(path), dataset="unswnb15", n_rows=300, seed=8)
    cfg = DataConfig(
        dataset="unswnb15", partition="disjoint", data_fraction=0.5, max_len=MAX_LEN
    )
    want = _inmemory(str(path), cfg, 2, tok)
    got = stream_client_tokens(str(path), cfg, 2, tok, chunk_rows=50)
    _assert_clients_equal(got, want)


def test_stream_subset_matches_full_run(tmp_path, tok):
    """stream_client_tokens_for materializes only the requested clients but
    plans globally: the subset's arrays are bit-identical to the full run's,
    and the returned sizes cover every client (the multi-host contract)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        stream_client_tokens_for,
    )

    csv = str(tmp_path / "flows.csv")
    write_synthetic_csv(csv, n_rows=400, seed=9)
    cfg = DataConfig(
        data_fraction=0.25, max_len=MAX_LEN, partition="disjoint"
    )
    full = stream_client_tokens(csv, cfg, 4, tok, max_len=MAX_LEN, chunk_rows=97)
    subset, sizes = stream_client_tokens_for(
        csv, cfg, 4, tok, [1, 3], max_len=MAX_LEN, chunk_rows=97
    )
    assert [c.client_id for c in subset] == [1, 3]
    assert len(sizes) == 4
    for cid, got in zip([1, 3], subset):
        want = full[cid]
        for name in ("train", "val", "test"):
            sa, sb = getattr(got, name), getattr(want, name)
            np.testing.assert_array_equal(sa.input_ids, sb.input_ids)
            np.testing.assert_array_equal(sa.attention_mask, sb.attention_mask)
            np.testing.assert_array_equal(sa.labels, sb.labels)
    for cid in range(4):
        for name in ("train", "val", "test"):
            assert sizes[cid][name] == len(getattr(full[cid], name))
    with pytest.raises(ValueError, match="client_ids"):
        stream_client_tokens_for(csv, cfg, 4, tok, [4], max_len=MAX_LEN)
