"""End-to-end federated training on a faked 8-device CPU mesh.

This is the TPU analogue of the reference's only integration evidence (the
2-client golden run logs): N clients train on private shards, FedAvg
aggregates, and the aggregated model must not regress vs local models —
the reference's headline result (99.09% local -> 99.93% aggregated)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
    default_tokenizer,
    make_all_client_splits,
    make_synthetic_flows,
    stack_clients,
    tokenize_client,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train import (
    FederatedTrainer,
    federated_batches,
    stack_eval_splits,
)

MAX_LEN = 64


@pytest.fixture(scope="module")
def tok():
    return default_tokenizer()


def _cfg(tok, clients=2, data=1, **fed_kw):
    return ExperimentConfig(
        model=ModelConfig.tiny(
            vocab_size=len(tok), max_len=MAX_LEN, max_position_embeddings=MAX_LEN,
            dim=64, n_layers=2, n_heads=4, hidden_dim=128,
        ),
        data=DataConfig(data_fraction=0.45, max_len=MAX_LEN, batch_size=16),
        train=TrainConfig(learning_rate=1e-3, epochs_per_round=1, seed=0),
        fed=FedConfig(num_clients=clients, **fed_kw),
        mesh=MeshConfig(clients=clients, data=data),
    )


@pytest.fixture(scope="module")
def fed_data(tok):
    df = make_synthetic_flows(2400, seed=11)
    cfg = DataConfig(data_fraction=0.45, max_len=MAX_LEN)
    splits = make_all_client_splits(df, 2, cfg)
    clients = [tokenize_client(s, tok, max_len=MAX_LEN) for s in splits]
    stacked_train = stack_clients([c.train for c in clients])
    return clients, stacked_train


def test_federated_batches_per_client_shuffles(fed_data):
    _, stacked = fed_data
    batches = list(federated_batches(stacked, 16, seed=0, epoch=0))
    C, N = stacked.labels.shape
    assert len(batches) == N // 16
    b0 = batches[0]
    assert b0["input_ids"].shape == (C, 16, MAX_LEN)
    assert not np.array_equal(b0["labels"][0], b0["labels"][1])
    again = list(federated_batches(stacked, 16, seed=0, epoch=0))
    np.testing.assert_array_equal(b0["labels"], again[0]["labels"])  # deterministic
    other = list(federated_batches(stacked, 16, seed=0, epoch=1))
    assert not np.array_equal(b0["labels"], other[0]["labels"])  # epoch decorrelated


def test_stack_eval_splits_counts(fed_data, tok):
    clients, _ = fed_data
    splits = [c.val for c in clients]
    stacked, valid = stack_eval_splits(splits, 16, pad_id=tok.pad_id)
    assert valid.shape == stacked.labels.shape
    for c, s in enumerate(splits):
        assert valid[c].sum() == len(s)


def test_two_client_federation_end_to_end(tok, fed_data, eight_devices):
    clients, stacked_train = fed_data
    cfg = _cfg(tok, clients=2, data=2)
    trainer = FederatedTrainer(cfg, pad_id=tok.pad_id)
    state = trainer.init_state()
    test_splits = [c.test for c in clients]

    state, history = trainer.run(state, stacked_train, test_splits, rounds=2)
    assert len(history) == 2
    last = history[-1]
    for c in range(2):
        assert last.aggregated_metrics[c]["Accuracy"] > 90.0
    # aggregated params are identical across clients after FedAvg
    p = np.asarray(jax.tree.leaves(state.params)[0])
    np.testing.assert_allclose(p[0], p[1], atol=1e-6)
    # losses decrease across rounds
    assert history[1].epoch_losses.mean() < history[0].epoch_losses.mean()


def test_aggregated_not_worse_than_local_fast_anchor(tok, eight_devices):
    """Fast-lane, ZERO-slack anchor for the headline parity property
    (VERDICT r5 weak #6): aggregation must not regress any client's test
    accuracy. Tiny model, 300 train rows per client, 2 epochs, one round
    — the run converges to 100/100 locally and 100/100 aggregated on
    this separable config (measured on the CPU mesh), so `agg >= local`
    binds with no tolerance while staying far cheaper than the slow-lane
    convergence pins."""
    L = 32
    df = make_synthetic_flows(1000, seed=11)
    dcfg = DataConfig(data_fraction=0.5, max_len=L, batch_size=16)
    splits = make_all_client_splits(df, 2, dcfg)
    clients = [tokenize_client(s, tok, max_len=L) for s in splits]
    stacked_train = stack_clients([c.train for c in clients])
    cfg = ExperimentConfig(
        model=ModelConfig.tiny(
            vocab_size=len(tok), max_len=L, max_position_embeddings=L,
            dim=64, n_layers=2, n_heads=4, hidden_dim=128,
        ),
        data=dcfg,
        train=TrainConfig(
            learning_rate=2e-3, epochs_per_round=2, seed=0, log_every=0
        ),
        fed=FedConfig(num_clients=2, rounds=1),
        mesh=MeshConfig(clients=2, data=1),
    )
    trainer = FederatedTrainer(cfg, pad_id=tok.pad_id)
    state = trainer.init_state()
    state, history = trainer.run(
        state, stacked_train, [c.test for c in clients], rounds=1
    )
    rec = history[-1]
    for c in range(2):
        local = rec.local_metrics[c]["Accuracy"]
        agg = rec.aggregated_metrics[c]["Accuracy"]
        assert agg >= local, (c, local, agg)  # zero slack
        # Convergence, not just non-regression: the config separates.
        assert local >= 95.0 and agg >= 95.0, (c, local, agg)


@pytest.mark.slow
def test_federation_not_worse_than_local(tok, fed_data, eight_devices):
    """The reference's headline property: aggregation helps each client's
    test metrics — aggregated >= local, NO slack (the run lands 100/100
    on this separable config; the old -5.0 tolerance could have hidden a
    real regression)."""
    clients, stacked_train = fed_data
    cfg = _cfg(tok, clients=2)
    trainer = FederatedTrainer(cfg, pad_id=tok.pad_id)
    state = trainer.init_state()
    state, history = trainer.run(state, stacked_train, [c.test for c in clients])
    rec = history[-1]
    for c in range(2):
        assert (
            rec.aggregated_metrics[c]["Accuracy"]
            >= rec.local_metrics[c]["Accuracy"]
        )


@pytest.mark.slow
def test_convergence_accuracy_parity_pin(tok, eight_devices):
    """THE accuracy-parity pin (VERDICT r4 #5): the reference's headline
    behavior is >=99% test accuracy with aggregation IMPROVING each
    client (client1 local 99.09 -> aggregated 99.93,
    reference client1_local_metrics.csv:2 ->
    client1_aggregated_metrics.csv:2). Reproduce the shape on separable
    synthetic flows: 3 federated rounds reach >=99% local test accuracy
    per client with aggregated strictly >= local, every round's
    aggregate >= 99.5%, and F1 tracking the reference's >= 0.99."""
    L = 32  # own length: the pinned trajectory was measured at L=32
    df = make_synthetic_flows(3200, seed=11)
    dcfg = DataConfig(data_fraction=0.6, max_len=L)
    splits = make_all_client_splits(df, 2, dcfg)
    clients = [tokenize_client(s, tok, max_len=L) for s in splits]
    stacked_train = stack_clients([c.train for c in clients])
    cfg = ExperimentConfig(
        model=ModelConfig.tiny(
            vocab_size=len(tok), max_len=L,
            max_position_embeddings=L,
            dim=64, n_layers=2, n_heads=4, hidden_dim=128,
        ),
        data=DataConfig(data_fraction=0.6, max_len=L, batch_size=16),
        train=TrainConfig(learning_rate=1e-3, epochs_per_round=1, seed=0),
        fed=FedConfig(num_clients=2, rounds=3),
        mesh=MeshConfig(clients=2, data=1),
    )
    trainer = FederatedTrainer(cfg, pad_id=tok.pad_id)
    state = trainer.init_state()
    state, history = trainer.run(
        state, stacked_train, [c.test for c in clients], rounds=3
    )
    assert len(history) == 3
    # One misclassified test sample's worth of accuracy — the tolerance
    # granted to INTERMEDIATE rounds only (platform numeric drift); the
    # final round is held to the reference's strict shape.
    one_sample = 100.0 / min(len(c.test) for c in clients)
    for rec in history:
        final = rec is history[-1]
        for c in range(2):
            local = rec.local_metrics[c]
            agg = rec.aggregated_metrics[c]
            slack = 0.0 if final else one_sample
            # Aggregation helps (or ties): the reference's 99.09 -> 99.93
            # shape — zero slack at the final evaluation.
            assert agg["Accuracy"] >= local["Accuracy"] - slack, (rec.round, c)
            assert agg["Accuracy"] >= 99.5, (rec.round, c, agg)
            assert local["Accuracy"] >= 99.0, (rec.round, c, local)
            assert agg["F1-Score"] >= 0.99, (rec.round, c, agg)


@pytest.mark.slow
def test_eight_client_mesh(tok, eight_devices):
    """8 logical clients on an 8-wide clients axis."""
    df = make_synthetic_flows(1600, seed=13)
    dcfg = DataConfig(data_fraction=0.12, max_len=MAX_LEN, partition="disjoint")
    splits = make_all_client_splits(df, 8, dcfg)
    clients = [tokenize_client(s, tok, max_len=MAX_LEN) for s in splits]
    stacked_train = stack_clients([c.train for c in clients])
    cfg = _cfg(tok, clients=8)
    trainer = FederatedTrainer(cfg, pad_id=tok.pad_id)
    state = trainer.init_state()
    state, losses = trainer.fit_local(state, stacked_train, epochs=1)
    assert losses.shape == (1, 8)
    state = trainer.aggregate(state)
    p = np.asarray(jax.tree.leaves(state.params)[0])
    for c in range(1, 8):
        np.testing.assert_allclose(p[0], p[c], atol=1e-6)


@pytest.mark.slow
def test_more_clients_than_mesh_axis(tok, eight_devices):
    """4 logical clients stacked on a 2-wide mesh axis (2 replicas/shard)."""
    df = make_synthetic_flows(1200, seed=17)
    dcfg = DataConfig(data_fraction=0.2, max_len=MAX_LEN, partition="disjoint")
    splits = make_all_client_splits(df, 4, dcfg)
    clients = [tokenize_client(s, tok, max_len=MAX_LEN) for s in splits]
    stacked_train = stack_clients([c.train for c in clients])
    cfg = ExperimentConfig(
        model=ModelConfig.tiny(vocab_size=len(tok), max_len=MAX_LEN,
                               max_position_embeddings=MAX_LEN),
        data=DataConfig(data_fraction=0.2, max_len=MAX_LEN),
        train=TrainConfig(learning_rate=1e-3, epochs_per_round=1),
        fed=FedConfig(num_clients=4),
        mesh=MeshConfig(clients=2, data=2),
    )
    trainer = FederatedTrainer(cfg, pad_id=tok.pad_id)
    state = trainer.init_state()
    state, _ = trainer.fit_local(state, stacked_train, epochs=1)
    metrics = trainer.evaluate_clients(state.params, [c.val for c in clients])
    assert len(metrics) == 4


@pytest.mark.slow
def test_sixty_four_client_fleet(tok, eight_devices):
    """BASELINE.json config 5 scale: a 64-client FedAvg fleet (8 replicas
    per mesh shard on the 8-row virtual mesh) trains a round and aggregates
    to identical replicas."""
    df = make_synthetic_flows(3200, seed=23)
    dcfg = DataConfig(
        data_fraction=1.0 / 64, max_len=MAX_LEN, partition="disjoint"
    )
    splits = make_all_client_splits(df, 64, dcfg)
    clients = [tokenize_client(s, tok, max_len=MAX_LEN) for s in splits]
    stacked_train = stack_clients([c.train for c in clients])
    cfg = ExperimentConfig(
        model=ModelConfig.tiny(vocab_size=len(tok), max_len=MAX_LEN,
                               max_position_embeddings=MAX_LEN),
        data=DataConfig(data_fraction=1.0 / 64, max_len=MAX_LEN, batch_size=8),
        train=TrainConfig(learning_rate=1e-3, epochs_per_round=1),
        fed=FedConfig(num_clients=64),
        mesh=MeshConfig(clients=8, data=1),
    )
    trainer = FederatedTrainer(cfg, pad_id=tok.pad_id)
    state = trainer.init_state()
    state, losses = trainer.fit_local(state, stacked_train, epochs=1)
    assert losses.shape == (1, 64)
    state = trainer.aggregate(state)
    leaf = np.asarray(jax.tree.leaves(state.params)[0])
    for c in range(1, 64):
        np.testing.assert_allclose(leaf[c], leaf[0], rtol=1e-6)
    metrics = trainer.evaluate_clients(state.params, [c.val for c in clients])
    assert len(metrics) == 64


def test_unequal_eval_sizes_loss_not_diluted(tok, fed_data, eight_devices):
    """All-padding batches (stacking a small client's eval split up to a big
    client's) must not dilute the reported Loss."""
    clients, _ = fed_data
    cfg = _cfg(tok, clients=2)
    trainer = FederatedTrainer(cfg, pad_id=tok.pad_id)
    state = trainer.init_state()
    small = clients[1].val.take(np.arange(24))  # 24 rows vs client 0's full val
    m = trainer.evaluate_clients(state.params, [clients[0].val, small])
    assert m[1]["n"] == 24
    # directly evaluate the small split alone via the other client slot
    m_alone = trainer.evaluate_clients(state.params, [small, small])
    np.testing.assert_allclose(m[1]["Loss"], m_alone[1]["Loss"], rtol=1e-5)


def test_weighted_requires_explicit_weights(tok, fed_data, eight_devices):
    clients, stacked_train = fed_data
    cfg = _cfg(tok, clients=2, weighted=True)
    trainer = FederatedTrainer(cfg, pad_id=tok.pad_id)
    state = trainer.init_state()
    with pytest.raises(ValueError, match="weights"):
        trainer.run(state, stacked_train, [c.test for c in clients], rounds=1)


def test_tiny_client_rejected_with_clear_error(tok, eight_devices):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
    )

    rng = np.random.default_rng(0)
    tiny = TokenizedSplit(
        rng.integers(1, 50, (2, 5, MAX_LEN)).astype(np.int32),
        np.ones((2, 5, MAX_LEN), np.int32),
        rng.integers(0, 2, (2, 5)).astype(np.int32),
    )
    cfg = _cfg(tok, clients=2)
    trainer = FederatedTrainer(cfg, pad_id=tok.pad_id)
    state = trainer.init_state()
    with pytest.raises(ValueError, match="zero batches"):
        trainer.fit_local(state, tiny)


@pytest.mark.slow
def test_fedprox_bounds_client_drift(tok, fed_data, eight_devices):
    """FedProx (FedConfig.prox_mu): a strong proximal term must keep local
    params closer to the round-start globals than plain FedAvg does, with
    mu=0 preserving the plain (state, batch) step signature."""
    clients, stacked_train = fed_data

    def drift(mu):
        cfg = _cfg(tok, clients=2, data=1, prox_mu=mu)
        trainer = FederatedTrainer(cfg, pad_id=tok.pad_id)
        state = trainer.init_state(seed=0)
        start = jax.tree.map(lambda x: np.asarray(x).copy(), state.params)
        state, _ = trainer.fit_local(state, stacked_train, epochs=1)
        sq = sum(
            float(np.sum((np.asarray(a) - b) ** 2))
            for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(start))
        )
        return sq

    free = drift(0.0)
    anchored = drift(50.0)
    assert anchored < free * 0.5, (anchored, free)


def test_partial_participation(tok, eight_devices):
    """FedConfig.participation: only the sampled clients' params enter the
    round mean; the replicated result overwrites every replica (incl.
    non-participants, whose local epochs are discarded)."""
    cfg = _cfg(tok, clients=2, data=1, participation=0.5, min_client_fraction=0.5)
    trainer = FederatedTrainer(cfg, pad_id=tok.pad_id)
    state = trainer.init_state(seed=0)
    # Distinct per-client params WITHOUT paying a train-step compile: the
    # test is about the aggregation mask, not the optimizer.
    state = state._replace(
        params=jax.tree.map(
            lambda x: x
            + jnp.arange(x.shape[0], dtype=x.dtype).reshape(
                (-1,) + (1,) * (x.ndim - 1)
            ),
            state.params,
        )
    )
    pre = jax.tree.map(lambda x: np.asarray(x).copy(), state.params)

    mask = trainer.participation_mask(0)
    assert mask is not None and mask.sum() == 1  # 1 of 2 clients sampled
    chosen = int(np.flatnonzero(mask)[0])
    state = trainer.aggregate(state, client_mask=mask)
    leaf = np.asarray(jax.tree.leaves(state.params)[0])
    want = np.asarray(jax.tree.leaves(pre)[0])[chosen]
    # Mean over a single participant = its params, replicated to everyone.
    np.testing.assert_allclose(leaf[0], want, rtol=1e-6)
    np.testing.assert_allclose(leaf[1], want, rtol=1e-6)
    # Masks are seeded per round and identical across calls.
    np.testing.assert_array_equal(mask, trainer.participation_mask(0))

    # Everyone-participates configs return no mask; invalid rates rejected.
    assert FederatedTrainer(
        _cfg(tok, clients=2, data=1), pad_id=tok.pad_id
    ).participation_mask(0) is None
    with pytest.raises(ValueError, match="participation"):
        _cfg(tok, clients=2, data=1, participation=0.0)
    with pytest.raises(ValueError, match="min_client_fraction"):
        _cfg(tok, clients=2, data=1, participation=0.5)  # min_frac stays 1.0


def test_masked_aggregation_and_min_fraction(tok, eight_devices):
    cfg = _cfg(tok, clients=4, min_client_fraction=0.5)
    trainer = FederatedTrainer(cfg, pad_id=tok.pad_id)
    state = trainer.init_state()
    mask = np.array([1, 1, 0, 0], np.float32)
    state2 = trainer.aggregate(state, client_mask=mask)
    p = np.asarray(jax.tree.leaves(state2.params)[0])
    np.testing.assert_allclose(p[0], p[3], atol=1e-6)  # result replicated
    with pytest.raises(RuntimeError, match="survived"):
        trainer.aggregate(state, client_mask=np.array([1, 0, 0, 0], np.float32))


@pytest.mark.parametrize(
    "mu", [0.0, pytest.param(0.1, marks=pytest.mark.slow)]
)
def test_packed_fit_matches_vmapped(tok, fed_data, eight_devices, mu):
    """The client-packing fast path (single-device mesh: per-client
    jitted steps, unstack/restack per fit — the +15-MFU-point product
    step, PARITY.md r5) is the SAME training program as the stacked
    vmapped step: identical per-client rng folds, lockstep counter, and
    Adam math. One epoch from one init must land on the same params and
    losses up to float reassociation."""
    import dataclasses

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.mesh import (
        make_mesh,
    )

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
    )

    clients, full_train = fed_data
    # A 10-batch slice: parity is per-step math, not convergence — the
    # full-epoch version tripled the fast lane's cost for no extra pin.
    stacked_train = TokenizedSplit(
        full_train.input_ids[:, :160],
        full_train.attention_mask[:, :160],
        full_train.labels[:, :160],
    )
    # threefry: counter-based bits are identical however the draw is
    # batched. The production default (rbg) generates LAYOUT-DEPENDENT
    # bitstreams — under rbg the two paths draw different (equally
    # distributed) dropout masks, so exact parity is pinned on threefry.
    # mu=0.1 additionally pins the FedProx anchor branch of the packed
    # step (per-client anchor slices, 3-arg signature).
    cfg = _cfg(tok, clients=2, prox_mu=mu)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, prng_impl="threefry2x32")
    )
    packed = FederatedTrainer(
        cfg, pad_id=tok.pad_id, mesh=make_mesh(1, 1, devices=eight_devices[:1])
    )
    vmapped = FederatedTrainer(
        cfg, pad_id=tok.pad_id, mesh=make_mesh(2, 1, devices=eight_devices[:2])
    )
    assert packed._packed_eligible()
    assert not vmapped._packed_eligible()
    sp, lp = packed.fit_local(packed.init_state(), stacked_train, epochs=1)
    sv, lv = vmapped.fit_local(vmapped.init_state(), stacked_train, epochs=1)
    np.testing.assert_allclose(lp, lv, atol=1e-4)
    # Param tolerance ~1.5 Adam steps (lr 1e-3): Adam's normalization
    # amplifies float-reassociation differences in near-zero gradients
    # (the FedProx prox-term sum especially) up to ~lr per step on those
    # coordinates; losses above pin the trajectories far tighter.
    for a, b in zip(jax.tree.leaves(sp.params), jax.tree.leaves(sv.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1.5e-3
        )
    assert int(sp.step) == int(sv.step)


def test_packed_unstack_emits_no_donation_warning(tok, eight_devices):
    """VERDICT r5 weak #2 run down: the packed path's stack/unstack
    boundary used to declare ``donate_argnums`` on the stacked->per-client
    split, but a [C, ...] buffer can never alias its 1/C-sized output
    slices, so XLA copied anyway and warned "Some donated buffers were
    not usable" on every fed2/fedseq bench record. The donation is gone
    (an explicit post-split delete keeps the eager-free contract); the
    whole unstack -> packed-step -> restack round trip must now be
    warning-clean, and the stacked source buffers must still be consumed."""
    import warnings

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.mesh import (
        make_mesh,
    )

    trainer = FederatedTrainer(
        _cfg(tok, clients=2),
        pad_id=tok.pad_id,
        mesh=make_mesh(1, 1, devices=eight_devices[:1]),
    )
    assert trainer._packed_eligible()
    state = trainer.init_state()
    step_fn = trainer._build_packed_step()
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(
            0, trainer.cfg.model.vocab_size, (16, MAX_LEN)
        ).astype(np.int32),
        "attention_mask": np.ones((16, MAX_LEN), np.int32),
        "labels": rng.integers(0, 2, 16).astype(np.int32),
    }
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        cstates = trainer._unstack_cstates(state)
        for c in range(trainer.C):
            cstates[c], _ = step_fn(cstates[c], batch)
        restacked = trainer._restack_fn(*cstates)
        jax.block_until_ready(restacked)
    donated = [
        w for w in caught if "donated buffers" in str(w.message).lower()
    ]
    assert not donated, [str(w.message)[:200] for w in donated]
    # The eager-free contract survives the fix: the stacked source
    # buffers are consumed by the unstack, exactly as under donation.
    assert all(
        leaf.is_deleted()
        for leaf in jax.tree.leaves((state.params, state.opt_state))
    )
