"""clients × data × seq composition: federated long-context training on
one 3-axis mesh must match N independent unsharded programs + FedAvg."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    ModelConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.distilbert import (
    DDoSClassifier,
    init_params,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.fedavg import (
    fedavg,
    stack_params,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.fedseq import (
    init_fedseq_state,
    make_fedseq_loss,
    make_fedseq_train_step,
)

C, B, L = 2, 4, 64


@pytest.fixture(scope="module")
def mesh3(eight_devices):
    return Mesh(
        np.array(eight_devices[:8]).reshape(2, 2, 2),
        ("clients", "data", "seq"),
    )


def _cfgs():
    base = ModelConfig.tiny(
        attention_dropout=0.0, max_len=L, max_position_embeddings=L
    )
    return base, base.replace(attention_impl="ring", ring_axis="seq")


def _data(seed=0):
    rng = np.random.default_rng(seed)
    base, _ = _cfgs()
    ids = rng.integers(0, base.vocab_size, (C, B, L)).astype(np.int32)
    mask = (rng.random((C, B, L)) > 0.3).astype(np.int32)
    mask[:, :, 0] = 1  # CLS always visible
    labels = rng.integers(0, 2, (C, B)).astype(np.int32)
    return jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(labels)


def test_fedseq_loss_matches_unsharded(mesh3):
    base, ring = _cfgs()
    model_dot = DDoSClassifier(base)
    model_ring = DDoSClassifier(ring)
    params = init_params(model_dot, base, jax.random.key(0))
    stacked = stack_params(params, C)
    ids, mask, labels = _data()

    # jit both sides: the eager shard_map dispatch alone costs ~10x the
    # compile on this single-core 8-virtual-device host.
    loss_fn = jax.jit(make_fedseq_loss(model_ring, mesh3))
    got = np.asarray(loss_fn(stacked, ids, mask, labels))

    @jax.jit
    def solo_loss(ids_c, mask_c, labels_c):
        return optax.softmax_cross_entropy_with_integer_labels(
            model_dot.apply({"params": params}, ids_c, mask_c, True),
            labels_c,
        ).mean()

    want = np.array([float(solo_loss(ids[c], mask[c], labels[c])) for c in range(C)])
    np.testing.assert_allclose(got, want, atol=2e-5)


@pytest.mark.slow
def test_fedseq_grads_match_unsharded(mesh3):
    """VERDICT-5 'done' criterion: grad parity of the 2-client x 2-seq-shard
    (x 2 data shards) stacked program vs the unsharded per-client program."""
    base, ring = _cfgs()
    model_dot = DDoSClassifier(base)
    model_ring = DDoSClassifier(ring)
    params = init_params(model_dot, base, jax.random.key(0))
    stacked = stack_params(params, C)
    ids, mask, labels = _data()
    loss_fn = make_fedseq_loss(model_ring, mesh3)

    g_stacked = jax.jit(
        jax.grad(lambda p: loss_fn(p, ids, mask, labels).sum())
    )(stacked)

    solo_grad = jax.jit(
        jax.grad(
            lambda p, ids_c, mask_c, labels_c:
            optax.softmax_cross_entropy_with_integer_labels(
                model_dot.apply({"params": p}, ids_c, mask_c, True),
                labels_c,
            ).mean()
        )
    )
    for c in range(C):
        g_solo = solo_grad(params, ids[c], mask[c], labels[c])
        for a, b in zip(jax.tree.leaves(g_stacked), jax.tree.leaves(g_solo)):
            np.testing.assert_allclose(
                np.asarray(a)[c], np.asarray(b), atol=5e-4
            )


@pytest.mark.slow
def test_fedseq_train_step_and_fedavg(mesh3):
    """One lockstep train step over the 3-axis mesh matches per-client Adam
    on the unsharded program; FedAvg then replicates the mean."""
    base, ring = _cfgs()
    model_dot = DDoSClassifier(base)
    model_ring = DDoSClassifier(ring)
    params = init_params(model_dot, base, jax.random.key(0))
    opt = optax.adam(1e-3)
    stacked, opt_state = init_fedseq_state(opt, mesh3, params, C)
    ids, mask, labels = _data()

    step = make_fedseq_train_step(model_ring, opt, mesh3)
    new_stacked, opt_state, losses = step(
        stacked, opt_state, jnp.int32(0),
        {"input_ids": ids, "attention_mask": mask, "labels": labels},
    )
    assert losses.shape == (C,)

    # Manual per-client Adam on the unsharded program.
    @jax.jit
    def manual_step(ids_c, mask_c, labels_c):
        g = jax.grad(
            lambda p: optax.softmax_cross_entropy_with_integer_labels(
                model_dot.apply({"params": p}, ids_c, mask_c, True),
                labels_c,
            ).mean()
        )(params)
        u, _ = opt.update(g, opt.init(params), params)
        return optax.apply_updates(params, u)

    manual = [manual_step(ids[c], mask[c], labels[c]) for c in range(C)]
    for a, m0, m1 in zip(
        jax.tree.leaves(new_stacked),
        jax.tree.leaves(manual[0]),
        jax.tree.leaves(manual[1]),
    ):
        a = np.asarray(a)
        np.testing.assert_allclose(a[0], np.asarray(m0), atol=1e-5)
        np.testing.assert_allclose(a[1], np.asarray(m1), atol=1e-5)

    # FedAvg across the clients axis of the 3-axis mesh.
    agg = fedavg(new_stacked)
    leaf = np.asarray(jax.tree.leaves(agg)[0])
    np.testing.assert_allclose(leaf[0], leaf[1], atol=1e-6)
    want = 0.5 * (
        np.asarray(jax.tree.leaves(manual[0])[0])
        + np.asarray(jax.tree.leaves(manual[1])[0])
    )
    np.testing.assert_allclose(leaf[0], want, atol=1e-5)


# --------------------------------------------------------- dropout + trainer


def _exp_cfg(seq, *, dropout=True, clients=2, data=1):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        DataConfig,
        ExperimentConfig,
        FedConfig,
        MeshConfig,
        TrainConfig,
    )

    ML = 16
    d = dict(dropout=0.1, attention_dropout=0.1, head_dropout=0.3)
    if not dropout:
        d = dict(dropout=0.0, attention_dropout=0.0, head_dropout=0.0)
    return ExperimentConfig(
        model=ModelConfig.tiny(max_len=ML, max_position_embeddings=ML, **d),
        data=DataConfig(max_len=ML, batch_size=8, eval_batch_size=8),
        train=TrainConfig(learning_rate=1e-3, epochs_per_round=1, seed=0),
        fed=FedConfig(num_clients=clients, rounds=1),
        mesh=MeshConfig(clients=clients, data=data, seq=seq),
    )


def _dense_train(ml=16, n=32, clients=2, seed=0):
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
    )

    rng = np.random.default_rng(seed)
    return TokenizedSplit(
        rng.integers(1, 200, (clients, n, ml)).astype(np.int32),
        np.ones((clients, n, ml), np.int32),
        rng.integers(0, 2, (clients, n)).astype(np.int32),
    )


@pytest.mark.slow
def test_fedseq_dropout_invariant_to_seq_shard_count(eight_devices):
    """VERDICT r2 #3 done-criterion: fedseq trains WITH dropout (incl. the
    reference's head 0.3, client1.py:57) and the loss trajectory is
    invariant to the seq-axis shard count (hash masks keyed on global
    coordinates, ops/hash_dropout.py)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.seqfed import (
        FedSeqTrainer,
    )

    train = _dense_train()

    def run(seq):
        tr = FedSeqTrainer(_exp_cfg(seq))
        state = tr.init_state()
        state, losses = tr.fit_local(state, train, epochs=2)
        return np.asarray(losses)

    l1, l2, l4 = run(1), run(2), run(4)
    np.testing.assert_allclose(l2, l1, atol=2e-4)
    np.testing.assert_allclose(l4, l1, atol=2e-4)
    # Dropout genuinely active: the deterministic trajectory differs.
    tr = FedSeqTrainer(_exp_cfg(2, dropout=False))
    state = tr.init_state()
    _, l_det = tr.fit_local(state, train, epochs=2)
    assert not np.allclose(np.asarray(l_det), l2, atol=1e-5)


@pytest.mark.slow
def test_fedseq_trainer_dense_ragged_eval(eight_devices):
    """FedSeqTrainer presents the FederatedTrainer surface: dense fit,
    ragged fit (masked lockstep + gated updates), stacked eval with
    probs, and FedAvg aggregate on the 3-axis mesh. (Slow: several
    3-axis compiles; the fast lane covers the trainer via
    test_fedseq_eval_counts_match_two_axis_trainer and the loss via
    test_fedseq_loss_matches_unsharded.)"""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
        stack_clients_ragged,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.seqfed import (
        FedSeqTrainer,
    )

    tr = FedSeqTrainer(_exp_cfg(2, clients=2, data=2))
    state = tr.init_state()
    state, losses = tr.fit_local(state, _dense_train())
    assert np.isfinite(np.asarray(losses)).all()

    rng = np.random.default_rng(3)

    def split(n):
        return TokenizedSplit(
            rng.integers(1, 200, (n, 16)).astype(np.int32),
            np.ones((n, 16), np.int32),
            rng.integers(0, 2, n).astype(np.int32),
        )

    st = stack_clients_ragged([split(20), split(9)])
    state, rl = tr.fit_local(state, st)
    assert np.isfinite(np.asarray(rl)).all()

    ms = tr.evaluate_clients(
        state.params,
        prepared=tr.prepare_eval([split(16), split(16)]),
        collect_probs=True,
    )
    assert len(ms) == 2 and ms[0]["probs"].shape == (16,)
    assert all(np.isfinite(m["Loss"]) for m in ms)

    state = tr.aggregate(state, weights=np.array([20.0, 9.0]))
    leaf = np.asarray(jax.tree.leaves(state.params)[0])
    np.testing.assert_allclose(leaf[0], leaf[1], rtol=1e-6)


@pytest.mark.slow
def test_fedseq_fedprox_matches_dense_trainer_and_bounds_drift(eight_devices):
    """Round-4 done-criterion: FedProx runs under --seq-parallel. The
    3-axis prox trajectory matches the dense 2-axis trainer's (reported
    losses are the task loss on both paths), and a strong mu bounds the
    round drift exactly as on the dense path."""
    import dataclasses as _dc

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.federated import (
        FederatedTrainer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.seqfed import (
        FedSeqTrainer,
    )

    train = _dense_train()

    def run(trainer_cls, seq, mu):
        cfg = _exp_cfg(seq, dropout=False)
        cfg = _dc.replace(cfg, fed=_dc.replace(cfg.fed, prox_mu=mu))
        tr = trainer_cls(cfg)
        state = tr.init_state(seed=0)
        start = jax.tree.map(lambda x: np.asarray(x).copy(), state.params)
        state, losses = tr.fit_local(state, train, epochs=2)
        sq = sum(
            float(np.sum((np.asarray(a) - b) ** 2))
            for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(start))
        )
        return np.asarray(losses), sq

    l3, drift3 = run(FedSeqTrainer, 2, 5.0)
    l2, drift2 = run(FederatedTrainer, 1, 5.0)
    np.testing.assert_allclose(l3, l2, atol=2e-4)
    np.testing.assert_allclose(drift3, drift2, rtol=0.02)
    _, free = run(FedSeqTrainer, 2, 0.0)
    assert drift3 < free * 0.8, (drift3, free)


@pytest.mark.slow
def test_fedseq_personalize_head_freezes_encoder(eight_devices):
    """Round-4 done-criterion: --personalize-epochs runs under
    --seq-parallel (head scope = FedPer): the shared encoder stays
    bit-frozen, the classifier moves, and the scope-matched side trainer
    is the 3-axis FedSeqTrainer itself (type(self) dispatch)."""
    import dataclasses as _dc

    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.seqfed import (
        FedSeqTrainer,
    )

    cfg = _exp_cfg(2, dropout=False)
    cfg = _dc.replace(
        cfg,
        fed=_dc.replace(
            cfg.fed, personalize_epochs=1, personalize_scope="head"
        ),
    )
    tr = FedSeqTrainer(cfg)
    state = tr.init_state(seed=0)
    train = _dense_train()
    state, _ = tr.fit_local(state, train, epochs=1)
    state = tr.aggregate(state)
    pstate, plosses = tr.personalize(state, train)
    assert np.isfinite(np.asarray(plosses)).all()
    for a, b in zip(
        jax.tree.leaves(state.params["encoder"]),
        jax.tree.leaves(pstate.params["encoder"]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(state.params["classifier"]),
            jax.tree.leaves(pstate.params["classifier"]),
        )
    )


def test_fedseq_eval_counts_match_two_axis_trainer(eight_devices):
    """The fedseq eval step and the dense 2-axis eval step must produce
    IDENTICAL metrics for the same params (both reduce to
    engine.eval_counts semantics)."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
        TokenizedSplit,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.federated import (
        FederatedTrainer,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.seqfed import (
        FedSeqTrainer,
    )

    cfg3 = _exp_cfg(2, dropout=False, clients=2, data=2)
    tr3 = FedSeqTrainer(cfg3)
    state = tr3.init_state()
    rng = np.random.default_rng(7)
    evals = [
        TokenizedSplit(
            rng.integers(1, 200, (13, 16)).astype(np.int32),
            np.ones((13, 16), np.int32),
            rng.integers(0, 2, 13).astype(np.int32),
        )
        for _ in range(2)
    ]
    m3 = tr3.evaluate_clients(state.params, splits=evals)

    import dataclasses as _dc

    cfg2 = _dc.replace(cfg3, mesh=_dc.replace(cfg3.mesh, seq=1))
    tr2 = FederatedTrainer(cfg2)
    state2 = tr2.init_state()
    m2 = tr2.evaluate_clients(state2.params, splits=evals)
    for a, b in zip(m3, m2):
        for k in ("Accuracy", "Precision", "Recall", "F1-Score"):
            np.testing.assert_allclose(a[k], b[k], atol=1e-4, err_msg=k)
        np.testing.assert_allclose(a["Loss"], b["Loss"], atol=1e-3)


@pytest.mark.slow
def test_packed_fedseq_matches_stacked():
    """3-axis variant of the packing parity: FedSeqTrainer on a
    single-device 1x1x1 mesh takes the packed per-client ring-path step;
    the same config on a 2-device mesh runs the stacked shard_map
    program. One epoch from one init must agree."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data import (
        default_tokenizer,
        make_all_client_splits,
        make_synthetic_flows,
        stack_clients,
        tokenize_client,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
        DataConfig,
        ExperimentConfig,
        FedConfig,
        MeshConfig,
        ModelConfig,
        TrainConfig,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.fedseq import (
        make_seq_mesh,
    )
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train.seqfed import (
        FedSeqTrainer,
    )

    L = 32
    tok = default_tokenizer()
    df = make_synthetic_flows(240, seed=5)
    dcfg = DataConfig(data_fraction=0.9, max_len=L)
    splits = make_all_client_splits(df, 2, dcfg)
    clients = [tokenize_client(s, tok, max_len=L) for s in splits]
    stacked_train = stack_clients([c.train for c in clients])
    cfg = ExperimentConfig(
        model=ModelConfig.tiny(
            vocab_size=len(tok), max_len=L, max_position_embeddings=L,
            dim=32, n_layers=2, n_heads=2, hidden_dim=64,
        ),
        data=DataConfig(data_fraction=0.9, max_len=L, batch_size=8),
        train=TrainConfig(learning_rate=1e-3, epochs_per_round=1, seed=0),
        fed=FedConfig(num_clients=2),
        mesh=MeshConfig(clients=1, data=1, seq=1),
    )
    devs = jax.devices()
    packed = FedSeqTrainer(
        cfg, pad_id=tok.pad_id,
        mesh=make_seq_mesh(1, 1, 1, devices=devs[:1]),
    )
    assert packed._packed_eligible()
    import dataclasses

    cfg2 = dataclasses.replace(
        cfg, mesh=MeshConfig(clients=2, data=1, seq=1)
    )
    stacked = FedSeqTrainer(
        cfg2, pad_id=tok.pad_id,
        mesh=make_seq_mesh(2, 1, 1, devices=devs[:2]),
    )
    assert not stacked._packed_eligible()
    sp, lp = packed.fit_local(packed.init_state(), stacked_train, epochs=1)
    sv, lv = stacked.fit_local(stacked.init_state(), stacked_train, epochs=1)
    np.testing.assert_allclose(lp, lv, atol=1e-4)
    for a, b in zip(jax.tree.leaves(sp.params), jax.tree.leaves(sv.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)
