"""clients × data × seq composition: federated long-context training on
one 3-axis mesh must match N independent unsharded programs + FedAvg."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    ModelConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models.distilbert import (
    DDoSClassifier,
    init_params,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.fedavg import (
    fedavg,
    stack_params,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel.fedseq import (
    init_fedseq_state,
    make_fedseq_loss,
    make_fedseq_train_step,
)

C, B, L = 2, 4, 64


@pytest.fixture(scope="module")
def mesh3(eight_devices):
    return Mesh(
        np.array(eight_devices[:8]).reshape(2, 2, 2),
        ("clients", "data", "seq"),
    )


def _cfgs():
    base = ModelConfig.tiny(
        attention_dropout=0.0, max_len=L, max_position_embeddings=L
    )
    return base, base.replace(attention_impl="ring", ring_axis="seq")


def _data(seed=0):
    rng = np.random.default_rng(seed)
    base, _ = _cfgs()
    ids = rng.integers(0, base.vocab_size, (C, B, L)).astype(np.int32)
    mask = (rng.random((C, B, L)) > 0.3).astype(np.int32)
    mask[:, :, 0] = 1  # CLS always visible
    labels = rng.integers(0, 2, (C, B)).astype(np.int32)
    return jnp.asarray(ids), jnp.asarray(mask), jnp.asarray(labels)


def test_fedseq_loss_matches_unsharded(mesh3):
    base, ring = _cfgs()
    model_dot = DDoSClassifier(base)
    model_ring = DDoSClassifier(ring)
    params = init_params(model_dot, base, jax.random.key(0))
    stacked = stack_params(params, C)
    ids, mask, labels = _data()

    loss_fn = make_fedseq_loss(model_ring, mesh3)
    got = np.asarray(loss_fn(stacked, ids, mask, labels))

    want = np.array(
        [
            float(
                optax.softmax_cross_entropy_with_integer_labels(
                    model_dot.apply({"params": params}, ids[c], mask[c], True),
                    labels[c],
                ).mean()
            )
            for c in range(C)
        ]
    )
    np.testing.assert_allclose(got, want, atol=2e-5)


@pytest.mark.slow
def test_fedseq_grads_match_unsharded(mesh3):
    """VERDICT-5 'done' criterion: grad parity of the 2-client x 2-seq-shard
    (x 2 data shards) stacked program vs the unsharded per-client program."""
    base, ring = _cfgs()
    model_dot = DDoSClassifier(base)
    model_ring = DDoSClassifier(ring)
    params = init_params(model_dot, base, jax.random.key(0))
    stacked = stack_params(params, C)
    ids, mask, labels = _data()
    loss_fn = make_fedseq_loss(model_ring, mesh3)

    g_stacked = jax.grad(
        lambda p: loss_fn(p, ids, mask, labels).sum()
    )(stacked)

    for c in range(C):
        g_solo = jax.grad(
            lambda p: optax.softmax_cross_entropy_with_integer_labels(
                model_dot.apply({"params": p}, ids[c], mask[c], True),
                labels[c],
            ).mean()
        )(params)
        for a, b in zip(jax.tree.leaves(g_stacked), jax.tree.leaves(g_solo)):
            np.testing.assert_allclose(
                np.asarray(a)[c], np.asarray(b), atol=5e-4
            )


@pytest.mark.slow
def test_fedseq_train_step_and_fedavg(mesh3):
    """One lockstep train step over the 3-axis mesh matches per-client Adam
    on the unsharded program; FedAvg then replicates the mean."""
    base, ring = _cfgs()
    model_dot = DDoSClassifier(base)
    model_ring = DDoSClassifier(ring)
    params = init_params(model_dot, base, jax.random.key(0))
    opt = optax.adam(1e-3)
    stacked, opt_state = init_fedseq_state(opt, mesh3, params, C)
    ids, mask, labels = _data()

    step = make_fedseq_train_step(model_ring, opt, mesh3)
    new_stacked, opt_state, losses = step(
        stacked, opt_state, jnp.int32(0),
        {"input_ids": ids, "attention_mask": mask, "labels": labels},
    )
    assert losses.shape == (C,)

    # Manual per-client Adam on the unsharded program.
    manual = []
    for c in range(C):
        g = jax.grad(
            lambda p: optax.softmax_cross_entropy_with_integer_labels(
                model_dot.apply({"params": p}, ids[c], mask[c], True),
                labels[c],
            ).mean()
        )(params)
        u, _ = opt.update(g, opt.init(params), params)
        manual.append(optax.apply_updates(params, u))
    for a, m0, m1 in zip(
        jax.tree.leaves(new_stacked),
        jax.tree.leaves(manual[0]),
        jax.tree.leaves(manual[1]),
    ):
        a = np.asarray(a)
        np.testing.assert_allclose(a[0], np.asarray(m0), atol=1e-5)
        np.testing.assert_allclose(a[1], np.asarray(m1), atol=1e-5)

    # FedAvg across the clients axis of the 3-axis mesh.
    agg = fedavg(new_stacked)
    leaf = np.asarray(jax.tree.leaves(agg)[0])
    np.testing.assert_allclose(leaf[0], leaf[1], atol=1e-6)
    want = 0.5 * (
        np.asarray(jax.tree.leaves(manual[0])[0])
        + np.asarray(jax.tree.leaves(manual[1])[0])
    )
    np.testing.assert_allclose(leaf[0], want, atol=1e-5)
