"""Fault injection: deterministic client failures in both deployment modes.

The reference has no fault injection and its only failure behavior is to
hang the accept loop until timeout when a client dies (server.py:69-71,
124-132; SURVEY.md §5). Here failures are first-class: mesh-mode rounds
take an injected fault mask (dropped clients are excluded from the masked
mean), and the TCP tier is exercised through the REUSABLE chaos harness
(faults/proxy.py — the seeded wire-level fault proxy the `fedtpu
scenario` runner drives) instead of hand-rolled socket poking: crashed,
corrupting, silent, and probe-racing clients, with the server
aggregating the survivors whenever the quorum allows.
"""

import itertools
import socket
import threading

import numpy as np
import pytest

import jax

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
    AggregationServer,
    FederatedClient,
    flatten_params,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm.client import (
    backoff_intervals,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.faults import (
    FaultProxy,
    FaultSpec,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.data.pipeline import (
    TokenizedSplit,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.parallel import (
    make_mesh,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.train import (
    FederatedTrainer,
)


# ------------------------------------------------------------- mesh mode
def _tiny_cfg(clients=4, **fed_kw):
    model = ModelConfig.tiny()
    fed_kw.setdefault("min_client_fraction", 0.5)
    return ExperimentConfig(
        model=model,
        data=DataConfig(max_len=model.max_len, batch_size=4),
        train=TrainConfig(learning_rate=1e-3, epochs_per_round=1, seed=0),
        fed=FedConfig(num_clients=clients, rounds=2, **fed_kw),
        mesh=MeshConfig(clients=clients, data=1),
    )


def _tiny_data(cfg, clients, n=16):
    rng = np.random.default_rng(0)
    L = cfg.model.max_len

    def split(rows):
        return TokenizedSplit(
            rng.integers(0, cfg.model.vocab_size, (rows, L)).astype(np.int32),
            np.ones((rows, L), np.int32),
            rng.integers(0, 2, rows).astype(np.int32),
        )

    train = TokenizedSplit(
        rng.integers(0, cfg.model.vocab_size, (clients, n, L)).astype(np.int32),
        np.ones((clients, n, L), np.int32),
        rng.integers(0, 2, (clients, n)).astype(np.int32),
    )
    return train, [split(8) for _ in range(clients)]


@pytest.mark.slow
def test_injected_fault_matches_manual_masked_aggregate(eight_devices):
    """run() with a fault plan must equal the manual fit_local +
    masked-aggregate sequence — the injected failure IS the masked mean."""
    C = 4
    faults = np.array([1.0, 1.0, 0.0, 1.0])  # client 2 dies in round 0

    def build():
        cfg = _tiny_cfg(clients=C)
        mesh = make_mesh(C, 1, devices=eight_devices[:C])
        t = FederatedTrainer(cfg, mesh=mesh)
        return t, t.init_state(seed=0)

    train, evals = _tiny_data(_tiny_cfg(clients=C), C)

    t1, s1 = build()
    s1, history = t1.run(
        s1, train, evals, rounds=1,
        fault_mask_fn=lambda r: faults if r == 0 else None,
    )
    assert len(history) == 1

    t2, s2 = build()
    s2, _ = t2.fit_local(s2, train, epochs=1)
    s2 = t2.aggregate(s2, client_mask=faults)

    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_fault_below_quorum_fails_the_round(eight_devices):
    # aggregate() hosts the survivor check run() hits — calling it directly
    # skips the (compile-heavy) local-training phase the check never needs.
    C = 4
    cfg = _tiny_cfg(clients=C, min_client_fraction=0.75)
    mesh = make_mesh(C, 1, devices=eight_devices[:C])
    trainer = FederatedTrainer(cfg, mesh=mesh)
    state = trainer.init_state(seed=0)
    with pytest.raises(RuntimeError, match="survived the round"):
        trainer.aggregate(state, client_mask=np.array([1.0, 0.0, 0.0, 1.0]))


@pytest.mark.slow
def test_recovery_round_after_fault(eight_devices):
    """A client dropped in round 0 rejoins in round 1 (it received the
    round-0 aggregate like everyone else — SPMD replicas move in lockstep),
    and the final replicas are identical and finite."""
    C = 4
    cfg = _tiny_cfg(clients=C)
    mesh = make_mesh(C, 1, devices=eight_devices[:C])
    trainer = FederatedTrainer(cfg, mesh=mesh)
    state = trainer.init_state(seed=0)
    train, evals = _tiny_data(cfg, C)
    state, history = trainer.run(
        state, train, evals, rounds=2,
        fault_mask_fn=lambda r: (
            np.array([0.0, 1.0, 1.0, 1.0]) if r == 0 else None
        ),
    )
    assert len(history) == 2
    leaf = np.asarray(jax.tree.leaves(state.params)[0])
    for c in range(1, C):
        np.testing.assert_allclose(leaf[c], leaf[0], rtol=1e-6)
    assert np.isfinite(leaf).all()


# -------------------------------------------------------------- TCP mode
#
# All wire-level failure shapes go through the faults/ harness (the
# deterministic proxy the scenario runner drives); the hand-rolled
# socket poking these tests used to carry is now the harness's job.
def _params(rng):
    return {
        "enc": {"w": rng.normal(size=(6, 4)).astype(np.float32)},
        "head": {"b": rng.normal(size=(4,)).astype(np.float32)},
    }


def _healthy(server, cid, params, results, port=None, host="127.0.0.1"):
    def _run():
        try:
            results[cid] = FederatedClient(
                host, port if port is not None else server.port,
                client_id=cid, timeout=10,
            ).exchange(params, max_retries=1)
        except (ConnectionError, OSError) as e:
            results[f"err{cid}"] = e

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    return t


def test_server_survives_mid_upload_crash(rng):
    """One client dies mid-frame (proxy drop-after-N); with min_clients=1
    the server aggregates the survivor instead of hanging (the reference
    hangs until timeout)."""
    p0 = _params(rng)
    results = {}
    with AggregationServer(
        port=0, num_clients=2, min_clients=1, timeout=10
    ) as server:
        with FaultProxy(
            "127.0.0.1", server.port,
            plan=FaultSpec(drop_after_bytes=256), seed=1,
        ) as prox:
            t1 = _healthy(
                server, 1, _params(rng), results, port=prox.port,
                host=prox.host,
            )
            t0 = _healthy(server, 0, p0, results)
            agg = server.serve_round(deadline=5.0)
            t0.join(timeout=10)
            t1.join(timeout=10)
            assert prox.events_of("drop"), "the fault must have fired"
    assert 0 in results
    assert "err1" in results  # the crasher's exchange failed, not hung
    for key, arr in flatten_params(results[0]).items():
        np.testing.assert_allclose(arr, flatten_params(p0)[key], rtol=1e-6)
    assert set(agg) == set(flatten_params(p0))


def test_server_rejects_corrupted_stream_and_serves_survivor(rng):
    """An in-flight bit flip (proxy) breaks the frame CRC; the corrupt
    upload is rejected, the survivor's round completes. (The wire-level
    payload-CRC layer beneath is unit-pinned in test_comm.py.)"""
    p0 = _params(rng)
    results = {}
    with AggregationServer(
        port=0, num_clients=2, min_clients=1, timeout=10
    ) as server:
        with FaultProxy(
            "127.0.0.1", server.port,
            plan=FaultSpec(flip_bit_after_bytes=80), seed=2,
        ) as prox:
            t1 = _healthy(
                server, 1, _params(rng), results, port=prox.port,
                host=prox.host,
            )
            t0 = _healthy(server, 0, p0, results)
            server.serve_round(deadline=5.0)
            t0.join(timeout=10)
            t1.join(timeout=10)
            assert prox.events_of("flip")
    assert 0 in results
    assert 1 not in results  # the corrupted upload never joined the round


def test_silent_client_excluded_at_deadline(rng):
    """A client that connects and never sends anything (a lurker through
    the proxy) is excluded when the round deadline passes; the survivor
    is still served."""
    p0 = _params(rng)
    results = {}
    with AggregationServer(
        port=0, num_clients=2, min_clients=1, timeout=10
    ) as server:
        with FaultProxy("127.0.0.1", server.port, seed=3) as prox:
            lurker = socket.create_connection(
                (prox.host, prox.port), timeout=5
            )
            t0 = _healthy(server, 0, p0, results)
            server.serve_round(deadline=4.0)
            t0.join(timeout=10)
            lurker.close()
    assert 0 in results


def test_duplicate_connect_probe_race_is_harmless(rng):
    """The reference's probe-connect race (SURVEY §5: a probe connection
    accepted by the send loop kills it) replayed through the proxy's
    duplicate-connect fault: the abandoned extra connection must not
    disturb the real exchange."""
    p0, p1 = _params(rng), _params(rng)
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=10
    ) as server:
        with FaultProxy(
            "127.0.0.1", server.port,
            plan=FaultSpec(duplicate_connect=True), seed=4,
        ) as prox:
            t0 = _healthy(
                server, 0, p0, results, port=prox.port, host=prox.host
            )
            t1 = _healthy(server, 1, p1, results)
            agg = server.serve_round(deadline=8.0)
            t0.join(timeout=10)
            t1.join(timeout=10)
            assert prox.events_of("duplicate-connect")
    assert 0 in results and 1 in results
    expected = {
        k: (flatten_params(p0)[k] + flatten_params(p1)[k]) / 2.0
        for k in flatten_params(p0)
    }
    for key, arr in flatten_params(results[0]).items():
        np.testing.assert_allclose(arr, expected[key], rtol=1e-5)
    assert set(agg) == set(expected)


def test_reset_mid_upload_then_retry_converges(rng):
    """A mid-stream RST on the first dial (the intermittent persona's
    wire shape) is healed by the client's retry inside the SAME round —
    and the proxy's RST is prompt (the round must not wait it out)."""
    p0, p1 = _params(rng), _params(rng)
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=20
    ) as server:
        with FaultProxy(
            "127.0.0.1", server.port,
            plan=lambda i, rng_: (
                FaultSpec(reset_after_bytes=64) if i == 0 else FaultSpec()
            ),
            seed=5,
        ) as prox:

            def _retrying():
                results[0] = FederatedClient(
                    prox.host, prox.port, client_id=0, timeout=15
                ).exchange(p0, max_retries=3)

            t0 = threading.Thread(target=_retrying, daemon=True)
            t0.start()
            t1 = _healthy(server, 1, p1, results)
            agg = server.serve_round(deadline=15.0)
            t0.join(timeout=20)
            t1.join(timeout=20)
            assert prox.events_of("reset")
    assert 0 in results and 1 in results
    for key in flatten_params(results[0]):
        np.testing.assert_array_equal(
            flatten_params(results[0])[key], flatten_params(results[1])[key]
        )
    assert agg is not None


def test_throttled_upload_is_a_straggler_not_a_dropout(rng):
    """A throttled (slow-persona) upload still lands inside the deadline:
    the slow client contributes — late — and every client gets the same
    mean."""
    big = {"w": rng.normal(size=(24_000,)).astype(np.float32)}
    p1 = {"w": rng.normal(size=(24_000,)).astype(np.float32)}
    results = {}
    with AggregationServer(
        port=0, num_clients=2, timeout=20
    ) as server:
        with FaultProxy(
            "127.0.0.1", server.port,
            plan=FaultSpec(throttle_bps=96_000), seed=6,
        ) as prox:
            t0 = _healthy(
                server, 0, big, results, port=prox.port, host=prox.host
            )
            t1 = _healthy(server, 1, p1, results)
            agg = server.serve_round(deadline=15.0)
            t0.join(timeout=20)
            t1.join(timeout=20)
            assert prox.events_of("throttle")
    assert 0 in results and 1 in results
    np.testing.assert_allclose(
        flatten_params(results[0])["w"], (big["w"] + p1["w"]) / 2.0,
        rtol=1e-5,
    )
    assert agg is not None


# ------------------------------------------------- dial-retry backoff
def test_backoff_first_probe_is_reference_compatible():
    """The first retry interval is EXACTLY the reference's 1 s probe
    cadence; later intervals grow toward the cap with jitter in
    [0.5, 1.0) of the nominal value."""
    sched = list(itertools.islice(backoff_intervals(seed=0), 8))
    assert sched[0] == 1.0
    for k, s in enumerate(sched[1:], start=1):
        nominal = min(15.0, 2.0**k)
        assert 0.5 * nominal <= s <= nominal
    # The envelope reaches (and never exceeds) the cap.
    assert max(sched) <= 15.0
    assert min(itertools.islice(backoff_intervals(seed=0), 6, 8)) >= 7.5


def test_backoff_schedule_deterministic_per_seed():
    a = list(itertools.islice(backoff_intervals(seed=7), 10))
    b = list(itertools.islice(backoff_intervals(seed=7), 10))
    c = list(itertools.islice(backoff_intervals(seed=8), 10))
    assert a == b
    assert a != c  # different clients desynchronize
    assert a[0] == c[0] == 1.0  # ... except the reference first probe


def test_many_concurrent_clients_stress(rng):
    """8 clients hammer one server simultaneously (the reference's thread-
    per-client path held 2; SURVEY §5 flags its accept-order identity race).
    Every client must get the identical, correct mean."""
    from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.comm import (
        aggregate_flat,
    )

    C = 8
    params = [_params(rng) for _ in range(C)]
    results = {}
    with AggregationServer(port=0, num_clients=C, timeout=30) as server:
        st = threading.Thread(
            target=lambda: results.__setitem__("agg", server.serve_round(deadline=30))
        )
        st.start()
        ts = [_healthy(server, cid, params[cid], results) for cid in range(C)]
        for t in ts:
            t.join(timeout=30)
        st.join(timeout=30)
    assert all(c in results for c in range(C))
    expected = aggregate_flat([flatten_params(p) for p in params])
    base = flatten_params(results[0])
    for key, arr in base.items():
        np.testing.assert_allclose(arr, expected[key], rtol=1e-5)
    for c in range(1, C):
        for key, arr in flatten_params(results[c]).items():
            np.testing.assert_array_equal(arr, base[key])
