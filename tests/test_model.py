"""Model tests: shapes, determinism, and numerical parity vs HF torch DistilBERT."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.config import (
    ModelConfig,
)
from detecting_cyber_attacks_with_distilled_large_language_models_in_distributed_networks_tpu.models import (
    DDoSClassifier,
    DistilBertEncoder,
    flax_to_hf,
    hf_to_flax,
    init_params,
    param_count,
)

TINY = ModelConfig.tiny()


@pytest.fixture(scope="module")
def tiny_params():
    model = DDoSClassifier(TINY)
    return init_params(model, TINY, jax.random.key(0))


def _batch(cfg, B=4, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(5, cfg.vocab_size, (B, cfg.max_len)).astype(np.int32)
    lens = rng.integers(4, cfg.max_len, B)
    mask = (np.arange(cfg.max_len)[None, :] < lens[:, None]).astype(np.int32)
    ids = np.where(mask == 1, ids, 0)
    return ids, mask


def test_forward_shapes_and_dtype(tiny_params):
    model = DDoSClassifier(TINY)
    ids, mask = _batch(TINY)
    logits = model.apply({"params": tiny_params}, ids, mask)
    assert logits.shape == (4, 2)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_padding_invariance(tiny_params):
    """Masked positions must not affect the CLS logits."""
    model = DDoSClassifier(TINY)
    ids, mask = _batch(TINY, B=2, seed=1)
    logits_a = model.apply({"params": tiny_params}, ids, mask)
    ids_b = np.where(mask == 1, ids, 7)  # garbage in padded region
    logits_b = model.apply({"params": tiny_params}, ids_b, mask)
    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b), atol=1e-5)


def test_dropout_train_vs_eval(tiny_params):
    model = DDoSClassifier(TINY)
    ids, mask = _batch(TINY)
    e1 = model.apply({"params": tiny_params}, ids, mask, True)
    e2 = model.apply({"params": tiny_params}, ids, mask, True)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))
    t1 = model.apply(
        {"params": tiny_params}, ids, mask, False, rngs={"dropout": jax.random.key(1)}
    )
    t2 = model.apply(
        {"params": tiny_params}, ids, mask, False, rngs={"dropout": jax.random.key(2)}
    )
    assert np.abs(np.asarray(t1) - np.asarray(t2)).max() > 1e-6


@pytest.mark.slow
def test_tanh_gelu_matches_exact_within_bf16_rounding():
    """The default fast path (gelu='tanh') must be indistinguishable from
    HF's erf GELU at bf16 activation width — the basis for keeping it the
    flagship default while 'exact' serves fp32 parity comparisons."""
    exact_cfg = TINY.replace(compute_dtype="bfloat16", gelu="exact")
    tanh_cfg = TINY.replace(compute_dtype="bfloat16", gelu="tanh")
    params = init_params(DDoSClassifier(exact_cfg), exact_cfg, jax.random.key(3))
    ids, mask = _batch(exact_cfg, B=8, seed=4)
    a = np.asarray(jax.jit(DDoSClassifier(exact_cfg).apply)({"params": params}, ids, mask))
    b = np.asarray(jax.jit(DDoSClassifier(tanh_cfg).apply)({"params": params}, ids, mask))
    # Logit differences must stay within a few bf16 ulps of the logit scale.
    scale = max(1.0, np.abs(a).max())
    assert np.abs(a - b).max() <= 0.02 * scale


def test_gelu_config_validation():
    with pytest.raises(ValueError, match="gelu"):
        ModelConfig(gelu="relu")


def test_param_count_distilbert_base():
    cfg = ModelConfig()  # distilbert-base
    # eval_shape: count parameters from abstract shapes without paying a
    # real 66M-parameter init on the CPU test mesh.
    params = jax.eval_shape(
        lambda: init_params(DistilBertEncoder(cfg), cfg, jax.random.key(0))
    )
    n = param_count(params)
    assert n == 66_362_880  # HF distilbert-base-uncased encoder size


def _hf_reference(cfg: ModelConfig, seed: int = 0):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(seed)
    hf_cfg = transformers.DistilBertConfig(
        vocab_size=cfg.vocab_size,
        max_position_embeddings=cfg.max_position_embeddings,
        n_layers=cfg.n_layers,
        n_heads=cfg.n_heads,
        dim=cfg.dim,
        hidden_dim=cfg.hidden_dim,
        dropout=cfg.dropout,
        attention_dropout=cfg.attention_dropout,
    )
    return transformers.DistilBertModel(hf_cfg).eval()


def test_encoder_parity_vs_hf():
    torch = pytest.importorskip("torch")
    cfg = ModelConfig.tiny()
    hf = _hf_reference(cfg)
    params = hf_to_flax(hf.state_dict(), cfg)["encoder"]
    ids, mask = _batch(cfg, B=3, seed=2)
    with torch.no_grad():
        theirs = hf(
            input_ids=torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
        ).last_hidden_state.numpy()
    ours = DistilBertEncoder(cfg).apply({"params": params}, ids, mask)
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-5, rtol=1e-4)


def test_classifier_parity_vs_reference_head():
    """Full-model parity: our DDoSClassifier vs the reference's architecture
    (HF encoder + CLS pool + dropout(eval) + Linear(dim,2), client1.py:53-65)."""
    torch = pytest.importorskip("torch")
    cfg = ModelConfig.tiny()
    hf = _hf_reference(cfg, seed=3)
    torch.manual_seed(4)
    head = torch.nn.Linear(cfg.dim, 2)

    sd = {f"distilbert.{k}": v for k, v in hf.state_dict().items()}
    sd["classifier.weight"] = head.weight
    sd["classifier.bias"] = head.bias
    params = hf_to_flax(sd, cfg)

    ids, mask = _batch(cfg, B=3, seed=5)
    with torch.no_grad():
        hidden = hf(
            input_ids=torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
        ).last_hidden_state
        theirs = head(hidden[:, 0, :]).numpy()
    ours = DDoSClassifier(cfg).apply({"params": params}, ids, mask)
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-5, rtol=1e-4)


def test_hf_round_trip():
    cfg = ModelConfig.tiny()
    params = init_params(DDoSClassifier(cfg), cfg, jax.random.key(7))
    sd = flax_to_hf(params, cfg)
    back = hf_to_flax(sd, cfg)
    flat_a = jax.tree_util.tree_leaves_with_path(params)
    flat_b = jax.tree_util.tree_leaves_with_path(back)
    assert len(flat_a) == len(flat_b)
    for (pa, a), (pb, b) in zip(sorted(flat_a, key=str), sorted(flat_b, key=str)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7, err_msg=str(pa))


def test_bert_base_scaleup_builds():
    # eval_shape: the assertion is structural, so skip the real 110M init.
    cfg = ModelConfig.bert_base(vocab_size=1000, max_len=32, max_position_embeddings=64)
    params = jax.eval_shape(
        lambda: init_params(DDoSClassifier(cfg), cfg, jax.random.key(0))
    )
    assert "layer_11" in params["encoder"]


def test_remat_matches(tiny_params):
    cfg = TINY.replace(remat=True)
    ids, mask = _batch(TINY)
    a = jax.jit(DDoSClassifier(TINY).apply)({"params": tiny_params}, ids, mask)
    b = jax.jit(DDoSClassifier(cfg).apply)({"params": tiny_params}, ids, mask)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


@pytest.mark.slow
def test_fused_qkv_matches_unfused():
    """fused_qkv computes identical logits from the identical parameter
    tree (the fusion is apply-time only; params/checkpoints/HF layout are
    unchanged), and gradients flow equivalently."""
    cfg = ModelConfig.tiny()
    fused_cfg = cfg.replace(fused_qkv=True)
    model = DDoSClassifier(cfg)
    model_f = DDoSClassifier(fused_cfg)
    params = init_params(model, cfg, jax.random.key(0))
    params_f = init_params(model_f, fused_cfg, jax.random.key(0))
    # Identical parameter trees from the same seed.
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params_f)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (4, cfg.max_len)), jnp.int32)
    mask = jnp.ones((4, cfg.max_len), jnp.int32)
    out = jax.jit(model.apply, static_argnums=3)({"params": params}, ids, mask, True)
    out_f = jax.jit(model_f.apply, static_argnums=3)({"params": params}, ids, mask, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_f), atol=1e-5)

    g = jax.jit(
        jax.grad(lambda p: model.apply({"params": p}, ids, mask, True).sum())
    )(params)
    g_f = jax.jit(
        jax.grad(lambda p: model_f.apply({"params": p}, ids, mask, True).sum())
    )(params)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(g_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
