from .distilbert import (  # noqa: F401
    DDoSClassifier,
    DistilBertEncoder,
    init_params,
    param_count,
)
from .hf_convert import flax_to_hf, hf_to_flax  # noqa: F401
from .presets import PRESETS, model_preset, preset_names  # noqa: F401
