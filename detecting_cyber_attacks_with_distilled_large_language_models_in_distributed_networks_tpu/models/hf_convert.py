"""HF DistilBERT checkpoint <-> Flax param pytree conversion.

The reference warm-starts from HF ``distilbert-base-uncased`` weights
(reference client1.py:56) and round-trips full ``state_dict``s through its
socket protocol. This converter maps a torch ``state_dict`` (either a bare
``DistilBertModel`` or the reference's full ``DDoSClassifier`` with its
``distilbert.`` prefix + ``classifier`` head, client1.py:53-58) into this
package's Flax layout, transposing ``nn.Linear`` weights ([out,in] ->
[in,out]). No torch import is required — any mapping of name -> array-like
works (e.g. numpy arrays loaded from a safetensors file).
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from ..config import ModelConfig


def _np(t: Any) -> np.ndarray:
    """torch.Tensor / numpy array -> float32 numpy, without importing torch."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def _strip_prefix(sd: Mapping[str, Any]) -> tuple[dict[str, Any], bool]:
    """Normalize to bare-encoder key space; returns (dict, had_classifier)."""
    out: dict[str, Any] = {}
    has_head = False
    for k, v in sd.items():
        if k.startswith("distilbert."):
            out[k[len("distilbert.") :]] = v
        elif k.startswith("classifier."):
            out[k] = v
            has_head = True
        else:
            out[k] = v
    return out, has_head


def hf_to_flax(
    state_dict: Mapping[str, Any], cfg: ModelConfig, head_rng: np.random.Generator | None = None
) -> dict:
    """Torch/HF state dict -> Flax ``DDoSClassifier`` params.

    If the state dict has no classifier head (a bare encoder checkpoint, the
    reference's starting condition), the head is initialized from
    ``head_rng`` (normal(initializer_range), zero bias) — mirroring the fresh
    ``nn.Linear(768, 2)`` at reference client1.py:58.

    HF ``DistilBertForSequenceClassification`` checkpoints carry an extra
    ``pre_classifier`` Linear+ReLU under their ``classifier`` — an
    architecture this model does not have (the reference's head is CLS ->
    dropout -> Linear, client1.py:57-58). Converting only ``classifier.*``
    would silently produce wrong logits, so such checkpoints are rejected.
    """
    sd, has_head = _strip_prefix(state_dict)
    if any(k.startswith("pre_classifier.") for k in sd):
        raise ValueError(
            "this is an HF sequence-classification checkpoint (it has a "
            "pre_classifier layer this architecture lacks) — converting it "
            "would silently drop trained weights. Start from its bare "
            "encoder instead and fine-tune here (local/federated)."
        )

    def lin(prefix: str) -> dict:
        return {
            "kernel": _np(sd[f"{prefix}.weight"]).T,
            "bias": _np(sd[f"{prefix}.bias"]),
        }

    def ln(prefix: str) -> dict:
        return {
            "scale": _np(sd[f"{prefix}.weight"]),
            "bias": _np(sd[f"{prefix}.bias"]),
        }

    encoder: dict[str, Any] = {
        "embeddings": {
            "word_embeddings": {
                "embedding": _np(sd["embeddings.word_embeddings.weight"])
            },
            "position_embeddings": {
                "embedding": _np(sd["embeddings.position_embeddings.weight"])
            },
            "ln": ln("embeddings.LayerNorm"),
        }
    }
    for i in range(cfg.n_layers):
        p = f"transformer.layer.{i}"
        encoder[f"layer_{i}"] = {
            "attn": {
                "q": lin(f"{p}.attention.q_lin"),
                "k": lin(f"{p}.attention.k_lin"),
                "v": lin(f"{p}.attention.v_lin"),
                "o": lin(f"{p}.attention.out_lin"),
            },
            "sa_ln": ln(f"{p}.sa_layer_norm"),
            "lin1": lin(f"{p}.ffn.lin1"),
            "lin2": lin(f"{p}.ffn.lin2"),
            "out_ln": ln(f"{p}.output_layer_norm"),
        }

    if has_head:
        head = lin("classifier")
    else:
        rng = head_rng or np.random.default_rng(0)
        head = {
            "kernel": rng.normal(0, cfg.initializer_range, (cfg.dim, cfg.n_classes)).astype(
                np.float32
            ),
            "bias": np.zeros((cfg.n_classes,), np.float32),
        }
    return {"encoder": encoder, "classifier": head}


def hf_dir_has_head(path: str) -> bool:
    """Whether the HF checkpoint dir carries trained ``classifier.*``
    weights — a bare encoder (the reference's ``./distilbert-base-uncased``)
    would get a randomly initialized head from :func:`hf_to_flax`, which is
    fine for training warm-starts but meaningless for inference."""
    import os

    st_path = os.path.join(path, "model.safetensors")
    bin_path = os.path.join(path, "pytorch_model.bin")
    if os.path.exists(st_path):
        from safetensors import safe_open

        with safe_open(st_path, framework="numpy") as f:
            keys = list(f.keys())
    elif os.path.exists(bin_path):
        import torch

        keys = list(torch.load(bin_path, map_location="cpu", weights_only=True))
    else:
        raise FileNotFoundError(
            f"no model.safetensors or pytorch_model.bin under {path}"
        )
    return any(k.startswith("classifier.") for k in keys)


def config_from_hf_dir(path: str, **overrides: Any) -> ModelConfig:
    """``config.json`` of an HF DistilBERT checkpoint dir -> ModelConfig.

    The reference hard-requires such a directory at startup
    (``./distilbert-base-uncased``, client1.py:357,360-361). Architecture
    fields come from the checkpoint; training-side knobs (max_len, attention
    impl, dtypes, dropout rates) stay at our defaults unless overridden.
    """
    import json
    import os

    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    # HF "gelu" = the erf form; "gelu_new"/"gelu_pytorch_tanh" = the tanh
    # form — keep whichever the checkpoint was trained under (export-hf
    # writes this field from ModelConfig.gelu).
    activation = hf.get("activation", "gelu")
    kw: dict[str, Any] = dict(
        vocab_size=hf["vocab_size"],
        dim=hf["dim"],
        n_layers=hf["n_layers"],
        n_heads=hf["n_heads"],
        hidden_dim=hf["hidden_dim"],
        max_position_embeddings=hf.get("max_position_embeddings", 512),
        pad_token_id=hf.get("pad_token_id", 0),
        initializer_range=hf.get("initializer_range", 0.02),
        gelu=(
            "tanh" if activation in ("gelu_new", "gelu_pytorch_tanh") else "exact"
        ),
    )
    kw.update(overrides)
    kw.setdefault("max_len", min(128, kw["max_position_embeddings"]))
    return ModelConfig(**kw)


def load_hf_dir(
    path: str,
    cfg: ModelConfig | None = None,
    head_rng: np.random.Generator | None = None,
) -> tuple[dict, ModelConfig]:
    """Load an HF DistilBERT checkpoint directory (the reference's
    ``./distilbert-base-uncased`` layout: ``config.json`` + weights in
    ``model.safetensors`` or ``pytorch_model.bin``) into Flax params.

    Returns ``(params, model_config)``; pass ``cfg`` to pin non-architecture
    knobs (attention impl, max_len, dtypes)."""
    import os

    if cfg is None:
        cfg = config_from_hf_dir(path)
    st_path = os.path.join(path, "model.safetensors")
    bin_path = os.path.join(path, "pytorch_model.bin")
    if os.path.exists(st_path):
        from safetensors.numpy import load_file

        sd: Mapping[str, Any] = load_file(st_path)
    elif os.path.exists(bin_path):
        import torch

        sd = torch.load(bin_path, map_location="cpu", weights_only=True)
    else:
        raise FileNotFoundError(
            f"no model.safetensors or pytorch_model.bin under {path}"
        )
    return hf_to_flax(sd, cfg, head_rng=head_rng), cfg


def load_reference_pth(path: str, cfg: ModelConfig) -> dict:
    """Load a reference-run ``.pth`` state dict (torch.save of its
    DDoSClassifier — ``distilbert.*`` encoder + ``classifier.*`` head,
    reference client1.py:53-58,388; server.py:77) into Flax params: the
    direct migration path for models trained by the reference itself.

    Requires the trained head — a headless dict is not a reference
    training artifact, and silently random-initializing would betray the
    "migrate my trained model" intent.
    """
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    if not any(str(k).startswith("classifier.") for k in sd):
        raise ValueError(
            f"{path} has no classifier.* keys — not a reference training "
            "artifact (expected its DDoSClassifier state dict, "
            "client1.py:53-58)"
        )
    return hf_to_flax(sd, cfg)


def flax_to_hf(params: Mapping[str, Any], cfg: ModelConfig) -> dict[str, np.ndarray]:
    """Inverse mapping, producing the reference's full-classifier key space
    (``distilbert.*`` + ``classifier.*``) as numpy arrays — e.g. to export a
    checkpoint a reference client could load."""
    enc = params["encoder"]

    out: dict[str, np.ndarray] = {}

    def put_lin(prefix: str, p: Mapping[str, Any]) -> None:
        out[f"{prefix}.weight"] = np.asarray(p["kernel"]).T.astype(np.float32)
        out[f"{prefix}.bias"] = np.asarray(p["bias"]).astype(np.float32)

    def put_ln(prefix: str, p: Mapping[str, Any]) -> None:
        out[f"{prefix}.weight"] = np.asarray(p["scale"]).astype(np.float32)
        out[f"{prefix}.bias"] = np.asarray(p["bias"]).astype(np.float32)

    emb = enc["embeddings"]
    out["distilbert.embeddings.word_embeddings.weight"] = np.asarray(
        emb["word_embeddings"]["embedding"], dtype=np.float32
    )
    out["distilbert.embeddings.position_embeddings.weight"] = np.asarray(
        emb["position_embeddings"]["embedding"], dtype=np.float32
    )
    put_ln("distilbert.embeddings.LayerNorm", emb["ln"])
    for i in range(cfg.n_layers):
        p = f"distilbert.transformer.layer.{i}"
        layer = enc[f"layer_{i}"]
        put_lin(f"{p}.attention.q_lin", layer["attn"]["q"])
        put_lin(f"{p}.attention.k_lin", layer["attn"]["k"])
        put_lin(f"{p}.attention.v_lin", layer["attn"]["v"])
        put_lin(f"{p}.attention.out_lin", layer["attn"]["o"])
        put_ln(f"{p}.sa_layer_norm", layer["sa_ln"])
        put_lin(f"{p}.ffn.lin1", layer["lin1"])
        put_lin(f"{p}.ffn.lin2", layer["lin2"])
        put_ln(f"{p}.output_layer_norm", layer["out_ln"])
    put_lin("classifier", params["classifier"])
    return out
