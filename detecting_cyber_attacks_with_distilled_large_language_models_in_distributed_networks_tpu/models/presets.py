"""Named model-size presets — the single registry behind ``--preset``.

Every entrypoint that sizes a model (train, federated, infer-serve,
bench) resolves the name here, so adding a scale point is one registry
entry instead of an if-chain edit per CLI. The ladder's top end exists
for the sharded tiers: ``bert-large`` (~335 M params, ~1.3 GB fp32)
does not fit a small accelerator's HBM next to its optimizer state —
it is the demonstration scale for ``train --fsdp`` and the sharded
scorer (``infer-serve --data-parallel N --fsdp``), where params live
split per-leaf across the mesh and are gathered at use.
"""

from __future__ import annotations

from typing import Any, Callable

from ..config import ModelConfig

#: name -> ModelConfig factory. Ordered small -> large so help strings
#: and error messages read as the scale ladder.
PRESETS: dict[str, Callable[..., ModelConfig]] = {
    "tiny": ModelConfig.tiny,
    "distilbert": ModelConfig.distilbert_base,
    "bert": ModelConfig.bert_base,
    "bert-large": ModelConfig.bert_large,
}


def preset_names() -> tuple[str, ...]:
    """The registry's names in ladder order (for help/error strings)."""
    return tuple(PRESETS)


def model_preset(name: str, **kw: Any) -> ModelConfig:
    """Resolve a preset name to its ModelConfig (ValueError on unknown —
    CLI callers wrap it into their SystemExit idiom)."""
    try:
        factory = PRESETS[name]
    except KeyError:
        raise ValueError(
            f"unknown model preset {name!r} "
            f"(one of: {'|'.join(PRESETS)})"
        ) from None
    return factory(**kw)
