"""Flax DistilBERT encoder + DDoS classification head.

Re-implements, TPU-first, what the reference gets from HF PyTorch
(``DistilBertModel`` at reference client1.py:56,61): embeddings (word +
learned position, LayerNorm eps 1e-12), N post-LayerNorm transformer blocks
(MHA -> residual -> LN -> exact-GELU FFN -> residual -> LN), followed by the
reference's head: CLS pooling -> Dropout(0.3) -> Linear(dim, 2) (reference
client1.py:57-58,62-64).

Design notes (TPU):
* depth/width come from ``ModelConfig`` — the same module is DistilBERT-base
  (6 layers) or BERT-base scale-up (12 layers, BASELINE.json config 4).
* activations in ``cfg.compute_dtype`` (bf16 by default) keep the MXU fed;
  params stay fp32; softmax and LayerNorm statistics run in fp32.
* no data-dependent control flow — one ``jit`` trace, static shapes.
* optional ``jax.checkpoint`` (remat) per block trades FLOPs for HBM.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..config import ModelConfig
from ..ops.attention import dot_product_attention, make_attention_bias


def _dtype(name: str):
    return jnp.dtype(name)


def _axis_bound(axis_name: str) -> bool:
    """Trace-time check: are we inside shard_map with ``axis_name`` bound?

    Lets ``attention_impl="ring"`` degrade to the mathematically identical
    unsharded path outside shard_map — in particular ``init_params`` (which
    traces the forward on dummy data with no mesh axes) would otherwise die
    on an unbound axis name.
    """
    try:
        jax.lax.axis_index(axis_name)
        return True
    except NameError:
        return False


def _drop_offsets(cfg: ModelConfig, batch_len: int, *, pos_len: int | None):
    """Global-coordinate offsets for hash-dropout masks inside shard_map:
    axis 0 (batch rows) offsets by the data-shard index — rows on
    different data shards must not reuse one mask — and the position axis
    by the seq-shard index. Unbound axes contribute offset 0."""
    offsets: dict[int, Any] = {}
    if _axis_bound(cfg.data_axis):
        offsets[0] = jax.lax.axis_index(cfg.data_axis) * batch_len
    if pos_len is not None:
        offsets[1] = jax.lax.axis_index(cfg.ring_axis) * pos_len
    return offsets


def _seq_dropout(mod: nn.Module, cfg: ModelConfig, x, rate: float,
                 deterministic: bool, *, pos: bool):
    """Dropout whose mask survives sequence AND batch sharding: on the
    ring path (inside shard_map over cfg.ring_axis) the keep mask is a
    hash of the GLOBAL element coordinates (ops/hash_dropout.py), so
    seq=1 and seq=N runs train identical trajectories and data shards
    draw independent row masks; everywhere else it is plain nn.Dropout.
    ``pos``: axis 1 of x is the (sharded) position axis."""
    if deterministic or rate == 0.0:
        return x
    if cfg.attention_impl == "ring" and _axis_bound(cfg.ring_axis):
        from ..ops.hash_dropout import hash_dropout

        return hash_dropout(
            x, rate, mod.make_rng("dropout"),
            offsets=_drop_offsets(
                cfg, x.shape[0], pos_len=x.shape[1] if pos else None
            ),
        )
    return nn.Dropout(rate)(x, deterministic=False)


class MultiHeadSelfAttention(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, x, bias, deterministic: bool):
        cfg = self.cfg
        dense = lambda name: nn.Dense(  # noqa: E731
            cfg.dim,
            dtype=_dtype(cfg.compute_dtype),
            param_dtype=_dtype(cfg.param_dtype),
            kernel_init=nn.initializers.normal(cfg.initializer_range),
            name=name,
        )
        B, L, _ = x.shape
        heads = cfg.n_heads
        d = cfg.head_dim

        def split(t):  # [B, L, dim] -> [B, H, L, d]
            return t.reshape(B, L, heads, d).transpose(0, 2, 1, 3)

        if cfg.fused_qkv:
            qd, kd, vd = dense("q"), dense("k"), dense("v")
            if self.is_initializing():
                # Materialize the SAME parameter tree the unfused path
                # builds (child Dense modules named q/k/v) — checkpoints
                # and HF conversion see an identical layout either way.
                probe = jnp.zeros((1, 1, cfg.dim), x.dtype)
                qd(probe), kd(probe), vd(probe)
            p = self.variables["params"]
            cd = _dtype(cfg.compute_dtype)
            W = jnp.concatenate(
                [p["q"]["kernel"], p["k"]["kernel"], p["v"]["kernel"]], axis=-1
            ).astype(cd)  # [D, 3D] — one MXU dispatch instead of three
            bias3 = jnp.concatenate(
                [p["q"]["bias"], p["k"]["bias"], p["v"]["bias"]]
            ).astype(cd)
            qkv = x @ W + bias3
            q, k, v = (split(t) for t in jnp.split(qkv, 3, axis=-1))
        else:
            q, k, v = (
                split(dense("q")(x)),
                split(dense("k")(x)),
                split(dense("v")(x)),
            )
        dropout_rng = (
            None
            if deterministic or cfg.attention_dropout == 0.0
            else self.make_rng("dropout")
        )
        if cfg.attention_impl == "flash":
            from ..ops.flash_attention import flash_attention

            ctx = flash_attention(
                q, k, v, bias,
                dropout_rate=cfg.attention_dropout,
                dropout_rng=dropout_rng,
                deterministic=deterministic,
            )
        elif cfg.attention_impl == "ring" and _axis_bound(cfg.ring_axis):
            # Sequence-sharded forward inside shard_map over cfg.ring_axis.
            from ..parallel.ring_attention import ring_attention

            batch_off = (
                jax.lax.axis_index(cfg.data_axis) * B
                if _axis_bound(cfg.data_axis)
                else 0
            )
            ctx = ring_attention(
                q, k, v, bias,
                axis_name=cfg.ring_axis,
                dropout_rate=cfg.attention_dropout,
                dropout_rng=dropout_rng,
                deterministic=deterministic,
                batch_offset=batch_off,
            )
        elif cfg.attention_impl in ("dot", "ring"):
            # "ring" outside shard_map (e.g. init_params, unsharded eval)
            # runs the identical unsharded math.
            ctx = dot_product_attention(
                q, k, v, bias,
                dropout_rate=cfg.attention_dropout,
                dropout_rng=dropout_rng,
                deterministic=deterministic,
            )
        else:
            raise ValueError(f"unknown attention_impl {cfg.attention_impl!r}")
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, L, cfg.dim)
        return dense("o")(ctx)


class TransformerBlock(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, x, bias, deterministic: bool):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(  # noqa: E731
            epsilon=cfg.layer_norm_eps,
            dtype=_dtype(cfg.compute_dtype),
            param_dtype=_dtype(cfg.param_dtype),
            name=name,
        )
        attn_out = MultiHeadSelfAttention(cfg, name="attn")(x, bias, deterministic)
        attn_out = _seq_dropout(
            self, cfg, attn_out, cfg.dropout, deterministic, pos=True
        )
        x = ln("sa_ln")(x + attn_out)

        h = nn.Dense(
            cfg.hidden_dim,
            dtype=_dtype(cfg.compute_dtype),
            param_dtype=_dtype(cfg.param_dtype),
            kernel_init=nn.initializers.normal(cfg.initializer_range),
            name="lin1",
        )(x)
        # cfg.gelu: "exact" = HF's erf GELU (fp32 parity); "tanh" = the
        # tanh form, within a few bf16 ulps of erf and ~20% faster per
        # step on TPU v5e (config.py ModelConfig.gelu).
        h = jax.nn.gelu(h, approximate=(cfg.gelu == "tanh"))
        h = nn.Dense(
            cfg.dim,
            dtype=_dtype(cfg.compute_dtype),
            param_dtype=_dtype(cfg.param_dtype),
            kernel_init=nn.initializers.normal(cfg.initializer_range),
            name="lin2",
        )(h)
        h = _seq_dropout(self, cfg, h, cfg.dropout, deterministic, pos=True)
        return ln("out_ln")(x + h)


class Embeddings(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, input_ids, deterministic: bool):
        cfg = self.cfg
        word = nn.Embed(
            cfg.vocab_size,
            cfg.dim,
            dtype=_dtype(cfg.compute_dtype),
            param_dtype=_dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            name="word_embeddings",
        )(input_ids)
        L = input_ids.shape[-1]
        pos_table = nn.Embed(
            cfg.max_position_embeddings,
            cfg.dim,
            dtype=_dtype(cfg.compute_dtype),
            param_dtype=_dtype(cfg.param_dtype),
            embedding_init=nn.initializers.normal(cfg.initializer_range),
            name="position_embeddings",
        )
        if cfg.attention_impl == "ring" and _axis_bound(cfg.ring_axis):
            # Sequence-sharded forward (inside shard_map over cfg.ring_axis):
            # this shard embeds global positions [shard*L_local, ...), not
            # [0, L_local).
            offset = jax.lax.axis_index(cfg.ring_axis) * L
            pos_ids = offset + jnp.arange(L, dtype=jnp.int32)
            pos = pos_table(pos_ids)[None, :, :]
        else:
            pos = pos_table(jnp.arange(L, dtype=jnp.int32))[None, :, :]
        x = word + pos
        x = nn.LayerNorm(
            epsilon=cfg.layer_norm_eps,
            dtype=_dtype(cfg.compute_dtype),
            param_dtype=_dtype(cfg.param_dtype),
            name="ln",
        )(x)
        return _seq_dropout(self, cfg, x, cfg.dropout, deterministic, pos=True)


class DistilBertEncoder(nn.Module):
    """Token ids + attention mask -> last hidden states ``[B, L, dim]``."""

    cfg: ModelConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask, deterministic: bool = True):
        cfg = self.cfg
        x = Embeddings(cfg, name="embeddings")(input_ids, deterministic)
        bias = make_attention_bias(attention_mask)
        block = TransformerBlock
        if cfg.remat:
            # static_argnums counts self: (self, x, bias, deterministic)
            block = nn.remat(TransformerBlock, static_argnums=(3,))
        for i in range(cfg.n_layers):
            x = block(cfg, name=f"layer_{i}")(x, bias, deterministic)
        return x


class DDoSClassifier(nn.Module):
    """Encoder + the reference's classification head (client1.py:53-65)."""

    cfg: ModelConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask, deterministic: bool = True):
        cfg = self.cfg
        hidden = DistilBertEncoder(cfg, name="encoder")(
            input_ids, attention_mask, deterministic
        )
        pooled = hidden[:, 0, :]  # CLS token (reference client1.py:62)
        if cfg.attention_impl == "ring" and _axis_bound(cfg.ring_axis):
            # Under sequence sharding only shard 0's token 0 is the global
            # CLS; broadcast it so every shard computes identical logits.
            is_first = (jax.lax.axis_index(cfg.ring_axis) == 0).astype(pooled.dtype)
            pooled = jax.lax.psum(pooled * is_first, cfg.ring_axis)
        # Head dropout ([B, dim], no position axis): still hash-keyed on
        # the ring path so the [C]-vmapped fedseq step stays shard-count-
        # invariant; the reference's Dropout(0.3) site (client1.py:57,63).
        pooled = _seq_dropout(
            self, cfg, pooled, cfg.head_dropout, deterministic, pos=False
        )
        logits = nn.Dense(
            cfg.n_classes,
            dtype=jnp.float32,  # head + loss in fp32
            param_dtype=_dtype(cfg.param_dtype),
            kernel_init=nn.initializers.normal(cfg.initializer_range),
            name="classifier",
        )(pooled.astype(jnp.float32))
        return logits


def init_params(
    model: nn.Module, cfg: ModelConfig, rng: jax.Array, batch_size: int = 2
) -> Any:
    dummy_ids = jnp.zeros((batch_size, cfg.max_len), jnp.int32)
    dummy_mask = jnp.ones((batch_size, cfg.max_len), jnp.int32)
    return model.init({"params": rng}, dummy_ids, dummy_mask, True)["params"]


def param_count(params: Any) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(params))
