"""Round-scoped trace contexts: span records on a unified events-JSONL.

The per-tier metrics-JSONL streams (reporting.append_metrics_jsonl) are
uncorrelated — no shared round/span identity crosses the wire, so nobody
can answer "where did round N's wall-clock go: client compute, straggler
wait, wire transfer, eval gate, or promotion?". This module is the shared
identity layer:

* the **server** mints one ``trace`` id per round (:func:`new_trace_id`)
  and stamps it into every reply's free-form wire ``meta`` (comm/wire.py
  — the format itself is unchanged, so old peers that omit the field
  still interop byte-for-byte);
* every process appends :class:`Span` records to its own events-JSONL
  through a :class:`Tracer` — one JSON object per line, written with a
  single atomic ``os.write`` append so concurrent writers (server round
  thread + reply fan-out threads) can never interleave partial lines;
* ``fedtpu obs`` (obs/timeline.py) merges the per-process files on the
  (trace, round) key into a per-round timeline and a Chrome trace-event
  export.

Span vocabulary (names are the contract the timeline tool groups by)::

    round         one aggregation round, server side (contains agg/reply)
    client-local  a client's local training phase
    wire-upload   a client's model upload send (streamed uploads carry
                  ``chunks`` + ``overlap_s``: pack/send seconds hidden by
                  running the two concurrently)
    wire-overlap  server-side: aggregation folds that ran DURING the wire
                  phase (streaming chunk aggregation) — overlapped wire
                  time, with ``overlap_frac`` and ``peak_agg_bytes``
    agg           the server's EXPOSED aggregation compute
    wire-reply    the reply transfer (server: fan-out; client: recv)
    batch-prefetch  a client's next-round input-pipeline work that ran
                  under the reply wait (train/batches.EpochPrefetcher)
    relay-forward a relay's upward exchange window (comm/relay.py): the
                  subtree partial going up + the root aggregate coming
                  back, with ``parent_trace``/``parent_round`` linking
                  this subtree round to the parent tier's round
    eval-gate     the controller's held-out eval + gate decision
    promote       a registry state transition / pointer swap
    serve-batch   one coalesced scoring dispatch on the serving tier
                  (``sampled_batches`` when span sampling is on)
    router-forward  one request's trip through the serving router
                  (router/core.py): send-to-replica -> reply-rewritten,
                  with ``replica`` + ``inflight`` (``sampled_requests``
                  when span sampling is on)
    replica-drain one replica's drain -> hot-swap -> readmit cycle of a
                  rolling fleet reload (router/fleet.py), with
                  ``replica``/``artifact``/``drained``
    slo-eval      one scrape-hub pass over the fleet's /metrics.json +
                  burn-rate evaluation (obs/fleet.py), with ``targets``/
                  ``up``/``firing``/``scrape_lag_ms``
    postmortem-dump  a flight-recorder bundle write (obs/flight.py),
                  with ``reason``/``bundle``/``spans``
    drift-trigger the controller's drift verdict that started a round
                  (control/controller.py), with the distance, method,
                  and ``top_bins`` per-bin PSI localization
    xla-compile   one XLA trace+compile of a jitted program
                  (obs/profile.py CompileLedger), with ``site``/
                  ``signature`` and ``recompile=True`` when the shape
                  appeared at an already-warm site (the flagged event
                  that can trip the flight recorder)
    shadow-mirror a sampled live request duplicated onto the shadow
                  backend (shadow/mirror.py), counter-strided like
                  serve-batch spans, with the running ``mirrored`` count
    shadow-compare one completed serving/shadow probability pair's
                  running disagreement stats (shadow/compare.py), with
                  ``pairs``/``flip_rate``/``psi``
    shadow-gate   the controller's live disagreement verdict for a
                  shadow-state candidate (shadow/gate.py), with
                  ``artifact``/``passed``/``pairs``/``flip_rate``/``psi``
    label-join    one deterministic join of scored-request records
                  against the ground-truth journal (labels/join.py),
                  with ``total``/``joined``/``coverage``
    label-gate    the controller's SUPERVISED verdict for a shadow-state
                  candidate over joined ground truth (labels/join.py),
                  with ``artifact``/``passed``/``joined``/``coverage``/
                  ``serving_error``/``candidate_error``
    canary-probe  one sentinel canary pass through the live serving
                  chain (obs/sentinel.py), with ``probes``/``failures``/
                  ``mismatches``/``flips``/``artifact``/
                  ``latency_p99_ms``
    sentinel-eval one full sentinel tick over every configured rung
                  (obs/sentinel.py), with ``tick``/``canary_incidents``/
                  ``drift_fired``/``regressions``
    regression-fire  a long-horizon trend regression against the pinned
                  baseline window (obs/sentinel.py RetentionRing), with
                  ``field``/``baseline``/``now_mean``/``ratio``/
                  ``direction``

Timestamps are wall-clock unix seconds (``ts``) with a separately
measured monotonic duration (``dur_s``): cross-process correlation needs
a shared clock, phase arithmetic needs one that never steps backwards.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Iterator

from .flight import get_global_recorder

#: Every span record carries this so stream consumers can reject (or
#: version-switch on) foreign JSONL lines when files get concatenated.
SCHEMA = "fedtpu-obs-v1"

#: The span-name vocabulary (documentation + timeline-tool contract; the
#: writer does not enforce membership — new tiers may add names).
SPAN_NAMES = (
    "round",
    "client-local",
    "wire-upload",
    "wire-overlap",
    "agg",
    "wire-reply",
    "batch-prefetch",
    "relay-forward",
    "eval-gate",
    "promote",
    "serve-batch",
    "router-forward",
    "replica-drain",
    "slo-eval",
    "postmortem-dump",
    "drift-trigger",
    "xla-compile",
    "shadow-mirror",
    "shadow-compare",
    "shadow-gate",
    "label-join",
    "label-gate",
    "canary-probe",
    "sentinel-eval",
    "regression-fire",
)

#: Wire meta key the trace id rides under (comm/server.py reply meta,
#: serving/protocol.py request/reply bodies). Optional everywhere.
TRACE_META_KEY = "trace"

_RUN_LOCK = threading.Lock()
_RUN_ID: str | None = None


def new_trace_id() -> str:
    """64 random bits of hex — one per round, minted by the round owner."""
    return os.urandom(8).hex()


def get_run_id() -> str:
    """Process-wide run id stamped on every span AND every metrics-JSONL
    record (reporting.append_metrics_jsonl), so `fedtpu obs` and the drift
    monitor can merge streams from several runs without guessing.
    FEDTPU_RUN_ID (or :func:`set_run_id` — the ObsConfig.run_id hook)
    pins it across processes of one deployment."""
    global _RUN_ID
    with _RUN_LOCK:
        if _RUN_ID is None:
            _RUN_ID = os.environ.get("FEDTPU_RUN_ID") or os.urandom(4).hex()
        return _RUN_ID


def set_run_id(run_id: str) -> None:
    """Pin the process run id (how ObsConfig.run_id takes effect — the
    CLI calls this before the first span/metrics record is written)."""
    global _RUN_ID
    with _RUN_LOCK:
        _RUN_ID = str(run_id)


_FD_LOCK = threading.Lock()
_FDS: dict[str, int] = {}


def _append_fd(path: str) -> int:
    """Long-lived O_APPEND descriptor per path (makedirs + open once,
    not per record — the serving tier appends per coalesced batch).
    O_APPEND atomicity is a property of the write, not of a fresh open.
    Trade-off: external rotation of a live file keeps writes going to
    the rotated inode — give each run its own file (the documented
    layout) rather than rotating one in place."""
    path = os.path.abspath(path)
    with _FD_LOCK:
        fd = _FDS.get(path)
        if fd is None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
            _FDS[path] = fd
        return fd


def append_jsonl_line(path: str, line: str) -> None:
    """One ATOMIC append: a single ``os.write`` of the whole line on an
    ``O_APPEND`` descriptor. Python's buffered ``open(path, "a").write``
    can flush a long line in several syscalls, and two threads' partial
    flushes interleave into unparseable garbage — exactly what the
    multi-threaded server and serving tiers would do to a shared
    stream."""
    data = line.encode()
    if not data.endswith(b"\n"):
        data += b"\n"
    os.write(_append_fd(path), data)


class Tracer:
    """Append-only span writer for ONE process/role.

    ``proc`` names the emitting role (``server``, ``client-0``,
    ``controller``, ``registry``, ``serve``, ``fed``); the timeline tool
    uses it as the per-lane identity, so give every process a distinct
    value. A Tracer is thread-safe by construction (each record is one
    atomic append; no shared mutable state beyond the path)."""

    def __init__(self, path: str, *, proc: str, run_id: str | None = None):
        self.path = path
        self.proc = str(proc)
        self.run_id = run_id or get_run_id()

    def record(
        self,
        name: str,
        *,
        t_start: float,
        dur_s: float,
        trace: str | None = None,
        round: int | None = None,
        **attrs: Any,
    ) -> dict:
        """Write one finished span. ``t_start`` is unix seconds,
        ``dur_s`` a monotonic-measured duration. Returns the record."""
        rec: dict[str, Any] = {
            "schema": SCHEMA,
            "run_id": self.run_id,
            "proc": self.proc,
            "span": str(name),
            "ts": float(t_start),
            "dur_s": float(dur_s),
        }
        if trace is not None:
            rec["trace"] = str(trace)
        if round is not None:
            rec["round"] = int(round)
        for k, v in attrs.items():
            if v is not None:
                rec[k] = v
        append_jsonl_line(self.path, json.dumps(rec))
        # Flight recorder tap (obs/flight.py): every traced process
        # keeps its recent spans in the postmortem ring for free — one
        # deque append when a recorder is installed, nothing otherwise.
        recorder = get_global_recorder()
        if recorder is not None:
            recorder.note_span(rec)
        return rec

    @contextmanager
    def span(
        self,
        name: str,
        *,
        trace: str | None = None,
        round: int | None = None,
        **attrs: Any,
    ) -> Iterator[dict]:
        """Measure a block and write the span on exit. The yielded dict
        may be mutated inside the block — in particular ``trace`` and
        ``round`` may be filled in late (a client learns the round's
        trace id only from the reply meta)."""
        info: dict[str, Any] = {"trace": trace, "round": round, **attrs}
        t_unix = time.time()
        t0 = time.monotonic()
        try:
            yield info
        finally:
            dur = time.monotonic() - t0
            trace = info.pop("trace", None)
            rnd = info.pop("round", None)
            self.record(
                name, t_start=t_unix, dur_s=dur, trace=trace, round=rnd, **info
            )


@contextmanager
def maybe_span(
    tracer: Tracer | None, name: str, **kw: Any
) -> Iterator[dict]:
    """``tracer.span(...)`` that degrades to a no-op when tracing is off —
    call sites stay one-liners with no ``if tracer is not None`` forest."""
    if tracer is None:
        yield {}
    else:
        with tracer.span(name, **kw) as info:
            yield info


_GLOBAL_LOCK = threading.Lock()
_GLOBAL: Tracer | None = None


def set_global_tracer(tracer: Tracer | None) -> None:
    """Install a process-wide tracer for call sites with no injection
    path (the mesh-tier trainers); CLI commands set it once at startup."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = tracer


def get_global_tracer() -> Tracer | None:
    with _GLOBAL_LOCK:
        return _GLOBAL
