"""Cross-tier observability: round tracing, metrics registry, timelines.

Three pieces (see each module's docstring):

* :mod:`.trace` — round-scoped trace contexts with span ids propagated
  across the TCP wire protocols via an optional meta field; every
  process appends spans to a unified events-JSONL.
* :mod:`.metrics` — in-process counters/gauges/histograms exposed over a
  stdlib-HTTP ``/metrics`` endpoint in Prometheus text format.
* :mod:`.timeline` — the ``fedtpu obs`` merge/analysis layer: per-round
  timeline tables and Chrome trace-event export.
"""

from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    MetricsServer,
    default_registry,
    maybe_start_metrics_server,
)
from .timeline import (  # noqa: F401
    chrome_trace,
    export_chrome_trace,
    group_rounds,
    load_spans,
    round_breakdown,
    round_summaries,
    tail_spans,
    timeline_table,
)
from .trace import (  # noqa: F401
    SCHEMA,
    SPAN_NAMES,
    TRACE_META_KEY,
    Tracer,
    get_global_tracer,
    get_run_id,
    maybe_span,
    new_trace_id,
    set_global_tracer,
)
